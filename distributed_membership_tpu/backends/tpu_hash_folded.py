"""Folded-layout ring step: ``[N/F, 128]`` physical state for S < 128.

**Why.**  TPU tiles the minormost array axis to 128 lanes (pallas guide
"Tiling Constraints"), so a ``[N, 16]`` u32 plane is stored — and every
pass streams — 8x its logical size.  The S=16 north-star regime
(PERF.md) therefore runs at ~1/8 effective HBM efficiency on the natural
layout.  This module re-expresses the single-chip ring step
(backends/tpu_hash.py make_step, 'ring' branch) on a *folded* layout:
``F = 128 // S`` nodes share each physical row, every plane is
``[N/F, 128]`` — zero lane padding — and per-node structure lives in
lane arithmetic (``node = row*F + lane//S``, ``slot = lane % S``).
Probe state folds at its own factor (``FP = 128 // P``).

**Bit-exactness.**  The folded step reproduces the unfolded ring run
EXACTLY (same seed -> same trajectory): every jax.random draw keeps the
unfolded call's key and flat element count (same-size shapes produce
identical flat bit streams — pinned by tests), and every tensor op is
the fold of the unfolded op:

* node-axis roll by ``r`` decomposes into an aligned row roll
  (``r // F``) plus a carry-select lane roll (``(r % F) * S``);
* slot-axis roll by ``c`` becomes a segment-wise lane roll (two lane
  rolls + a lane-position select);
* per-node reductions are ``reshape(NF, F, S)`` reduces; per-node
  vectors broadcast by lane-group repeat.

Both decompositions are verified element-for-element against the padded
ops (tests/test_folded.py; scripts/tpu_layout_probe.py times them on
hardware).  Scope (enforced in tpu_hash.make_config): ring exchange,
warm join, aggregate events with the FastAgg path, ``128 % S == 0``,
``N % F == 0``, and ``128 % P == 0`` when probing.  Cold joins, full
event collection, and the scatter exchange keep the natural layout.

**Multi-tick residency.**  The folded step composes with ``MEGA_TICKS``
unchanged: the T-block segment runner (ops/megakernel.mega_scan) wraps
whatever step _get_step_and_init returns, and the shrunk-carry codec
classifies leaves by FIELD NAME and dtype — the folded HashState keeps
the natural field names (``view_ts`` is the same i32 payload reshaped
to ``[N*S/128, 128]``, ``self_hb`` stays ``[N]``), so the 16-bit lane
pack and the bool bitplanes apply to the folded carry with no
layout-specific code.  Bit-exactness of the folded mega scan vs the
folded per-tick scan is pinned alongside the natural twins
(tests/test_megakernel.py).

Reference lineage: the step semantics are tpu_hash's, which replicate
/root/reference/MP1Node.cpp:404-495 (nodeLoopOps) + EmulNet delivery —
see the tpu_hash module docstring for the mapping.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from distributed_membership_tpu.backends.tpu_sparse import SparseTickEvents
from distributed_membership_tpu.observability.aggregates import (
    init_fast_agg, update_fast_agg)

I32 = jnp.int32
U32 = jnp.uint32
EMPTY = -1
LANES = 128


def folded_supported(n: int, s: int, probes: int) -> bool:
    # probes < s mirrors make_step's ring guard (the probe window is a
    # cyclic band of the node's own S slots); the folded runner never
    # reaches that guard, so it must hold here.
    return (0 < s < LANES and LANES % s == 0 and n % (LANES // s) == 0
            and (probes <= 0 or (probes < s and LANES % probes == 0
                                 and n % (LANES // probes) == 0)))


def roll_nodes(x: jax.Array, r: jax.Array, f: int, s: int) -> jax.Array:
    """Fold of ``jnp.roll(unfolded, r, axis=0)`` (node-axis circulant).

    Flat shift is ``r*S = (r//F)*128 + (r%F)*S``: an aligned row roll
    plus a lane roll whose wrapped lanes take the once-more-rolled row.
    """
    rq = r // f
    rr = (r % f) * s
    a = jnp.roll(x, rq, axis=0)
    b = jnp.roll(a, 1, axis=0)
    lane = jax.lax.broadcasted_iota(I32, x.shape, 1)
    # Pre-select, then roll ONCE: result[l] = a[l-rr] for l >= rr and
    # b[l-rr+128] for l < rr, i.e. roll(mix, rr) with mix = a on source
    # lanes [0, 128-rr) and b on [128-rr, 128).  One dynamic lane roll
    # instead of two — the dynamic misaligned lane rotate is the op
    # class the 1M_s16 hardware pass flagged, and the folded step pays
    # it every gossip shift (PERF.md round-4 anomalies).
    mix = jnp.where(lane < LANES - rr, a, b)
    return jnp.roll(mix, rr, axis=1)


def roll_slots(x: jax.Array, c: jax.Array, s: int) -> jax.Array:
    """Fold of ``jnp.roll(unfolded, c, axis=1)`` (per-node slot roll):
    a segment-wise lane roll, c in [0, s)."""
    lane = jax.lax.broadcasted_iota(I32, x.shape, 1)
    pos = jax.lax.rem(lane, s)
    # Pre-select, then roll ONCE (same identity as roll_nodes): lanes
    # whose post-roll position wraps inside the segment must source the
    # NEXT segment's value — roll(x, -s) is a STATIC lane roll, so this
    # form costs one static roll + select + one dynamic roll instead of
    # two dynamic rolls + select.
    mix = jnp.where(pos >= s - c, jnp.roll(x, -s, axis=1), x)
    return jnp.roll(mix, c, axis=1)


def _folded_receive(n, tfail, tremove, rep, rowsum, self_mask, node,
                    t, view, view_ts, mail, cand_sf, rcol, act, self_val,
                    *, fused=False, s=0, stride=0, interpret=True, row0=0):
    """The receive pass (admit + ack-merge + self-write + TFAIL/TREMOVE
    sweep) on folded planes — the folded twin of
    ops/fused_receive._receive_body, shared by the single-chip and
    sharded folded steps so the two cannot drift.  The elementwise core
    lives in ops/fused_folded._folded_receive_body; with ``fused`` it
    runs as ONE Pallas traversal (receive_folded_fused — same body, so
    the paths cannot drift either) and the per-node reductions happen
    here on the returned planes.

    Returns (view, view_ts, mail_cleared, join_mask, rm_ids, numfailed,
    size, cur_id, present, difft)."""
    from distributed_membership_tpu.observability.timeline import (
        PHASE_RECEIVE)
    from distributed_membership_tpu.ops.fused_folded import (
        _folded_receive_body, receive_folded_fused)

    with jax.named_scope(PHASE_RECEIVE):
        if fused:
            (new_view, new_ts, mail, join_mask, rm_ids, stale) = \
                receive_folded_fused(n, s, tfail, tremove, stride,
                                     interpret, t, row0, view, view_ts,
                                     mail, cand_sf, rcol, rep(act),
                                     rep(self_val))
        else:
            (new_view, new_ts, mail, join_mask, rm_ids, stale) = \
                _folded_receive_body(n, tfail, tremove, self_mask, node,
                                     t, view, view_ts, mail, cand_sf,
                                     rcol, rep(act), rep(self_val))
        numfailed = rowsum(stale.astype(I32))
        present = new_view > 0
        cur_id = jnp.where(present,
                           ((new_view - U32(1)) % U32(n)).astype(I32),
                           EMPTY)
        size = rowsum(present.astype(I32))
        difft = t - new_ts
    return (new_view, new_ts, mail, join_mask, rm_ids, numfailed, size,
            cur_id, present, difft)


def _repP(v, rows, fp, p_cnt):
    """[rows] per-node vector -> [rows/FP, 128] P-folded broadcast."""
    return jnp.repeat(v.reshape(rows // fp, fp), p_cnt, axis=1,
                      total_repeat_length=LANES)


def _sumP(x, rows, fp, p_cnt):
    """[rows/FP, 128] P-folded plane -> per-node [rows] sums."""
    return x.reshape(rows // fp, fp, p_cnt).sum(-1).reshape(rows)


def _fold_ack_candidates(n, s, p_cnt, fp, cand_idx, rows, t, ids2, vec,
                         recv_mask, ack_u, p_drop, use_drop,
                         drop_lo, drop_hi, tbl=None, ids1=None,
                         count_dropped=False, scn_ctx=None):
    """Ack candidates for probes issued at t-2 (the gather pipeline of
    tpu_hash.make_step ring), on P-folded probe state.  ``vec`` is the
    lagged heartbeat vector ([N]; the sharded caller passes its
    all_gather).  ``ack_u`` is the planned ack-leg drop uniform (flat,
    ops/rng_plan — None when drops are off).  When ``tbl`` (the packed
    probe table, tpu_hash._pack_probe_table — the sharded caller passes
    its single all_gather) and ``ids1`` are given, the ack heartbeat AND
    the t-1 counter-filter bits ride ONE concatenated gather; returns
    (cand_sf [rows/F, 128], ack_recv_cnt [rows], bits1, ack_dropped)
    with ``bits1`` the packed filter bits gathered at the t-1 targets
    (None on the split arm) and ``ack_dropped`` the count of candidates
    the ack-leg coin killed (TELEMETRY scalars; None unless
    ``count_dropped``).  ``scn_ctx = (static, scn, cuts_prev, prober)``
    arms the scenario plan (scenario/compile.py): the partition cut and
    per-link drop override for the ack's t-1 transit, with ``prober``
    the P-folded global node ids of the ack receivers."""
    from distributed_membership_tpu.backends.tpu_hash import (
        _gathered_hb, ptr_switch)
    from distributed_membership_tpu.observability.timeline import PHASE_ACK

    with jax.named_scope(PHASE_ACK):
        id2 = jnp.clip(ids2.astype(I32) - 1, 0)
        bits1 = None
        if tbl is not None and ids1 is not None:
            tgt1 = jnp.clip(ids1.astype(I32) - 1, 0)
            gcat = tbl[jnp.concatenate([id2, tgt1], axis=1)]
            hb_ack = _gathered_hb(gcat[:, :id2.shape[1]])
            bits1 = gcat[:, id2.shape[1]:]
        else:
            hb_ack = vec[id2]
        valid2 = (ids2 > 0) & (hb_ack > 0)
        if scn_ctx is not None:
            from distributed_membership_tpu.scenario.compile import (
                cross_group)
            static, scn, cuts_prev, prober = scn_ctx
            if static.n_parts:
                valid2 &= ~cross_group(cuts_prev, id2, prober)
        ack_dropped = None
        if use_drop:
            if scn_ctx is not None:
                from distributed_membership_tpu.scenario.compile import (
                    site_drop_prob)
                ack_coin = (ack_u.reshape(ids2.shape)
                            < site_drop_prob(static, scn, t - 1, id2,
                                             prober))
            else:
                da_ack = (t - 1 > drop_lo) & (t - 1 <= drop_hi)
                ack_coin = (ack_u.reshape(ids2.shape) < p_drop) & da_ack
            if count_dropped:
                ack_dropped = (valid2 & ack_coin).sum(dtype=I32)
            valid2 &= ~ack_coin
        elif count_dropped:
            ack_dropped = jnp.zeros((), I32)
        cand = jnp.where(
            valid2,
            hb_ack.astype(U32) * U32(n) + id2.astype(U32) + U32(1), 0)
        ptr2 = jax.lax.rem(jax.lax.rem((t - 2) * p_cnt, s) + s, s)
        cand_ext = jnp.concatenate([cand.reshape(-1),
                                    jnp.zeros((1,), U32)])
        # Pointer takes only multiples of gcd(P, S): switch over static
        # roll_slots calls (every roll inside goes static —
        # tpu_hash.ptr_switch).
        cand_sf = ptr_switch(ptr2, p_cnt, s,
                             lambda o, c: roll_slots(c, o, s),
                             cand_ext[cand_idx])
        ack_recv_cnt = _sumP(valid2 & _repP(recv_mask, rows, fp, p_cnt),
                             rows, fp, p_cnt).astype(I32)
    return cand_sf, ack_recv_cnt, bits1, ack_dropped


def _fold_keep(g, s, fresh, is_self_slot, act, rep, rowsum, thin_u):
    """Gossip entry thinning to ~G per row (self always kept), folded.
    ``thin_u`` is the planned thinning uniform (flat, ops/rng_plan —
    same flat bits as the natural layout's (N, S) draw)."""
    if g >= s:
        keep = fresh
    else:
        fresh_cnt = rowsum(fresh.astype(I32))
        p_keep = jnp.where(
            fresh_cnt > 1,
            (g - 1) / jnp.maximum(fresh_cnt - 1, 1).astype(jnp.float32),
            1.0)
        u = thin_u.reshape(fresh.shape)
        keep = fresh & ((u < rep(p_keep)) | is_self_slot)
    return keep & rep(act)


def _fold_probe_window(n, s, p_cnt, fp, window_idx, rows, t, view, act,
                       node_p, probe_u, p_drop, use_drop, drop_active,
                       count_dropped=False, scn_ctx=None):
    """Issue this tick's probes from the cyclic window (P-folded).
    ``probe_u`` is the planned issue-time drop uniform (flat; None when
    drops are off).  Returns (ids_new [rows/FP, 128] u32, p_valid bool,
    probe_dropped) — the last the issue-leg coin-kill count (TELEMETRY
    scalars; None unless ``count_dropped``).
    ``scn_ctx = (static, scn, cuts)`` arms the scenario plan: probes to
    targets across the active partition are cut at issue time, and the
    drop coin takes the per-link effective probability (``node_p`` must
    then carry GLOBAL node ids)."""
    from distributed_membership_tpu.backends.tpu_hash import ptr_switch
    from distributed_membership_tpu.observability.timeline import (
        PHASE_PROBE)

    with jax.named_scope(PHASE_PROBE):
        ptr = jax.lax.rem(t * p_cnt, s)
        rolled_w = ptr_switch((s - ptr) % s, p_cnt, s,
                              lambda o, v: roll_slots(v, o, s), view)
        window = rolled_w.reshape(-1)[window_idx]
        w_pres = window > 0
        w_id = ((window - U32(1)) % U32(n)).astype(I32)
        p_valid = w_pres & (w_id != node_p) & _repP(act, rows, fp, p_cnt)
        if scn_ctx is not None:
            from distributed_membership_tpu.scenario.compile import (
                cross_group)
            static, scn, cuts = scn_ctx
            if static.n_parts:
                p_valid = p_valid & ~cross_group(cuts, node_p, w_id)
        probe_dropped = None
        if use_drop:
            if scn_ctx is not None:
                from distributed_membership_tpu.scenario.compile import (
                    site_drop_prob)
                probe_coin = (probe_u.reshape(p_valid.shape)
                              < site_drop_prob(static, scn, t, node_p,
                                               w_id))
            else:
                probe_coin = ((probe_u.reshape(p_valid.shape) < p_drop)
                              & drop_active)
            if count_dropped:
                probe_dropped = (p_valid & probe_coin).sum(dtype=I32)
            p_valid = p_valid & ~probe_coin
        elif count_dropped:
            probe_dropped = jnp.zeros((), I32)
        ids_new = jnp.where(p_valid, w_id.astype(U32) + U32(1), U32(0))
    return ids_new, p_valid, probe_dropped


def _fold_probe_window_fused(n, s, p_cnt, window_idx, tfail, fail_ids,
                             want_hist, want_agg, t, row0, view, view_ts,
                             actp, rm_ids, node_p, probe_u, p_drop,
                             use_drop, drop_active, count_dropped=False,
                             scn_ctx=None):
    """FUSED_PROBE twin of :func:`_fold_probe_window`: one Pallas
    traversal (ops/fused_probe) rolls the S-folded window and
    pre-validates the ids (occupied, not self, observer act) while the
    FastAgg/hist reductions ride as row partials; the pre-existing
    ``window_idx`` gather then compacts the VALIDATED plane into the
    P-folded layout (same gather count as the unfused path).  Scenario
    cuts and drop coins apply here in P-folded space with the exact
    unfused streams — suppressed positions are consulted nowhere else,
    so trajectories are bit-exact.  Returns the unfused triple plus the
    kernel-output dict for the agg/telemetry blocks."""
    from distributed_membership_tpu.observability.timeline import (
        PHASE_PROBE)
    from distributed_membership_tpu.ops.fused_probe import (
        probe_folded_window_fused)

    with jax.named_scope(PHASE_PROBE):
        ptr = jax.lax.rem(t * p_cnt, s)
        pfo = probe_folded_window_fused(
            n, s, p_cnt, tfail, tuple(fail_ids) if want_agg else (),
            want_hist, want_agg, jax.default_backend() != "tpu",
            t, ptr, row0, view, view_ts if want_hist else None,
            actp, rm_ids if want_agg else None)
        window = pfo["ids"].reshape(-1)[window_idx]
        p_valid = window > 0
        w_id = jnp.where(p_valid, window.astype(I32) - 1, 0)
        if scn_ctx is not None:
            from distributed_membership_tpu.scenario.compile import (
                cross_group)
            static, scn, cuts = scn_ctx
            if static.n_parts:
                p_valid = p_valid & ~cross_group(cuts, node_p, w_id)
        probe_dropped = None
        if use_drop:
            if scn_ctx is not None:
                from distributed_membership_tpu.scenario.compile import (
                    site_drop_prob)
                probe_coin = (probe_u.reshape(p_valid.shape)
                              < site_drop_prob(static, scn, t, node_p,
                                               w_id))
            else:
                probe_coin = ((probe_u.reshape(p_valid.shape) < p_drop)
                              & drop_active)
            if count_dropped:
                probe_dropped = (p_valid & probe_coin).sum(dtype=I32)
            p_valid = p_valid & ~probe_coin
        elif count_dropped:
            probe_dropped = jnp.zeros((), I32)
        ids_new = jnp.where(p_valid, w_id.astype(U32) + U32(1), U32(0))
    return ids_new, p_valid, probe_dropped, pfo


def _fused_probe_pre(pfo, fail_ids, rowany):
    """update_fast_agg ``pre=`` dict from the fused-probe kernel outputs
    (None passthrough when the kernel did not run / emit agg partials)."""
    if pfo is None or "rm_cnt" not in pfo:
        return None
    pre = {"rm_total": pfo["rm_cnt"].sum(dtype=I32)}
    if fail_ids:
        pre["det_tick"] = jnp.stack(
            [d.sum(dtype=I32) for d in pfo["det_cols"]])
        pre["any_true_rm"] = rowany(pfo["det_any"] != 0)
    return pre


def make_folded_step(cfg):
    """Per-tick transition on folded state.  Mirrors make_step's ring
    branch (tpu_hash.py) op for op; the warm-inert join machinery is
    omitted (proven no-op under JOIN_MODE warm, which the config gate
    requires)."""
    from distributed_membership_tpu.backends.tpu_hash import (
        STRIDE, HashConfig)
    assert isinstance(cfg, HashConfig) and cfg.exchange == "ring"
    n, s, g, p_cnt = cfg.n, cfg.s, cfg.g, cfg.probes
    f = LANES // s
    nf = n // f
    k_max = min(cfg.fanout, s)
    scenario = cfg.scenario
    use_drop = cfg.drop_prob > 0.0 or (scenario is not None
                                       and scenario.has_drop)
    p_red = 1 if cfg.qp >= n else 2
    cstride = STRIDE % s
    single_col_roll = (n * STRIDE) % s == 0
    idx = jnp.arange(n, dtype=I32)

    # Static per-element coordinates of the big plane.
    lane = jax.lax.broadcasted_iota(I32, (nf, LANES), 1)
    row = jax.lax.broadcasted_iota(I32, (nf, LANES), 0)
    pos = jax.lax.rem(lane, s)                       # slot within node
    node = row * f + lane // s                       # global node id
    self_slot = jax.lax.rem(
        jax.lax.rem(node, s) * ((1 + STRIDE) % s), s)
    self_mask = pos == self_slot

    if p_cnt > 0:
        fp = LANES // p_cnt
        nfp = n // fp
        lane_p = jax.lax.broadcasted_iota(I32, (nfp, LANES), 1)
        row_p = jax.lax.broadcasted_iota(I32, (nfp, LANES), 0)
        node_p = row_p * fp + lane_p // p_cnt        # node per probe elem
        # Static gather maps between the two fold factors (small arrays:
        # N*P elements).  window_idx: S-folded flat -> P-folded layout;
        # cand_idx: P-folded flat (or the trailing zero) -> S-folded.
        nd = np.arange(n)[:, None]
        j = np.arange(p_cnt)[None, :]
        window_idx = jnp.asarray(
            (nd * s + j).reshape(nfp, LANES), I32)
        q = np.arange(s)[None, :]
        cand_src = np.where(q < p_cnt, np.arange(n)[:, None] * p_cnt + q,
                            n * p_cnt)
        cand_idx = jnp.asarray(cand_src.reshape(nf, LANES), I32)

    def rep(v):
        """[N] per-node vector -> [NF, 128] per-element broadcast."""
        return jnp.repeat(v.reshape(nf, f), s, axis=1, total_repeat_length=LANES)

    def rowsum(x):
        return x.reshape(nf, f, s).sum(-1).reshape(n)

    def rowany(x):
        return x.reshape(nf, f, s).any(-1).reshape(n)

    from distributed_membership_tpu.backends.tpu_hash import (
        _ring_rng_builder)
    rng_build = _ring_rng_builder(cfg, use_drop)
    packed = cfg.probe_gather == "packed" and n >= 4

    def step(state, inputs):
        (t, key, start_ticks, fail_mask, fail_time, drop_lo,
         drop_hi) = inputs[:7]
        from distributed_membership_tpu.ops.rng_plan import RingRng
        rng = key if isinstance(key, RingRng) else rng_build(key)
        p_drop = cfg.drop_prob
        drop_active = (t > drop_lo) & (t <= drop_hi)

        # ---- scenario plan activation (tpu_hash.make_step's twin on
        # folded planes: same per-node quantities, rep()'d — the fold
        # contract keeps the two trajectories bit-exact) ----
        if scenario is not None:
            from distributed_membership_tpu.scenario.compile import (
                base_drop_prob, cross_group, cuts_at, delayed_mask,
                site_drop_prob, updown_masks)
            scn = inputs[7]
            if scenario.has_updown:
                down_now, up_now = updown_masks(scn, t, idx)
                fails_now = down_now | up_now
            else:
                down_now = up_now = fails_now = None
            cuts = cuts_at(scn, t, n) if scenario.n_parts else None
            cuts_prev = (cuts_at(scn, t - 1, n) if scenario.n_parts
                         else None)
        else:
            scn = fails_now = None

        recv_mask = state.started & (t > start_ticks) & ~state.failed
        act_base = recv_mask
        if scenario is not None and scenario.n_delays:
            # delay_window (tpu_hash.make_step's twin): delivery to
            # covered nodes is held — mail max-merges across held ticks
            # and drains after the window.  ``act`` derives from the
            # PRE-gate mask (act_base): in the natural twin act comes
            # from started/failed/in_group independently of the gated
            # recv_mask, so the folded act must not pick up the gate.
            recv_mask = recv_mask & ~delayed_mask(scn, t, idx)
        rcol = rep(recv_mask)
        telem_dropped = []      # TELEMETRY scalars only (guarded below)

        def wf_now():
            if fails_now is not None:
                return recv_mask & ~fails_now
            from distributed_membership_tpu.backends.tpu_hash import (
                _will_flush)
            return _will_flush(recv_mask, fail_mask, t, fail_time)

        recv_tick = jnp.where(recv_mask, state.pending_recv, 0)
        pending_recv = jnp.where(recv_mask, 0, state.pending_recv)

        # ---- self refresh (warm: join machinery is inert, omitted) ----
        act = act_base & state.in_group
        own_hb = state.self_hb + 1
        self_hb = jnp.where(act, state.self_hb + 2, state.self_hb)
        self_val = jnp.where(act, own_hb, 0).astype(U32) * U32(n) \
            + idx.astype(U32) + U32(1)

        # ---- ack candidates (gather pipeline, P-folded, shared) ----
        # Sits after act so the packed probe table can ride the counter
        # bits on the SAME gather (tpu_hash._pack_probe_table).
        ack_recv_cnt = jnp.zeros((n,), I32)
        cand_sf = jnp.zeros((nf, LANES), U32)
        will_flush = bits1 = None
        if p_cnt > 0:
            from distributed_membership_tpu.backends.tpu_hash import (
                _pack_probe_table)
            vec = jnp.where(state.act_prev, state.self_hb - 1, 0)
            tbl = ids1_for_tbl = None
            if packed and not cfg.probe_io_none:
                will_flush = wf_now()
                tbl = _pack_probe_table(vec, will_flush, act)
                ids1_for_tbl = state.probe_ids1
            cand_sf, ack_recv_cnt, bits1, ack_dropped = \
                _fold_ack_candidates(
                    n, s, p_cnt, fp, cand_idx, n, t, state.probe_ids2,
                    vec, recv_mask, rng.ack_u if use_drop else None,
                    p_drop, use_drop, drop_lo, drop_hi, tbl=tbl,
                    ids1=ids1_for_tbl, count_dropped=cfg.telemetry,
                    scn_ctx=(None if scenario is None else
                             (scenario, scn, cuts_prev, node_p)))
            if cfg.telemetry and ack_dropped is not None:
                telem_dropped.append(ack_dropped)

        # ---- receive: admit + ack + self + sweep (shared folded core) --
        (view, view_ts, mail, join_mask, rm_ids, numfailed, size, cur_id,
         present, difft) = _folded_receive(
            n, cfg.tfail, cfg.tremove, rep, rowsum, self_mask, node,
            t, state.view, state.view_ts, state.mail, cand_sf, rcol, act,
            self_val, fused=cfg.fused_receive, s=s, stride=STRIDE,
            interpret=jax.default_backend() != "tpu")

        # ---- gossip: circulant shifts in folded space ----
        numpotential = size - 1 - numfailed
        fresh = present & (difft < cfg.tfail)
        is_self_slot = cur_id == node
        k_eff = jnp.clip(jnp.minimum(cfg.fanout, numpotential), 0)

        keep = _fold_keep(g, s, fresh, is_self_slot, act, rep, rowsum,
                          rng.thin_u if g < s else None)
        if cfg.shift_set:
            # Static-table shifts (SHIFT_SET, same key stream and draw
            # as tpu_hash.make_step so folded stays bit-exact with the
            # natural sw run): with a Python-int shift, roll_nodes and
            # roll_slots lower to STATIC rolls throughout — the folded
            # gossip path carries zero dynamic lane rotates.
            from distributed_membership_tpu.backends.tpu_hash import (
                shift_table)
            table = shift_table(n, cfg.shift_set)
            shift_idx = rng.shift_draw
            shifts = jnp.asarray(table, I32)[shift_idx]
        else:
            shifts = rng.shift_draw
        sent_gossip = jnp.zeros((n,), I32)
        recv_add = jnp.zeros((n,), I32)

        def deliver_folded(r, payload, cnt):
            """One folded circulant delivery; ``r`` traced or Python int
            (the SHIFT_SET switch branches — mirrors
            tpu_hash.deliver_shift's dual contract)."""
            from distributed_membership_tpu.observability.timeline import (
                PHASE_GOSSIP)
            with jax.named_scope(PHASE_GOSSIP):
                static = isinstance(r, int)
                s1 = ((r % s) * cstride % s if static
                      else jax.lax.rem(jax.lax.rem(r, s) * cstride, s))
                rolled = roll_nodes(payload, r, f, s)
                r1 = roll_slots(rolled, s1, s)
                if single_col_roll:
                    delivered = r1
                else:
                    s2 = (((r - n) % s) * cstride % s if static
                          else jax.lax.rem(
                              jax.lax.rem(jax.lax.rem(r - n, s) + s, s)
                              * cstride, s))
                    r2 = roll_slots(rolled, s2, s)
                    delivered = jnp.where(rep((idx >= r)), r1, r2)
                return delivered, jnp.roll(cnt, r)

        stacked = []      # (payload, r, s1, s2) when cfg.fused_gossip
        for jshift in range(k_max):
            m = keep & rep(jshift < k_eff)
            r = shifts[jshift]
            if scenario is not None and (scenario.n_parts
                                         or scenario.n_flakes):
                dst_g = jax.lax.rem(idx + r, n)          # [N] per sender
            if scenario is not None and scenario.n_parts:
                m = m & ~rep(cross_group(cuts, idx, dst_g))
            if use_drop:
                if scenario is not None:
                    p_g = (site_drop_prob(scenario, scn, t, idx, dst_g)
                           if scenario.n_flakes
                           else base_drop_prob(scn, t))
                    p_ge = rep(p_g) if getattr(p_g, "ndim", 0) else p_g
                    gossip_coin = (rng.gossip_u[jshift].reshape(nf, LANES)
                                   < p_ge)
                else:
                    gossip_coin = ((rng.gossip_u[jshift].reshape(nf, LANES)
                                    < p_drop) & drop_active)
                if cfg.telemetry:
                    telem_dropped.append(
                        (m & gossip_coin).sum(dtype=I32))
                m = m & ~gossip_coin
            payload = jnp.where(m, view, U32(0))
            cnt = rowsum(m.astype(I32))
            sent_gossip = sent_gossip + cnt
            if cfg.fused_gossip:
                # All shifts accumulate in ONE Pallas traversal below
                # (ops/fused_folded.gossip_folded_stacked); payloads are
                # fully masked here — including any drop masks — so the
                # kernel is pure data movement.
                recv_add = recv_add + jnp.roll(cnt, r)
                s1 = jax.lax.rem(jax.lax.rem(r, s) * cstride, s)
                s2 = (jnp.asarray(0, I32) if single_col_roll
                      else jax.lax.rem(
                          jax.lax.rem(jax.lax.rem(r - n, s) + s, s)
                          * cstride, s))
                stacked.append((payload, r, s1, s2))
                continue
            if cfg.shift_set:
                delivered, cnt_r = jax.lax.switch(
                    shift_idx[jshift],
                    [(lambda pl, c, rv=rv: deliver_folded(rv, pl, c))
                     for rv in table], payload, cnt)
            else:
                delivered, cnt_r = deliver_folded(r, payload, cnt)
            recv_add = recv_add + cnt_r
            mail = jnp.maximum(mail, delivered)
        if cfg.fused_gossip and stacked:
            from distributed_membership_tpu.ops.fused_folded import (
                gossip_folded_stacked)
            mail = gossip_folded_stacked(
                nf, s, k_max, single_col_roll,
                jax.default_backend() != "tpu", mail,
                jnp.stack([p for p, _, _, _ in stacked]),
                jnp.stack([r for _, r, _, _ in stacked]),
                jnp.stack([s1 for _, _, s1, _ in stacked]),
                jnp.stack([s2 for _, _, _, s2 in stacked]))
        sent_tick = sent_gossip

        # ---- SWIM probes (P-folded, shared window issue) ----
        probe_ids1, probe_ids2 = state.probe_ids1, state.probe_ids2
        act_prev = state.act_prev
        pfo = None
        if p_cnt > 0:
            if cfg.fused_probe:
                (ids_new, p_valid, probe_dropped,
                 pfo) = _fold_probe_window_fused(
                    n, s, p_cnt, window_idx, cfg.tfail, cfg.fail_ids,
                    cfg.telemetry and cfg.telemetry_hist, True, t,
                    jnp.zeros((), I32), view, view_ts, rep(act), rm_ids,
                    node_p, rng.probe_u if use_drop else None, p_drop,
                    use_drop, drop_active, count_dropped=cfg.telemetry,
                    scn_ctx=(None if scenario is None else
                             (scenario, scn, cuts)))
            else:
                ids_new, p_valid, probe_dropped = _fold_probe_window(
                    n, s, p_cnt, fp, window_idx, n, t, view, act, node_p,
                    rng.probe_u if use_drop else None, p_drop, use_drop,
                    drop_active, count_dropped=cfg.telemetry,
                    scn_ctx=(None if scenario is None else
                             (scenario, scn, cuts)))
            if cfg.telemetry and probe_dropped is not None:
                telem_dropped.append(probe_dropped)
            probe_ids2, probe_ids1 = probe_ids1, ids_new
            act_prev = act
            psum_row = lambda x: _sumP(x, n, fp, p_cnt)  # noqa: E731
            sent_probes = psum_row(p_valid.astype(I32)) * p_red

            ids1 = state.probe_ids1
            v1 = ids1 > 0
            tgt1 = jnp.clip(ids1.astype(I32) - 1, 0)
            if cfg.count_probe_io:
                from distributed_membership_tpu.backends.tpu_hash import (
                    _gathered_act)
                # act-of-target filter rides the packed combined gather
                # (bits1 — _fold_ack_candidates) on the default arm, its
                # own gather on the split arm.
                ack_send = v1 & (act[tgt1] if bits1 is None
                                 else _gathered_act(bits1))
                recv_probe = jnp.zeros((n + 1,), I32).at[
                    jnp.where(v1, tgt1, n).reshape(-1)].add(
                        p_red, mode="drop")[:n]
                sent_ack = jnp.zeros((n + 1,), I32).at[
                    jnp.where(ack_send, tgt1, n).reshape(-1)].add(
                        1, mode="drop")[:n]
            elif cfg.probe_io_none:
                # PROFILING ONLY (PROBE_IO: none): zero the probe-recv/
                # ack-send counters, no per-target gather — probe sends /
                # ack recvs still counted (tpu_hash.make_step's twin).
                recv_probe = jnp.zeros((n,), I32)
                sent_ack = jnp.zeros((n,), I32)
            else:
                # Approximate per-node split, exact totals — the filters
                # of tpu_hash.make_step's scale branch on folded planes
                # (see _will_flush / _credit_orphan_recvs there).  On the
                # default arm the bits rode the combined ack gather
                # (bits1); the split arm gathers its own bit table.
                from distributed_membership_tpu.backends.tpu_hash import (
                    _credit_orphan_recvs, _gathered_act, _gathered_flush,
                    _pack_probe_bits)
                if bits1 is None:
                    will_flush = wf_now()
                    packed_g = _pack_probe_bits(will_flush, act)[tgt1]
                else:
                    packed_g = bits1
                per_prober = psum_row(
                    (v1 & _gathered_flush(packed_g)).astype(I32)) * p_red
                recv_probe = _credit_orphan_recvs(per_prober, will_flush)
                sent_ack = psum_row(
                    (v1 & _gathered_act(packed_g)).astype(I32))
            sent_tick = sent_tick + sent_probes + sent_ack
            recv_add = recv_add + recv_probe + ack_recv_cnt

        pending_recv = pending_recv + recv_add
        if scenario is not None and scenario.has_updown:
            # Scenario up/down transitions at end of tick — the folded
            # twin of tpu_hash.make_step's reset block (rep()'d planes).
            failed = (state.failed | down_now) & ~up_now
            up_e = rep(up_now)
            view = jnp.where(up_e, U32(0), view)
            view_ts = jnp.where(up_e, 0, view_ts)
            mail = jnp.where(up_e, U32(0), mail)
            pending_recv = jnp.where(up_now, 0, pending_recv)
            self_hb = jnp.where(up_now,
                                jnp.maximum(self_hb, 2 * (t + 1)),
                                self_hb)
            if p_cnt > 0:
                up_p = _repP(up_now, n, fp, p_cnt)
                probe_ids1 = jnp.where(up_p, U32(0), probe_ids1)
                probe_ids2 = jnp.where(up_p, U32(0), probe_ids2)
                act_prev = act_prev & ~up_now
        elif scenario is not None:
            failed = state.failed
        else:
            failed = state.failed | (fail_mask & (t == fail_time))

        pre = _fused_probe_pre(pfo, cfg.fail_ids, rowany)
        agg = update_fast_agg(
            state.agg, t=t, fail_ids=cfg.fail_ids,
            join_events=join_mask, rm_ids=rm_ids,
            view_ids=cur_id, view_present=present,
            fail_time=fail_time, holder_failed=fail_mask,
            sent_tick=sent_tick, recv_tick=recv_tick,
            row_any=rowany, row_expand=rep, pre=pre)
        out = SparseTickEvents(join_mask.sum(dtype=I32),
                               (pre["rm_total"] if pre is not None else
                                (rm_ids != EMPTY).sum(dtype=I32)),
                               sent_tick.sum(dtype=I32),
                               recv_tick.sum(dtype=I32))

        from distributed_membership_tpu.backends.tpu_hash import HashState
        new_state = HashState(view, view_ts, state.started, state.in_group,
                              failed, self_hb, mail, state.amail,
                              state.pmail, state.joinreq_infl,
                              state.joinrep_infl, pending_recv, agg,
                              probe_ids1, probe_ids2, act_prev,
                              state.wf_prev)
        if cfg.telemetry:
            # Flight-recorder scalars (observability/timeline.py) — the
            # folded twin of tpu_hash.make_step's emission, from the same
            # quantities on folded planes (bit-equal by the fold
            # contract; tests/test_timeline.py).
            from distributed_membership_tpu.observability.timeline import (
                PHASE_TELEMETRY, TickTelemetry, build_tick_hist)
            with jax.named_scope(PHASE_TELEMETRY):
                zero = jnp.zeros((), I32)
                det_tick = (agg.det_count.sum(dtype=I32)
                            - state.agg.det_count.sum(dtype=I32))
                dropped_tick = sum(telem_dropped, zero)
                telem = TickTelemetry(
                    live=act.sum(dtype=I32),
                    suspected=numfailed.sum(dtype=I32),
                    joins=out.join_ids,
                    removals=out.rm_ids,
                    detections=det_tick,
                    msgs_sent=out.sent,
                    msgs_recv=out.recv,
                    dropped=dropped_tick,
                    probe_acks=ack_recv_cnt.sum(dtype=I32),
                    gossip_rows=sent_gossip.sum(dtype=I32))
                if cfg.telemetry_hist:
                    # difft/present are folded planes; the shared
                    # builder reduces over every axis, and a fold is a
                    # reshape, so the counts are bit-equal to the
                    # natural twin's.  Under FUSED_PROBE the staleness/
                    # suspicion counts come off the fused traversal.
                    stale = susp = None
                    if pfo is not None and "stale_rows" in pfo:
                        stale = pfo["stale_rows"].sum(axis=0)
                        susp = pfo["susp_rows"].sum(axis=0)
                    hist = build_tick_hist(
                        difft=difft, present=present, size=size,
                        act=act, t=t, fail_time=fail_time,
                        tfail=cfg.tfail, det_tick=det_tick,
                        dropped=dropped_tick, stale=stale, susp=susp)
                    return new_state, (out, (telem, hist))
            return new_state, (out, telem)
        return new_state, out

    return step


def make_ring_sharded_folded_step(cfg, n_local: int, n_shards: int,
                                  axes=None, axis_sizes=()):
    """Folded twin of make_ring_sharded_step's warm path
    (tpu_hash_sharded.py): local planes are ``[L/F, 128]``, so the
    per-shift ``ppermute`` moves 1/F the bytes over ICI as well as HBM.
    Bit-exact with the natural sharded ring step at the same seed
    (tests/test_folded.py); cold joins keep the natural layout (the
    make_config gate requires JOIN_MODE warm for FOLDED)."""
    from jax import lax

    from distributed_membership_tpu.backends.tpu_hash import (
        STRIDE, HashConfig)
    from distributed_membership_tpu.backends.tpu_hash_sharded import (
        NODE_AXIS, ShardedHashState, make_block_send)
    if axes is None:
        axes = (NODE_AXIS,)
    assert isinstance(cfg, HashConfig) and cfg.exchange == "ring"
    n, s, g, p_cnt = cfg.n, cfg.s, cfg.g, cfg.probes
    f = LANES // s
    lf = n_local // f
    k_max = min(cfg.fanout, s)
    scenario = cfg.scenario
    use_drop = cfg.drop_prob > 0.0 or (scenario is not None
                                       and scenario.has_drop)
    p_red = 1 if cfg.qp >= n else 2
    cstride = STRIDE % s
    single_col_roll = (n_local * STRIDE) % s == 0
    l_idx = jnp.arange(n_local, dtype=I32)

    lane = jax.lax.broadcasted_iota(I32, (lf, LANES), 1)
    row = jax.lax.broadcasted_iota(I32, (lf, LANES), 0)
    pos = jax.lax.rem(lane, s)
    local_node = row * f + lane // s                 # local row index

    if p_cnt > 0:
        fp = LANES // p_cnt
        lfp = n_local // fp
        lane_p = jax.lax.broadcasted_iota(I32, (lfp, LANES), 1)
        row_p = jax.lax.broadcasted_iota(I32, (lfp, LANES), 0)
        local_node_p = row_p * fp + lane_p // p_cnt
        nd = np.arange(n_local)[:, None]
        j = np.arange(p_cnt)[None, :]
        window_idx = jnp.asarray((nd * s + j).reshape(lfp, LANES), I32)
        q = np.arange(s)[None, :]
        cand_src = np.where(q < p_cnt,
                            np.arange(n_local)[:, None] * p_cnt + q,
                            n_local * p_cnt)
        cand_idx = jnp.asarray(cand_src.reshape(lf, LANES), I32)

    def rep(v):
        return jnp.repeat(v.reshape(lf, f), s, axis=1,
                          total_repeat_length=LANES)

    def rowsum(x):
        return x.reshape(lf, f, s).sum(-1).reshape(n_local)

    def rowany(x):
        return x.reshape(lf, f, s).any(-1).reshape(n_local)

    AX = axes if len(axes) > 1 else axes[0]
    block_send = make_block_send(n_shards, axes, axis_sizes or (n_shards,))
    bx = None
    if cfg.batched_exchange:
        # EXCHANGE_MODE batched on folded planes: one all_to_all per
        # tick with sender-side folded alignment (ops/exchange.py);
        # result carried one tick in the (state, xbuf) lane — see the
        # natural twin for the bit-exactness argument.
        from distributed_membership_tpu.ops.exchange import BatchedExchange
        bx = BatchedExchange(
            n_shards=n_shards, axes=axes, n_local=n_local, s=s,
            cstride=cstride, single_col_roll=single_col_roll,
            folded=True, lanes=LANES)

    from distributed_membership_tpu.ops.rng_plan import (
        RingRng, sharded_ring_rng)
    packed = cfg.probe_gather == "packed" and n >= 4
    seed_rows = min(cfg.seed_cap, n)

    def step(state, inputs):
        xbuf = None
        if bx is not None:
            state, xbuf = state
        (t, key, start_ticks_g, fail_mask_g, fail_time, drop_lo,
         drop_hi) = inputs[:7]
        me = lax.axis_index(AX)
        row0 = (me * n_local).astype(I32)
        lrows = row0 + l_idx
        node = local_node + row0                     # global id / element
        self_slot = jax.lax.rem(
            jax.lax.rem(node, s) * ((1 + STRIDE) % s), s)
        self_mask = pos == self_slot
        fail_mask_l = lax.dynamic_slice(fail_mask_g, (row0,), (n_local,))
        start_ticks_l = lax.dynamic_slice(start_ticks_g, (row0,),
                                          (n_local,))
        rng = key if isinstance(key, RingRng) else sharded_ring_rng(
            key, me, n=n, n_local=n_local, s=s, g=g, k_max=k_max,
            p_cnt=max(p_cnt, 0), seed_rows=seed_rows, use_drop=use_drop,
            cold_join=False, batched=cfg.rng_mode != "scattered")
        drop_active = (t > drop_lo) & (t <= drop_hi)

        # ---- scenario plan activation (local rows; the tensors are
        # replicated inputs, so every shard computes its slice
        # elementwise — no collectives added) ----
        if scenario is not None:
            from distributed_membership_tpu.scenario.compile import (
                base_drop_prob, cross_group, cuts_at, delayed_mask,
                site_drop_prob, updown_masks)
            scn = inputs[7]
            if scenario.has_updown:
                down_now, up_now = updown_masks(scn, t, lrows)
                fails_now = down_now | up_now
            else:
                down_now = up_now = fails_now = None
            cuts = cuts_at(scn, t, n) if scenario.n_parts else None
            cuts_prev = (cuts_at(scn, t - 1, n) if scenario.n_parts
                         else None)
        else:
            scn = fails_now = None

        recv_mask = state.started & (t > start_ticks_l) & ~state.failed
        act_base = recv_mask
        if scenario is not None and scenario.n_delays:
            # delay_window on local rows (see the single-shard folded
            # twin): gate delivery only; ``act`` keeps the pre-gate
            # mask.  The xbuf head-merge below still lands held wire
            # mail into the carry (mail_cleared preserves it), so
            # nothing is lost across the window.
            recv_mask = recv_mask & ~delayed_mask(scn, t, lrows)
        rcol = rep(recv_mask)
        telem_dropped = []      # TELEMETRY scalars only (guarded below)

        def wf_now():
            if fails_now is not None:
                return recv_mask & ~fails_now
            from distributed_membership_tpu.backends.tpu_hash import (
                _will_flush)
            return _will_flush(recv_mask, fail_mask_l, t, fail_time)

        # xbuf head-merge (batched exchange): last tick's collective
        # lands exactly where the legacy merge becomes observable.
        pend_eff = state.pending_recv
        mail_eff = state.mail
        if bx is not None:
            pend_eff = pend_eff + bx.merge_pending(xbuf[1])
            mail_eff = bx.merge_mail(mail_eff, xbuf[0])
        recv_tick = jnp.where(recv_mask, pend_eff, 0)
        pending_recv = jnp.where(recv_mask, 0, pend_eff)

        # ---- self refresh (warm: join machinery inert) ----
        act = act_base & state.in_group
        own_hb = state.self_hb + 1
        self_hb = jnp.where(act, state.self_hb + 2, state.self_hb)
        self_val = jnp.where(act, own_hb, 0).astype(U32) * U32(n) \
            + lrows.astype(U32) + U32(1)

        # ---- ack candidates (gather pipeline, P-folded, shared) ----
        # After act: on the packed arm the per-node probe table
        # (heartbeat + will-flush + act bits, tpu_hash._pack_probe_table)
        # travels as ONE [N] u32 all_gather — replacing the separate
        # vec/act/will_flush gathers — and the counter bits ride the
        # same concatenated per-target gather.
        ack_recv_cnt = jnp.zeros((n_local,), I32)
        cand_sf = jnp.zeros((lf, LANES), U32)
        will_flush_l = will_flush_g = bits1 = None
        if p_cnt > 0:
            from distributed_membership_tpu.backends.tpu_hash import (
                _gathered_flush, _pack_probe_table)
            vec_l = jnp.where(state.act_prev, state.self_hb - 1, 0)
            tbl = ids1_for_tbl = None
            if packed and not cfg.probe_io_none:
                will_flush_l = wf_now()
                tbl = lax.all_gather(
                    _pack_probe_table(vec_l, will_flush_l, act), AX,
                    tiled=True)                             # ONE [N] wire
                will_flush_g = _gathered_flush(tbl)
                vec_g = None
                ids1_for_tbl = state.probe_ids1
            else:
                vec_g = lax.all_gather(vec_l, AX, tiled=True)    # [N]
            cand_sf, ack_recv_cnt, bits1, ack_dropped = \
                _fold_ack_candidates(
                    n, s, p_cnt, fp, cand_idx, n_local, t,
                    state.probe_ids2, vec_g, recv_mask,
                    rng.ack_u if use_drop else None, cfg.drop_prob,
                    use_drop, drop_lo, drop_hi, tbl=tbl,
                    ids1=ids1_for_tbl, count_dropped=cfg.telemetry,
                    scn_ctx=(None if scenario is None else
                             (scenario, scn, cuts_prev,
                              local_node_p + row0)))
            if cfg.telemetry and ack_dropped is not None:
                telem_dropped.append(ack_dropped)

        # ---- receive: admit + ack + self + sweep (shared folded core) --
        (view, view_ts, mail, join_mask, rm_ids, numfailed, size, cur_id,
         present, difft) = _folded_receive(
            n, cfg.tfail, cfg.tremove, rep, rowsum, self_mask, node,
            t, state.view, state.view_ts, mail_eff, cand_sf, rcol, act,
            self_val, fused=cfg.fused_receive, s=s, stride=STRIDE,
            interpret=jax.default_backend() != "tpu", row0=row0)

        # ---- gossip: torus-product shifts, folded local planes ----
        numpotential = size - 1 - numfailed
        fresh = present & (difft < cfg.tfail)
        is_self_slot = cur_id == node
        k_eff = jnp.clip(jnp.minimum(cfg.fanout, numpotential), 0)
        keep = _fold_keep(g, s, fresh, is_self_slot, act, rep, rowsum,
                          rng.thin_u if g < s else None)

        shifts = rng.shift_draw
        sent_gossip = jnp.zeros((n_local,), I32)
        recv_add = jnp.zeros((n_local,), I32)
        stacked = []      # (payload_r, c, s1, s2) when cfg.fused_gossip
        bpay = bcnt = None
        if bx is not None:
            bpay, bcnt = bx.zero()
        for jshift in range(k_max):
            m = keep & rep(jshift < k_eff)
            u = shifts[jshift]
            if scenario is not None and (scenario.n_parts
                                         or scenario.n_flakes):
                dst_g = lax.rem(lrows + u, n)        # [L] per sender row
            if scenario is not None and scenario.n_parts:
                m = m & ~rep(cross_group(cuts, lrows, dst_g))
            if use_drop:
                if scenario is not None:
                    p_g = (site_drop_prob(scenario, scn, t, lrows, dst_g)
                           if scenario.n_flakes
                           else base_drop_prob(scn, t))
                    p_ge = rep(p_g) if getattr(p_g, "ndim", 0) else p_g
                    gossip_coin = (rng.gossip_u[jshift].reshape(lf, LANES)
                                   < p_ge)
                else:
                    gossip_coin = ((rng.gossip_u[jshift].reshape(lf, LANES)
                                    < cfg.drop_prob) & drop_active)
                if cfg.telemetry:
                    telem_dropped.append(
                        (m & gossip_coin).sum(dtype=I32))
                m = m & ~gossip_coin
            payload = jnp.where(m, view, U32(0))
            cnt = rowsum(m.astype(I32))
            sent_gossip = sent_gossip + cnt
            b = u // n_local
            c = lax.rem(u, n_local)
            if bx is not None:
                # Sender-side folded alignment + destination bucketing;
                # the wire hop happens ONCE after the loop.
                bpay, bcnt = bx.add_shift(bpay, bcnt, payload, cnt,
                                          b, c, me)
                continue
            payload_r, cnt_r = block_send((payload, cnt), b)
            cnt_r = jnp.roll(cnt_r, c, axis=0)
            recv_add = recv_add + cnt_r
            bp = jnp.where(me < b, b - n_shards, b)
            base1 = lax.rem(lax.rem(bp * n_local + c, s) + s, s)
            s1 = lax.rem(base1 * cstride, s)
            base2 = lax.rem(
                lax.rem(bp * n_local + c - n_local, s) + s, s)
            s2 = lax.rem(base2 * cstride, s)
            if cfg.fused_gossip:
                # The Pallas accumulate below applies the intra-shard
                # folded row roll + slot alignment for ALL shifts in one
                # mail traversal (ops/fused_folded.gossip_folded_stacked);
                # the ppermute wire hop above stays as is.
                stacked.append((payload_r, c, s1, s2))
                continue
            from distributed_membership_tpu.observability.timeline import (
                PHASE_GOSSIP)
            with jax.named_scope(PHASE_GOSSIP):
                payload_r = roll_nodes(payload_r, c, f, s)
                r1 = roll_slots(payload_r, s1, s)
                if single_col_roll:
                    result = r1
                else:
                    r2 = roll_slots(payload_r, s2, s)
                    result = jnp.where(rep(l_idx >= c), r1, r2)
                mail = jnp.maximum(mail, result)
        if cfg.fused_gossip and stacked:
            from distributed_membership_tpu.ops.fused_folded import (
                gossip_folded_stacked)
            mail = gossip_folded_stacked(
                lf, s, k_max, single_col_roll,
                jax.default_backend() != "tpu", mail,
                jnp.stack([p for p, _, _, _ in stacked]),
                jnp.stack([c for _, c, _, _ in stacked]),
                jnp.stack([s1 for _, _, s1, _ in stacked]),
                jnp.stack([s2 for _, _, _, s2 in stacked]))
        xnew = None
        if bx is not None:
            # The tick's ONLY exchange launch; its result rides the
            # carry to the next head (unconsumed here), so XLA overlaps
            # the collective with the probe/agg tail below.
            xnew = bx.exchange(bpay, bcnt)
        sent_tick = sent_gossip

        # ---- probe issue (P-folded, shared) ----
        probe_ids1, probe_ids2 = state.probe_ids1, state.probe_ids2
        act_prev = state.act_prev
        pfo = None
        if p_cnt > 0:
            if cfg.fused_probe:
                (ids_new, p_valid, probe_dropped,
                 pfo) = _fold_probe_window_fused(
                    n, s, p_cnt, window_idx, cfg.tfail, cfg.fail_ids,
                    cfg.telemetry and cfg.telemetry_hist, True, t,
                    row0, view, view_ts, rep(act), rm_ids,
                    local_node_p + row0,
                    rng.probe_u if use_drop else None, cfg.drop_prob,
                    use_drop, drop_active, count_dropped=cfg.telemetry,
                    scn_ctx=(None if scenario is None else
                             (scenario, scn, cuts)))
            else:
                ids_new, p_valid, probe_dropped = _fold_probe_window(
                    n, s, p_cnt, fp, window_idx, n_local, t, view, act,
                    local_node_p + row0,
                    rng.probe_u if use_drop else None,
                    cfg.drop_prob, use_drop, drop_active,
                    count_dropped=cfg.telemetry,
                    scn_ctx=(None if scenario is None else
                             (scenario, scn, cuts)))
            if cfg.telemetry and probe_dropped is not None:
                telem_dropped.append(probe_dropped)
            probe_ids2, probe_ids1 = probe_ids1, ids_new
            act_prev = act
            psum_row = lambda x: _sumP(x, n_local, fp, p_cnt)  # noqa: E731
            sent_probes = psum_row(p_valid.astype(I32)) * p_red
            # Counter attribution: the folded twin of the natural sharded
            # step's exact/approx branches (tpu_hash_sharded
            # make_ring_sharded_step — same expressions on P-folded
            # planes, so the two runs stay bit-exact).
            ids1 = state.probe_ids1
            v1 = ids1 > 0
            tgt1 = jnp.clip(ids1.astype(I32) - 1, 0)    # global target ids
            # act_g gathered per-branch on the split arm only: the packed
            # arm's act bit already rode the single all_gather + combined
            # gather (bits1), and the profiling-only 'none' branch must
            # structurally pay no [N] all_gather (its whole point is
            # removing the counter-side ops from the measured tick).
            if cfg.count_probe_io:
                from distributed_membership_tpu.backends.tpu_hash import (
                    _gathered_act as _g_act)
                if bits1 is None:
                    act_g = lax.all_gather(act, AX, tiled=True)  # [N]
                    ack_send = v1 & act_g[tgt1]
                else:
                    ack_send = v1 & _g_act(bits1)
                recv_hist = jnp.zeros((n + 1,), I32).at[
                    jnp.where(v1, tgt1, n).reshape(-1)].add(
                        p_red, mode="drop")[:n]
                ack_hist = jnp.zeros((n + 1,), I32).at[
                    jnp.where(ack_send, tgt1, n).reshape(-1)].add(
                        1, mode="drop")[:n]
                recv_probe = lax.psum_scatter(
                    recv_hist, AX, scatter_dimension=0, tiled=True)
                sent_ack = lax.psum_scatter(
                    ack_hist, AX, scatter_dimension=0, tiled=True)
            elif cfg.probe_io_none:
                # PROFILING ONLY (PROBE_IO: none): zero the probe-recv/
                # ack-send counters, no per-target gather — probe sends /
                # ack recvs still counted (tpu_hash.make_step's twin).
                recv_probe = jnp.zeros((n_local,), I32)
                sent_ack = jnp.zeros((n_local,), I32)
            else:
                from distributed_membership_tpu.backends.tpu_hash import (
                    _credit_orphan_recvs_sharded, _gathered_act,
                    _gathered_flush, _pack_probe_bits)
                if bits1 is None:
                    # split arm: three separate all_gathers + a bit-table
                    # gather (the pre-round-6 lowering).
                    will_flush_l = wf_now()
                    will_flush_g = lax.all_gather(
                        will_flush_l, AX, tiled=True)        # [N]
                    act_g = lax.all_gather(act, AX, tiled=True)  # [N]
                    packed_g = _pack_probe_bits(will_flush_g, act_g)[tgt1]
                else:
                    # packed arm: the bits rode the combined gather, and
                    # will_flush_g is the single all_gathered table's
                    # low bit (ack-candidate block above).
                    packed_g = bits1
                per_prober = psum_row(
                    (v1 & _gathered_flush(packed_g)).astype(I32)) * p_red
                recv_probe = _credit_orphan_recvs_sharded(
                    per_prober, will_flush_l, will_flush_g, lrows,
                    AX)
                sent_ack = psum_row(
                    (v1 & _gathered_act(packed_g)).astype(I32))
            sent_tick = sent_tick + sent_probes + sent_ack
            recv_add = recv_add + recv_probe + ack_recv_cnt

        pending_recv = pending_recv + recv_add
        if scenario is not None and scenario.has_updown:
            failed = (state.failed | down_now) & ~up_now
            up_e = rep(up_now)
            view = jnp.where(up_e, U32(0), view)
            view_ts = jnp.where(up_e, 0, view_ts)
            mail = jnp.where(up_e, U32(0), mail)
            pending_recv = jnp.where(up_now, 0, pending_recv)
            self_hb = jnp.where(up_now,
                                jnp.maximum(self_hb, 2 * (t + 1)),
                                self_hb)
            if p_cnt > 0:
                up_p = _repP(up_now, n_local, fp, p_cnt)
                probe_ids1 = jnp.where(up_p, U32(0), probe_ids1)
                probe_ids2 = jnp.where(up_p, U32(0), probe_ids2)
                act_prev = act_prev & ~up_now
            if bx is not None:
                # Chase the up/down wipe into the fresh exchange (the
                # legacy merge precedes this wipe; see natural twin).
                xnew = bx.wipe(*xnew, up_now)
        elif scenario is not None:
            failed = state.failed
        else:
            failed = state.failed | (fail_mask_l & (t == fail_time))

        pre = _fused_probe_pre(pfo, cfg.fail_ids, rowany)
        agg = update_fast_agg(
            state.agg, t=t, fail_ids=cfg.fail_ids,
            join_events=join_mask, rm_ids=rm_ids,
            view_ids=cur_id, view_present=present,
            fail_time=fail_time, holder_failed=fail_mask_l,
            sent_tick=sent_tick, recv_tick=recv_tick,
            row_any=rowany, row_expand=rep, pre=pre)
        out = SparseTickEvents(
            lax.psum(join_mask.sum(dtype=I32), AX),
            lax.psum(pre["rm_total"] if pre is not None else
                     (rm_ids != EMPTY).sum(dtype=I32), AX),
            lax.psum(sent_tick.sum(dtype=I32), AX),
            lax.psum(recv_tick.sum(dtype=I32), AX))

        new_state = ShardedHashState(
            view, view_ts, state.started, state.in_group, failed,
            self_hb, mail, state.amail, state.pmail,
            state.joinreq_infl, state.joinrep_infl, pending_recv, agg,
            probe_ids1, probe_ids2, act_prev)
        if bx is not None:
            new_state = (new_state, xnew)
        if cfg.telemetry:
            # Sharded flight-recorder scalars: local reductions + one
            # psum each (observability/timeline.py).
            from distributed_membership_tpu.observability.timeline import (
                PHASE_TELEMETRY, TickTelemetry, build_tick_hist)
            with jax.named_scope(PHASE_TELEMETRY):
                zero = jnp.zeros((), I32)
                det_local = (agg.det_count.sum(dtype=I32)
                             - state.agg.det_count.sum(dtype=I32))
                dropped_g = lax.psum(sum(telem_dropped, zero), AX)
                telem = TickTelemetry(
                    live=lax.psum(act.sum(dtype=I32), AX),
                    suspected=lax.psum(numfailed.sum(dtype=I32), AX),
                    joins=out.join_ids,
                    removals=out.rm_ids,
                    detections=lax.psum(det_local, AX),
                    msgs_sent=out.sent,
                    msgs_recv=out.recv,
                    dropped=dropped_g,
                    probe_acks=lax.psum(ack_recv_cnt.sum(dtype=I32), AX),
                    gossip_rows=lax.psum(sent_gossip.sum(dtype=I32), AX))
                if cfg.telemetry_hist:
                    # Local partial histograms psum'd per field (the
                    # count reductions are linear); the log2 drop bucket
                    # is not, so it takes the GLOBAL dropped scalar.
                    # Fused-probe stale/susp partials are local too —
                    # the builder psums them with the rest.
                    stale = susp = None
                    if pfo is not None and "stale_rows" in pfo:
                        stale = pfo["stale_rows"].sum(axis=0)
                        susp = pfo["susp_rows"].sum(axis=0)
                    hist = build_tick_hist(
                        difft=difft, present=present, size=size,
                        act=act, t=t, fail_time=fail_time,
                        tfail=cfg.tfail, det_tick=det_local,
                        dropped=dropped_g,
                        psum=lambda v: lax.psum(v, AX),
                        stale=stale, susp=susp)
                    return new_state, (out, (telem, hist))
            return new_state, (out, telem)
        return new_state, out

    step.batched_exchange = bx
    return step


def init_local_state_warm_folded(cfg, n_local: int, key: jax.Array,
                                 ax=None):
    """Fold of tpu_hash_sharded.init_local_state_warm (pure reshape)."""
    from distributed_membership_tpu.backends.tpu_hash_sharded import (
        NODE_AXIS, ShardedHashState, init_local_state_warm)
    st = init_local_state_warm(cfg, n_local, key,
                               ax=NODE_AXIS if ax is None else ax)
    f = LANES // cfg.s
    lf = n_local // f
    probe_shape = ((n_local // (LANES // cfg.probes), LANES)
                   if cfg.probes > 0 else (1, 1))
    return ShardedHashState(
        view=st.view.reshape(lf, LANES),
        view_ts=st.view_ts.reshape(lf, LANES),
        started=st.started, in_group=st.in_group, failed=st.failed,
        self_hb=st.self_hb,
        mail=st.mail.reshape(lf, LANES),
        amail=st.amail, pmail=st.pmail,
        joinreq_infl=st.joinreq_infl, joinrep_infl=st.joinrep_infl,
        pending_recv=st.pending_recv,
        agg=init_fast_agg(len(cfg.fail_ids), n_local),
        probe_ids1=jnp.zeros(probe_shape, U32),
        probe_ids2=jnp.zeros(probe_shape, U32),
        act_prev=jnp.zeros((n_local,), bool),
    )


def init_state_warm_folded(cfg, key: jax.Array):
    """Fold of tpu_hash.init_state_warm: identical content, folded shapes
    (a pure reshape of the unfolded warm state — one-time relayout)."""
    from distributed_membership_tpu.backends.tpu_hash import (
        HashState, init_state_warm)
    st = init_state_warm(cfg, key)
    f = LANES // cfg.s
    nf = cfg.n // f
    probe_shape = ((cfg.n // (LANES // cfg.probes), LANES)
                   if cfg.probes > 0 else (1, 1))
    return HashState(
        view=st.view.reshape(nf, LANES),
        view_ts=st.view_ts.reshape(nf, LANES),
        started=st.started, in_group=st.in_group, failed=st.failed,
        self_hb=st.self_hb,
        mail=st.mail.reshape(nf, LANES),
        amail=st.amail, pmail=st.pmail,
        joinreq_infl=st.joinreq_infl, joinrep_infl=st.joinrep_infl,
        pending_recv=st.pending_recv,
        agg=init_fast_agg(len(cfg.fail_ids), cfg.n),
        probe_ids1=jnp.zeros(probe_shape, U32),
        probe_ids2=jnp.zeros(probe_shape, U32),
        act_prev=jnp.zeros((cfg.n,), bool),
        wf_prev=jnp.zeros((1,), bool),   # approx_lag is natural-layout only
    )
