"""`tpu` backend: the protocol as one jitted tensor transition.

The entire reference hot path — ENrecv buffer scans, per-message list merges,
the TFAIL/TREMOVE sweep, gossip sends (SURVEY.md §3.2's four hot loops) —
fuses into a single pure function ``step(state, t)`` over dense
``[N, N]`` tensors, run under ``lax.scan`` for the whole simulation with no
per-tick host synchronization.  Event extraction (the joined/removed log
lines the grader reads) happens host-side afterwards by scanning the stacked
per-tick event tensors.

Why this is *exactly* (not approximately) the reference protocol, tick for
tick: the receiver-side merge keeps the max heartbeat per entry and refreshes
the local timestamp only on strict increase (MP1Node.cpp:278-288) — a
commutative, associative combine — and cross-node interaction happens only
through the 1-tick-latency message buffer (messages sent in pass 2 of tick t
are received in pass 1 of tick t+1, Application.cpp:121-164).  Hence the
reference's sequential per-node processing order within a tick is
unobservable in the state, and a synchronous-parallel tensor step computes
the identical state trajectory.  The only divergences are RNG draws (seeded
jax.random here vs the reference's random_device mt19937, MP1Node.cpp:450)
and log-line ordering — both checked distributionally against the `emul`
backend (tests/test_tpu_backend.py).

Structure-of-arrays state, one row per node:
  present/hb/ts [N,N]  — member list as a dense table indexed by node id
                         (id i+1 ↔ column i); heartbeats int32 (justified
                         downcast from the reference's long: +2/tick for
                         TOTAL_TIME ticks, bound checked in Params.validate)
  infl_*        [N,N]  — in-flight messages, max-aggregated per receiver —
                         this *is* EmulNet's buffer, reduced eagerly; entries
                         addressed to not-yet-receiving nodes accumulate
                         losslessly under max (join staggering, dead nodes)
  joinreq/joinrep [N]  — the join handshake (MP1Node.cpp:126-163,226-251)
  pending_recv  [N]    — queued message counts for the recv_msgs profile
"""

from __future__ import annotations

import dataclasses
import random as _pyrandom
import time as _time
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from distributed_membership_tpu.addressing import INTRODUCER_INDEX
from distributed_membership_tpu.backends import RunResult, register
from distributed_membership_tpu.config import Params
from distributed_membership_tpu.eventlog import EventLog
from distributed_membership_tpu.ops.merge import broadcast_deliver, fanout_deliver_indexed
from distributed_membership_tpu.ops.sampling import sample_k_indices
from distributed_membership_tpu.runtime.failures import (
    FailurePlan, log_failures, plan_tensors, resolve_plan)

I32 = jnp.int32


class State(NamedTuple):
    present: jax.Array      # [N,N] bool
    hb: jax.Array           # [N,N] i32
    ts: jax.Array           # [N,N] i32
    started: jax.Array      # [N] bool
    in_group: jax.Array     # [N] bool
    failed: jax.Array       # [N] bool
    self_hb: jax.Array      # [N] i32
    infl_has: jax.Array     # [N,N] bool
    infl_hb: jax.Array      # [N,N] i32
    joinreq_infl: jax.Array  # [N] bool — JOINREQ awaiting the introducer
    joinrep_infl: jax.Array  # [N] bool — JOINREP awaiting the joiner
    pending_recv: jax.Array  # [N] i32


class TickEvents(NamedTuple):
    joins: jax.Array        # [N,N] bool — logger i added entry j this tick
    removes: jax.Array      # [N,N] bool
    sent: jax.Array         # [N] i32
    recv: jax.Array         # [N] i32


@dataclasses.dataclass(frozen=True)
class StepConfig:
    """Static (compile-time) protocol constants."""
    n: int
    tfail: int
    tremove: int
    fanout: int
    drop_prob: float        # effective int(p*100)/100, 0 disables drop code
    collect_events: bool = True


def init_state(n: int) -> State:
    return State(
        present=jnp.zeros((n, n), bool),
        hb=jnp.zeros((n, n), I32),
        ts=jnp.zeros((n, n), I32),
        started=jnp.zeros((n,), bool),
        in_group=jnp.zeros((n,), bool),
        failed=jnp.zeros((n,), bool),
        self_hb=jnp.zeros((n,), I32),
        infl_has=jnp.zeros((n, n), bool),
        infl_hb=jnp.full((n, n), -1, I32),
        joinreq_infl=jnp.zeros((n,), bool),
        joinrep_infl=jnp.zeros((n,), bool),
        pending_recv=jnp.zeros((n,), I32),
    )


def make_step(cfg: StepConfig):
    """Build the per-tick transition.

    The returned function has signature
    ``step(state, (t, key, start_ticks, fail_mask, fail_time, drop_window))
    -> (state, TickEvents)`` and is pure/jittable; dynamic per-run inputs
    (schedules) are tensors so one compilation serves every seed/scenario of
    the same shape.
    """
    n = cfg.n
    idx = jnp.arange(n)
    intro = INTRODUCER_INDEX

    def step(state: State, inputs):
        t, key, start_ticks, fail_mask, fail_time, drop_lo, drop_hi = inputs
        k_targets, k_drop, k_ctrl = jax.random.split(key, 3)

        # Effective drop window: the emul driver flips dropmsg *after* pass 2
        # of DROP_START and clears it after pass 2 of DROP_STOP, so sends are
        # dropped for t in (DROP_START, DROP_STOP] (Application.cpp:177-179,
        # 198-200 ordering within Application::run).
        drop_active = (t > drop_lo) & (t <= drop_hi)
        # Control messages (JOINREQ/JOINREP) face the same Bernoulli drop as
        # any send — EmulNet::ENsend makes no message-type distinction.  A
        # dropped JOINREQ strands the joiner forever, as in the reference
        # (sent exactly once, MP1Node.cpp:126-163); only reachable when the
        # join schedule overlaps the drop window (large staggered N).
        if cfg.drop_prob > 0.0:
            ctrl_kept = ~(jax.random.bernoulli(k_ctrl, cfg.drop_prob, (2, n))
                          & drop_active)
        else:
            ctrl_kept = jnp.ones((2, n), bool)

        # ---- pass 1 + message handling: deliver in-flight, merge, join
        # handshake (MP1Node::recvLoop + checkMessages; identical eligibility
        # gates, Application.cpp:130,153) ----
        recv_mask = state.started & (t > start_ticks) & ~state.failed
        deliver = state.infl_has & recv_mask[:, None]
        newly = deliver & ~state.present
        upd = deliver & state.present & (state.infl_hb > state.hb)
        present = state.present | newly
        hb = jnp.where(newly | upd, state.infl_hb, state.hb)
        ts = jnp.where(newly | upd, t, state.ts)
        infl_has = state.infl_has & ~recv_mask[:, None]
        infl_hb = jnp.where(recv_mask[:, None], -1, state.infl_hb)
        join_events = newly

        recv_tick = jnp.where(recv_mask, state.pending_recv, 0)
        pending_recv = jnp.where(recv_mask, 0, state.pending_recv)

        in_group = state.in_group | (state.joinrep_infl & recv_mask)
        joinrep_infl = state.joinrep_infl & ~recv_mask

        # JOINREQs reaching the introducer this tick: these joiners are
        # guaranteed gossip targets ("newNodes", MP1Node.cpp:240-242,454)
        # and each gets a JOINREP (MP1Node.cpp:246-250).
        seeds = state.joinreq_infl & recv_mask[intro]
        joinreq_infl = state.joinreq_infl & ~recv_mask[intro]
        rep_ok = seeds & ctrl_kept[1]  # JOINREPs that survive the drop window
        joinrep_infl = joinrep_infl | rep_ok
        n_seeds = seeds.sum(dtype=I32)
        sent_rep = jnp.where(idx == intro,
                             jnp.where(recv_mask[intro], rep_ok.sum(dtype=I32), 0), 0)
        pending_recv = pending_recv + rep_ok.astype(I32)

        # ---- nodeStart (Application.cpp:143-148, MP1Node.cpp:73-163) ----
        start_now = t == start_ticks
        started = state.started | start_now
        boot = start_now[intro]  # introducer boots the group
        present = present.at[intro, intro].set(present[intro, intro] | boot)
        hb = hb.at[intro, intro].set(jnp.where(boot, 0, hb[intro, intro]))
        ts = ts.at[intro, intro].set(jnp.where(boot, t, ts[intro, intro]))
        in_group = in_group.at[intro].set(in_group[intro] | boot)

        joiner_req = start_now & (idx != intro) & ctrl_kept[0]
        infl_has = infl_has.at[intro].set(infl_has[intro] | joiner_req)
        infl_hb = infl_hb.at[intro].set(
            jnp.where(joiner_req, jnp.maximum(infl_hb[intro], 0), infl_hb[intro]))
        joinreq_infl = joinreq_infl | joiner_req
        pending_recv = pending_recv.at[intro].add(joiner_req.sum(dtype=I32))
        sent_req = joiner_req.astype(I32)

        # ---- pass 2: nodeLoopOps (MP1Node.cpp:404-495) ----
        act = started & (t > start_ticks) & ~state.failed & in_group

        # Self refresh: the double heartbeat increment — own entry gets the
        # odd intermediate value (MP1Node.cpp:412-415).
        own_hb = state.self_hb + 1
        self_hb = jnp.where(act, state.self_hb + 2, state.self_hb)
        present = present.at[idx, idx].set(present[idx, idx] | act)
        hb = hb.at[idx, idx].set(jnp.where(act, own_hb, hb[idx, idx]))
        ts = ts.at[idx, idx].set(jnp.where(act, t, ts[idx, idx]))

        # TFAIL / TREMOVE sweep (MP1Node.cpp:429-446).
        difft = t - ts
        stale = present & (difft >= cfg.tfail) & act[:, None]
        numfailed = stale.sum(1, dtype=I32)
        removes = stale & (difft >= cfg.tremove)
        present = present & ~removes

        # Gossip target selection (MP1Node.cpp:449-489): sample a uniform
        # k-subset of fresh non-self entries, k bounded by the reference's
        # (quirky: post-removal size, pre-removal stale count) potential
        # formula at MP1Node.cpp:463.
        size = present.sum(1, dtype=I32)
        numpotential = size - 1 - numfailed
        fresh = present & (difft < cfg.tfail)
        seed_burst = seeds & act[intro]
        eligible = fresh & (idx[None, :] != idx[:, None]) & act[:, None]
        eligible = eligible.at[intro].set(eligible[intro] & ~seed_burst)
        n_seeds_row = jnp.where(idx == intro, jnp.where(act[intro], n_seeds, 0), 0)
        k_extra = jnp.clip(jnp.minimum(cfg.fanout, numpotential) - n_seeds_row, 0)
        targets_idx, targets_valid = sample_k_indices(
            k_targets, eligible, k_extra, min(cfg.fanout, n))

        # Send: one message per (sender, target, live entry); stale entries
        # withheld (MP1Node.cpp:376 — prevents failed-node resurrection).
        # Random-fanout traffic rides the O(N*K*E) indexed scatter; the
        # introducer's unbounded burst to this tick's joiners is a separate
        # broadcast.
        send_hb = jnp.where(fresh, hb, -1)
        k_drop_f, k_drop_s = jax.random.split(k_drop)
        contrib, sent_list, recv_add = fanout_deliver_indexed(
            k_drop_f, targets_idx, targets_valid, send_hb, n,
            drop_active, cfg.drop_prob)
        contrib_seed, sent_seed, recv_seed = broadcast_deliver(
            k_drop_s, seed_burst, send_hb[intro], drop_active, cfg.drop_prob)
        contrib = jnp.maximum(contrib, contrib_seed)
        infl_has = infl_has | (contrib >= 0)
        infl_hb = jnp.maximum(infl_hb, contrib)
        pending_recv = pending_recv + recv_add + recv_seed
        sent_tick = (sent_list.at[intro].add(sent_seed) + sent_req + sent_rep)

        # ---- failure injection, end of tick (Application::fail) ----
        failed = state.failed | (fail_mask & (t == fail_time))

        new_state = State(present, hb, ts, started, in_group, failed, self_hb,
                          infl_has, infl_hb, joinreq_infl, joinrep_infl,
                          pending_recv)
        if cfg.collect_events:
            out = TickEvents(join_events, removes, sent_tick, recv_tick)
        else:
            out = TickEvents(join_events.sum(dtype=I32),
                             removes.sum(dtype=I32), sent_tick, recv_tick)
        return new_state, out

    return step


_RUNNER_CACHE: dict = {}


def _get_runner(cfg: StepConfig):
    """One compiled whole-run scan per config: per-run values (seed,
    schedules, failure plan) are jit *arguments*, so a single compilation
    serves every seed and scenario of the same shape."""
    if cfg not in _RUNNER_CACHE:
        step = make_step(cfg)

        def run(keys, ticks, start_ticks, fail_mask, fail_time,
                drop_lo, drop_hi):
            def body(state, inp):
                t, k = inp
                return step(state, (t, k, start_ticks, fail_mask,
                                    fail_time, drop_lo, drop_hi))

            return jax.lax.scan(body, init_state(cfg.n), (ticks, keys))

        _RUNNER_CACHE[cfg] = jax.jit(run)
    return _RUNNER_CACHE[cfg]


def _get_segment_runner(cfg: StepConfig):
    """The chunked-scan twin of :func:`_get_runner`: same step, but the
    carry is an argument, so the run can stop at any segment boundary and
    continue bit-exactly (runtime/checkpoint.py)."""
    key = (cfg, "segment")
    if key not in _RUNNER_CACHE:
        step = make_step(cfg)

        def run_seg(state, ticks, keys, start_ticks, fail_mask, fail_time,
                    drop_lo, drop_hi):
            def body(state, inp):
                t, k = inp
                return step(state, (t, k, start_ticks, fail_mask,
                                    fail_time, drop_lo, drop_hi))

            return jax.lax.scan(body, state, (ticks, keys))

        _RUNNER_CACHE[key] = jax.jit(run_seg)
    return _RUNNER_CACHE[key]


def run_scan(params: Params, plan: FailurePlan, seed: int,
             collect_events: bool = True, total_time: Optional[int] = None):
    """Run the full simulation; returns (final_state, events)."""
    n = params.EN_GPSZ
    total = total_time if total_time is not None else params.TOTAL_TIME
    cfg = StepConfig(
        n=n, tfail=params.TFAIL, tremove=params.TREMOVE, fanout=params.FANOUT,
        drop_prob=params.effective_drop_prob(),
        collect_events=collect_events)

    if params.CHECKPOINT_EVERY > 0:
        from distributed_membership_tpu.runtime.checkpoint import (
            chunked_run, compact_dense)
        seg = _get_segment_runner(cfg)
        return chunked_run(
            params, plan, seed, total,
            init_carry=lambda: init_state(n),
            segment_fn=seg, collect_events=collect_events,
            compact_fn=compact_dense if collect_events else None,
            event_type=None if collect_events else TickEvents)

    (ticks, keys, start_ticks, fail_mask, fail_time,
     drop_lo, drop_hi) = plan_tensors(params, plan, seed, total)

    run = _get_runner(cfg)
    final_state, events = run(keys, ticks, start_ticks, fail_mask,
                              fail_time, drop_lo, drop_hi)
    return final_state, jax.tree.map(np.asarray, events)


def events_to_log(params: Params, plan: FailurePlan, events: TickEvents,
                  log: EventLog) -> None:
    """Reconstruct the reference's dbg.log from stacked event tensors.

    Emits the same line inventory as the reference run (SURVEY.md §4
    log-format contract): APP lines, Starting up group / Trying to join,
    joined/removed events, @@time beacons, failure notices.  Line order
    within a tick differs from the reference's descending-node-order
    interleaving; the grading oracle is order-insensitive (sort -u).
    """
    from distributed_membership_tpu.runtime.checkpoint import (
        CompactEvents, compact_dense)

    if not isinstance(events, CompactEvents):
        events = compact_dense(events)
    n = params.EN_GPSZ
    total = events.total
    starts = [params.start_tick(i) for i in range(n)]
    for i in range(n):
        log.log(i + 1, 0, "APP")  # constructor lines (Application.cpp:67)

    join_by_tick: dict = {}
    for t, i, j in events.joins:
        join_by_tick.setdefault(int(t), []).append((int(i), int(j)))
    remove_by_tick: dict = {}
    for t, i, j in events.removes:
        remove_by_tick.setdefault(int(t), []).append((int(i), int(j)))

    intro_failed = (plan.fail_time is not None
                    and INTRODUCER_INDEX in plan.failed_indices)
    for t in range(total):
        for i in range(n - 1, -1, -1):
            if starts[i] == t:
                if i == INTRODUCER_INDEX:
                    log.log(i + 1, t, "Starting up group...")
                else:
                    log.log(i + 1, t, "Trying to join...")
        for i, j in join_by_tick.get(t, ()):
            log.node_add(i + 1, j + 1, t)
        for i, j in remove_by_tick.get(t, ()):
            log.node_remove(i + 1, j + 1, t)
        if (t % 500 == 0 and t > starts[INTRODUCER_INDEX]
                and not (intro_failed and t > plan.fail_time)):
            log.log(INTRODUCER_INDEX + 1, t, f"@@time={t}")  # Application.cpp:156-160
        if plan.fail_time == t:
            log_failures(plan, log, t)


@register("tpu")
def run_tpu(params: Params, log: Optional[EventLog] = None,
            seed: Optional[int] = None) -> RunResult:
    t0 = _time.time()
    seed = params.SEED if seed is None else seed
    log = log if log is not None else EventLog()
    # Same failure-plan RNG stream as the emul backend: identical seeds fail
    # identical nodes, making runs directly comparable across backends.
    plan = resolve_plan(params, _pyrandom.Random(f"app:{seed}"))

    final_state, events = run_scan(params, plan, seed)
    events_to_log(params, plan, events, log)

    return RunResult(
        params=params, log=log,
        sent=np.asarray(events.sent).T, recv=np.asarray(events.recv).T,
        failed_indices=plan.failed_indices if plan.fail_time is not None else [],
        fail_time=plan.fail_time,
        wall_seconds=_time.time() - t0,
        extra={"final_state": final_state},
    )
