"""Backend registry.

A backend turns a :class:`~distributed_membership_tpu.config.Params` into a
completed simulation: an :class:`~distributed_membership_tpu.eventlog.EventLog`
full of grader-visible events plus message counters.  The ``BACKEND:`` config
key selects one (the rebuild extension called out in BASELINE.json), replacing
the reference's single hardwired EmulNet path (Application.cpp:53).

Backends:
  * ``emul``        — faithful queue-level host simulator (executable spec);
  * ``emul_native`` — same semantics, C++ core via ctypes;
  * ``tpu``         — dense vectorized jitted step under ``lax.scan``;
  * ``tpu_sharded`` — node axis sharded over a device mesh (shard_map);
  * ``tpu_sparse``  — exact bounded member views (sorted merge);
  * ``tpu_hash``    — hash-slotted bounded views, elementwise-max merge:
    the high-throughput scale path;
  * ``tpu_hash_sharded`` — tpu_hash node-sharded over a device mesh with a
    bucketed all_to_all message exchange: the flagship multi-chip path
    (BASELINE.json config #4).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional

import numpy as np

from distributed_membership_tpu.config import Params
from distributed_membership_tpu.eventlog import EventLog


@dataclasses.dataclass
class RunResult:
    """Everything a completed run produces.

    ``sent``/``recv`` are ``[N, T]`` int arrays mirroring the reference's
    ``sent_msgs``/``recv_msgs`` matrices (EmulNet.h:83-84) — the reference's
    only profiler, dumped to msgcount.log at shutdown (EmulNet.cpp:184-218).
    """

    params: Params
    log: EventLog
    sent: np.ndarray
    recv: np.ndarray
    failed_indices: List[int]
    fail_time: Optional[int]
    wall_seconds: float = 0.0
    extra: Dict[str, object] = dataclasses.field(default_factory=dict)


BackendFn = Callable[..., RunResult]

_REGISTRY: Dict[str, BackendFn] = {}


def register(name: str):
    def deco(fn: BackendFn) -> BackendFn:
        _REGISTRY[name] = fn
        return fn
    return deco


_MODULES = {
    "emul": "distributed_membership_tpu.backends.emul",
    "emul_native": "distributed_membership_tpu.backends.emul_native",
    "tpu": "distributed_membership_tpu.backends.tpu",
    "tpu_sharded": "distributed_membership_tpu.backends.tpu_sharded",
    "tpu_sparse": "distributed_membership_tpu.backends.tpu_sparse",
    "tpu_hash": "distributed_membership_tpu.backends.tpu_hash",
    "tpu_hash_sharded": "distributed_membership_tpu.backends.tpu_hash_sharded",
}


def get_backend(name: str) -> BackendFn:
    # Import lazily so that e.g. the emul backend works without jax present.
    if name not in _REGISTRY:
        import importlib
        try:
            importlib.import_module(_MODULES[name])
        except (ImportError, KeyError) as e:
            raise NotImplementedError(
                f"backend {name!r} is not available "
                f"(known: {sorted(_MODULES)})") from e
    return _REGISTRY[name]
