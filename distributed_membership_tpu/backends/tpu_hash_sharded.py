"""`tpu_hash_sharded` backend: the hashed bounded-view scale path sharded
over a device mesh — the rebuild's flagship (BASELINE.json config #4).

Node rows are sharded over a 1-D :class:`jax.sharding.Mesh`: shard ``d``
owns rows ``[d*L, (d+1)*L)`` of the `tpu_hash` state — views, mailboxes and
per-node scalars — and the whole run's ``lax.scan`` executes inside one
``shard_map`` call, so state never leaves the devices.

**The cross-chip EmulNet.**  The reference's network is a global in-memory
mailbox (EmulNet.h:35-72); `tpu_hash` turned it into hash-slotted
per-receiver mailboxes combined by ``max``.  Across chips the delivery
becomes a *bucketed all_to_all* — the sparse random-fanout exchange the
north star prescribes, rather than a dense [N, S] partial per shard (which
would ring-reduce half a GB per tick at N=1M):

  1. every shard flattens its tick's outgoing traffic — gossip entries,
     probe transmissions (both redundant copies), acks, join requests, the
     introducer's seed bursts — into one message list of
     ``(target, packed entry, channel)`` triples;
  2. the list is sorted by ``(destination shard, channel priority)`` and
     cut into fixed-capacity per-destination buckets (capacity overflow
     drops messages exactly like EmulNet's bounded buffer, EmulNet.cpp:90;
     the sort priority makes overflow eat gossip before probes/acks);
  3. one ``jax.lax.all_to_all`` ships the buckets over ICI;
  4. each shard scatter-maxes what it received into its local mailboxes —
     the same slot maps as `tpu_hash`, so per-id semantics are unchanged.

Per-tick ICI traffic is proportional to actual messages (~L*(K*G + 6P)
u32 pairs per shard), not to state size.  Everything else — the admit/
refresh combine, the TFAIL/TREMOVE sweep, target sampling, SWIM round-robin
probing — is `tpu_hash`'s elementwise/TPU-friendly code applied to the
local rows (see backends/tpu_hash.py for the protocol argument; reference
semantics per MP1Node.cpp:404-495).

Join handshake state (who has a JOINREQ/JOINREP in flight, whether the
introducer can receive) is a handful of ``[N]``-bool ``all_gather``s per
tick, as in `tpu_sharded` — at scale runs use ``JOIN_MODE: warm`` and this
machinery is inert.

RNG: per-shard streams via ``fold_in(key, shard)`` for gossip targets and
entry subsets; the tick keys themselves are replicated inputs.  Parity with
single-chip `tpu_hash` is therefore distributional (same protocol, same
fanout distribution), verified by the grader scenarios and the removal-
latency window tests (tests/test_hash_sharded.py).

**Exchange modes.**  The bucketed all_to_all above is the ``scatter``
lowering.  ``EXCHANGE: ring`` (auto-selected for warm bounded-view scale
runs, as on `tpu_hash`) replaces it with torus-product circulant gossip —
one static-perm ``ppermute`` payload per shift — and the gather-pipeline
probe/ack channel; see :func:`make_ring_sharded_step`.
"""

from __future__ import annotations

import random as _pyrandom
import time as _time
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from distributed_membership_tpu.parallel import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from distributed_membership_tpu.addressing import INTRODUCER_INDEX
from distributed_membership_tpu.backends import RunResult, register
from distributed_membership_tpu.backends.tpu_hash import (
    STRIDE, HashConfig, I32, U32, _credit_orphan_recvs_sharded,
    _gathered_act, _gathered_flush, _gathered_hb, _pack_probe_bits,
    _pack_probe_table, ptr_switch, _will_flush, make_admit, make_config,
    pack, resolve_mega_pack, slot_of, unpack)
from distributed_membership_tpu.ops.megakernel import mega_scan
from distributed_membership_tpu.backends.tpu_sparse import (
    SparseTickEvents, finish_run)
from distributed_membership_tpu.config import Params
from distributed_membership_tpu.eventlog import EventLog
from distributed_membership_tpu.observability.aggregates import (
    AggStats, FastAgg, init_agg, init_fast_agg, update_agg, update_fast_agg)
from distributed_membership_tpu.observability.timeline import (
    PHASE_ACK, PHASE_PROBE, PHASE_TELEMETRY, TickTelemetry,
    build_tick_hist, hist_spec, telemetry_spec)
from distributed_membership_tpu.ops.fused_receive import (
    receive_core, receive_fused)
from distributed_membership_tpu.ops.sampling import sample_k_indices
from distributed_membership_tpu.ops.view_merge import EMPTY, hash_slot
from distributed_membership_tpu.parallel.mesh import NODE_AXIS, make_mesh
from distributed_membership_tpu.runtime.failures import (
    FailurePlan, make_run_key, plan_tensors, resolve_plan)

INTRO = INTRODUCER_INDEX

# Message channels (3 bits packed next to the target id).  Priority order =
# numeric order: bucket-capacity overflow drops the highest channel first,
# so reliability-critical probe/ack traffic survives congestion ahead of
# (redundant) gossip — EmulNet drops indiscriminately (EmulNet.cpp:90); we
# can do better without changing per-message semantics.
CH_ACK = 0
CH_PROBE0 = 1
CH_PROBE1 = 2
CH_JOIN = 3     # JOINREQ: admitted into the introducer's gossip mailbox
CH_GOSSIP = 4
N_CH = 5


class ShardedHashState(NamedTuple):
    """Per-shard slice: matrices are [L, S]-shaped local rows, vectors [L]."""
    view: jax.Array
    view_ts: jax.Array
    started: jax.Array
    in_group: jax.Array
    failed: jax.Array
    self_hb: jax.Array
    mail: jax.Array
    amail: jax.Array
    pmail: jax.Array     # [L, Qp]
    joinreq_infl: jax.Array
    joinrep_infl: jax.Array
    pending_recv: jax.Array
    agg: AggStats        # per-shard partials over GLOBAL ids ([N]-shaped,
    #                      or FastAgg on the ring fast path); reduced once
    #                      after the scan
    probe_ids1: jax.Array    # [L, P] u32 ids probed last tick (ring mode;
    #                          [1, 1] zeros otherwise), 0 = none
    probe_ids2: jax.Array    # [L, P] u32 ids probed two ticks ago (ring)
    act_prev: jax.Array      # [L] bool act mask of the previous tick (ring)


def init_local_state(cfg: HashConfig, n_local: int) -> ShardedHashState:
    s = cfg.s
    ring = cfg.exchange == "ring"
    probe_shape = (n_local, cfg.probes) if ring and cfg.probes > 0 else (1, 1)
    return ShardedHashState(
        agg=(init_fast_agg(len(cfg.fail_ids), n_local) if cfg.fast_agg
             else init_agg(cfg.n, n_local)),
        view=jnp.zeros((n_local, s), U32),
        view_ts=jnp.zeros((n_local, s), I32),
        started=jnp.zeros((n_local,), bool),
        in_group=jnp.zeros((n_local,), bool),
        failed=jnp.zeros((n_local,), bool),
        self_hb=jnp.zeros((n_local,), I32),
        mail=jnp.zeros((n_local, s), U32),
        amail=jnp.zeros((n_local, s) if not ring else (1, 1), U32),
        pmail=jnp.zeros((n_local, cfg.qp) if not ring else (1, 1), U32),
        joinreq_infl=jnp.zeros((n_local,), bool),
        joinrep_infl=jnp.zeros((n_local,), bool),
        pending_recv=jnp.zeros((n_local,), I32),
        probe_ids1=jnp.zeros(probe_shape, U32),
        probe_ids2=jnp.zeros(probe_shape, U32),
        act_prev=jnp.zeros((n_local,) if ring else (1,), bool),
    )


def init_local_state_warm(cfg: HashConfig, n_local: int,
                          key: jax.Array,
                          ax=NODE_AXIS) -> ShardedHashState:
    """Warm bootstrap of the local rows (cf. tpu_hash.init_state_warm)."""
    me = lax.axis_index(ax)
    lrows = me * n_local + jnp.arange(n_local, dtype=I32)
    st = init_local_state(cfg, n_local)
    fill = max(cfg.s // 2, 1)
    offs = jax.random.randint(jax.random.fold_in(key, me),
                              (n_local, fill), 1, max(cfg.n, 2), dtype=I32)
    nbrs = lax.rem(lrows[:, None] + offs, cfg.n)
    # Local scatter of neighbor entries into each local row's hashed slots.
    addr = (jnp.arange(n_local, dtype=I32)[:, None] * cfg.s
            + slot_of(cfg, lrows[:, None], nbrs))
    view = st.view.reshape(-1).at[addr.reshape(-1)].max(
        pack(cfg, jnp.zeros_like(nbrs), nbrs).reshape(-1),
        mode="drop").reshape(n_local, cfg.s)
    # Self slot belongs to self unconditionally.
    view = view.at[jnp.arange(n_local), slot_of(cfg, lrows, lrows)].set(
        pack(cfg, jnp.zeros((n_local,), I32), lrows))
    return st._replace(view=view,
                       started=jnp.ones((n_local,), bool),
                       in_group=jnp.ones((n_local,), bool))


def bucket_capacity(cfg: HashConfig, n_local: int, n_shards: int) -> int:
    """Static per-destination-shard bucket size.

    Expected per-dest traffic is ~L*(K*G + 3P (2 probe copies + ~1 ack
    in expectation... acks mirror delivered probes) + joins)/D; 2.5x
    headroom absorbs Poisson fluctuation, the introducer's seed bursts,
    and ack fan-in skew.  Overflow drops lowest-priority messages —
    EmulNet's bounded-buffer behavior (EmulNet.h:12)."""
    k = min(cfg.fanout, cfg.s)
    per_sender = k * cfg.g + 6 * cfg.probes + 2
    seed_total = cfg.seed_cap * cfg.s
    expect = (n_local * per_sender + seed_total) / n_shards
    cap = int(2.5 * expect) + 64
    return min(cap, n_local * per_sender + seed_total)


def make_block_send(n_shards: int, axes: tuple, axis_sizes: tuple):
    """Build the block-shift router: route tensors to shard ``me + b``
    (flat shard index), ``lax.switch`` over D static permutations since
    ``b`` is traced but replicated.

    On a 1-D mesh each branch is one ``ppermute`` rotation.  On an N-D
    torus mesh (flat index = mixed-radix digits, major axis first) the
    flat shift decomposes into per-axis ring rotations — the hops every
    torus interconnect implements natively — instead of asking the
    router for an arbitrary flat permutation.  It is mixed-radix
    ADDITION run minor-axis-first: stage j rotates axis j by its shift
    digit ``r_j`` plus the carry from the stage below.  The carry into
    stage j depends only on digits MINOR to j, which rotations on axis j
    and above preserve — so it is per-shard computable from
    ``axis_index`` values, identical at an axis-j hop's source and
    destination, and each stage is at most two masked rotations
    (``r_j`` / ``r_j + 1``) combined by the carry select.  Wire cost per
    axis: one payload on the minormost, two (one mostly-zero) above —
    neighbor traffic on every torus dimension.  The outermost axis can
    span DCN (multi-slice): it carries exactly one block hop per gossip
    shift, the minimum any cross-slice delivery needs."""
    if len(axis_sizes) != len(axes):
        raise ValueError(
            f"axis_sizes {axis_sizes} must match axes {axes} — pass one "
            "size per mesh axis (the per-axis decomposition needs both)")
    from distributed_membership_tpu.observability.timeline import (
        PHASE_COLLECTIVE)
    if len(axes) == 1:
        ax = axes[0]

        def block_send(tensors, b):
            def mk(i):
                if i == 0:
                    return lambda ops: ops
                perm = [(src, (src + i) % n_shards)
                        for src in range(n_shards)]
                return lambda ops: tuple(
                    lax.ppermute(o, ax, perm) for o in ops)
            with jax.named_scope(PHASE_COLLECTIVE):
                return lax.switch(b, [mk(i) for i in range(n_shards)],
                                  tensors)
        return block_send

    assert int(np.prod(axis_sizes)) == n_shards

    def _digits(i: int) -> list:
        """Mixed-radix digits of the flat shift, minor axis first."""
        out = []
        for size in reversed(axis_sizes):
            i, d = divmod(i, size)
            out.append(d)
        return out

    def block_send(tensors, b):
        def mk(i):
            if i == 0:
                return lambda ops: ops
            digits = _digits(i)

            def go(ops):
                carry = None          # stage 0 has no carry-in
                # minor → major: axes[-1] is the minormost mesh axis.
                for j, r in enumerate(digits):
                    ax = axes[-1 - j]
                    size = axis_sizes[-1 - j]
                    perm_r = [(s, (s + r) % size) for s in range(size)]
                    perm_r1 = [(s, (s + r + 1) % size)
                               for s in range(size)]
                    if carry is None:
                        if r:
                            ops = tuple(lax.ppermute(o, ax, perm_r)
                                        for o in ops)
                    else:
                        def hop(o):
                            z = jnp.zeros_like(o)
                            stay = jnp.where(carry, z, o)
                            a = (lax.ppermute(stay, ax, perm_r)
                                 if r else stay)
                            c = lax.ppermute(jnp.where(carry, o, z),
                                             ax, perm_r1)
                            return jnp.where(carry, c, a)
                        ops = tuple(hop(o) for o in ops)
                    if j < len(digits) - 1 and not (carry is None
                                                    and r == 0):
                        # Carry out of stage j: the digit wrapped iff the
                        # POST-rotation digit is below the amount added
                        # (r, or r+1 on the carried stream).  A zero
                        # digit with no carry-in keeps carry None — the
                        # statically-false carry must not force the
                        # two-stream masked hop on the axes above (e.g.
                        # a pure slice-axis shift would otherwise send
                        # two DCN streams where one suffices).
                        d_new = lax.axis_index(ax)
                        eff = r if carry is None else r + carry.astype(I32)
                        carry = d_new < eff
                return ops
            return go
        with jax.named_scope(PHASE_COLLECTIVE):
            return lax.switch(b, [mk(i) for i in range(n_shards)], tensors)
    return block_send


def make_ring_sharded_step(cfg: HashConfig, n_local: int, n_shards: int,
                           cold_join: bool = False,
                           axes: tuple = (NODE_AXIS,),
                           axis_sizes: tuple = ()):
    """Ring exchange on the sharded backend (EXCHANGE ring).

    Gossip shifts are torus-product translations ``(j, d) -> (j+c, d+b)``
    with ``u = b*L + c ~ U[1, N)`` re-drawn per shift per tick: the block
    part rides ONE static-perm ``ppermute`` (``lax.switch`` over the traced
    ``b`` — D branches; every shard takes the same branch because the shift
    key is replicated), the intra part is a local ``jnp.roll``, and slot
    alignment is two column rolls selected per row (the sender→receiver
    global-id delta changes by L across the row wrap and by N across the
    block wrap, a per-shard constant).  Wire cost per shift is exactly one
    [L, S] payload — no bucket sort, no all_to_all, no scatter.

    Probes/acks use `tpu_hash`'s gather pipeline with one [N] ``all_gather``
    of the lagged heartbeat vector per tick (4 MB at N=1M — the whole
    cross-shard probe subsystem).  Per-node probe counters follow
    ``cfg.count_probe_io``: exact per-target attribution builds two local
    [N]-index histograms and ``psum_scatter``s them back to their owner
    shards (plus one bool act all_gather) — the same wire the ack
    pipeline's [N] all_gather already rides; approx mode charges probe
    traffic to the prober's row with exact totals (the ack count keeps
    the act-of-target filter via the gathered act vector).

    With ``cold_join`` the full join handshake runs
    (MP1Node.cpp:126-163,226-251 semantics, as the single-chip ring and
    the scatter-mode sharded step implement it).  The key observation
    keeping it cheap: the introducer's receive/act flags are deterministic
    functions of the replicated schedules (its ``in_group`` comes from its
    own boot, never from messages), so the whole control plane —
    JOINREQ/JOINREP bits, seed selection, drop coins — is computed
    *replicated* on every shard from the shared tick key; the only
    cross-shard traffic added is one [N]-bool ``all_gather`` of the
    in-flight JOINREQ bits and two [S] ``psum`` broadcasts of the
    introducer's row for the seed burst.  In warm mode (the scale
    regime) all of it compiles away — the fast path is unchanged.

    The union of ``fanout`` torus translations re-drawn each tick is an
    expander family with uniform target marginals, like the single-chip
    circulant ring (backends/tpu_hash.py make_step).  Pinned by
    tests/test_hash_sharded.py: the warm scale tests run both exchanges,
    and test_mesh_matches_single_chip_distribution compares this path's
    latency distribution against single-chip `tpu_hash` (both on ring via
    EXCHANGE auto).
    """
    n, s, g = cfg.n, cfg.s, cfg.g
    k_max = min(cfg.fanout, s)
    l_idx = jnp.arange(n_local, dtype=I32)
    scenario = cfg.scenario
    use_drop = cfg.drop_prob > 0.0 or (scenario is not None
                                       and scenario.has_drop)
    p_red = 1 if cfg.qp >= n else 2
    cstride = STRIDE % s
    if cfg.probes >= s:
        raise ValueError("ring mode needs PROBES < VIEW_SIZE "
                         f"(got {cfg.probes} >= {s})")
    if scenario is not None and cold_join:
        # The cold-join control plane (replicated JOINREQ/JOINREP +
        # seed bursts) predates the scenario engine; scale scenarios
        # run warm.  Loud gate rather than silently un-partitioned
        # join traffic.
        raise ValueError(
            "SCENARIO general events on tpu_hash_sharded require "
            "JOIN_MODE warm (the cold-join control plane does not "
            "model partitions/flakes)")
    # AX feeds every whole-axis collective; a tuple of axis names has the
    # flattened-mesh semantics (outer-major), so the protocol below is
    # mesh-shape-agnostic — only block_send decomposes per axis.
    AX = axes if len(axes) > 1 else axes[0]
    block_send = make_block_send(n_shards, axes,
                                 axis_sizes or (n_shards,))
    bx = None
    if cfg.batched_exchange:
        # EXCHANGE_MODE batched: the per-shift block_send launches are
        # replaced by ONE all_to_all per tick (ops/exchange.py), its
        # result carried one tick in the (state, xbuf) lane and merged
        # at the next head — where the receive pass consumes mail in
        # both modes, so the deferral is bit-exact while the collective
        # overlaps this tick's probe/agg tail.
        from distributed_membership_tpu.ops.exchange import BatchedExchange
        bx = BatchedExchange(
            n_shards=n_shards, axes=axes, n_local=n_local, s=s,
            cstride=cstride,
            single_col_roll=(n_local * STRIDE) % s == 0, folded=False)

    from distributed_membership_tpu.ops.rng_plan import sharded_ring_rng
    packed_gather = cfg.probe_gather == "packed" and n >= 4
    seed_rows = min(cfg.seed_cap, n)

    def step(state: ShardedHashState, inputs):
        xbuf = None
        if bx is not None:
            state, xbuf = state
        (t, key, start_ticks_g, fail_mask_g, fail_time, drop_lo,
         drop_hi) = inputs[:7]
        me = lax.axis_index(AX)
        row0 = (me * n_local).astype(I32)
        lrows = row0 + l_idx
        fail_mask_l = lax.dynamic_slice(fail_mask_g, (row0,), (n_local,))
        start_ticks_l = lax.dynamic_slice(start_ticks_g, (row0,), (n_local,))
        # Per-tick RNG plan (ops/rng_plan.py): same key derivations and
        # bits as the scattered per-site draws; RNG_MODE batched groups
        # the same-size streams into one vmapped threefry.
        rng = sharded_ring_rng(
            key, me, n=n, n_local=n_local, s=s, g=g, k_max=k_max,
            p_cnt=max(cfg.probes, 0), seed_rows=seed_rows,
            use_drop=use_drop, cold_join=cold_join,
            batched=cfg.rng_mode != "scattered")
        drop_active = (t > drop_lo) & (t <= drop_hi)
        telem_dropped = []      # LOCAL counts (psum'd at emission);
        #                         TELEMETRY scalars only — guarded below.

        # ---- scenario plan activation (scenario/compile.py): local
        # rows against replicated event/window tensors — elementwise,
        # no collectives added.  cfg.scenario None => this block and
        # every consulting site below do not exist in the program.
        if scenario is not None:
            from distributed_membership_tpu.scenario.compile import (
                base_drop_prob, cross_group, cuts_at, delayed_mask,
                site_drop_prob, updown_masks)
            scn = inputs[7]
            if scenario.has_updown:
                down_now, up_now = updown_masks(scn, t, lrows)
                fails_now = down_now | up_now
            else:
                down_now = up_now = fails_now = None
            cuts = cuts_at(scn, t, n) if scenario.n_parts else None
            cuts_prev = (cuts_at(scn, t - 1, n) if scenario.n_parts
                         else None)
        else:
            scn = fails_now = None

        # ---- receive: admit + ack + self + sweep as one fused pass ----
        # (ops/fused_receive: receive_core, or its Pallas twin when
        # cfg.fused_receive — identical math, tpu_hash.make_step ring.)
        recv_mask = state.started & (t > start_ticks_l) & ~state.failed
        if scenario is not None and scenario.n_delays:
            # delay_window on local rows (tpu_hash.make_step's gate):
            # inbound delivery held — mail max-merges across the held
            # ticks (the xbuf head-merge below still lands in the
            # preserved carry), pending recvs flush after the window.
            # ``act`` below derives independently, so the node keeps
            # sending/probing and aging its sweep.
            recv_mask = recv_mask & ~delayed_mask(scn, t, lrows)
        rcol = recv_mask[:, None]

        def wf_now():
            if fails_now is not None:
                return recv_mask & ~fails_now
            return _will_flush(recv_mask, fail_mask_l, t, fail_time)

        # ---- join handshake control plane (cold_join only) ----
        # Replicated computation throughout: the introducer's receive/act
        # state is schedule-deterministic, so every shard derives the same
        # control vectors from the shared key (docstring).
        if cold_join:
            is_intro_row = lrows == INTRO
            idx_g = jnp.arange(n, dtype=I32)
            intro_failed = fail_mask_g[INTRO] & (t > fail_time)
            intro_recv = ((t > start_ticks_g[INTRO]) & ~intro_failed)
            if use_drop:
                ctrl_kept_g = ~((rng.ctrl_u.reshape(2, n) < cfg.drop_prob)
                                & drop_active)
            else:
                ctrl_kept_g = jnp.ones((2, n), bool)

            in_group = state.in_group | (state.joinrep_infl & recv_mask)
            joinrep_infl = state.joinrep_infl & ~recv_mask

            joinreq_g = lax.all_gather(state.joinreq_infl, AX,
                                       tiled=True)
            seeds_g = joinreq_g & intro_recv
            joinreq_infl = state.joinreq_infl & ~intro_recv
            rep_ok_g = seeds_g & ctrl_kept_g[1]
            if cfg.telemetry and use_drop:
                # Local slice of the replicated control plane so the
                # emission psum counts each dropped JOINREP once.
                telem_dropped.append(lax.dynamic_slice(
                    seeds_g & ~ctrl_kept_g[1], (row0,),
                    (n_local,)).sum(dtype=I32))
            rep_ok_l = lax.dynamic_slice(rep_ok_g, (row0,), (n_local,))
            joinrep_infl = joinrep_infl | rep_ok_l
            n_seeds = seeds_g.sum(dtype=I32)
            sent_rep = jnp.where(is_intro_row & intro_recv,
                                 rep_ok_g.sum(dtype=I32), 0)

            start_now = t == start_ticks_l
            started = state.started | start_now
            boot = t == start_ticks_g[INTRO]
            in_group = in_group | (is_intro_row & boot)
            ctrl0_l = lax.dynamic_slice(ctrl_kept_g[0], (row0,), (n_local,))
            joiner_req = start_now & (lrows != INTRO) & ctrl0_l
            if cfg.telemetry and use_drop:
                telem_dropped.append(
                    (start_now & (lrows != INTRO)
                     & ~ctrl0_l).sum(dtype=I32))
            joinreq_infl = joinreq_infl | joiner_req
            sent_req = joiner_req.astype(I32)
            joiner_req_g = ((t == start_ticks_g) & (idx_g != INTRO)
                            & ctrl_kept_g[0])
            pending_joins = (rep_ok_l.astype(I32)
                             + jnp.where(is_intro_row,
                                         joiner_req_g.sum(dtype=I32), 0))
        else:
            started, in_group = state.started, state.in_group
            joinreq_infl = state.joinreq_infl
            joinrep_infl = state.joinrep_infl
            sent_req = sent_rep = jnp.zeros((n_local,), I32)
            pending_joins = jnp.zeros((n_local,), I32)

        # xbuf head-merge: last tick's batched exchange lands here —
        # exactly where the legacy (immediately merged) value becomes
        # observable, so pend_eff/mail_eff equal the legacy carries.
        pend_eff = state.pending_recv
        mail_eff = state.mail
        if bx is not None:
            pend_eff = pend_eff + bx.merge_pending(xbuf[1])
            mail_eff = bx.merge_mail(mail_eff, xbuf[0])
        recv_tick = jnp.where(recv_mask, pend_eff, 0)
        pending_recv = (jnp.where(recv_mask, 0, pend_eff)
                        + pending_joins)

        # ---- self refresh vectors ----
        act = (started & (t > start_ticks_l) & ~state.failed & in_group)
        own_hb = state.self_hb + 1
        self_hb = jnp.where(act, state.self_hb + 2, state.self_hb)
        self_on = (act | (is_intro_row & boot)) if cold_join else act
        self_val = pack(cfg, jnp.where(act, own_hb, 0), lrows)

        ack_recv_cnt = jnp.zeros((n_local,), I32)
        cand_full = jnp.zeros((n_local, s), U32)
        will_flush_l = will_flush_g = probe_bits1 = None
        if cfg.probes > 0:
            # Ack candidates for probes issued at t-2 (gather pipeline):
            # one [N] all_gather is the whole cross-shard probe
            # subsystem.  On the default packed arm that gather carries
            # the whole per-node probe table — lagged heartbeat +
            # will-flush + act bits (tpu_hash._pack_probe_table), so the
            # separate act/will_flush all_gathers of the counting
            # branches disappear — and the t-1 counter bits ride the
            # SAME per-target gather as the ack value ([N, 2P] indices).
            vec_l = jnp.where(state.act_prev, state.self_hb - 1, 0)
            ids2 = state.probe_ids2
            id2 = jnp.clip(ids2.astype(I32) - 1, 0)
            ids1 = state.probe_ids1
            v1 = ids1 > 0
            tgt1 = jnp.clip(ids1.astype(I32) - 1, 0)   # global target ids
            with jax.named_scope(PHASE_ACK):
                if packed_gather and not cfg.probe_io_none:
                    will_flush_l = wf_now()
                    tbl_g = lax.all_gather(
                        _pack_probe_table(vec_l, will_flush_l, act), AX,
                        tiled=True)                      # ONE [N] wire
                    will_flush_g = _gathered_flush(tbl_g)
                    gcat = tbl_g[jnp.concatenate([id2, tgt1], axis=1)]
                    hb_ack = _gathered_hb(gcat[:, :cfg.probes])
                    probe_bits1 = gcat[:, cfg.probes:]
                else:
                    vec_g = lax.all_gather(vec_l, AX, tiled=True)    # [N]
                    hb_ack = vec_g[id2]
                valid2 = (ids2 > 0) & (hb_ack > 0)
                if scenario is not None and scenario.n_parts:
                    # Ack traveled target (id2) -> prober (lrows) during
                    # tick t-1: cut if the partition was up then.
                    valid2 &= ~cross_group(cuts_prev, id2,
                                           lrows[:, None])
                if use_drop:
                    if scenario is not None:
                        ack_coin = (rng.ack_u.reshape(ids2.shape)
                                    < site_drop_prob(
                                        scenario, scn, t - 1, id2,
                                        lrows[:, None]))
                    else:
                        da_ack = (t - 1 > drop_lo) & (t - 1 <= drop_hi)
                        ack_coin = ((rng.ack_u.reshape(ids2.shape)
                                     < cfg.drop_prob) & da_ack)
                    if cfg.telemetry:
                        telem_dropped.append(
                            (valid2 & ack_coin).sum(dtype=I32))
                    valid2 &= ~ack_coin
                cand = jnp.where(valid2, pack(cfg, hb_ack, id2), 0)
                ptr2 = lax.rem(lax.rem((t - 2) * cfg.probes, s) + s, s)
                cand_full = jnp.concatenate(
                    [cand, jnp.zeros((n_local, s - cfg.probes), U32)],
                    axis=1)
                # Static-roll switch over the pointer's multiples-of-gcd
                # set (see tpu_hash.ptr_switch).
                cand_full = ptr_switch(
                    ptr2, cfg.probes, s,
                    lambda o, c: jnp.roll(c, o, axis=1), cand_full)
                ack_recv_cnt = (valid2 & rcol).sum(1, dtype=I32)

        recv_fn = (
            (lambda *a: receive_fused(
                n, s, cfg.tfail, cfg.tremove, STRIDE,
                jax.default_backend() != "tpu", *a))
            if cfg.fused_receive else
            (lambda *a: receive_core(
                n, s, cfg.tfail, cfg.tremove, STRIDE, *a)))
        (view, view_ts, mail, join_mask, rm_ids, numfailed,
         size) = recv_fn(t, state.view, state.view_ts, mail_eff,
                         cand_full, recv_mask, act, self_on, self_val,
                         lrows)
        cur_id, cur_hb, present = unpack(cfg, view)
        join_ids = jnp.where(join_mask, cur_id, EMPTY)
        difft = t - view_ts

        if cold_join:
            # This tick's JOINREQ entries land in the introducer's mailbox
            # row (hb 0, joiner id) — a local scatter on the owning shard;
            # every shard knows joiner_req_g (replicated control plane).
            intro_here = (INTRO >= row0) & (INTRO < row0 + n_local)
            intro_local = jnp.clip(INTRO - row0, 0, n_local - 1)
            jr_valid = joiner_req_g & intro_here
            jr_addr = jnp.where(
                jr_valid,
                intro_local * s + slot_of(cfg, jnp.full((n,), INTRO, I32),
                                          idx_g),
                n_local * s)
            mail = mail.reshape(-1).at[jr_addr].max(
                jnp.where(jr_valid, (idx_g + 1).astype(U32), 0),
                mode="drop").reshape(n_local, s)

        # ---- gossip: torus-product circulant shifts ----
        numpotential = size - 1 - numfailed
        fresh = present & (difft < cfg.tfail)
        is_self_slot = cur_id == lrows[:, None]
        k_eff = jnp.clip(jnp.minimum(cfg.fanout, numpotential), 0)
        if cold_join:
            # Seeded joiners consume gossip slots on the introducer's row
            # (MP1Node.cpp:240-242 newNodes seeding, as single-chip ring).
            n_seeds_row = jnp.where(is_intro_row & act, n_seeds, 0)
            k_eff = jnp.clip(k_eff - n_seeds_row, 0)
        if g >= s:
            keep = fresh
        else:
            fresh_cnt = fresh.sum(1, dtype=I32)
            p_keep = jnp.where(
                fresh_cnt > 1,
                (g - 1) / jnp.maximum(fresh_cnt - 1, 1).astype(jnp.float32),
                1.0)
            u_keep = rng.thin_u.reshape(n_local, s)
            keep = fresh & ((u_keep < p_keep[:, None]) | is_self_slot)
        keep = keep & act[:, None]

        shifts = rng.shift_draw
        sent_gossip = jnp.zeros((n_local,), I32)
        recv_add = jnp.zeros((n_local,), I32)
        stacked = []      # (payload_r, c, s1, s2) when cfg.fused_gossip
        bpay = bcnt = None
        if bx is not None:
            bpay, bcnt = bx.zero()
        for j in range(k_max):
            m = keep & (j < k_eff)[:, None]
            u = shifts[j]
            if scenario is not None and (scenario.n_parts
                                         or scenario.n_flakes):
                # Shift u sends global row i to (i + u) mod n: the
                # partition cut and flake override are per-sender-row
                # vectors on the local slice — elementwise, no gather.
                dst_g = lax.rem(lrows + u, n)
            if scenario is not None and scenario.n_parts:
                m = m & ~cross_group(cuts, lrows, dst_g)[:, None]
            if use_drop:
                if scenario is not None:
                    p_g = (site_drop_prob(scenario, scn, t, lrows, dst_g)
                           if scenario.n_flakes
                           else base_drop_prob(scn, t))
                    p_gc = (p_g[:, None]
                            if getattr(p_g, "ndim", 0) else p_g)
                    gossip_coin = (rng.gossip_u[j].reshape(n_local, s)
                                   < p_gc)
                else:
                    gossip_coin = ((rng.gossip_u[j].reshape(n_local, s)
                                    < cfg.drop_prob) & drop_active)
                if cfg.telemetry:
                    telem_dropped.append(
                        (m & gossip_coin).sum(dtype=I32))
                m = m & ~gossip_coin
            payload = jnp.where(m, view, U32(0))
            cnt = m.sum(1, dtype=I32)
            sent_gossip = sent_gossip + cnt
            b = u // n_local
            c = lax.rem(u, n_local)
            if bx is not None:
                # Sender-side alignment + destination bucketing; the
                # wire hop happens ONCE after the loop.
                bpay, bcnt = bx.add_shift(bpay, bcnt, payload, cnt,
                                          b, c, me)
                continue
            payload_r, cnt_r = block_send((payload, cnt), b)
            cnt_r = jnp.roll(cnt_r, c, axis=0)
            recv_add = recv_add + cnt_r
            # Column alignment: receiver slot = sender slot + delta*STRIDE,
            # delta = b'*L + c' with b' = b - D on block wrap (receiving
            # shards me < b, exact via bp) and c' = c - L on row wrap
            # (rows jd < c).  The row-wrap shifts coincide iff
            # L*STRIDE % S == 0 — statically true when S divides L (the
            # usual scale config) — saving one [L, S] pass per shift.
            bp = jnp.where(me < b, b - n_shards, b)
            base1 = lax.rem(lax.rem(bp * n_local + c, s) + s, s)
            s1 = lax.rem(base1 * cstride, s)
            base2 = lax.rem(
                lax.rem(bp * n_local + c - n_local, s) + s, s)
            s2 = lax.rem(base2 * cstride, s)
            if cfg.fused_gossip:
                # The Pallas accumulate (below) applies the local row
                # roll + column alignment for ALL shifts in one mail
                # traversal (ops/fused_gossip.gossip_fused_stacked); the
                # ppermute wire hop above stays as is.
                stacked.append((payload_r, c, s1, s2))
                continue
            payload_r = jnp.roll(payload_r, c, axis=0)
            r1 = jnp.roll(payload_r, s1, axis=1)
            if (n_local * STRIDE) % s == 0:
                result = r1
            else:
                r2 = jnp.roll(payload_r, s2, axis=1)
                result = jnp.where((l_idx >= c)[:, None], r1, r2)
            mail = jnp.maximum(mail, result)
        if cfg.fused_gossip and stacked:
            from distributed_membership_tpu.ops.fused_gossip import (
                gossip_fused_stacked)
            mail = gossip_fused_stacked(
                n_local, s, k_max, (n_local * STRIDE) % s == 0,
                jax.default_backend() != "tpu", mail,
                jnp.stack([p for p, _, _, _ in stacked]),
                jnp.stack([c for _, c, _, _ in stacked]),
                jnp.stack([s1 for _, _, s1, _ in stacked]),
                jnp.stack([s2 for _, _, _, s2 in stacked]))
        xnew = None
        if bx is not None:
            # The tick's ONLY exchange launch.  Its result is NOT
            # consumed below — it rides the carry to the next head, so
            # XLA is free to overlap the collective with the probe /
            # agg tail that follows.
            xnew = bx.exchange(bpay, bcnt)
        sent_tick = sent_gossip + sent_req + sent_rep

        if cold_join:
            # Introducer burst: its full fresh post-sweep view to each of
            # this tick's seeded joiners.  The row is broadcast with two
            # [S] psums; each shard delivers locally to the seed rows it
            # owns.  Burst drop coins come from a replicated stream so the
            # sender-side counter and receiver-side delivery agree.
            row_view = lax.psum(
                jnp.where(intro_here, view[intro_local], U32(0)), AX)
            row_ts = lax.psum(
                jnp.where(intro_here, view_ts[intro_local], 0), AX)
            b_id, b_hb, b_present = unpack(cfg, row_view)
            b_fresh = b_present & ((t - row_ts) < cfg.tfail)
            cap = min(cfg.seed_cap, n)
            _, seed_idx = jax.lax.top_k(seeds_g.astype(I32), cap)
            seed_burst_on = (t > start_ticks_g[INTRO]) & ~intro_failed
            seed_valid = seeds_g[seed_idx] & seed_burst_on
            burst_valid = seed_valid[:, None] & b_fresh[None, :]
            if use_drop:
                burst_coin = ((rng.burst_u.reshape(cap, s)
                               < cfg.drop_prob) & drop_active)
                if cfg.telemetry:
                    # burst_valid/coin are REPLICATED (the burst stream
                    # is shared): attribute the count to the introducer's
                    # shard so the emission psum counts it once.
                    telem_dropped.append(jnp.where(
                        intro_here,
                        (burst_valid & burst_coin).sum(dtype=I32), 0))
                burst_valid = burst_valid & ~burst_coin
            owned = (seed_idx >= row0) & (seed_idx < row0 + n_local)
            lrow = jnp.clip(seed_idx - row0, 0, n_local - 1)
            b_addr = jnp.where(
                owned[:, None] & burst_valid,
                lrow[:, None] * s + slot_of(cfg, seed_idx[:, None],
                                            jnp.clip(b_id, 0)[None, :]),
                n_local * s)
            b_val = jnp.where(burst_valid,
                              pack(cfg, jnp.clip(b_hb, 0),
                                   jnp.clip(b_id, 0))[None, :], 0)
            mail = mail.reshape(-1).at[b_addr.reshape(-1)].max(
                b_val.reshape(-1), mode="drop").reshape(n_local, s)
            burst_total = burst_valid.sum(dtype=I32)
            sent_tick = sent_tick + jnp.where(is_intro_row & act,
                                              burst_total, 0)
            recv_add = recv_add + jnp.zeros((n_local + 1,), I32).at[
                jnp.where(owned, lrow, n_local)].add(
                    burst_valid.sum(1, dtype=I32) * seed_valid.astype(I32),
                    mode="drop")[:n_local]

        # ---- probe issue ----
        probe_ids1, probe_ids2 = state.probe_ids1, state.probe_ids2
        act_prev = state.act_prev
        pfo = None
        if cfg.probes > 0:
            with jax.named_scope(PHASE_PROBE):
                ptr = lax.rem(t * cfg.probes, s)
                if cfg.fused_probe:
                    # One Pallas traversal of the local post-receive
                    # planes: pre-validated window ids + FastAgg/hist
                    # row partials (ops/fused_probe; cuts and coins
                    # apply below with the exact unfused streams).
                    from distributed_membership_tpu.ops.fused_probe \
                        import probe_window_fused
                    want_hist = cfg.telemetry and cfg.telemetry_hist
                    want_agg = cfg.fast_agg and not cfg.collect_events
                    pfo = probe_window_fused(
                        n, s, cfg.probes, cfg.tfail,
                        cfg.fail_ids if want_agg else (),
                        want_hist, want_agg,
                        jax.default_backend() != "tpu",
                        t, ptr, row0, view,
                        view_ts if want_hist else None, act,
                        rm_ids if want_agg else None)
                    window_ids = pfo["ids"][:, :cfg.probes]
                    p_valid = window_ids > 0
                    w_id = jnp.where(p_valid,
                                     window_ids.astype(I32) - 1, 0)
                else:
                    window = ptr_switch(
                        ptr, cfg.probes, s,
                        lambda o, v:
                            jnp.roll(v, -o, axis=1)[:, :cfg.probes],
                        view)
                    w_pres = window > 0
                    w_id = ((window - U32(1)) % U32(n)).astype(I32)
                    p_valid = (w_pres & (w_id != lrows[:, None])
                               & act[:, None])
                if scenario is not None and scenario.n_parts:
                    # Cross-partition probes cut at issue time (as the
                    # drop coin), so counters and the ack pipeline see
                    # only surviving probes.
                    p_valid = p_valid & ~cross_group(
                        cuts, lrows[:, None], w_id)
                if use_drop:
                    if scenario is not None:
                        probe_coin = (rng.probe_u.reshape(p_valid.shape)
                                      < site_drop_prob(
                                          scenario, scn, t,
                                          lrows[:, None], w_id))
                    else:
                        probe_coin = ((rng.probe_u.reshape(p_valid.shape)
                                       < cfg.drop_prob) & drop_active)
                    if cfg.telemetry:
                        telem_dropped.append(
                            (p_valid & probe_coin).sum(dtype=I32))
                    p_valid = p_valid & ~probe_coin
                ids_new = jnp.where(p_valid, w_id.astype(U32) + U32(1),
                                    U32(0))
            probe_ids2, probe_ids1 = probe_ids1, ids_new
            act_prev = act
            sent_probes = p_valid.sum(1, dtype=I32) * p_red
            # ids1/v1/tgt1 were derived in the ack-candidate block above
            # (state.probe_ids1 — probes issued at t-1).  The
            # act-of-target filter rode the packed table's single
            # all_gather + combined gather on the default arm
            # (probe_bits1); the split arm gathers per-branch so the
            # profiling-only 'none' branch structurally pays no [N]
            # all_gather.
            if cfg.count_probe_io:
                if probe_bits1 is None:
                    act_g = lax.all_gather(act, AX, tiled=True)     # [N]
                    ack_send = v1 & act_g[tgt1]
                else:
                    ack_send = v1 & _gathered_act(probe_bits1)
                # Exact per-target attribution (tpu_hash.make_step's
                # exact branch, distributed): local histograms over the
                # GLOBAL index space, summed-and-sliced back to the
                # owner shards by one psum_scatter each.
                recv_hist = jnp.zeros((n + 1,), I32).at[
                    jnp.where(v1, tgt1, n).reshape(-1)].add(
                        p_red, mode="drop")[:n]
                ack_hist = jnp.zeros((n + 1,), I32).at[
                    jnp.where(ack_send, tgt1, n).reshape(-1)].add(
                        1, mode="drop")[:n]
                recv_probe = lax.psum_scatter(
                    recv_hist, AX, scatter_dimension=0, tiled=True)
                sent_ack = lax.psum_scatter(
                    ack_hist, AX, scatter_dimension=0, tiled=True)
            elif cfg.probe_io_none:
                # PROFILING ONLY (PROBE_IO: none): zero the probe-recv/
                # ack-send counters, no per-target gather — probe sends /
                # ack recvs still counted (tpu_hash.make_step's twin).
                recv_probe = jnp.zeros_like(lrows)
                sent_ack = jnp.zeros_like(lrows)
            else:
                # Approximate per-node split, exact totals — the filters
                # of tpu_hash.make_step's scale branch, distributed
                # (_will_flush / _credit_orphan_recvs_sharded there).
                if probe_bits1 is None:
                    # split arm: three separate all_gathers + its own
                    # per-target bit gather (pre-round-6 lowering).
                    will_flush_l = wf_now()
                    will_flush_g = lax.all_gather(
                        will_flush_l, AX, tiled=True)        # [N]
                    act_g = lax.all_gather(act, AX, tiled=True)     # [N]
                    packed_g = _pack_probe_bits(will_flush_g,
                                                act_g)[tgt1]
                else:
                    # packed arm: bits1 rode the combined gather;
                    # will_flush_l/_g came from the packed table.
                    packed_g = probe_bits1
                per_prober = (v1 & _gathered_flush(packed_g)).sum(
                    1, dtype=I32) * p_red
                recv_probe = _credit_orphan_recvs_sharded(
                    per_prober, will_flush_l, will_flush_g, lrows,
                    AX)
                sent_ack = (v1 & _gathered_act(packed_g)).sum(
                    1, dtype=I32)
            sent_tick = sent_tick + sent_probes + sent_ack
            recv_add = recv_add + recv_probe + ack_recv_cnt

        pending_recv = pending_recv + recv_add
        if scenario is not None and scenario.has_updown:
            # Scenario up/down transitions at end of tick; a restart
            # wipes the node's local rows to a fresh incarnation
            # (tpu_hash.make_step's reset block on the local slice).
            failed = (state.failed | down_now) & ~up_now
            rcol_r = up_now[:, None]
            view = jnp.where(rcol_r, U32(0), view)
            view_ts = jnp.where(rcol_r, 0, view_ts)
            mail = jnp.where(rcol_r, U32(0), mail)
            pending_recv = jnp.where(up_now, 0, pending_recv)
            self_hb = jnp.where(up_now,
                                jnp.maximum(self_hb, 2 * (t + 1)),
                                self_hb)
            if cfg.probes > 0:
                probe_ids1 = jnp.where(rcol_r, U32(0), probe_ids1)
                probe_ids2 = jnp.where(rcol_r, U32(0), probe_ids2)
                act_prev = act_prev & ~up_now
            if bx is not None:
                # Legacy merges gossip into mail BEFORE this wipe; with
                # delivery deferred one tick the wipe must chase the
                # fresh exchange into the xbuf (distributes over the
                # max/sum head-merge, so the composite equals legacy).
                xnew = bx.wipe(*xnew, up_now)
        elif scenario is not None:
            failed = state.failed
        else:
            failed = state.failed | (fail_mask_l & (t == fail_time))

        if cfg.collect_events:
            agg = state.agg
            out = SparseTickEvents(join_ids, rm_ids, sent_tick, recv_tick)
        else:
            if cfg.fast_agg:
                pre = None
                if pfo is not None and "rm_cnt" in pfo:
                    # Row partials off the fused probe traversal —
                    # order-free integer sums/ors, bit-equal to the
                    # plane passes they replace.
                    pre = {"rm_total": pfo["rm_cnt"].sum(dtype=I32)}
                    if cfg.fail_ids:
                        det_cols = pfo["det_cols"]
                        pre["det_tick"] = jnp.stack(
                            [d.sum(dtype=I32) for d in det_cols])
                        any_rm = det_cols[0][:, 0] > 0
                        for d in det_cols[1:]:
                            any_rm = any_rm | (d[:, 0] > 0)
                        pre["any_true_rm"] = any_rm
                agg = update_fast_agg(
                    state.agg, t=t, fail_ids=cfg.fail_ids,
                    join_events=join_mask, rm_ids=rm_ids,
                    view_ids=cur_id, view_present=present,
                    fail_time=fail_time, holder_failed=fail_mask_l,
                    sent_tick=sent_tick, recv_tick=recv_tick, pre=pre)
            else:
                agg = update_agg(
                    state.agg, t=t, join_ids=join_ids, rm_ids=rm_ids,
                    view_ids=cur_id, view_present=present,
                    fail_mask=fail_mask_g, fail_time=fail_time,
                    sent_tick=sent_tick, recv_tick=recv_tick,
                    holder_failed=fail_mask_l)
            out = SparseTickEvents(
                lax.psum((join_ids != EMPTY).sum(dtype=I32), AX),
                lax.psum((rm_ids != EMPTY).sum(dtype=I32), AX),
                lax.psum(sent_tick.sum(dtype=I32), AX),
                lax.psum(recv_tick.sum(dtype=I32), AX))

        new_state = ShardedHashState(
            view, view_ts, started, in_group, failed, self_hb,
            mail, state.amail, state.pmail, joinreq_infl,
            joinrep_infl, pending_recv, agg,
            probe_ids1, probe_ids2, act_prev)
        if bx is not None:
            new_state = (new_state, xnew)
        if cfg.telemetry:
            # Sharded flight-recorder scalars: local reductions + one
            # psum each (observability/timeline.py).  The detections
            # delta is over the per-shard agg partials (0 in collect
            # mode, where agg passes through untouched).
            with jax.named_scope(PHASE_TELEMETRY):
                zero = jnp.zeros((), I32)
                det_local = (agg.det_count.sum(dtype=I32)
                             - state.agg.det_count.sum(dtype=I32)
                             if not cfg.collect_events else zero)
                dropped_g = lax.psum(sum(telem_dropped, zero), AX)
                telem = TickTelemetry(
                    live=lax.psum(act.sum(dtype=I32), AX),
                    suspected=lax.psum(numfailed.sum(dtype=I32), AX),
                    joins=lax.psum(
                        (join_ids != EMPTY).sum(dtype=I32), AX),
                    removals=lax.psum(
                        (rm_ids != EMPTY).sum(dtype=I32), AX),
                    detections=lax.psum(det_local, AX),
                    msgs_sent=lax.psum(sent_tick.sum(dtype=I32), AX),
                    msgs_recv=lax.psum(recv_tick.sum(dtype=I32), AX),
                    dropped=dropped_g,
                    probe_acks=lax.psum(
                        ack_recv_cnt.sum(dtype=I32), AX),
                    gossip_rows=lax.psum(
                        sent_gossip.sum(dtype=I32), AX))
                if cfg.telemetry_hist:
                    # Local partial histograms psum'd per field (linear
                    # reductions); the log2 drop bucket takes the GLOBAL
                    # dropped scalar (observability/timeline.py).  The
                    # fused-probe stale/susp partials are local too.
                    stale = susp = None
                    if pfo is not None and "stale_rows" in pfo:
                        stale = pfo["stale_rows"].sum(axis=0)
                        susp = pfo["susp_rows"].sum(axis=0)
                    hist = build_tick_hist(
                        difft=difft, present=present, size=size,
                        act=act, t=t, fail_time=fail_time,
                        tfail=cfg.tfail, det_tick=det_local,
                        dropped=dropped_g,
                        psum=lambda v: lax.psum(v, AX),
                        stale=stale, susp=susp)
                    return new_state, (out, (telem, hist))
            return new_state, (out, telem)
        return new_state, out

    step.batched_exchange = bx
    return step


def make_sharded_step(cfg: HashConfig, n_local: int, n_shards: int):
    n, s, g = cfg.n, cfg.s, cfg.g
    k_max = min(cfg.fanout, s)
    cap = bucket_capacity(cfg, n_local, n_shards)
    l_idx = jnp.arange(n_local, dtype=I32)

    def step(state: ShardedHashState, inputs):
        t, key, start_ticks_g, fail_mask_g, fail_time, drop_lo, drop_hi = inputs
        me = lax.axis_index(NODE_AXIS)
        row0 = (me * n_local).astype(I32)
        lrows = row0 + l_idx
        fail_mask_l = lax.dynamic_slice(fail_mask_g, (row0,), (n_local,))
        key_l = jax.random.fold_in(key, me)
        k_targets, k_entries, k_drop, k_drop_p = jax.random.split(key_l, 4)
        k_ctrl = jax.random.split(key, 1)[0]   # replicated draw
        start_ticks_l = lax.dynamic_slice(start_ticks_g, (row0,), (n_local,))
        self_slot = slot_of(cfg, lrows, lrows)
        self_slot_mask = jnp.arange(s, dtype=I32)[None, :] == self_slot[:, None]

        drop_active = (t > drop_lo) & (t <= drop_hi)
        if cfg.drop_prob > 0.0:
            ctrl_kept_g = ~(jax.random.bernoulli(k_ctrl, cfg.drop_prob, (2, n))
                            & drop_active)
        else:
            ctrl_kept_g = jnp.ones((2, n), bool)

        # ---- pass 1: receive = admit-or-refresh combine on local rows ----
        recv_mask = state.started & (t > start_ticks_l) & ~state.failed
        rcol = recv_mask[:, None]
        prev_id, _, prev_present = unpack(cfg, state.view)

        admit = make_admit(n, self_slot_mask, lrows)
        view = jnp.where(rcol, admit(state.view, state.amail), state.view)
        view = jnp.where(rcol, admit(view, state.mail), view)
        changed = view > state.view
        view_ts = jnp.where(changed, t, state.view_ts)
        mail = jnp.where(rcol, 0, state.mail)
        amail = jnp.where(rcol, 0, state.amail)

        cur_id, cur_hb, present = unpack(cfg, view)
        join_mask = changed & ~prev_present
        join_ids = jnp.where(join_mask, cur_id, EMPTY)

        ack_valid = (state.pmail > 0) & rcol
        ack_tgt = jnp.where(ack_valid, state.pmail.astype(I32) - 1, 0)
        pmail = jnp.where(rcol, 0, state.pmail)

        recv_tick = jnp.where(recv_mask, state.pending_recv, 0)
        pending_recv = jnp.where(recv_mask, 0, state.pending_recv)

        in_group = state.in_group | (state.joinrep_infl & recv_mask)
        joinrep_infl = state.joinrep_infl & ~recv_mask

        # ---- join handshake over gathered [N] bools ----
        started_g = lax.all_gather(state.started, NODE_AXIS, tiled=True)
        failed_g = lax.all_gather(state.failed, NODE_AXIS, tiled=True)
        joinreq_g = lax.all_gather(state.joinreq_infl, NODE_AXIS, tiled=True)
        in_group_g = lax.all_gather(in_group, NODE_AXIS, tiled=True)
        intro_recv = (started_g[INTRO] & (t > start_ticks_g[INTRO])
                      & ~failed_g[INTRO])
        seeds_g = joinreq_g & intro_recv
        joinreq_infl = state.joinreq_infl & ~intro_recv
        rep_ok_g = seeds_g & ctrl_kept_g[1]
        rep_ok_l = lax.dynamic_slice(rep_ok_g, (row0,), (n_local,))
        joinrep_infl = joinrep_infl | rep_ok_l
        n_seeds = seeds_g.sum(dtype=I32)
        is_intro_row = lrows == INTRO
        sent_rep = jnp.where(is_intro_row & intro_recv,
                             rep_ok_g.sum(dtype=I32), 0)
        pending_recv = pending_recv + rep_ok_l.astype(I32)

        # ---- nodeStart ----
        start_now = t == start_ticks_l
        started = state.started | start_now
        boot = t == start_ticks_g[INTRO]
        in_group = in_group | (is_intro_row & boot)

        ctrl0_l = lax.dynamic_slice(ctrl_kept_g[0], (row0,), (n_local,))
        joiner_req = start_now & (lrows != INTRO) & ctrl0_l
        joinreq_infl = joinreq_infl | joiner_req
        sent_req = joiner_req.astype(I32)

        # ---- self refresh ----
        act = started & (t > start_ticks_l) & ~state.failed & in_group
        own_hb = state.self_hb + 1
        self_hb = jnp.where(act, state.self_hb + 2, state.self_hb)
        self_on = act | (is_intro_row & boot)
        self_val = pack(cfg, jnp.where(act, own_hb, 0), lrows)
        old_self = view[l_idx, self_slot]
        view = view.at[l_idx, self_slot].set(
            jnp.where(self_on, self_val, old_self))
        view_ts = view_ts.at[l_idx, self_slot].set(
            jnp.where(self_on, t, view_ts[l_idx, self_slot]))
        cur_id, cur_hb, present = unpack(cfg, view)

        # ---- TFAIL / TREMOVE sweep ----
        difft = t - view_ts
        stale = present & (difft >= cfg.tfail) & act[:, None]
        numfailed = stale.sum(1, dtype=I32)
        removes = stale & (difft >= cfg.tremove)
        rm_ids = jnp.where(removes, cur_id, EMPTY)
        view = jnp.where(removes, 0, view)
        present = present & ~removes

        # ---- gossip selection ----
        size = present.sum(1, dtype=I32)
        numpotential = size - 1 - numfailed
        fresh = present & (difft < cfg.tfail)
        is_self_slot = cur_id == lrows[:, None]
        eligible = fresh & ~is_self_slot & act[:, None]
        in_seed = seeds_g[jnp.clip(cur_id, 0)] & present
        eligible = jnp.where(is_intro_row[:, None], eligible & ~in_seed,
                             eligible)
        seed_burst_on = boolean_any(is_intro_row & act)
        n_seeds_row = jnp.where(is_intro_row & act, n_seeds, 0)
        k_extra = jnp.clip(jnp.minimum(cfg.fanout, numpotential) - n_seeds_row, 0)
        tgt_slot, tgt_valid = sample_k_indices(k_targets, eligible, k_extra,
                                               k_max)
        tgt = jnp.take_along_axis(cur_id, tgt_slot, axis=1)         # [L, K]

        if g >= s:
            e_ids, e_hbs, e_valid = cur_id, cur_hb, fresh
        else:
            scores = jnp.where(is_self_slot, -1.0,
                               jax.random.uniform(k_entries, (n_local, s)))
            scores = jnp.where(fresh, scores, 2.0)
            _, e_idx = jax.lax.top_k(-scores, g)
            e_valid = jnp.take_along_axis(fresh, e_idx, axis=1)
            e_ids = jnp.take_along_axis(cur_id, e_idx, axis=1)
            e_hbs = jnp.take_along_axis(cur_hb, e_idx, axis=1)
        g_eff = e_ids.shape[1]

        msg_valid = tgt_valid[:, :, None] & e_valid[:, None, :]     # [L,K,G']
        if cfg.drop_prob > 0.0:
            kd_f, kd_s = jax.random.split(k_drop)
            dropped = jax.random.bernoulli(kd_f, cfg.drop_prob,
                                           (n_local, k_max, g_eff))
            msg_valid = msg_valid & ~(dropped & drop_active)
        else:
            kd_s = k_drop

        # ---- probe schedule (round-robin window, compacted to [L, P]) ----
        msgs = []   # (tgt, val, chan, valid) flattened pieces

        def emit(tgts, vals, chan, valids):
            msgs.append((tgts.reshape(-1), vals.reshape(-1),
                         jnp.full((tgts.size,), chan, I32),
                         valids.reshape(-1)))

        emit(jnp.broadcast_to(tgt[:, :, None], (n_local, k_max, g_eff)),
             pack(cfg, jnp.broadcast_to(e_hbs[:, None, :],
                                        (n_local, k_max, g_eff)),
                  jnp.broadcast_to(e_ids[:, None, :],
                                   (n_local, k_max, g_eff))),
             CH_GOSSIP, msg_valid)

        emit(jnp.full((n_local,), INTRO, I32),
             pack(cfg, jnp.zeros((n_local,), I32), lrows),
             CH_JOIN, joiner_req)

        # Introducer seed burst: full fresh view to each of this tick's
        # seeded joiners.  Only the introducer's shard emits valid entries.
        _, seed_idx = jax.lax.top_k(seeds_g.astype(I32), min(cfg.seed_cap, n))
        seed_valid = seeds_g[seed_idx] & seed_burst_on
        intro_here = (INTRO >= row0) & (INTRO < row0 + n_local)
        intro_local = jnp.clip(INTRO - row0, 0, n_local - 1)
        intro_fresh = fresh[intro_local]
        intro_ids = cur_id[intro_local]
        intro_hbs = cur_hb[intro_local]
        burst_valid = (seed_valid[:, None] & intro_fresh[None, :]
                       & intro_here)
        if cfg.drop_prob > 0.0:
            dropped = jax.random.bernoulli(kd_s, cfg.drop_prob,
                                           burst_valid.shape)
            burst_valid = burst_valid & ~(dropped & drop_active)
        emit(jnp.broadcast_to(seed_idx[:, None], burst_valid.shape),
             pack(cfg, jnp.broadcast_to(intro_hbs[None, :], burst_valid.shape),
                  jnp.broadcast_to(intro_ids[None, :], burst_valid.shape)),
             CH_GOSSIP, burst_valid)

        n_probe_tx = 0
        if cfg.probes > 0:
            ptr = lax.rem(t * cfg.probes, s)
            widx = lax.rem(ptr + jnp.arange(cfg.probes, dtype=I32), s)
            p_tgt = cur_id[:, widx]                               # [L, P]
            p_ok = (jnp.take_along_axis(
                        present & ~is_self_slot,
                        jnp.broadcast_to(widx[None, :], (n_local, cfg.probes)),
                        axis=1)
                    & act[:, None])
            ack_ok = ack_valid & act[:, None]
            if cfg.drop_prob > 0.0:
                kd1, kd2 = jax.random.split(k_drop_p)
                p_ok = p_ok & ~(jax.random.bernoulli(
                    kd1, cfg.drop_prob, p_ok.shape) & drop_active)
                ack_ok = ack_ok & ~(jax.random.bernoulli(
                    kd2, cfg.drop_prob, ack_ok.shape) & drop_active)
            own_entry = pack(cfg, jnp.broadcast_to(own_hb[:, None], p_tgt.shape),
                             jnp.broadcast_to(lrows[:, None], p_tgt.shape))
            # Redundant transmission when the pmail map is lossy
            # (tpu_hash.make_step): each copy is a separate wire message.
            p_copies = 1 if cfg.qp >= n else 2
            n_probe_tx = p_copies
            emit(p_tgt, own_entry, CH_PROBE0, p_ok)
            if p_copies == 2:
                emit(p_tgt, own_entry, CH_PROBE1, p_ok)
            # Acks: my (id, current hb) to each prober — collision-free
            # slot-addressed delivery at the receiver.
            emit(ack_tgt,
                 pack(cfg, jnp.broadcast_to(own_hb[:, None], ack_tgt.shape),
                      jnp.broadcast_to(lrows[:, None], ack_tgt.shape)),
                 CH_ACK, ack_ok)
            sent_probe_ack = (p_ok.sum(1, dtype=I32) * p_copies
                              + ack_ok.sum(1, dtype=I32))
        else:
            sent_probe_ack = jnp.zeros((n_local,), I32)

        all_tgt = jnp.concatenate([m[0] for m in msgs])
        all_val = jnp.concatenate([m[1] for m in msgs])
        all_chan = jnp.concatenate([m[2] for m in msgs])
        all_ok = jnp.concatenate([m[3] for m in msgs])

        # ---- bucket by destination shard, ship, deliver ----
        dest = all_tgt // n_local
        sort_key = jnp.where(all_ok, dest * N_CH + all_chan,
                             n_shards * N_CH)
        # a-plane carries (tgt, chan) packed; b-plane the entry payload.
        a_plane = (all_tgt.astype(U32) * U32(8) + all_chan.astype(U32))
        a_plane = jnp.where(all_ok, a_plane, U32(0xFFFFFFFF))
        b_plane = jnp.where(all_ok, all_val, 0)
        n_msgs = all_tgt.size
        if (n_shards * N_CH + 1) * (1 << 26) <= (1 << 32) \
                and n_msgs <= (1 << 26):
            # Pack (key, position) into ONE u32 and sort that alone: a
            # single-operand sort is ~4.5x a 3-operand comparator sort
            # (measured on the 8-dev CPU mesh: 65 vs 294 ms/shard at
            # 795k messages — the dominant term of the scatter step's
            # 10-min 32k warm-up, PERF.md), and the iota tie-break makes
            # it bit-identical to the stable multi-operand order.  The
            # payload planes follow by gather.  Falls back when the key
            # range (> 64 shards x channels) or message count overflows
            # the 6/26-bit packing.
            iota = jax.lax.iota(U32, n_msgs)
            packed = sort_key.astype(U32) * U32(1 << 26) + iota
            packed = jax.lax.sort(packed)
            perm = (packed & U32((1 << 26) - 1)).astype(I32)
            a_sorted = a_plane[perm]
            b_sorted = b_plane[perm]
        else:
            _, a_sorted, b_sorted = jax.lax.sort(
                (sort_key, a_plane, b_plane), num_keys=1)
        counts = jnp.zeros((n_shards + 1,), I32).at[
            jnp.where(all_ok, dest, n_shards)].add(1, mode="drop")[:n_shards]
        offsets = jnp.concatenate(
            [jnp.zeros((1,), I32), jnp.cumsum(counts)[:-1]])
        take = offsets[:, None] + jnp.arange(cap, dtype=I32)[None, :]
        in_bucket = jnp.arange(cap, dtype=I32)[None, :] < counts[:, None]
        take = jnp.clip(take, 0, all_tgt.size - 1)
        send_a = jnp.where(in_bucket, a_sorted[take], U32(0xFFFFFFFF))
        send_b = jnp.where(in_bucket, b_sorted[take], 0)
        # Overflow accounting (counts > cap drops the tail = lowest-priority
        # channels, thanks to the sort order).
        recv_a = lax.all_to_all(send_a, NODE_AXIS, split_axis=0,
                                concat_axis=0, tiled=True).reshape(
                                    n_shards * cap)
        recv_b = lax.all_to_all(send_b, NODE_AXIS, split_axis=0,
                                concat_axis=0, tiled=True).reshape(
                                    n_shards * cap)

        r_ok = recv_a != U32(0xFFFFFFFF)
        r_tgt = (recv_a // U32(8)).astype(I32)
        r_chan = (recv_a % U32(8)).astype(I32)
        r_row = jnp.clip(r_tgt - row0, 0, n_local - 1)
        r_ok = r_ok & (r_tgt >= row0) & (r_tgt < row0 + n_local)
        r_id = ((recv_b - U32(1)) % U32(n)).astype(I32)

        def scatter_channel(buf, slot, val, mask):
            addr = jnp.where(mask, r_row * buf.shape[1] + slot,
                             n_local * buf.shape[1])
            return buf.reshape(-1).at[addr].max(
                jnp.where(mask, val, 0), mode="drop").reshape(buf.shape)

        view_slot = slot_of(cfg, r_tgt, r_id)
        is_gossip = r_ok & ((r_chan == CH_GOSSIP) | (r_chan == CH_JOIN)
                            | (r_chan == CH_PROBE0) | (r_chan == CH_PROBE1))
        mail = scatter_channel(mail, view_slot, recv_b, is_gossip)
        amail = scatter_channel(amail, view_slot, recv_b,
                                r_ok & (r_chan == CH_ACK))
        if cfg.probes > 0:
            for c, ch in enumerate([CH_PROBE0, CH_PROBE1][:n_probe_tx]):
                pslot = hash_slot(r_id, t + c * 0x2545F49, cfg.qp, n)
                pmail = scatter_channel(pmail, pslot,
                                        r_id.astype(U32) + U32(1),
                                        r_ok & (r_chan == ch))
        # JOINREQ flag for the introducer (value also merged as gossip, as
        # in tpu_hash: the joiner's entry is admitted into intro's view).
        # The in-flight joinreq bool is tracked sender-side above.

        recv_add = jnp.zeros((n_local + 1,), I32).at[
            jnp.where(r_ok, r_row, n_local)].add(1, mode="drop")[:n_local]
        pending_recv = pending_recv + recv_add

        sent_tick = (msg_valid.sum((1, 2), dtype=I32) + sent_req + sent_rep
                     + sent_probe_ack
                     + jnp.where(is_intro_row,
                                 burst_valid.sum(dtype=I32), 0))

        failed = state.failed | (fail_mask_l & (t == fail_time))

        if cfg.collect_events:
            agg = state.agg
            out = SparseTickEvents(join_ids, rm_ids, sent_tick, recv_tick)
        else:
            # Per-shard partials: id-indexed fields are [N] scatter targets
            # (psum-reduced after the scan), observer-row fields are local
            # [L] slices (all_gathered after the scan).
            agg = update_agg(
                state.agg, t=t, join_ids=join_ids, rm_ids=rm_ids,
                view_ids=cur_id, view_present=present,
                fail_mask=fail_mask_g, fail_time=fail_time,
                sent_tick=sent_tick, recv_tick=recv_tick,
                holder_failed=fail_mask_l)
            out = SparseTickEvents(
                lax.psum((join_ids != EMPTY).sum(dtype=I32), NODE_AXIS),
                lax.psum((rm_ids != EMPTY).sum(dtype=I32), NODE_AXIS),
                lax.psum(sent_tick.sum(dtype=I32), NODE_AXIS),
                lax.psum(recv_tick.sum(dtype=I32), NODE_AXIS))

        new_state = ShardedHashState(
            view, view_ts, started, in_group, failed, self_hb, mail, amail,
            pmail, joinreq_infl, joinrep_infl, pending_recv, agg,
            state.probe_ids1, state.probe_ids2, state.act_prev)
        return new_state, out

    return step


def boolean_any(x: jax.Array) -> jax.Array:
    return x.any()


def reduce_fast_agg(agg: FastAgg, ax=NODE_AXIS) -> FastAgg:
    """Reduce per-shard FastAgg partials to the replicated global value."""
    return FastAgg(
        det_count=lax.psum(agg.det_count, ax),
        trackers=lax.psum(agg.trackers, ax),
        tracker_obs=lax.all_gather(agg.tracker_obs, ax, tiled=True),
        det_obs=lax.all_gather(agg.det_obs, ax, tiled=True),
        lat_hist=lax.psum(agg.lat_hist, ax),
        join_total=lax.psum(agg.join_total, ax),
        rm_total=lax.psum(agg.rm_total, ax),
        sent_total=lax.all_gather(agg.sent_total, ax, tiled=True),
        recv_total=lax.all_gather(agg.recv_total, ax, tiled=True),
    )


def reduce_agg(agg: AggStats, ax=NODE_AXIS) -> AggStats:
    """Reduce per-shard agg partials to the replicated global AggStats:
    psum for counts/histogram, pmin/pmax for first/last ticks, all_gather
    for observer-row-indexed fields."""
    return AggStats(
        rm_count=lax.psum(agg.rm_count, ax),
        det_count=lax.psum(agg.det_count, ax),
        rm_first=lax.pmin(agg.rm_first, ax),
        rm_last=lax.pmax(agg.rm_last, ax),
        join_count=lax.psum(agg.join_count, ax),
        trackers=lax.psum(agg.trackers, ax),
        tracker_obs=lax.all_gather(agg.tracker_obs, ax, tiled=True),
        det_obs=lax.all_gather(agg.det_obs, ax, tiled=True),
        lat_hist=lax.psum(agg.lat_hist, ax),
        sent_total=lax.all_gather(agg.sent_total, ax, tiled=True),
        recv_total=lax.all_gather(agg.recv_total, ax, tiled=True),
    )


_RUNNER_CACHE: dict = {}


def carry_state_spec(cfg: HashConfig, axes):
    """The boundary carry's PartitionSpec tree (shared by _build_step's
    shard_map specs and the multi-process chunked driver, which must
    rebuild the global device carry from the host snapshot with exactly
    these shardings — runtime/distributed.device_put_global)."""
    agg_t = FastAgg if cfg.fast_agg else AggStats
    agg_spec = agg_t(*(P() for _ in agg_t._fields))
    return ShardedHashState(
        **{f: (agg_spec if f == "agg" else P(axes))
           for f in ShardedHashState._fields})


def _build_step(cfg: HashConfig, n_local: int, mesh: Mesh, warm: bool):
    """(step, init, state_spec, out_spec, AX) — the shared construction of
    the whole-run and chunked segment runners, single-sourced so the two
    cannot drift (the segment runner's bit-exactness with the whole-run
    scan is a test contract, tests/test_checkpoint.py)."""
    axes = tuple(mesh.axis_names)
    axis_sizes = tuple(mesh.shape[a] for a in axes)
    n_shards = int(np.prod(axis_sizes))
    AX = axes if len(axes) > 1 else axes[0]
    ring = cfg.exchange == "ring"
    if len(axes) > 1 and not ring:
        raise ValueError(
            "2-D torus meshes require EXCHANGE ring (the bucketed "
            "all_to_all exchange is 1-D only)")
    if cfg.folded:
        from distributed_membership_tpu.backends.tpu_hash_folded import (
            init_local_state_warm_folded, make_ring_sharded_folded_step)
        step = make_ring_sharded_folded_step(cfg, n_local, n_shards,
                                             axes=axes,
                                             axis_sizes=axis_sizes)
        init = lambda k: init_local_state_warm_folded(  # noqa: E731
            cfg, n_local, k, ax=AX)
    else:
        step = (make_ring_sharded_step(cfg, n_local, n_shards,
                                       cold_join=not warm, axes=axes,
                                       axis_sizes=axis_sizes) if ring
                else make_sharded_step(cfg, n_local, n_shards))
        init = lambda k: (init_local_state_warm(cfg, n_local, k,  # noqa: E731
                                                ax=AX)
                          if warm else init_local_state(cfg, n_local))

    # The reduced (or untouched-zero) agg is replicated; everything
    # else is node-sharded (over BOTH axes when the mesh is 2-D —
    # P(axes-tuple) is the outer-major flattening AX flattens to).
    state_spec = carry_state_spec(cfg, axes)
    if cfg.collect_events:
        out_spec = SparseTickEvents(
            join_ids=P(None, axes, None),
            rm_ids=P(None, axes, None),
            sent=P(None, axes), recv=P(None, axes))
    else:
        out_spec = SparseTickEvents(P(None), P(None), P(None), P(None))
    if cfg.telemetry:
        # The per-tick outputs become (events, TickTelemetry) — every
        # telemetry field is a replicated scalar (psum'd in-step).
        # Under the hist tier the telemetry slot is a (scalars, hists)
        # pair: each histogram is a replicated [B] vector.
        tspec = telemetry_spec(P(None))
        out_spec = (out_spec, ((tspec, hist_spec(P(None)))
                               if cfg.telemetry_hist else tspec))
    return step, init, state_spec, out_spec, AX


def _xchg_of(step):
    """The step's BatchedExchange handle (None on the legacy paths).

    The xbuf lane lives strictly INSIDE the scan: runners wrap the
    boundary carry with a zero xbuf and flush the final one back into
    mail/pending_recv, so the shard_map boundary (state_spec, the
    checkpoint codec, resume identity) stays legacy-shaped and
    EXCHANGE_MODE is trajectory-inert."""
    return getattr(step, "batched_exchange", None)


def _flush_xbuf(carry, bx):
    state, (xpay, xcnt) = carry
    return state._replace(
        mail=bx.merge_mail(state.mail, xpay),
        pending_recv=state.pending_recv + bx.merge_pending(xcnt))


def _get_runner(cfg: HashConfig, n_local: int, mesh: Mesh, warm: bool):
    cache_key = (cfg, n_local, mesh, warm)
    if cache_key not in _RUNNER_CACHE:
        step, init, state_spec, out_spec, AX = _build_step(
            cfg, n_local, mesh, warm)

        def whole_run(*args):
            # Trailing arg beyond the 8 fixed ones is the scenario
            # tensor plan (replicated — every shard slices its rows
            # elementwise).
            (keys, ticks, start_ticks, fail_mask_g, fail_time,
             drop_lo, drop_hi, warm_key) = args[:8]
            extra = args[8:]
            state0 = init(warm_key)
            bx = _xchg_of(step)
            if bx is not None:
                state0 = (state0, bx.zero())

            def body(state, inp):
                t, k = inp
                return step(state, (t, k, start_ticks, fail_mask_g,
                                    fail_time, drop_lo, drop_hi) + extra)

            final_state, out = lax.scan(body, state0, (ticks, keys))
            if bx is not None:
                final_state = _flush_xbuf(final_state, bx)
            if not cfg.collect_events:
                final_state = final_state._replace(
                    agg=(reduce_fast_agg if cfg.fast_agg else reduce_agg)(
                        final_state.agg, ax=AX))
            return final_state, out

        n_in = 9 if cfg.scenario is not None else 8
        sharded = shard_map(
            whole_run, mesh=mesh,
            in_specs=(P(),) * n_in,
            out_specs=(state_spec, out_spec),
            check_vma=False,
        )
        _RUNNER_CACHE[cache_key] = jax.jit(sharded)
    return _RUNNER_CACHE[cache_key]


def _get_init_runner(cfg: HashConfig, n_local: int, mesh: Mesh, warm: bool):
    """shard_map'd initial-carry builder for the chunked driver: outputs
    the GLOBAL carry representation the segment runner round-trips
    (node-sharded fields concatenated; agg replicated — in aggregate mode
    the agg slot carries the cross-segment ACCUMULATED global aggregates,
    so it is initialized in the reduced/global shape)."""
    cache_key = (cfg, n_local, mesh, warm, "init")
    if cache_key not in _RUNNER_CACHE:
        _, init, state_spec, _, AX = _build_step(cfg, n_local, mesh, warm)

        def init_run(warm_key):
            state0 = init(warm_key)
            if not cfg.collect_events:
                state0 = state0._replace(
                    agg=(init_fast_agg(len(cfg.fail_ids), cfg.n)
                         if cfg.fast_agg else init_agg(cfg.n)))
            return state0

        _RUNNER_CACHE[cache_key] = jax.jit(shard_map(
            init_run, mesh=mesh, in_specs=(P(),), out_specs=state_spec,
            check_vma=False))
    return _RUNNER_CACHE[cache_key]


def _get_segment_runner(cfg: HashConfig, n_local: int, mesh: Mesh,
                        warm: bool):
    """Chunked-scan twin of :func:`_get_runner` (runtime/checkpoint.py).

    The carry crosses the shard_map boundary in its global representation
    (the same one the whole-run out_specs produce).  In aggregate mode the
    carried agg slot holds the cross-segment accumulated GLOBAL
    aggregates: the segment ignores it, accumulates fresh per-shard
    partials from zero, and returns them reduced — the chunked adapter in
    :func:`run_scan_sharded` merges segment results host-side
    (observability/aggregates.merge_agg)."""
    cache_key = (cfg, n_local, mesh, warm, "segment")
    if cache_key not in _RUNNER_CACHE:
        step, _, state_spec, out_spec, AX = _build_step(
            cfg, n_local, mesh, warm)

        def seg_run(state, *args):
            (ticks, keys, start_ticks, fail_mask_g, fail_time,
             drop_lo, drop_hi) = args[:7]
            extra = args[7:]            # scenario tensor plan, if any
            if not cfg.collect_events:
                # The incoming agg is the accumulated global value (shape
                # ≠ the per-shard partials); start this segment's
                # partials from the local zero identity.
                state = state._replace(
                    agg=(init_fast_agg(len(cfg.fail_ids), n_local)
                         if cfg.fast_agg else init_agg(cfg.n, n_local)))

            def body(state, inp):
                t, k = inp
                return step(state, (t, k, start_ticks, fail_mask_g,
                                    fail_time, drop_lo, drop_hi) + extra)

            # MEGA_TICKS >= 2: T-tick blocks inside the shard_map — the
            # codec and block restitching are elementwise/reshape-only
            # on the per-shard leaves (no collectives), so the mega
            # wrapper slots between the agg re-init above and the agg
            # reduction below without touching either.
            bx = _xchg_of(step)
            if bx is not None:
                # The xbuf rides INSIDE the segment only: the boundary
                # carry stays legacy-shaped (checkpoints / resume
                # identity unchanged), at the cost of one un-overlapped
                # head merge per segment boundary.
                state = (state, bx.zero())
            if cfg.mega_ticks > 1:
                final_state, out = mega_scan(
                    body, state, (ticks, keys), cfg.mega_ticks,
                    cfg.mega_pack)
            else:
                final_state, out = lax.scan(body, state, (ticks, keys))
            if bx is not None:
                final_state = _flush_xbuf(final_state, bx)
            if not cfg.collect_events:
                final_state = final_state._replace(
                    agg=(reduce_fast_agg if cfg.fast_agg else reduce_agg)(
                        final_state.agg, ax=AX))
            return final_state, out

        n_in = 8 if cfg.scenario is not None else 7
        sharded = shard_map(
            seg_run, mesh=mesh,
            in_specs=(state_spec,) + (P(),) * n_in,
            out_specs=(state_spec, out_spec),
            check_vma=False,
        )
        _RUNNER_CACHE[cache_key] = jax.jit(sharded)
    return _RUNNER_CACHE[cache_key]


def sharded_config(params: Params, collect_events: bool, fail_ids: tuple,
                   scenario, n_local: int) -> HashConfig:
    """``make_config`` + the per-shard structural re-validation, as one
    function: make_config checked the GLOBAL shapes; the folded planes /
    kernel row blocks cover the LOCAL rows here.  A violated path that
    the user PINNED on (knob 1) raises loudly; one the fusegate
    auto-enabled (knob -1, resolved against global shapes only) silently
    downgrades to the jnp path — auto never raises.

    Single-sourced so the service daemon's live-injection recompile
    (service/daemon._make_hook) builds EXACTLY the config this batch
    entrypoint runs — same downgrade decisions, same cache key shape."""
    cfg = make_config(params, collect_events, fail_ids=fail_ids,
                      scenario=scenario)
    if cfg.probe_io_lag:
        raise ValueError(
            "PROBE_IO approx_lag is single-chip tpu_hash only (the "
            "sharded twins keep the two-gather attribution)")

    def _downgrade_or_raise(knob: int, msg: str, **off):
        nonlocal cfg
        if knob == -1:
            import dataclasses as _dc
            cfg = _dc.replace(cfg, **off)
        else:
            raise ValueError(msg)

    if cfg.folded:
        from distributed_membership_tpu.backends.tpu_hash_folded import (
            folded_supported)
        if not folded_supported(n_local, cfg.s, cfg.probes):
            _downgrade_or_raise(
                params.FOLDED,
                f"FOLDED on tpu_hash_sharded needs the per-shard row "
                f"count to fold (L={n_local}, S={cfg.s}, P={cfg.probes}: "
                "L must be a multiple of 128/S and 128/P)",
                folded=False,
                # The folded-fused twins ship as a pair with the layout;
                # auto-resolved kernels must not survive its downgrade
                # onto the natural S<128 planes they cannot tile.
                fused_receive=(cfg.fused_receive
                               and params.FUSED_RECEIVE != -1),
                fused_gossip=(cfg.fused_gossip
                              and params.FUSED_GOSSIP != -1))
    if cfg.folded and (cfg.fused_gossip or cfg.fused_receive):
        # Only the row-block tiling minimum applies on the local planes.
        if (n_local * cfg.s) // 128 < 8:
            pinned = ((cfg.fused_receive and params.FUSED_RECEIVE == 1)
                      or (cfg.fused_gossip and params.FUSED_GOSSIP == 1))
            _downgrade_or_raise(
                1 if pinned else -1,
                f"FOLDED FUSED_* on tpu_hash_sharded needs at least 8 "
                f"local plane rows (L*S/128 >= 8; got L={n_local}, "
                f"S={cfg.s})",
                fused_receive=False, fused_gossip=False)
    elif not cfg.folded:
        # Full natural-shape re-check for BOTH kernels: a pinned kernel
        # can arrive here having passed only make_config's FOLDED-branch
        # validation (8 plane rows) and then lost the folded layout to
        # the per-shard downgrade above — S < 128 or a droppy config
        # must not reach the natural stacked kernel.
        if cfg.fused_gossip and (n_local < 8 or cfg.s % 128 != 0):
            # Drops are fine here: the stacked payloads are drop-masked
            # at the sender before the ppermute, so the kernel never
            # sees the RNG stream.
            _downgrade_or_raise(
                params.FUSED_GOSSIP,
                f"FUSED_GOSSIP on tpu_hash_sharded needs S % 128 == 0 "
                f"and at least 8 rows per shard "
                f"(got L={n_local}, S={cfg.s}); "
                "for S < 128 it requires the FOLDED layout, which the "
                "per-shard row count rejected",
                fused_gossip=False)
        if cfg.fused_receive:
            from distributed_membership_tpu.ops.fused_receive import (
                fused_supported)
            if not fused_supported(n_local, cfg.s):
                _downgrade_or_raise(
                    params.FUSED_RECEIVE,
                    f"FUSED_RECEIVE on tpu_hash_sharded needs the "
                    f"per-shard row count to support the kernel tiling "
                    f"(got L={n_local}, S={cfg.s}; need S % 128 == 0 "
                    f"and L >= 8)",
                    fused_receive=False)
    return cfg


def run_scan_sharded(params: Params, plan: FailurePlan, seed: int,
                     mesh: Mesh, collect_events: bool = True,
                     total_time: Optional[int] = None, telemetry=None):
    n = params.EN_GPSZ
    d = mesh.size
    if n % d != 0:
        raise ValueError(f"EN_GPSZ={n} not divisible by mesh size {d}")
    n_local = n // d
    fail_ids = tuple(plan.failed_indices) if plan.fail_time is not None else ()
    scn_prog = getattr(plan, "scenario", None)
    cfg = sharded_config(params, collect_events, fail_ids,
                         None if scn_prog is None else scn_prog.static,
                         n_local)
    scn_extra = () if scn_prog is None else (scn_prog.tensors(),)
    total = total_time if total_time is not None else params.TOTAL_TIME
    params.validate_sparse_packing(total)
    cfg = resolve_mega_pack(cfg, params, total)
    warm = params.JOIN_MODE == "warm"

    if params.CHECKPOINT_EVERY > 0:
        from distributed_membership_tpu.observability.aggregates import (
            merge_agg)
        from distributed_membership_tpu.runtime.checkpoint import (
            chunked_run, compact_sparse)
        init_run = _get_init_runner(cfg, n_local, mesh, warm)
        seg = _get_segment_runner(cfg, n_local, mesh, warm)
        warm_key = make_run_key(params, seed ^ 0x5EED)
        from distributed_membership_tpu.runtime.distributed import (
            device_put_global, process_count, to_host)
        multi = process_count() > 1
        spec = (carry_state_spec(cfg, tuple(mesh.axis_names))
                if multi else None)

        def segment_fn(carry, *rest):
            agg_host = None if collect_events else to_host(carry.agg)
            if multi:
                # The chunked driver hosts the carry after every
                # segment (global numpy on every process); rebuild the
                # global device carry against the mesh before the next
                # shard_map segment.
                carry = device_put_global(carry, mesh, spec)
            new_state, ev = seg(carry, *rest)
            if not collect_events:
                # The carried agg slot is the cross-segment GLOBAL
                # accumulator; the segment returned its own reduced
                # contribution — merge host-side (disjoint tick ranges).
                new_state = new_state._replace(agg=merge_agg(
                    agg_host, to_host(new_state.agg)))
            return new_state, ev

        return chunked_run(
            params, plan, seed, total,
            init_carry=lambda: init_run(warm_key),
            segment_fn=segment_fn, collect_events=collect_events,
            compact_fn=compact_sparse if collect_events else None,
            event_type=None if collect_events else SparseTickEvents,
            extra_inputs=scn_extra,
            telemetry_sink=(
                (telemetry.flush if telemetry is not None
                 else lambda telem, t0: None) if cfg.telemetry else None))

    (ticks, keys, start_ticks, fail_mask, fail_time,
     drop_lo, drop_hi) = plan_tensors(params, plan, seed, total)

    run = _get_runner(cfg, n_local, mesh, warm)
    final_state, events = run(keys, ticks, start_ticks, fail_mask,
                              fail_time, drop_lo, drop_hi,
                              make_run_key(params, seed ^ 0x5EED),
                              *scn_extra)
    from distributed_membership_tpu.runtime.distributed import (
        process_count, to_host)
    events = to_host(events)
    if process_count() > 1:
        # finish_run and the summary readers np.asarray these fields;
        # gather the global values so every process reports (and logs)
        # identically.
        final_state = to_host(final_state)
    if cfg.telemetry:
        events, telem = events
        if telemetry is not None:
            telemetry.flush(telem, 0)
    return final_state, events


def resolve_mesh(params: Params, mesh: Optional[Mesh] = None) -> Mesh:
    """The run mesh: MESH_SHAPE when pinned, else the largest device
    count dividing N.  Single-sourced so the service daemon's served
    sharded run shards exactly as this batch entrypoint would."""
    if mesh is not None:
        return mesh
    if params.MESH_SHAPE:
        from distributed_membership_tpu.parallel.mesh import (
            make_torus_mesh)
        dims = [int(x) for x in params.MESH_SHAPE.lower().split("x")]
        return make_torus_mesh(*dims)
    n_dev = len(jax.devices())
    d = max(x for x in range(1, n_dev + 1)
            if params.EN_GPSZ % x == 0)
    return make_mesh(d)


def bind_run_scan(mesh: Mesh):
    """A ``run_scan``-shaped callable closed over ``mesh`` — the form
    ``finish_run`` and the service daemon drive."""
    def run_scan_bound(params, plan, seed, collect_events=True,
                       total_time=None, telemetry=None):
        return run_scan_sharded(params, plan, seed, mesh,
                                collect_events=collect_events,
                                total_time=total_time,
                                telemetry=telemetry)
    return run_scan_bound


@register("tpu_hash_sharded")
def run_tpu_hash_sharded(params: Params, log: Optional[EventLog] = None,
                         seed: Optional[int] = None,
                         mesh: Optional[Mesh] = None) -> RunResult:
    t0 = _time.time()
    seed = params.SEED if seed is None else seed
    log = log if log is not None else EventLog()
    plan = resolve_plan(params, _pyrandom.Random(f"app:{seed}"))

    mesh = resolve_mesh(params, mesh)
    result = finish_run(params, plan, log, bind_run_scan(mesh), t0, seed)
    result.extra["mesh_size"] = mesh.size
    return result
