"""`tpu_sparse` backend: bounded member views for large N.

The dense `tpu` backend's ``[N, N]`` id-indexed state is exact but O(N^2) —
structurally the same wall the reference hits with its full-list gossip
(SURVEY.md §5 "long-context" note: the scaling axis here is node count).
This backend is the scale path: each node keeps a bounded view of
``M = VIEW_SIZE`` slots ``(member id, heartbeat, timestamp)`` and gossips
``G = GOSSIP_LEN`` entries to ``FANOUT`` targets per tick — the fixed-size
partial list the spec explicitly permits (mp1_specifications.pdf §4), i.e.
SWIM-style dissemination with the reference's gossip-heartbeat semantics:

  * receiver merge rule: max heartbeat per id, timestamp refreshed only on
    strict increase (MP1Node.cpp:278-288) — ops/view_merge.merge_views;
  * TFAIL/TREMOVE sweep per slot (MP1Node.cpp:429-446);
  * stale entries withheld from gossip (MP1Node.cpp:376 — the
    anti-resurrection rule);
  * join handshake through the introducer (MP1Node.cpp:126-163, 226-251),
    with the joiner's JOINREQ riding the same mailbox as gossip;
  * messages-in-flight = per-receiver hash-slotted mailbox with max-combine
    (ops/view_merge.scatter_mailbox): 1-tick latency like EmulNet, lossless
    when ``MAILBOX_SIZE >= N``, bounded-capacity drops beyond — EmulNet's
    ENBUFFSIZE behavior recast per receiver (EmulNet.h:12, EmulNet.cpp:90).

With ``VIEW_SIZE = 0`` (M = N) and ``MAILBOX_SIZE >= N`` the protocol is
equivalent to the dense backend's (same merge, same sweep, same fanout
distribution — RNG draws differ, so parity is distributional:
tests/test_sparse_backend.py).  With M << N it runs at 100k-1M nodes on one
chip: all per-tick work is O(N * (M + Q + K*G)) with static shapes — two
batched sorts, one scatter-max, one top_k — no data-dependent shapes
anywhere, so XLA tiles every op.

``JOIN_MODE: warm`` bootstraps every node in-group with a random M-slot
neighborhood at t=0 (the standard deployment assumption for a 1M-node
failure-detection service, where a single introducer would be the
bottleneck); staggered/batch introducer joins remain for parity runs.

**Direct probing (``PROBES > 0``) — why heartbeat gossip alone cannot scale.**
With bounded views, news about member x reaches a given view-holder at rate
~``FANOUT * GOSSIP_LEN / N`` per tick — entries go stale faster than TFAIL
once ``N > FANOUT * GOSSIP_LEN * TFAIL`` and the detector drowns in false
positives.  (The reference never sees this: its full-list gossip refreshes
every entry at rate FANOUT, but only because each message carries all N
entries — the O(N^2) traffic wall.)  The SWIM answer, and ours, is direct
probing: each node pings ``PROBES`` random view members per tick (a probe
mailbox slot keyed by prober id + the prober's own entry piggybacked), and a
probed node acks with its current heartbeat next tick.  Entry refresh
interval becomes ``M/PROBES + 2`` ticks — independent of N — so TFAIL/TREMOVE
keep their O(1) meaning at any scale.  The TFAIL stage doubles as SWIM's
suspicion state: a suspect is withheld from gossip but stays probed, and a
late ack (strictly higher heartbeat) rescues it before TREMOVE.
"""

from __future__ import annotations

import dataclasses
import os
import random as _pyrandom
import time as _time
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from distributed_membership_tpu.addressing import INTRODUCER_INDEX
from distributed_membership_tpu.backends import RunResult, register
from distributed_membership_tpu.config import Params
from distributed_membership_tpu.eventlog import EventLog
from distributed_membership_tpu.observability.aggregates import (
    AggStats, detection_summary, init_agg, update_agg)
from distributed_membership_tpu.ops.sampling import sample_k_indices
from distributed_membership_tpu.ops.view_merge import (
    EMPTY, merge_views, scatter_mailbox, unpack_mailbox)
from distributed_membership_tpu.runtime.failures import (
    FailurePlan, log_failures, make_run_key, plan_tensors, resolve_plan)

I32 = jnp.int32
U32 = jnp.uint32
SEED_CAP = 8  # max JOINREQs the introducer can answer per tick; the
#               staggered schedule produces at most ceil(1/STEP_RATE) = 4.


class SparseState(NamedTuple):
    slot_id: jax.Array   # [N, M] i32, EMPTY = free
    slot_hb: jax.Array   # [N, M] i32
    slot_ts: jax.Array   # [N, M] i32
    started: jax.Array   # [N] bool
    in_group: jax.Array  # [N] bool
    failed: jax.Array    # [N] bool
    self_hb: jax.Array   # [N] i32
    mail: jax.Array      # [N, Q] u32 packed (hb * N + id + 1), 0 = empty
    pmail: jax.Array     # [N, Qp] u32 probe mailbox (prober id + 1), 0 = empty
    amail: jax.Array     # [N, Qa] u32 ack mailbox — acks get their own
    #                      channel so their delivery never competes with
    #                      gossip volume for hash slots
    joinreq_infl: jax.Array  # [N] bool
    joinrep_infl: jax.Array  # [N] bool
    pending_recv: jax.Array  # [N] i32
    agg: AggStats        # on-device event aggregates (updated only when
    #                      collect_events=False — the scale path)


class SparseTickEvents(NamedTuple):
    join_ids: jax.Array   # [N, M] i32 — id joined into this slot, EMPTY none
    rm_ids: jax.Array     # [N, M] i32 — id removed from this slot, EMPTY none
    sent: jax.Array       # [N] i32
    recv: jax.Array       # [N] i32


@dataclasses.dataclass(frozen=True)
class SparseConfig:
    n: int
    m: int          # view slots per node
    q: int          # mailbox slots per node
    g: int          # entries piggybacked per gossip message
    tfail: int
    tremove: int
    fanout: int
    drop_prob: float
    probes: int = 0  # direct probes per tick (0 = pure gossip, parity mode)
    qp: int = 16     # probe-mailbox slots
    qa: int = 16     # ack-mailbox slots
    seed_cap: int = SEED_CAP  # max JOINREQs answered with a burst per tick
    collect_events: bool = True


def auto_mailbox_size(n: int, m: int, g: int, fanout: int) -> int:
    """Default Q: lossless (== N) while affordable, else sized so expected
    distinct incoming ids per tick (~ fanout * G) hash with low collision."""
    if n <= 1024:
        return n
    return max(256, 4 * fanout * g)


def init_state(cfg: SparseConfig) -> SparseState:
    n, m, q = cfg.n, cfg.m, cfg.q
    return SparseState(
        slot_id=jnp.full((n, m), EMPTY, I32),
        slot_hb=jnp.zeros((n, m), I32),
        slot_ts=jnp.zeros((n, m), I32),
        started=jnp.zeros((n,), bool),
        in_group=jnp.zeros((n,), bool),
        failed=jnp.zeros((n,), bool),
        self_hb=jnp.zeros((n,), I32),
        mail=jnp.zeros((n, q), U32),
        pmail=jnp.zeros((n, cfg.qp), U32),
        amail=jnp.zeros((n, cfg.qa), U32),
        joinreq_infl=jnp.zeros((n,), bool),
        joinrep_infl=jnp.zeros((n,), bool),
        pending_recv=jnp.zeros((n,), I32),
        agg=init_agg(n),
    )


def init_state_warm(cfg: SparseConfig, key: jax.Array) -> SparseState:
    """Every node in-group at t=0 with self + M-1 random neighbors (hb 0,
    ts 0).  Sampling is with replacement — duplicate ids within a row are
    collapsed by the first tick's merge (merge_views dedupes local slots)."""
    n, m = cfg.n, cfg.m
    st = init_state(cfg)
    idx = jnp.arange(n, dtype=I32)
    # Neighbor j of node i: i + 1 + U[0, n-2] (mod n) — never self.
    offs = jax.random.randint(key, (n, m - 1), 1, max(n, 2), dtype=I32)
    nbrs = jax.lax.rem(idx[:, None] + offs, n)
    slot_id = jnp.concatenate([idx[:, None], nbrs], axis=1)
    return st._replace(
        slot_id=slot_id,
        started=jnp.ones((n,), bool),
        in_group=jnp.ones((n,), bool),
    )


def make_step(cfg: SparseConfig):
    """Per-tick transition, mirroring the dense step's pass structure
    (backends/tpu.py) on bounded state.  Pure/jittable; schedules arrive as
    tensors so one compilation serves every seed and failure plan."""
    n, m, q, g = cfg.n, cfg.m, cfg.q, cfg.g
    intro = INTRODUCER_INDEX
    idx = jnp.arange(n, dtype=I32)
    k_max = min(cfg.fanout, m)

    def step(state: SparseState, inputs):
        t, key, start_ticks, fail_mask, fail_time, drop_lo, drop_hi = inputs
        (k_targets, k_entries, k_drop, k_ctrl,
         k_probe, k_drop_p) = jax.random.split(key, 6)

        drop_active = (t > drop_lo) & (t <= drop_hi)
        if cfg.drop_prob > 0.0:
            ctrl_kept = ~(jax.random.bernoulli(k_ctrl, cfg.drop_prob, (2, n))
                          & drop_active)
        else:
            ctrl_kept = jnp.ones((2, n), bool)

        # ---- pass 1: receive (recvLoop gate, Application.cpp:130) ----
        recv_mask = state.started & (t > start_ticks) & ~state.failed
        in_id, in_hb, in_valid = unpack_mailbox(state.mail, n)
        in_valid = in_valid & recv_mask[:, None]
        mail = jnp.where(recv_mask[:, None], 0, state.mail)
        # Probe mailbox: who pinged me last tick → ack them this tick.
        ack_tgt, _, ack_valid = unpack_mailbox(state.pmail, n)
        ack_valid = ack_valid & recv_mask[:, None]
        pmail = jnp.where(recv_mask[:, None], 0, state.pmail)
        # Ack mailbox: merged into the view alongside gossip deliveries.
        a_id, a_hb, a_valid = unpack_mailbox(state.amail, n)
        a_valid = a_valid & recv_mask[:, None]
        amail = jnp.where(recv_mask[:, None], 0, state.amail)
        in_id = jnp.concatenate([in_id, a_id], axis=1)
        in_hb = jnp.concatenate([in_hb, a_hb], axis=1)
        in_valid = jnp.concatenate([in_valid, a_valid], axis=1)

        recv_tick = jnp.where(recv_mask, state.pending_recv, 0)
        pending_recv = jnp.where(recv_mask, 0, state.pending_recv)

        in_group = state.in_group | (state.joinrep_infl & recv_mask)
        joinrep_infl = state.joinrep_infl & ~recv_mask

        # JOINREQs reaching the introducer: guaranteed gossip targets this
        # tick + a JOINREP each (MP1Node.cpp:240-250).
        seeds = state.joinreq_infl & recv_mask[intro]
        joinreq_infl = state.joinreq_infl & ~recv_mask[intro]
        rep_ok = seeds & ctrl_kept[1]
        joinrep_infl = joinrep_infl | rep_ok
        n_seeds = seeds.sum(dtype=I32)
        sent_rep = jnp.where(idx == intro,
                             jnp.where(recv_mask[intro], rep_ok.sum(dtype=I32), 0), 0)
        pending_recv = pending_recv + rep_ok.astype(I32)

        # ---- nodeStart (Application.cpp:143-148) ----
        start_now = t == start_ticks
        started = state.started | start_now
        boot = start_now[intro]
        in_group = in_group.at[intro].set(in_group[intro] | boot)
        boot_row = (idx == intro) & boot

        joiner_req = start_now & (idx != intro) & ctrl_kept[0]
        joinreq_infl = joinreq_infl | joiner_req
        mail = scatter_mailbox(
            mail, jnp.full((n,), intro, I32), idx, jnp.zeros((n,), I32),
            joiner_req, n, salt=t)
        pending_recv = pending_recv.at[intro].add(joiner_req.sum(dtype=I32))
        sent_req = joiner_req.astype(I32)

        # ---- merge: mailbox + self refresh into the bounded view ----
        act = started & (t > start_ticks) & ~state.failed & in_group
        own_hb = state.self_hb + 1  # odd intermediate (MP1Node.cpp:412-415)
        self_hb = jnp.where(act, state.self_hb + 2, state.self_hb)
        self_on = act | boot_row
        self_ent_hb = jnp.where(boot_row, 0, own_hb)

        merged = merge_views(
            state.slot_id, state.slot_hb, state.slot_ts,
            in_id, in_hb, in_valid,
            idx, self_ent_hb, self_on, t,
            apply_row=recv_mask | boot_row)
        slot_id, slot_hb, slot_ts = merged.slot_id, merged.slot_hb, merged.slot_ts
        join_ids = jnp.where(merged.join_mask, slot_id, EMPTY)
        # The introducer's boot self-insert is silent in the reference
        # (updateMyPos, MP1Node.cpp:308-322) and in the emul/dense backends;
        # suppress it so dbg.log inventories match.  Joiner self-joins are
        # unaffected: they coincide with the gossiped copy's arrival tick.
        join_ids = jnp.where(boot_row[:, None] & (join_ids == idx[:, None]),
                             EMPTY, join_ids)

        # ---- TFAIL / TREMOVE sweep (MP1Node.cpp:429-446) ----
        present = slot_id != EMPTY
        difft = t - slot_ts
        stale = present & (difft >= cfg.tfail) & act[:, None]
        numfailed = stale.sum(1, dtype=I32)
        removes = stale & (difft >= cfg.tremove)
        rm_ids = jnp.where(removes, slot_id, EMPTY)
        slot_id = jnp.where(removes, EMPTY, slot_id)
        present = present & ~removes

        # ---- gossip (MP1Node.cpp:449-495) ----
        size = present.sum(1, dtype=I32)
        numpotential = size - 1 - numfailed  # post-removal size, pre-removal
        #                                      stale count (MP1Node.cpp:463)
        fresh = present & (difft < cfg.tfail)
        is_self_slot = slot_id == idx[:, None]
        eligible = fresh & ~is_self_slot & act[:, None]
        # The introducer's random targets exclude this tick's seeded joiners.
        in_seed = seeds[jnp.clip(slot_id, 0)] & present
        eligible = eligible.at[intro].set(eligible[intro] & ~in_seed[intro])
        seed_burst_on = act[intro]
        n_seeds_row = jnp.where((idx == intro) & seed_burst_on, n_seeds, 0)
        k_extra = jnp.clip(jnp.minimum(cfg.fanout, numpotential) - n_seeds_row, 0)
        tgt_slot, tgt_valid = sample_k_indices(k_targets, eligible, k_extra, k_max)
        tgt = jnp.take_along_axis(slot_id, tgt_slot, axis=1)          # [N, K]

        # Entry selection: all fresh entries when G >= M (the reference's
        # full-list send), else self + a uniform (G-1)-subset of the rest.
        if g >= m:
            e_idx = jnp.broadcast_to(jnp.arange(m, dtype=I32), (n, m))
            e_valid = fresh
        else:
            scores = jnp.where(is_self_slot, -1.0,
                               jax.random.uniform(k_entries, (n, m)))
            scores = jnp.where(fresh, scores, 2.0)
            _, e_idx = jax.lax.top_k(-scores, g)
            e_valid = jnp.take_along_axis(fresh, e_idx, axis=1)
        e_ids = jnp.take_along_axis(slot_id, e_idx, axis=1)           # [N, G']
        e_hbs = jnp.take_along_axis(slot_hb, e_idx, axis=1)
        g_eff = e_ids.shape[1]

        msg_valid = tgt_valid[:, :, None] & e_valid[:, None, :]       # [N,K,G']
        if cfg.drop_prob > 0.0:
            k_drop_f, k_drop_s = jax.random.split(k_drop)
            dropped = jax.random.bernoulli(k_drop_f, cfg.drop_prob,
                                           (n, k_max, g_eff))
            msg_valid = msg_valid & ~(dropped & drop_active)
        else:
            k_drop_s = k_drop
        tgt_b = jnp.broadcast_to(tgt[:, :, None], (n, k_max, g_eff))
        mail = scatter_mailbox(
            mail, tgt_b, jnp.broadcast_to(e_ids[:, None, :], (n, k_max, g_eff)),
            jnp.broadcast_to(e_hbs[:, None, :], (n, k_max, g_eff)),
            msg_valid, n, salt=t)
        sent_tick = msg_valid.sum((1, 2), dtype=I32) + sent_req + sent_rep
        recv_add = jnp.zeros((n + 1,), I32).at[
            jnp.where(tgt_valid, tgt, n).reshape(-1)
        ].add(msg_valid.sum(2, dtype=I32).reshape(-1), mode="drop")[:n]

        # Introducer burst to this tick's joiners: its full fresh view
        # (sendMemberList to each newNode, MP1Node.cpp:240-242,454).
        _, seed_idx = jax.lax.top_k(seeds.astype(I32), min(cfg.seed_cap, n))
        seed_valid = seeds[seed_idx] & seed_burst_on
        burst_valid = seed_valid[:, None] & fresh[intro][None, :]     # [S, M]
        if cfg.drop_prob > 0.0:
            dropped = jax.random.bernoulli(k_drop_s, cfg.drop_prob,
                                           (seed_idx.shape[0], m))
            burst_valid = burst_valid & ~(dropped & drop_active)
        mail = scatter_mailbox(
            mail, jnp.broadcast_to(seed_idx[:, None], burst_valid.shape),
            jnp.broadcast_to(slot_id[intro][None, :], burst_valid.shape),
            jnp.broadcast_to(slot_hb[intro][None, :], burst_valid.shape),
            burst_valid, n, salt=t)
        sent_tick = sent_tick.at[intro].add(burst_valid.sum(dtype=I32))
        recv_add = recv_add.at[seed_idx].add(
            burst_valid.sum(1, dtype=I32) * seed_valid.astype(I32))

        # ---- SWIM direct probing (see module docstring) ----
        # Round-robin slot sweep (SWIM's randomized round-robin member
        # selection): tick t probes the P slots starting at (t*P) mod M, so
        # every slot is pinged at least every ceil(M/P) ticks — a
        # *deterministic* staleness bound, unlike uniform sampling whose
        # geometric gap tail would trickle false removals forever.
        if cfg.probes > 0:
            ptr = jax.lax.rem(t * cfg.probes, m)
            off = jax.lax.rem(jnp.arange(m, dtype=I32) - ptr + 2 * m, m)
            sweep = off < cfg.probes                                  # [M]
            p_valid = sweep[None, :] & present & ~is_self_slot & act[:, None]
            p_tgt = jnp.where(p_valid, slot_id, EMPTY)                # [N, M]
            ack_ok = ack_valid & act[:, None]                         # [N, Qp]
            if cfg.drop_prob > 0.0:
                kd1, kd2 = jax.random.split(k_drop_p)
                p_valid = p_valid & ~(jax.random.bernoulli(
                    kd1, cfg.drop_prob, p_valid.shape) & drop_active)
                ack_ok = ack_ok & ~(jax.random.bernoulli(
                    kd2, cfg.drop_prob, ack_ok.shape) & drop_active)
            own_id_p = jnp.broadcast_to(idx[:, None], p_tgt.shape)
            own_hb_p = jnp.broadcast_to(own_hb[:, None], p_tgt.shape)
            # Probe: prober id into the target's probe mailbox, prober's own
            # entry piggybacked into the gossip mailbox (one wire message).
            # When the probe/ack slot maps are lossy (qp/qa < N), each
            # message is transmitted twice with independent hashes, squaring
            # the per-cycle collision loss (see tpu_hash.make_step) — the
            # duplicates merge idempotently at the receiver.
            p_copies = 1 if cfg.qp >= n else 2
            for c in range(p_copies):
                pmail = scatter_mailbox(pmail, p_tgt, own_id_p,
                                        jnp.zeros_like(p_tgt), p_valid, n,
                                        salt=t + c * 0x2545F49)
            mail = scatter_mailbox(mail, p_tgt, own_id_p, own_hb_p,
                                   p_valid, n, salt=t)
            # Ack: my current (id, heartbeat) back to each prober.
            a_copies = 1 if cfg.qa >= n else 2
            for c in range(a_copies):
                amail = scatter_mailbox(
                    amail, ack_tgt,
                    jnp.broadcast_to(idx[:, None], ack_tgt.shape),
                    jnp.broadcast_to(own_hb[:, None], ack_tgt.shape),
                    ack_ok, n, salt=t + c * 0x2545F49)
            sent_tick = (sent_tick + p_valid.sum(1, dtype=I32) * p_copies
                         + ack_ok.sum(1, dtype=I32) * a_copies)
            recv_add = recv_add + jnp.zeros((n + 1,), I32).at[
                jnp.where(p_valid, p_tgt, n).reshape(-1)
            ].add(p_copies, mode="drop")[:n]
            recv_add = recv_add + jnp.zeros((n + 1,), I32).at[
                jnp.where(ack_ok, ack_tgt, n).reshape(-1)
            ].add(a_copies, mode="drop")[:n]

        pending_recv = pending_recv + recv_add

        # ---- failure injection, end of tick (Application::fail) ----
        failed = state.failed | (fail_mask & (t == fail_time))

        if cfg.collect_events:
            agg = state.agg
            out = SparseTickEvents(join_ids, rm_ids, sent_tick, recv_tick)
        else:
            # Scale path: fold events into O(N) on-device aggregates; emit
            # only per-tick scalars so stacked outputs stay O(T).
            agg = update_agg(
                state.agg, t=t, join_ids=join_ids, rm_ids=rm_ids,
                view_ids=slot_id, view_present=present,
                fail_mask=fail_mask, fail_time=fail_time,
                sent_tick=sent_tick, recv_tick=recv_tick)
            out = SparseTickEvents((join_ids != EMPTY).sum(dtype=I32),
                                   (rm_ids != EMPTY).sum(dtype=I32),
                                   sent_tick.sum(dtype=I32),
                                   recv_tick.sum(dtype=I32))
        new_state = SparseState(slot_id, slot_hb, slot_ts, started, in_group,
                                failed, self_hb, mail, pmail, amail,
                                joinreq_infl, joinrep_infl, pending_recv, agg)
        return new_state, out

    return step


def make_config(params: Params, collect_events: bool = True) -> SparseConfig:
    n = params.EN_GPSZ
    m = params.VIEW_SIZE if params.VIEW_SIZE > 0 else n
    g = params.GOSSIP_LEN if params.GOSSIP_LEN > 0 else m
    q = (params.MAILBOX_SIZE if params.MAILBOX_SIZE > 0
         else auto_mailbox_size(n, m, g, params.FANOUT))
    # Probe in-degree is ~2*PROBES transmissions in expectation (redundant
    # double-hash sends; each of the ~M holders of my entry pings each view
    # slot at rate PROBES/M).  Ack in-degree is up to ~4*PROBES transmissions
    # (each delivered probe copy is acked, each ack double-hashed), but
    # duplicates of one acker share the same two slots, so distinct occupied
    # slots stay ~2*PROBES.  Lossless (== N) while affordable, else 32x
    # PROBES headroom: per-copy collision loss ~3-6%, squared by the
    # redundancy, TREMOVE >= 4 cycles (Params.validate) — consecutive-miss
    # removals are ~1e-12 per entry.
    qp = qa = n if n <= 1024 else max(128, 32 * params.PROBES)
    # Batch join delivers every JOINREQ to the introducer in one tick, so
    # the guaranteed burst must cover all N-1 joiners; the staggered
    # schedule produces at most ceil(1/STEP_RATE) per tick.
    seed_cap = n if params.JOIN_MODE == "batch" else SEED_CAP
    return SparseConfig(
        n=n, m=m, q=q, g=min(g, m), tfail=params.TFAIL,
        tremove=params.TREMOVE, fanout=params.FANOUT,
        drop_prob=params.effective_drop_prob(),
        probes=params.PROBES, qp=qp, qa=qa, seed_cap=seed_cap,
        collect_events=collect_events)


_RUNNER_CACHE: dict = {}


def _get_runner(cfg: SparseConfig, warm: bool):
    """One compiled whole-run scan per (config, bootstrap mode).

    All per-run values — seeds, schedules, failure plans — are *arguments*
    of the jitted function, never closed-over constants, so a single
    compilation serves every seed and scenario of the same shape.  (A fresh
    ``@jax.jit`` closure per call would re-trace and re-compile the full
    scan every run — tens of seconds at scale.)
    """
    cache_key = (cfg, warm)
    if cache_key not in _RUNNER_CACHE:
        step = make_step(cfg)

        def run(keys, ticks, start_ticks, fail_mask, fail_time,
                drop_lo, drop_hi, warm_key):
            state0 = (init_state_warm(cfg, warm_key) if warm
                      else init_state(cfg))

            def body(state, inp):
                t, k = inp
                return step(state, (t, k, start_ticks, fail_mask,
                                    fail_time, drop_lo, drop_hi))

            return jax.lax.scan(body, state0, (ticks, keys))

        _RUNNER_CACHE[cache_key] = jax.jit(run)
    return _RUNNER_CACHE[cache_key]


def _get_segment_runner(cfg: SparseConfig):
    """Chunked-scan twin of :func:`_get_runner`: the carry is an argument,
    so the run can stop at any segment boundary and continue bit-exactly
    (runtime/checkpoint.py)."""
    cache_key = (cfg, "segment")
    if cache_key not in _RUNNER_CACHE:
        step = make_step(cfg)

        def run_seg(state, ticks, keys, start_ticks, fail_mask, fail_time,
                    drop_lo, drop_hi):
            def body(state, inp):
                t, k = inp
                return step(state, (t, k, start_ticks, fail_mask,
                                    fail_time, drop_lo, drop_hi))

            return jax.lax.scan(body, state, (ticks, keys))

        _RUNNER_CACHE[cache_key] = jax.jit(run_seg)
    return _RUNNER_CACHE[cache_key]


def run_scan(params: Params, plan: FailurePlan, seed: int,
             collect_events: bool = True, total_time: Optional[int] = None):
    """Run the full simulation; returns (final_state, events)."""
    cfg = make_config(params, collect_events)
    n = cfg.n
    total = total_time if total_time is not None else params.TOTAL_TIME
    # Re-validate against the *effective* run length: total_time may exceed
    # params.TOTAL_TIME (bench/sweep drivers), which would otherwise bypass
    # the uint32 (heartbeat, id) packing guard.
    params.validate_sparse_packing(total)
    warm = params.JOIN_MODE == "warm"

    if params.CHECKPOINT_EVERY > 0:
        from distributed_membership_tpu.runtime.checkpoint import (
            chunked_run, compact_sparse)
        warm_key = make_run_key(params, seed ^ 0x5EED)
        return chunked_run(
            params, plan, seed, total,
            init_carry=lambda: (init_state_warm(cfg, warm_key) if warm
                                else init_state(cfg)),
            segment_fn=_get_segment_runner(cfg),
            collect_events=collect_events,
            compact_fn=compact_sparse if collect_events else None,
            event_type=None if collect_events else SparseTickEvents)

    (ticks, keys, start_ticks, fail_mask, fail_time,
     drop_lo, drop_hi) = plan_tensors(params, plan, seed, total)

    run = _get_runner(cfg, warm)
    final_state, events = run(
        keys, ticks, start_ticks, fail_mask, fail_time, drop_lo, drop_hi,
        make_run_key(params, seed ^ 0x5EED))
    return final_state, jax.tree.map(np.asarray, events)


def events_to_log(params: Params, plan: FailurePlan, events: SparseTickEvents,
                  log: EventLog) -> None:
    """Reconstruct dbg.log from stacked sparse event tensors — or from
    their chunked-run host compaction — (same line inventory as the dense
    backend's events_to_log, backends/tpu.py)."""
    from distributed_membership_tpu.runtime.checkpoint import (
        CompactEvents, compact_sparse)

    if not isinstance(events, CompactEvents):
        events = compact_sparse(events)
    n = params.EN_GPSZ
    total = events.total
    starts = [params.start_tick(i) for i in range(n)]
    for i in range(n):
        log.log(i + 1, 0, "APP")

    join_by_tick: dict = {}
    for t, i, j in events.joins:
        join_by_tick.setdefault(int(t), []).append((int(i), int(j)))
    remove_by_tick: dict = {}
    for t, i, j in events.removes:
        remove_by_tick.setdefault(int(t), []).append((int(i), int(j)))

    intro_failed = (plan.fail_time is not None
                    and INTRODUCER_INDEX in plan.failed_indices)
    warm = params.JOIN_MODE == "warm"
    for t in range(total):
        if not warm:
            for i in range(n - 1, -1, -1):
                if starts[i] == t:
                    if i == INTRODUCER_INDEX:
                        log.log(i + 1, t, "Starting up group...")
                    else:
                        log.log(i + 1, t, "Trying to join...")
        for i, j in join_by_tick.get(t, ()):
            log.node_add(i + 1, j + 1, t)
        for i, j in remove_by_tick.get(t, ()):
            log.node_remove(i + 1, j + 1, t)
        if (not warm and t % 500 == 0 and t > starts[INTRODUCER_INDEX]
                and not (intro_failed and t > plan.fail_time)):
            log.log(INTRODUCER_INDEX + 1, t, f"@@time={t}")
        if plan.fail_time == t:
            log_failures(plan, log, t)


def finish_run(params: Params, plan: FailurePlan, log: EventLog,
               run_scan_fn, t0: float, seed: int) -> RunResult:
    """Shared tail of the bounded-view entrypoints: run the scan in the
    resolved event mode, then either reconstruct dbg.log (full) or compute
    the detection summary from the on-device aggregates (agg — the only
    mode that works at 1M nodes, VERDICT r1 item 3)."""
    aggregate = params.resolved_event_mode() == "agg"
    kw = {}
    recorder = None
    if params.TELEMETRY in ("scalars", "hist"):
        # Flight recorder (observability/timeline.py): only the ring
        # backends get here (config.validate gates the knob), and their
        # run_scan accepts the recorder.  Series land in
        # extra['timeline']; TELEMETRY_DIR additionally streams
        # timeline.jsonl per segment boundary.  The hist tier rides the
        # same recorder — its records just gain the [K][B] bucket lists.
        from distributed_membership_tpu.observability.timeline import (
            TimelineRecorder)
        recorder = TimelineRecorder(params.TELEMETRY_DIR or None)
        kw["telemetry"] = recorder
    final_state, events = run_scan_fn(params, plan, seed,
                                      collect_events=not aggregate, **kw)
    failed = plan.failed_indices if plan.fail_time is not None else []
    if aggregate:
        if plan.fail_time is not None:
            log_failures(plan, log, plan.fail_time)
        fail_mask = np.zeros((params.EN_GPSZ,), bool)
        fail_mask[failed] = True
        summary = detection_summary(final_state.agg, fail_mask,
                                    plan.fail_time)
        if params.BACKEND.startswith("tpu_hash"):
            # Mark when per-node probe recv/ack-send counters are
            # attributed to the prober's row rather than the true nodes
            # (tpu_hash.probe_attribution_exact) so no summary needs a
            # PERF.md footnote to be read correctly.
            from distributed_membership_tpu.backends.tpu_hash import (
                probe_attribution_exact)
            summary["approx_probe_attribution"] = (
                not probe_attribution_exact(params))
        # Per-node totals only (the [N, T] matrix is the thing that cannot
        # exist at scale); write_msgcount is skipped by the driver.
        sent = np.asarray(final_state.agg.sent_total)[:, None]
        recv = np.asarray(final_state.agg.recv_total)[:, None]
        extra = {"final_state": final_state, "aggregate": True,
                 "detection_summary": summary}
    else:
        events_to_log(params, plan, events, log)
        sent = np.asarray(events.sent).T
        recv = np.asarray(events.recv).T
        extra = {"final_state": final_state}
    scn_prog = getattr(plan, "scenario", None)
    if scn_prog is not None:
        # Scenario oracle (scenario/oracle.py): grade the run against
        # its declared chaos schedule from whatever this run recorded —
        # telemetry series > dbg.log events — plus the final carry.
        from distributed_membership_tpu.scenario.oracle import (
            scenario_report)
        report = scenario_report(
            scn_prog, params, final_state=final_state,
            summary=extra.get("detection_summary"),
            timeline=(recorder.series() if recorder is not None
                      else None),
            dbg_text=(log.dbg_text() if not aggregate else None))
        extra["scenario_report"] = report
        if params.TELEMETRY_DIR:
            # Next to timeline.jsonl/summary.json so run_report.py can
            # render scenario markers and cross-check oracle totals
            # against the telemetry counters.
            import json as _json
            with open(os.path.join(params.TELEMETRY_DIR,
                                   "scenario.json"), "w") as fh:
                _json.dump(report, fh, indent=1)
    if recorder is not None:
        extra["timeline"] = recorder.series()
        extra["timeline_path"] = recorder.path
        if params.TELEMETRY_DIR and aggregate:
            # Make the flight-recorder dir self-contained for
            # scripts/run_report.py: the detection verdicts next to the
            # per-tick series they must reconcile with.
            import json as _json
            with open(os.path.join(params.TELEMETRY_DIR,
                                   "summary.json"), "w") as fh:
                _json.dump(extra["detection_summary"], fh, indent=1)
    return RunResult(
        params=params, log=log, sent=sent, recv=recv,
        failed_indices=failed, fail_time=plan.fail_time,
        wall_seconds=_time.time() - t0, extra=extra)


@register("tpu_sparse")
def run_tpu_sparse(params: Params, log: Optional[EventLog] = None,
                   seed: Optional[int] = None) -> RunResult:
    t0 = _time.time()
    seed = params.SEED if seed is None else seed
    log = log if log is not None else EventLog()
    plan = resolve_plan(params, _pyrandom.Random(f"app:{seed}"))

    return finish_run(params, plan, log, run_scan, t0, seed)
