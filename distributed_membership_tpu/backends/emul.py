"""`emul` backend: faithful queue-level host simulator.

This is the executable specification the TPU backends are validated against.
It reproduces the reference's semantics message-for-message:

  * global bounded in-memory buffer with swap-remove receive scans
    (EmulNet.cpp:87-177) — here keyed by integer id (fixing defect D5, the
    strcmp aliasing on binary addresses at EmulNet.cpp:154);
  * the two-pass synchronous tick: all receives (ascending node order), then
    all protocol steps (descending), exactly as Application::mp1Run
    (Application.cpp:121-164) — giving a 1-tick minimum message latency;
  * the staggered join schedule, JOINREQ/JOINREP handshake through the
    introducer, full-member-list gossip to FANOUT random targets per tick,
    and the TFAIL/TREMOVE sweep (MP1Node.cpp:182-495).

Protocol-visible quirks of the reference are replicated deliberately
(SURVEY.md §7 "faithful quirks policy"):

  * the double heartbeat increment: +2 per tick, own list entry gets the
    odd intermediate value (MP1Node.cpp:412-414);
  * gossip skips entries whose timestamp is stale by >= TFAIL
    (MP1Node.cpp:376) — this is what prevents failed-node resurrection;
  * the fanout bound ``numpotential = len(list) - 1 - numfailed`` computed
    with the post-removal length but the pre-removal stale count
    (MP1Node.cpp:463);
  * new joiners (JOINREQs processed this tick) are guaranteed gossip targets
    (MP1Node.cpp:240-242,454).

Reference *defects* are fixed, not replicated: D3 (the ``&&`` in
updateMyPos' self-insert test, MP1Node.cpp:316) becomes a correct
"insert-if-absent"; D4 (per-message leak) and D1/D2 (log truncation /
shutdown UB) have no analog here.

Messages are Python tuples, never serialized: ('LIST', id, port, hb) etc.
Wire sizes (19 B per LIST/JOINREQ, 4 B JOINREP; MP1Node.cpp:143,364,247)
are retained only for the buffer/size checks and counters.
"""

from __future__ import annotations

import bisect
import random
import time as _time
from collections import deque
from typing import List, Optional, Tuple

import numpy as np

from distributed_membership_tpu.addressing import INTRODUCER_ID, index_to_id
from distributed_membership_tpu.backends import RunResult, register
from distributed_membership_tpu.config import Params
from distributed_membership_tpu.eventlog import EventLog
from distributed_membership_tpu.runtime.failures import (
    FailurePlan, log_failures, resolve_plan)

# Wire sizes (bytes), for buffer accounting only.
LIST_MSG_SIZE = 19      # hdr 4 + addr 6 + pad 1 + heartbeat 8 (MP1Node.cpp:364)
JOINREQ_MSG_SIZE = 19   # same layout (MP1Node.cpp:143)
JOINREP_MSG_SIZE = 4    # bare header (MP1Node.cpp:246-250)
EN_MSG_HDR = 16         # sizeof(en_msg): int + 2 x 6-byte Address (EmulNet.h:23-30)


class EmulNetwork:
    """In-memory packet network (reference EmulNet, EmulNet.{h,cpp})."""

    def __init__(self, params: Params, rng: random.Random, total_time: int):
        self.params = params
        self.rng = rng
        # buffer of (src_id, dst_id, payload_tuple, size)
        self.buff: List[Tuple[int, int, tuple, int]] = []
        n = params.EN_GPSZ
        self.sent = np.zeros((n + 1, total_time), dtype=np.int64)
        self.recv = np.zeros((n + 1, total_time), dtype=np.int64)
        # General-path scenario (scenario/compile.ScenarioHost): owns
        # the drop windows, partitions, and link flakes when set; the
        # legacy dropmsg toggle never fires then (the plan carries no
        # drop window).
        self.scenario = None

    def send(self, src_id: int, dst_id: int, payload: tuple, size: int, t: int) -> int:
        """ENsend (EmulNet.cpp:87-118): drop on full buffer, oversize, or
        Bernoulli when the drop window is open; count only accepted sends.
        With a general scenario attached, partition cuts drop the message
        deterministically and the Bernoulli threshold is the per-link
        effective percentage (windows + flakes)."""
        p = self.params
        if (len(self.buff) >= p.EN_BUFFSIZE
                or size + EN_MSG_HDR >= p.MAX_MSG_SIZE):
            return 0
        if self.scenario is not None:
            si, di = src_id - 1, dst_id - 1        # EmulNet ids are idx+1
            if self.scenario.blocked(t, si, di):
                return 0
            pct = self.scenario.drop_pct(t, si, di)
            if pct and self.rng.randrange(100) < pct:
                return 0
        elif p.dropmsg and self.rng.randrange(100) < int(p.MSG_DROP_PROB * 100):
            return 0
        self.buff.append((src_id, dst_id, payload, size))
        self.sent[src_id, t] += 1
        return size

    def recv_all(self, dst_id: int, t: int) -> List[tuple]:
        """ENrecv (EmulNet.cpp:144-177): scan the whole buffer top-down,
        swap-remove matches; delivery order is therefore newest-first."""
        out: List[tuple] = []
        buff = self.buff
        i = len(buff) - 1
        while i >= 0:
            if buff[i][1] == dst_id:
                out.append(buff[i][2])
                last = buff.pop()
                if i < len(buff):
                    buff[i] = last
                self.recv[dst_id, t] += 1
            i -= 1
        return out


def _entry_key(e: List[int]) -> Tuple[int, int]:
    # Reference ordering: by (id, port) (MemberCompareLessThan, MP1Node.cpp:13-18).
    return (e[0], e[1])


class EmulNode:
    """One protocol participant (reference MP1Node + Member state)."""

    __slots__ = ("idx", "id", "port", "params", "net", "log", "rng",
                 "failed", "inited", "in_group", "hb", "members", "queue")

    def __init__(self, idx: int, params: Params, net: EmulNetwork,
                 log: EventLog, rng: random.Random):
        self.idx = idx
        self.id = index_to_id(idx)
        self.port = 0  # ENinit forces port 0 (EmulNet.cpp:75)
        self.params = params
        self.net = net
        self.log = log
        self.rng = rng
        self.failed = False
        self.inited = False
        self.in_group = False
        self.hb = 0
        # member list entries [id, port, heartbeat, timestamp], sorted by (id, port)
        self.members: List[List[int]] = []
        self.queue: deque = deque()

    # -- lifecycle (MP1Node::nodeStart, MP1Node.cpp:73-119) ---------------
    def node_start(self, t: int) -> None:
        self.failed = False
        self.inited = True
        self.in_group = False
        self.hb = 0
        self.members = []
        if self.id == INTRODUCER_ID:
            self.log.log(self.id, t, "Starting up group...")
            self._update_my_pos(t)
            self.in_group = True
        else:
            self.log.log(self.id, t, "Trying to join...")
            self.net.send(self.id, INTRODUCER_ID,
                          ("JOINREQ", self.id, self.port, self.hb),
                          JOINREQ_MSG_SIZE, t)

    # -- pass 1 (MP1Node::recvLoop, MP1Node.cpp:47-54) --------------------
    def recv_loop(self, t: int) -> None:
        if self.failed:
            return
        for payload in self.net.recv_all(self.id, t):
            self.queue.append(payload)

    # -- pass 2 (MP1Node::nodeLoop, MP1Node.cpp:182-201) ------------------
    def node_loop(self, t: int) -> None:
        if self.failed:
            return
        new_nodes: List[List[int]] = []
        while self.queue:
            self._dispatch(self.queue.popleft(), new_nodes, t)
        if not self.in_group:
            return
        self._node_loop_ops(new_nodes, t)

    # -- message handlers (MP1Node::recvCallBack, MP1Node.cpp:329-353) ----
    def _dispatch(self, payload: tuple, new_nodes: List[List[int]], t: int) -> None:
        kind = payload[0]
        if kind == "JOINREQ":
            _, src_id, src_port, src_hb = payload
            if self._update_list(src_id, src_port, src_hb, t):
                new_nodes.append([src_id, src_port, src_hb, t])
            self.net.send(self.id, src_id, ("JOINREP",), JOINREP_MSG_SIZE, t)
        elif kind == "JOINREP":
            self.in_group = True
        elif kind == "LIST":
            _, src_id, src_port, src_hb = payload
            self._update_list(src_id, src_port, src_hb, t)

    def _update_list(self, eid: int, eport: int, ehb: int, t: int) -> bool:
        """Merge one (id, heartbeat) into the member list
        (MP1Node::updatelistCallBack, MP1Node.cpp:259-301).

        Existing entry: update heartbeat *and* timestamp only if the incoming
        heartbeat is strictly greater.  New entry: insert sorted + log the
        join.  This merge is commutative in the incoming set — the fact the
        whole TPU design rests on.
        """
        members = self.members
        pos = bisect.bisect_left(members, (eid, eport), key=_entry_key)
        if pos < len(members) and members[pos][0] == eid and members[pos][1] == eport:
            if members[pos][2] < ehb:
                members[pos][2] = ehb
                members[pos][3] = t
            return False
        members.insert(pos, [eid, eport, ehb, t])
        self.log.node_add(self.id, eid, t)
        return True

    def _update_my_pos(self, t: int) -> int:
        """Locate (insert if absent) this node's own entry
        (MP1Node::updateMyPos, MP1Node.cpp:308-322, with defect D3 — the
        ``&&`` self-insert condition — fixed to a plain membership test)."""
        members = self.members
        pos = bisect.bisect_left(members, (self.id, self.port), key=_entry_key)
        if pos == len(members) or members[pos][0] != self.id or members[pos][1] != self.port:
            members.insert(pos, [self.id, self.port, self.hb, t])
        return pos

    # -- the per-tick protocol kernel (MP1Node::nodeLoopOps, MP1Node.cpp:404-495)
    def _node_loop_ops(self, new_nodes: List[List[int]], t: int) -> None:
        p = self.params
        members = self.members

        mypos = self._update_my_pos(t)
        # Double heartbeat increment: own entry receives the odd intermediate
        # value (MP1Node.cpp:412-414) — protocol-visible, replicated.
        self.hb += 1
        members[mypos][2] = self.hb
        self.hb += 1
        members[mypos][3] = t

        # TFAIL / TREMOVE sweep (MP1Node.cpp:429-444).  The reference walks
        # indices downward with swap-remove; every pre-sweep entry is
        # examined exactly once, so a single filtering pass is equivalent.
        numfailed = 0
        kept: List[List[int]] = []
        for e in members:
            difft = t - e[3]
            if difft >= p.TFAIL:
                numfailed += 1
                if difft >= p.TREMOVE:
                    self.log.node_remove(self.id, e[0], t)
                    continue
            kept.append(e)
        # (filtering a sorted list preserves order — no re-sort needed, unlike
        # the reference whose swap-remove shuffles and re-sorts at :446)
        self.members = members = kept

        # Gossip target selection (MP1Node.cpp:449-489): start from this
        # tick's new joiners, then rejection-sample distinct live non-self
        # entries until FANOUT targets or the (quirky) potential bound.
        gossip: List[List[int]] = list(new_nodes)
        n = len(gossip)
        numpotential = len(members) - 1 - numfailed
        while n < p.FANOUT and n < numpotential:
            e = members[self.rng.randrange(len(members))]
            if e[0] == self.id and e[1] == self.port:
                continue
            if t - e[3] >= p.TFAIL:
                continue  # never gossip *to* a suspected-failed node
            if any(g[0] == e[0] and g[1] == e[1] for g in gossip):
                continue
            gossip.append(e)
            n += 1

        for target in gossip:
            self._send_member_list(target[0], t)

    def _send_member_list(self, to_id: int, t: int) -> None:
        """One LIST message per live entry (MP1Node::sendMemberList,
        MP1Node.cpp:360-395); entries stale by >= TFAIL are withheld
        (MP1Node.cpp:376)."""
        for e in self.members:
            if t - e[3] >= self.params.TFAIL:
                continue
            self.net.send(self.id, to_id, ("LIST", e[0], e[1], e[2]),
                          LIST_MSG_SIZE, t)


@register("emul")
def run_emul(params: Params, log: Optional[EventLog] = None,
             seed: Optional[int] = None) -> RunResult:
    """Full simulation with the faithful host backend.

    Replicates Application::run / mp1Run (Application.cpp:90-164): for each of
    TOTAL_TIME ticks, pass 1 receives for every eligible node in ascending
    order, pass 2 starts/steps nodes in descending order, then failures are
    injected.  Node i becomes eligible after its staggered start tick
    (``t > int(STEP_RATE*i)``, Application.cpp:130,143,153).
    """
    t0 = _time.time()
    seed = params.SEED if seed is None else seed
    log = log if log is not None else EventLog()

    # Deterministic per-purpose streams (random.Random(str) hashes the string
    # with a stable algorithm, unlike Python's per-process salted str hash).
    rng_app = random.Random(f"app:{seed}")
    rng_net = random.Random(f"net:{seed}")
    rng_gossip = random.Random(f"gossip:{seed}")

    n = params.EN_GPSZ
    total = params.TOTAL_TIME
    net = EmulNetwork(params, rng_net, total)
    nodes = [EmulNode(i, params, net, log, rng_gossip) for i in range(n)]
    for node in nodes:
        log.log(node.id, 0, "APP")  # constructor APP lines (Application.cpp:67)

    plan = resolve_plan(params, rng_app)
    scn_prog = getattr(plan, "scenario", None)
    host = None
    if scn_prog is not None:
        host = scn_prog.host()
        net.scenario = host
    starts = [params.start_tick(i) for i in range(n)]

    for t in range(total):
        params.globaltime = t
        for i in range(n):                      # pass 1: receive
            # delay_window: a covered node skips its receive pass — its
            # messages stay queued in net.buff and drain the first tick
            # after the window (EN_BUFFSIZE overflow during the hold is
            # honest bounded-queue behavior).  The node still acts in
            # pass 2: asymmetric gray failure, not isolation.
            if (t > starts[i] and not nodes[i].failed
                    and (host is None or not host.delayed(t, i))):
                nodes[i].recv_loop(t)
        for i in range(n - 1, -1, -1):          # pass 2: start / act
            if t == starts[i]:
                nodes[i].node_start(t)
            elif t > starts[i] and not nodes[i].failed:
                nodes[i].node_loop(t)
                if i == 0 and t % 500 == 0:
                    log.log(nodes[i].id, t, f"@@time={t}")  # Application.cpp:156-160
        if host is not None:
            _inject_scenario(host, nodes, log, t)
        else:
            _inject(plan, nodes, params, log, t)

    extra = {"final_lists": {node.id: [list(e) for e in node.members]
                             for node in nodes}}
    if scn_prog is not None:
        from distributed_membership_tpu.scenario.oracle import (
            scenario_report)
        extra["scenario_report"] = scenario_report(
            scn_prog, params, dbg_text=log.dbg_text(),
            final_live=sum(1 for nd in nodes
                           if nd.inited and nd.in_group and not nd.failed),
            final_failed=sum(1 for nd in nodes if nd.failed),
            final_failed_indices=[nd.idx for nd in nodes if nd.failed])
    return RunResult(
        params=params, log=log,
        sent=net.sent[1:, :], recv=net.recv[1:, :],
        failed_indices=plan.failed_indices if plan.fail_time is not None else [],
        fail_time=plan.fail_time,
        wall_seconds=_time.time() - t0,
        extra=extra,
    )


def _inject(plan: FailurePlan, nodes, params: Params, log: EventLog, t: int) -> None:
    """Application::fail (Application.cpp:173-202)."""
    if plan.drop_start is not None and t == plan.drop_start:
        params.dropmsg = 1
    if plan.fail_time == t:
        log_failures(plan, log, t)
        for i in plan.failed_indices:
            nodes[i].failed = True
    if plan.drop_stop is not None and t == plan.drop_stop:
        params.dropmsg = 0


def _inject_scenario(host, nodes, log: EventLog, t: int) -> None:
    """End-of-tick scenario transitions (scenario/compile.ScenarioHost)
    — the host twin of the jitted steps' up/down block.  Crash/leave
    nodes go dark (reference-faithfully: the queue strands); restarted
    nodes come back as a fresh incarnation: empty member list with only
    their own entry, heartbeat bumped past anything the old incarnation
    gossiped, warm rejoin (in-group, no introducer round trip)."""
    from distributed_membership_tpu.addressing import index_to_id

    for i in host.down_at(t):
        if not nodes[i].failed:
            log.node_failed_multi(index_to_id(i), t)
        nodes[i].failed = True
    for i in host.up_at(t):
        node = nodes[i]
        node.failed = False
        node.inited = True
        node.in_group = True
        node.hb = max(node.hb, 2 * (t + 1))
        node.members = []
        node.queue.clear()
        node._update_my_pos(t)
