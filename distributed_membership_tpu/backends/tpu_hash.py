"""`tpu_hash` backend: hash-slotted member views — the high-throughput
scale path.

**The design insight.** The dense `tpu` backend's ``[N, N]`` state is a
member table with a *perfect* hash (column = member id) whose merge is an
elementwise max.  The sorted `tpu_sparse` backend bounds memory but pays for
exact bounded-membership semantics with batched sorts — O(S log^2 S) bitonic
passes per tick that burn HBM bandwidth (measured ~15 GB/tick at N=4096).
This backend keeps the dense backend's *shape* and bounds memory by making
the hash lossy: node ``i`` stores member ``id`` at slot
``h_i(id) = (id + i * STRIDE) mod S`` in a ``[N, S]`` table of uint32-packed
``(heartbeat, id)`` entries, and the per-receiver mailbox uses the *same*
slot map — so delivery + merge collapse into ONE elementwise ``max``:

    view' = max(view, mail)        # the whole receive path, pure VPU

Per-id semantics are the reference's exactly (max heartbeat wins; local
timestamp refreshes only on strict increase, MP1Node.cpp:278-288), because
packing puts the heartbeat in the high bits.  When ``S >= N`` the slot map
is injective and the protocol is the dense backend's (modulo a per-row
column permutation).

**Admission control — why a slot is never stolen.**  When ``S < N``, far
more ids circulate through gossip than a view can hold.  If the slot
combine were a blind heartbeat max, a failed member (frozen heartbeat)
would be silently evicted by any colliding live id long before its TREMOVE
deadline and the detector would log nothing.  So occupancy is sticky: an
occupied slot accepts only updates for its *current occupant's id*; new
ids are admitted only into empty slots; the only eviction is the TREMOVE
sweep itself (which frees the slot for churn).  Each node therefore tracks
a stable ~S-member random subset — exactly the fixed partial list the spec
permits, with clean join/remove events and full per-view detection
completeness.

Two delivery channels with different reliability by construction:
  * gossip/mailbox (``mail``): scatter-max per receiver slot; collisions
    between different ids can drop a message — best-effort discovery;
  * acks (``amail``): slot-addressed by the probed id.  Probed ids are view
    occupants and occupants have distinct slots, so this channel is
    collision-free — entry *refresh* (what false-positive avoidance
    depends on) never competes with gossip volume.

Failure detection at scale uses the same SWIM round-robin probe/ack scheme
as `tpu_sparse` (see its docstring for why bounded gossip alone cannot
work): every occupied slot is pinged once per ``ceil(S/PROBES)`` ticks, so
TFAIL/TREMOVE must be sized in units of that cycle — the SWIM protocol
period, now decoupled from N.

**Sizing under message loss.**  With drop probability p, one probe/ack
round trip fails with ~1-(1-p)^2 per cycle; a false removal needs
``TREMOVE/cycle`` *consecutive* failures, so the expected false-removal
count is ~(tracked entries) x (window ticks) x (1-(1-p)^2)^(TREMOVE/cycle).
At p=0.1 that is ~0.19^k: k >= ~12 cycles inside TREMOVE makes the tail
negligible at any N; k ~ 7 measurably false-removes at N >= 1024 — for
BOTH exchange lowerings (the reference grader disables its accuracy check
in the drop scenario; bounded views + probing can hold accuracy under
loss, but only when TREMOVE buys enough probe cycles).

Everything is [N, S]-elementwise ops, one scatter-max for sends, and one
top_k for target sampling — no sorts, no data-dependent shapes.  Per-tick
HBM traffic is ~6 passes over [N, S] u32: ~0.9 GB at N=1M, S=128.

**Exchange modes.**  The scatter-max delivery above (``EXCHANGE: scatter``)
is the reference-shaped lowering; XLA serializes large scatters on TPU, so
it is also the entire per-tick cost at scale.  ``EXCHANGE: ring`` removes
every full-width scatter — circulant-roll gossip plus a gather-pipeline
probe/ack channel (see :func:`make_step`); ``EXCHANGE: auto`` (default)
picks ring for warm-join bounded-view scale runs, scatter for the
grader-parity regime.  Measured (this repo's bench, N=65536, S=128):
ring is ~2.8x scatter on CPU and removes the scatter serialization wall
on TPU.
"""

from __future__ import annotations

import dataclasses
import random as _pyrandom
import time as _time
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from distributed_membership_tpu.addressing import INTRODUCER_INDEX
from distributed_membership_tpu.backends import RunResult, register
from distributed_membership_tpu.backends.tpu_sparse import (
    SEED_CAP, SparseTickEvents, finish_run)
from distributed_membership_tpu.config import Params
from distributed_membership_tpu.eventlog import EventLog
from distributed_membership_tpu.observability.aggregates import (
    FAST_AGG_MAX_FAILED, AggStats, init_agg, init_fast_agg, update_agg,
    update_fast_agg)
from distributed_membership_tpu.observability.timeline import (
    PHASE_ACK, PHASE_GOSSIP, PHASE_PROBE, PHASE_TELEMETRY, TickTelemetry,
    build_tick_hist)
from distributed_membership_tpu.ops.fused_gossip import (
    gossip_fused, gossip_fused_stacked, gossip_fused_supported)
from distributed_membership_tpu.ops.fused_probe import (
    probe_fused_supported, probe_window_fused)
from distributed_membership_tpu.ops.fused_receive import (
    fused_supported, receive_core, receive_fused)
from distributed_membership_tpu.ops.megakernel import (
    PACK_SAFE_TICKS as _MEGA_PACK_SAFE, mega_scan, pack_fits)
from distributed_membership_tpu.ops.rng_plan import RingRng, hash_ring_rng
from distributed_membership_tpu.ops.sampling import sample_k_indices
from distributed_membership_tpu.ops.view_merge import (
    EMPTY, STRIDE, hash_slot)
from distributed_membership_tpu.runtime.failures import (
    FailurePlan, make_run_key, plan_tensors, resolve_plan)

I32 = jnp.int32
U32 = jnp.uint32
# STRIDE (re-exported above from ops/view_merge, its single source): odd
# prime per-node slot-map offset — decorrelates which id pairs collide
# across different nodes' views.
# Above this node count the ring mode stops building the two full-width
# [N*P]-index histograms that attribute probe recv / ack sends to their
# true rows; totals stay exact, the per-node split becomes approximate
# (attributed to the prober's row).  Summaries carry an
# ``approx_probe_attribution`` flag derived from this same constant so the
# degradation is visible in the output, not just in PERF.md (VERDICT r2
# weak-6/item-8).
PROBE_IO_EXACT_MAX = 1 << 17
# MEGA_TICKS auto candidates, largest first: the block sizes the ladder
# runs hardware rungs for (1M_s16_mega{8,32}) and tpu_correctness banks
# mega_t{T} families for — auto picks the biggest banked T that tiles
# CHECKPOINT_EVERY (make_config; fail closed without chip evidence).
MEGA_AUTO_TICKS = (32, 8)


def probe_attribution_exact(params: Params) -> bool:
    """Whether per-node probe/ack recv counters are exactly attributed
    (see PROBE_IO_EXACT_MAX; scatter mode and probe-free configs always
    are).  ``PROBE_IO: exact|approx`` overrides the size gate — on the
    sharded ring, exact attribution rides one bool all_gather plus two
    [N]-histogram psum_scatters per tick (the per-target counts travel
    the same wire the ack pipeline's [N] all_gather already does)."""
    if params.resolved_exchange() != "ring" or params.PROBES <= 0:
        return True
    if params.PROBE_IO != "auto":
        return params.PROBE_IO == "exact"
    return params.EN_GPSZ <= PROBE_IO_EXACT_MAX


def _will_flush(recv_mask, fail_mask, t, fail_time):
    """Rows whose ``pending_recv`` accumulated THIS tick flushes at t+1:
    receiving now AND not failing this tick — the failed flag is set at
    the END of ``t == fail_time``, so pending added during that tick
    strands forever (reference-faithfully: a crashed node never drains
    its queue)."""
    return recv_mask & ~(fail_mask & (t == fail_time))


def deliver_shift(payload, r, n, s, cstride, idx):
    """Deliver one circulant gossip shift: row roll by ``r`` + column
    alignment (receiver slot = sender slot + delta*STRIDE with delta = r
    for unwrapped receiver rows and r - N for wrapped ones; the two
    coincide iff N*STRIDE % S == 0, saving a full [N, S] pass).

    ``r`` may be a traced scalar (the default dynamic-roll path) or a
    Python int — the SHIFT_SET lax.switch branches pass table constants
    so every roll lowers to an aligned static copy.  Both callers share
    this one definition, so the static path cannot drift from the
    dynamic one (equality pinned in tests/test_shift_set.py)."""
    with jax.named_scope(PHASE_GOSSIP):
        static = isinstance(r, int)
        rolled = jnp.roll(payload, r, axis=0)
        s1 = ((r % s) * cstride % s if static
              else jax.lax.rem(jax.lax.rem(r, s) * cstride, s))
        r1 = jnp.roll(rolled, s1, axis=1)
        if (n * STRIDE) % s == 0:
            return r1
        s2 = (((r - n) % s) * cstride % s if static
              else jax.lax.rem(
                  jax.lax.rem(jax.lax.rem(r - n, s) + s, s) * cstride, s))
        r2 = jnp.roll(rolled, s2, axis=1)
        return jnp.where((idx >= r)[:, None], r1, r2)


def ptr_switch(ptr, step: int, s: int, fn, *operands, max_branches: int = 16):
    """Dispatch a deterministic slot-pointer roll over its STATIC value set.

    The probe/ack pointers advance by ``step`` slots per tick mod ``s``,
    so they only ever take the multiples of ``d = gcd(step, s)`` — at
    most ``s // d`` distinct values.  When that set is small, a
    ``lax.switch`` over static branches replaces the full-plane dynamic
    lane roll XLA would otherwise emit (the op class flagged at 1M_s16,
    PERF.md); each branch calls ``fn`` with a Python-int pointer, which
    lowers to aligned static copies/slices.  Falls back to
    ``fn(ptr)`` (traced) when the value set is too large.  Bit-exact by
    construction: both paths evaluate the same ``fn``."""
    import math

    d = math.gcd(step % s or s, s)
    if s // d > max_branches:
        return fn(ptr, *operands)
    return jax.lax.switch(
        ptr // d, [(lambda *ops, o=o: fn(o, *ops))
                   for o in range(0, s, d)], *operands)


def shift_table(n: int, k: int) -> tuple:
    """The static gossip-shift candidates for ``SHIFT_SET: K``:
    golden-ratio-spread values in [1, n).  Entry 0 is shift 1, so the
    union-of-K-circulants gossip graph always contains the full ring
    cycle and stays connected regardless of n's factorization."""
    tab = tuple(1 + (h * 2654435761) % (n - 1) for h in range(k))
    # K distinct shifts is what "K-way diversity, uniform draw" means;
    # it currently holds because the multiplier is prime (coprime to any
    # n-1 < 2^32), but a constant/formula tweak must fail HERE, not skew
    # the shift distribution silently (ADVICE r5 #3).
    assert len(set(tab)) == k, (
        f"shift_table({n}, {k}) produced duplicate shifts: {tab}")
    return tab


def _pack_probe_bits(will_flush, act):
    """Pack the two per-target filter bits of the approx probe-attribution
    branch into ONE i32 table (bit0 = will_flush, bit1 = act): ``act[tgt1]``
    and ``will_flush[tgt1]`` share their index tensor, and random [N, P]
    gathers are the op class the 1M_s16 HLO census flagged — pay the
    random access once.  Unpack with the companions below; all four
    backends (natural/folded x single/sharded) must use these so the bit
    layout cannot drift between the bit-exactness twins."""
    return will_flush.astype(I32) | (act.astype(I32) << 1)


def _gathered_flush(packed):
    return (packed & 1) != 0


def _gathered_act(packed):
    return (packed & 2) != 0    # bit test stays valid if the pack widens


def _pack_probe_table(hb, wf, act):
    """Widen :func:`_pack_probe_bits` into the full packed probe table:
    the ack-value heartbeat rides the HIGH 30 bits over the same two
    filter bits, so ack value + will-flush + act + counter bits travel
    ONE u32 per-target gather (PROBE_GATHER packed) instead of the two
    [N, P] random gathers the 1M_s16 census flagged.  Headroom: hb must
    fit 30 bits — implied by validate_sparse_packing's uint32 view-pack
    bound whenever N >= 4 (hb_max * N < 2^32), which is why make_config
    normalizes PROBE_GATHER to 'split' below that size.  The low-bit
    layout is _pack_probe_bits', so _gathered_flush/_gathered_act apply
    to this table's gathers unchanged."""
    return ((hb.astype(U32) << 2)
            | _pack_probe_bits(wf, act).astype(U32))


def _gathered_hb(packed):
    """The ack-value heartbeat back out of a _pack_probe_table gather."""
    return (packed >> 2).astype(I32)


def _credit_orphan_recvs(per_prober, will_flush):
    """Approx probe-recv attribution, single chip: keep rows that will
    flush; recvs counted for a non-flushing prober (already dead — its
    probes are still in flight — or failing this tick) would strand in
    ITS pending where exact mode charges the live target instead, so
    their sum is re-credited to one surviving row.  The per-node split
    is approximate by contract; TOTALS match exact mode bit-for-bit
    (tests/test_probe_io.py)."""
    orphan = jnp.where(will_flush, 0, per_prober).sum(dtype=I32)
    safe = jnp.argmax(will_flush).astype(I32)
    return jnp.where(will_flush, per_prober, 0).at[safe].add(
        jnp.where(will_flush.any(), orphan, 0))


def _credit_orphan_recvs_sharded(per_prober, will_flush_l, will_flush_g,
                                 lrows, axis):
    """The sharded twin of :func:`_credit_orphan_recvs`: the orphan sum
    rides a scalar psum and lands on the globally-first surviving row
    (whichever shard owns it)."""
    orphan = jax.lax.psum(
        jnp.where(will_flush_l, 0, per_prober).sum(dtype=I32), axis)
    safe_g = jnp.argmax(will_flush_g).astype(I32)
    return jnp.where(will_flush_l, per_prober, 0) + jnp.where(
        (lrows == safe_g) & will_flush_g.any(), orphan, 0)


class HashState(NamedTuple):
    view: jax.Array      # [N, S] u32 packed (hb * N + id + 1), 0 = empty
    view_ts: jax.Array   # [N, S] i32 — tick of last strict packed increase
    started: jax.Array   # [N] bool
    in_group: jax.Array  # [N] bool
    failed: jax.Array    # [N] bool
    self_hb: jax.Array   # [N] i32
    mail: jax.Array      # [N, S] u32 — receiver-slot-mapped, max-combined
    amail: jax.Array     # [N, S] u32 — ack channel, collision-free (docstring)
    pmail: jax.Array     # [N, Qp] u32 probe mailbox (prober id + 1)
    joinreq_infl: jax.Array  # [N] bool
    joinrep_infl: jax.Array  # [N] bool
    pending_recv: jax.Array  # [N] i32
    agg: AggStats        # on-device event aggregates (AggStats or FastAgg;
    #                      updated only when collect_events=False)
    probe_ids1: jax.Array    # [N, P] u32 ids probed last tick (ring mode;
    #                          [1,1] zeros otherwise), 0 = none
    probe_ids2: jax.Array    # [N, P] u32 ids probed two ticks ago (ring)
    act_prev: jax.Array      # [N] bool act mask of the previous tick (ring)
    wf_prev: jax.Array       # [N] bool will_flush of the previous tick
    #                          (probe_io_lag only; [1] zeros otherwise)


@dataclasses.dataclass(frozen=True)
class HashConfig:
    n: int
    s: int           # view/mailbox slots per node
    g: int           # entries piggybacked per gossip message
    tfail: int
    tremove: int
    fanout: int
    drop_prob: float
    probes: int = 0
    qp: int = 16
    seed_cap: int = SEED_CAP
    collect_events: bool = True
    exchange: str = "scatter"   # 'scatter' (reference-shaped delivery) or
    #                             'ring' (circulant rolls — see make_step)
    fail_ids: tuple = ()        # static failed-id list for the FastAgg path
    fast_agg: bool = False      # scatter-free aggregates (ring scale runs)
    count_probe_io: bool = True  # exact per-node probe/ack recv counters
    #                              (two [N*P]-index histograms per tick);
    #                              off at huge N, totals stay ~exact
    probe_io_none: bool = False  # PROFILING ONLY (PROBE_IO: none): zero
    #                              the probe-recv/ack-send counters,
    #                              removing their per-target random
    #                              gather from the tick
    probe_io_lag: bool = False   # PROBE_IO: approx_lag — one [N, 2]-wide
    #                              per-target gather per tick: counter
    #                              bits ride the ack-value gather, with
    #                              attribution delayed one tick (run
    #                              totals stay exact; single-chip ring
    #                              natural layout only)
    fused_receive: bool = False  # ring receive via the Pallas one-pass
    #                              kernel (ops/fused_receive) instead of
    #                              the jnp expression of the same math
    fused_gossip: bool = False   # all circulant shifts delivered in one
    #                              Pallas traversal (ops/fused_gossip)
    #                              instead of fanout roll+max passes
    fused_probe: bool = False    # probe-window read + FastAgg/telemetry
    #                              hist reductions in ONE Pallas traversal
    #                              of the post-receive planes
    #                              (ops/fused_probe); drop coins and
    #                              scenario cuts stay outside in [N, P]
    #                              space with the exact unfused streams
    folded: bool = False         # [N/F, 128] folded physical layout for
    #                              S < 128 (backends/tpu_hash_folded.py)
    mega_ticks: int = 0          # T >= 2: segment runners restructure
    #                              the per-tick scan into T-tick blocks
    #                              (ops/megakernel.mega_scan) — carry
    #                              resident across the inner loop,
    #                              materialized per block boundary only.
    #                              0/1 = the plain per-tick scan (T=1 is
    #                              op-count-identical by construction —
    #                              tests/test_hlo_census.py)
    mega_pack: bool = False      # shrink the T-block boundary carry:
    #                              view_ts/self_hb as 16-bit lanes, bool
    #                              planes bit-packed (megakernel codec;
    #                              bit-exact under the static tick bound
    #                              run_scan re-proves per run)
    send_budget: int = 0         # per-tick global send cap modeling
    #                              EmulNet's bounded buffer (EN_BUFFSIZE
    #                              drop-on-full, EmulNet.cpp:92-94);
    #                              0 = unbounded (documented deviation)
    shift_set: int = 0           # K > 0: gossip shifts drawn from a
    #                              static K-table, delivered via
    #                              lax.switch over static-roll branches
    #                              (the node-minor dynamic-roll
    #                              mitigation — config.py SHIFT_SET)
    rng_mode: str = "batched"    # ring-path RNG lowering (config.py
    #                              RNG_MODE; ops/rng_plan.py): 'scattered'
    #                              one threefry per draw site, 'batched'
    #                              same-size draws in ONE vmapped
    #                              invocation, 'hoisted' batched + a
    #                              whole segment pre-drawn outside the
    #                              scan (chunked runs only).  Bit-exact
    #                              streams in every mode.
    probe_gather: str = "packed"  # ring probe/ack pipeline lowering
    #                              (config.py PROBE_GATHER): 'packed'
    #                              rides ack value + counter bits on ONE
    #                              per-target gather via
    #                              _pack_probe_table; 'split' keeps the
    #                              pre-round-6 two-gather form (A/B arm)
    telemetry: bool = False      # TELEMETRY: scalars — emit the per-tick
    #                              TickTelemetry scalar reductions
    #                              alongside the event outputs
    #                              (observability/timeline.py).  Every
    #                              emission site is guarded on this flag,
    #                              so the off program is op-identical to
    #                              the pre-flight-recorder lowering
    #                              (tests/test_hlo_census.py).  Ring only.
    telemetry_hist: bool = False  # TELEMETRY: hist — additionally emit
    #                              the per-tick TickHist fixed-bucket
    #                              histograms (staleness / suspicion age
    #                              / detection latency / occupancy /
    #                              drops) as bucketed one-hot reductions
    #                              over tensors the step already holds:
    #                              no RNG, no gathers, no scatters
    #                              (census-pinned).  Implies telemetry.
    batched_exchange: bool = False  # ring gossip shifts cross shards as
    #                              ONE all_to_all per tick (sender-side
    #                              alignment + per-destination max/sum
    #                              combine, ops/exchange.py) instead of
    #                              one masked ppermute rotation per shift
    #                              per mesh axis, and the result double-
    #                              buffers through the scan carry so the
    #                              collective overlaps the probe/agg tail
    #                              (EXCHANGE_MODE; tpu_hash_sharded only
    #                              — the single-chip ring has no
    #                              cross-shard wire, so the knob is
    #                              structurally inert there).  Bit-exact
    #                              vs legacy: tests/test_exchange.py.
    scenario: object = None      # General-path scenario structural
    #                              descriptor (scenario/compile.py
    #                              ScenarioStatic — hashable, so it keys
    #                              the runner caches).  When set, the
    #                              step takes the ScenarioTensors plan as
    #                              an 8th input and applies crash/restart
    #                              transitions, the partition cross-group
    #                              send mask, and per-window/per-link
    #                              drop-prob overrides — all elementwise
    #                              (tests/test_hlo_census.py bounds the
    #                              addition; None = the unchanged
    #                              program, op-count identical).  Ring
    #                              exchange only.


def slot_of(cfg: HashConfig, node: jax.Array, member: jax.Array) -> jax.Array:
    """The per-node slot map h_node(member) = (member + node*STRIDE) mod S.

    Computed modularly: the naive ``member + node * STRIDE`` overflows
    int32 for node ids above ~271k (2^31 / STRIDE), yielding negative
    slots — which silently corrupted self-slot protection and scatter
    addresses at N > 271k.  ``node % S`` first keeps every intermediate
    below S^2.  Callers must mask invalid (EMPTY-member) messages
    themselves — _scatter_msgs' msg_valid/sentinel-address path — the
    slot value for EMPTY is meaningless, not reliably out of range."""
    return jax.lax.rem(
        jax.lax.rem(member, cfg.s) + jax.lax.rem(node, cfg.s) * (STRIDE % cfg.s),
        cfg.s)


def pack(cfg: HashConfig, hb: jax.Array, member: jax.Array) -> jax.Array:
    return (hb.astype(U32) * U32(cfg.n) + member.astype(U32) + U32(1))


def unpack(cfg: HashConfig, packed: jax.Array):
    """→ (member id [EMPTY if none], hb, present)."""
    present = packed > 0
    v = packed - U32(1)
    member = (v % U32(cfg.n)).astype(I32)
    hb = (v // U32(cfg.n)).astype(I32)
    return jnp.where(present, member, EMPTY), jnp.where(present, hb, -1), present


def make_admit(n: int, self_slot_mask: jax.Array, row_ids: jax.Array):
    """The sticky admit-or-refresh combine (module docstring), shared by
    every step builder (single-chip scatter/ring and both sharded steps).

    Occupied slots accept only updates for their current occupant's id;
    empty slots admit the incoming winner.  The self slot is
    occupied-by-self from the start: it admits only the node's own id even
    while empty, so no foreign id is ever evicted by the self refresh —
    preserving the sticky-admission invariant (the only eviction is the
    TREMOVE sweep).  ``row_ids`` are the global node ids of the local rows
    (``arange(N)`` single-chip; the shard's row range sharded).
    """
    from distributed_membership_tpu.ops.fused_receive import _admit

    def admit(view: jax.Array, incoming: jax.Array) -> jax.Array:
        return _admit(n, self_slot_mask, row_ids, view, incoming)

    return admit


def _scatter_msgs(cfg: HashConfig, mail: jax.Array, tgt: jax.Array,
                  msg_id: jax.Array, msg_hb: jax.Array,
                  msg_valid: jax.Array) -> jax.Array:
    """Max-combine messages into receiver-slot-mapped mailboxes."""
    n, s = mail.shape
    addr = tgt * s + slot_of(cfg, tgt, msg_id)
    addr = jnp.where(msg_valid, addr, n * s).reshape(-1)
    val = jnp.where(msg_valid, pack(cfg, msg_hb, msg_id), 0).reshape(-1)
    flat = mail.reshape(-1).at[addr].max(val, mode="drop")
    return flat.reshape(n, s)


def init_state(cfg: HashConfig) -> HashState:
    n, s = cfg.n, cfg.s
    ring = cfg.exchange == "ring"
    probe_shape = (n, cfg.probes) if ring and cfg.probes > 0 else (1, 1)
    return HashState(
        view=jnp.zeros((n, s), U32),
        view_ts=jnp.zeros((n, s), I32),
        started=jnp.zeros((n,), bool),
        in_group=jnp.zeros((n,), bool),
        failed=jnp.zeros((n,), bool),
        self_hb=jnp.zeros((n,), I32),
        mail=jnp.zeros((n, s), U32),
        # ring mode's ack channel is the gather pipeline below — the
        # scatter-mode amail/pmail buffers shrink to placeholders.
        amail=jnp.zeros((n, s) if not ring else (1, 1), U32),
        pmail=jnp.zeros((n, cfg.qp) if not ring else (1, 1), U32),
        joinreq_infl=jnp.zeros((n,), bool),
        joinrep_infl=jnp.zeros((n,), bool),
        pending_recv=jnp.zeros((n,), I32),
        agg=(init_fast_agg(len(cfg.fail_ids), n) if cfg.fast_agg
             else init_agg(n)),
        probe_ids1=jnp.zeros(probe_shape, U32),
        probe_ids2=jnp.zeros(probe_shape, U32),
        act_prev=jnp.zeros((n,) if ring else (1,), bool),
        wf_prev=jnp.zeros((n,) if cfg.probe_io_lag else (1,), bool),
    )


def init_state_warm(cfg: HashConfig, key: jax.Array) -> HashState:
    """Every node in-group at t=0 with self + ~S/2 random neighbors."""
    n, s = cfg.n, cfg.s
    st = init_state(cfg)
    idx = jnp.arange(n, dtype=I32)
    fill = max(s // 2, 1)
    offs = jax.random.randint(key, (n, fill), 1, max(n, 2), dtype=I32)
    nbrs = jax.lax.rem(idx[:, None] + offs, n)
    view = _scatter_msgs(
        cfg, st.view, jnp.broadcast_to(idx[:, None], nbrs.shape), nbrs,
        jnp.zeros_like(nbrs), jnp.ones(nbrs.shape, bool))
    # The self slot belongs to self unconditionally (admit() reserves it);
    # overwrite any neighbor that collided into it during the warm scatter.
    view = view.at[idx, slot_of(cfg, idx, idx)].set(
        pack(cfg, jnp.zeros((n,), I32), idx))
    return st._replace(
        view=view,
        started=jnp.ones((n,), bool),
        in_group=jnp.ones((n,), bool),
    )


def _ring_rng_builder(cfg: HashConfig, use_drop: bool):
    """``fn(tick_key) -> RingRng`` for this config's ring step (natural
    or folded) — the SINGLE source both the inline per-tick draw and the
    hoisted segment pre-draw build from, so the two cannot drift (the
    hoisted [K, ...] tensors are exactly ``vmap(fn)(keys)``).  The
    folded step never consumes the (trajectory-inert under warm join)
    control/burst coins, so they are not drawn for it — natural keeps
    them, matching the scattered step's draw set exactly."""
    k_max = min(cfg.fanout, cfg.s)

    def build(key):
        return hash_ring_rng(
            key, n=cfg.n, s=cfg.s, g=cfg.g, k_max=k_max,
            p_cnt=max(cfg.probes, 0),
            seed_rows=min(cfg.seed_cap, cfg.n),
            shift_set=cfg.shift_set, use_drop=use_drop,
            need_ctrl=not cfg.folded, need_burst=not cfg.folded,
            batched=cfg.rng_mode != "scattered")

    return build


def make_step(cfg: HashConfig, dynamic_knobs: bool = False):
    """Per-tick transition; same pass structure as the dense backend
    (backends/tpu.py) with hashed coordinates.  Pure/jittable.

    Two exchange modes:

    * ``'scatter'`` — reference-shaped delivery: sampled view-occupant
      targets, scatter-max message delivery, slot-addressed probe/ack
      mailboxes.  Exact bit-parity shape with the original design; the mode
      the grader-parity and distribution tests pin down.
    * ``'ring'`` — the TPU fast path.  XLA lowers a scatter over R random
      receiver addresses to a serialized loop, which is the whole per-tick
      cost at scale; this mode removes every full-width scatter:

      - *Gossip as circulant rolls.*  Per tick, ``fanout`` shared shifts
        ``r_j ~ U[1, N)`` are drawn; sender ``i`` gossips to ``i + r_j``.
        Because the slot map is affine (``h_i(id) = id + i*STRIDE mod S``),
        a sender's whole hashed row lands on the receiver's coordinates by
        rotating columns by ``r_j * STRIDE mod S`` — delivery for one shift
        is ``roll(rows) → roll(cols) → elementwise max``: pure VPU + HBM,
        no scatter.  The per-tick gossip graph is a union of ``fanout``
        random circulant permutations (re-drawn every tick) instead of
        iid per-sender target sets — an expander w.h.p. with the same
        uniform per-target marginals; the distributional parity gate pins
        the resulting detection-latency window.
      - *Probes/acks as a gather pipeline.*  A probe/ack round trip is
        semantically "refresh my slot for id from id's own heartbeat, two
        ticks later, if id was alive in between" — so instead of routing
        mailbox messages, the ack value is gathered from a 1-tick-lagged
        ``self_hb`` vector (``vec[id] = self_hb - 1`` where the target was
        act) for the ids probed two ticks ago, and applied to the
        deterministic probe-window slots by a pad-and-roll.  The probe-leg
        drop coin applies at issue time (as in scatter mode, one coin for
        both redundant copies), the ack-leg coin at application time.
        Unlike the scatter mode's hashed pmail, this channel has NO
        collision loss, and a stale ack can never re-admit a removed id
        (the refresh requires the occupant to still match).

    With ``dynamic_knobs`` the returned step takes two extra *traced*
    scalars ``(fanout, drop_prob)`` after ``inputs`` — ``cfg.fanout`` then
    only bounds the static target count and ``cfg.drop_prob`` is ignored.
    This lets a phase-diagram sweep compile ONE step and vmap it over the
    whole (fanout x drop-rate) grid instead of one compile per cell
    (sweeps/phase.py)."""
    n, s, g = cfg.n, cfg.s, cfg.g
    intro = INTRODUCER_INDEX
    idx = jnp.arange(n, dtype=I32)
    k_max = min(cfg.fanout, s)
    ring = cfg.exchange == "ring"
    # Redundant probe transmission factor (scatter mode sends each probe
    # into two independently-hashed pmail slots when the map is lossy; both
    # copies share one drop coin, so redundancy counters collision loss,
    # not drop loss).  Ring's channel has no collisions; p_red only keeps
    # the wire-message counters comparable.
    p_red = 1 if cfg.qp >= n else 2
    if ring and cfg.probes >= s:
        raise ValueError("ring mode needs PROBES < VIEW_SIZE "
                         f"(got {cfg.probes} >= {s})")
    if cfg.fused_gossip and (dynamic_knobs or cfg.send_budget > 0
                             or not gossip_fused_supported(n, s)):
        # Dynamic-knob sweeps vmap one compiled cell over the grid (no
        # place for a per-cell kernel), the send budget is a sequential
        # cross-shift mask the kernels don't model, and unsupported
        # shapes need the two-roll wrapped-row column alignment the
        # single-payload kernel omits (make_config rejects these too;
        # this guards direct make_step callers like the sweep driver).
        # DROPS, drop windows, and scenario flakes are all fine: the
        # per-shift keep masks ride the kernel as precomputed inputs
        # (ops/fused_gossip masks=..., step body below).
        raise ValueError(
            "FUSED_GOSSIP requires a static budget-free config and "
            f"supported shapes (ring mode, S % 128 == 0, "
            f"(N*STRIDE) % S == 0; got N={n}, S={s}, "
            f"dynamic_knobs={dynamic_knobs}, budget={cfg.send_budget})")
    self_slot_mask = jnp.arange(s, dtype=I32)[None, :] == slot_of(
        cfg, idx, idx)[:, None]                                   # [N, S]
    scenario = cfg.scenario
    use_drop = (dynamic_knobs or cfg.drop_prob > 0.0
                or (scenario is not None and scenario.has_drop))
    if cfg.telemetry and not ring:
        # make_config gates this (TELEMETRY requires the ring exchange);
        # direct constructors must not silently get an empty timeline.
        raise ValueError("cfg.telemetry requires the ring exchange")
    if scenario is not None and (not ring or dynamic_knobs
                                 or cfg.send_budget > 0):
        # make_config gates these too (this guards direct constructors):
        # general scenarios are ring-only, and incompatible with the
        # dynamic-knob sweep step and the sequential send budget.
        # FUSED_GOSSIP composes: the per-shift partition/flake masks ride
        # the kernel as precomputed mask-stack inputs (ops/fused_gossip).
        raise ValueError(
            "cfg.scenario requires the plain ring exchange (no "
            "dynamic knobs or ENFORCE_BUFFSIZE)")

    rng_build = _ring_rng_builder(cfg, use_drop) if ring else None

    def step(state: HashState, inputs, fanout=None, drop_prob=None):
        (t, key, start_ticks, fail_mask, fail_time, drop_lo,
         drop_hi) = inputs[:7]
        fanout_eff = cfg.fanout if fanout is None else fanout
        p_drop = cfg.drop_prob if drop_prob is None else drop_prob
        if ring:
            # All ring random streams come from the per-tick RNG plan
            # (ops/rng_plan.py — same keys and bits as the scattered
            # per-site draws; RNG_MODE selects the threefry lowering).
            # Hoisted segments pass the pre-drawn plan in the key slot.
            rng = key if isinstance(key, RingRng) else rng_build(key)
        else:
            (k_targets, k_entries, k_drop, k_ctrl, k_drop_p, k_shifts,
             k_ack1, k_ack2) = jax.random.split(key, 8)

        drop_active = (t > drop_lo) & (t <= drop_hi)
        # Per-tick coin-drop counts (TELEMETRY scalars only — every
        # append below is guarded, so the off program gains nothing).
        telem_dropped = []
        # ---- scenario plan activation (scenario/compile.py) ----
        # Everything here is elementwise math over the small event/
        # window tensors riding as the 8th scan input; with
        # cfg.scenario None this whole block (and every site below
        # that consults it) does not exist in the traced program
        # (tests/test_hlo_census.py pins op-count identity).
        if scenario is not None:
            from distributed_membership_tpu.scenario.compile import (
                cross_group, cuts_at, delayed_mask, site_drop_prob,
                updown_masks)
            scn = inputs[7]
            intro_v = jnp.full((n,), intro, I32)
            if scenario.has_updown:
                down_now, up_now = updown_masks(scn, t, idx)
                fails_now = down_now | up_now
            else:
                down_now = up_now = fails_now = None
            cuts = cuts_at(scn, t, n) if scenario.n_parts else None
            cuts_prev = (cuts_at(scn, t - 1, n) if scenario.n_parts
                         else None)

            def site_p(tt, src, dst):
                p = site_drop_prob(scenario, scn, tt, src, dst)
                return p

        else:
            scn = fails_now = None

        def wf_now():
            """Rows whose pending flushes at t+1 (see _will_flush);
            under a scenario the legacy single-crash term is replaced
            by this tick's down/restart transitions."""
            if fails_now is not None:
                return recv_mask & ~fails_now
            return _will_flush(recv_mask, fail_mask, t, fail_time)

        if use_drop:
            ctrl_u = (rng.ctrl_u.reshape(2, n) if ring
                      else jax.random.uniform(k_ctrl, (2, n)))
            if scenario is not None:
                # Per-message effective probs: JOINREQ (idx -> intro)
                # and JOINREP (intro -> idx); window gating is baked
                # into the prob, so no drop_active conjunction.
                p_ctrl = jnp.stack([
                    jnp.broadcast_to(site_p(t, idx, intro_v), (n,)),
                    jnp.broadcast_to(site_p(t, intro_v, idx), (n,))])
                ctrl_kept = ~(ctrl_u < p_ctrl)
            else:
                ctrl_kept = ~((ctrl_u < p_drop) & drop_active)
        else:
            ctrl_kept = jnp.ones((2, n), bool)
        if scenario is not None and scenario.n_parts:
            # Partition: join control crossing group boundaries is cut
            # deterministically (no coin).
            ctrl_kept = ctrl_kept & ~cross_group(cuts, idx,
                                                 intro_v)[None, :]

        # EmulNet bounded-buffer model (ENFORCE_BUFFSIZE): one per-tick
        # global send budget, consumed with drop-on-full per message
        # (EmulNet.cpp:92-94) in this model's traversal order — join
        # control (JOINREP then JOINREQ, node-minor), gossip shifts,
        # the introducer seed burst, then probes; acks are exempt
        # (README fidelity notes).  A budget-dropped JOINREQ/JOINREP is
        # dropped FOREVER — the reference's joiner never retries
        # (introduceSelfToGroup runs once, MP1Node.cpp:126-159), so a
        # join storm over the cap permanently strands late joiners,
        # which is exactly the regime the reference's 30k cap binds in.
        track_budget = ring and cfg.send_budget > 0
        if track_budget:
            budget = jnp.asarray(cfg.send_budget, I32)
            used = jnp.zeros((), I32)

            def _budget_take(mask, used_now):
                """Accept `mask`'s messages in traversal order (row-major)
                until the budget is spent; returns (kept, new_used).

                2-D masks use the decomposed row-count/clip form — bit-
                identical to the flat cumsum but the scan dimensions stay
                N and S instead of one N*S-element scan (the gossip loop
                calls this per shift on [N, S] at the 1M scale).

                Monotonicity note the join sites rely on: once the budget
                is spent nothing later in the tick is accepted, so a
                budget-dropped JOINREP implies the (later-ordered) seed
                burst to that joiner drops too — matching the reference,
                where a full buffer stays full for the rest of the tick
                (recvs only drain it next pass 1).  A COIN-dropped
                JOINREP with a delivered burst is also faithful: the
                reference rolls each ENsend independently."""
                if mask.ndim == 1:
                    csum = jnp.cumsum(mask.astype(I32)) + used_now
                    kept = mask & (csum <= budget)
                    return kept, used_now + kept.sum(dtype=I32)
                cnt0 = mask.sum(1, dtype=I32)
                starts = used_now + jnp.cumsum(cnt0) - cnt0
                allowed = jnp.clip(budget - starts, 0, cnt0)
                kept = mask & (jnp.cumsum(mask.astype(I32), axis=1)
                               <= allowed[:, None])
                return kept, used_now + allowed.sum(dtype=I32)

        # ---- pass 1: receive = elementwise admit-or-refresh combine ----
        # (make_admit: sticky admission.)  Acks apply first: their channel
        # is collision-free, and an occupant whose slot the gossip winner
        # contends for still gets its refresh.
        recv_mask = state.started & (t > start_ticks) & ~state.failed
        if scenario is not None and scenario.n_delays:
            # delay_window: inbound delivery to covered nodes is HELD —
            # the node neither admits mail nor flushes pending recvs
            # while a window covers it (mail max-merges across the held
            # ticks, absorbing reorder; everything drains the first tick
            # after the window).  Acks landing in the window are lost,
            # not delayed (the one-shot expected-ack candidates are not
            # in the carry).  ``act`` below is derived independently of
            # this mask, so the node keeps sending, probing, and aging
            # its TFAIL/TREMOVE sweep — asymmetric gray failure.
            recv_mask = recv_mask & ~delayed_mask(scn, t, idx)
        rcol = recv_mask[:, None]

        if not ring:
            prev_id, _, prev_present = unpack(cfg, state.view)
            admit = make_admit(n, self_slot_mask, idx)
            view = jnp.where(rcol, admit(state.view, state.amail), state.view)
            view = jnp.where(rcol, admit(view, state.mail), view)
            changed = view > state.view
            view_ts = jnp.where(changed, t, state.view_ts)
            mail = jnp.where(rcol, 0, state.mail)
            amail = jnp.where(rcol, 0, state.amail)

            cur_id, cur_hb, present = unpack(cfg, view)
            join_mask = changed & ~prev_present  # admission into empty slot
            join_ids = jnp.where(join_mask, cur_id, EMPTY)

            # Probe mailbox stores bare prober ids (id + 1, 0 = empty).
            ack_valid = (state.pmail > 0) & recv_mask[:, None]
            ack_tgt = jnp.where(ack_valid, state.pmail.astype(I32) - 1, 0)
            pmail = jnp.where(recv_mask[:, None], 0, state.pmail)
        else:
            # Ring admit/ack/self/sweep run as ONE fused receive pass
            # (ops/fused_receive: receive_core, or its Pallas twin when
            # cfg.fused_receive) — below, after the vector control plane
            # resolves act/self_on.  The ack-candidate gather also moved
            # down next to that call: the packed probe table wants THIS
            # tick's act/will_flush so the counter bits ride the same
            # gather (PROBE_GATHER packed).
            amail, pmail = state.amail, state.pmail

        recv_tick = jnp.where(recv_mask, state.pending_recv, 0)
        pending_recv = jnp.where(recv_mask, 0, state.pending_recv)

        in_group = state.in_group | (state.joinrep_infl & recv_mask)
        joinrep_infl = state.joinrep_infl & ~recv_mask

        seeds = state.joinreq_infl & recv_mask[intro]
        joinreq_infl = state.joinreq_infl & ~recv_mask[intro]
        rep_ok = seeds & ctrl_kept[1]
        if cfg.telemetry and use_drop:
            telem_dropped.append((seeds & ~ctrl_kept[1]).sum(dtype=I32))
        if track_budget:
            # A dropped JOINREP permanently strands the joiner (the
            # request was consumed; the reference never re-replies).
            rep_ok, used = _budget_take(rep_ok, used)
        joinrep_infl = joinrep_infl | rep_ok
        n_seeds = seeds.sum(dtype=I32)
        sent_rep = jnp.where(idx == intro,
                             jnp.where(recv_mask[intro], rep_ok.sum(dtype=I32), 0), 0)
        pending_recv = pending_recv + rep_ok.astype(I32)

        # ---- nodeStart ----
        start_now = t == start_ticks
        started = state.started | start_now
        boot = start_now[intro]
        in_group = in_group.at[intro].set(in_group[intro] | boot)

        joiner_req = start_now & (idx != intro) & ctrl_kept[0]
        if cfg.telemetry and use_drop:
            telem_dropped.append(
                (start_now & (idx != intro) & ~ctrl_kept[0]).sum(dtype=I32))
        if track_budget:
            # A dropped JOINREQ is never retried (nodeStart runs once):
            # the node stays started but never enters the group.
            joiner_req, used = _budget_take(joiner_req, used)
        joinreq_infl = joinreq_infl | joiner_req
        if not ring:
            mail = _scatter_msgs(cfg, mail, jnp.full((n,), intro, I32), idx,
                                 jnp.zeros((n,), I32), joiner_req)
        pending_recv = pending_recv.at[intro].add(joiner_req.sum(dtype=I32))
        sent_req = joiner_req.astype(I32)

        # ---- self refresh (double heartbeat increment, MP1Node.cpp:412-415) --
        act = started & (t > start_ticks) & ~state.failed & in_group
        own_hb = state.self_hb + 1
        self_hb = jnp.where(act, state.self_hb + 2, state.self_hb)
        self_on = act | ((idx == intro) & boot)
        self_val = pack(cfg, jnp.where(act, own_hb, 0), idx)

        if not ring:
            self_slot = slot_of(cfg, idx, idx)
            old_self = view[idx, self_slot]
            view = view.at[idx, self_slot].set(
                jnp.where(self_on, self_val, old_self))
            view_ts = view_ts.at[idx, self_slot].set(
                jnp.where(self_on, t, view_ts[idx, self_slot]))
            cur_id, cur_hb, present = unpack(cfg, view)

            # ---- TFAIL / TREMOVE sweep ----
            difft = t - view_ts
            stale = present & (difft >= cfg.tfail) & act[:, None]
            numfailed = stale.sum(1, dtype=I32)
            removes = stale & (difft >= cfg.tremove)
            rm_ids = jnp.where(removes, cur_id, EMPTY)
            view = jnp.where(removes, 0, view)
            present = present & ~removes
            size = present.sum(1, dtype=I32)
        else:
            ack_recv_cnt = jnp.zeros((n,), I32)
            cand_full = jnp.zeros((n, s), U32)
            will_flush = probe_bits1 = lag_bits = None
            if cfg.probes > 0:
                # Acks for probes issued at t-2 (gather pipeline, see
                # docstring).  vec[id] = the hb the target acked at t-1
                # (self_hb at start of t-1, +1 — the mid-increment value
                # the scatter path's own_hb carries), 0 if it wasn't act.
                with jax.named_scope(PHASE_ACK):
                    p_cnt = cfg.probes
                    ids2 = state.probe_ids2
                    id2 = jnp.clip(ids2.astype(I32) - 1, 0)
                    vec = jnp.where(state.act_prev, state.self_hb - 1, 0)
                    ids1 = state.probe_ids1
                    v1 = ids1 > 0
                    tgt1 = jnp.clip(ids1.astype(I32) - 1, 0)
                    # 'packed' (default): ack value + will-flush + act +
                    # counter bits ride ONE per-target gather per tick
                    # (_pack_probe_table) — the [N, 2P] index tensor is
                    # the t-2 ack indices and the t-1 counter indices
                    # concatenated.  n >= 4 guards the 30-bit hb headroom
                    # (see _pack_probe_table); PROBE_IO none draws no
                    # counter bits in either arm.
                    packed = cfg.probe_gather == "packed" and n >= 4
                    if cfg.probe_io_lag and packed:
                        # approx_lag: the [N, P, 2] stacked gather
                        # collapses to one packed-u32 [N, P] gather (t-1
                        # snapshots of the filter bits under the lagged
                        # heartbeat).
                        g2 = _pack_probe_table(vec, state.wf_prev,
                                               state.act_prev)[id2]
                        hb_ack = _gathered_hb(g2)
                        lag_bits = g2
                    elif cfg.probe_io_lag:
                        # split arm (the pre-round-6 lowering): counter
                        # bits ride the ack-value gather as a 2-wide
                        # last axis.
                        tbl2 = jnp.stack(
                            [vec, _pack_probe_bits(state.wf_prev,
                                                   state.act_prev)],
                            axis=1)
                        g2 = tbl2[id2]              # [N, P, 2] one gather
                        hb_ack = g2[..., 0]
                        lag_bits = g2[..., 1]
                    elif packed and not cfg.probe_io_none:
                        will_flush = wf_now()
                        tbl = _pack_probe_table(vec, will_flush, act)
                        gcat = tbl[jnp.concatenate([id2, tgt1], axis=1)]
                        hb_ack = _gathered_hb(gcat[:, :p_cnt])
                        probe_bits1 = gcat[:, p_cnt:]
                    else:
                        hb_ack = vec[id2]                  # [N, P] gather
                    valid2 = (ids2 > 0) & (hb_ack > 0)
                    if scenario is not None and scenario.n_parts:
                        # The ack traveled target (id2) -> prober (idx)
                        # during tick t-1: cut it if the partition was
                        # up then.
                        valid2 &= ~cross_group(cuts_prev, id2,
                                               idx[:, None])
                    # Probe-leg drops applied at issue time (probe block
                    # below, one coin shared by both redundant copies, as
                    # in scatter mode); only the ack leg's coin applies
                    # here.
                    if use_drop:
                        if scenario is not None:
                            ack_coin = (rng.ack_u.reshape(ids2.shape)
                                        < site_p(t - 1, id2,
                                                 idx[:, None]))
                        else:
                            da_ack = (t - 1 > drop_lo) & (t - 1 <= drop_hi)
                            ack_coin = ((rng.ack_u.reshape(ids2.shape)
                                         < p_drop) & da_ack)
                        if cfg.telemetry:
                            telem_dropped.append(
                                (valid2 & ack_coin).sum(dtype=I32))
                        valid2 &= ~ack_coin
                    cand = jnp.where(valid2, pack(cfg, hb_ack, id2), 0)
                    ptr2 = jax.lax.rem(
                        jax.lax.rem((t - 2) * p_cnt, s) + s, s)
                    cand_full = jnp.concatenate(
                        [cand, jnp.zeros((n, s - p_cnt), U32)], axis=1)
                    # ptr2 only takes multiples of gcd(P, S): static-roll
                    # switch instead of a full-plane dynamic lane roll.
                    cand_full = ptr_switch(
                        ptr2, p_cnt, s,
                        lambda o, c: jnp.roll(c, o, axis=1), cand_full)
                    ack_recv_cnt = (valid2 & rcol).sum(1, dtype=I32)
            recv_fn = (
                (lambda *a: receive_fused(
                    n, s, cfg.tfail, cfg.tremove, STRIDE,
                    jax.default_backend() != "tpu", *a))
                if cfg.fused_receive else
                (lambda *a: receive_core(
                    n, s, cfg.tfail, cfg.tremove, STRIDE, *a)))
            (view, view_ts, mail, join_mask, rm_ids, numfailed,
             size) = recv_fn(t, state.view, state.view_ts, state.mail,
                             cand_full, recv_mask, act, self_on, self_val,
                             idx)
            mail = _scatter_msgs(cfg, mail, jnp.full((n,), intro, I32), idx,
                                 jnp.zeros((n,), I32), joiner_req)
            cur_id, cur_hb, present = unpack(cfg, view)
            join_ids = jnp.where(join_mask, cur_id, EMPTY)
            difft = t - view_ts

        # ---- gossip ----
        numpotential = size - 1 - numfailed
        fresh = present & (difft < cfg.tfail)
        is_self_slot = cur_id == idx[:, None]
        seed_burst_on = act[intro]
        n_seeds_row = jnp.where((idx == intro) & seed_burst_on, n_seeds, 0)
        k_eff = jnp.clip(jnp.minimum(fanout_eff, numpotential) - n_seeds_row, 0)

        if ring:
            # Circulant exchange (see docstring): shared shifts, entry
            # subset by Bernoulli thinning to ~G (self entry always
            # included, as the scatter mode's score floor guarantees).
            if g >= s:
                keep = fresh
            else:
                fresh_cnt = fresh.sum(1, dtype=I32)
                p_keep = jnp.where(
                    fresh_cnt > 1,
                    (g - 1) / jnp.maximum(fresh_cnt - 1, 1).astype(jnp.float32),
                    1.0)
                u = rng.thin_u.reshape(n, s)
                keep = fresh & ((u < p_keep[:, None]) | is_self_slot)
            keep = keep & act[:, None]
            if cfg.shift_set:
                # Static-table shifts (SHIFT_SET): same per-tick key
                # stream, uniform over the K candidates; the delivery
                # below switches over K static-roll branches.
                table = shift_table(n, cfg.shift_set)
                shift_idx = rng.shift_draw
                shifts = jnp.asarray(table, I32)[shift_idx]
            else:
                shifts = rng.shift_draw
            cstride = STRIDE % s
            sent_gossip = jnp.zeros((n,), I32)
            recv_add = jnp.zeros((n,), I32)
            # Budget state (track_budget/budget/used/_budget_take) is
            # initialized before the join section: consumption order is
            # join control, gossip shifts, seed burst, probes.
            scenario_cuts_gossip = scenario is not None and (
                scenario.n_parts or scenario.n_flakes)
            if (cfg.fused_gossip and not use_drop
                    and not scenario_cuts_gossip and k_max > 0):
                # One Pallas traversal for all shifts (ops/fused_gossip):
                # mail is read+written once; sender rows arrive by
                # scalar-prefetch block indexing.  Counters reduce to a
                # per-row nonzero count times the clipped fanout — payload
                # is nonzero exactly where keep holds (kept slots are
                # present, and packed entries are > 0).
                payload = jnp.where(keep, view, U32(0))
                mail = gossip_fused(
                    n, s, k_max, jax.default_backend() != "tpu",
                    mail, payload, k_eff, shifts)
                c0 = keep.sum(1, dtype=I32)
                for j in range(k_max):
                    cnt = jnp.where(j < k_eff, c0, 0)
                    sent_gossip = sent_gossip + cnt
                    recv_add = recv_add + jnp.roll(cnt, shifts[j])
            elif cfg.fused_gossip and k_max > 0:
                # Lossy/scenario configs ride the SAME kernel with the
                # per-shift keep decisions as a stacked mask input
                # (ops/fused_gossip masks=...): the kernel cannot
                # replicate the host-RNG drop/flake streams, so each
                # shift's mask is computed outside with the EXACT draws
                # the jnp loop makes (same fold_in stream —
                # bit-exactness is the contract) and the kernel zeroes
                # non-kept sender entries in VMEM.  The payload stays the
                # SINGLE unmasked view: no [K, N, S] payload copies are
                # materialized, and the counters reduce over the masks
                # the step had to build anyway.
                masks = []
                for j in range(k_max):
                    m = keep & (j < k_eff)[:, None]
                    if scenario_cuts_gossip:
                        # Same per-SENDER-row cut/flake math as the jnp
                        # loop below — elementwise, no gather.
                        dst_g = jax.lax.rem(idx + shifts[j], n)
                    if scenario is not None and scenario.n_parts:
                        m = m & ~cross_group(cuts, idx, dst_g)[:, None]
                    if use_drop:
                        if scenario is not None:
                            p_g = site_p(t, idx, dst_g) \
                                if scenario.n_flakes else site_p(t, 0, 0)
                            p_gc = (p_g[:, None]
                                    if getattr(p_g, "ndim", 0) else p_g)
                            gossip_coin = (rng.gossip_u[j].reshape(n, s)
                                           < p_gc)
                        else:
                            gossip_coin = ((rng.gossip_u[j].reshape(n, s)
                                            < p_drop) & drop_active)
                        if cfg.telemetry:
                            telem_dropped.append(
                                (m & gossip_coin).sum(dtype=I32))
                        m = m & ~gossip_coin
                    masks.append(m)
                    cnt = m.sum(1, dtype=I32)
                    sent_gossip = sent_gossip + cnt
                    recv_add = recv_add + jnp.roll(cnt, shifts[j])
                mail = gossip_fused(
                    n, s, k_max, jax.default_backend() != "tpu",
                    mail, view, k_eff, shifts,
                    masks=jnp.stack(masks).astype(I32))
            else:
                for j in range(k_max):
                    m = keep & (j < k_eff)[:, None]
                    r = shifts[j]
                    if scenario is not None and (scenario.n_parts
                                                 or scenario.n_flakes):
                        # Shift j sends row i to row (i + r) mod n: the
                        # cross-group cut and any link-flake override
                        # are per-SENDER-row vectors — elementwise, no
                        # gather.
                        dst_g = jax.lax.rem(idx + r, n)
                    if scenario is not None and scenario.n_parts:
                        m = m & ~cross_group(cuts, idx, dst_g)[:, None]
                    if use_drop:
                        if scenario is not None:
                            p_g = site_p(t, idx, dst_g) \
                                if scenario.n_flakes else site_p(t, 0, 0)
                            p_gc = (p_g[:, None]
                                    if getattr(p_g, "ndim", 0) else p_g)
                            gossip_coin = (rng.gossip_u[j].reshape(n, s)
                                           < p_gc)
                        else:
                            gossip_coin = ((rng.gossip_u[j].reshape(n, s)
                                            < p_drop) & drop_active)
                        if cfg.telemetry:
                            telem_dropped.append(
                                (m & gossip_coin).sum(dtype=I32))
                        m = m & ~gossip_coin
                    if track_budget:
                        m, used = _budget_take(m, used)
                    payload = jnp.where(m, view, U32(0))
                    cnt = m.sum(1, dtype=I32)
                    if cfg.shift_set:
                        # lax.switch over K static-roll branches: every
                        # roll amount (row, column, wrapped column, AND
                        # the recv-count roll) is a Python int, so XLA
                        # lowers aligned copies instead of the dynamic
                        # misaligned lane rotate the node-minor layout
                        # forces (PERF.md 1M_s16).
                        delivered, cnt_r = jax.lax.switch(
                            shift_idx[j],
                            [(lambda pl, c, rv=rv: (
                                deliver_shift(pl, rv, n, s, cstride,
                                              idx),
                                jnp.roll(c, rv)))
                             for rv in table], payload, cnt)
                    else:
                        delivered = deliver_shift(payload, r, n, s,
                                                  cstride, idx)
                        cnt_r = jnp.roll(cnt, r)
                    mail = jnp.maximum(mail, delivered)
                    sent_gossip = sent_gossip + cnt
                    recv_add = recv_add + cnt_r
            sent_tick = sent_gossip + sent_req + sent_rep
        else:
            eligible = fresh & ~is_self_slot & act[:, None]
            in_seed = seeds[jnp.clip(cur_id, 0)] & present
            eligible = eligible.at[intro].set(
                eligible[intro] & ~in_seed[intro])
            tgt_slot, tgt_valid = sample_k_indices(
                k_targets, eligible, k_eff, k_max)
            tgt = jnp.take_along_axis(cur_id, tgt_slot, axis=1)

            if g >= s:
                e_ids, e_hbs, e_valid = cur_id, cur_hb, fresh
            else:
                scores = jnp.where(is_self_slot, -1.0,
                                   jax.random.uniform(k_entries, (n, s)))
                scores = jnp.where(fresh, scores, 2.0)
                _, e_idx = jax.lax.top_k(-scores, g)
                e_valid = jnp.take_along_axis(fresh, e_idx, axis=1)
                e_ids = jnp.take_along_axis(cur_id, e_idx, axis=1)
                e_hbs = jnp.take_along_axis(cur_hb, e_idx, axis=1)
            g_eff = e_ids.shape[1]

            msg_valid = tgt_valid[:, :, None] & e_valid[:, None, :]
            if use_drop:
                k_drop_f, k_drop_s = jax.random.split(k_drop)
                dropped = jax.random.bernoulli(k_drop_f, p_drop,
                                               (n, k_max, g_eff))
                msg_valid = msg_valid & ~(dropped & drop_active)
            else:
                k_drop_s = k_drop
            tgt_b = jnp.broadcast_to(tgt[:, :, None], (n, k_max, g_eff))
            mail = _scatter_msgs(
                cfg, mail, tgt_b,
                jnp.broadcast_to(e_ids[:, None, :], (n, k_max, g_eff)),
                jnp.broadcast_to(e_hbs[:, None, :], (n, k_max, g_eff)),
                msg_valid)
            sent_tick = msg_valid.sum((1, 2), dtype=I32) + sent_req + sent_rep
            recv_add = jnp.zeros((n + 1,), I32).at[
                jnp.where(tgt_valid, tgt, n).reshape(-1)
            ].add(msg_valid.sum(2, dtype=I32).reshape(-1), mode="drop")[:n]

        # Introducer burst to this tick's joiners (full fresh view).
        _, seed_idx = jax.lax.top_k(seeds.astype(I32), min(cfg.seed_cap, n))
        seed_valid = seeds[seed_idx] & seed_burst_on
        burst_valid = seed_valid[:, None] & fresh[intro][None, :]
        if scenario is not None and scenario.n_parts:
            # Introducer burst crossing a partition boundary is cut.
            burst_valid = burst_valid & ~cross_group(
                cuts, jnp.full_like(seed_idx, intro), seed_idx)[:, None]
        if use_drop:
            if scenario is not None:
                p_b = site_p(t, jnp.full_like(seed_idx, intro), seed_idx)
                p_bc = (p_b[:, None] if getattr(p_b, "ndim", 0) else p_b)
                dropped = rng.burst_u.reshape(seed_idx.shape[0], s) < p_bc
                if cfg.telemetry:
                    telem_dropped.append(
                        (burst_valid & dropped).sum(dtype=I32))
                burst_valid = burst_valid & ~dropped
            else:
                # Ring: the burst coin comes from the plan's k_drop
                # stream (the ring mode's k_drop_s == k_drop); scatter
                # keeps its split-off key.
                dropped = (rng.burst_u.reshape(seed_idx.shape[0], s)
                           < p_drop
                           if ring else
                           jax.random.bernoulli(k_drop_s, p_drop,
                                                (seed_idx.shape[0], s)))
                if cfg.telemetry:
                    telem_dropped.append(
                        (burst_valid & dropped
                         & drop_active).sum(dtype=I32))
                burst_valid = burst_valid & ~(dropped & drop_active)
        if track_budget:
            # One wire message per burst entry, after the gossip shifts
            # in the consumption order (the reference's introducer sends
            # its newNodes burst from the same sendMemberList phase).
            burst_valid, used = _budget_take(burst_valid, used)
        mail = _scatter_msgs(
            cfg, mail, jnp.broadcast_to(seed_idx[:, None], burst_valid.shape),
            jnp.broadcast_to(cur_id[intro][None, :], burst_valid.shape),
            jnp.broadcast_to(cur_hb[intro][None, :], burst_valid.shape),
            burst_valid)
        sent_tick = sent_tick.at[intro].add(burst_valid.sum(dtype=I32))
        recv_add = recv_add.at[seed_idx].add(
            burst_valid.sum(1, dtype=I32) * seed_valid.astype(I32))

        # ---- SWIM round-robin probing (see tpu_sparse docstring) ----
        probe_ids1, probe_ids2 = state.probe_ids1, state.probe_ids2
        act_prev = state.act_prev
        pfo = None   # FUSED_PROBE kernel outputs (consumed by the agg
        #              and telemetry blocks below when armed)
        if ring and cfg.probes > 0:
            # Issue this tick's probes: record the occupant ids of the
            # deterministic window (a cyclic P-column band) — the ack
            # pipeline above applies the refresh two ticks later.
            p_cnt = cfg.probes
            ptr = jax.lax.rem(t * p_cnt, s)
            # The window is a cyclic P-column band at a pointer that only
            # takes multiples of gcd(P, S): each switch branch is a
            # static roll + static slice (a contiguous copy when the
            # band doesn't wrap) instead of rolling the whole [N, S]
            # plane dynamically to read P columns.
            with jax.named_scope(PHASE_PROBE):
                if cfg.fused_probe:
                    # One Pallas traversal reads the post-receive planes
                    # once: rolled window ids come out pre-validated
                    # (occupied, not self, observer act) and the
                    # FastAgg/hist reductions ride as row partials
                    # (ops/fused_probe).  Scenario cuts and drop coins
                    # apply below in [N, P] space with the exact unfused
                    # streams — every suppressed position is consulted
                    # nowhere else, so the trajectory is bit-exact.
                    want_hist = cfg.telemetry and cfg.telemetry_hist
                    want_agg = cfg.fast_agg and not cfg.collect_events
                    pfo = probe_window_fused(
                        n, s, p_cnt, cfg.tfail,
                        cfg.fail_ids if want_agg else (),
                        want_hist, want_agg,
                        jax.default_backend() != "tpu",
                        t, ptr, jnp.zeros((), I32), view,
                        view_ts if want_hist else None, act,
                        rm_ids if want_agg else None)
                    window_ids = pfo["ids"][:, :p_cnt]
                    p_valid = window_ids > 0
                    w_id = jnp.where(p_valid,
                                     window_ids.astype(I32) - 1, 0)
                else:
                    window = ptr_switch(
                        ptr, p_cnt, s,
                        lambda o, v: jnp.roll(v, -o, axis=1)[:, :p_cnt],
                        view)
                    w_pres = window > 0
                    w_id = ((window - U32(1)) % U32(n)).astype(I32)
                    p_valid = (w_pres & (w_id != idx[:, None])
                               & act[:, None])
                if scenario is not None and scenario.n_parts:
                    # A probe to a node across the partition never
                    # arrives; cut it at issue time (like the drop
                    # coin), so the ack pipeline and counters only see
                    # surviving probes.
                    p_valid = p_valid & ~cross_group(cuts, idx[:, None],
                                                     w_id)
                if use_drop:
                    # Probe-leg drop at issue time (drop_active is the
                    # *current* window state, matching the scatter mode's
                    # timing); the dropped probe is never recorded, so
                    # counters and the ack pipeline both see only
                    # surviving probes.
                    if scenario is not None:
                        probe_coin = (rng.probe_u.reshape(p_valid.shape)
                                      < site_p(t, idx[:, None], w_id))
                    else:
                        probe_coin = ((rng.probe_u.reshape(p_valid.shape)
                                       < p_drop) & drop_active)
                    if cfg.telemetry:
                        telem_dropped.append(
                            (p_valid & probe_coin).sum(dtype=I32))
                    p_valid = p_valid & ~probe_coin
            if track_budget:
                # Probes queue after the gossip shifts; each costs p_red
                # wire messages.  A budget-dropped probe is never
                # recorded (like a coin-dropped one), so the ack pipeline
                # and counters stay consistent.
                pc = p_valid.sum(1, dtype=I32) * p_red
                starts = used + jnp.cumsum(pc) - pc
                accepted = jnp.clip(budget - starts, 0, pc) // p_red
                p_valid = p_valid & (
                    jnp.cumsum(p_valid.astype(I32), axis=1)
                    <= accepted[:, None])
                used = used + (accepted * p_red).sum(dtype=I32)
            ids_new = jnp.where(p_valid, w_id.astype(U32) + U32(1), U32(0))
            probe_ids2, probe_ids1 = probe_ids1, ids_new
            act_prev = act
            # p_red wire messages per surviving probe (see closure comment).
            sent_probes = p_valid.sum(1, dtype=I32) * p_red

            # ids1/v1/tgt1 were derived in the ack-candidate block above
            # (state.probe_ids1 — probes issued at t-1).
            if cfg.count_probe_io:
                # Exact per-node attribution: probes issued at t-1 arrive
                # at their targets now; targets that are act send acks —
                # the act-of-target filter rides the packed combined
                # gather (probe_bits1) on the default arm, its own
                # [N, P] gather on the split arm.
                ack_send = v1 & (act[tgt1] if probe_bits1 is None
                                 else _gathered_act(probe_bits1))
                recv_probe = jnp.zeros((n + 1,), I32).at[
                    jnp.where(v1, tgt1, n).reshape(-1)].add(
                        p_red, mode="drop")[:n]
                sent_ack = jnp.zeros((n + 1,), I32).at[
                    jnp.where(ack_send, tgt1, n).reshape(-1)].add(
                        1, mode="drop")[:n]
            elif cfg.probe_io_none:
                # PROFILING ONLY (PROBE_IO: none): zero the
                # probe-recv/ack-send counters — no per-target gather in
                # the tick (probe sends / ack recvs are still counted).
                recv_probe = jnp.zeros((n,), I32)
                sent_ack = jnp.zeros((n,), I32)
            elif cfg.probe_io_lag:
                # approx_lag: counts for arrivals at t-1, from the bits
                # that rode the ack gather (lag_bits — t-1 snapshots of
                # will_flush/act for the ids probed at t-2).  The recv
                # counts inject DIRECTLY into this tick's recv stream
                # (recv_direct, not pending_recv): exact mode's arrival
                # at tau flushes into the stream at tau+1, which is
                # exactly now — per-tick recv totals match exact, and
                # the stranded-final-arrival behavior matches too (see
                # run_scan's lag epilogue for the ack-send tail).
                # Per-NODE split caveat: these recvs credit the
                # prober's row with no _credit_orphan_recvs-style
                # re-credit, so a row exact mode would never credit
                # (e.g. a prober that failed between t-1 and now) can
                # carry probe recvs here.  The approx branch below
                # re-credits such orphans to a surviving row; the two
                # approximate modes therefore differ in per-node
                # attribution while agreeing on run and per-tick
                # totals (pinned in tests/test_probe_io.py).
                v2 = ids2 > 0
                recv_probe = jnp.zeros((n,), I32)
                recv_direct = (v2 & _gathered_flush(lag_bits)).sum(
                    1, dtype=I32) * p_red
                sent_ack = (v2 & _gathered_act(lag_bits)).sum(1, dtype=I32)
            else:
                # Scale mode: same global volume, attributed to the
                # prober's row (per-node probe recv/ack-send counters
                # would need full-width histograms — msgcount TOTALS stay
                # exact, the per-node split is approximate for probe
                # traffic; tests/test_probe_io.py pins the equality).
                # Ack sends take the exact branch's act[tgt] filter (a
                # dead target sends no ack); recv filtering and the
                # orphan re-credit live in _will_flush /
                # _credit_orphan_recvs.  The filter bits rode the packed
                # combined gather (probe_bits1) on the default arm; the
                # split arm gathers its own _pack_probe_bits table.
                if probe_bits1 is None:
                    will_flush = wf_now()
                    bits1 = _pack_probe_bits(will_flush, act)[tgt1]
                else:
                    bits1 = probe_bits1
                per_prober = (v1 & _gathered_flush(bits1)).sum(
                    1, dtype=I32) * p_red
                recv_probe = _credit_orphan_recvs(per_prober, will_flush)
                sent_ack = (v1 & _gathered_act(bits1)).sum(1, dtype=I32)
            sent_tick = sent_tick + sent_probes + sent_ack
            recv_add = recv_add + recv_probe + ack_recv_cnt
            if cfg.probe_io_lag:
                recv_tick = recv_tick + recv_direct
        elif cfg.probes > 0:
            ptr = jax.lax.rem(t * cfg.probes, s)
            off = jax.lax.rem(jnp.arange(s, dtype=I32) - ptr + 2 * s, s)
            sweep = off < cfg.probes
            p_valid = sweep[None, :] & present & ~is_self_slot & act[:, None]
            p_tgt = jnp.where(p_valid, cur_id, EMPTY)
            ack_ok = ack_valid & act[:, None]
            if use_drop:
                kd1, kd2 = jax.random.split(k_drop_p)
                p_valid = p_valid & ~(jax.random.bernoulli(
                    kd1, p_drop, p_valid.shape) & drop_active)
                ack_ok = ack_ok & ~(jax.random.bernoulli(
                    kd2, p_drop, ack_ok.shape) & drop_active)
            own_id_p = jnp.broadcast_to(idx[:, None], p_tgt.shape)
            own_hb_p = jnp.broadcast_to(own_hb[:, None], p_tgt.shape)
            # Probe: prober id into target's probe mailbox (salted hash) +
            # prober's own entry piggybacked into the gossip mailbox.
            qp = cfg.qp
            pval = jnp.where(p_valid, own_id_p.astype(U32) + U32(1), 0).reshape(-1)
            # Redundant probe transmission when the slot map is lossy
            # (qp < N, p_red from the closure): each probe is sent twice to
            # independently-hashed slots, squaring the per-cycle collision
            # loss (~3% → ~1e-3), so a TREMOVE-spanning run of consecutive
            # misses is negligible even over 1M nodes x 700 ticks.
            for c in range(p_red):
                paddr = p_tgt * qp + hash_slot(own_id_p, t + c * 0x2545F49,
                                               qp, n)
                paddr = jnp.where(p_valid, paddr, n * qp).reshape(-1)
                pmail = pmail.reshape(-1).at[paddr].max(
                    pval, mode="drop").reshape(n, qp)
            mail = _scatter_msgs(cfg, mail, p_tgt, own_id_p, own_hb_p, p_valid)
            # Ack: my (id, current hb) into each prober's ack channel — lands
            # at the prober's slot for me, the exact entry the probe
            # refreshes, with no gossip contention (module docstring).
            amail = _scatter_msgs(
                cfg, amail, ack_tgt, jnp.broadcast_to(idx[:, None], ack_tgt.shape),
                jnp.broadcast_to(own_hb[:, None], ack_tgt.shape), ack_ok)
            sent_tick = (sent_tick + p_valid.sum(1, dtype=I32) * p_red
                         + ack_ok.sum(1, dtype=I32))
            recv_add = recv_add + jnp.zeros((n + 1,), I32).at[
                jnp.where(p_valid, p_tgt, n).reshape(-1)].add(
                    p_red, mode="drop")[:n]
            recv_add = recv_add + jnp.zeros((n + 1,), I32).at[
                jnp.where(ack_ok, ack_tgt, n).reshape(-1)].add(1, mode="drop")[:n]

        pending_recv = pending_recv + recv_add

        if scenario is not None and scenario.has_updown:
            # Scenario transitions apply at the END of the tick (the
            # node acts through it — Application::fail timing).  A
            # restart brings the node back as a FRESH INCARNATION:
            # state wiped to empty (the receive pass re-seeds the self
            # slot next tick), heartbeat bumped past anything its old
            # incarnation ever gossiped so peers' sticky slots refresh.
            failed = (state.failed | down_now) & ~up_now
            rcol_r = up_now[:, None]
            view = jnp.where(rcol_r, U32(0), view)
            view_ts = jnp.where(rcol_r, 0, view_ts)
            mail = jnp.where(rcol_r, U32(0), mail)
            pending_recv = jnp.where(up_now, 0, pending_recv)
            self_hb = jnp.where(up_now,
                                jnp.maximum(self_hb, 2 * (t + 1)),
                                self_hb)
            if ring and cfg.probes > 0:
                probe_ids1 = jnp.where(rcol_r, U32(0), probe_ids1)
                probe_ids2 = jnp.where(rcol_r, U32(0), probe_ids2)
                act_prev = act_prev & ~up_now
        elif scenario is not None:
            failed = state.failed          # partition/flake-only: no
            #                                up/down machinery compiled
        else:
            failed = state.failed | (fail_mask & (t == fail_time))

        if cfg.collect_events:
            agg = state.agg
            out = SparseTickEvents(join_ids, rm_ids, sent_tick, recv_tick)
        else:
            # Scale path: fold events into O(N) on-device aggregates; emit
            # only per-tick scalars so stacked outputs stay O(T).
            if cfg.fast_agg:
                pre = None
                if pfo is not None and "rm_cnt" in pfo:
                    # Partials from the fused probe traversal: integer
                    # sums/ors are order-free, so these reduce bit-equal
                    # to the in-place plane passes they replace.
                    pre = {"rm_total": pfo["rm_cnt"].sum(dtype=I32)}
                    if cfg.fail_ids:
                        det_cols = pfo["det_cols"]
                        pre["det_tick"] = jnp.stack(
                            [d.sum(dtype=I32) for d in det_cols])
                        any_rm = det_cols[0][:, 0] > 0
                        for d in det_cols[1:]:
                            any_rm = any_rm | (d[:, 0] > 0)
                        pre["any_true_rm"] = any_rm
                agg = update_fast_agg(
                    state.agg, t=t, fail_ids=cfg.fail_ids,
                    join_events=join_mask, rm_ids=rm_ids,
                    view_ids=cur_id, view_present=present,
                    fail_time=fail_time, holder_failed=fail_mask,
                    sent_tick=sent_tick, recv_tick=recv_tick, pre=pre)
            else:
                agg = update_agg(
                    state.agg, t=t, join_ids=join_ids, rm_ids=rm_ids,
                    view_ids=cur_id, view_present=present,
                    fail_mask=fail_mask, fail_time=fail_time,
                    sent_tick=sent_tick, recv_tick=recv_tick)
            out = SparseTickEvents((join_ids != EMPTY).sum(dtype=I32),
                                   (rm_ids != EMPTY).sum(dtype=I32),
                                   sent_tick.sum(dtype=I32),
                                   recv_tick.sum(dtype=I32))
        wf_prev = wf_now() if cfg.probe_io_lag else state.wf_prev
        new_state = HashState(view, view_ts, started, in_group, failed,
                              self_hb, mail, amail, pmail, joinreq_infl,
                              joinrep_infl, pending_recv, agg,
                              probe_ids1, probe_ids2, act_prev, wf_prev)
        if cfg.telemetry:
            # Flight-recorder scalars (observability/timeline.py): pure
            # reductions over tensors computed above — no RNG, no state,
            # so the trajectory is bit-identical with telemetry off
            # (tests/test_timeline.py) and the off program never pays
            # for this block (tests/test_hlo_census.py).
            with jax.named_scope(PHASE_TELEMETRY):
                zero = jnp.zeros((), I32)
                dropped_tick = sum(telem_dropped, zero)
                # Per-tick TRUE detections as the agg delta (identical on
                # the FastAgg and AggStats paths; 0 in EVENT_MODE full
                # runs, where no on-device detection state exists).
                det_tick = (agg.det_count.sum(dtype=I32)
                            - state.agg.det_count.sum(dtype=I32)
                            if not cfg.collect_events else zero)
                telem = TickTelemetry(
                    live=act.sum(dtype=I32),
                    suspected=numfailed.sum(dtype=I32),
                    joins=(join_ids != EMPTY).sum(dtype=I32),
                    removals=(rm_ids != EMPTY).sum(dtype=I32),
                    detections=det_tick,
                    msgs_sent=sent_tick.sum(dtype=I32),
                    msgs_recv=recv_tick.sum(dtype=I32),
                    dropped=dropped_tick,
                    probe_acks=ack_recv_cnt.sum(dtype=I32),
                    gossip_rows=sent_gossip.sum(dtype=I32))
                if cfg.telemetry_hist:
                    # Distribution tier: bucketed one-hot reductions
                    # over the post-receive staleness/occupancy tensors
                    # (observability/timeline.py — shared builders, so
                    # all four twins emit bit-equal counts).  With
                    # FUSED_PROBE the staleness/suspicion counts arrive
                    # as row partials off the fused traversal instead of
                    # two more plane passes here.
                    stale = susp = None
                    if pfo is not None and "stale_rows" in pfo:
                        stale = pfo["stale_rows"].sum(axis=0)
                        susp = pfo["susp_rows"].sum(axis=0)
                    hist = build_tick_hist(
                        difft=difft, present=present, size=size,
                        act=act, t=t, fail_time=fail_time,
                        tfail=cfg.tfail, det_tick=det_tick,
                        dropped=dropped_tick, stale=stale, susp=susp)
                    return new_state, (out, (telem, hist))
            return new_state, (out, telem)
        return new_state, out

    return step


def make_config(params: Params, collect_events: bool = True,
                fail_ids: tuple = (), scenario=None) -> HashConfig:
    n = params.EN_GPSZ
    s = params.VIEW_SIZE if params.VIEW_SIZE > 0 else n
    g = params.GOSSIP_LEN if params.GOSSIP_LEN > 0 else s
    # Probe in-degree is ~2*PROBES transmissions in expectation (redundant
    # double-hash sends); 32x headroom keeps per-copy collision loss ~3%,
    # squared to ~1e-3 per cycle by the redundancy, so a TREMOVE-spanning
    # (>= 4-cycle, enforced by Params.validate) run of consecutive misses
    # is ~1e-12 per entry — zero expected even at 1M x 700.
    qp = n if n <= 1024 else max(128, 32 * params.PROBES)
    seed_cap = n if params.JOIN_MODE == "batch" else SEED_CAP
    exchange = params.resolved_exchange()
    if scenario is not None:
        # General-path scenarios (scenario/compile.py) are implemented
        # on the ring exchange of the hash twins; legacy-shaped
        # scenarios never reach here (they lower to a plain FailurePlan
        # and cfg.scenario stays None).
        if exchange != "ring":
            raise ValueError(
                "SCENARIO files with restart/partition/link_flake "
                "events require the ring exchange on the hash backends "
                "(EXCHANGE ring / the warm-join auto regime); the "
                "scatter lowering runs legacy-shaped scenarios only")
        if params.ENFORCE_BUFFSIZE:
            raise ValueError(
                "SCENARIO general events and ENFORCE_BUFFSIZE are "
                "incompatible (the sequential send budget does not "
                "model the per-shift partition/flake masks)")
    if params.PROBE_IO == "approx_lag" and exchange != "ring":
        # Loud-rejection policy of the off-path layouts (the sharded and
        # folded guards): on scatter the lag counting branch is
        # unreachable, so silently accepting the knob would hand back
        # exact counters while claiming the single-gather pipeline.
        raise ValueError(
            "PROBE_IO approx_lag requires EXCHANGE ring (scatter keeps "
            "exact slot-addressed counters)")
    # The scatter-free aggregate path needs the failed-id set statically
    # and does F elementwise passes per tick (observability/aggregates.py).
    fast_agg = (not collect_events and exchange == "ring"
                and len(fail_ids) <= FAST_AGG_MAX_FAILED)
    send_budget_req = params.EN_BUFFSIZE if params.ENFORCE_BUFFSIZE else 0
    # --- resolve the -1 (auto) fast-path knobs --------------------------
    # Auto turns a path on only when the process runs on a real TPU, the
    # config structurally supports it (same predicates the explicit-1
    # branches below enforce loudly), and the chip has banked bit-exact
    # evidence for the family (runtime/fusegate.py; fail closed).  Auto
    # never raises — an unsupported config quietly keeps the jnp path.
    fr_knob, fg_knob = params.FUSED_RECEIVE, params.FUSED_GOSSIP
    fp_knob, fold_knob = params.FUSED_PROBE, params.FOLDED
    if -1 in (fr_knob, fg_knob, fp_knob, fold_knob):
        from distributed_membership_tpu.backends.tpu_hash_folded import (
            folded_supported)
        from distributed_membership_tpu.runtime.fusegate import (
            banked_correctness, families_clean, on_tpu)
        # Auto enables only what the banked evidence actually proves.
        # scripts/tpu_correctness.py runs two arms on the chip: BACKEND
        # tpu_hash single-chip (bare families) and the same scans inside
        # shard_map over a one-device mesh ('sharded_' families — the
        # kernels' shard_map elaboration is different Mosaic; the
        # cross-chip ppermutes it cannot exercise are standard XLA
        # collectives).  Each backend's auto knobs unlock only on ITS
        # families; other backends never auto-enable.  Explicit 1 stays
        # available everywhere (validated loudly).
        pre = {"tpu_hash": "", "tpu_hash_sharded": "sharded_"}.get(
            params.BACKEND)
        eligible = on_tpu() and pre is not None
        rec = banked_correctness() if eligible else None
        cleared = lambda *fams: families_clean(  # noqa: E731
            rec, *(pre + f for f in fams))
        if fold_knob == -1:
            # SHIFT_SET is the NATURAL-layout roll experiment: auto must
            # keep the conflicting fast paths off rather than resolve
            # into the loud gates below ("auto never raises" — only
            # explicitly pinned knobs conflict loudly).  The service
            # daemon's snapshot decoder reads the NATURAL carry, so a
            # served run keeps auto-fold off too (config.validate
            # rejects the explicit pin loudly).
            fold_knob = int(
                not params.SHIFT_SET and params.SERVICE_PORT < 0
                and eligible and exchange == "ring"
                and params.JOIN_MODE == "warm" and fast_agg
                and folded_supported(n, s, params.PROBES)
                and send_budget_req == 0
                and cleared(f"folded_s{s}"))
        if fold_knob and 0 < s < 128:
            # Folded planes: the fused twins ship as one pair, gated on
            # the folded_fused family at this fold factor.
            kernels_ok = (eligible and (n * s) // 128 >= 8
                          and cleared(f"folded_fused_s{s}"))
            if fr_knob == -1:
                fr_knob = int(kernels_ok)
            if fg_knob == -1:
                # The gossip kernel conflicts with SHIFT_SET (loud
                # gate); auto must keep it off rather than resolve into
                # the error.  Drops and scenario flakes are fine — the
                # stacked kernel takes per-shift masks/payloads.
                fg_knob = int(kernels_ok and not params.SHIFT_SET)
            if fp_knob == -1:
                fp_knob = int(
                    eligible and (n * s) // 128 >= 8
                    and 0 < params.PROBES < s
                    and cleared(f"folded_fused_probe_s{s}"))
        else:
            if fr_knob == -1:
                fr_knob = int(
                    eligible and exchange == "ring"
                    and fused_supported(n, s)
                    and cleared("fused_receive", "fused_both"))
            if fg_knob == -1:
                # Drop-free configs run the single-payload kernel;
                # lossy/flaky ones the masks-as-inputs stacked variant —
                # each auto-enables only on ITS OWN banked hardware
                # family (fail closed).  A general scenario takes the
                # masks path unconditionally (its cut/flake masks are
                # per shift).
                fg_knob = int(
                    not params.SHIFT_SET
                    and eligible and exchange == "ring"
                    and gossip_fused_supported(n, s)
                    and send_budget_req == 0
                    and (cleared("fused_gossip", "fused_both")
                         if (params.effective_drop_prob() == 0
                             and scenario is None)
                         else cleared("fused_gossip_drops")))
            if fp_knob == -1:
                fp_knob = int(
                    eligible and exchange == "ring"
                    and probe_fused_supported(n, s, params.PROBES)
                    and cleared("fused_probe"))
    fused = bool(fr_knob)
    if fused and exchange != "ring":
        raise ValueError("FUSED_RECEIVE requires the ring exchange")
    fused_g = bool(fg_knob)
    if fused_g and exchange != "ring":
        raise ValueError("FUSED_GOSSIP requires the ring exchange")
    fused_p = bool(fp_knob)
    if fused_p and (exchange != "ring" or params.PROBES <= 0):
        raise ValueError(
            "FUSED_PROBE requires the ring exchange with PROBES > 0")
    folded = bool(fold_knob)
    if folded:
        from distributed_membership_tpu.backends.tpu_hash_folded import (
            folded_supported)
        if exchange != "ring" or params.JOIN_MODE != "warm":
            raise ValueError(
                "FOLDED requires EXCHANGE ring and JOIN_MODE warm")
        if collect_events:
            raise ValueError(
                "FOLDED requires aggregate events (EVENT_MODE agg)")
        if not folded_supported(n, s, params.PROBES):
            raise ValueError(
                f"FOLDED needs 0 < VIEW_SIZE < 128 dividing 128, N a "
                f"multiple of 128/VIEW_SIZE, and PROBES dividing 128 "
                f"(got N={n}, S={s}, P={params.PROBES})")
        if not fast_agg:
            raise ValueError(
                "FOLDED requires the FastAgg event path (a static failed "
                f"set of at most {FAST_AGG_MAX_FAILED} ids)")
        # Folded planes are [N*S/128, 128]: the minormost axis is already
        # exactly 128 lanes, so the FUSED_* kernels apply on their folded
        # twins (ops/fused_folded) — including, for gossip, under drops
        # (the stacked-payload kernel takes pre-masked payloads).  The
        # only extra requirement is the row-block tiling minimum.
        if (fused or fused_g or fused_p) and (n * s) // 128 < 8:
            raise ValueError(
                f"FOLDED FUSED_* kernels need at least 8 plane rows "
                f"(N*VIEW_SIZE/128 >= 8; got N={n}, S={s})")
        if fused_p and not 0 < params.PROBES < s:
            raise ValueError(
                f"FUSED_PROBE needs 0 < PROBES < VIEW_SIZE "
                f"(got PROBES={params.PROBES}, S={s})")
    else:
        if fused and not fused_supported(n, s):
            raise ValueError(
                f"FUSED_RECEIVE needs VIEW_SIZE % 128 == 0 and N >= 8 "
                f"(got N={n}, S={s}); for S < 128 combine it with FOLDED")
        if fused_g and not gossip_fused_supported(n, s):
            raise ValueError(
                f"FUSED_GOSSIP needs VIEW_SIZE % 128 == 0 and "
                f"(N*STRIDE) % VIEW_SIZE == 0 (got N={n}, S={s}); for "
                f"S < 128 combine it with FOLDED")
        if fused_p and not probe_fused_supported(n, s, params.PROBES):
            raise ValueError(
                f"FUSED_PROBE needs VIEW_SIZE % 128 == 0, N >= 8 and "
                f"0 < PROBES < VIEW_SIZE (got N={n}, S={s}, "
                f"P={params.PROBES}); for S < 128 combine it with FOLDED")
    # --- multi-tick residency (MEGA_TICKS / MEGA_PACK) ------------------
    # Params.validate already enforced the cheap invariants (backend
    # family, CHECKPOINT_EVERY > 0, K % T == 0); here the resolved
    # exchange gates the pinned knob loudly and auto resolves against
    # the banked per-T hardware families, mirroring the FUSED_* block.
    mega_knob = params.MEGA_TICKS
    if mega_knob == -1:
        mega_knob = 0
        from distributed_membership_tpu.runtime.fusegate import (
            banked_correctness, families_clean, on_tpu)
        pre_m = {"tpu_hash": "", "tpu_hash_sharded": "sharded_"}.get(
            params.BACKEND)
        if (on_tpu() and pre_m is not None and exchange == "ring"
                and params.CHECKPOINT_EVERY > 0):
            rec_m = banked_correctness()
            for t in MEGA_AUTO_TICKS:
                # Largest banked block size that tiles the segment wins;
                # a chip without a mega_t{T} verdict keeps the per-tick
                # scan (fail closed, auto never raises).
                if (params.CHECKPOINT_EVERY % t == 0
                        and families_clean(rec_m, f"{pre_m}mega_t{t}")):
                    mega_knob = t
                    break
    mega = int(mega_knob)
    if mega > 0 and exchange != "ring":
        raise ValueError(
            "MEGA_TICKS requires the ring exchange (the scatter "
            "lowering keeps the per-tick scan)")
    mp_knob = params.MEGA_PACK
    if mp_knob == -1:
        # Auto packs exactly when the static 16-bit bound is proven for
        # the declared run length; run_scan re-proves it against the
        # effective total (a longer total_time override widens an auto
        # pack silently, raises on a pinned one).
        mp_knob = int(mega > 1 and pack_fits(params.TOTAL_TIME))
    elif mp_knob == 1:
        if mega <= 1:
            raise ValueError(
                "MEGA_PACK: 1 requires MEGA_TICKS >= 2 (resolved "
                f"T={mega}: no T-block boundary exists to shrink)")
        if not pack_fits(params.TOTAL_TIME):
            raise ValueError(
                f"MEGA_PACK: 1 cannot prove the 16-bit carry bound for "
                f"TOTAL_TIME={params.TOTAL_TIME} (heartbeats/timestamps "
                f"must stay under 2**16 after the +1 sentinel offset: "
                f"at most {_MEGA_PACK_SAFE} ticks — "
                "ops/megakernel.PACK_SAFE_TICKS); use MEGA_PACK 0 or "
                "-1 (auto widens to the full-width carry)")
    # --- pod-scale exchange wire (EXCHANGE_MODE) ------------------------
    # Batching exists only where the gossip shifts cross shards: the
    # sharded ring step.  The single-chip ring twins have no exchange
    # collective, so the knob is structurally inert there (a pinned
    # 'batched' run is trivially bit-exact with legacy) — inert, not an
    # error, so one conf can drive all four ring twins (the
    # tests/test_exchange.py pin matrix).  Pinned 'batched' on a scatter
    # lowering raises loudly (nothing to batch); auto resolves batched
    # only on a real TPU with the banked exchange family for this layout
    # (fail closed, exactly the FUSED_*/MEGA posture above).
    xm_knob = params.EXCHANGE_MODE
    batched_x = False
    if params.BACKEND == "tpu_hash_sharded":
        if xm_knob == "batched":
            if exchange != "ring":
                raise ValueError(
                    "EXCHANGE_MODE batched requires the ring exchange on "
                    "tpu_hash_sharded (the scatter lowering has no "
                    "per-shift collective round to batch)")
            batched_x = True
        elif xm_knob == "-1" and exchange == "ring":
            from distributed_membership_tpu.runtime.fusegate import (
                banked_correctness, families_clean, on_tpu)
            if on_tpu():
                batched_x = families_clean(
                    banked_correctness(),
                    "sharded_folded_exchange_batched" if folded
                    else "sharded_exchange_batched")
    if params.SHIFT_SET:
        # Loud-rejection policy (same as PROBE_IO approx_lag): off-path
        # layouts must not silently ignore the knob.
        if exchange != "ring":
            raise ValueError("SHIFT_SET requires the ring exchange")
        if params.BACKEND != "tpu_hash":
            raise ValueError(
                "SHIFT_SET is single-chip tpu_hash only (the sharded "
                "step's local rolls + collectives are a different "
                "lowering; measure the mitigation single-chip first)")
        if fused_g:
            raise ValueError(
                "SHIFT_SET and FUSED_GOSSIP are incompatible (the "
                "Pallas kernel rolls in VMEM — dynamic shifts are not "
                "its bottleneck)")
        if n <= params.SHIFT_SET:
            raise ValueError(
                f"SHIFT_SET ({params.SHIFT_SET}) must be < N ({n})")
    send_budget = send_budget_req
    if send_budget:
        if exchange != "ring":
            raise ValueError(
                "ENFORCE_BUFFSIZE on tpu_hash requires the ring exchange "
                "(the emul backends enforce the cap natively; the scatter "
                "lowering does not model it — README fidelity notes)")
        if params.BACKEND == "tpu_hash_sharded":
            raise ValueError(
                "ENFORCE_BUFFSIZE is not modeled on tpu_hash_sharded "
                "(its scatter exchange bounds per-destination buckets "
                "instead — bucket_capacity; README fidelity notes)")
        # Cold joins (JOIN_MODE staggered/batch) ARE budgeted since
        # round 5: JOINREQ/JOINREP and the introducer seed burst consume
        # the same per-tick budget (join control first, then gossip,
        # burst, probes), with drop-forever semantics matching the
        # reference's retry-free join handshake — join storms over the
        # cap permanently strand late joiners, the regime where the
        # reference's 30k cap binds (EmulNet.cpp:87-94).
        if folded:
            raise ValueError(
                "ENFORCE_BUFFSIZE is not modeled on the FOLDED layout")
        if fused_g:
            raise ValueError(
                "ENFORCE_BUFFSIZE and FUSED_GOSSIP are incompatible (the "
                "budget is a per-slot send mask; the natural-layout kernel "
                "applies its fanout mask in-kernel)")
    return HashConfig(
        n=n, s=s, g=min(g, s), tfail=params.TFAIL, tremove=params.TREMOVE,
        fanout=params.FANOUT,
        drop_prob=params.effective_drop_prob(),
        probes=params.PROBES, qp=qp, seed_cap=seed_cap,
        collect_events=collect_events, exchange=exchange,
        fail_ids=tuple(fail_ids) if fast_agg else (),
        fast_agg=fast_agg,
        count_probe_io=(n <= PROBE_IO_EXACT_MAX
                        if params.PROBE_IO == "auto"
                        else params.PROBE_IO == "exact"),
        probe_io_none=params.PROBE_IO == "none",
        probe_io_lag=params.PROBE_IO == "approx_lag",
        fused_receive=fused, fused_gossip=fused_g, fused_probe=fused_p,
        folded=folded, batched_exchange=batched_x,
        mega_ticks=mega, mega_pack=bool(mp_knob),
        send_budget=send_budget, shift_set=params.SHIFT_SET,
        # Normalized so configs whose lowering cannot differ share one
        # compiled runner: non-ring paths keep site-local draws
        # ('scattered'); probe_gather only exists with ring probes, and
        # n < 4 lacks the packed table's 30-bit hb headroom
        # (_pack_probe_table), so those pin 'split'/'packed' defaults.
        rng_mode=params.RNG_MODE if exchange == "ring" else "scattered",
        probe_gather=(params.PROBE_GATHER
                      if exchange == "ring" and params.PROBES > 0
                      and n >= 4 else
                      "split" if n < 4 else "packed"),
        telemetry=params.TELEMETRY in ("scalars", "hist"),
        telemetry_hist=params.TELEMETRY == "hist",
        scenario=scenario)


def resolve_mega_pack(cfg: HashConfig, params: Params,
                      total: int) -> HashConfig:
    """Re-prove the shrunk-carry bound against the EFFECTIVE run length
    (run_scan's ``total_time`` override can exceed the TOTAL_TIME that
    make_config proved the bound for).  Auto widens silently to the
    full-width carry; a pinned ``MEGA_PACK: 1`` raises.  This host-side
    static variant selection IS the codec's overflow widening: the
    packed and wide programs are separate compiled runners (cfg is the
    cache key), chosen by the proven tick bound, both bit-exact."""
    if not cfg.mega_pack or pack_fits(total):
        return cfg
    if params.MEGA_PACK == 1:
        raise ValueError(
            f"MEGA_PACK: 1 cannot prove the 16-bit carry bound for the "
            f"effective run length {total} (at most {_MEGA_PACK_SAFE} "
            "ticks — ops/megakernel.PACK_SAFE_TICKS); use MEGA_PACK 0 "
            "or -1 (auto widens to the full-width carry)")
    return dataclasses.replace(cfg, mega_pack=False)


_RUNNER_CACHE: dict = {}


def _get_runner(cfg: HashConfig, warm: bool):
    cache_key = (cfg, warm)
    if cache_key not in _RUNNER_CACHE:
        if cfg.rng_mode == "hoisted":
            raise ValueError(
                "RNG_MODE hoisted pre-draws per CHECKPOINT_EVERY segment "
                "— it has no monolithic-scan runner (config.validate "
                "enforces CHECKPOINT_EVERY > 0)")
        step, init = _get_step_and_init(cfg, warm)

        def run(keys, ticks, start_ticks, fail_mask, fail_time,
                drop_lo, drop_hi, warm_key, *extra):
            # *extra carries the scenario tensor plan when cfg.scenario
            # is set (scenario/compile.ScenarioTensors — scan-invariant
            # inputs, exactly like the failure schedule).
            state0 = init(warm_key)

            def body(state, inp):
                t, k = inp
                return step(state, (t, k, start_ticks, fail_mask,
                                    fail_time, drop_lo, drop_hi) + extra)

            final, ys = jax.lax.scan(body, state0, (ticks, keys))
            telem = None
            if cfg.telemetry:
                # The telemetry series rides beside the event outputs;
                # the lag epilogue below touches run TOTALS only (the
                # timeline keeps the in-scan per-tick counters —
                # observability/timeline.py field notes).
                ys, telem = ys
            if cfg.probe_io_lag and cfg.probes > 0:
                # Lag tail, ON-DEVICE inside the same jit (one [N, P]
                # gather per RUN — amortized to nothing; a host epilogue
                # here would bias any timed caller and be skipped by
                # direct-runner drivers): the delayed counters cover ack
                # sends for arrivals 0..T-2; the final tick's (probes
                # issued T-2 arriving T-1, still in the final
                # probe_ids2/act_prev snapshots) are added so run totals
                # equal exact mode's.  Recv needs no tail: exact mode's
                # final-tick arrival counts strand in pending_recv and
                # never reach the stream either.
                ids2f = final.probe_ids2
                corr = ((ids2f > 0) & final.act_prev[
                    jnp.clip(ids2f.astype(I32) - 1, 0)]).sum(1, dtype=I32)
                if cfg.collect_events:
                    ys = ys._replace(sent=ys.sent.at[-1].add(corr))
                else:
                    final = final._replace(agg=final.agg._replace(
                        sent_total=final.agg.sent_total + corr))
                    ys = ys._replace(sent=ys.sent.at[-1].add(
                        corr.sum(dtype=I32)))
            return final, ((ys, telem) if cfg.telemetry else ys)

        _RUNNER_CACHE[cache_key] = jax.jit(run)
    return _RUNNER_CACHE[cache_key]


def _get_step_and_init(cfg: HashConfig, warm: bool):
    """(step, init(warm_key)) for the natural or folded layout — the
    single source both the whole-run and segment runners build from."""
    if cfg.folded and cfg.probe_io_lag:
        raise ValueError(
            "PROBE_IO approx_lag requires the natural layout "
            "(FOLDED: 0) — the folded step keeps the two-gather "
            "attribution")
    if cfg.folded:
        from distributed_membership_tpu.backends.tpu_hash_folded import (
            init_state_warm_folded, make_folded_step)
        return (make_folded_step(cfg),
                lambda warm_key: init_state_warm_folded(cfg, warm_key))
    return (make_step(cfg),
            lambda warm_key: (init_state_warm(cfg, warm_key) if warm
                              else init_state(cfg)))


def _get_segment_runner(cfg: HashConfig, warm: bool):
    """Chunked-scan twin of :func:`_get_runner`: the carry is an argument,
    so the run can stop at any segment boundary and continue bit-exactly
    (runtime/checkpoint.py).  probe_io_lag composes since round 6: its
    state (probe_ids/act_prev/wf_prev) rides the checkpointed carry, and
    the run-total counter epilogue is applied by run_scan's finalize
    hook after the last segment.

    With ``RNG_MODE: hoisted`` the whole segment's random material is
    pre-drawn OUTSIDE the scan as ``[K, ...]`` tensors
    (vmap of the same per-tick builder the inline step uses —
    _ring_rng_builder, so the streams are bit-identical) and the scan
    consumes slices: RNG leaves the per-tick critical path entirely."""
    cache_key = (cfg, warm, "segment")
    if cache_key not in _RUNNER_CACHE:
        step, _ = _get_step_and_init(cfg, warm)
        hoist = cfg.rng_mode == "hoisted"
        if hoist and cfg.exchange != "ring":
            raise ValueError("RNG_MODE hoisted requires the ring exchange")
        # use_drop must match the step's own formula (a scenario with
        # drop windows/flakes arms the coin streams even when the conf
        # drop prob is 0) — otherwise the hoisted pre-draw would build a
        # plan missing the streams the step consumes.
        seg_use_drop = (cfg.drop_prob > 0.0
                        or (cfg.scenario is not None
                            and cfg.scenario.has_drop))
        build = _ring_rng_builder(cfg, seg_use_drop) if hoist else None

        def run_seg(state, ticks, keys, start_ticks, fail_mask, fail_time,
                    drop_lo, drop_hi, *extra):
            xs = (ticks, jax.vmap(build)(keys)) if hoist else (ticks, keys)

            def body(state, inp):
                t, k = inp
                return step(state, (t, k, start_ticks, fail_mask,
                                    fail_time, drop_lo, drop_hi) + extra)

            # MEGA_TICKS >= 2 restructures the segment into T-tick
            # blocks (carry resident across the inner loop, shrunk at
            # block boundaries under mega_pack); T <= 1 IS the plain
            # scan below — op-count identical (ops/megakernel.py).
            if cfg.mega_ticks > 1:
                return mega_scan(body, state, xs, cfg.mega_ticks,
                                 cfg.mega_pack)
            return jax.lax.scan(body, state, xs)

        _RUNNER_CACHE[cache_key] = jax.jit(run_seg)
    return _RUNNER_CACHE[cache_key]


def plan_fail_ids(plan: FailurePlan) -> tuple:
    """The static failed-id list make_config needs for the FastAgg path.

    Single-sourced so external profilers (scripts/profile_step.py --cost)
    construct EXACTLY the config run_scan runs — a drifted copy once made
    the analyzed program differ from the timed one (ADVICE r2)."""
    return tuple(plan.failed_indices) if plan.fail_time is not None else ()


def run_scan(params: Params, plan: FailurePlan, seed: int,
             collect_events: bool = True, total_time: Optional[int] = None,
             telemetry=None):
    """Run the full simulation; returns (final_state, events).

    ``telemetry`` (a TimelineRecorder, observability/timeline.py) receives
    the per-tick scalar series when ``TELEMETRY: scalars`` is on (a
    ``(scalars, hist)`` pair under ``TELEMETRY: hist``) — per segment
    boundary on the chunked path, once at the end of a monolithic scan.
    With telemetry on and no recorder the series is computed and dropped
    (the bench's overhead legs time exactly this)."""
    scn_prog = getattr(plan, "scenario", None)
    cfg = make_config(params, collect_events, fail_ids=plan_fail_ids(plan),
                      scenario=None if scn_prog is None
                      else scn_prog.static)
    scn_extra = () if scn_prog is None else (scn_prog.tensors(),)
    total = total_time if total_time is not None else params.TOTAL_TIME
    # Same effective-run-length packing guard as tpu_sparse.run_scan.
    params.validate_sparse_packing(total)
    cfg = resolve_mega_pack(cfg, params, total)
    warm = params.JOIN_MODE == "warm"

    if params.CHECKPOINT_EVERY > 0:
        from distributed_membership_tpu.runtime.checkpoint import (
            chunked_run, compact_sparse)
        _, init = _get_step_and_init(cfg, warm)
        warm_key = make_run_key(params, seed ^ 0x5EED)
        finalize = None
        if cfg.probe_io_lag and cfg.probes > 0:
            def finalize(carry, acc):
                """Host-side twin of _get_runner's on-device lag tail:
                the final tick's ack sends (probes issued T-2 arriving
                T-1, still in the final probe_ids2/act_prev snapshots)
                are added so run totals equal exact mode's.  Applied by
                the chunked driver after the LAST segment — the carry
                snapshots on disk stay pre-epilogue, so a resumed run
                applies it exactly once (tests/test_checkpoint.py)."""
                ids2f = np.asarray(carry.probe_ids2).astype(np.int64)
                act_prev = np.asarray(carry.act_prev)
                corr = ((ids2f > 0) & act_prev[
                    np.clip(ids2f - 1, 0, None)]).sum(1).astype(np.int32)
                if collect_events:
                    sent = acc.sent.copy()
                    sent[-1] = sent[-1] + corr
                    acc = acc._replace(sent=sent)
                else:
                    carry = carry._replace(agg=carry.agg._replace(
                        sent_total=np.asarray(carry.agg.sent_total)
                        + corr))
                    sent = acc[2].copy()         # SparseTickEvents.sent
                    sent[-1] += int(corr.sum())
                    acc = (acc[0], acc[1], sent, acc[3])
                return carry, acc
        return chunked_run(
            params, plan, seed, total,
            init_carry=lambda: init(warm_key),
            segment_fn=_get_segment_runner(cfg, warm),
            collect_events=collect_events,
            compact_fn=compact_sparse if collect_events else None,
            event_type=None if collect_events else SparseTickEvents,
            finalize=finalize,
            extra_inputs=scn_extra,
            telemetry_sink=(
                (telemetry.flush if telemetry is not None
                 else lambda telem, t0: None) if cfg.telemetry else None))

    (ticks, keys, start_ticks, fail_mask, fail_time,
     drop_lo, drop_hi) = plan_tensors(params, plan, seed, total)

    run = _get_runner(cfg, warm)
    final_state, events = run(
        keys, ticks, start_ticks, fail_mask, fail_time, drop_lo, drop_hi,
        make_run_key(params, seed ^ 0x5EED), *scn_extra)
    events = jax.tree.map(np.asarray, events)
    if cfg.telemetry:
        events, telem = events
        if telemetry is not None:
            telemetry.flush(telem, 0)
    return final_state, events


@register("tpu_hash")
def run_tpu_hash(params: Params, log: Optional[EventLog] = None,
                 seed: Optional[int] = None) -> RunResult:
    t0 = _time.time()
    seed = params.SEED if seed is None else seed
    log = log if log is not None else EventLog()
    plan = resolve_plan(params, _pyrandom.Random(f"app:{seed}"))

    return finish_run(params, plan, log, run_scan, t0, seed)
