"""`tpu_sharded` backend: the node axis sharded over a device mesh.

Same protocol, same tick semantics as the dense `tpu` backend
(backends/tpu.py — see its docstring for the exactness argument), with the
``[N, N]`` state row-sharded over a 1-D :class:`jax.sharding.Mesh`: shard
``s`` owns nodes ``[s*L, (s+1)*L)`` — their member-list rows, in-flight
buffers, and scalar per-node state.  The whole 700-tick ``lax.scan`` runs
*inside* one ``shard_map`` call, so state never leaves the devices and each
tick's cross-shard traffic is exactly two collectives:

  * gossip delivery: each shard max-reduces its local senders' contributions
    into a partial ``[N, E]`` tensor, then a **ppermute ring reduce-scatter
    (max)** delivers each receiver-row block to its owner shard
    (parallel/collectives.py — bandwidth-optimal on ICI, the TPU-native
    replacement for the reference's global EmulNet mailbox);
  * message counts: a sum reduce-scatter (``lax.psum_scatter``).

Plus a handful of tiny ``[N]``-bool ``all_gather``s for the join handshake
(the introducer needs the global JOINREQ view; everyone needs the
introducer's liveness bit).

RNG discipline: by default the target-sampling scores are drawn *per shard*
([L, N], keys folded by shard index), so per-tick per-shard FLOPs and
memory scale as N^2/S.  The ``replicated_rng`` debug mode draws the full
[N, N] replicated and row-slices, making drop-free trajectories
bit-identical to the dense backend's and invariant to mesh size
(tests/test_sharded.py) — the sharding-changes-nothing proof.  Per-message
gossip drops are always shard-decorrelated and match distributionally.
"""

from __future__ import annotations

import functools
import random as _pyrandom
import time as _time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from distributed_membership_tpu.parallel import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from distributed_membership_tpu.addressing import INTRODUCER_INDEX
from distributed_membership_tpu.backends import RunResult, register
from distributed_membership_tpu.backends.tpu import (
    I32, State, StepConfig, TickEvents, events_to_log)
from distributed_membership_tpu.config import Params
from distributed_membership_tpu.eventlog import EventLog
from distributed_membership_tpu.ops.merge import broadcast_deliver, fanout_deliver_indexed
from distributed_membership_tpu.ops.sampling import sample_k_indices
from distributed_membership_tpu.parallel.collectives import (
    all_gather_vec, reduce_scatter_sum, ring_reduce_scatter_max)
from distributed_membership_tpu.parallel.mesh import NODE_AXIS, make_mesh
from distributed_membership_tpu.runtime.failures import plan_tensors, resolve_plan

INTRO = INTRODUCER_INDEX


def make_sharded_step(cfg: StepConfig, n_local: int,
                      replicated_rng: bool = False):
    """Per-tick transition over shard-local state.

    Shapes inside shard_map: matrices ``[L, N]`` (this shard's rows of the
    global ``[N, N]``), per-node vectors ``[L]``.  ``row0`` is this shard's
    first global row index.

    ``replicated_rng`` is the bit-parity debug mode: every shard draws the
    full ``[N, N]`` score tensor with the same key and slices its rows, so
    the trajectory is bit-identical to the dense backend (and invariant to
    mesh size) — at the cost of O(N^2) per-shard work.  The default draws
    per-shard ``[L, N]`` scores (same distribution, keys folded by shard),
    so per-tick per-shard FLOPs and memory scale as N^2/S.
    """
    n = cfg.n

    def step(state: State, inputs):
        t, key, start_ticks_g, fail_mask_l, fail_time, drop_lo, drop_hi = inputs
        k_targets, k_drop, k_ctrl = jax.random.split(key, 3)
        me = lax.axis_index(NODE_AXIS)
        row0 = me * n_local
        lrows = row0 + jnp.arange(n_local)          # global ids of local rows
        start_ticks_l = lax.dynamic_slice(start_ticks_g, (row0,), (n_local,))
        col_ids = jnp.arange(n)

        drop_active = (t > drop_lo) & (t <= drop_hi)
        if cfg.drop_prob > 0.0:  # replicated draw — identical on every shard
            ctrl_kept_g = ~(jax.random.bernoulli(k_ctrl, cfg.drop_prob, (2, n))
                            & drop_active)
        else:
            ctrl_kept_g = jnp.ones((2, n), bool)

        # ---- delivery & merge (local rows only) ----
        recv_mask = state.started & (t > start_ticks_l) & ~state.failed
        deliver = state.infl_has & recv_mask[:, None]
        newly = deliver & ~state.present
        upd = deliver & state.present & (state.infl_hb > state.hb)
        present = state.present | newly
        hb = jnp.where(newly | upd, state.infl_hb, state.hb)
        ts = jnp.where(newly | upd, t, state.ts)
        infl_has = state.infl_has & ~recv_mask[:, None]
        infl_hb = jnp.where(recv_mask[:, None], -1, state.infl_hb)
        join_events = newly

        recv_tick = jnp.where(recv_mask, state.pending_recv, 0)
        pending_recv = jnp.where(recv_mask, 0, state.pending_recv)

        in_group = state.in_group | (state.joinrep_infl & recv_mask)
        joinrep_infl = state.joinrep_infl & ~recv_mask

        # ---- join handshake: needs the global view of tiny vectors ----
        # recv eligibility of the introducer (lives on shard 0).
        started_g = all_gather_vec(state.started, NODE_AXIS)
        failed_g = all_gather_vec(state.failed, NODE_AXIS)
        in_group_g = all_gather_vec(in_group, NODE_AXIS)
        intro_recv = (started_g[INTRO] & (t > start_ticks_g[INTRO])
                      & ~failed_g[INTRO])
        joinreq_g = all_gather_vec(state.joinreq_infl, NODE_AXIS)
        seeds_g = joinreq_g & intro_recv
        joinreq_l = state.joinreq_infl & ~intro_recv
        rep_ok_g = seeds_g & ctrl_kept_g[1]
        joinrep_infl = joinrep_infl | lax.dynamic_slice(rep_ok_g, (row0,), (n_local,))
        n_seeds = seeds_g.sum(dtype=I32)
        sent_rep = jnp.where((lrows == INTRO) & intro_recv,
                             rep_ok_g.sum(dtype=I32), 0)
        pending_recv = pending_recv + lax.dynamic_slice(
            rep_ok_g, (row0,), (n_local,)).astype(I32)

        # ---- nodeStart ----
        start_now_l = t == start_ticks_l
        started = state.started | start_now_l
        boot = (t == start_ticks_g[INTRO])
        is_intro_row = lrows == INTRO
        intro_diag = is_intro_row[:, None] & (col_ids == INTRO)[None, :]
        present = jnp.where(intro_diag & boot, True, present)
        hb = jnp.where(intro_diag & boot, 0, hb)
        ts = jnp.where(intro_diag & boot, t, ts)
        in_group = in_group | (is_intro_row & boot)

        # JOINREQs: visible to all shards from the static schedule +
        # replicated drop coins; shard 0 merges them into the introducer's
        # in-flight row, every shard updates its own joiners' pending flags.
        start_now_g = t == start_ticks_g
        joiner_req_g = start_now_g & (col_ids != INTRO) & ctrl_kept_g[0]
        req_row = is_intro_row[:, None] & joiner_req_g[None, :]
        infl_has = infl_has | req_row
        infl_hb = jnp.where(req_row, jnp.maximum(infl_hb, 0), infl_hb)
        joinreq_infl = joinreq_l | (start_now_l & (lrows != INTRO)
                                    & lax.dynamic_slice(ctrl_kept_g[0], (row0,), (n_local,)))
        pending_recv = pending_recv + jnp.where(
            is_intro_row, joiner_req_g.sum(dtype=I32), 0)
        sent_req = (start_now_l & (lrows != INTRO)
                    & lax.dynamic_slice(ctrl_kept_g[0], (row0,), (n_local,))).astype(I32)

        # ---- nodeLoopOps on local rows ----
        act = started & (t > start_ticks_l) & ~state.failed & in_group
        own_hb = state.self_hb + 1
        self_hb = jnp.where(act, state.self_hb + 2, state.self_hb)
        diag = lrows[:, None] == col_ids[None, :]
        present = jnp.where(diag & act[:, None], True, present)
        hb = jnp.where(diag & act[:, None], own_hb[:, None], hb)
        ts = jnp.where(diag & act[:, None], t, ts)

        difft = t - ts
        stale = present & (difft >= cfg.tfail) & act[:, None]
        numfailed = stale.sum(1, dtype=I32)
        removes = stale & (difft >= cfg.tremove)
        present = present & ~removes

        size = present.sum(1, dtype=I32)
        numpotential = size - 1 - numfailed
        fresh = present & (difft < cfg.tfail)
        seed_burst_g = seeds_g & in_group_g[INTRO] & intro_recv
        eligible = fresh & ~diag & act[:, None]
        eligible = jnp.where(is_intro_row[:, None], eligible & ~seed_burst_g[None, :],
                             eligible)
        n_seeds_row = jnp.where(is_intro_row & act, n_seeds, 0)
        k_extra = jnp.clip(jnp.minimum(cfg.fanout, numpotential) - n_seeds_row, 0)
        if replicated_rng:
            # Bit-parity debug mode: replicated [N, N] draw sliced to local
            # rows — selections match the dense backend bit-for-bit.
            scores_g = jax.random.uniform(k_targets, (n, n))
            scores_l = lax.dynamic_slice(scores_g, (row0, 0), (n_local, n))
        else:
            # Scalable default: per-shard [L, N] draw, same distribution.
            scores_l = jax.random.uniform(
                jax.random.fold_in(k_targets, me), (n_local, n))
        targets_idx, targets_valid = sample_k_indices(
            k_targets, eligible, k_extra, min(cfg.fanout, n), scores=scores_l)

        # ---- gossip: local partial → ring reduce-scatter(max) over ICI ----
        send_hb = jnp.where(fresh, hb, -1)
        k_drop_f, k_drop_s = jax.random.split(jax.random.fold_in(k_drop, me))
        contrib_partial, sent_list, recv_add_partial = fanout_deliver_indexed(
            k_drop_f, targets_idx, targets_valid, send_hb, n,
            drop_active, cfg.drop_prob)
        # Introducer burst to new joiners: contributed only by the shard that
        # owns the introducer's row; other shards pass an empty recipient set.
        intro_shard, intro_local_row = divmod(INTRO, n_local)
        seed_recipients = seed_burst_g & (me == intro_shard)
        contrib_seed, sent_seed, recv_seed = broadcast_deliver(
            k_drop_s, seed_recipients, send_hb[intro_local_row],
            drop_active, cfg.drop_prob)
        contrib_partial = jnp.maximum(contrib_partial, contrib_seed)
        sent_list = jnp.where(is_intro_row, sent_list + sent_seed, sent_list)
        contrib_local = ring_reduce_scatter_max(contrib_partial, NODE_AXIS)
        recv_add = reduce_scatter_sum(recv_add_partial + recv_seed, NODE_AXIS)
        infl_has = infl_has | (contrib_local >= 0)
        infl_hb = jnp.maximum(infl_hb, contrib_local)
        pending_recv = pending_recv + recv_add
        sent_tick = sent_list + sent_req + sent_rep

        failed = state.failed | (fail_mask_l & (t == fail_time))

        new_state = State(present, hb, ts, started, in_group, failed, self_hb,
                          infl_has, infl_hb, joinreq_infl, joinrep_infl,
                          pending_recv)
        return new_state, TickEvents(join_events, removes, sent_tick, recv_tick)

    return step


def init_local_state(n: int, n_local: int) -> State:
    return State(
        present=jnp.zeros((n_local, n), bool),
        hb=jnp.zeros((n_local, n), I32),
        ts=jnp.zeros((n_local, n), I32),
        started=jnp.zeros((n_local,), bool),
        in_group=jnp.zeros((n_local,), bool),
        failed=jnp.zeros((n_local,), bool),
        self_hb=jnp.zeros((n_local,), I32),
        infl_has=jnp.zeros((n_local, n), bool),
        infl_hb=jnp.full((n_local, n), -1, I32),
        joinreq_infl=jnp.zeros((n_local,), bool),
        joinrep_infl=jnp.zeros((n_local,), bool),
        pending_recv=jnp.zeros((n_local,), I32),
    )


_RUNNER_CACHE: dict = {}


def _get_runner(cfg: StepConfig, n_local: int, mesh: Mesh,
                replicated_rng: bool = False):
    """One compiled shard_map scan per (config, mesh): per-run values are jit
    arguments so repeated seeds/scenarios never re-trace (same pattern as
    backends/tpu.py's _get_runner)."""
    cache_key = (cfg, n_local, mesh, replicated_rng)
    if cache_key not in _RUNNER_CACHE:
        n = cfg.n
        step = make_sharded_step(cfg, n_local, replicated_rng)

        def whole_run(keys, ticks, start_ticks, fail_mask_l, fail_time,
                      drop_lo, drop_hi):
            # fail_mask_l: [L] local slice; everything else replicated.
            state0 = init_local_state(n, n_local)

            def body(state, inp):
                t, k = inp
                return step(state, (t, k, start_ticks, fail_mask_l,
                                    fail_time, drop_lo, drop_hi))

            return lax.scan(body, state0, (ticks, keys))

        sharded = shard_map(
            whole_run, mesh=mesh,
            in_specs=(P(), P(), P(), P(NODE_AXIS), P(), P(), P()),
            out_specs=(
                State(*(P(NODE_AXIS) for _ in State._fields)),
                TickEvents(joins=P(None, NODE_AXIS, None),
                           removes=P(None, NODE_AXIS, None),
                           sent=P(None, NODE_AXIS), recv=P(None, NODE_AXIS)),
            ),
            check_vma=False,
        )
        _RUNNER_CACHE[cache_key] = jax.jit(sharded)
    return _RUNNER_CACHE[cache_key]


def run_scan_sharded(params: Params, plan, seed: int, mesh: Mesh,
                     total_time: Optional[int] = None,
                     replicated_rng: bool = False):
    """Jit + shard_map the full simulation over the mesh."""
    n = params.EN_GPSZ
    s = mesh.shape[NODE_AXIS]
    if n % s != 0:
        raise ValueError(f"EN_GPSZ={n} not divisible by mesh size {s}")
    n_local = n // s
    total = total_time if total_time is not None else params.TOTAL_TIME
    cfg = StepConfig(
        n=n, tfail=params.TFAIL, tremove=params.TREMOVE, fanout=params.FANOUT,
        drop_prob=params.effective_drop_prob())

    (ticks, keys, start_ticks, fail_mask, fail_time,
     drop_lo, drop_hi) = plan_tensors(params, plan, seed, total)

    run = _get_runner(cfg, n_local, mesh, replicated_rng)
    final_state, events = run(keys, ticks, start_ticks, fail_mask,
                              fail_time, drop_lo, drop_hi)
    return final_state, jax.tree.map(np.asarray, events)


@register("tpu_sharded")
def run_tpu_sharded(params: Params, log: Optional[EventLog] = None,
                    seed: Optional[int] = None,
                    mesh: Optional[Mesh] = None,
                    replicated_rng: bool = False) -> RunResult:
    t0 = _time.time()
    seed = params.SEED if seed is None else seed
    log = log if log is not None else EventLog()
    plan = resolve_plan(params, _pyrandom.Random(f"app:{seed}"))

    if mesh is None:
        # Largest device count that divides N (grader N=10 on 8 devices → 5).
        n_dev = len(jax.devices())
        s = max(d for d in range(1, n_dev + 1) if params.EN_GPSZ % d == 0)
        mesh = make_mesh(s)

    final_state, events = run_scan_sharded(params, plan, seed, mesh,
                                           replicated_rng=replicated_rng)
    events_to_log(params, plan, events, log)

    return RunResult(
        params=params, log=log,
        sent=np.asarray(events.sent).T, recv=np.asarray(events.recv).T,
        failed_indices=plan.failed_indices if plan.fail_time is not None else [],
        fail_time=plan.fail_time,
        wall_seconds=_time.time() - t0,
        extra={"final_state": final_state, "mesh_size": mesh.shape[NODE_AXIS]},
    )
