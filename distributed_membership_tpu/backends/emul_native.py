"""`emul_native` backend: the host simulator core in C++.

The reference's runtime is native (C++ end to end); this backend keeps that
property for the rebuild's host path: the entire tick loop — network buffer,
protocol, sweep, gossip — runs inside ``native/emul_engine.cpp`` (see its
header comment for the design deltas vs. the reference), compiled on first
use with the system g++ and loaded through ctypes.  Python retains what
Python owns: config parsing, failure planning, the dbg.log format contract
(eventlog.py), and grading.

The engine streams (joined/removed) protocol events back in one buffer;
this wrapper replays them through :class:`EventLog` interleaved with the
driver-level lines (APP, Starting up group/Trying to join, @@time beacons,
failure notices) so the log line inventory matches the `emul` backend's.

Throughput: ~40x the pure-Python `emul` backend on the 10-node grader
scenarios (measured in-tree), making it the preferred oracle for sweeps.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import random as _pyrandom
import subprocess
import threading
import time as _time
from typing import Optional

import numpy as np

from distributed_membership_tpu.addressing import INTRODUCER_INDEX
from distributed_membership_tpu.backends import RunResult, register
from distributed_membership_tpu.config import Params
from distributed_membership_tpu.eventlog import EventLog
from distributed_membership_tpu.runtime.failures import log_failures, resolve_plan

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "native")
_SRC = os.path.join(_NATIVE_DIR, "emul_engine.cpp")
_SO = os.path.join(_NATIVE_DIR, "build", "libemul_engine.so")
_LOCK = threading.Lock()
_LIB = None


class DmConfig(ctypes.Structure):
    _fields_ = [
        ("n", ctypes.c_int32), ("total_time", ctypes.c_int32),
        ("tfail", ctypes.c_int32), ("tremove", ctypes.c_int32),
        ("fanout", ctypes.c_int32), ("fail_time", ctypes.c_int32),
        ("drop_start", ctypes.c_int32), ("drop_stop", ctypes.c_int32),
        ("drop_pct", ctypes.c_int32),
        ("en_buffsize", ctypes.c_int64), ("max_msg_size", ctypes.c_int64),
        ("join_mode", ctypes.c_int32),
        ("step_rate", ctypes.c_double), ("seed", ctypes.c_uint64),
    ]


def _src_digest() -> str:
    with open(_SRC, "rb") as fh:
        return hashlib.sha256(fh.read()).hexdigest()


def _build() -> str:
    """Compile the engine if the .so is missing or built from different source.

    Staleness is decided by a content hash of emul_engine.cpp stored next to
    the .so — mtimes are arbitrary after a fresh checkout, so an mtime gate
    could silently load a stale or foreign binary."""
    os.makedirs(os.path.dirname(_SO), exist_ok=True)
    stamp = _SO + ".srchash"
    digest = _src_digest()
    built = (os.path.exists(_SO) and os.path.exists(stamp)
             and open(stamp).read().strip() == digest)
    if not built:
        cmd = ["g++", "-O2", "-std=c++17", "-shared", "-fPIC",
               "-o", _SO, _SRC]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"native engine build failed:\n{proc.stderr}")
        with open(stamp, "w") as fh:
            fh.write(digest)
    return _SO


def _lib():
    global _LIB
    with _LOCK:
        if _LIB is None:
            lib = ctypes.CDLL(_build())
            lib.dm_run.restype = ctypes.c_int
            lib.dm_run.argtypes = [
                ctypes.POINTER(DmConfig),
                ctypes.POINTER(ctypes.c_uint8),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.POINTER(ctypes.c_int32),
                ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64),
            ]
            _LIB = lib
    return _LIB


def _as_ptr(arr: np.ndarray, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


@register("emul_native")
def run_emul_native(params: Params, log: Optional[EventLog] = None,
                    seed: Optional[int] = None) -> RunResult:
    t0 = _time.time()
    seed = params.SEED if seed is None else seed
    log = log if log is not None else EventLog()
    # Same failure-plan RNG stream as every other backend: identical seeds
    # crash identical nodes across backends.
    plan = resolve_plan(params, _pyrandom.Random(f"app:{seed}"))

    n = params.EN_GPSZ
    total = params.TOTAL_TIME
    cfg = DmConfig(
        n=n, total_time=total, tfail=params.TFAIL, tremove=params.TREMOVE,
        fanout=params.FANOUT,
        fail_time=plan.fail_time if plan.fail_time is not None else -1,
        drop_start=plan.drop_start if plan.drop_start is not None else -1,
        drop_stop=plan.drop_stop if plan.drop_stop is not None else -1,
        drop_pct=params.drop_pct(),
        en_buffsize=params.EN_BUFFSIZE, max_msg_size=params.MAX_MSG_SIZE,
        join_mode=1 if params.JOIN_MODE == "batch" else 0,
        step_rate=params.STEP_RATE, seed=seed & (2**64 - 1),
    )

    fail_mask = np.zeros((n,), dtype=np.uint8)
    if plan.fail_time is not None:
        fail_mask[plan.failed_indices] = 1
    sent = np.zeros((n, total), dtype=np.int32)
    recv = np.zeros((n, total), dtype=np.int32)
    # joins are bounded by n per logger view + churn; removes likewise.
    events_cap = 4 * n * n + 4096
    events = np.zeros((events_cap, 4), dtype=np.int32)
    n_events = ctypes.c_int64(0)

    rc = _lib().dm_run(
        ctypes.byref(cfg), _as_ptr(fail_mask, ctypes.c_uint8),
        _as_ptr(sent, ctypes.c_int32), _as_ptr(recv, ctypes.c_int32),
        _as_ptr(events, ctypes.c_int32), events_cap, ctypes.byref(n_events))
    if rc != 0:
        raise RuntimeError("native engine event buffer overflowed")

    _replay_log(params, plan, events[:n_events.value], log)

    return RunResult(
        params=params, log=log, sent=sent, recv=recv,
        failed_indices=plan.failed_indices if plan.fail_time is not None else [],
        fail_time=plan.fail_time,
        wall_seconds=_time.time() - t0,
        extra={"native": True},
    )


def _replay_log(params: Params, plan, events: np.ndarray,
                log: EventLog) -> None:
    """Interleave engine events with the driver-level lines, matching the
    `emul` backend's inventory (Application.cpp:67,143-148,156-160,184,192)."""
    n = params.EN_GPSZ
    starts = [params.start_tick(i) for i in range(n)]
    for i in range(n):
        log.log(i + 1, 0, "APP")

    by_tick: dict = {}
    for kind, logger, subject, tick in events:
        by_tick.setdefault(int(tick), []).append(
            (int(kind), int(logger), int(subject)))

    intro_failed = (plan.fail_time is not None
                    and INTRODUCER_INDEX in plan.failed_indices)
    for t in range(params.TOTAL_TIME):
        for i in range(n - 1, -1, -1):
            if starts[i] == t:
                if i == INTRODUCER_INDEX:
                    log.log(i + 1, t, "Starting up group...")
                else:
                    log.log(i + 1, t, "Trying to join...")
        for kind, logger, subject, in by_tick.get(t, ()):
            if kind == 0:
                log.node_add(logger, subject, t)
            else:
                log.node_remove(logger, subject, t)
        if (t % 500 == 0 and t > starts[INTRODUCER_INDEX]
                and not (intro_failed and t > plan.fail_time)):
            log.log(INTRODUCER_INDEX + 1, t, f"@@time={t}")
        if plan.fail_time == t:
            log_failures(plan, log, t)
