"""Flight recorder, part 1: in-scan per-tick telemetry (``TELEMETRY``).

A served hardware window used to bank one wall-clock number per rung while
protocol health (live/suspect counts, gossip freshness, detection progress)
was only visible as run-total aggregates after the last tick.  This module
is the per-tick counterpart: with ``TELEMETRY: scalars`` the jitted ring
steps (tpu_hash natural + folded, tpu_hash_sharded natural + folded) emit a
:class:`TickTelemetry` of scalar reductions every tick — O(1) extra
reductions over tensors the step already computes, consuming no RNG and
touching no state, so the trajectory is bit-identical to a telemetry-off
run (pinned in tests/test_timeline.py).  The scalars stack into
``[K]``-shaped per-segment series inside each ``CHECKPOINT_EVERY`` scan
segment (O(K) device memory) and flush host-side at every segment boundary
into ``timeline.jsonl`` — composing with kill/resume: a re-run segment
re-flushes its record and the reader keeps the last write per tick range.

With ``TELEMETRY: off`` (the default) none of this exists in the compiled
program: every emission site is guarded by ``cfg.telemetry``, and
tests/test_hlo_census.py pins the off program op-count identical to the
default lowering at the [1M, 16] north-star geometry.

Field semantics (all int32 scalars per tick):
  * ``live``        — nodes active this tick (started, in-group, not failed);
  * ``suspected``   — view entries in the TFAIL suspicion state this tick;
  * ``joins``       — admissions into empty view slots this tick;
  * ``removals``    — TREMOVE evictions this tick;
  * ``detections``  — TRUE detections this tick (removals of a crashed id
    after its crash; 0 in EVENT_MODE full runs — cumulate host-side, see
    :func:`read_timeline`'s ``detections_cum``);
  * ``msgs_sent`` / ``msgs_recv`` — wire messages sent / delivered into
    the receive stream this tick (PROBE_IO approx_lag's final-tick
    ack-send epilogue applies to run totals only, not this series);
  * ``dropped``     — messages killed by drop coins this tick (budget
    drops under ENFORCE_BUFFSIZE are not counted here);
  * ``probe_acks``  — ack messages applied by the probe pipeline this tick;
  * ``gossip_rows`` — view entries carried by gossip payloads this tick.

The histogram tier (``TELEMETRY: hist``) layers the distributional
quantities the scalars cannot carry on top of the same pipeline: each
tick additionally emits a :class:`TickHist` of fixed-bucket int32
histograms computed in-graph as nibble-packed masked reductions over
tensors the step already holds — no gathers, no scatters, no RNG (the
census test pins this), just compares/shifts summed over the state axes
(see :func:`hist_bucket_counts` for the packing), so
the hist program stays trajectory-inert and fold/shard-invariant (a
fold is a reshape and each reduction is linear, so per-shard partials
psum to the global counts bit-exactly).  Bucket edges are static
(``HIST_BUCKETS`` / ``HIST_EDGES`` below):

  * ``h_staleness``  — heartbeat staleness ``t - view_ts`` of present
    view entries; 8 buckets x 8 ticks (last = overflow >= 56);
  * ``h_suspicion``  — suspicion age ``staleness - TFAIL`` of entries
    past TFAIL; 8 buckets x 8 ticks (last = overflow);
  * ``h_latency``    — detection latency ``t - fail_time`` at each TRUE
    detection this tick; 64 UNIT buckets (last = overflow >= 63) — unit
    width makes the reconstructed removal-latency distribution EXACT,
    the property the SLO report (observability/latency_dist.py) and the
    N=10 eventlog-match test rely on;
  * ``h_occupancy``  — per-node view occupancy (live nodes only);
    16 unit buckets (last = overflow >= 15);
  * ``h_drops``      — the tick's total dropped-message count on a log2
    scale: bucket 0 = no drops, bucket k = [2^(k-1), 2^k), 16 buckets.

The series ride the scan outputs exactly like the scalars ([K, B] per
``CHECKPOINT_EVERY`` segment), flush into the same torn-tolerant
``timeline.jsonl`` (records gain nested ``[K][B]`` lists), and merge
last-t0-wins across kill/resume.

Part 2 of the recorder is phase-scoped tracing: the protocol phases are
wrapped in ``jax.named_scope`` (names below) across all four ring twins
and the fused kernels, so a ``jax.profiler`` capture
(``scripts/profile_step.py --trace-dir``) attributes per-phase device time
without a dedicated bisect run.  Part 3 (the structured run/ladder event
log) lives in observability/runlog.py.
"""

from __future__ import annotations

import gzip
import json
import os
from typing import NamedTuple, Optional

import numpy as np

# The protocol-phase annotation names every ring twin emits
# (jax.named_scope); the ``dm_`` prefix makes them greppable in captured
# profiler artifacts (scripts/profile_step.py --trace-dir byte-scans the
# xplane/trace files for exactly these strings).
PHASE_RECEIVE = "dm_receive_sweep"      # admit + ack-merge + self + sweep
PHASE_ACK = "dm_ack_apply"              # ack-candidate gather pipeline
PHASE_GOSSIP = "dm_gossip_exchange"     # circulant shift delivery
PHASE_COLLECTIVE = "dm_exchange_collective"  # sharded ppermute wire hop
PHASE_PROBE = "dm_probe_issue"          # probe window issue + counters
PHASE_AGG = "dm_aggregates"             # on-device event aggregation
PHASE_TELEMETRY = "dm_telemetry"        # the scalar reductions themselves

# The subset guaranteed present in ANY compiled ring step (single-chip or
# sharded, probes on, natural or folded) — what the trace test asserts.
PHASE_NAMES = (PHASE_RECEIVE, PHASE_ACK, PHASE_GOSSIP, PHASE_PROBE,
               PHASE_AGG)


class TickTelemetry(NamedTuple):
    """One tick's scalar telemetry (module docstring for semantics).
    Inside the scan each field is a [] int32; stacked by the scan they
    become the per-segment [K] series the recorder flushes."""
    live: object
    suspected: object
    joins: object
    removals: object
    detections: object
    msgs_sent: object
    msgs_recv: object
    dropped: object
    probe_acks: object
    gossip_rows: object


class TickHist(NamedTuple):
    """One tick's fixed-bucket histograms (module docstring for bucket
    semantics).  Inside the scan each field is a [B] int32 vector;
    stacked by the scan they become [K, B] per-segment series."""
    h_staleness: object
    h_suspicion: object
    h_latency: object
    h_occupancy: object
    h_drops: object


TELEMETRY_FIELDS = TickTelemetry._fields
HIST_FIELDS = TickHist._fields
TIMELINE_NAME = "timeline.jsonl"

# Static bucket geometry (documented in the module docstring; README
# "Observability").  Changing these changes the timeline.jsonl schema —
# consumers read bucket counts positionally.
HIST_BUCKETS = {"h_staleness": 8, "h_suspicion": 8, "h_latency": 64,
                "h_occupancy": 16, "h_drops": 16}
STALENESS_BUCKET_TICKS = 8      # h_staleness / h_suspicion bucket width
LATENCY_BUCKETS = HIST_BUCKETS["h_latency"]


def telemetry_spec(p):
    """A TickTelemetry of identical (sharding/shape) specs — the sharded
    backend's out_specs entry (every field is a replicated scalar)."""
    return TickTelemetry(*(p for _ in TELEMETRY_FIELDS))


def hist_spec(p):
    """A TickHist of identical specs (every histogram is a replicated
    [B] vector after the in-step psum) — the sharded backend's
    out_specs entry for the hist tier."""
    return TickHist(*(p for _ in HIST_FIELDS))


# ---------------------------------------------------------------------------
# In-graph histogram builders (shared by all four ring twins).
#
# Everything here is reductions + bounded elementwise: per static bucket
# index, a masked compare summed over the state axes.  No gathers, no
# scatters, no RNG — tests/test_hlo_census.py pins that structural
# contract at the [1M, 16] geometry.  jax is imported lazily so the
# pure-numpy readers below stay importable without it.

def hist_bucket_counts(vals, mask, nbins: int, width: int):
    """[nbins] int32 bucket counts of ``vals`` (int) under ``mask``:
    bucket ``b`` counts masked elements with ``vals // width == b``,
    clipped into [0, nbins-1] (last bucket = overflow).  Works on any
    shape — natural [N, S], folded planes, or [N] vectors — and a fold
    is a reshape, so folded counts are bit-equal to natural ones: the
    histogram only sees the element multiset and integer sums are
    order-free.

    The large-tensor path is a nibble-packed two-stage reduction, not a
    per-bucket compare-and-reduce and not an [..., nbins] one-hot
    expansion: XLA:CPU fuses neither into a single pass, so at
    [65536, 16] the expansion costs ~8 full-tensor passes' bandwidth
    (measured 22.9 ms) and the unrolled compares one pass PER BUCKET
    (5.7 ms; ~20% step overhead against a ~5% budget).  Instead the
    tensor is reshaped into rows of 8, each masked element contributes
    ``1 << 4*id`` so a single row-sum packs eight per-row bucket counts
    into one int32 (counts <= 8 per 4-bit field — no carries; the top
    field's wrap past the sign bit is benign because decoding only
    reinterprets bits), and the 8 scalar counts decode from the 8x
    smaller packed vector.  Two full-tensor passes replace sixteen for
    the staleness + suspicion pair.  Tiny or non-divisible tensors keep
    the unrolled form; both forms count identically."""
    import jax.numpy as jnp

    ids = jnp.clip(vals // width if width > 1 else vals, 0, nbins - 1)
    total = 1
    for d in ids.shape:
        total *= d
    if total % 8 or total <= 1024:
        return jnp.stack([((ids == b) & mask).sum(dtype=jnp.int32)
                          for b in range(nbins)])
    rows_i = ids.reshape(-1, 8).astype(jnp.int32)
    rows_m = mask.reshape(-1, 8)
    counts = []
    for lo in range(0, nbins, 8):
        in_chunk = rows_m & (rows_i >= lo) & (rows_i < lo + 8)
        field = jnp.clip(rows_i - lo, 0, 7)   # shift stays in-range even
        packed = jnp.where(in_chunk,          # where in_chunk is False
                           jnp.int32(1) << (4 * field),
                           0).sum(axis=1, dtype=jnp.int32)
        counts.extend(((packed >> (4 * b)) & 0xF).sum(dtype=jnp.int32)
                      for b in range(min(8, nbins - lo)))
    return jnp.stack(counts)


def scalar_one_hot(idx, nbins: int, count):
    """[nbins] int32 with ``count`` at ``clip(idx, 0, nbins-1)`` — the
    free histogram of a quantity that is a single scalar this tick
    (detection latency: every detection at tick t shares t - fail_time)."""
    import jax.numpy as jnp

    where = jnp.clip(idx, 0, nbins - 1)
    return ((jnp.arange(nbins) == where).astype(jnp.int32)
            * count.astype(jnp.int32))


def drops_hist(dropped, nbins: int = HIST_BUCKETS["h_drops"]):
    """[nbins] int32 log2 one-hot of the tick's total drop count:
    bucket 0 = zero drops, bucket k = [2^(k-1), 2^k) (last = overflow).
    The log index is a static unrolled compare chain — no float log, no
    data-dependent control flow."""
    import jax.numpy as jnp

    idx = sum((dropped >= (1 << i)).astype(jnp.int32)
              for i in range(nbins - 1))
    return (jnp.arange(nbins) == idx).astype(jnp.int32)


def build_tick_hist(*, difft, present, size, act, t, fail_time, tfail,
                    det_tick, dropped, psum=None, stale=None, susp=None):
    """The TickHist every ring twin emits, from tensors the step already
    holds: ``difft``/``present`` are the post-receive staleness planes
    ([N, S] natural or [N*S/128, 128] folded), ``size``/``act`` the
    per-node occupancy and liveness vectors, ``det_tick`` this tick's
    TRUE-detection count and ``dropped`` its drop count.  On the sharded
    twins pass the LOCAL tensors plus ``psum`` (the axis reducer) and
    the GLOBAL ``dropped`` scalar — the four count histograms are linear
    so per-shard partials psum exactly; the log2 drop bucket is not, so
    it must be computed after the merge.

    ``stale``/``susp`` (optional [8] int32) are PRECOMPUTED staleness/
    suspicion bucket counts — the FUSED_PROBE kernel emits them as
    integer partials riding its [N, S] traversal (ops/fused_probe), and
    integer bucket sums are order-free, so the counts are bit-equal to
    :func:`hist_bucket_counts` over the same planes.  When given, the
    corresponding plane passes here are skipped."""
    if stale is None:
        stale = hist_bucket_counts(difft, present,
                                   HIST_BUCKETS["h_staleness"],
                                   STALENESS_BUCKET_TICKS)
    if susp is None:
        susp = hist_bucket_counts(difft - tfail,
                                  present & (difft >= tfail),
                                  HIST_BUCKETS["h_suspicion"],
                                  STALENESS_BUCKET_TICKS)
    occ = hist_bucket_counts(size, act, HIST_BUCKETS["h_occupancy"], 1)
    lat = scalar_one_hot(t - fail_time, LATENCY_BUCKETS, det_tick)
    if psum is not None:
        stale, susp, occ, lat = (psum(stale), psum(susp), psum(occ),
                                 psum(lat))
    return TickHist(h_staleness=stale, h_suspicion=susp, h_latency=lat,
                    h_occupancy=occ, h_drops=drops_hist(dropped))


class TimelineRecorder:
    """Accumulates per-segment telemetry series and (optionally) appends
    them to ``<dir>/timeline.jsonl`` as they arrive.

    One JSONL record per flushed segment: ``{"t0": <first tick>,
    "ticks": K, "<field>": [K ints], ...}``.  Appending is crash-safe by
    construction (a torn trailing line is skipped by the reader) and
    resume-safe by keying on ``t0``: a killed-and-resumed run re-flushes
    the segments after its last durable checkpoint, and
    :func:`read_timeline` keeps the LAST record per ``t0`` — so the file
    converges to the uninterrupted run's content (tests/test_timeline.py).
    """

    def __init__(self, directory: Optional[str] = None):
        self.path = None
        if directory:
            os.makedirs(directory, exist_ok=True)
            self.path = os.path.join(directory, TIMELINE_NAME)
        self._chunks: list = []      # [(t0, {field: np.ndarray[K]})]

    def flush(self, telem, t0: int) -> None:
        """Bank one segment's [K]-shaped series starting at tick ``t0``.

        ``telem`` is either a TickTelemetry of [K] series (TELEMETRY:
        scalars) or a ``(TickTelemetry, TickHist)`` pair (TELEMETRY:
        hist) whose hist fields are [K, B] series — the hist records
        carry nested ``[K][B]`` lists in the same JSONL line."""
        hist = None
        if type(telem) is tuple:          # (scalars, hist) — the hist tier
            telem, hist = telem
        rec = {f: np.asarray(getattr(telem, f)).astype(np.int64).reshape(-1)
               for f in TELEMETRY_FIELDS}
        if hist is not None:
            k = len(rec["live"])
            rec.update({f: np.asarray(getattr(hist, f))
                        .astype(np.int64).reshape(k, -1)
                        for f in HIST_FIELDS})
        self._chunks.append((int(t0), rec))
        if self.path:
            line = {"t0": int(t0), "ticks": int(len(rec["live"]))}
            line.update({f: rec[f].tolist() for f in rec})
            with open(self.path, "a") as fh:
                fh.write(json.dumps(line) + "\n")

    def series(self) -> dict:
        """The concatenated per-tick series (dict of [T] arrays plus
        ``t0``/``ticks``/``detections_cum``).  Reads the file back when
        one is being written — a resumed recorder only saw the segments
        after the resume point, but the file holds the whole run."""
        if self.path and os.path.exists(self.path):
            return read_timeline(self.path)
        return _merge_chunks(self._chunks)


def _merge_chunks(chunks) -> dict:
    dedup = {}
    for t0, rec in chunks:          # later flushes win (resume re-runs)
        dedup[t0] = rec
    if not dedup:
        out = {f: np.zeros((0,), np.int64) for f in TELEMETRY_FIELDS}
        out.update(t0=0, ticks=0, detections_cum=np.zeros((0,), np.int64))
        return out
    t0s = sorted(dedup)
    # Hist fields are only present on hist-tier records; a field merges
    # only when EVERY surviving chunk carries it (mixed-tier files keep
    # the scalar series intact rather than producing ragged hist ones).
    fields = set(dedup[t0s[0]])
    for t in t0s[1:]:
        fields &= set(dedup[t])
    out = {f: np.concatenate([dedup[t][f] for t in t0s])
           for f in fields}
    out["t0"] = t0s[0]
    out["ticks"] = int(sum(len(dedup[t]["live"]) for t in t0s))
    # ``detections`` is per-tick (delta) so it stays segment-local exact
    # on every backend (the sharded chunked driver resets its per-shard
    # partials each segment); the so-far view is its running sum.
    out["detections_cum"] = np.cumsum(out["detections"])
    return out


def read_timeline(path: str) -> dict:
    """Parse ``timeline.jsonl`` into the merged per-tick series (see
    :meth:`TimelineRecorder.series`).  Tolerates a torn trailing line
    (crash mid-append) and duplicate ``t0`` records (kill/resume): the
    last record per tick range wins."""
    chunks = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue            # torn trailing write
            chunks.append((int(rec["t0"]),
                           {f: np.asarray(rec[f], np.int64)
                            for f in TELEMETRY_FIELDS + HIST_FIELDS
                            if f in rec}))
    return _merge_chunks(chunks)


def timeline_summary(series: dict) -> dict:
    """Aggregate view of a timeline (run_report's timeline section)."""
    if not series or series.get("ticks", 0) == 0:
        return {"ticks": 0}
    det = series["detections"]
    det_ticks = np.nonzero(det)[0]
    hist_extra = {}
    if "h_latency" in series:
        # Hist-tier cross-check totals: the latency histogram's mass is
        # exactly the detections series (both count TRUE detections), so
        # any divergence means a torn artifact set (run_report and the
        # scenario oracle reconcile on this).
        hist_extra = {
            "hist": True,
            "latency_hist_detections": int(series["h_latency"].sum()),
            "occupancy_mean": (
                round(float((series["h_occupancy"]
                             * np.arange(series["h_occupancy"].shape[1])
                             ).sum())
                      / max(int(series["h_occupancy"].sum()), 1), 2)),
            "staleness_overflow_total": int(
                series["h_staleness"][:, -1].sum()),
        }
    return {
        **hist_extra,
        "ticks": int(series["ticks"]),
        "t0": int(series["t0"]),
        "joins_total": int(series["joins"].sum()),
        "removals_total": int(series["removals"].sum()),
        "detections_total": int(det.sum()),
        "msgs_sent_total": int(series["msgs_sent"].sum()),
        "msgs_recv_total": int(series["msgs_recv"].sum()),
        "dropped_total": int(series["dropped"].sum()),
        "probe_acks_total": int(series["probe_acks"].sum()),
        "gossip_rows_total": int(series["gossip_rows"].sum()),
        "live_min": int(series["live"].min()),
        "live_max": int(series["live"].max()),
        "suspected_peak": int(series["suspected"].max()),
        "first_detection_tick": (int(series["t0"] + det_ticks[0])
                                 if det_ticks.size else None),
        "last_detection_tick": (int(series["t0"] + det_ticks[-1])
                                if det_ticks.size else None),
    }


def scan_trace_for_phases(trace_dir: str, names=PHASE_NAMES) -> list:
    """Which phase-annotation names appear in a captured profiler trace
    (byte-scan of every file under ``trace_dir``, gzip-aware: the op
    names carrying ``jax.named_scope`` prefixes are embedded verbatim in
    the xplane protobuf / trace json)."""
    want = {n: n.encode() for n in names}
    found = set()
    for root, _, files in os.walk(trace_dir):
        for fname in files:
            path = os.path.join(root, fname)
            try:
                with open(path, "rb") as fh:
                    blob = fh.read()
                if fname.endswith(".gz"):
                    try:
                        blob = gzip.decompress(blob)
                    except OSError:
                        pass
            except OSError:
                continue
            for name, pat in want.items():
                if name not in found and pat in blob:
                    found.add(name)
    return sorted(found)
