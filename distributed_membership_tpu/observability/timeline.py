"""Flight recorder, part 1: in-scan per-tick telemetry (``TELEMETRY``).

A served hardware window used to bank one wall-clock number per rung while
protocol health (live/suspect counts, gossip freshness, detection progress)
was only visible as run-total aggregates after the last tick.  This module
is the per-tick counterpart: with ``TELEMETRY: scalars`` the jitted ring
steps (tpu_hash natural + folded, tpu_hash_sharded natural + folded) emit a
:class:`TickTelemetry` of scalar reductions every tick — O(1) extra
reductions over tensors the step already computes, consuming no RNG and
touching no state, so the trajectory is bit-identical to a telemetry-off
run (pinned in tests/test_timeline.py).  The scalars stack into
``[K]``-shaped per-segment series inside each ``CHECKPOINT_EVERY`` scan
segment (O(K) device memory) and flush host-side at every segment boundary
into ``timeline.jsonl`` — composing with kill/resume: a re-run segment
re-flushes its record and the reader keeps the last write per tick range.

With ``TELEMETRY: off`` (the default) none of this exists in the compiled
program: every emission site is guarded by ``cfg.telemetry``, and
tests/test_hlo_census.py pins the off program op-count identical to the
default lowering at the [1M, 16] north-star geometry.

Field semantics (all int32 scalars per tick):
  * ``live``        — nodes active this tick (started, in-group, not failed);
  * ``suspected``   — view entries in the TFAIL suspicion state this tick;
  * ``joins``       — admissions into empty view slots this tick;
  * ``removals``    — TREMOVE evictions this tick;
  * ``detections``  — TRUE detections this tick (removals of a crashed id
    after its crash; 0 in EVENT_MODE full runs — cumulate host-side, see
    :func:`read_timeline`'s ``detections_cum``);
  * ``msgs_sent`` / ``msgs_recv`` — wire messages sent / delivered into
    the receive stream this tick (PROBE_IO approx_lag's final-tick
    ack-send epilogue applies to run totals only, not this series);
  * ``dropped``     — messages killed by drop coins this tick (budget
    drops under ENFORCE_BUFFSIZE are not counted here);
  * ``probe_acks``  — ack messages applied by the probe pipeline this tick;
  * ``gossip_rows`` — view entries carried by gossip payloads this tick.

Part 2 of the recorder is phase-scoped tracing: the protocol phases are
wrapped in ``jax.named_scope`` (names below) across all four ring twins
and the fused kernels, so a ``jax.profiler`` capture
(``scripts/profile_step.py --trace-dir``) attributes per-phase device time
without a dedicated bisect run.  Part 3 (the structured run/ladder event
log) lives in observability/runlog.py.
"""

from __future__ import annotations

import gzip
import json
import os
from typing import NamedTuple, Optional

import numpy as np

# The protocol-phase annotation names every ring twin emits
# (jax.named_scope); the ``dm_`` prefix makes them greppable in captured
# profiler artifacts (scripts/profile_step.py --trace-dir byte-scans the
# xplane/trace files for exactly these strings).
PHASE_RECEIVE = "dm_receive_sweep"      # admit + ack-merge + self + sweep
PHASE_ACK = "dm_ack_apply"              # ack-candidate gather pipeline
PHASE_GOSSIP = "dm_gossip_exchange"     # circulant shift delivery
PHASE_COLLECTIVE = "dm_exchange_collective"  # sharded ppermute wire hop
PHASE_PROBE = "dm_probe_issue"          # probe window issue + counters
PHASE_AGG = "dm_aggregates"             # on-device event aggregation
PHASE_TELEMETRY = "dm_telemetry"        # the scalar reductions themselves

# The subset guaranteed present in ANY compiled ring step (single-chip or
# sharded, probes on, natural or folded) — what the trace test asserts.
PHASE_NAMES = (PHASE_RECEIVE, PHASE_ACK, PHASE_GOSSIP, PHASE_PROBE,
               PHASE_AGG)


class TickTelemetry(NamedTuple):
    """One tick's scalar telemetry (module docstring for semantics).
    Inside the scan each field is a [] int32; stacked by the scan they
    become the per-segment [K] series the recorder flushes."""
    live: object
    suspected: object
    joins: object
    removals: object
    detections: object
    msgs_sent: object
    msgs_recv: object
    dropped: object
    probe_acks: object
    gossip_rows: object


TELEMETRY_FIELDS = TickTelemetry._fields
TIMELINE_NAME = "timeline.jsonl"


def telemetry_spec(p):
    """A TickTelemetry of identical (sharding/shape) specs — the sharded
    backend's out_specs entry (every field is a replicated scalar)."""
    return TickTelemetry(*(p for _ in TELEMETRY_FIELDS))


class TimelineRecorder:
    """Accumulates per-segment telemetry series and (optionally) appends
    them to ``<dir>/timeline.jsonl`` as they arrive.

    One JSONL record per flushed segment: ``{"t0": <first tick>,
    "ticks": K, "<field>": [K ints], ...}``.  Appending is crash-safe by
    construction (a torn trailing line is skipped by the reader) and
    resume-safe by keying on ``t0``: a killed-and-resumed run re-flushes
    the segments after its last durable checkpoint, and
    :func:`read_timeline` keeps the LAST record per ``t0`` — so the file
    converges to the uninterrupted run's content (tests/test_timeline.py).
    """

    def __init__(self, directory: Optional[str] = None):
        self.path = None
        if directory:
            os.makedirs(directory, exist_ok=True)
            self.path = os.path.join(directory, TIMELINE_NAME)
        self._chunks: list = []      # [(t0, {field: np.ndarray[K]})]

    def flush(self, telem, t0: int) -> None:
        """Bank one segment's [K]-shaped series starting at tick ``t0``."""
        rec = {f: np.asarray(getattr(telem, f)).astype(np.int64).reshape(-1)
               for f in TELEMETRY_FIELDS}
        self._chunks.append((int(t0), rec))
        if self.path:
            line = {"t0": int(t0), "ticks": int(len(rec["live"]))}
            line.update({f: rec[f].tolist() for f in TELEMETRY_FIELDS})
            with open(self.path, "a") as fh:
                fh.write(json.dumps(line) + "\n")

    def series(self) -> dict:
        """The concatenated per-tick series (dict of [T] arrays plus
        ``t0``/``ticks``/``detections_cum``).  Reads the file back when
        one is being written — a resumed recorder only saw the segments
        after the resume point, but the file holds the whole run."""
        if self.path and os.path.exists(self.path):
            return read_timeline(self.path)
        return _merge_chunks(self._chunks)


def _merge_chunks(chunks) -> dict:
    dedup = {}
    for t0, rec in chunks:          # later flushes win (resume re-runs)
        dedup[t0] = rec
    if not dedup:
        out = {f: np.zeros((0,), np.int64) for f in TELEMETRY_FIELDS}
        out.update(t0=0, ticks=0, detections_cum=np.zeros((0,), np.int64))
        return out
    t0s = sorted(dedup)
    out = {f: np.concatenate([dedup[t][f] for t in t0s])
           for f in TELEMETRY_FIELDS}
    out["t0"] = t0s[0]
    out["ticks"] = int(sum(len(dedup[t]["live"]) for t in t0s))
    # ``detections`` is per-tick (delta) so it stays segment-local exact
    # on every backend (the sharded chunked driver resets its per-shard
    # partials each segment); the so-far view is its running sum.
    out["detections_cum"] = np.cumsum(out["detections"])
    return out


def read_timeline(path: str) -> dict:
    """Parse ``timeline.jsonl`` into the merged per-tick series (see
    :meth:`TimelineRecorder.series`).  Tolerates a torn trailing line
    (crash mid-append) and duplicate ``t0`` records (kill/resume): the
    last record per tick range wins."""
    chunks = []
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue            # torn trailing write
            chunks.append((int(rec["t0"]),
                           {f: np.asarray(rec[f], np.int64)
                            for f in TELEMETRY_FIELDS}))
    return _merge_chunks(chunks)


def timeline_summary(series: dict) -> dict:
    """Aggregate view of a timeline (run_report's timeline section)."""
    if not series or series.get("ticks", 0) == 0:
        return {"ticks": 0}
    det = series["detections"]
    det_ticks = np.nonzero(det)[0]
    return {
        "ticks": int(series["ticks"]),
        "t0": int(series["t0"]),
        "joins_total": int(series["joins"].sum()),
        "removals_total": int(series["removals"].sum()),
        "detections_total": int(det.sum()),
        "msgs_sent_total": int(series["msgs_sent"].sum()),
        "msgs_recv_total": int(series["msgs_recv"].sum()),
        "dropped_total": int(series["dropped"].sum()),
        "probe_acks_total": int(series["probe_acks"].sum()),
        "gossip_rows_total": int(series["gossip_rows"].sum()),
        "live_min": int(series["live"].min()),
        "live_max": int(series["live"].max()),
        "suspected_peak": int(series["suspected"].max()),
        "first_detection_tick": (int(series["t0"] + det_ticks[0])
                                 if det_ticks.size else None),
        "last_detection_tick": (int(series["t0"] + det_ticks[-1])
                                if det_ticks.size else None),
    }


def scan_trace_for_phases(trace_dir: str, names=PHASE_NAMES) -> list:
    """Which phase-annotation names appear in a captured profiler trace
    (byte-scan of every file under ``trace_dir``, gzip-aware: the op
    names carrying ``jax.named_scope`` prefixes are embedded verbatim in
    the xplane protobuf / trace json)."""
    want = {n: n.encode() for n in names}
    found = set()
    for root, _, files in os.walk(trace_dir):
        for fname in files:
            path = os.path.join(root, fname)
            try:
                with open(path, "rb") as fh:
                    blob = fh.read()
                if fname.endswith(".gz"):
                    try:
                        blob = gzip.decompress(blob)
                    except OSError:
                        pass
            except OSError:
                continue
            for name, pat in want.items():
                if name not in found and pat in blob:
                    found.add(name)
    return sorted(found)
