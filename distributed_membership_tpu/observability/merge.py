"""Cross-process telemetry merge: K per-process timeline shards ->
one global series, with a consistency cross-check.

A multi-process mesh run (scripts/multiproc_launch.py) gives every
process its own artifact dir, so process i appends its own
``p{i}/timeline.jsonl``.  Crucially those shards are NOT per-shard
partials: every per-tick value a process flushes is already the GLOBAL
quantity — the scalar reductions ride ``to_host`` gathers of global
state and the hist fields are psum'd in-graph before they leave the
step (observability/timeline.py), so all K shards describe the same
global run.  The merge therefore must never re-sum across shards
(that would overcount every series K times); it VERIFIES the shards
against each other record-by-record and takes the union:

  * within a shard, duplicate ``t0`` records keep the last write
    (kill/resume re-flushes a segment — same rule as
    :func:`~observability.timeline.read_timeline`);
  * across shards, a ``t0`` present in several shards must carry
    bit-identical field lists; any disagreement is a hard
    :class:`MergeError` naming the shard pair, field and first
    diverging tick — a disagreeing shard means the run itself diverged
    (the invariant tests/test_exchange.py pins), and silently picking
    one shard would bury exactly the bug the cross-check exists to
    catch;
  * the union covers tick ranges only some shards flushed (a process
    SIGKILLed after its peers' boundary flush) — the merged file is
    the most complete honest view of the run.

The merged records serialize back into the SAME ``timeline.jsonl``
schema, so every existing consumer (read_timeline, run_report,
/v1/timeline, the SLO verdict) works on a merged file unchanged — and
the acceptance contract is byte-level: a merged K-process timeline
parses into a series bit-identical to the single-process twin's
(tests/test_metrics_plane.py pins K=2 at N=2048).
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, List, Optional, Tuple

import numpy as np

from distributed_membership_tpu.observability.timeline import (
    HIST_FIELDS, TELEMETRY_FIELDS, TIMELINE_NAME, _merge_chunks)

_SHARD_DIR_RE = re.compile(r"p(\d+)")


class MergeError(ValueError):
    """Two shards disagree on an overlapping segment — the run itself
    diverged across processes; there is no honest merged series."""


def _read_records(path: str) -> Dict[int, dict]:
    """Raw per-``t0`` records of one shard, last write per ``t0``
    winning (torn trailing lines skipped, like read_timeline)."""
    dedup: Dict[int, dict] = {}
    try:
        with open(path) as fh:
            lines = fh.readlines()
    except OSError:
        return dedup
    for line in lines:
        line = line.strip()
        if not line:
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue                    # torn trailing write
        if isinstance(rec, dict) and "t0" in rec:
            dedup[int(rec["t0"])] = rec
    return dedup


def _check_equal(a: dict, b: dict, t0: int, la: str, lb: str) -> None:
    """Field-by-field bitwise comparison of two shards' records for
    the same segment; raises :class:`MergeError` on the first
    divergence (field + tick index within the segment)."""
    fields = sorted((set(a) | set(b)) - {"t0"})
    for f in fields:
        va, vb = a.get(f), b.get(f)
        if va == vb:
            continue
        detail = ""
        if isinstance(va, list) and isinstance(vb, list):
            k = next((i for i in range(min(len(va), len(vb)))
                      if va[i] != vb[i]), min(len(va), len(vb)))
            detail = f" (first divergence at tick {t0 + k})"
        raise MergeError(
            f"shards {la!r} and {lb!r} disagree on segment t0={t0}, "
            f"field {f!r}{detail} — the per-process runs diverged; "
            "refusing to merge")


def merge_paths(paths: List[Tuple[str, str]]) -> Dict[int, dict]:
    """Verify + union (label, timeline path) shards ->
    ``{t0: record}``.  Raises :class:`MergeError` on any overlapping
    disagreement."""
    merged: Dict[int, dict] = {}
    source: Dict[int, str] = {}
    for label, path in paths:
        for t0, rec in _read_records(path).items():
            if t0 in merged:
                _check_equal(merged[t0], rec, t0, source[t0], label)
            else:
                merged[t0] = rec
                source[t0] = label
    return merged


def shard_dirs(root: str) -> List[Tuple[str, str]]:
    """The ``p{i}`` shard dirs under a multiproc out-root, ordered by
    process id -> [(label, timeline path)] for those with a
    timeline."""
    out = []
    try:
        names = os.listdir(root)
    except OSError:
        return out
    for name in names:
        m = _SHARD_DIR_RE.fullmatch(name)
        if m is None:
            continue
        path = os.path.join(root, name, TIMELINE_NAME)
        if os.path.exists(path):
            out.append((int(m.group(1)), name, path))
    return [(name, path) for _, name, path in sorted(out)]


def merged_series(records: Dict[int, dict]) -> dict:
    """The concatenated per-tick series of merged records — the same
    dict shape :func:`~observability.timeline.read_timeline` returns,
    via the same chunk merger (so ``detections_cum`` etc. match)."""
    chunks = [(t0, {f: np.asarray(rec[f], np.int64)
                    for f in TELEMETRY_FIELDS + HIST_FIELDS
                    if f in rec})
              for t0, rec in records.items()]
    return _merge_chunks(chunks)


def write_merged(records: Dict[int, dict], out_path: str) -> None:
    """Serialize merged records back into the timeline.jsonl schema,
    atomically (tmp + rename: a crashed merge never leaves a torn
    global file next to intact shards)."""
    tmp = f"{out_path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        for t0 in sorted(records):
            fh.write(json.dumps(records[t0]) + "\n")
    os.replace(tmp, out_path)


def merge_run(root: str, out_name: str = TIMELINE_NAME,
              write: bool = True) -> Optional[dict]:
    """Merge every ``<root>/p{i}/timeline.jsonl`` shard into
    ``<root>/<out_name>`` -> an info dict, or None when there are no
    shards.  The consistency cross-check is load-bearing: MergeError
    propagates."""
    shards = shard_dirs(root)
    if not shards:
        return None
    records = merge_paths(shards)
    series = merged_series(records)
    if write:
        write_merged(records, os.path.join(root, out_name))
    return {"shards": [label for label, _ in shards],
            "segments": len(records),
            "ticks": int(series.get("ticks", 0)),
            "t0": int(series.get("t0", 0)),
            "path": os.path.join(root, out_name) if write else None}
