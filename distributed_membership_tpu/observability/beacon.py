"""One beacon format for every side-channel status file.

Before this module, three surfaces each invented the same thing:
``replica_<i>.json`` (service/replica.py), ``service.json`` and
``fleet.json`` (service/daemon.py, fleet/daemon.py), and
``run_state.json`` (runtime/checkpoint.py) — all "atomically rename a
small JSON dict next to the run so an uncoordinated reader can poll
it", each with its own writer copy and each consumer with its own
staleness/liveness parsing.  This module is the single writer/reader
pair; the per-consumer copies are gone.

Schema: every beacon is one JSON object with two reserved keys added
by the writer —

  ``v``     schema version (``BEACON_VERSION``); readers reject
            versions NEWER than they know (a newer writer may have
            changed field meaning) and accept anything older or
            missing (pre-unification files still parse during a
            mixed-version fleet recovery),
  ``time``  ``time.time()`` at write, the staleness clock.

Tolerance contract (the same posture as the timeline readers): a
missing file, a torn/garbage file, or a stale ``time`` all read as
``None`` — beacons are advisory, and a reader must never crash or
block on one.  Liveness is optional and explicit: pass
``require_pid="pid"`` and a beacon whose pid is dead reads as None
(the fleet scheduler's port-discovery contract).
"""

from __future__ import annotations

import json
import os
import time
from typing import Optional

BEACON_VERSION = 1


def write_beacon(path: str, doc: dict) -> bool:
    """Atomically publish ``doc`` (plus ``v``/``time``) at ``path``.

    tmp + ``os.replace`` so a reader never sees a half-written file;
    the tmp name carries the pid so two writers (e.g. a stale worker
    and its replacement) cannot collide on it.  Best-effort: returns
    False instead of raising on OSError (a full disk must not kill a
    beacon thread, let alone the engine).
    """
    out = dict(doc)
    out.setdefault("v", BEACON_VERSION)
    out.setdefault("time", time.time())
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "w") as fh:
            json.dump(out, fh)
        os.replace(tmp, path)
        return True
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False


def pid_alive(pid) -> bool:
    try:
        os.kill(int(pid), 0)
        return True
    except (OSError, TypeError, ValueError):
        return False


def read_beacon(path: str, max_age_s: Optional[float] = None,
                require_pid: Optional[str] = None) -> Optional[dict]:
    """→ the beacon dict, or None if missing/torn/stale/dead.

    ``max_age_s`` bounds ``time.time() - doc["time"]`` (a beacon
    without a time field fails any age bound — it cannot prove
    freshness).  ``require_pid`` names the field holding the writer's
    pid; a dead or absent pid reads as None.
    """
    try:
        with open(path) as fh:
            doc = json.load(fh)
    except (OSError, json.JSONDecodeError, ValueError):
        return None
    if not isinstance(doc, dict):
        return None
    v = doc.get("v", 0)
    if isinstance(v, (int, float)) and v > BEACON_VERSION:
        return None
    if max_age_s is not None:
        ts = doc.get("time")
        if not isinstance(ts, (int, float)):
            return None
        if time.time() - ts > max_age_s:
            return None
    if require_pid is not None and not pid_alive(doc.get(require_pid)):
        return None
    return doc
