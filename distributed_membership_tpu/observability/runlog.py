"""Flight recorder, part 3: the structured run/ladder event log.

One JSONL event stream replaces the ad-hoc text logs the harness grew
(``artifacts/ladder_daemon*.log`` prints, ``rung_errors.log`` traceback
dumps): every record is ``{"ts": <iso8601Z>, "kind": <event>, ...}``, so
``scripts/run_report.py`` can render rung provenance, compile-vs-execute
timing, and per-segment checkpoint overlap from one file without parsing
free-form text.

Producers:
  * ``runtime/checkpoint.chunked_run`` — ``segments_start`` /
    ``segment`` (per-boundary wall, device-sync and checkpoint-write-wait
    seconds) / ``segments_done``, written to
    ``<TELEMETRY_DIR>/runlog.jsonl``;
  * ``scripts/profile_step.py`` — ``compile`` / ``execute`` timestamps
    per timing point (``--runlog``);
  * ``scripts/tpu_ladder.py`` — ``rung_start`` / ``rung_attempt`` /
    ``rung_timeout`` / ``rung_retry`` / ``rung_land`` / ``rung_fail`` /
    ``rung_error`` / ``pass_done`` into
    ``artifacts/ladder_events.jsonl``.

The log rotates by size (``path`` → ``path.1`` → … ``path.<keep>``) so a
long-lived ladder daemon cannot grow it unboundedly, and every append is
a single ``write`` of one line — a crash can tear at most the trailing
record, which :func:`read_events` skips.
"""

from __future__ import annotations

import json
import os
import time
from typing import List, Optional


class RunLog:
    """Append-only rotating JSONL event log."""

    def __init__(self, path: str, max_bytes: int = 4 << 20, keep: int = 2):
        self.path = path
        self.max_bytes = max_bytes
        self.keep = max(keep, 1)
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)

    def _rotate_if_needed(self) -> None:
        try:
            if os.path.getsize(self.path) < self.max_bytes:
                return
        except OSError:
            return
        for i in range(self.keep - 1, 0, -1):
            src = f"{self.path}.{i}"
            if os.path.exists(src):
                os.replace(src, f"{self.path}.{i + 1}")
        os.replace(self.path, f"{self.path}.1")

    def _tail_unterminated(self) -> bool:
        """True when the file ends mid-line (a previous writer died
        mid-append): the next record must start on a fresh line or it
        would concatenate onto — and corrupt — the torn one."""
        try:
            with open(self.path, "rb") as fh:
                fh.seek(-1, os.SEEK_END)
                return fh.read(1) != b"\n"
        except (OSError, ValueError):
            return False

    def event(self, kind: str, **fields) -> dict:
        """Append one event; returns the record (with its timestamp)."""
        rec = {"ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
               "t_mono": round(time.monotonic(), 3),
               "kind": kind}
        rec.update(fields)
        self._rotate_if_needed()
        lead = "\n" if self._tail_unterminated() else ""
        with open(self.path, "a") as fh:
            fh.write(lead + json.dumps(rec, default=str) + "\n")
        return rec


def read_events(path: str, kinds=None,
                include_rotated: bool = True) -> List[dict]:
    """Parse a RunLog file (oldest first, rotated generations included);
    skips torn/non-JSON lines.  ``kinds`` filters by event kind."""
    paths = []
    if include_rotated:
        gen = 1
        while os.path.exists(f"{path}.{gen}"):
            paths.append(f"{path}.{gen}")
            gen += 1
        paths.reverse()
    if os.path.exists(path):
        paths.append(path)
    out = []
    for p in paths:
        with open(p) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if kinds is None or rec.get("kind") in kinds:
                    out.append(rec)
    return out


def maybe_runlog(directory: Optional[str],
                 name: str = "runlog.jsonl") -> Optional[RunLog]:
    """A RunLog under ``directory`` when one is configured, else None —
    the one-liner chunked_run and the drivers gate their emission on.

    ``DM_RUNLOG_MAX_BYTES`` overrides the rotation threshold for every
    log built here (an env knob rather than a conf key: rotation is a
    host-side durability concern, not part of run identity — the same
    class as DM_CRASH_AT_TICK).  ``0`` disables rotation (unbounded);
    unset/invalid keeps the 4 MiB default.  Rotation preserves the
    reader contracts either way: :func:`read_events` walks the rotated
    generations oldest-first and skips torn lines, so last-t0-wins
    merging over the surviving window is unchanged."""
    if not directory:
        return None
    max_bytes = 4 << 20
    env = os.environ.get("DM_RUNLOG_MAX_BYTES", "")
    if env:
        try:
            v = int(env)
            # 0 = unbounded (a threshold no append reaches); negative
            # or unparsable values keep the default.
            max_bytes = (1 << 62) if v == 0 else v if v > 0 else max_bytes
        except ValueError:
            pass
    return RunLog(os.path.join(directory, name), max_bytes=max_bytes)
