"""Perf ledger: one append-only JSONL bank for every throughput number.

The repo measures performance in four disconnected places — ``bench.py``
legs (``BENCH_r*.json`` at the repo root), the multichip dry-run
(``MULTICHIP_r*.json``), the TPU ladder (``artifacts/TPU_PROFILE.json``)
and the scale smoke (``artifacts/SCALE_SMOKE.json``) — each with its own
schema and no cross-run memory: a rung that silently lost 30% between
two sessions is invisible unless someone diffs JSON by hand.  The ledger
normalizes all of them into one row shape, keyed by

    (rung, n, s, backend, platform, knobs_digest)

where ``knobs_digest`` is a stable hash of the remaining run-identity
knobs (mode, exchange, timing, mesh, ...), so rows are comparable iff
they measured the same configuration.  Rows append to
``artifacts/perf_ledger.jsonl``; ingestion is idempotent (a row identical
up to ingestion timestamp is skipped), writes are single-line appends
(same torn-tolerance contract as the other JSONL artifacts — the reader
skips damaged lines).

:func:`check` is the regression tripwire ``scripts/perf_ledger.py
--check`` and the bench/ladder wiring call: within each key group it
compares every row against the best earlier row and flags drops beyond
a noise band (default :data:`DEFAULT_NOISE_BAND` — container-CPU timing
noise between sessions is real, so the band is generous; the ladder's
own retry logic handles finer-grained regressions within a session).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import time
from typing import Dict, Iterable, List, Optional

LEDGER_PATH = os.path.join("artifacts", "perf_ledger.jsonl")

# Fractional drop vs the best banked row for the same key before a row
# counts as a regression.  Higher-is-better metrics only (throughput);
# lower-is-better metrics are stored with ``higher_is_better: False``.
DEFAULT_NOISE_BAND = 0.30

# Row fields that define identity for idempotent re-ingestion (the
# ingestion timestamp deliberately excluded).
_IDENTITY_FIELDS = ("key", "metric", "value", "source", "timestamp")


def knobs_digest(knobs: Optional[dict]) -> str:
    blob = json.dumps(knobs or {}, sort_keys=True, separators=(",", ":"))
    return hashlib.sha1(blob.encode()).hexdigest()[:12]


def make_row(rung: str, *, metric: str, value: float,
             n: Optional[int] = None, s: Optional[int] = None,
             backend: Optional[str] = None, platform: Optional[str] = None,
             knobs: Optional[dict] = None, source: Optional[str] = None,
             timestamp: Optional[str] = None,
             higher_is_better: bool = True) -> dict:
    knobs = dict(knobs or {})
    # Multi-tick-residency rows key per BLOCK SIZE: a truthy
    # knobs["mega_ticks"] lifts T into the rung itself (rung:t{T}), so
    # --check trends T=8 and T=32 separately — the knobs digest alone
    # would also separate them, but only the rung is human-readable in
    # the regression report, and a T=8 trend must never mask a T=32
    # regression behind an opaque digest.
    if knobs.get("mega_ticks"):
        rung = f"{rung}:t{int(knobs['mega_ticks'])}"
    # Multi-process rows key per PROCESS TOPOLOGY the same way: a truthy
    # knobs["procs"] lifts the process count into the rung (rung:p{P}),
    # so a single-process trend never masks a pod-run regression (the
    # cross-process collective legs dominate at P > 1 and the two
    # operating points move independently).
    if knobs.get("procs"):
        rung = f"{rung}:p{int(knobs['procs'])}"
    # Query-tier rows key per POOL WIDTH too: a truthy
    # knobs["service_workers"] lifts W into the rung (rung:w{W}) — the
    # engine-serves-queries point (W=0) and the replica-pool points
    # scale differently (one GIL vs W processes) and must trend
    # separately in the regression report.
    if knobs.get("service_workers"):
        rung = f"{rung}:w{int(knobs['service_workers'])}"
    # Elastic-resume rows key per RESUME KIND: a truthy
    # knobs["reshard"] lifts the reshard arm into the rung
    # (rung:reshard) — a same-shape resume trend must never mask a
    # reshard-path regression (the host-side redistribute + codec
    # round-trip exist only on that arm).
    if knobs.get("reshard"):
        rung = f"{rung}:reshard"
    digest = knobs_digest(knobs)
    key = "|".join([rung, str(n), str(s), str(backend), str(platform),
                    metric, digest])
    return {
        "key": key, "rung": rung, "n": n, "s": s, "backend": backend,
        "platform": platform, "knobs": knobs, "knobs_digest": digest,
        "metric": metric, "value": float(value),
        "higher_is_better": bool(higher_is_better),
        "source": source, "timestamp": timestamp,
        "ingested_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }


def load_ledger(path: str = LEDGER_PATH) -> List[dict]:
    """All ledger rows, oldest first; torn/non-JSON lines skipped."""
    rows = []
    if not os.path.exists(path):
        return rows
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(rec, dict) and "key" in rec and "value" in rec:
                rows.append(rec)
    return rows


def append_rows(rows: Iterable[dict], path: str = LEDGER_PATH) -> int:
    """Append rows not already banked (identity up to ingestion time);
    returns how many were actually written."""
    existing = {tuple(r.get(f) for f in _IDENTITY_FIELDS)
                for r in load_ledger(path)}
    fresh = [r for r in rows
             if tuple(r.get(f) for f in _IDENTITY_FIELDS) not in existing]
    if fresh:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path, "a") as fh:
            for r in fresh:
                fh.write(json.dumps(r, sort_keys=True) + "\n")
    return len(fresh)


def check(rows: List[dict],
          band: float = DEFAULT_NOISE_BAND) -> List[dict]:
    """Regressions: rows whose value dropped more than ``band`` below the
    best earlier row of the same key (or rose above it, for
    lower-is-better metrics).  Returns one record per offending row."""
    best: Dict[str, dict] = {}
    out = []
    for row in rows:
        key = row["key"]
        prior = best.get(key)
        if prior is not None:
            hib = row.get("higher_is_better", True)
            ref = prior["value"]
            val = row["value"]
            if ref > 0:
                drop = (ref - val) / ref if hib else (val - ref) / ref
                if drop > band:
                    out.append({
                        "key": key, "rung": row.get("rung"),
                        "metric": row.get("metric"),
                        "best": ref, "value": val,
                        "drop_pct": round(drop * 100, 1),
                        "band_pct": round(band * 100, 1),
                        "source": row.get("source"),
                    })
        if (prior is None
                or (row["value"] > prior["value"]) == row.get(
                    "higher_is_better", True)):
            best[key] = row
    return out


# ---------------------------------------------------------------------------
# Collectors: one per producer artifact family.

_BENCH_NS_RE = re.compile(r"N=(\d+)(?:, S=(\d+))?")
_BENCH_BACKEND_RE = re.compile(r"\((\w+) N=")
_MULTICHIP_RE = re.compile(r"mesh=(\d+) nodes=(\d+)")


def rows_from_bench(doc: dict, source: str) -> List[dict]:
    """BENCH_r*.json: headline parsed metric + the dense/live_cpu/
    hash_alt/hist side legs bench.py banks alongside it."""
    rows: List[dict] = []
    if doc.get("rc") not in (0, None):
        return rows
    parsed = doc.get("parsed")
    if not isinstance(parsed, dict):
        return rows
    metric_str = str(parsed.get("metric", ""))
    m = _BENCH_NS_RE.search(metric_str)
    n = int(m.group(1)) if m else None
    s = int(m.group(2)) if m and m.group(2) else None
    bk = _BENCH_BACKEND_RE.search(metric_str)
    if parsed.get("value") is not None:
        rows.append(make_row(
            "bench:headline", metric="node_ticks_per_sec",
            value=parsed["value"], n=n, s=s,
            backend=bk.group(1) if bk else None,
            platform=parsed.get("platform"),
            knobs={"timing": parsed.get("timing"),
                   "mode": parsed.get("mode"),
                   "unit": parsed.get("unit")},
            source=source))
    for leg in ("dense", "live_cpu", "hash_alt", "hist"):
        sub = parsed.get(leg)
        if not isinstance(sub, dict):
            continue
        if sub.get("node_ticks_per_sec") is None:
            continue
        rows.append(make_row(
            f"bench:{leg}", metric="node_ticks_per_sec",
            value=sub["node_ticks_per_sec"],
            n=sub.get("n"), s=sub.get("view_size"),
            backend=sub.get("leg") if leg == "dense" else "tpu_hash",
            platform=sub.get("platform", "cpu"),
            knobs={k: sub.get(k) for k in ("ticks", "exchange", "mode")
                   if sub.get(k) is not None},
            source=source))
    return rows


def rows_from_multichip(doc: dict, source: str) -> List[dict]:
    if doc.get("skipped"):
        return []
    m = _MULTICHIP_RE.search(str(doc.get("tail", "")))
    return [make_row(
        "multichip:dryrun", metric="ok",
        value=1.0 if doc.get("ok") else 0.0,
        n=int(m.group(2)) if m else None,
        platform="multichip",
        knobs={"mesh": int(m.group(1)) if m else None},
        source=source)]


def rows_from_tpu_profile(records: List[dict], source: str) -> List[dict]:
    rows = []
    for rec in records:
        if not isinstance(rec, dict):
            continue
        if rec.get("node_ticks_per_sec") is None:
            continue
        rows.append(make_row(
            f"ladder:{rec.get('rung')}", metric="node_ticks_per_sec",
            value=rec["node_ticks_per_sec"],
            n=rec.get("n"), s=rec.get("s"),
            backend=rec.get("backend"), platform=rec.get("platform"),
            knobs={k: rec.get(k) for k in ("timing", "mode", "exchange")
                   if rec.get(k) is not None},
            source=source, timestamp=rec.get("timestamp")))
    return rows


def rows_from_scale_smoke(records: List[dict], source: str) -> List[dict]:
    rows = []
    for rec in records:
        if not isinstance(rec, dict):
            continue
        if rec.get("node_ticks_per_sec") is None:
            continue
        rows.append(make_row(
            f"scale_smoke:{rec.get('n')}_s{rec.get('view_size')}",
            metric="node_ticks_per_sec",
            value=rec["node_ticks_per_sec"],
            n=rec.get("n"), s=rec.get("view_size"),
            backend=rec.get("backend"), platform=rec.get("platform"),
            knobs={k: rec.get(k) for k in
                   ("mesh_size", "ticks", "probes", "fanout")
                   if rec.get(k) is not None},
            source=source, timestamp=rec.get("timestamp")))
    return rows


def collect_all(root: str = ".") -> List[dict]:
    """Every banked perf row discoverable under ``root`` (repo layout:
    BENCH/MULTICHIP at the root, profiles under artifacts/)."""
    rows: List[dict] = []

    def _load(path):
        try:
            with open(path) as fh:
                return json.load(fh)
        except (OSError, json.JSONDecodeError):
            return None

    for name in sorted(os.listdir(root)):
        full = os.path.join(root, name)
        if re.fullmatch(r"BENCH_r\d+\.json", name):
            doc = _load(full)
            if isinstance(doc, dict):
                rows.extend(rows_from_bench(doc, name))
        elif re.fullmatch(r"MULTICHIP_r\d+\.json", name):
            doc = _load(full)
            if isinstance(doc, dict):
                rows.extend(rows_from_multichip(doc, name))
    for name, fn in (("TPU_PROFILE.json", rows_from_tpu_profile),
                     ("SCALE_SMOKE.json", rows_from_scale_smoke)):
        full = os.path.join(root, "artifacts", name)
        doc = _load(full)
        if isinstance(doc, list):
            rows.extend(fn(doc, os.path.join("artifacts", name)))
    return rows
