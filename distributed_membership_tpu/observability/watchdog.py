"""Mid-run SLO watchdog: degradation alerts while the run is alive.

Every SLO this repo can grade — detection-latency distribution, replica
staleness, oracle invariants — used to be computed AFTER the run, in
run_report/the campaign grader.  The watchdog moves the cheap rule
evaluations into the run itself: a daemon thread owned by the service
daemon (service/daemon.py) wakes at every segment boundary (the engine
hook's ``notify`` is one Event.set — O(1) on the engine thread) and
evaluates four rules off-thread:

  ``tick_rate_collapse``   the latest segment's tick rate fell below
                           half the rolling median of earlier segments
  ``publisher_backlog``    the snapshot publisher's submitted-vs-
                           published gap grew monotonically across the
                           last evaluations (the engine is lapping the
                           query tier)
  ``replica_staleness``    a live replica beacon serves a snapshot
                           more than STALENESS_FACTOR snapshot periods
                           behind the engine tick
  ``detection_slo``        the live ``h_latency`` reconstruction
                           (hist tier) fails the banked reference SLO
                           (observability/latency_dist.slo_verdict)

Alerts are structured runlog records (``kind: "alert"`` —
observability/runlog.py) with rising-edge dedup: a rule alerts once
when it trips and re-arms only after it recovers, so a 500-boundary
stall is one record, not 500.  scripts/run_report.py renders them as
timeline markers; the fleet summary counts them per run.  The rule
functions are pure (inputs in, verdict-or-None out) so the unit tests
(tests/test_metrics_plane.py) drive them with synthetic degradation —
no run needed.

The thread also owns the observed span stages (observability/spans.py
``update_observed_stages``) and the segment-timing metrics gauges:
everything that needs the timeline, the runlog, or the replica beacons
happens here, never on the engine thread.
"""

from __future__ import annotations

import glob
import os
import re
import threading
import time
from typing import List, Optional, Sequence

from distributed_membership_tpu.observability.beacon import read_beacon

TICK_RATE_MIN_SEGMENTS = 4       # baseline needs this many rates
TICK_RATE_COLLAPSE_FRACTION = 0.5
BACKLOG_GROWTH_EVALS = 3         # strictly-growing evals that trip
BACKLOG_MIN_TICKS = 2            # ... and the gap must reach this
STALENESS_FACTOR = 4             # x the snapshot period, in ticks
BEACON_FRESH_S = 10.0            # replica beacons older than this are
                                 # some dead replica's leftovers
EVAL_INTERVAL_S = 2.0            # idle re-evaluation period


def _median(xs: Sequence[float]) -> float:
    s = sorted(xs)
    n = len(s)
    return s[n // 2] if n % 2 else 0.5 * (s[n // 2 - 1] + s[n // 2])


# ---- pure rules (unit-testable with synthetic inputs) -----------------

def rule_tick_rate(rates: Sequence[float],
                   min_segments: int = TICK_RATE_MIN_SEGMENTS,
                   fraction: float = TICK_RATE_COLLAPSE_FRACTION
                   ) -> Optional[dict]:
    """``rates`` is the per-segment ticks/s history, oldest first.
    Trips when the latest rate collapses below ``fraction`` x the
    median of the earlier ones (median, not mean: one slow compile
    segment must not drag the baseline down with it)."""
    if len(rates) < min_segments:
        return None
    baseline = _median(rates[:-1])
    latest = rates[-1]
    if baseline > 0 and latest < fraction * baseline:
        return {"rule": "tick_rate_collapse", "severity": "warn",
                "rate_per_s": round(latest, 2),
                "baseline_per_s": round(baseline, 2)}
    return None


def rule_backlog(backlogs: Sequence[float],
                 evals: int = BACKLOG_GROWTH_EVALS,
                 min_ticks: float = BACKLOG_MIN_TICKS
                 ) -> Optional[dict]:
    """``backlogs`` is the submitted-minus-published tick gap at each
    evaluation, oldest first.  A transiently busy publisher bounces
    between 0 and one period — only a STRICTLY growing gap across
    ``evals`` observations (reaching ``min_ticks``) means the engine
    is durably outrunning the query tier."""
    if len(backlogs) < evals:
        return None
    tail = list(backlogs[-evals:])
    if all(x < y for x, y in zip(tail, tail[1:])) \
            and tail[-1] >= min_ticks:
        return {"rule": "publisher_backlog", "severity": "warn",
                "backlog_ticks": tail[-1], "history": tail}
    return None


def rule_staleness(lag_ticks: Optional[float], bound_ticks: float
                   ) -> Optional[dict]:
    """``lag_ticks`` is the worst fresh replica's engine-minus-snapshot
    tick gap (None = no fresh replica beacons, nothing to judge)."""
    if lag_ticks is None or lag_ticks <= bound_ticks:
        return None
    return {"rule": "replica_staleness", "severity": "warn",
            "lag_ticks": int(lag_ticks),
            "bound_ticks": int(bound_ticks)}


def rule_detection_slo(series: Optional[dict]) -> Optional[dict]:
    """The live SLO check over the hist tier's ``h_latency`` series
    (None/scalars-only runs are unassessable, never alerting)."""
    if series is None or "h_latency" not in series:
        return None
    from distributed_membership_tpu.observability.latency_dist import (
        slo_verdict)
    v = slo_verdict(series)
    if v["passed"] is False:
        return {"rule": "detection_slo", "severity": "error",
                "max_cdf_deviation": round(v["max_cdf_deviation"], 4),
                "threshold": v["threshold"],
                "detections_total": v["detections_total"]}
    return None


# ---- the daemon-owned thread ------------------------------------------

class Watchdog(threading.Thread):
    """Boundary-driven evaluator bound to a serve_run's ControlState.

    ``state`` duck-type: ``params``, ``total``, ``tick``, ``publisher``
    (or None), ``stop_event``, ``metrics`` (a MetricsRegistry), and
    optionally ``spans`` (a SpanLog).  ``runlog`` receives the alert
    records; None disables emission but rules still evaluate (the
    alert counter still counts).
    """

    def __init__(self, state, out_dir: str, runlog=None,
                 interval_s: float = EVAL_INTERVAL_S):
        super().__init__(daemon=True, name="slo-watchdog")
        self.state = state
        self.out_dir = out_dir
        self.runlog = runlog
        self.interval_s = interval_s
        self._wake = threading.Event()
        self._closing = False
        self._marks: List[tuple] = []      # (t_mono, tick) per notify
        self._backlogs: List[float] = []
        self._active = set()               # rules currently tripped
        self._lock = threading.Lock()
        self.alerts: List[dict] = []       # emitted (rising edges)
        p = state.params
        self.snapshot_period = max(
            p.CHECKPOINT_EVERY * max(p.SERVICE_SNAPSHOT_EVERY, 1), 1)
        self._m_alerts = state.metrics.counter(
            "dm_watchdog_alerts_total",
            "Watchdog alert rising edges by rule")
        self._m_rate = state.metrics.gauge(
            "dm_tick_rate_per_sec",
            "Engine ticks per second over the latest segment")
        self._m_wall = state.metrics.gauge(
            "dm_segment_wall_seconds",
            "Latest segment wall time (runlog)")
        self._m_sync = state.metrics.gauge(
            "dm_segment_device_sync_seconds",
            "Latest segment device-sync seconds (runlog)")
        self._m_ckpt = state.metrics.gauge(
            "dm_segment_ckpt_wait_seconds",
            "Latest segment checkpoint-wait seconds (runlog)")

    # O(1), called from the engine thread's boundary hook.
    def notify(self, tick: int) -> None:
        with self._lock:
            self._marks.append((time.monotonic(), int(tick)))
            if len(self._marks) > 256:
                del self._marks[:len(self._marks) - 256]
        self._wake.set()

    def close(self) -> None:
        self._closing = True
        self._wake.set()

    def alert_counts(self) -> dict:
        out: dict = {}
        for a in self.alerts:
            out[a["rule"]] = out.get(a["rule"], 0) + 1
        return out

    # ---- evaluation ---------------------------------------------------

    def _segment_rates(self) -> List[float]:
        with self._lock:
            marks = list(self._marks)
        rates = []
        for (t0, a), (t1, b) in zip(marks, marks[1:]):
            if t1 > t0 and b > a:
                rates.append((b - a) / (t1 - t0))
        return rates

    def _replica_lag(self) -> Optional[int]:
        worst = None
        for path in glob.glob(os.path.join(self.out_dir,
                                           "replica_*.json")):
            if not re.fullmatch(r"replica_\d+\.json",
                                os.path.basename(path)):
                continue
            doc = read_beacon(path, max_age_s=BEACON_FRESH_S)
            if doc is None:
                continue
            lag = doc.get("tick_lag")
            if isinstance(lag, (int, float)):
                worst = lag if worst is None else max(worst, lag)
        return worst

    def _timeline_series(self) -> Optional[dict]:
        path = self.state.timeline_path()
        if not path or not os.path.exists(path):
            return None
        from distributed_membership_tpu.observability.timeline import (
            read_timeline)
        try:
            return read_timeline(path)
        except Exception:
            return None

    def _replica_beacons(self) -> List[dict]:
        out = []
        for path in sorted(glob.glob(os.path.join(
                self.out_dir, "replica_*.json"))):
            if not re.fullmatch(r"replica_\d+\.json",
                                os.path.basename(path)):
                continue
            doc = read_beacon(path, max_age_s=BEACON_FRESH_S)
            if doc is not None:
                out.append(doc)
        return out

    def _segment_gauges(self) -> None:
        tel_dir = self.state.params.TELEMETRY_DIR or None
        if not tel_dir:
            return
        from distributed_membership_tpu.observability.runlog import (
            read_events)
        try:
            segs = read_events(os.path.join(tel_dir, "runlog.jsonl"),
                               kinds=("segment",),
                               include_rotated=False)
        except OSError:
            return
        if not segs:
            return
        s = segs[-1]
        sync = float(s.get("device_sync_s", 0.0))
        flush = float(s.get("flush_s", 0.0))
        ckpt = float(s.get("ckpt_wait_s", 0.0))
        self._m_wall.set(round(sync + flush + ckpt, 4))
        self._m_sync.set(sync)
        self._m_ckpt.set(ckpt)

    def _emit(self, alert: Optional[dict], boundary_tick: int) -> None:
        """Rising-edge dedup + emission for one rule evaluation."""
        if alert is None:
            return
        rule = alert["rule"]
        if rule in self._active:
            return
        self._active.add(rule)
        rec = dict(alert)
        rec["boundary_tick"] = int(boundary_tick)
        self.alerts.append(rec)
        self._m_alerts.inc(rule=rule)
        if self.runlog is not None:
            try:
                self.runlog.event("alert", **rec)
            except OSError:
                pass

    def evaluate(self) -> None:
        state = self.state
        tick = int(state.tick)
        rates = self._segment_rates()
        if rates:
            self._m_rate.set(round(rates[-1], 2))
        self._segment_gauges()

        backlog = 0.0
        pub = state.publisher
        if pub is not None:
            backlog = float(pub.backlog_ticks())
        self._backlogs.append(backlog)
        if len(self._backlogs) > 64:
            del self._backlogs[:len(self._backlogs) - 64]

        series = self._timeline_series()
        lag = self._replica_lag()

        verdicts = {
            "tick_rate_collapse": rule_tick_rate(rates),
            "publisher_backlog": rule_backlog(self._backlogs),
            "replica_staleness": rule_staleness(
                lag, STALENESS_FACTOR * self.snapshot_period),
            "detection_slo": rule_detection_slo(series),
        }
        for rule, alert in verdicts.items():
            if alert is None:
                self._active.discard(rule)   # recovered: re-arm
            else:
                self._emit(alert, tick)

        span_log = getattr(state, "spans", None)
        if span_log is not None:
            from distributed_membership_tpu.observability.spans import (
                read_spans, update_observed_stages)
            try:
                update_observed_stages(
                    span_log, read_spans(span_log.path), series,
                    self._replica_beacons())
            except Exception:
                pass        # spans are advisory; keep evaluating

    def run(self) -> None:
        while not self._closing and not self.state.stop_event.is_set():
            self._wake.wait(self.interval_s)
            self._wake.clear()
            if self._closing:
                break
            try:
                self.evaluate()
            except Exception:
                # The watchdog must never take the run down with it.
                pass
        # Final pass so stamps/alerts for the last boundary land.
        try:
            self.evaluate()
        except Exception:
            pass
