"""Message counters and the msgcount.log dump.

The reference's only profiler: per-(node, tick) send/receive count matrices
(EmulNet.h:83-84, incremented at EmulNet.cpp:111,172) dumped at shutdown in a
fixed text format (EmulNet::ENcleanup, EmulNet.cpp:189-218).  Every backend
carries these counters — as numpy arrays on the host path and as int32
tensors in the scan state on the TPU paths — and this writer reproduces the
dump format, including the reference's odd special-casing of node 67
(EmulNet.cpp:210-212).
"""

from __future__ import annotations

import os


def write_msgcount(result, out_dir: str = ".") -> str:
    """Dump sent/recv matrices in the EmulNet.cpp:189-218 format."""
    sent, recv = result.sent, result.recv
    n, total = sent.shape
    path = os.path.join(out_dir, "msgcount.log")
    chunks = []
    for i in range(n):
        node_id = i + 1
        chunks.append(f"node {node_id:3d} ")
        sent_total = int(sent[i].sum())
        recv_total = int(recv[i].sum())
        if node_id != 67:
            for j in range(total):
                chunks.append(f" ({int(sent[i, j]):4d}, {int(recv[i, j]):4d})")
                if j % 10 == 9:
                    chunks.append("\n         ")
        else:
            for j in range(total):
                chunks.append(f"special {j:4d} {int(sent[i, j]):4d} {int(recv[i, j]):4d}\n")
        chunks.append("\n")
        chunks.append(f"node {node_id:3d} sent_total {sent_total:6d}  recv_total {recv_total:6d}\n\n")
    with open(path, "w") as fh:
        fh.write("".join(chunks))
    return path


def removal_latencies(dbg_text: str, fail_time: int):
    """Detection latency distribution: ticks from failure to each logged
    removal of a failed node.  The parity metric BASELINE.md tracks
    (reference measures 21-22 single / 21-23 multi)."""
    failed_addrs = set()
    lats = []
    for line in dbg_text.splitlines():
        if "Node failed at time" in line:
            failed_addrs.add(line.split()[0])
    for line in dbg_text.splitlines():
        if "removed" not in line:
            continue
        parts = line.split()
        # " <logger> [t] Node <addr> removed at time <t>"
        removed_addr = parts[3]
        if removed_addr in failed_addrs:
            t = int(parts[1].strip("[]"))
            lats.append(t - fail_time)
    return lats
