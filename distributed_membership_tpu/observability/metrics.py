"""Message counters and the msgcount.log dump.

The reference's only profiler: per-(node, tick) send/receive count matrices
(EmulNet.h:83-84, incremented at EmulNet.cpp:111,172) dumped at shutdown in a
fixed text format (EmulNet::ENcleanup, EmulNet.cpp:189-218).  Every backend
carries these counters — as numpy arrays on the host path and as int32
tensors in the scan state on the TPU paths — and this writer reproduces the
dump format, including the reference's odd special-casing of node 67
(EmulNet.cpp:210-212).

At scale the full per-(node, tick) text matrix is the problem, not the
answer: N=1M x T=700 4-digit pairs is a multi-GB file nobody can read.
Above :data:`MSGCOUNT_FULL_MATRIX_MAX` nodes the writer emits the
totals-only form (one ``sent_total/recv_total`` line per node — the rows
the graders and tooling actually consume); the reference-scale full
matrix is retained below the threshold for grader parity.
"""

from __future__ import annotations

import os
import re

# Full per-tick matrix only at reference scale (matches EVENT_MODE auto's
# full-events threshold, config.resolved_event_mode); totals-only above.
MSGCOUNT_FULL_MATRIX_MAX = 4096


def write_msgcount(result, out_dir: str = ".",
                   totals_only: bool | None = None) -> str:
    """Dump sent/recv matrices in the EmulNet.cpp:189-218 format.

    ``totals_only`` (default: auto by node count) drops the per-tick
    pair matrix and keeps one ``node <id> sent_total ... recv_total ...``
    line per node — the multi-GB-file guard for large N."""
    sent, recv = result.sent, result.recv
    n, total = sent.shape
    if totals_only is None:
        totals_only = n > MSGCOUNT_FULL_MATRIX_MAX
    path = os.path.join(out_dir, "msgcount.log")
    chunks = []
    for i in range(n):
        node_id = i + 1
        sent_total = int(sent[i].sum())
        recv_total = int(recv[i].sum())
        if not totals_only:
            chunks.append(f"node {node_id:3d} ")
            if node_id != 67:
                for j in range(total):
                    chunks.append(
                        f" ({int(sent[i, j]):4d}, {int(recv[i, j]):4d})")
                    if j % 10 == 9:
                        chunks.append("\n         ")
            else:
                for j in range(total):
                    chunks.append(f"special {j:4d} {int(sent[i, j]):4d} "
                                  f"{int(recv[i, j]):4d}\n")
            chunks.append("\n")
        chunks.append(f"node {node_id:3d} sent_total {sent_total:6d}  "
                      f"recv_total {recv_total:6d}\n\n")
    with open(path, "w") as fh:
        fh.write("".join(chunks))
    return path


# Anchored on the reference phrasing (Log.cpp:129 "Node <addr> removed at
# time <t>"; Application.cpp:184/192 "Node failed at time[ ]=[ ]<t>"),
# with the logger address + bracketed time prefix the EventLog emits
# (" <addr> [<t>] <message>").  Variant logger prefixes (extra tokens
# before the address) and non-conforming lines are skipped instead of
# positionally mis-parsed — parts[3]/parts[1] indexing silently read the
# wrong fields the moment a prefix shifted the columns.
_FAILED_RE = re.compile(
    r"(\S+)\s+\[\d+\]\s+Node failed at time\s*=")
_REMOVED_RE = re.compile(
    r"\[(\d+)\]\s+Node\s+(\S+)\s+removed at time\s+\d+")


def removal_latencies(dbg_text: str, fail_time: int):
    """Detection latency distribution: ticks from failure to each logged
    removal of a failed node.  The parity metric BASELINE.md tracks
    (reference measures 21-22 single / 21-23 multi)."""
    failed_addrs = set()
    for line in dbg_text.splitlines():
        m = _FAILED_RE.search(line)
        if m:
            failed_addrs.add(m.group(1))
    lats = []
    for line in dbg_text.splitlines():
        m = _REMOVED_RE.search(line)
        if m and m.group(2) in failed_addrs:
            lats.append(int(m.group(1)) - fail_time)
    return lats
