"""End-to-end span records for injected events.

Every event accepted by ``POST /v1/events`` (service/events.py) gets a
trace through the stages an injection actually moves through:

  accepted            the POST passed validation (engine tick at accept)
  journaled           fsynced into service_events.jsonl — durable
  compiled            merged into a recompiled segment runner at a
                      boundary (the tick it takes effect from)
  first_detection     first tick >= the event's fire time where the
                      live timeline's ``detections`` series is non-zero
  removal             same, for the ``removals`` series
  visible_at_replica  a read replica served a snapshot at/after the
                      first-detection tick

Each stage is ONE appended JSONL line ``{"event_id", "stage", "tick",
"t_wall", ...}`` in ``spans.jsonl`` beside the run — the torn-tolerant
append/read posture of runlog.jsonl (a kill tears at most the trailing
line), and last-wins per (event_id, stage) so a resumed daemon may
re-stamp stages idempotently.  Event ids are deterministic in journal
order (``kind@time#seq``): a SIGKILL + ``--resume`` replays the journal
in the same order and re-derives the same ids, which is what keeps the
file consistent across lives (tests/test_metrics_plane.py pins it).

The live stages (accepted/journaled/compiled) are stamped by the
service daemon; the observed stages (first_detection/removal/
visible_at_replica) are stamped OFF the engine thread by the watchdog
(observability/watchdog.py) from the flight-recorder timeline and the
replica beacons — the engine never does span work beyond an O(1)
append.  ``crosscheck`` reconciles span latencies against the scenario
oracle's detection verdicts in scripts/run_report.py.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Dict, List, Optional

SPANS_NAME = "spans.jsonl"
STAGES = ("accepted", "journaled", "compiled", "first_detection",
          "removal", "visible_at_replica")


def event_id(ev: dict, seq: int) -> str:
    """Deterministic id: journal position + the event's own identity.

    ``seq`` is the event's 0-based position in the service journal —
    replaying the journal on resume reproduces the same ids, so resumed
    stamps land on the same spans."""
    t = ev.get("time", ev.get("start", "?"))
    return f"{ev.get('kind', '?')}@{t}#{seq}"


class SpanLog:
    """Append-only torn-tolerant JSONL span stream (runlog posture:
    one ``write`` per stamp, lead-newline repair after a torn tail)."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)

    def _tail_unterminated(self) -> bool:
        try:
            with open(self.path, "rb") as fh:
                fh.seek(-1, os.SEEK_END)
                return fh.read(1) != b"\n"
        except (OSError, ValueError):
            return False

    def stamp(self, eid: str, stage: str, tick: Optional[int] = None,
              **extra) -> dict:
        rec = {"event_id": eid, "stage": stage,
               "t_wall": round(time.time(), 3)}
        if tick is not None:
            rec["tick"] = int(tick)
        rec.update(extra)
        with self._lock:
            lead = "\n" if self._tail_unterminated() else ""
            try:
                with open(self.path, "a") as fh:
                    fh.write(lead + json.dumps(rec, default=str) + "\n")
            except OSError:
                pass            # spans are advisory; never kill the run
        return rec


def read_spans(path: str) -> Dict[str, Dict[str, dict]]:
    """→ {event_id: {stage: record}}, last-wins, torn lines skipped."""
    out: Dict[str, Dict[str, dict]] = {}
    if not os.path.exists(path):
        return out
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue        # torn trailing write
            eid, stage = rec.get("event_id"), rec.get("stage")
            if not eid or stage not in STAGES:
                continue
            out.setdefault(eid, {})[stage] = rec
    return out


def _first_nonzero_at_or_after(series: dict, field: str,
                               fire_tick: int) -> Optional[int]:
    vals = series.get(field)
    if vals is None:
        return None
    t0 = int(series.get("t0", 0))
    for i in range(max(fire_tick - t0, 0), len(vals)):
        if int(vals[i]) > 0:
            return t0 + i
    return None


def update_observed_stages(span_log: SpanLog,
                           spans: Dict[str, Dict[str, dict]],
                           series: Optional[dict],
                           replica_beacons: List[dict]) -> int:
    """Stamp the observed stages that have become decidable; → stamps
    written.  Idempotent: already-present stages are skipped, so the
    watchdog can call this at every evaluation (and a resumed run can
    call it over a spans file from a previous life)."""
    wrote = 0
    for eid, stages in spans.items():
        acc = stages.get("accepted")
        ev = (acc or {}).get("event") or {}
        fire = ev.get("time", ev.get("start"))
        if fire is None:
            continue
        det_tick = None
        if "first_detection" in stages:
            det_tick = stages["first_detection"].get("tick")
        elif series is not None:
            src = "detections"
            det_tick = _first_nonzero_at_or_after(
                series, "detections", int(fire))
            if det_tick is None:
                # EVENT_MODE full (the injection path) emits no
                # per-tick TRUE-detection scalar by design
                # (observability/timeline.py): the removal of the
                # crashed id IS the protocol's detection observation.
                src = "removals"
                det_tick = _first_nonzero_at_or_after(
                    series, "removals", int(fire))
            if det_tick is not None:
                span_log.stamp(eid, "first_detection", tick=det_tick,
                               latency_ticks=det_tick - int(fire),
                               source=src)
                wrote += 1
        if "removal" not in stages and series is not None:
            rm = _first_nonzero_at_or_after(series, "removals",
                                            int(fire))
            if rm is not None:
                span_log.stamp(eid, "removal", tick=rm)
                wrote += 1
        if ("visible_at_replica" not in stages and det_tick is not None
                and replica_beacons):
            best = None
            for b in replica_beacons:
                st = b.get("snapshot_tick")
                if isinstance(st, int) and st >= det_tick:
                    best = b if best is None else best
            if best is not None:
                span_log.stamp(eid, "visible_at_replica",
                               tick=best["snapshot_tick"],
                               replica=best.get("index"))
                wrote += 1
    return wrote


def crosscheck(spans: Dict[str, Dict[str, dict]],
               oracle_report: Optional[dict],
               series: Optional[dict] = None,
               tremove: Optional[int] = None) -> List[dict]:
    """Reconcile span stamps against the scenario oracle's verdicts
    (scenario/oracle.scenario_report) for every injected crash.

    Per crash event fired at tick T, three independently assessable
    consistency checks (unassessable ones pass vacuously — absence of
    an artifact stream is not an inconsistency, the oracle's own
    posture):

      * ``latency_supported`` — the span's detection latency
        (first_detection.tick − T) lands in a bucket the run's
        reconstructed h_latency distribution actually populated: the
        live trace and the flight recorder must tell the same story;
      * ``removal_in_window`` — when the oracle counted
        ``removals_within_2tremove`` for this crash, the span's
        removal stamp falls inside (T, T + 2*TREMOVE];
      * ``ordered`` — stage ticks are monotone: accepted <= compiled
        <= first_detection <= removal.

    → [{event_id, fire_tick, span_latency, ..., consistent}]."""
    from distributed_membership_tpu.observability.latency_dist import (
        latency_counts)
    crashes = {}
    for c in (oracle_report or {}).get("crashes", []):
        crashes[int(c["time"])] = c
    counts = None
    if series is not None and "h_latency" in series:
        counts = latency_counts(series)
        if not counts.sum():
            # No detections recorded (EVENT_MODE full's injection
            # path): no distribution to support the span against —
            # unassessable, same posture as slo_verdict's None.
            counts = None
    out = []
    for eid in sorted(spans):
        stages = spans[eid]
        ev = (stages.get("accepted") or {}).get("event") or {}
        fire = ev.get("time")
        det = stages.get("first_detection")
        if fire is None or det is None or det.get("tick") is None:
            continue
        fire = int(fire)
        lat = int(det["tick"]) - fire
        row = {"event_id": eid, "fire_tick": fire,
               "span_latency": lat}
        checks = []
        if counts is not None:
            ok = bool(0 <= lat < len(counts) and counts[lat] > 0)
            row["latency_supported"] = ok
            checks.append(ok)
        chk = crashes.get(fire)
        rm = stages.get("removal", {}).get("tick")
        if (chk is not None and tremove
                and chk.get("removals_within_2tremove")):
            ok = rm is not None and fire < rm <= fire + 2 * tremove
            row["removal_tick"] = rm
            row["removal_in_window"] = ok
            checks.append(ok)
        order = [stages[s].get("tick") for s in
                 ("accepted", "compiled", "first_detection", "removal")
                 if s in stages and stages[s].get("tick") is not None]
        ok = all(a <= b for a, b in zip(order, order[1:]))
        row["ordered"] = ok
        checks.append(ok)
        row["consistent"] = all(checks)
        out.append(row)
    return out
