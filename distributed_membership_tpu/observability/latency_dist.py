"""Detection-latency SLO: distribution reconstruction + reference compare.

The hist telemetry tier (``TELEMETRY: hist``, observability/timeline.py)
records ``h_latency`` — a per-tick ``[64]`` one-hot of ``t - fail_time``
scaled by that tick's true-detection count.  Because the buckets are
unit-width, summing the series over ticks reconstructs the detection-
latency distribution EXACTLY (the same multiset
:func:`..metrics.removal_latencies` parses out of dbg.log at reference
scale), at any N — including runs where nobody can afford to keep, ship,
or parse an event log.

The SLO itself is BASELINE.md's fidelity target ("detection-latency
distribution within 5% of the C++ EmulNet reference") made executable:
compare the reconstructed distribution against the banked reference via
the Kolmogorov statistic — the maximum absolute deviation between the
two normalized CDFs — and pass iff it is within
:data:`SLO_MAX_DEVIATION`.  A CDF-space compare is deliberately chosen
over per-bucket relative error: the reference multiset is tiny (9
removals), so a single removal sliding one tick flips per-bucket counts
by 100% while moving the CDF by ~1/9 — the Kolmogorov form measures the
distributional shift the SLO actually cares about.

``scripts/run_report.py --slo`` is the CLI face: it reconstructs from a
TELEMETRY_DIR's timeline.jsonl, renders the verdict, and drops
``slo.json`` next to the timeline.
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional

import numpy as np

# Banked reference distribution: latency tick -> removal count.
# Measured on testcases/singlefailure.conf (N=10, fail @ t=100) with
# BACKEND tpu_hash / EXCHANGE ring / seed 3 — byte-identical between the
# eventlog parse (metrics.removal_latencies) and the h_latency
# reconstruction (tests/test_latency_dist.py pins the exact match), and
# inside the C++ reference's measured window (BASELINE.md: removals @
# t=121-123, latencies 21-23).
REFERENCE_DISTRIBUTION: Dict[int, int] = {21: 4, 22: 4, 23: 1}

# BASELINE.md north-star: "detection-latency distribution within 5% of
# the C++ EmulNet reference".
SLO_MAX_DEVIATION = 0.05


def latency_counts(series) -> np.ndarray:
    """Total removals per unit latency bucket, ``[64]`` i64.

    ``series`` is either the dict :func:`..timeline.read_timeline`
    returns (uses its ``h_latency`` field) or a ``[K, 64]`` array."""
    if isinstance(series, Mapping):
        series = series["h_latency"]
    arr = np.asarray(series, dtype=np.int64)
    if arr.ndim == 1:
        return arr
    return arr.sum(axis=0)


def counts_from_mapping(dist: Mapping[int, int],
                        nbins: Optional[int] = None) -> np.ndarray:
    """A ``{latency: count}`` mapping as a dense bucket vector."""
    hi = max(dist) if dist else 0
    n = nbins if nbins is not None else hi + 1
    out = np.zeros((max(n, hi + 1),), dtype=np.int64)
    for k, v in dist.items():
        out[int(k)] += int(v)
    return out


def max_cdf_deviation(a, b) -> float:
    """Kolmogorov statistic between two bucket-count vectors: the max
    absolute difference of their normalized CDFs (0.0 when either side
    is empty — "no data" is reported separately, not as deviation)."""
    a = np.asarray(a, dtype=np.float64).reshape(-1)
    b = np.asarray(b, dtype=np.float64).reshape(-1)
    n = max(len(a), len(b))
    a = np.pad(a, (0, n - len(a)))
    b = np.pad(b, (0, n - len(b)))
    if a.sum() == 0 or b.sum() == 0:
        return 0.0
    return float(np.abs(np.cumsum(a / a.sum()) -
                        np.cumsum(b / b.sum())).max())


def slo_verdict(series,
                reference: Optional[Mapping[int, int]] = None,
                threshold: float = SLO_MAX_DEVIATION) -> dict:
    """The SLO report record: observed distribution, reference, the
    Kolmogorov deviation, and the pass/fail verdict.

    ``passed`` is None (verdict withheld, not failed) when the run saw
    zero detections — an all-zero histogram carries no distribution to
    compare, and failing it would turn every failure-free run red."""
    ref = dict(REFERENCE_DISTRIBUTION if reference is None else reference)
    counts = latency_counts(series)
    observed = {int(k): int(v) for k, v in enumerate(counts) if v}
    total = int(counts.sum())
    dev = max_cdf_deviation(counts, counts_from_mapping(ref, len(counts)))
    return {
        "slo": "detection_latency_distribution",
        "threshold": float(threshold),
        "max_cdf_deviation": dev,
        "detections_total": total,
        "observed": observed,
        "reference": {int(k): int(v) for k, v in sorted(ref.items())},
        "passed": None if total == 0 else bool(dev <= threshold),
    }
