"""On-device event aggregation for scale runs.

The grader-parity paths stack per-tick event tensors (``[T, N, M]`` join /
remove ids) and reconstruct dbg.log host-side — exact, but structurally
impossible at scale: N=1M, M=128, T=700 is ~350 GB.  The reference has the
same wall in miniature: its per-node×tick ``sent_msgs/recv_msgs[1001][3600]``
matrices (EmulNet.h:83-84) only exist because N is small.

This module is the scale replacement: a small set of ``[N]``-shaped (plus one
fixed-width histogram) accumulators carried *inside* the jitted scan state and
updated with one masked scatter-add per tick, so a 1M-node run produces the
full detection-latency distribution, completeness and accuracy verdicts, and
msgcount totals — everything the grading oracle measures — in O(N) memory,
independent of T.

Accumulators (all updated only on the aggregate path — the parity path's
behavior and cost are untouched):
  * ``rm_count[N]``   — removal events naming id i (all observers, all ticks);
  * ``rm_first[N]``   — first tick any observer removed id i (INT32_MAX none);
  * ``rm_last[N]``    — last such tick;
  * ``join_count[N]`` — join events naming id i;
  * ``trackers[N]``   — how many views held id i at the failure-injection
    tick: the denominator for per-view detection completeness (a bounded
    view tracks ~M members, so "all N-1 survivors detect" is replaced by
    "every *tracker* detects" — the SWIM-scale completeness criterion);
  * ``lat_hist[LAT_BINS]`` — histogram of (removal tick - fail_time) over
    removal events naming *failed* ids: the detection-latency distribution
    (BASELINE.md fidelity row) straight off the device;
  * ``sent_total[N] / recv_total[N]`` — per-node message totals (msgcount.log
    totals row, EmulNet.cpp:189-218, without the per-tick matrix).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


def _phase_scoped(fn):
    """Wrap an update in the flight recorder's aggregation phase scope
    (observability/timeline.PHASE_AGG) so profiler captures attribute
    its cost separately from the exchange (zero-op: named_scope only
    prefixes op metadata)."""
    from distributed_membership_tpu.observability.timeline import PHASE_AGG

    @functools.wraps(fn)
    def wrapped(*args, **kw):
        with jax.named_scope(PHASE_AGG):
            return fn(*args, **kw)
    return wrapped

I32 = jnp.int32
LAT_BINS = 512          # ticks-after-failure resolution; last bin is overflow
_NO_TICK = np.iinfo(np.int32).max


class AggStats(NamedTuple):
    rm_count: jax.Array    # [N] i32 — ALL removal events naming id i
    det_count: jax.Array   # [N] i32 — true detections only: removals of a
    #                        crashed id strictly after the crash tick
    rm_first: jax.Array    # [N] i32, INT32_MAX = never removed
    rm_last: jax.Array     # [N] i32, -1 = never removed
    join_count: jax.Array  # [N] i32
    trackers: jax.Array    # [N] i32, views holding id i at fail_time
    tracker_obs: jax.Array  # [N] bool — live node i held >=1 crashed id at
    #                         the crash tick (distinct-observer denominator)
    det_obs: jax.Array     # [N] bool — node i issued >=1 true detection
    #                        (distinct-observer numerator; event counts alone
    #                        can overcount via readmission churn)
    lat_hist: jax.Array    # [LAT_BINS] i32
    sent_total: jax.Array  # [N] i32
    recv_total: jax.Array  # [N] i32


def init_agg(n: int, rows: int | None = None) -> AggStats:
    """``rows`` (default N) sizes the observer-row-indexed fields — a
    node-sharded backend passes its local row count and psum/gathers the
    partials after its scan (backends/tpu_hash_sharded.py)."""
    rows = n if rows is None else rows
    return AggStats(
        rm_count=jnp.zeros((n,), I32),
        det_count=jnp.zeros((n,), I32),
        rm_first=jnp.full((n,), _NO_TICK, I32),
        rm_last=jnp.full((n,), -1, I32),
        join_count=jnp.zeros((n,), I32),
        trackers=jnp.zeros((n,), I32),
        tracker_obs=jnp.zeros((rows,), bool),
        det_obs=jnp.zeros((rows,), bool),
        lat_hist=jnp.zeros((LAT_BINS,), I32),
        sent_total=jnp.zeros((rows,), I32),
        recv_total=jnp.zeros((rows,), I32),
    )


@_phase_scoped
def update_agg(agg: AggStats, *, t: jax.Array,
               join_ids: jax.Array, rm_ids: jax.Array,
               view_ids: jax.Array, view_present: jax.Array,
               fail_mask: jax.Array, fail_time: jax.Array,
               sent_tick: jax.Array, recv_tick: jax.Array,
               holder_failed: jax.Array | None = None) -> AggStats:
    """One tick's aggregate update (pure, jittable, O(rows*M) scatter-adds).

    ``join_ids`` / ``rm_ids``: ``[rows, M]`` member ids (EMPTY/-1 = none) —
    the same per-slot event tensors the parity path would have stacked.
    ``view_ids`` / ``view_present``: the post-merge view table, used once (at
    ``t == fail_time``) to count trackers per id.  ``fail_mask`` is indexed
    by *global member id*; ``holder_failed`` (default: fail_mask) is the
    observer-row-aligned crash mask — a sharded caller passes its local
    slice.
    """
    n = agg.rm_count.shape[0]
    if holder_failed is None:
        holder_failed = fail_mask

    def count_by_id(ids, mask):
        sel = jnp.where(mask, ids, n)
        return jnp.zeros((n + 1,), I32).at[sel.reshape(-1)].add(
            1, mode="drop")[:n]

    rm_mask = rm_ids >= 0
    rm_add = count_by_id(rm_ids, rm_mask)
    removed_any = rm_add > 0
    rm_count = agg.rm_count + rm_add
    rm_first = jnp.where(removed_any, jnp.minimum(agg.rm_first, t),
                         agg.rm_first)
    rm_last = jnp.where(removed_any, jnp.maximum(agg.rm_last, t), agg.rm_last)

    join_count = agg.join_count + count_by_id(join_ids, join_ids >= 0)

    # Tracker census, captured exactly once (the failure-injection tick) —
    # lax.cond so the O(N*M) scatter runs on that one tick, not all T.
    # Rows belonging to nodes that crash are excluded: a dead holder (and
    # its self entry) can never detect, so it is not a completeness
    # denominator.
    at_fail = t == fail_time
    live_holder = ~holder_failed[:, None]
    holds_failed = view_present & fail_mask[jnp.clip(view_ids, 0)]
    trackers, tracker_obs = jax.lax.cond(
        at_fail,
        lambda: (count_by_id(view_ids, view_present & live_holder),
                 holds_failed.any(axis=1) & ~holder_failed),
        lambda: (agg.trackers, agg.tracker_obs))

    # True detections: removals naming a crashed id strictly after the
    # crash.  A removal of that id *before* the crash is a false positive
    # and must count as one — not as a detection with clipped latency.
    true_rm = rm_mask & fail_mask[jnp.clip(rm_ids, 0)] & (t > fail_time)
    det_count = agg.det_count + count_by_id(rm_ids, true_rm)
    det_obs = agg.det_obs | true_rm.any(axis=1)

    # Latency histogram: all true detections this tick share latency
    # (t - fail_time); clip into the overflow bin (reported explicitly by
    # detection_summary).
    lat = jnp.clip(t - fail_time, 0, LAT_BINS - 1)
    lat_hist = agg.lat_hist.at[lat].add(true_rm.sum(dtype=I32))

    return AggStats(rm_count, det_count, rm_first, rm_last, join_count,
                    trackers, tracker_obs, det_obs, lat_hist,
                    agg.sent_total + sent_tick, agg.recv_total + recv_tick)


class FastAgg(NamedTuple):
    """Scatter-free aggregates for the ring-exchange scale path.

    ``update_agg`` costs three full-width ``[rows*M]``-index scatter-adds per
    tick (``count_by_id``) — cheap next to the scatter-based message
    exchange, but the dominant per-tick cost once the exchange itself is
    roll/gather-based (tpu_hash ``exchange='ring'``).  When the failed-id
    set is small and known host-side (it always is: the FailurePlan is
    computed up front, runtime/failures.py), everything the detection
    summary needs reduces to per-failed-id *elementwise* compares and
    scalar reductions — no scatters at all.  Per-id ``join_count`` /
    ``rm_count`` histograms are dropped: the summary only ever consumed
    their sums, which the per-tick scalar event outputs already carry.
    """
    det_count: jax.Array    # [F] i32 — true detections per failed id
    trackers: jax.Array     # [F] i32 — live views holding id f at fail_time
    tracker_obs: jax.Array  # [rows] bool — held >=1 crashed id at the crash
    det_obs: jax.Array      # [rows] bool — issued >=1 true detection
    lat_hist: jax.Array     # [LAT_BINS] i32
    join_total: jax.Array   # [] i32 — all join events
    rm_total: jax.Array     # [] i32 — all removal events (false = rm - det)
    sent_total: jax.Array   # [rows] i32
    recv_total: jax.Array   # [rows] i32


FAST_AGG_MAX_FAILED = 8     # per-id work is F elementwise passes; beyond
#                             this the scatter-based AggStats path wins


def init_fast_agg(n_failed: int, rows: int) -> FastAgg:
    return FastAgg(
        det_count=jnp.zeros((max(n_failed, 1),), I32),
        trackers=jnp.zeros((max(n_failed, 1),), I32),
        tracker_obs=jnp.zeros((rows,), bool),
        det_obs=jnp.zeros((rows,), bool),
        lat_hist=jnp.zeros((LAT_BINS,), I32),
        join_total=jnp.zeros((), I32),
        rm_total=jnp.zeros((), I32),
        sent_total=jnp.zeros((rows,), I32),
        recv_total=jnp.zeros((rows,), I32),
    )


@_phase_scoped
def update_fast_agg(agg: FastAgg, *, t: jax.Array, fail_ids: tuple,
                    join_events: jax.Array, rm_ids: jax.Array,
                    view_ids: jax.Array, view_present: jax.Array,
                    fail_time: jax.Array, holder_failed: jax.Array,
                    sent_tick: jax.Array, recv_tick: jax.Array,
                    row_any=None, row_expand=None, pre=None) -> FastAgg:
    """One tick, all elementwise/reduce (``fail_ids`` is a STATIC tuple).

    ``join_events``: [rows, M] bool (admissions this tick); ``rm_ids``:
    [rows, M] member ids (EMPTY = none); ``holder_failed``: [rows] bool
    crash mask aligned to observer rows (a sharded caller passes its local
    slice).  ``row_any`` / ``row_expand`` map between the event plane and
    per-observer [rows] vectors — default to ``any(axis=1)`` /
    ``v[:, None]`` for the natural [rows, M] layout; the folded layout
    passes its segment-aware pair (backends/tpu_hash_folded.py).

    ``pre`` (optional dict) supplies PRECOMPUTED per-tick reductions of
    the rm plane — keys ``det_tick`` ([F] i32, NOT yet gated by
    ``t > fail_time``), ``any_true_rm`` ([rows] bool), and ``rm_total``
    (scalar i32).  The FUSED_PROBE kernel emits these as row partials
    riding its state traversal (ops/fused_probe), so the per-fail-id
    compare passes over ``rm_ids`` are skipped here; integer sums and
    or-reductions are order-free, so the results are bit-equal.  The
    fail-tick tracker census still reads the view planes (cond-gated to
    one tick).
    """
    rm_mask = rm_ids >= 0
    post = t > fail_time
    if row_any is None:
        def row_any(m):
            return m.any(axis=1)
    if row_expand is None:
        def row_expand(v):
            return v[:, None]
    n_obs = holder_failed.shape[0]

    if fail_ids:
        if pre is not None:
            det_tick = pre["det_tick"] * post.astype(I32)
            any_true_rm = pre["any_true_rm"]
        else:
            per_f_rm = [rm_mask & (rm_ids == f) for f in fail_ids]
            det_tick = jnp.stack(
                [m.sum(dtype=I32) for m in per_f_rm]) * post.astype(I32)
            any_true_rm = jnp.zeros((n_obs,), bool)
            for m in per_f_rm:
                any_true_rm = any_true_rm | row_any(m)

        def census():
            live = ~row_expand(holder_failed)
            tr = jnp.stack([(view_present & (view_ids == f) & live)
                            .sum(dtype=I32) for f in fail_ids])
            holds = jnp.zeros((n_obs,), bool)
            for f in fail_ids:
                holds = holds | row_any(view_present & (view_ids == f))
            return tr, holds & ~holder_failed

        trackers, tracker_obs = jax.lax.cond(
            t == fail_time, census, lambda: (agg.trackers, agg.tracker_obs))
    else:
        det_tick = jnp.zeros_like(agg.det_count)
        any_true_rm = jnp.zeros((n_obs,), bool)
        trackers, tracker_obs = agg.trackers, agg.tracker_obs

    lat = jnp.clip(t - fail_time, 0, LAT_BINS - 1)
    return FastAgg(
        det_count=agg.det_count + det_tick,
        trackers=trackers,
        tracker_obs=tracker_obs,
        det_obs=agg.det_obs | (any_true_rm & post),
        lat_hist=agg.lat_hist.at[lat].add(det_tick.sum()),
        join_total=agg.join_total + join_events.sum(dtype=I32),
        rm_total=agg.rm_total + (rm_mask.sum(dtype=I32) if pre is None
                                 else pre["rm_total"]),
        sent_total=agg.sent_total + sent_tick,
        recv_total=agg.recv_total + recv_tick,
    )


def merge_agg(a, b):
    """Merge two aggregate pytrees computed over DISJOINT tick ranges of
    the same run (host-side, numpy) — the cross-segment accumulator of the
    chunked/checkpointed sharded driver (runtime/checkpoint.py).

    Every field has a clean merge because each is either a sum over ticks
    (counts, histogram, totals), an or over ticks (observer flags), an
    extremum (first/last removal tick, with the init values as
    identities), or captured in exactly one segment (the fail-tick census
    — zero everywhere else, so ``+`` is exact)."""
    if isinstance(a, FastAgg):
        return FastAgg(
            det_count=np.add(a.det_count, b.det_count),
            trackers=np.add(a.trackers, b.trackers),
            tracker_obs=np.logical_or(a.tracker_obs, b.tracker_obs),
            det_obs=np.logical_or(a.det_obs, b.det_obs),
            lat_hist=np.add(a.lat_hist, b.lat_hist),
            join_total=np.add(a.join_total, b.join_total),
            rm_total=np.add(a.rm_total, b.rm_total),
            sent_total=np.add(a.sent_total, b.sent_total),
            recv_total=np.add(a.recv_total, b.recv_total),
        )
    return AggStats(
        rm_count=np.add(a.rm_count, b.rm_count),
        det_count=np.add(a.det_count, b.det_count),
        rm_first=np.minimum(a.rm_first, b.rm_first),
        rm_last=np.maximum(a.rm_last, b.rm_last),
        join_count=np.add(a.join_count, b.join_count),
        trackers=np.add(a.trackers, b.trackers),
        tracker_obs=np.logical_or(a.tracker_obs, b.tracker_obs),
        det_obs=np.logical_or(a.det_obs, b.det_obs),
        lat_hist=np.add(a.lat_hist, b.lat_hist),
        sent_total=np.add(a.sent_total, b.sent_total),
        recv_total=np.add(a.recv_total, b.recv_total),
    )


def latency_stats(hist: np.ndarray) -> dict:
    """min/max/p50/p99/overflow/nonzero-bins view of a latency histogram
    (shared by detection_summary, fast_summary, and the phase sweep)."""
    hist = np.asarray(hist)
    total_det = int(hist.sum())
    if not total_det:
        return {}
    ticks = np.arange(hist.shape[0])
    cdf = np.cumsum(hist)
    return {
        "latency_min": int(ticks[hist > 0][0]),
        "latency_max": int(ticks[hist > 0][-1]),
        "latency_p50": int(np.searchsorted(cdf, 0.50 * total_det)),
        "latency_p99": int(np.searchsorted(cdf, 0.99 * total_det)),
        # Detections at >= LAT_BINS-1 ticks land in the last bin; when
        # nonzero, max/percentiles at the last bin mean ">= that".
        "latency_overflow_count": int(hist[hist.shape[0] - 1]),
        "latency_hist_nonzero": {
            int(k): int(v) for k, v in zip(ticks[hist > 0], hist[hist > 0])},
    }


def _completeness_stats(trackers: np.ndarray, detections: np.ndarray,
                        tracker_obs: np.ndarray, det_obs: np.ndarray,
                        n_failed: int, total_det: int) -> dict:
    tracker_nodes = int(tracker_obs.sum())
    detecting = int((det_obs & tracker_obs).sum())
    return {
        "failed_nodes": n_failed,
        "trackers_per_failed_min": int(trackers.min()),
        "trackers_per_failed_mean": float(trackers.mean()),
        "detections_total": total_det,
        # Distinct-observer completeness: of the live nodes that held a
        # crashed id at the crash, how many issued >= 1 true detection.
        # (Event-count ratios can overcount via post-crash readmission
        # churn; this is the honest grader-style criterion.)
        "tracker_nodes": tracker_nodes,
        "observer_completeness": (
            detecting / tracker_nodes if tracker_nodes else 1.0),
        # Event-count view, per failed id (>=1 event per tracker view).
        "detection_completeness": float((detections >= trackers).mean()),
        "detected_by_someone": float((detections > 0).mean()),
    }


def fast_summary(agg: FastAgg, fail_ids, fail_time: int | None) -> dict:
    """detection_summary for FastAgg — same keys, same criteria."""
    agg = jax.tree.map(np.asarray, agg)
    det_total = int(agg.det_count.sum())
    out = {
        "n": agg.tracker_obs.shape[0],
        "joins_total": int(agg.join_total),
        "false_removals": int(agg.rm_total) - det_total,
        "msgs_sent": int(agg.sent_total.sum()),
        "msgs_recv": int(agg.recv_total.sum()),
    }
    if fail_time is not None and len(fail_ids):
        f = len(fail_ids)
        out.update(_completeness_stats(
            agg.trackers[:f], agg.det_count[:f], agg.tracker_obs,
            agg.det_obs, f, int(agg.lat_hist.sum())))
        out.update(latency_stats(agg.lat_hist))
    return out


def detection_summary(agg: AggStats, fail_mask: np.ndarray,
                      fail_time: int | None) -> dict:
    """Host-side verdicts from the aggregates: the grading oracle's
    completeness/accuracy criteria (Grader_verbose.sh semantics) recast for
    tracker-relative bounded views, plus the latency distribution."""
    if isinstance(agg, FastAgg):
        fail_ids = tuple(np.nonzero(np.asarray(fail_mask, bool))[0])
        return fast_summary(agg, fail_ids, fail_time)
    agg = jax.tree.map(np.asarray, agg)
    fail_mask = np.asarray(fail_mask, bool)
    n = agg.rm_count.shape[0]

    # Accuracy: every removal that is not a true detection is false —
    # including removals of a to-be-crashed id before its crash.
    false_removals = int(agg.rm_count.sum() - agg.det_count.sum())
    out = {
        "n": n,
        "joins_total": int(agg.join_count.sum()),
        "false_removals": false_removals,          # accuracy: must be 0
        "msgs_sent": int(agg.sent_total.sum()),
        "msgs_recv": int(agg.recv_total.sum()),
    }
    if fail_time is not None and fail_mask.any():
        failed = np.nonzero(fail_mask)[0]
        out.update(_completeness_stats(
            agg.trackers[failed], agg.det_count[failed], agg.tracker_obs,
            agg.det_obs, int(fail_mask.sum()), int(agg.lat_hist.sum())))
        out.update(latency_stats(agg.lat_hist))
    return out
