"""Typed in-process metrics registry with a Prometheus-text encoder.

The metrics plane's one shared vocabulary: every running surface (the
engine daemon, each read replica, the fleet controller) builds a
:class:`MetricsRegistry`, registers counters/gauges/histograms once,
and serves ``registry.render()`` from ``GET /metrics`` (wired through
``service/api.route_get`` so all three surfaces share one route).

Design constraints, in order:

  * **Zero new dependencies** — the text exposition format
    (``# HELP`` / ``# TYPE`` + ``name{label="v"} value`` lines) is
    trivial to emit from the stdlib, and any Prometheus-compatible
    scraper parses it.  No client library is vendored or imported.
  * **Cheap on the hot path** — ``Counter.inc`` / ``Gauge.set`` are a
    dict store under one registry lock; no allocation beyond the label
    key tuple.  Nothing here ever runs on the engine thread: the
    instruments are updated by the API handler threads and the
    watchdog thread, so telemetry-off programs stay op-count identical
    (the census pin in tests/test_hlo_census.py is untouched).
  * **Deterministic text** — families render in registration order and
    label sets in sorted order, so the golden-format test
    (tests/test_metrics_plane.py) can pin the shape without fuzzing.

``parse_text`` is the strict inverse used by the golden test and by
the fleet daemon's scrape-union path; ``relabel`` rewrites sample
lines to inject the fleet's ``run_id``/``proc``/``replica`` labels
without re-parsing values.  :class:`LatencyReservoir` is the sampled
sliding-window p50/p99 estimator that used to live privately in
service/replica.py — hoisted here so the engine daemon's query tier
reports latency the same way the replicas do.
"""

from __future__ import annotations

import re
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)$")


def _fmt(v: float) -> str:
    """Prometheus sample value: integers without the trailing .0 (so
    counters read naturally), floats via repr (round-trip exact)."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape(v: str) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _unescape(v: str) -> str:
    return (v.replace("\\n", "\n").replace('\\"', '"')
            .replace("\\\\", "\\"))


def _label_str(labels: Sequence[Tuple[str, str]]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{_escape(v)}"' for k, v in labels)
    return "{" + inner + "}"


class _Instrument:
    """One metric family: a name, a help line, and per-label-set
    values.  The label key is the sorted (k, v) tuple so ``inc(a=1,
    b=2)`` and ``inc(b=2, a=1)`` hit the same series."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str,
                 lock: threading.Lock):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        self.name = name
        self.help = help_text
        self._lock = lock
        self._values: Dict[Tuple[Tuple[str, str], ...], float] = {}

    @staticmethod
    def _key(labels: dict) -> Tuple[Tuple[str, str], ...]:
        return tuple(sorted((k, str(v)) for k, v in labels.items()))

    def render_into(self, out: List[str],
                    const: Sequence[Tuple[str, str]]) -> None:
        out.append(f"# HELP {self.name} {self.help}")
        out.append(f"# TYPE {self.name} {self.kind}")
        for key in sorted(self._values):
            out.append(f"{self.name}{_label_str(tuple(const) + key)} "
                       f"{_fmt(self._values[key])}")


class Counter(_Instrument):
    kind = "counter"

    def inc(self, amount: float = 1, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + amount

    def set_total(self, value: float, **labels) -> None:
        """For counters mirrored from an external monotonic source
        (e.g. ControlState.queries): store the absolute total."""
        with self._lock:
            self._values[self._key(labels)] = value


class Gauge(_Instrument):
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[self._key(labels)] = value

    def clear(self) -> None:
        """Drop every series (fleet scrape gauges are rebuilt whole
        each pass; stale workers must not linger)."""
        with self._lock:
            self._values.clear()


class Histogram(_Instrument):
    """Cumulative-bucket histogram (native Prometheus shape).

    ``observe`` bins into the first bucket whose upper bound holds the
    value; render emits the cumulative ``_bucket{le=...}`` ladder plus
    ``_sum``/``_count``, one ladder per label set.
    """

    kind = "histogram"

    def __init__(self, name, help_text, lock,
                 buckets: Sequence[float]):
        super().__init__(name, help_text, lock)
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError("histogram needs >= 1 bucket bound")
        self._counts: Dict[Tuple[Tuple[str, str], ...], List] = {}

    def observe(self, value: float, **labels) -> None:
        key = self._key(labels)
        with self._lock:
            rec = self._counts.get(key)
            if rec is None:
                rec = self._counts[key] = [
                    [0] * (len(self.buckets) + 1), 0.0, 0]
            counts, _, _ = rec
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            rec[1] += value
            rec[2] += 1

    def render_into(self, out, const) -> None:
        out.append(f"# HELP {self.name} {self.help}")
        out.append(f"# TYPE {self.name} {self.kind}")
        for key in sorted(self._counts):
            counts, total, n = self._counts[key]
            cum = 0
            for i, b in enumerate(self.buckets):
                cum += counts[i]
                lbl = _label_str(tuple(const) + key
                                 + (("le", _fmt(b)),))
                out.append(f"{self.name}_bucket{lbl} {cum}")
            lbl = _label_str(tuple(const) + key + (("le", "+Inf"),))
            out.append(f"{self.name}_bucket{lbl} {n}")
            base = _label_str(tuple(const) + key)
            out.append(f"{self.name}_sum{base} {_fmt(total)}")
            out.append(f"{self.name}_count{base} {n}")


class MetricsRegistry:
    """Registration-ordered family set with shared const labels.

    ``constlabels`` (e.g. ``{"proc": "0"}`` under multi-process,
    ``{"replica": "2"}`` on a replica) are stamped onto every sample
    line at render time — instruments never need to know them.
    """

    def __init__(self, constlabels: Optional[dict] = None):
        self._lock = threading.Lock()
        self._families: List[_Instrument] = []
        self._names: Dict[str, _Instrument] = {}
        self.constlabels = tuple(sorted(
            (k, str(v)) for k, v in (constlabels or {}).items()))

    def _add(self, inst: _Instrument) -> _Instrument:
        prior = self._names.get(inst.name)
        if prior is not None:
            if type(prior) is not type(inst):
                raise ValueError(
                    f"metric {inst.name!r} re-registered as a "
                    f"different type")
            return prior
        self._families.append(inst)
        self._names[inst.name] = inst
        return inst

    def counter(self, name: str, help_text: str) -> Counter:
        return self._add(Counter(name, help_text, self._lock))

    def gauge(self, name: str, help_text: str) -> Gauge:
        return self._add(Gauge(name, help_text, self._lock))

    def histogram(self, name: str, help_text: str,
                  buckets: Sequence[float]) -> Histogram:
        return self._add(Histogram(name, help_text, self._lock,
                                   buckets))

    def render(self) -> str:
        out: List[str] = []
        with self._lock:
            for fam in self._families:
                fam.render_into(out, self.constlabels)
        return "\n".join(out) + "\n" if out else ""


def parse_text(text: str) -> Dict[Tuple[str, Tuple[Tuple[str, str],
                                                   ...]], float]:
    """Strict exposition-format parser → {(name, labels): value}.

    The golden test's oracle and the fleet union's reader.  Raises
    ValueError on any malformed sample line (comments and blanks are
    skipped) — strictness is the point: the encoder above must produce
    text this accepts, which is exactly what an external scraper
    needs.
    """
    out: Dict = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"malformed sample line {line!r}")
        name, labelstr, value = m.groups()
        labels = _parse_labels(labelstr) if labelstr else ()
        try:
            val = float(value)
        except ValueError as e:
            raise ValueError(
                f"malformed value in {line!r}") from e
        out[(name, labels)] = val
    return out


def _parse_labels(s: str) -> Tuple[Tuple[str, str], ...]:
    """``a="x",b="y\\"z"`` → sorted ((a, x), (b, y"z)).  A tiny state
    machine rather than a regex: label values may contain escaped
    quotes and commas."""
    labels = []
    i, n = 0, len(s)
    while i < n:
        j = s.index("=", i)
        key = s[i:j].strip()
        if not _NAME_RE.match(key):
            raise ValueError(f"malformed label name {key!r}")
        if j + 1 >= n or s[j + 1] != '"':
            raise ValueError(f"unquoted label value after {key!r}")
        k = j + 2
        buf = []
        while k < n:
            c = s[k]
            if c == "\\" and k + 1 < n:
                buf.append(s[k:k + 2])
                k += 2
                continue
            if c == '"':
                break
            buf.append(c)
            k += 1
        else:
            raise ValueError(f"unterminated label value for {key!r}")
        labels.append((key, _unescape("".join(buf))))
        i = k + 1
        if i < n:
            if s[i] != ",":
                raise ValueError(f"junk after label {key!r}: "
                                 f"{s[i:]!r}")
            i += 1
    return tuple(sorted(labels))


def relabel(text: str, extra: dict) -> str:
    """Inject ``extra`` labels into every sample line of ``text``.

    The fleet daemon's union step: a worker's own exposition comes
    back verbatim, gains ``run_id="..."`` (and keeps whatever
    ``proc``/``replica`` labels the worker stamped), and is
    concatenated into the fleet reply.  Existing keys are NOT
    overridden — the surface closest to the data wins.
    """
    add = tuple(sorted((k, str(v)) for k, v in extra.items()))
    out = []
    for line in text.splitlines():
        stripped = line.strip()
        if not stripped or stripped.startswith("#"):
            out.append(line)
            continue
        m = _SAMPLE_RE.match(stripped)
        if m is None:
            continue                 # drop malformed, keep the rest
        name, labelstr, value = m.groups()
        have = dict(_parse_labels(labelstr)) if labelstr else {}
        for k, v in add:
            have.setdefault(k, v)
        merged = tuple(sorted(have.items()))
        out.append(f"{name}{_label_str(merged)} {value}")
    return "\n".join(out) + "\n" if out else ""


class LatencyReservoir:
    """Sampled sliding-window latency estimator (p50/p99).

    Hoisted from service/replica.py so the engine daemon and the
    replicas report query latency identically: every ``sample_every``-th
    request is timed, the last ``window`` samples are kept, and the
    percentiles read from the sorted window.  ``should_sample`` is a
    modulo on the caller's own request counter so the reservoir needs
    no counter of its own.
    """

    SAMPLE_EVERY = 16
    WINDOW = 512

    def __init__(self, sample_every: int = SAMPLE_EVERY,
                 window: int = WINDOW):
        self.sample_every = sample_every
        self.window = window
        self._lock = threading.Lock()
        self._ms: List[float] = []

    def should_sample(self, request_index: int) -> bool:
        return request_index % self.sample_every == 0

    def record(self, ms: float) -> None:
        with self._lock:
            self._ms.append(ms)
            if len(self._ms) > self.window:
                del self._ms[:len(self._ms) - self.window]

    def percentiles(self) -> dict:
        with self._lock:
            lat = sorted(self._ms)
        if not lat:
            return {"p50_ms": None, "p99_ms": None}
        return {
            "p50_ms": round(lat[len(lat) // 2], 4),
            "p99_ms": round(lat[min(len(lat) - 1,
                                    int(len(lat) * 0.99))], 4),
        }


class ScrapeRate:
    """q/s between scrapes: remembers (t, count) at the last render
    and reports the delta rate, the same shape the replica beacons
    use for their 1 Hz qps field."""

    def __init__(self):
        self._lock = threading.Lock()
        self._t = time.monotonic()
        self._count = 0

    def rate(self, count: int) -> float:
        now = time.monotonic()
        with self._lock:
            dt = now - self._t
            dq = count - self._count
            self._t, self._count = now, count
        return round(dq / dt, 1) if dt > 0 else 0.0
