"""Chaos campaigns: seeded scenario fuzzing, oracle-gated sweeps,
auto-shrunk regression repros.

The scenario engine (scenario/) made chaos schedules *declarative*; the
oracle (scenario/oracle.py) made grading *mechanical* (hard invariant
verdicts).  This package closes the loop and makes chaos *cheap to run
in bulk*:

  * :mod:`.fuzz` — a seeded fuzzer that turns a campaign spec (seed,
    schedule count, N, tick budget, event-mix weights) into
    random-but-valid scenario JSON over the full event vocabulary.
    Every schedule in a campaign shares one
    :class:`..scenario.compile.ScenarioStatic` (fixed per-kind event
    counts), so a whole campaign pays ONE jitted compile.
  * :mod:`.campaign` — the runner: fans schedules out in-process or as
    fleet submissions (sweeps/fleet_submit.py plumbing), grades every
    run with the oracle's invariant verdicts, and journals per-run
    verdicts into a torn-tolerant ``campaign.jsonl`` that
    ``scripts/run_report.py --watch`` renders live.
  * :mod:`.shrink` — deterministic delta debugging of violating
    schedules down to a minimal repro, banked with its seed + campaign
    digest so the bug reproduces from the JSON alone.
"""

from distributed_membership_tpu.chaos.fuzz import (        # noqa: F401
    CampaignSpec, campaign_digest, dump_schedule, fuzz_schedule,
    kind_counts, schedule_digest)
from distributed_membership_tpu.chaos.campaign import (    # noqa: F401
    read_journal, run_campaign)
from distributed_membership_tpu.chaos.shrink import (      # noqa: F401
    bank_repro, shrink_schedule)
