"""Campaign runner: fan fuzzed schedules out, grade, journal, shrink.

A campaign is ``spec.schedules`` runs of the SAME conf under different
fuzzed chaos schedules (chaos/fuzz.py), each graded by the scenario
oracle's hard invariant verdicts (scenario/oracle.py).  Two execution
modes share the grading and journaling tail:

  * **inproc** — every run executes in this process through the jitted
    backend runner.  Because the fuzzer holds ``ScenarioStatic`` fixed
    across the campaign, the whole sweep pays ONE compile; this is the
    CI tier (tests/test_chaos.py runs an 8-schedule campaign inside the
    slow-budget audit).
  * **fleet** — schedules ship inline to a ``--fleet`` controller
    (sweeps/fleet_submit.py: retrying submit, terminal-state wait) and
    verdicts are graded from each run dir's ``scenario.json`` oracle
    report (the worker's finish_run writes it — the controller's
    ring-family workers always run with ``--telemetry-dir``).

Every graded run appends one line to ``campaign.jsonl`` — write +
flush + fsync per line, so a reader (scripts/run_report.py --watch) or
a crashed campaign never sees more than one torn line, and
:func:`read_journal` skips it.  Violating schedules are delta-debugged
to a minimal repro (chaos/shrink.py) and banked with the campaign
digest + seed; the journal records the shrink start and the banked
path, so a watcher shows "currently shrinking" honestly.
"""

from __future__ import annotations

import json
import os
from typing import Callable, List, Optional

from distributed_membership_tpu.chaos.fuzz import (
    CampaignSpec, campaign_digest, dump_schedule, fuzz_schedule,
    schedule_digest)
from distributed_membership_tpu.chaos.shrink import (
    bank_repro, shrink_schedule)

#: Conf the campaign grades against; spec fields fill the blanks.
#: tpu_hash + ring + warm join + agg events + scalar telemetry is the
#: cheapest config that exercises the full scenario vocabulary AND
#: records the series the oracle grades from.
_CONF_TEMPLATE = (
    "MAX_NNB: {n}\nSINGLE_FAILURE: 0\nDROP_MSG: 0\nMSG_DROP_PROB: 0\n"
    "VIEW_SIZE: {view}\nGOSSIP_LEN: {gossip}\nPROBES: {probes}\nFANOUT: 2\n"
    "TFAIL: {tfail}\nTREMOVE: {tremove}\nTOTAL_TIME: {total}\n"
    "JOIN_MODE: warm\nEVENT_MODE: agg\nEXCHANGE: ring\n"
    "TELEMETRY: scalars\nBACKEND: tpu_hash\n")


def base_conf(spec: CampaignSpec, overrides: Optional[dict] = None) -> str:
    """The campaign's conf text; ``overrides`` lets a caller grade a
    DELIBERATELY broken config (the acceptance exercise: TREMOVE <
    TFAIL must produce violations that shrink to banked repros)."""
    from distributed_membership_tpu.sweeps.fleet_submit import override_conf
    view = max(4, min(16, spec.n // 2 * 2))
    # Probe rate scaled so a full view refresh fits >= 4 times inside
    # TREMOVE (config.py's probe-cycle floor) at any campaign N.
    probes = max(2, -(-view * 4 // max(1, spec.tremove)))
    conf = _CONF_TEMPLATE.format(n=spec.n, total=spec.total,
                                 tfail=spec.tfail, tremove=spec.tremove,
                                 view=view, gossip=max(2, view // 2),
                                 probes=probes)
    for k, v in sorted((overrides or {}).items()):
        conf = override_conf(conf, k, v)
    return conf


class Journal:
    """Torn-tolerant append-only JSONL (one fsynced line per event)."""

    def __init__(self, path: str):
        self.path = path
        self._fh = open(path, "a")

    def append(self, obj: dict) -> None:
        self._fh.write(json.dumps(obj, sort_keys=True) + "\n")
        self._fh.flush()
        os.fsync(self._fh.fileno())

    def close(self) -> None:
        self._fh.close()


def read_journal(path: str) -> List[dict]:
    """Replay a ``campaign.jsonl``; torn/corrupt lines are skipped (a
    campaign killed mid-write loses at most its last line)."""
    rows: List[dict] = []
    try:
        with open(path) as fh:
            for line in fh:
                try:
                    rows.append(json.loads(line))
                except ValueError:
                    continue
    except OSError:
        pass
    return rows


def _grade(report: Optional[dict]) -> dict:
    """Journal-row fields from an oracle report (None = run lost)."""
    if not report or "invariants" not in report:
        return {"ok": False, "violations": ["no_oracle_report"]}
    return {
        "ok": bool(report["ok"]),
        "violations": list(report["violations"]),
        "live": report.get("final", {}).get("live"),
        "false_removals": report.get("detection_summary",
                                     {}).get("false_removals"),
    }


def _run_inproc(conf_text: str, scn_path: str, seed: int) -> dict:
    """One run through the jitted backend; -> the oracle report.

    Schedules carrying harness-level ``migrate`` events (chaos/fuzz.py,
    opt-in mix) take the elastic path: checkpointed, killed at each
    migrate tick, resharded, resumed — the oracle then grades the same
    full trajectory a migration-free run produces, because chunked
    resume is byte-exact repo-wide."""
    from distributed_membership_tpu.backends import get_backend
    from distributed_membership_tpu.config import Params
    from distributed_membership_tpu.sweeps.fleet_submit import override_conf
    try:
        with open(scn_path) as fh:
            sch = json.load(fh)
    except (OSError, ValueError):
        sch = {}
    migrations = sorted({int(e["time"]) for e in sch.get("events", ())
                         if e.get("kind") == "migrate"})
    if migrations:
        return _run_inproc_migrating(conf_text, sch, scn_path, seed,
                                     migrations)
    params = Params.from_text(
        override_conf(conf_text, "SCENARIO", scn_path))
    r = get_backend(params.BACKEND)(params, seed=seed)
    return r.extra["scenario_report"]


def _run_inproc_migrating(conf_text: str, sch: dict, scn_path: str,
                          seed: int, migrations) -> dict:
    """Execute a schedule's migrate events for real: run chunked, inject
    the kill at each migrate tick (the same fault a worker death
    leaves), reshard the durable carry in place (same geometry — the
    provenance chain and codec round-trip are what this exercises), and
    resume.  The engine never sees ``migrate``: it gets a stripped
    scenario side file."""
    from distributed_membership_tpu.backends import get_backend
    from distributed_membership_tpu.config import Params
    from distributed_membership_tpu.elastic.reshard import reshard
    from distributed_membership_tpu.runtime.checkpoint import (
        CRASH_ENV, load_manifest)
    from distributed_membership_tpu.sweeps.fleet_submit import override_conf
    engine_path = scn_path + ".engine.json"
    engine = dict(sch)
    engine["events"] = [e for e in sch.get("events", ())
                        if e.get("kind") != "migrate"]
    with open(engine_path, "w") as fh:
        fh.write(dump_schedule(engine))
    ck = scn_path + ".ckpt"
    conf = override_conf(conf_text, "SCENARIO", engine_path)
    conf = override_conf(conf, "CHECKPOINT_EVERY", 10)
    conf = override_conf(conf, "CHECKPOINT_DIR", ck)
    conf = override_conf(conf, "RESUME", 1)
    params = Params.from_text(conf)
    run = get_backend(params.BACKEND)
    prev = os.environ.get(CRASH_ENV)
    try:
        for t in migrations:
            os.environ[CRASH_ENV] = str(t)
            try:
                r = run(params, seed=seed)
                return r.extra["scenario_report"]   # tick past the end
            except RuntimeError as e:
                if "injected crash" not in str(e):
                    raise
            if load_manifest(ck) is not None:
                # Same-geometry reshard: codec round-trip + provenance
                # stamp without changing where the run resumes.
                reshard([ck], [ck])
        os.environ.pop(CRASH_ENV, None)
        r = run(params, seed=seed)
    finally:
        if prev is None:
            os.environ.pop(CRASH_ENV, None)
        else:
            os.environ[CRASH_ENV] = prev
    return r.extra["scenario_report"]


def oracle_predicate(conf_text: str, seed: int, probe_path: str,
                     target: set) -> Callable[[dict], bool]:
    """The shrinker's predicate: does this candidate still trip one of
    the ORIGINAL violations?  Schema-invalid candidates (ddmin dropping
    a crash whose restart stayed, say) count as non-violating."""
    def violating(cand: dict) -> bool:
        with open(probe_path, "w") as fh:
            fh.write(dump_schedule(cand))
        try:
            report = _run_inproc(conf_text, probe_path, seed)
        except ValueError:
            return False
        return bool(target.intersection(report["violations"]))
    return violating


def run_campaign(spec: CampaignSpec, out_dir: str, *,
                 overrides: Optional[dict] = None,
                 mode: str = "inproc",
                 port: Optional[int] = None,
                 fleet_root: Optional[str] = None,
                 shrink: bool = True,
                 bank_dir: Optional[str] = None,
                 progress: Optional[Callable[[str], None]] = None) -> dict:
    """Run a full campaign; -> summary dict (also journaled).

    ``out_dir`` receives ``scenarios/`` (every fuzzed schedule, banked
    as runnable JSON), ``campaign.jsonl``, and — for violations —
    ``regressions/`` (unless ``bank_dir`` redirects the bank).
    """
    if mode not in ("inproc", "fleet"):
        raise ValueError(f"mode {mode!r}: expected inproc|fleet")
    if mode == "fleet" and (port is None or fleet_root is None):
        raise ValueError("fleet mode needs port= and fleet_root=")
    say = progress or (lambda s: None)
    os.makedirs(out_dir, exist_ok=True)
    scen_dir = os.path.join(out_dir, "scenarios")
    os.makedirs(scen_dir, exist_ok=True)
    conf_text = base_conf(spec, overrides)
    digest = campaign_digest(spec)
    journal = Journal(os.path.join(out_dir, "campaign.jsonl"))
    journal.append({"kind": "campaign", "digest": digest, "mode": mode,
                    "spec": spec.to_dict(),
                    "overrides": dict(overrides or {})})

    schedules, paths, seeds = [], [], []
    for i in range(spec.schedules):
        sch = fuzz_schedule(spec, i)
        path = os.path.join(scen_dir, f"{sch['name']}.json")
        with open(path, "w") as fh:
            fh.write(dump_schedule(sch))
        schedules.append(sch)
        paths.append(path)
        seeds.append(spec.seed + i)

    reports: List[Optional[dict]] = []
    if mode == "inproc":
        for i, (sch, path, seed) in enumerate(
                zip(schedules, paths, seeds)):
            reports.append(_run_inproc(conf_text, path, seed))
            _journal_graded(journal, spec, i, sch, seed, reports[-1])
            say(f"{sch['name']}: "
                f"{'ok' if reports[-1]['ok'] else 'VIOLATION'}")
    else:
        reports = _run_fleet(journal, spec, schedules, seeds, conf_text,
                             port, fleet_root, say)

    violators = [(i, r) for i, r in enumerate(reports)
                 if not (r and r.get("ok"))]
    repros = []
    if shrink:
        bank = bank_dir or os.path.join(out_dir, "regressions")
        probe = os.path.join(out_dir, "shrink_probe.json")
        for i, report in violators:
            if not report or "violations" not in report:
                continue            # lost run: nothing to shrink
            target = set(report["violations"])
            journal.append({"kind": "shrinking",
                            "run_id": schedules[i]["name"],
                            "violations": sorted(target)})
            say(f"shrinking {schedules[i]['name']} ({sorted(target)})")
            minimal, stats = shrink_schedule(
                schedules[i],
                oracle_predicate(conf_text, seeds[i], probe, target))
            path = bank_repro(minimal, bank, {
                "seed": seeds[i], "campaign": digest,
                "violations": sorted(target),
                "shrunk_from": schedule_digest(schedules[i]),
                "probes": stats["probes"],
                # The repro only violates under the campaign's conf —
                # carry the deliberate breakage for self-containedness.
                "overrides": dict(overrides or {})})
            repros.append(path)
            journal.append({"kind": "shrunk",
                            "run_id": schedules[i]["name"],
                            "path": path, "probes": stats["probes"],
                            "events": stats["events_after"]})
            say(f"banked {path} ({stats['events_after']} events, "
                f"{stats['probes']} probes)")

    summary = {"kind": "done", "digest": digest,
               "runs": len(schedules),
               "violations": [schedules[i]["name"] for i, _ in violators],
               "repros": repros,
               "ok": not violators}
    journal.append(summary)
    journal.close()
    return summary


def _journal_graded(journal: Journal, spec: CampaignSpec, index: int,
                    sch: dict, seed: int, report: Optional[dict]) -> None:
    journal.append({"kind": "graded", "run_id": sch["name"],
                    "index": index, "seed": seed,
                    "digest": schedule_digest(sch), **_grade(report)})


def _run_fleet(journal: Journal, spec: CampaignSpec, schedules, seeds,
               conf_text: str, port: int, fleet_root: str,
               say) -> List[Optional[dict]]:
    """Fleet fan-out: inline scenario submissions, graded from each run
    dir's oracle report once the grid is terminal."""
    from distributed_membership_tpu.sweeps.fleet_submit import (
        submit_grid, wait_grid)
    # Harness-level migrate events are inproc-only (the controller's
    # own FLEET_MIGRATE_* machinery owns migration in fleet mode);
    # strip them and say so in the journal rather than silently.
    stripped = 0
    subs = []
    for sch, seed in zip(schedules, seeds):
        events = [e for e in sch["events"] if e.get("kind") != "migrate"]
        stripped += len(sch["events"]) - len(events)
        subs.append({"conf": conf_text, "run_id": sch["name"],
                     "seed": seed,
                     "scenario": {"name": sch["name"], "events": events}})
    if stripped:
        journal.append({"kind": "note",
                        "note": f"fleet mode: stripped {stripped} "
                                "harness-level migrate event(s); use "
                                "inproc mode or FLEET_MIGRATE_ON to "
                                "exercise migration"})
        say(f"stripped {stripped} migrate event(s) (fleet mode)")
    submit_grid(port, subs)
    say(f"submitted {len(subs)} runs to fleet :{port}")
    rows = wait_grid(port, [s["run_id"] for s in subs])
    reports: List[Optional[dict]] = []
    for i, (sch, seed) in enumerate(zip(schedules, seeds)):
        report = None
        if rows.get(sch["name"], {}).get("state") == "done":
            try:
                with open(os.path.join(fleet_root, sch["name"],
                                       "scenario.json")) as fh:
                    report = json.load(fh)
            except (OSError, ValueError):
                report = None
        reports.append(report)
        _journal_graded(journal, spec, i, sch, seed, report)
    return reports
