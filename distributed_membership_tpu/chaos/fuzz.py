"""Seeded schedule fuzzer: campaign spec -> random-but-valid scenarios.

Design constraints, in order:

1. **Deterministic.**  Schedule ``i`` of a campaign is a pure function
   of ``(spec, i)`` — ``random.Random(f"chaos:{seed}:{i}")``, nothing
   else.  Same spec, same index, byte-identical JSON
   (:func:`dump_schedule` is the canonical encoding the digests pin).

2. **One compile per campaign.**  The jitted runner caches on
   ``ScenarioStatic`` — tensor shapes, i.e. per-kind event counts.  The
   fuzzer therefore fixes the per-kind counts ONCE per campaign
   (largest-remainder apportionment of ``spec.events`` over the mix
   weights, :func:`kind_counts`) and randomizes only times, node
   ranges, and probabilities.  A 64-schedule campaign compiles once.

3. **Green on a healthy protocol.**  Schedules are random but not
   adversarial to the ORACLE: every generated schedule leaves
   ``settle_ticks`` of quiet tail (so excused false removals heal and
   permanent failures finish removing), and windows that would trip
   ``no_false_removals`` WITHOUT qualifying for one of its
   schedule-derived excuses are bounded away from the tripwire — mild
   flakes stay under the ``heavy_loss`` probability threshold, hard
   one-way blackholes and long delay windows are stretched PAST the
   excuse thresholds (>= TFAIL ticks) so the oracle knows the schedule
   masked liveness.  A violation on an unmodified protocol is therefore
   a real bug, not fuzzer noise.

Churn storms (clustered crash/restart pairs on disjoint ranges) and
flapping nodes (repeated crash/restart cycles on ONE range) are
composed from the existing crash/restart primitives — no new event
kinds, just time-sequenced reuse of a range.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import random
from typing import Mapping, Optional, Tuple

# Default event-mix weights (relative; zero drops a kind entirely).
DEFAULT_MIX: Mapping[str, float] = {
    "crash": 2.0,
    "restart": 1.5,
    "leave": 0.5,
    "partition": 1.0,
    "link_flake": 1.0,
    "drop_window": 0.5,
    "one_way_flake": 1.0,
    "delay_window": 1.0,
}

# Mild loss stays strictly under oracle._masking_excuses' heavy_loss
# probability threshold (0.5): no excuse needed, none granted.
_MILD_PROBS = (0.05, 0.1, 0.15, 0.2, 0.25, 0.3)


@dataclasses.dataclass(frozen=True)
class CampaignSpec:
    """Everything that defines a campaign; the digest pins it."""
    seed: int = 0
    schedules: int = 64
    n: int = 10
    total: int = 160          # tick budget per run
    tfail: int = 8
    tremove: int = 20
    events: int = 6           # events per schedule (pre-apportionment)
    mix: Optional[Mapping[str, float]] = None   # None -> DEFAULT_MIX
    name: str = "chaos"

    def weights(self) -> Mapping[str, float]:
        return DEFAULT_MIX if self.mix is None else self.mix

    def settle_ticks(self) -> int:
        """Quiet tail after the last event: long enough for a removal
        to complete (TFAIL + TREMOVE) and for excused false removals to
        heal by re-admission."""
        return max(2 * self.tremove, 3 * self.tfail)

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["mix"] = {k: float(v) for k, v in sorted(self.weights().items())}
        return d


def _canonical(obj) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def campaign_digest(spec: CampaignSpec) -> str:
    return hashlib.sha256(
        _canonical(spec.to_dict()).encode()).hexdigest()[:16]


def schedule_digest(schedule: dict) -> str:
    return hashlib.sha256(
        dump_schedule(schedule).encode()).hexdigest()[:16]


def dump_schedule(schedule: dict) -> str:
    """The canonical byte encoding (digest + byte-stability contract)."""
    return json.dumps(schedule, sort_keys=True, indent=1) + "\n"


def kind_counts(spec: CampaignSpec) -> Mapping[str, int]:
    """Largest-remainder apportionment of ``spec.events`` over the mix.

    Deterministic, and the SAME for every schedule in the campaign —
    this is what keeps ``ScenarioStatic`` constant (fuzzer contract #2).
    Restarts never outnumber crashes (each restart re-raises a crashed
    range); the excess is reassigned to ``crash``.
    """
    weights = {k: float(v) for k, v in spec.weights().items() if v > 0}
    if not weights:
        raise ValueError("campaign mix has no positive weights")
    wsum = sum(weights.values())
    quota = {k: spec.events * w / wsum for k, w in weights.items()}
    counts = {k: int(q) for k, q in quota.items()}
    short = spec.events - sum(counts.values())
    # Stable remainder order: largest fraction first, name breaks ties.
    order = sorted(weights, key=lambda k: (-(quota[k] - counts[k]), k))
    for k in order[:short]:
        counts[k] += 1
    extra = counts.get("restart", 0) - counts.get("crash", 0)
    if extra > 0:
        counts["restart"] -= extra
        counts["crash"] = counts.get("crash", 0) + extra
    return {k: v for k, v in sorted(counts.items()) if v > 0}


class _NodeAlloc:
    """Disjoint contiguous node-range allocator for down-events.

    Crash/restart chains, permanent crashes, and leaves get ranges that
    never overlap each other (overlapping down-chains can be VALID but
    make time-sequencing ambiguous — the fuzzer does not need them to
    cover the vocabulary).  At most half the group is ever allocated,
    so the membership always has a live majority to heal from.
    """

    def __init__(self, rng: random.Random, n: int):
        self.rng = rng
        self.n = n
        self.used: set = set()
        self.budget = max(1, n // 2)

    def take(self, width: int) -> Tuple[int, int]:
        """A free range; narrows down to width 1 under fragmentation
        (range WIDTH does not touch ScenarioStatic — only the range
        COUNT does — so narrowing preserves the one-compile contract
        while dropping the event would break it)."""
        if len(self.used) >= self.budget:
            raise ValueError(
                f"down-event node budget exhausted ({self.budget} of "
                f"{self.n}) — fuzz_schedule's upfront check is wrong")
        width = max(1, min(width, self.budget - len(self.used)))
        for w in range(width, 0, -1):
            for _ in range(32):
                lo = self.rng.randrange(0, self.n - w + 1)
                span = range(lo, lo + w)
                if not self.used.intersection(span):
                    self.used.update(span)
                    return (lo, lo + w)
            for lo in range(self.n - w + 1):   # deterministic sweep
                span = range(lo, lo + w)
                if not self.used.intersection(span):
                    self.used.update(span)
                    return (lo, lo + w)
        raise AssertionError("unreachable: width-1 always fits "
                             "under budget")


def _any_range(rng: random.Random, n: int, max_width: int) -> Tuple[int, int]:
    w = rng.randint(1, max(1, max_width))
    lo = rng.randrange(0, n - w + 1)
    return (lo, lo + w)


def fuzz_schedule(spec: CampaignSpec, index: int) -> dict:
    """Schedule ``index`` of the campaign (module docstring contracts)."""
    if not 0 <= index:
        raise ValueError(f"index {index} out of range")
    rng = random.Random(f"chaos:{spec.seed}:{index}")
    n, tfail = spec.n, spec.tfail
    counts = dict(kind_counts(spec))
    lo_t = max(4, tfail // 2)
    hi_t = spec.total - spec.settle_ticks()
    if hi_t - lo_t < 6 * len(counts):
        raise ValueError(
            f"tick budget {spec.total} too small for {spec.events} "
            f"events with a {spec.settle_ticks()}-tick settle tail")
    # Every apportioned event MUST be emitted (dropping one would
    # change ScenarioStatic and break the one-compile contract), so the
    # node and tick budgets are checked upfront, loudly.
    down_takes = (counts.get("crash", 0) - counts.get("restart", 0)
                  + counts.get("restart", 0) + counts.get("leave", 0))
    if down_takes > max(1, n // 2):
        raise ValueError(
            f"campaign mix asks for {down_takes} disjoint down-event "
            f"ranges but N={n} budgets only {max(1, n // 2)}; lower "
            "the crash/leave weights or events per schedule")
    alloc = _NodeAlloc(rng, n)
    events = []

    # -- crash/restart chains: churn storms + flapping ------------------
    pairs = counts.pop("restart", 0)
    permanent = counts.pop("crash", 0) - pairs
    chains = []                 # [(range, n_cycles)]
    for _ in range(pairs):
        if chains and rng.random() < 0.35:
            # Flap: another crash/restart cycle on an existing range.
            j = rng.randrange(len(chains))
            chains[j] = (chains[j][0], chains[j][1] + 1)
            continue
        chains.append((alloc.take(rng.randint(1, max(1, n // 8))), 1))
    for r, cycles in chains:
        # 2*cycles strictly increasing ticks: crash/restart alternate.
        ticks = sorted(rng.sample(range(lo_t, hi_t), 2 * cycles))
        for c in range(cycles):
            events.append({"kind": "crash", "time": ticks[2 * c],
                           "range": [r[0], r[1]]})
            events.append({"kind": "restart", "time": ticks[2 * c + 1],
                           "range": [r[0], r[1]]})
    for _ in range(max(0, permanent)):
        r = alloc.take(1)
        events.append({"kind": "crash", "time": rng.randrange(lo_t, hi_t),
                       "range": [r[0], r[1]]})

    # -- leaves ---------------------------------------------------------
    for _ in range(counts.pop("leave", 0)):
        r = alloc.take(1)
        events.append({"kind": "leave", "time": rng.randrange(lo_t, hi_t),
                       "range": [r[0], r[1]]})

    # -- migrations (harness-level; opt-in via --mix, NOT in
    # DEFAULT_MIX — adding it there would shift every pinned campaign
    # digest).  Not a scenario-engine kind: the campaign runner
    # executes a migrate by killing the checkpointed run at this tick,
    # resharding the durable carry, and resuming (chaos/campaign.py).
    # Byte-exact chunked resume keeps the graded trajectory identical,
    # so the oracle verdict is unchanged by WHERE the migrations land.
    for _ in range(counts.pop("migrate", 0)):
        events.append({"kind": "migrate",
                       "time": rng.randrange(lo_t, hi_t)})

    # -- partitions (2-group, non-overlapping in time) ------------------
    # Segmented placement: partition j draws inside its own slice of
    # the active window, so any count fits without overlap and none is
    # ever dropped.
    n_parts = counts.pop("partition", 0)
    if n_parts:
        per = (hi_t - lo_t) // n_parts
        if per < tfail + 4:
            raise ValueError(
                f"tick budget {spec.total} too small for {n_parts} "
                f"partition windows of >= {tfail} ticks")
        for j in range(n_parts):
            seg_lo = lo_t + j * per
            length = rng.randint(tfail, min(3 * tfail, per - 4))
            start = rng.randrange(seg_lo, seg_lo + per - length - 2)
            cut = rng.randint(1, n - 1) if n > 2 else 1
            events.append({"kind": "partition", "start": start,
                           "stop": start + length,
                           "groups": [[0, cut], [cut, n]]})

    # -- loss / delay windows ------------------------------------------
    def window(min_len, max_len):
        length = rng.randint(min_len, max(min_len, max_len))
        start = rng.randrange(lo_t, max(lo_t + 1, hi_t - length))
        return start, start + length

    for _ in range(counts.pop("link_flake", 0)):
        start, stop = window(3, 3 * tfail)
        events.append({"kind": "link_flake", "start": start, "stop": stop,
                       "src": list(_any_range(rng, n, n)),
                       "dst": list(_any_range(rng, n, n)),
                       "drop_prob": rng.choice(_MILD_PROBS)})
    for _ in range(counts.pop("drop_window", 0)):
        start, stop = window(3, 3 * tfail)
        events.append({"kind": "drop_window", "start": start, "stop": stop,
                       "drop_prob": rng.choice(_MILD_PROBS[:4])})
    for _ in range(counts.pop("one_way_flake", 0)):
        # Hard blackhole (drop_prob defaults to 1.0): stretched PAST the
        # heavy_loss excuse threshold so the oracle excuses the false
        # removals it may cause — healing is the binding check.
        start, stop = window(tfail, 2 * tfail)
        events.append({"kind": "one_way_flake", "start": start,
                       "stop": stop,
                       "src": list(_any_range(rng, n, n)),
                       "dst": list(_any_range(rng, n, max(1, n // 4)))})
    for _ in range(counts.pop("delay_window", 0)):
        # Short windows stay comfortably under TFAIL (no removals, no
        # excuse needed); long ones clear the long_delay excuse.
        if tfail > 5 and rng.random() < 0.5:
            start, stop = window(2, tfail - 3)
        else:
            start, stop = window(tfail, 2 * tfail)
        events.append({"kind": "delay_window", "start": start,
                       "stop": stop,
                       "dst": list(_any_range(rng, n, max(1, n // 4)))})
    if counts:
        raise ValueError(f"unknown kinds in campaign mix: {sorted(counts)}")

    # Stable order (time, then kind/fields) — part of byte-stability.
    events.sort(key=lambda e: (e.get("time", e.get("start", 0)),
                               e["kind"], _canonical(e)))
    return {
        "name": f"{spec.name}-{spec.seed}-{index:04d}",
        "events": events,
        "meta": {"campaign": campaign_digest(spec), "seed": spec.seed,
                 "index": index},
    }
