"""Deterministic delta debugging of violating chaos schedules.

A fuzzed schedule that trips an oracle invariant usually carries five
events of noise around the one interaction that matters.  The shrinker
reduces it to a minimal repro by re-running the oracle predicate after
every candidate edit, in a FIXED order:

  1. **Event ddmin** — classic delta debugging over the event list
     (drop chunks at doubling granularity; keep any reduction that
     still violates).  Crash/restart pairing is respected as a
     side-effect of the predicate: dropping a restart whose crash
     remains yields a valid (harsher) schedule, dropping a crash and
     keeping its restart fails validation and the predicate treats an
     invalid candidate as non-violating.
  2. **Window narrowing** — for each surviving window event, repeatedly
     halve the span (from the stop side, then the start side) while the
     violation persists.
  3. **Range shrinking** — for each surviving node selector
     (``range``/``src``/``dst``), halve the width (keeping the low
     side, then the high side).  Partition ``groups`` are left alone:
     they must tile ``[0, N)`` exactly, so the only shrink is dropping
     the whole event (phase 1's job).

Phases repeat until a full pass changes nothing.  Everything is a pure
function of ``(schedule, predicate)`` — no RNG, no wall clock — so the
same violating input always shrinks to the SAME minimal repro (pinned
by tests/test_chaos.py).  The predicate is typically "run it and check
the oracle verdicts" (chaos/campaign.py), which is deterministic too.
"""

from __future__ import annotations

import copy
import json
import os
from typing import Callable, Tuple

from distributed_membership_tpu.chaos.fuzz import (
    dump_schedule, schedule_digest)

_WINDOW_KINDS = ("partition", "link_flake", "drop_window",
                 "one_way_flake", "delay_window")
_RANGE_KEYS = ("range", "src", "dst")


def _with_events(schedule: dict, events: list) -> dict:
    out = dict(schedule)
    out["events"] = events
    return out


def _ddmin_events(schedule: dict, violates, stats) -> dict:
    """Minimal violating event subset (ddmin over the event list)."""
    events = list(schedule["events"])
    gran = 2
    while len(events) >= 2:
        chunk = max(1, len(events) // gran)
        reduced = False
        i = 0
        while i < len(events):
            keep = events[:i] + events[i + chunk:]
            if keep and violates(_with_events(schedule, keep), stats):
                events = keep
                gran = max(gran - 1, 2)
                reduced = True
            else:
                i += chunk
        if not reduced:
            if gran >= len(events):
                break
            gran = min(len(events), gran * 2)
    return _with_events(schedule, events)


def _narrow_windows(schedule: dict, violates, stats) -> dict:
    events = [dict(e) for e in schedule["events"]]
    for ev in events:
        if ev["kind"] not in _WINDOW_KINDS:
            continue
        changed = True
        while changed and ev["stop"] - ev["start"] > 1:
            changed = False
            span = ev["stop"] - ev["start"]
            # Halving steps first (log convergence), then 1-tick trims
            # (halving stalls at span 3 when the live tick is mid-span).
            for key, val in (("stop", ev["start"] + span // 2),
                             ("start", ev["stop"] - span // 2),
                             ("stop", ev["stop"] - 1),
                             ("start", ev["start"] + 1)):
                cand = dict(ev, **{key: val})
                trial = [cand if e is ev else e for e in events]
                if violates(_with_events(schedule, trial), stats):
                    ev[key] = val
                    changed = True
                    break
    return _with_events(schedule, events)


def _shrink_ranges(schedule: dict, violates, stats) -> dict:
    events = [dict(e) for e in schedule["events"]]
    for ev in events:
        for key in _RANGE_KEYS:
            if key not in ev:
                continue
            changed = True
            while changed and ev[key][1] - ev[key][0] > 1:
                changed = False
                lo, hi = ev[key]
                w = hi - lo
                for cand_range in ([lo, hi - w // 2], [lo + w // 2, hi]):
                    cand = dict(ev, **{key: list(cand_range)})
                    trial = [cand if e is ev else e for e in events]
                    if violates(_with_events(schedule, trial), stats):
                        ev[key] = list(cand_range)
                        changed = True
                        break
    return _with_events(schedule, events)


def shrink_schedule(schedule: dict,
                    is_violating: Callable[[dict], bool],
                    max_rounds: int = 8) -> Tuple[dict, dict]:
    """-> ``(minimal_schedule, stats)``; module docstring contract.

    ``is_violating(schedule) -> bool`` must treat an INVALID candidate
    (one the schema rejects) as non-violating — campaign.py's oracle
    predicate does.  ``stats`` reports ``probes`` (predicate calls) and
    ``rounds``; both are part of the determinism pin.
    """
    stats = {"probes": 0}

    def violates(cand: dict, st) -> bool:
        st["probes"] += 1
        return bool(is_violating(cand))

    if not violates(schedule, stats):
        raise ValueError("shrink_schedule: input does not violate — "
                         "nothing to shrink")
    cur = copy.deepcopy(schedule)
    rounds = 0
    for _ in range(max_rounds):
        before = dump_schedule(cur)
        cur = _ddmin_events(cur, violates, stats)
        cur = _narrow_windows(cur, violates, stats)
        cur = _shrink_ranges(cur, violates, stats)
        rounds += 1
        if dump_schedule(cur) == before:
            break
    stats["rounds"] = rounds
    stats["events_before"] = len(schedule["events"])
    stats["events_after"] = len(cur["events"])
    return cur, stats


def bank_repro(minimal: dict, bank_dir: str, meta: dict) -> str:
    """Write the minimal repro under ``bank_dir`` and return its path.

    The file is a runnable scenario (``--scenario`` accepts it as-is —
    ``Scenario.from_dict`` ignores the ``meta`` key) named by its own
    digest, so re-banking the same repro is idempotent and two
    different bugs can never collide."""
    banked = dict(minimal)
    banked["meta"] = {**minimal.get("meta", {}), **meta}
    # Digest over the EVENTS alone: the repro's identity is the
    # minimal schedule, not which fuzzed run first found it.
    digest = schedule_digest({"events": banked["events"]})
    banked["name"] = f"repro-{digest}"
    os.makedirs(bank_dir, exist_ok=True)
    path = os.path.join(bank_dir, f"repro-{digest}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        fh.write(json.dumps(banked, sort_keys=True, indent=1) + "\n")
    os.replace(tmp, path)
    return path
