"""The control-plane daemon: serve driver + boundary hook.

Layout of a served run (``--serve``):

  * the TICK ENGINE runs in the MAIN thread — it is the unchanged
    backend entrypoint tail (``resolve_plan`` → ``finish_run`` →
    ``chunked_run``), so a served run computes byte-for-byte what the
    batch run computes (tests/test_service.py pins dbg.log equality);
  * the HTTP API (service/api.py) runs on a daemon thread, answering
    from the published snapshot;
  * the seam between them is ``runtime/checkpoint.boundary_hook``: at
    every segment boundary the engine calls into :func:`_make_hook`'s
    closure with the host carry, which (a) publishes a fresh
    :class:`~service.snapshot.Snapshot`, (b) drains accepted injections
    into a recompiled segment runner (service/events.py), and (c)
    relays a shutdown request as a ``stop``, which the engine honors by
    barriering the checkpoint writer and raising ``RunInterrupted`` —
    the graceful exit (finish segment, final checkpoint + timeline
    flush, exit 0).

After the run completes the daemon writes the batch artifacts
(dbg.log/stats.log/msgcount.log) and keeps serving the final snapshot
until ``POST /v1/admin/shutdown`` (or SIGTERM/SIGINT) stops it.
"""

from __future__ import annotations

import json
import os
import random
import signal
import subprocess
import sys
import threading
import time
from typing import List, Optional

from distributed_membership_tpu.config import Params
from distributed_membership_tpu.eventlog import EventLog
from distributed_membership_tpu.observability import metricsbus, spans
from distributed_membership_tpu.observability.beacon import (
    read_beacon, write_beacon)
from distributed_membership_tpu.observability.metrics import write_msgcount
from distributed_membership_tpu.service.events import (
    JOURNAL_NAME, EventJournal, apply_merge, base_events,
    injection_unsupported, validate_injection)
from distributed_membership_tpu.service.snapshot import (
    SnapshotStore, decode_state)

SERVICE_JSON = "service.json"


class SnapshotPublisher(threading.Thread):
    """The off-engine-thread snapshot pipeline.

    The boundary hook's only snapshot work is :meth:`submit` — stash
    the host carry reference (the chunked driver rebinds its own
    carry to fresh arrays every segment, so the submitted arrays are
    never mutated) and notify.  This thread does everything O(N*S):
    decode, the incremental (or fallback full) derive, the census
    pre-encode, the store swap, and the shm-ring write for the
    replica pool.  The mailbox is latest-wins: if the engine laps the
    publisher, intermediate boundaries are skipped, never queued —
    boundary work on the engine thread stays O(N) regardless of
    publisher backlog (the acceptance criterion
    tests/test_query_tier.py asserts by thread identity).

    :meth:`drain` blocks until the newest submitted boundary is
    published — serve_run calls it before flipping the run status to
    complete, so the final snapshot is always visible to pollers that
    key on ``status``.
    """

    def __init__(self, state: "ControlState", ring=None):
        super().__init__(daemon=True, name="snapshot-publisher")
        self.state = state
        self.ring = ring
        self._cv = threading.Condition()
        self._item = None
        self._closing = False
        self._submitted: Optional[int] = None
        self._published: Optional[int] = None
        self.publishes = 0
        self.last_derive: Optional[dict] = None

    def submit(self, carry, tick: int) -> None:
        with self._cv:
            self._item = (carry, int(tick))
            self._submitted = int(tick)
            self._cv.notify_all()

    def run(self) -> None:
        params = self.state.params
        n, tfail = params.EN_GPSZ, params.TFAIL
        prev = None
        while True:
            with self._cv:
                while self._item is None and not self._closing:
                    self._cv.wait()
                if self._item is None:
                    return
                carry, tick = self._item
                self._item = None
            try:
                snap = decode_state(carry, tick, n, tfail)
                snap.precompute(prev)
            except AttributeError as e:   # undecodable carry layout
                self.state.snapshot_error = str(e)
                with self._cv:
                    self._published = tick
                    self._cv.notify_all()
                continue
            self.state.store.publish(snap)
            if self.ring is not None:
                try:
                    self.ring.publish(snap, prev)
                except Exception as e:
                    self.state.snapshot_error = f"shm publish: {e}"
            self.push_engine_meta()
            self.publishes += 1
            self.last_derive = snap.derive_info
            prev = snap
            with self._cv:
                self._published = tick
                self._cv.notify_all()

    def push_engine_meta(self) -> None:
        """Refresh the ring's lock-free engine-liveness fields (also
        called by serve_run on status transitions, so replicas see
        ``complete`` without waiting for another boundary)."""
        if self.ring is not None:
            try:
                self.ring.set_engine(self.state.status,
                                     self.state.tick,
                                     len(self.state.applied))
            except Exception:
                pass

    def backlog_ticks(self) -> int:
        """Submitted-minus-published tick gap — the watchdog's and
        /metrics' backlog signal (0 = the publisher is caught up)."""
        with self._cv:
            s, p = self._submitted, self._published
        if s is None:
            return 0
        return max(int(s) - int(p or 0), 0)

    def drain(self, timeout_s: float = 120.0) -> bool:
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while (self._item is not None
                   or self._published != self._submitted):
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(left)
        return True

    def close(self) -> None:
        with self._cv:
            self._closing = True
            self._cv.notify_all()


class ControlState:
    """Shared state between the engine (main thread) and the API
    handlers (per-connection daemon threads).  The lock covers the
    mutable command-queue fields; the snapshot path is lock-free
    (reference swap)."""

    def __init__(self, params: Params, plan, seed: int, total: int,
                 journal: Optional[EventJournal], base_evs: List[dict]):
        self.params = params
        self.plan = plan
        self.seed = int(seed)
        self.total = int(total)
        self.journal = journal
        self.base_events = base_evs
        self.store = SnapshotStore()
        self.status = "starting"   # running | complete | interrupted
        self.tick = 0
        self.port: Optional[int] = None
        self.queries = 0
        self.pending: List[dict] = []   # accepted, awaiting a boundary
        self.applied: List[dict] = []   # already merged into the plan
        self.applied_at: List[dict] = []  # [{tick, events}] audit trail
        self.snapshot_error = ""
        self.stop_event = threading.Event()
        # serve_run arms these; unit-level ControlState uses stay None
        # (the boundary hook then publishes synchronously, underived).
        self.publisher: Optional[SnapshotPublisher] = None
        self.replicas: List[dict] = []      # [{index, port, pid}]
        self.shm_name: Optional[str] = None
        self._lock = threading.Lock()
        self._inject_unsupported = injection_unsupported(params)
        # Metrics plane: the engine daemon's /metrics registry.  Under
        # a multi-process launch the proc index rides as a const label
        # so the fleet union can tell the shards apart.
        proc = os.environ.get("DM_DIST_PROC_ID", "")
        self.metrics = metricsbus.MetricsRegistry(
            constlabels={"proc": proc} if proc else None)
        m = self.metrics
        self._m_queries = m.counter(
            "dm_queries_total", "Queries served by this surface")
        self._m_qps = m.gauge(
            "dm_queries_per_sec", "Query rate since the last scrape")
        self._m_p50 = m.gauge(
            "dm_query_p50_ms", "Sampled query latency p50 (ms)")
        self._m_p99 = m.gauge(
            "dm_query_p99_ms", "Sampled query latency p99 (ms)")
        self._m_tick = m.gauge(
            "dm_engine_tick", "Engine tick at the last boundary")
        self._m_total = m.gauge(
            "dm_run_total_ticks", "Configured run length in ticks")
        self._m_snap_tick = m.gauge(
            "dm_snapshot_tick", "Tick of the freshest served snapshot")
        self._m_snap_age = m.gauge(
            "dm_snapshot_age_seconds",
            "Seconds since the served snapshot was decoded")
        self._m_snap_lag = m.gauge(
            "dm_snapshot_lag_ticks",
            "Engine tick minus served snapshot tick")
        self._m_pending = m.gauge(
            "dm_pending_events", "Accepted injections awaiting a "
            "segment boundary")
        self._m_applied = m.gauge(
            "dm_applied_events", "Injections merged into the plan")
        self._m_publishes = m.counter(
            "dm_publisher_publishes_total",
            "Snapshots the publisher thread derived and published")
        self._m_backlog = m.gauge(
            "dm_publisher_backlog_ticks",
            "Publisher submitted-minus-published tick gap")
        self.lat = metricsbus.LatencyReservoir()
        self._rate = metricsbus.ScrapeRate()
        # Event tracing (observability/spans.py): serve_run arms the
        # SpanLog; the seq counter is the journal position so resume
        # replay re-derives identical event ids.
        self.spans: Optional[spans.SpanLog] = None
        self.watchdog = None
        self._event_seq = 0
        self._pending_ids: List[str] = []
        # The run mesh (tpu_hash_sharded only), resolved ONCE by
        # serve_run and shared with the injection hook: the recompiled
        # merged runner must close over the very mesh the engine runs
        # on, or the swap would silently change the sharding.
        self.mesh = None

    # ---- query side -------------------------------------------------
    def count_query(self) -> None:
        with self._lock:
            self.queries += 1

    def record_latency(self, ms: float) -> None:
        self.lat.record(ms)

    def metrics_text(self) -> str:
        """GET /metrics: refresh the live gauges, render the registry.
        Runs on a handler thread — never the engine thread."""
        snap = self.store.get()
        q = self.queries
        self._m_queries.set_total(q)
        self._m_qps.set(self._rate.rate(q))
        pct = self.lat.percentiles()
        if pct["p50_ms"] is not None:
            self._m_p50.set(pct["p50_ms"])
            self._m_p99.set(pct["p99_ms"])
        self._m_tick.set(self.tick)
        self._m_total.set(self.total)
        self._m_snap_tick.set(-1 if snap is None else snap.tick)
        if snap is not None:
            self._m_snap_age.set(
                round(time.time() - snap.decoded_at, 3))
            self._m_snap_lag.set(max(self.tick - snap.tick, 0))
        self._m_pending.set(len(self.pending))
        self._m_applied.set(len(self.applied))
        if self.publisher is not None:
            self._m_publishes.set_total(self.publisher.publishes)
            self._m_backlog.set(self.publisher.backlog_ticks())
        return self.metrics.render()

    def health(self) -> dict:
        snap = self.store.get()
        h = {
            "status": self.status,
            "tick": self.tick,
            "total": self.total,
            "backend": self.params.BACKEND,
            "n": self.params.EN_GPSZ,
            "port": self.port,
            "queries_served": self.queries,
            "pending_events": len(self.pending),
            "applied_events": len(self.applied),
            "snapshot_tick": None if snap is None else snap.tick,
            "snapshot_age_s": (None if snap is None else
                               round(time.time() - snap.decoded_at, 3)),
        }
        if self.snapshot_error:
            h["snapshot_error"] = self.snapshot_error
        if self.publisher is not None:
            h["publishes"] = self.publisher.publishes
            h["derive"] = self.publisher.last_derive
        if self.replicas:
            h["replicas"] = [{k: r[k] for k in ("index", "port", "pid")}
                             for r in self.replicas]
        return h

    def timeline_path(self) -> Optional[str]:
        if self.params.TELEMETRY_DIR and self.params.TELEMETRY != "off":
            from distributed_membership_tpu.observability.timeline import (
                TIMELINE_NAME)
            return os.path.join(self.params.TELEMETRY_DIR, TIMELINE_NAME)
        return None

    def stopped(self) -> bool:
        return self.stop_event.is_set()

    def run_complete(self) -> bool:
        return self.status in ("complete", "interrupted")

    # ---- command side -----------------------------------------------
    def inject(self, events) -> tuple:
        """POST /v1/events → (http_code, reply dict)."""
        if not isinstance(events, list):
            return 400, {"error": "body must be an event object or "
                                  "{'events': [...]}"}
        if self._inject_unsupported:
            return 409, {"error": self._inject_unsupported}
        if self.run_complete():
            return 409, {"error": f"run is {self.status}; no further "
                                  "segments to inject into"}
        with self._lock:
            # The hook drains under this lock and bumps self.tick at
            # the boundary FIRST, so this bound is the earliest
            # boundary the event is guaranteed to be merged at.
            next_tick = min(self.tick + self.params.CHECKPOINT_EVERY,
                            self.total)
            try:
                validate_injection(events, self.params, next_tick)
            except ValueError as e:
                return 400, {"error": str(e)}
            if self.journal is not None:
                # Durability before the ACK: an acknowledged event
                # survives any kill (RESUME replays the journal).
                self.journal.append(events)
            ids = []
            for ev in events:
                ids.append(spans.event_id(ev, self._event_seq))
                self._event_seq += 1
            self.pending.extend(events)
            self._pending_ids.extend(ids)
        if self.spans is not None:
            for eid, ev in zip(ids, events):
                self.spans.stamp(eid, "accepted", tick=self.tick,
                                 event=ev)
                if self.journal is not None:
                    self.spans.stamp(eid, "journaled", tick=self.tick)
        return 202, {"accepted": len(events), "apply_at_tick": next_tick,
                     "journaled": self.journal is not None}

    def checkpoint_barrier(self, timeout_s: float = 120.0) -> tuple:
        """POST /v1/admin/checkpoint: block until a checkpoint at or
        after the current tick is durable, return its tick."""
        from distributed_membership_tpu.runtime.checkpoint import (
            manifest_tick)
        ckpt_dir = self.params.CHECKPOINT_DIR or None
        if not ckpt_dir:
            return 409, {"error": "no CHECKPOINT_DIR configured"}
        want = self.tick
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            t = manifest_tick(ckpt_dir)
            if t is not None and (t >= want or self.run_complete()):
                return 200, {"tick": int(t)}
            if self.stopped():
                break
            time.sleep(0.1)
        return 504, {"error": "timed out waiting for a durable "
                              "checkpoint", "durable_tick":
                     manifest_tick(ckpt_dir)}

    def request_shutdown(self) -> None:
        self.stop_event.set()


def _make_hook(state: ControlState):
    """The boundary-hook closure driving snapshots/injection/stop."""
    params = state.params
    n, tfail = params.EN_GPSZ, params.TFAIL
    decode_every = max(params.SERVICE_SNAPSHOT_EVERY, 1)
    boundary_no = [0]

    def hook(carry, tick: int):
        i, boundary_no[0] = boundary_no[0], boundary_no[0] + 1
        if i % decode_every == 0 or tick >= state.total:
            if state.publisher is not None:
                # O(1) on the engine thread: the decode/derive/census/
                # shm pipeline runs on the publisher thread.
                state.publisher.submit(carry, tick)
            else:
                try:
                    state.store.publish(
                        decode_state(carry, tick, n, tfail))
                except AttributeError as e:   # undecodable carry
                    state.snapshot_error = str(e)
        if i == 0 and state.spans is not None and state.applied:
            # Resume: the journal replay merged state.applied before
            # the first segment — stamp whatever stages the previous
            # life's spans.jsonl is missing (ids are deterministic in
            # journal order, so stamps land on the same spans; stages
            # already present are left alone — last-wins would clobber
            # the original wall clocks).
            have = spans.read_spans(state.spans.path)
            for seq, ev in enumerate(state.applied):
                eid = spans.event_id(ev, seq)
                stages = have.get(eid, {})
                if "accepted" not in stages:
                    state.spans.stamp(eid, "accepted", tick=tick,
                                      event=ev, replayed=True)
                if "journaled" not in stages:
                    state.spans.stamp(eid, "journaled", tick=tick,
                                      replayed=True)
                if "compiled" not in stages:
                    state.spans.stamp(eid, "compiled", tick=tick,
                                      replayed=True)
        upd = {}
        with state._lock:
            state.tick = tick
            drained, state.pending = state.pending, []
            drained_ids, state._pending_ids = state._pending_ids, []
        if state.watchdog is not None:
            state.watchdog.notify(tick)     # one Event.set — O(1)
        if drained:
            state.applied.extend(drained)
            state.applied_at.append({"tick": int(tick),
                                     "events": len(drained)})
            # Recompile the merged program and swap the segment runner
            # + scenario tensors from the NEXT segment on.  The plan is
            # mutated in place so finish_run's tail (dbg lines, oracle)
            # matches an uninterrupted union-scenario run.
            from distributed_membership_tpu.backends.tpu_hash import (
                plan_fail_ids)
            apply_merge(params, state.plan, state.base_events,
                        state.applied, state.seed)
            warm = params.JOIN_MODE == "warm"
            if params.BACKEND == "tpu_hash_sharded":
                # EVENT_MODE full (the injection gate) means the
                # segment runner needs no agg-merge adapter — the raw
                # shard_map runner slots straight into chunked_run.
                from distributed_membership_tpu.backends.tpu_hash_sharded \
                    import _get_segment_runner, sharded_config
                n_local = n // state.mesh.size
                cfg = sharded_config(
                    params, True, plan_fail_ids(state.plan),
                    state.plan.scenario.static, n_local)
                upd["segment_fn"] = _get_segment_runner(
                    cfg, n_local, state.mesh, warm)
            else:
                from distributed_membership_tpu.backends.tpu_hash import (
                    _get_segment_runner, make_config)
                cfg = make_config(params, collect_events=True,
                                  fail_ids=plan_fail_ids(state.plan),
                                  scenario=state.plan.scenario.static)
                upd["segment_fn"] = _get_segment_runner(cfg, warm)
            upd["extra_inputs"] = (state.plan.scenario.tensors(),)
            if state.spans is not None:
                # The merged runner takes effect from THIS boundary's
                # next segment — the tick the injection is live from.
                for eid in drained_ids:
                    state.spans.stamp(eid, "compiled", tick=tick)
        if state.stop_event.is_set():
            upd["stop"] = True
        return upd or None

    return hook


def _run_backend(params: Params, plan, log: EventLog, seed: int,
                 t0: float, mesh=None):
    """The backend entrypoint tail, with the resolved plan held by the
    CALLER (so the boundary hook can mutate it) — otherwise identical
    to run_tpu_hash / run_tpu_hash_sharded.  ``mesh`` lets serve_run
    pass the mesh it already resolved for the injection hook."""
    from distributed_membership_tpu.backends.tpu_sparse import finish_run
    if params.BACKEND == "tpu_hash_sharded":
        from distributed_membership_tpu.backends.tpu_hash_sharded import (
            bind_run_scan, resolve_mesh)
        mesh = resolve_mesh(params, mesh)
        result = finish_run(params, plan, log, bind_run_scan(mesh), t0,
                            seed)
        result.extra["mesh_size"] = mesh.size
        return result
    from distributed_membership_tpu.backends.tpu_hash import run_scan
    return finish_run(params, plan, log, run_scan, t0, seed)


def port_in_use_hint(err, out_dir: str) -> str:
    """Operator-facing message for a bind failure: name the run dir
    that owns the port when its discovery file says so (the common
    collision is re-serving an out-dir whose daemon is still up)."""
    lines = [f"service: cannot bind — {err.strerror}; pick another "
             "--port (or 0 for ephemeral), or stop the owner"]
    info = read_beacon(os.path.join(out_dir, SERVICE_JSON))
    if info is not None and info.get("port") == err.port:
        lines.append(
            f"service: {SERVICE_JSON} in {out_dir!r} records pid "
            f"{info.get('pid')} serving this run dir on port "
            f"{err.port} — that daemon likely still owns it")
    return "\n".join(lines)


def _write_service_json(out_dir: str, state: ControlState) -> None:
    os.makedirs(out_dir, exist_ok=True)
    doc = {"port": state.port, "pid": os.getpid(),
           "backend": state.params.BACKEND,
           "n": state.params.EN_GPSZ, "total": state.total}
    if state.replicas:
        doc["replicas"] = [{k: r[k] for k in ("index", "port", "pid")}
                           for r in state.replicas]
    if state.shm_name:
        doc["shm"] = state.shm_name
    write_beacon(os.path.join(out_dir, SERVICE_JSON), doc)


def _leash_sigterm():
    """preexec_fn for replicas: SIGTERM when the daemon dies (Linux
    PR_SET_PDEATHSIG) — the replica's handler distinguishes parent
    death (unlink the ring) from an individual kill (leave it)."""
    try:
        import ctypes
        libc = ctypes.CDLL("libc.so.6", use_errno=True)
        libc.prctl(1, signal.SIGTERM)      # PR_SET_PDEATHSIG = 1
    except Exception:
        pass


def spawn_replicas(state: ControlState, out_dir: str,
                   ring_name: str, workers: int) -> List[dict]:
    """Start ``workers`` read-replica processes against ``ring_name``
    and wait for each one's hello line (its bound port).  Replicas
    hold a stdin pipe (EOF = daemon gone, even on SIGKILL) and a
    PDEATHSIG leash; stdout carries exactly the one hello line, then
    beacons go to ``replica_<i>.json`` files."""
    import selectors
    timeline = state.timeline_path() or ""
    procs = []
    for i in range(workers):
        argv = [sys.executable, "-m",
                "distributed_membership_tpu.service.replica",
                "--ring", ring_name, "--port", "0", "--dir", out_dir,
                "--index", str(i)]
        if timeline:
            argv += ["--timeline", timeline]
        kwargs = {}
        if os.name == "posix":
            kwargs["preexec_fn"] = _leash_sigterm
        procs.append(subprocess.Popen(
            argv, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            text=True, **kwargs))
    out = []
    try:
        for i, p in enumerate(procs):
            sel = selectors.DefaultSelector()
            sel.register(p.stdout, selectors.EVENT_READ)
            line = ""
            if sel.select(timeout=30):
                line = p.stdout.readline()
            sel.close()
            try:
                hello = json.loads(line)
                out.append({"index": i, "port": int(hello["port"]),
                            "pid": p.pid, "proc": p})
            except (ValueError, KeyError, TypeError):
                raise RuntimeError(
                    f"replica {i} failed to start (rc={p.poll()})")
    except BaseException:
        stop_replicas([{"proc": p} for p in procs])
        raise
    return out


def stop_replicas(replicas: List[dict]) -> None:
    """Tear the pool down: close stdin (the replicas' parent-death
    signal — they best-effort unlink the ring and exit), then
    escalate to kill for stragglers."""
    for r in replicas:
        p = r.get("proc")
        if p is None:
            continue
        for f in (p.stdin, p.stdout):
            try:
                if f:
                    f.close()
            except OSError:
                pass
    for r in replicas:
        p = r.get("proc")
        if p is None:
            continue
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                pass


def resume_journal_run(params: Params, log: EventLog,
                       seed: Optional[int] = None):
    """Headless ``--resume`` of a SERVED checkpoint: replay the
    acknowledged injections journaled beside the checkpoints, so a
    restart WITHOUT ``--serve`` still reproduces the served
    trajectory bit-exactly (dbg.log included — the merged plan also
    owns the 'Node failed' banner lines).

    Returns the RunResult, or None when there is nothing to replay
    (no journal / empty journal) and the plain backend path should
    run.  Called by ``run_conf`` whenever RESUME + CHECKPOINT_DIR are
    set; a non-empty journal on a backend the merge path cannot drive
    raises rather than silently dropping acknowledged events."""
    from distributed_membership_tpu.runtime.failures import resolve_plan
    path = os.path.join(params.CHECKPOINT_DIR, JOURNAL_NAME)
    if not os.path.exists(path):
        return None
    replay = EventJournal(path).read()
    if not replay:
        return None
    if params.BACKEND not in ("tpu_hash", "tpu_hash_sharded"):
        raise ValueError(
            f"checkpoint dir {params.CHECKPOINT_DIR!r} holds a service "
            f"event journal ({len(replay)} injected events) but backend "
            f"{params.BACKEND!r} cannot replay it — resume with the "
            "backend that served the run")
    t0 = time.time()
    seed = params.SEED if seed is None else seed
    plan = resolve_plan(params, random.Random(f"app:{seed}"))
    apply_merge(params, plan, base_events(params, plan), replay, seed)
    return _run_backend(params, plan, log, seed, t0)


def serve_run(params: Params, seed: Optional[int] = None,
              out_dir: str = ".") -> int:
    """Drive one served run to completion (or graceful stop); → exit
    code.  ``params`` must already be validated with
    ``SERVICE_PORT >= 0``.  Runs the engine in the calling thread —
    call from the main thread so SIGTERM/SIGINT get the graceful
    boundary-stop treatment (runtime/checkpoint.py)."""
    from distributed_membership_tpu.runtime.checkpoint import (
        RunInterrupted, boundary_hook)
    from distributed_membership_tpu.runtime.failures import resolve_plan
    from distributed_membership_tpu.service import api

    t0 = time.time()
    seed = params.SEED if seed is None else seed
    log = EventLog(out_dir)
    plan = resolve_plan(params, random.Random(f"app:{seed}"))
    base_evs = base_events(params, plan)
    ckpt_dir = params.CHECKPOINT_DIR or None
    journal = (EventJournal(os.path.join(ckpt_dir, JOURNAL_NAME))
               if ckpt_dir else None)

    state = ControlState(params, plan, seed, params.TOTAL_TIME, journal,
                         base_evs)
    if params.BACKEND == "tpu_hash_sharded":
        from distributed_membership_tpu.backends.tpu_hash_sharded import (
            resolve_mesh)
        state.mesh = resolve_mesh(params)
    if journal is not None:
        if params.RESUME:
            # Replay acknowledged injections BEFORE the first segment:
            # the resumed run compiles the merged program from the
            # start (events are inert before their times, so the
            # pre-injection prefix is unchanged — bit-exactness pinned
            # in tests/test_service.py).
            replay = journal.read()
            if replay:
                state.applied = list(replay)
                apply_merge(params, plan, base_evs, state.applied, seed)
        else:
            journal.reset()

    server = api.make_server(state, params.SERVICE_PORT)
    state.port = server.server_address[1]
    threading.Thread(target=server.serve_forever, daemon=True,
                     name="service-api").start()

    # Query tier: every served run derives/encodes snapshots off the
    # engine thread; with SERVICE_WORKERS > 0 the publisher also lands
    # them in a shm ring feeding a pool of read-replica processes.
    ring = None
    workers = getattr(params, "SERVICE_WORKERS", 0)
    if workers > 0:
        import numpy as np

        from distributed_membership_tpu.service.shm_ring import (
            ShmRingWriter)
        n = params.EN_GPSZ
        s = params.VIEW_SIZE if params.VIEW_SIZE > 0 else n
        ring = ShmRingWriter(
            n, s, np.uint32, np.int32, params.TFAIL, state.total,
            getattr(params, "SERVICE_SHM_BUFFERS", 4))
        state.shm_name = ring.name
    state.publisher = SnapshotPublisher(state, ring)
    state.publisher.start()
    replicas = []
    if workers > 0:
        try:
            replicas = spawn_replicas(state, out_dir, ring.name,
                                      workers)
        except BaseException:
            ring.close()
            raise
        state.replicas = replicas
        print(f"service: {len(replicas)} read replica(s) on ports "
              f"{[r['port'] for r in replicas]}", flush=True)

    # Event tracing: spans.jsonl beside the run (observability/
    # spans.py).  A fresh run clears the previous run's spans, the
    # same posture as journal.reset(); a resume keeps them so the
    # replay stamps land on the prior life's records.
    state.spans = spans.SpanLog(os.path.join(out_dir,
                                             spans.SPANS_NAME))
    if not params.RESUME:
        try:
            os.unlink(state.spans.path)
        except OSError:
            pass
    watchdog = None
    if getattr(params, "WATCHDOG", 1):
        from distributed_membership_tpu.observability.runlog import (
            maybe_runlog)
        from distributed_membership_tpu.observability.watchdog import (
            Watchdog)
        watchdog = Watchdog(
            state, out_dir,
            runlog=maybe_runlog(params.TELEMETRY_DIR or out_dir))
        state.watchdog = watchdog
        watchdog.start()

    _write_service_json(out_dir, state)
    print(f"service: listening on 127.0.0.1:{state.port} "
          f"(pid {os.getpid()})", flush=True)

    try:
        try:
            with boundary_hook(_make_hook(state)):
                state.status = "running"
                result = _run_backend(params, plan, log, seed, t0,
                                      mesh=state.mesh)
        except RunInterrupted as e:
            state.status = "interrupted"
            state.publisher.drain()
            state.publisher.push_engine_meta()
            print(f"service: {e} — resume with --resume", flush=True)
            return 0
        # Final boundary visible BEFORE the status flips: pollers that
        # key on status == complete must see the final snapshot.
        state.publisher.drain()
        state.status = "complete"
        state.publisher.push_engine_meta()
        # The batch driver's artifact tail (runtime/application.py).
        result.log.flush(out_dir)
        if not result.extra.get("aggregate"):
            write_msgcount(result, out_dir)
        print(f"service: run complete at tick {state.tick}; serving "
              "until /v1/admin/shutdown", flush=True)
        try:
            state.stop_event.wait()
        except KeyboardInterrupt:
            pass
        return 0
    finally:
        if watchdog is not None:
            watchdog.close()
        server.shutdown()
        server.server_close()
        state.publisher.close()
        if replicas:
            stop_replicas(replicas)
        if ring is not None:
            ring.close()


def serve_conf(conf_path: str, port: Optional[int] = None,
               out_dir: str = ".", **overrides) -> int:
    """CLI entry (``--serve``): parse + override like ``run_conf``,
    arm SERVICE_PORT, validate, then :func:`serve_run`."""
    from distributed_membership_tpu.runtime.application import (
        apply_overrides)
    import sys

    from distributed_membership_tpu.service.api import PortInUseError
    seed = overrides.pop("seed", None)
    params = Params.from_file(conf_path, validate=False)
    apply_overrides(params, **overrides)
    if port is not None:
        params.SERVICE_PORT = port
    elif params.SERVICE_PORT < 0:
        params.SERVICE_PORT = 0       # --serve alone: ephemeral port
    params.validate()
    try:
        return serve_run(params, seed=seed, out_dir=out_dir)
    except PortInUseError as e:
        print(port_in_use_hint(e, out_dir), file=sys.stderr, flush=True)
        return 2
