"""Read-replica worker: the GET surface served from the shm ring.

One replica is one OS process (``python -m
distributed_membership_tpu.service.replica``) that maps the daemon's
snapshot ring (service/shm_ring.py) read-only and answers the full
query surface — ``/healthz``, ``/v1/census``, ``/v1/member/<id>``,
``/v1/timeline``, ``/v1/stream`` — through the very same
``api.route_get`` the engine daemon uses, so replies are byte-for-byte
what the engine would have sent (the census is the engine's own
pre-encoded bytes; member records re-encode the same scalar dict).
Writes never come here: ``/v1/events`` and the admin verbs stay on the
engine daemon (a direct POST answers 405 with that hint), which is
what keeps journaling/replay bit-exactness untouched by the pool.

Lifecycle: the daemon spawns replicas with a pipe on stdin and a
JSON hello line expected on stdout (``{"port": ..., "pid": ...}``).
Parent death — clean or SIGKILL — closes the pipe; the stdin watcher
then best-effort unlinks the ring segment (idempotent across the
pool) and exits, so a SIGKILLed daemon leaks no /dev/shm segment.  An
individually killed replica (SIGTERM) just exits WITHOUT unlinking:
the ring still feeds its surviving siblings.

Each replica drops a ``replica_<i>.json`` beacon (the shared
observability/beacon.py format) next to the run every second: queries
served,
q/s over the last interval, sampled server-side p50/p99, snapshot
tick/generation and the engine-tick lag — scripts/run_report.py
renders these as the query-tier rows.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time
from typing import Optional

from distributed_membership_tpu.observability import metricsbus
from distributed_membership_tpu.observability.beacon import write_beacon
from distributed_membership_tpu.service import api
from distributed_membership_tpu.service.shm_ring import ShmRingReader

BEACON_INTERVAL_S = 1.0


class ShmSnapshot:
    """Snapshot facade over one validated ring slot — the same duck
    type ``api.route_get`` consumes (``n``/``tick``/``census_json``/
    ``member``), built zero-copy: the [N,S] planes and derived [N]
    stats stay in shared memory; ``member`` copies ten scalars."""

    def __init__(self, slot, n: int):
        self._slot = slot
        self.n = n
        self.tick = slot.tick

    def census_json(self) -> bytes:
        return self._slot.census

    def member(self, i: int) -> dict:
        a = self._slot.arrays
        # Field order matches Snapshot.member exactly: the JSON bytes
        # must be identical to the engine daemon's reply.
        return {
            "id": int(i),
            "tick": self.tick,
            "live": bool(a["live"][i]),
            "suspected": bool(a["suspected"][i]),
            "removed": bool(a["removed"][i]),
            "started": bool(a["started"][i]),
            "in_group": bool(a["in_group"][i]),
            "self_hb": int(a["self_hb"][i]),
            "known_by": int(a["known_by"][i]),
            "suspected_by": int(a["suspected_by"][i]),
            "best_heartbeat": int(a["best_hb"][i]),
            "staleness": int(a["staleness"][i]),
        }

    def valid(self) -> bool:
        return self._slot.valid()


class _ShmStore:
    """SnapshotStore duck type: ``get`` re-validates the seqlock and
    hands back a fresh slot when the writer lapped the cached one."""

    def __init__(self, reader: ShmRingReader):
        self._reader = reader
        self._cached: Optional[ShmSnapshot] = None

    def get(self) -> Optional[ShmSnapshot]:
        # Freshness, not just validity: a slot stays valid until ITS
        # slot is rewritten — B-1 publications after it stopped being
        # the newest — so "cached and valid" alone would serve reads
        # up to B-1 boundaries stale.  The gen scan is 8 bytes/slot.
        snap = self._cached
        if (snap is not None and snap.valid()
                and snap._slot.gen == self._reader.newest_gen()):
            return snap
        slot = self._reader.latest()
        if slot is None:
            # Mid-write across every slot: keep serving the cached
            # snapshot while it holds rather than flapping to 503.
            return snap if snap is not None and snap.valid() else None
        self._cached = ShmSnapshot(slot, self._reader.n)
        return self._cached


class ReplicaState:
    """ControlState's GET surface, backed by the ring."""

    def __init__(self, reader: ShmRingReader, index: int,
                 timeline: Optional[str]):
        self.reader = reader
        self.index = index
        self.store = _ShmStore(reader)
        self.total = reader.total
        self.port: Optional[int] = None
        self.queries = 0
        self.stop_event = threading.Event()
        self._timeline = timeline or None
        self._lock = threading.Lock()
        self.lat = metricsbus.LatencyReservoir()
        self._metrics = metricsbus.MetricsRegistry(
            constlabels={"replica": str(index)})
        m = self._metrics
        self._m_queries = m.counter(
            "dm_queries_total", "Queries served by this surface")
        self._m_qps = m.gauge(
            "dm_queries_per_sec", "Query rate since the last scrape")
        self._m_p50 = m.gauge(
            "dm_query_p50_ms", "Sampled query latency p50 (ms)")
        self._m_p99 = m.gauge(
            "dm_query_p99_ms", "Sampled query latency p99 (ms)")
        self._m_snap_tick = m.gauge(
            "dm_snapshot_tick", "Tick of the freshest served snapshot")
        self._m_eng_tick = m.gauge(
            "dm_engine_tick", "Engine tick (from the ring header)")
        self._m_lag = m.gauge(
            "dm_snapshot_lag_ticks",
            "Engine tick minus served snapshot tick")
        self._rate = metricsbus.ScrapeRate()

    def count_query(self) -> None:
        with self._lock:
            self.queries += 1

    def record_latency(self, ms: float) -> None:
        self.lat.record(ms)

    def latency_percentiles(self) -> dict:
        return self.lat.percentiles()

    def metrics_text(self) -> str:
        eng = self.reader.engine()
        snap = self.store.get()
        q = self.queries
        self._m_queries.set_total(q)
        self._m_qps.set(self._rate.rate(q))
        pct = self.lat.percentiles()
        if pct["p50_ms"] is not None:
            self._m_p50.set(pct["p50_ms"])
            self._m_p99.set(pct["p99_ms"])
        self._m_eng_tick.set(eng["tick"])
        self._m_snap_tick.set(-1 if snap is None else snap.tick)
        self._m_lag.set(-1 if snap is None
                        else max(eng["tick"] - snap.tick, 0))
        return self._metrics.render()

    def health(self) -> dict:
        eng = self.reader.engine()
        snap = self.store.get()
        return {
            "status": eng["status"],
            "tick": eng["tick"],
            "total": self.total,
            "role": "replica",
            "replica_index": self.index,
            "n": self.reader.n,
            "port": self.port,
            "queries_served": self.queries,
            "applied_events": eng["applied_events"],
            "snapshot_tick": None if snap is None else snap.tick,
            "snapshot_gen": (None if snap is None
                             else snap._slot.gen // 2),
        }

    def timeline_path(self) -> Optional[str]:
        return self._timeline

    def stopped(self) -> bool:
        return self.stop_event.is_set()

    def run_complete(self) -> bool:
        return self.reader.engine()["status"] in ("complete",
                                                  "interrupted")


def make_replica_server(state: ReplicaState, port: int):
    class Handler(api.ApiHandler):
        def _route_get(self):
            upath, _, query = self.path.partition("?")
            if state.lat.should_sample(state.queries):
                t0 = time.perf_counter()
                api.route_get(self, state, upath, query)
                state.record_latency((time.perf_counter() - t0) * 1e3)
            else:
                api.route_get(self, state, upath, query)

        def _route_post(self):
            self._json(405, {"error": "read replica: POST to the "
                                      "engine daemon (see "
                                      "service.json port)"})

    return api.bind_server(Handler, port)


def beacon_path(out_dir: str, index: int) -> str:
    return os.path.join(out_dir, f"replica_{index}.json")


def _write_beacon(state: ReplicaState, out_dir: str,
                  prev: dict) -> dict:
    now = time.monotonic()
    q = state.queries
    dt = now - prev["t"]
    qps = (q - prev["q"]) / dt if dt > 0 else 0.0
    eng = state.reader.engine()
    snap = state.store.get()
    doc = {
        "role": "replica",
        "index": state.index,
        "pid": os.getpid(),
        "port": state.port,
        "queries": q,
        "qps": round(qps, 1),
        "snapshot_tick": None if snap is None else snap.tick,
        "snapshot_gen": (None if snap is None
                         else snap._slot.gen // 2),
        "engine_tick": eng["tick"],
        "engine_status": eng["status"],
        "tick_lag": (None if snap is None
                     else max(eng["tick"] - snap.tick, 0)),
    }
    doc.update(state.latency_percentiles())
    write_beacon(beacon_path(out_dir, state.index), doc)
    return {"t": now, "q": q}


def replica_main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="replica")
    ap.add_argument("--ring", required=True)
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--dir", default=".")
    ap.add_argument("--index", type=int, default=0)
    ap.add_argument("--timeline", default="")
    args = ap.parse_args(argv)

    reader = ShmRingReader(args.ring)
    state = ReplicaState(reader, args.index, args.timeline)
    server = make_replica_server(state, args.port)
    state.port = server.server_address[1]

    def _shutdown(signum, frame):
        # Individual kill: exit WITHOUT unlinking (siblings still
        # read the ring); the daemon owns normal teardown.
        state.stop_event.set()
        threading.Thread(target=server.shutdown, daemon=True).start()

    signal.signal(signal.SIGTERM, _shutdown)
    signal.signal(signal.SIGINT, _shutdown)

    def _watch_parent():
        try:
            sys.stdin.buffer.read()     # EOF = parent is gone
        except Exception:
            pass
        state.stop_event.set()
        # Parent died (possibly SIGKILL): last one out of the pool
        # turns off the lights.  Unlink is idempotent; attached
        # siblings keep their mappings.
        try:
            reader.unlink()
        except Exception:
            pass
        os._exit(0)

    threading.Thread(target=_watch_parent, daemon=True,
                     name="parent-watch").start()

    print(json.dumps({"port": state.port, "pid": os.getpid()}),
          flush=True)

    def _beacons():
        prev = {"t": time.monotonic(), "q": 0}
        while not state.stop_event.is_set():
            prev = _write_beacon(state, args.dir, prev)
            state.stop_event.wait(BEACON_INTERVAL_S)
        _write_beacon(state, args.dir, prev)

    threading.Thread(target=_beacons, daemon=True,
                     name="beacon").start()

    server.serve_forever()
    server.server_close()
    reader.close()
    return 0


if __name__ == "__main__":
    sys.exit(replica_main())
