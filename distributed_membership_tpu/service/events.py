"""Live event injection: validation, journal, and plan merging.

An injected event is a scenario/schema.py event dict POSTed to
``/v1/events`` while the run is ticking.  The contract that keeps the
whole thing bit-exact (pinned by tests/test_service.py):

  * injected events are merged with the BASE schedule (the conf's
    SCENARIO file, or the legacy failure plan converted to explicit
    events) into one union scenario, recompiled on the general tensor
    path (``compile_scenario(..., force_general=True)``) with a fresh
    ``Random(f"app:{seed}")`` — so the merged program is exactly what
    an uninterrupted run with the union scenario file would compile;
  * the merged runner takes effect from the NEXT segment boundary, and
    every injected time/start must be >= that boundary — history is
    never rewritten, so the pre-injection ticks already computed are
    identical to the union run's (events are inert before they fire);
  * events are journaled (append + fsync) BEFORE the POST is
    acknowledged, so a kill after the ACK cannot lose them: ``--resume``
    replays the journal into the plan before the first resumed segment.

The merge happens at the PLAN level, never by editing ``params``: the
checkpoint manifest pins ``params_text`` (and the SCENARIO digest), so
a resumed daemon must present the exact base config — injected events
live in ``service_events.jsonl`` beside the checkpoints instead.
"""

from __future__ import annotations

import json
import os
import random
from typing import List, Optional

from distributed_membership_tpu.config import Params
from distributed_membership_tpu.scenario.schema import (
    Scenario, load_scenario, validate_scenario)

JOURNAL_NAME = "service_events.jsonl"
_POINT_KINDS = ("crash", "restart", "leave")


def injection_unsupported(params: Params) -> Optional[str]:
    """Why live injection is unavailable for this run (None = ok).

    Narrower than serving itself: queries work on both ring-family
    backends in either event mode, but swapping the segment runner
    mid-run needs (a) a hash-twin scan — single-chip tpu_hash, or
    tpu_hash_sharded, whose merged runner the daemon rebuilds against
    the SAME mesh via ``sharded_config`` so the swapped shard_map
    program is exactly what an uninterrupted union-scenario run
    compiles — (b) the ring exchange (make_config rejects general
    scenarios on scatter), and (c) EVENT_MODE full — the aggregate
    carry bakes the static failed-id set (FastAgg) into its shapes,
    which an injected crash would have to reshape mid-run.
    """
    if params.BACKEND not in ("tpu_hash", "tpu_hash_sharded"):
        return ("live injection is implemented on the hash twins only "
                "(BACKEND tpu_hash / tpu_hash_sharded; got "
                f"{params.BACKEND!r})")
    if params.resolved_exchange() != "ring":
        return ("live injection requires the ring exchange (the "
                "scatter lowering runs legacy-shaped plans only)")
    if params.resolved_event_mode() != "full":
        return ("live injection requires EVENT_MODE full (the "
                "aggregate carry bakes the failed-id set into its "
                "shapes; an injected crash cannot reshape it mid-run)")
    if params.ENFORCE_BUFFSIZE:
        return ("live injection and ENFORCE_BUFFSIZE are incompatible "
                "(general scenario programs reject the send budget)")
    if params.FUSED_GOSSIP == 1:
        return ("live injection and FUSED_GOSSIP are incompatible "
                "(general scenario programs reject the fused kernel)")
    return None


def validate_injection(events: List[dict], params: Params,
                       next_tick: int) -> None:
    """Structural + service-constraint validation; raises ValueError.

    Reuses ``scenario.schema.validate_scenario`` wholesale, then adds
    the no-rewriting-history rule: every point time and window start
    must be at or after ``next_tick`` (the earliest boundary the merged
    plan can take effect).
    """
    if not events:
        raise ValueError("no events given")
    validate_scenario(Scenario(name="injected", events=events),
                      params.EN_GPSZ, params.TOTAL_TIME)
    for ev in events:
        if ev["kind"] in _POINT_KINDS:
            if ev["time"] < next_tick:
                raise ValueError(
                    f"injected event {ev}: 'time' {ev['time']} is "
                    f"before the next segment boundary ({next_tick}) — "
                    "the merged plan takes effect from the next "
                    "segment; history is never rewritten")
        elif ev["start"] < next_tick:
            raise ValueError(
                f"injected event {ev}: 'start' {ev['start']} is before "
                f"the next segment boundary ({next_tick})")


class EventJournal:
    """Append-only JSONL journal of accepted injections.

    One event dict per line, fsynced before the POST is acknowledged.
    ``read`` is torn-line tolerant (the same posture as the timeline
    readers): a kill mid-append loses at most the un-ACKed trailing
    line, never an acknowledged event.
    """

    def __init__(self, path: str):
        self.path = path

    def reset(self) -> None:
        """Fresh (non-resume) run: acknowledged events of a PREVIOUS
        run at this checkpoint dir must not leak into this one."""
        if os.path.exists(self.path):
            os.unlink(self.path)

    def append(self, events: List[dict]) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        with open(self.path, "a") as fh:
            for ev in events:
                fh.write(json.dumps(ev) + "\n")
            fh.flush()
            os.fsync(fh.fileno())

    def read(self) -> List[dict]:
        if not os.path.exists(self.path):
            return []
        out = []
        with open(self.path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    continue        # torn trailing write
        return out


def base_events(params: Params, plan) -> List[dict]:
    """The base schedule as explicit scenario events.

    With a SCENARIO conf key the file's raw events are reused (draw
    selectors re-consume the same seeded stream on recompile, in the
    same order — base events precede injected ones).  A legacy conf
    plan is converted from its RESOLVED form (the draw already
    happened), so the union compiles to the same victims the base run
    computed.  The conf-level drop window needs no conversion: the
    general compile path appends it from ``params.DROP_MSG`` itself.
    """
    if params.SCENARIO:
        return [dict(e) for e in load_scenario(params.SCENARIO).events]
    if (plan.fail_time is not None and len(plan.failed_indices)
            and 0 <= int(plan.fail_time) < params.TOTAL_TIME):
        # A FAIL_TIME at/after TOTAL_TIME never fires — dropping it is
        # bit-exact and keeps the union within the schema's time bound.
        return [{"kind": "crash", "time": int(plan.fail_time),
                 "nodes": [int(i) for i in plan.failed_indices]}]
    return []


def merged_plan(params: Params, base: List[dict], injected: List[dict],
                seed: int):
    """Compile the union schedule on the forced-general path.

    Returns a fresh FailurePlan whose ``scenario`` program contains
    base + injected events — bit-exact vs. compiling a union scenario
    FILE, because the event list and the RNG stream
    (``Random(f"app:{seed}")``, draws consumed in event order) are
    identical in both constructions.
    """
    from distributed_membership_tpu.scenario.compile import (
        compile_scenario)
    scn = Scenario(name="service-injected",
                   events=[dict(e) for e in base + injected],
                   source="<service>")
    return compile_scenario(scn, params, random.Random(f"app:{seed}"),
                            force_general=True)


def apply_merge(params: Params, plan, base: List[dict],
                injected: List[dict], seed: int) -> None:
    """Mutate ``plan`` in place to the merged program.

    In place because the run tail (``finish_run``: events_to_log,
    log_failures, the scenario oracle) holds THIS plan object — after
    the mutation its dbg lines and oracle verdicts match the union
    run's exactly.
    """
    new = merged_plan(params, base, injected, seed)
    plan.kind = new.kind
    plan.fail_time = new.fail_time
    plan.failed_indices = new.failed_indices
    plan.drop_start = new.drop_start
    plan.drop_stop = new.drop_stop
    plan.scenario = new.scenario
