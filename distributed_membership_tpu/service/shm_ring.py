"""Shared-memory snapshot ring: publish once, map everywhere.

One ``multiprocessing.shared_memory`` segment holds a ring of
``SERVICE_SHM_BUFFERS`` slots.  The daemon's snapshot publisher writes
each boundary's snapshot into the next slot; read-replica processes
(service/replica.py) map the segment READ-ONLY and serve queries from
numpy views constructed directly over the slot bytes — the [N,S]
planes and the derived [N] stats are never copied into a replica.

Consistency is a per-slot seqlock: the slot header's ``gen`` stamp is
bumped to an odd value before the writer touches the slot and to the
(even) publication sequence afterwards.  A reader picks the slot with
the highest even gen, reads, and re-validates the gen; a torn read
(writer lapped the ring mid-read) fails validation and the reader
retries on the new newest slot.  The writer never blocks on readers —
with B >= 2 slots a reader holding the previous slot has a full
publication interval to finish before its bytes are rewritten.

Delta writes: the planes of slot ``i`` were last written B
publications ago, so the writer keeps the last B per-publication
dirty-row masks (``Snapshot.dirty_rows``) and rewrites only the union
of rows that changed since — the same row diff the incremental derive
uses.  The derived [N] arrays and the pre-encoded census are always
written whole (staleness ages for everyone every boundary).  Per-slot
byte accounting (full vs actually written) feeds PERF.md.

Engine liveness (status/tick/applied-events) lives in the global
header as single 8-byte fields — aligned 8-byte stores, so replicas
read them without taking any lock.
"""

from __future__ import annotations

import os
import secrets
import struct
from collections import deque
from multiprocessing import resource_tracker, shared_memory
from typing import Optional

try:                            # POSIX only; stdlib shared_memory's own
    import _posixshmem          # unlink primitive, used tracker-free
except ImportError:             # pragma: no cover - non-POSIX fallback
    _posixshmem = None

import numpy as np

MAGIC = b"DMSHMRG1"
CENSUS_CAP = 4096               # pre-encoded census reply, bytes
_GLOBAL_FMT = "<8Q"             # nslots n s tfail total slot_size + dtypes
_ENGINE_FMT = "<3Q"             # status tick applied  (8-byte atomics)
_GLOBAL_SIZE = 4096
_SLOT_FMT = "<8Q"               # gen tick census_len mode dirty bytes r r
_SLOT_HEADER = struct.calcsize(_SLOT_FMT)
_ENGINE_OFF = len(MAGIC) + struct.calcsize(_GLOBAL_FMT) + 16

STATUS_CODES = {"starting": 0, "running": 1, "complete": 2,
                "interrupted": 3}
STATUS_NAMES = {v: k for k, v in STATUS_CODES.items()}

# name -> (dtype, per-member count multiplier is always n)
_DERIVED_FIELDS = (
    ("live", np.bool_), ("removed", np.bool_), ("started", np.bool_),
    ("in_group", np.bool_), ("suspected", np.bool_),
    ("self_hb", np.int64), ("known_by", np.int64),
    ("suspected_by", np.int64), ("best_hb", np.int64),
    ("staleness", np.int64),
)


def _unregister(shm: shared_memory.SharedMemory) -> None:
    """Detach this process's resource_tracker claim: Python 3.10's
    tracker registers EVERY SharedMemory (create and attach alike) and
    unlinks everything it saw at interpreter exit, which for an
    ATTACHED reader would tear the ring down under the writer (the
    3.13 ``track=False`` flag, backported by hand).  Ring teardown is
    ours explicitly — see ``_unlink_quiet``."""
    try:
        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:
        pass


def _unlink_quiet(raw_name: str) -> bool:
    """Remove the segment file WITHOUT touching the resource tracker
    (``SharedMemory.unlink`` unregisters internally, which double-fires
    against ``_unregister`` and misfires when the file is already
    gone).  ``raw_name`` is ``shm._name`` — leading slash included."""
    if _posixshmem is None:
        return False
    try:
        _posixshmem.shm_unlink(raw_name)
        return True
    except FileNotFoundError:
        return False
    except OSError:
        return False


def unlink(name: str) -> bool:
    """Best-effort unlink of a ring segment by name (idempotent)."""
    if _posixshmem is not None:
        return _unlink_quiet("/" + name.lstrip("/"))
    try:
        shm = shared_memory.SharedMemory(name=name)
    except FileNotFoundError:
        return False
    try:
        shm.unlink()
    finally:
        shm.close()
    return True


def stale_segments(prefix: str = "dmring_") -> list:
    """Names of ring segments present under /dev/shm (Linux), for the
    fleet scheduler's orphan sweep."""
    try:
        return sorted(f for f in os.listdir("/dev/shm")
                      if f.startswith(prefix))
    except OSError:
        return []


class _Layout:
    """Byte offsets for one ring geometry, shared by writer/reader."""

    def __init__(self, nslots: int, n: int, s: int,
                 view_dtype, ts_dtype):
        self.nslots, self.n, self.s = nslots, n, s
        self.view_dtype = np.dtype(view_dtype)
        self.ts_dtype = np.dtype(ts_dtype)
        off = _SLOT_HEADER + CENSUS_CAP
        self.derived_offsets = {}
        for fname, dt in _DERIVED_FIELDS:
            self.derived_offsets[fname] = (off, np.dtype(dt))
            off += n * np.dtype(dt).itemsize
        self.view_off = off
        off += n * s * self.view_dtype.itemsize
        self.ts_off = off
        off += n * s * self.ts_dtype.itemsize
        self.slot_size = (off + 63) & ~63       # cache-line pad
        self.total_size = _GLOBAL_SIZE + nslots * self.slot_size
        self.plane_bytes = (n * s * self.view_dtype.itemsize
                            + n * s * self.ts_dtype.itemsize)
        self.derived_bytes = sum(
            n * dt.itemsize for _, dt in self.derived_offsets.values())

    def slot_off(self, i: int) -> int:
        return _GLOBAL_SIZE + i * self.slot_size


def _pack_dtype(dt: np.dtype) -> int:
    code = np.dtype(dt).str.encode().ljust(8, b"\0")
    return int.from_bytes(code, "little")


def _unpack_dtype(q: int) -> np.dtype:
    return np.dtype(q.to_bytes(8, "little").rstrip(b"\0").decode())


class ShmRingWriter:
    """The daemon side: create the segment, publish snapshots."""

    def __init__(self, n: int, s: int, view_dtype, ts_dtype,
                 tfail: int, total: int, nslots: int,
                 name: Optional[str] = None):
        if nslots < 2:
            raise ValueError(f"ring needs >= 2 slots, got {nslots}")
        self.layout = _Layout(nslots, n, s, view_dtype, ts_dtype)
        self.name = name or f"dmring_{os.getpid():x}_{secrets.token_hex(4)}"
        self.shm = shared_memory.SharedMemory(
            create=True, size=self.layout.total_size, name=self.name)
        _unregister(self.shm)   # teardown is close(), not the tracker
        buf = self.shm.buf
        buf[:len(MAGIC)] = MAGIC
        struct.pack_into(
            _GLOBAL_FMT, buf, len(MAGIC), nslots, n, s, int(tfail),
            int(total), self.layout.slot_size,
            _pack_dtype(view_dtype), _pack_dtype(ts_dtype))
        self._seq = 0
        self._dirty_hist: deque = deque(maxlen=nslots)
        self._slot_seq = [None] * nslots    # last publication per slot
        self.stats = {"publishes": 0, "bytes_written": 0,
                      "bytes_full": 0, "rows_written": 0,
                      "rows_full": 0}

    # ---- engine liveness (lock-free 8-byte fields) -------------------
    def set_engine(self, status: str, tick: int, applied: int) -> None:
        struct.pack_into(_ENGINE_FMT, self.shm.buf, _ENGINE_OFF,
                         STATUS_CODES.get(status, 0), int(tick),
                         int(applied))

    # ---- publication -------------------------------------------------
    def publish(self, snap, prev=None) -> dict:
        """Write ``snap`` (derived + census precomputed) into the next
        slot; ``prev`` is the previously PUBLISHED snapshot, used for
        the per-publication dirty mask.  Returns per-publish stats."""
        lay = self.layout
        self._seq += 1
        seq = self._seq
        slot = (seq - 1) % lay.nslots
        base = lay.slot_off(slot)
        buf = self.shm.buf

        n, s = lay.n, lay.s
        if prev is not None and prev._view.shape == snap._view.shape:
            dirty = snap.dirty_rows(prev)
        else:
            dirty = np.ones(n, bool)
        self._dirty_hist.append(dirty)

        # Rows whose bytes in THIS slot are stale: union of the dirty
        # masks since the slot last held a snapshot (B publications
        # ago); full rewrite when the history doesn't reach back.
        last = self._slot_seq[slot]
        if last is None or seq - last > len(self._dirty_hist):
            rows = np.ones(n, bool)
        else:
            rows = np.zeros(n, bool)
            for mask in list(self._dirty_hist)[-(seq - last):]:
                rows |= mask
        ridx = np.flatnonzero(rows)

        census = snap.census_json()
        if len(census) > CENSUS_CAP:
            raise ValueError(f"census reply {len(census)}B exceeds "
                             f"shm slot cap {CENSUS_CAP}B")

        # Seqlock: odd while mutating, publication sequence when done.
        struct.pack_into("<Q", buf, base, 2 * seq - 1)
        off = base + _SLOT_HEADER
        buf[off:off + len(census)] = census
        written = len(census)
        for fname, (foff, dt) in lay.derived_offsets.items():
            arr = np.ascontiguousarray(
                getattr(snap, fname), dtype=dt)
            raw = arr.tobytes()
            buf[base + foff:base + foff + len(raw)] = raw
            written += len(raw)
        view_np = np.ndarray((n, s), dtype=lay.view_dtype,
                             buffer=buf, offset=base + lay.view_off)
        ts_np = np.ndarray((n, s), dtype=lay.ts_dtype,
                           buffer=buf, offset=base + lay.ts_off)
        if len(ridx) == n:
            view_np[:] = snap._view
            ts_np[:] = snap._view_ts
        elif len(ridx):
            view_np[ridx] = snap._view[ridx]
            ts_np[ridx] = snap._view_ts[ridx]
        row_bytes = (len(ridx) * s * (lay.view_dtype.itemsize
                                      + lay.ts_dtype.itemsize))
        written += row_bytes
        struct.pack_into(
            _SLOT_FMT, buf, base, 2 * seq, int(snap.tick), len(census),
            1 if (snap.derive_info or {}).get("mode") == "delta" else 0,
            int(dirty.sum()), written, 0, 0)
        self._slot_seq[slot] = seq
        st = self.stats
        st["publishes"] += 1
        st["bytes_written"] += written
        st["bytes_full"] += (lay.plane_bytes + lay.derived_bytes
                             + len(census))
        st["rows_written"] += int(len(ridx))
        st["rows_full"] += n
        return {"slot": slot, "seq": seq, "rows": int(len(ridx)),
                "bytes": written}

    def close(self, do_unlink: bool = True) -> None:
        raw = self.shm._name
        try:
            self.shm.close()
        finally:
            if do_unlink:
                _unlink_quiet(raw)


class SlotView:
    """A gen-validated view over one ring slot.  The numpy arrays are
    views STRAIGHT OVER the shared buffer (zero-copy); ``valid()``
    re-reads the gen stamp — call it after consuming whatever you
    read and retry on a newer slot if the writer lapped you."""

    def __init__(self, reader: "ShmRingReader", slot: int, gen: int,
                 tick: int, census: bytes):
        self._reader = reader
        self._slot = slot
        self.gen = gen
        self.tick = tick
        self.census = census
        lay = reader.layout
        base = lay.slot_off(slot)
        buf = reader.shm.buf
        self.arrays = {}
        for fname, (foff, dt) in lay.derived_offsets.items():
            self.arrays[fname] = np.ndarray(
                (lay.n,), dtype=dt, buffer=buf, offset=base + foff)
        self.view = np.ndarray((lay.n, lay.s), dtype=lay.view_dtype,
                               buffer=buf, offset=base + lay.view_off)
        self.view_ts = np.ndarray((lay.n, lay.s), dtype=lay.ts_dtype,
                                  buffer=buf, offset=base + lay.ts_off)

    def valid(self) -> bool:
        return self._reader.slot_gen(self._slot) == self.gen


class ShmRingReader:
    """The replica side: attach read-only, hand out validated slots."""

    def __init__(self, name: str):
        self.shm = shared_memory.SharedMemory(name=name)
        _unregister(self.shm)
        buf = self.shm.buf
        if bytes(buf[:len(MAGIC)]) != MAGIC:
            raise ValueError(f"shm segment {name!r} is not a snapshot "
                             "ring")
        (nslots, n, s, tfail, total, slot_size, vq,
         tq) = struct.unpack_from(_GLOBAL_FMT, buf, len(MAGIC))
        self.layout = _Layout(nslots, n, s, _unpack_dtype(vq),
                              _unpack_dtype(tq))
        assert self.layout.slot_size == slot_size, "layout mismatch"
        self.n, self.s, self.tfail, self.total = n, s, tfail, total

    def engine(self) -> dict:
        code, tick, applied = struct.unpack_from(
            _ENGINE_FMT, self.shm.buf, _ENGINE_OFF)
        return {"status": STATUS_NAMES.get(code, "starting"),
                "tick": int(tick), "applied_events": int(applied)}

    def slot_gen(self, i: int) -> int:
        return struct.unpack_from("<Q", self.shm.buf,
                                  self.layout.slot_off(i))[0]

    def newest_gen(self) -> int:
        """Highest stable gen across the ring (0 before the first
        publication) — the cheap per-query freshness probe: a cached
        slot at this gen is current, anything lower has been lapped by
        a newer publication in ANOTHER slot (still valid, but stale)."""
        return max((g for i in range(self.layout.nslots)
                    if (g := self.slot_gen(i)) and g % 2 == 0),
                   default=0)

    def latest(self, tries: int = 8) -> Optional[SlotView]:
        """The newest stable slot, seqlock-validated; None before the
        first publication (or if the writer outpaces every retry —
        callers treat that as "no snapshot yet")."""
        lay = self.layout
        for _ in range(tries):
            gens = [self.slot_gen(i) for i in range(lay.nslots)]
            stable = [(g, i) for i, g in enumerate(gens)
                      if g and g % 2 == 0]
            if not stable:
                return None
            gen, slot = max(stable)
            base = lay.slot_off(slot)
            hdr = struct.unpack_from(_SLOT_FMT, self.shm.buf, base)
            census = bytes(
                self.shm.buf[base + _SLOT_HEADER:
                             base + _SLOT_HEADER + hdr[2]])
            view = SlotView(self, slot, gen, hdr[1], census)
            if self.slot_gen(slot) == gen:
                return view
        return None

    def unlink(self) -> bool:
        """Reader-side teardown for orphaned rings (parent daemon died
        without cleaning up).  Idempotent across the pool; attached
        sibling mappings survive the unlink."""
        return _unlink_quiet(self.shm._name)

    def close(self) -> None:
        self.shm.close()
