"""Stdlib threaded HTTP API for the membership control plane.

No new dependencies: ``http.server.ThreadingHTTPServer`` with one
daemon thread per connection.  Every query is answered from the
published :class:`~service.snapshot.Snapshot` (or the on-disk flight
recorder for /v1/timeline and /v1/stream) — handler threads never
touch device state, never block the tick engine, and a torn client
connection kills only its own thread (BrokenPipe is swallowed).

Endpoints (README "Service"):

  GET  /healthz               liveness + run phase + snapshot tick
  GET  /v1/census             cluster-level counts from the snapshot
  GET  /v1/member/<id>        one member's O(1) record
  GET  /v1/timeline?from=T    merged per-tick series from timeline.jsonl
  GET  /v1/stream             SSE of per-tick telemetry scalars
  POST /v1/events             inject scenario events (202 on accept)
  POST /v1/admin/checkpoint   wait for the next durable checkpoint
  POST /v1/admin/shutdown     graceful: finish segment, final
                              checkpoint + flush, exit 0
"""

from __future__ import annotations

import json
import os
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs

SSE_POLL_SECONDS = 0.25


def _timeline_rows(path: str, start: int):
    """Per-tick scalar dicts from tick ``start`` on (torn-tolerant)."""
    from distributed_membership_tpu.observability.timeline import (
        TELEMETRY_FIELDS, read_timeline)
    series = read_timeline(path)
    ticks = int(series.get("ticks", 0))
    t0 = int(series.get("t0", 0))
    rows = []
    for i in range(max(start - t0, 0), ticks):
        row = {"t": t0 + i}
        row.update({f: int(series[f][i]) for f in TELEMETRY_FIELDS
                    if f in series})
        rows.append(row)
    return rows


def make_server(state, port: int) -> ThreadingHTTPServer:
    """Build (not start) the API server bound to 127.0.0.1:``port``
    (0 = ephemeral).  ``state`` is the daemon's ControlState."""

    class Handler(BaseHTTPRequestHandler):
        # Content-Length is set on every JSON reply, so keep-alive is
        # safe — and it is what lets the bench's 8 query clients reuse
        # connections instead of paying a TCP handshake per query.
        protocol_version = "HTTP/1.1"
        # Every reply is two small writes on an unbuffered wfile (the
        # header buffer flush, then the body); with Nagle on, the body
        # write sits behind the peer's delayed ACK — a ~40 ms stall per
        # request that caps one keep-alive client near 25 queries/s.
        disable_nagle_algorithm = True

        def log_message(self, fmt, *args):   # stdlib default is stderr
            pass

        def _json(self, code: int, obj: dict) -> None:
            self._body(code, (json.dumps(obj) + "\n").encode())

        def _body(self, code: int, body: bytes) -> None:
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _snapshot(self):
            snap = state.store.get()
            if snap is None:
                self._json(503, {"error": "no snapshot published yet"})
            return snap

        def do_GET(self):
            try:
                self._route_get()
            except (BrokenPipeError, ConnectionResetError):
                pass            # client went away; its thread exits

        def do_POST(self):
            try:
                self._route_post()
            except (BrokenPipeError, ConnectionResetError):
                pass

        def _route_get(self):
            # partition, not urlparse: census/member are the bench's
            # hot path and carry no query string.
            upath, _, query = self.path.partition("?")
            state.count_query()
            if upath == "/healthz":
                self._json(200, state.health())
            elif upath == "/v1/census":
                snap = self._snapshot()
                if snap is not None:
                    self._body(200, snap.census_json())
            elif upath.startswith("/v1/member/"):
                snap = self._snapshot()
                if snap is None:
                    return
                try:
                    i = int(upath[len("/v1/member/"):])
                except ValueError:
                    self._json(400, {"error": "member id must be an int"})
                    return
                if not 0 <= i < snap.n:
                    self._json(404, {"error": f"member {i} out of range "
                                              f"[0, {snap.n})"})
                    return
                self._json(200, snap.member(i))
            elif upath == "/v1/timeline":
                path = state.timeline_path()
                if not path or not os.path.exists(path):
                    self._json(404, {"error": "no timeline (run with "
                                              "TELEMETRY scalars and a "
                                              "TELEMETRY_DIR)"})
                    return
                q = parse_qs(query)
                start = int(q.get("from", ["0"])[0])
                self._json(200, {"from": start,
                                 "rows": _timeline_rows(path, start)})
            elif upath == "/v1/stream":
                self._stream()
            else:
                self._json(404, {"error": f"unknown path {upath!r}"})

        def _route_post(self):
            if self.path == "/v1/events":
                length = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(length) or b"{}")
                except json.JSONDecodeError as e:
                    self._json(400, {"error": f"invalid JSON ({e})"})
                    return
                events = (body.get("events", [body])
                          if isinstance(body, dict) else body)
                code, reply = state.inject(events)
                self._json(code, reply)
            elif self.path == "/v1/admin/checkpoint":
                code, reply = state.checkpoint_barrier()
                self._json(code, reply)
            elif self.path == "/v1/admin/shutdown":
                state.request_shutdown()
                self._json(200, {"stopping": True,
                                 "status": state.status})
            else:
                self._json(404, {"error": f"unknown path {self.path!r}"})

        def _stream(self):
            """SSE: per-tick telemetry scalars as they reach the
            on-disk timeline, one ``data:`` message per tick.  The
            loop ends when the client disconnects (write raises) or
            the daemon stops."""
            path = state.timeline_path()
            if not path:
                self._json(404, {"error": "no telemetry stream (run "
                                          "with TELEMETRY scalars and "
                                          "a TELEMETRY_DIR)"})
                return
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.send_header("Connection", "close")
            self.end_headers()
            sent_to = 0
            while not state.stopped():
                if os.path.exists(path):
                    for row in _timeline_rows(path, sent_to):
                        msg = f"data: {json.dumps(row)}\n\n".encode()
                        self.wfile.write(msg)
                        sent_to = row["t"] + 1
                    self.wfile.flush()
                if state.run_complete() and sent_to >= state.total:
                    break
                time.sleep(SSE_POLL_SECONDS)

    server = ThreadingHTTPServer(("127.0.0.1", port), Handler)
    server.daemon_threads = True
    return server
