"""Stdlib threaded HTTP API for the membership control plane.

No new dependencies: ``http.server.ThreadingHTTPServer`` with one
daemon thread per connection.  Every query is answered from the
published :class:`~service.snapshot.Snapshot` (or the on-disk flight
recorder for /v1/timeline and /v1/stream) — handler threads never
touch device state, never block the tick engine, and a torn client
connection kills only its own thread (BrokenPipe is swallowed).

The route logic lives in module-level functions (:func:`route_get`,
:func:`route_post`) that take the ControlState and a path with any
mount prefix ALREADY STRIPPED — so the same handlers answer both the
single-run daemon's bare paths (``/v1/census``) and the fleet
controller's prefixed ones (``/v1/runs/<id>/v1/census`` forwards the
stripped remainder to the run's worker daemon, whose handlers are
these very functions; fleet/daemon.py never re-implements a route).

Endpoints (README "Service"):

  GET  /healthz               liveness + run phase + snapshot tick
  GET  /metrics               Prometheus text (observability/metricsbus)
  GET  /v1/census             cluster-level counts from the snapshot
  GET  /v1/member/<id>        one member's O(1) record
  GET  /v1/timeline?from=T    merged per-tick series from timeline.jsonl
  GET  /v1/stream             SSE of per-tick telemetry scalars
  POST /v1/events             inject scenario events (202 on accept)
  POST /v1/admin/checkpoint   wait for the next durable checkpoint
  POST /v1/admin/shutdown     graceful: finish segment, final
                              checkpoint + flush, exit 0
"""

from __future__ import annotations

import errno
import json
import os
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs

SSE_POLL_SECONDS = 0.25


class PortInUseError(OSError):
    """``bind()`` failed with EADDRINUSE — the CLI entries turn this
    into a run-dir hint + exit 2 instead of a raw traceback."""

    def __init__(self, port: int):
        super().__init__(errno.EADDRINUSE,
                         f"port {port} is already in use")
        self.port = port


def _timeline_rows(path: str, start: int):
    """Per-tick scalar dicts from tick ``start`` on (torn-tolerant)."""
    from distributed_membership_tpu.observability.timeline import (
        TELEMETRY_FIELDS, read_timeline)
    series = read_timeline(path)
    ticks = int(series.get("ticks", 0))
    t0 = int(series.get("t0", 0))
    rows = []
    for i in range(max(start - t0, 0), ticks):
        row = {"t": t0 + i}
        row.update({f: int(series[f][i]) for f in TELEMETRY_FIELDS
                    if f in series})
        rows.append(row)
    return rows


class ApiHandler(BaseHTTPRequestHandler):
    """Shared HTTP plumbing for the service AND fleet servers.

    Subclasses implement ``_route_get``/``_route_post``; everything
    transport-level (keep-alive, Nagle, JSON replies, torn-client
    tolerance) lives here once.
    """

    # Content-Length is set on every JSON reply, so keep-alive is
    # safe — and it is what lets the bench's 8 query clients reuse
    # connections instead of paying a TCP handshake per query.
    protocol_version = "HTTP/1.1"
    # Every reply is two small writes on an unbuffered wfile (the
    # header buffer flush, then the body); with Nagle on, the body
    # write sits behind the peer's delayed ACK — a ~40 ms stall per
    # request that caps one keep-alive client near 25 queries/s.
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):   # stdlib default is stderr
        pass

    def _json(self, code: int, obj: dict) -> None:
        self._body(code, (json.dumps(obj) + "\n").encode())

    def _body(self, code: int, body: bytes,
              ctype: str = "application/json") -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def read_json_body(self):
        """→ parsed JSON body, or None after replying 400."""
        length = int(self.headers.get("Content-Length", 0))
        try:
            return json.loads(self.rfile.read(length) or b"{}")
        except json.JSONDecodeError as e:
            self._json(400, {"error": f"invalid JSON ({e})"})
            return None

    def do_GET(self):
        try:
            self._route_get()
        except (BrokenPipeError, ConnectionResetError):
            pass            # client went away; its thread exits

    def do_POST(self):
        try:
            self._route_post()
        except (BrokenPipeError, ConnectionResetError):
            pass


def route_get(h: ApiHandler, state, upath: str, query: str) -> None:
    """The run-surface GET routes, mount-point agnostic: ``upath`` has
    any prefix already stripped.  ``state`` is the daemon's
    ControlState; ``h`` the handler to reply on."""
    if upath == "/metrics":
        # Before count_query: a scraper polling every second must not
        # inflate the query-tier q/s it is trying to observe.
        text = state.metrics_text()
        h._body(200, text.encode(),
                ctype="text/plain; version=0.0.4; charset=utf-8")
        return
    state.count_query()

    def _snapshot():
        snap = state.store.get()
        if snap is None:
            h._json(503, {"error": "no snapshot published yet"})
        return snap

    if upath == "/healthz":
        h._json(200, state.health())
    elif upath == "/v1/census":
        snap = _snapshot()
        if snap is not None:
            h._body(200, snap.census_json())
    elif upath.startswith("/v1/member/"):
        snap = _snapshot()
        if snap is None:
            return
        try:
            i = int(upath[len("/v1/member/"):])
        except ValueError:
            h._json(400, {"error": "member id must be an int"})
            return
        if not 0 <= i < snap.n:
            h._json(404, {"error": f"member {i} out of range "
                                   f"[0, {snap.n})"})
            return
        h._json(200, snap.member(i))
    elif upath == "/v1/timeline":
        path = state.timeline_path()
        if not path or not os.path.exists(path):
            h._json(404, {"error": "no timeline (run with "
                                   "TELEMETRY scalars and a "
                                   "TELEMETRY_DIR)"})
            return
        q = parse_qs(query)
        start = int(q.get("from", ["0"])[0])
        h._json(200, {"from": start,
                      "rows": _timeline_rows(path, start)})
    elif upath == "/v1/stream":
        stream(h, state)
    else:
        h._json(404, {"error": f"unknown path {upath!r}"})


def route_post(h: ApiHandler, state, upath: str) -> None:
    """The run-surface POST routes (same stripping contract as
    :func:`route_get`)."""
    if upath == "/v1/events":
        body = h.read_json_body()
        if body is None:
            return
        events = (body.get("events", [body])
                  if isinstance(body, dict) else body)
        code, reply = state.inject(events)
        h._json(code, reply)
    elif upath == "/v1/admin/checkpoint":
        code, reply = state.checkpoint_barrier()
        h._json(code, reply)
    elif upath == "/v1/admin/shutdown":
        state.request_shutdown()
        h._json(200, {"stopping": True,
                      "status": state.status})
    else:
        h._json(404, {"error": f"unknown path {upath!r}"})


def stream(h: ApiHandler, state) -> None:
    """SSE: per-tick telemetry scalars as they reach the on-disk
    timeline, one ``data:`` message per tick.  The loop ends when the
    client disconnects (a write raises) or the daemon stops.  Idle
    polls write an SSE comment keepalive — without it a disconnected
    client is only noticed at the next data row, so a stream opened
    against a paused run would pin its handler thread (and the
    socket) until the daemon exits."""
    path = state.timeline_path()
    if not path:
        h._json(404, {"error": "no telemetry stream (run "
                               "with TELEMETRY scalars and "
                               "a TELEMETRY_DIR)"})
        return
    h.send_response(200)
    h.send_header("Content-Type", "text/event-stream")
    h.send_header("Cache-Control", "no-cache")
    h.send_header("Connection", "close")
    h.end_headers()
    sent_to = 0
    while not state.stopped():
        wrote = False
        if os.path.exists(path):
            for row in _timeline_rows(path, sent_to):
                msg = f"data: {json.dumps(row)}\n\n".encode()
                h.wfile.write(msg)
                sent_to = row["t"] + 1
                wrote = True
        if state.run_complete() and sent_to >= state.total:
            break
        if not wrote:
            # Keepalive comment: detects a gone client within one
            # poll period even when no new ticks are flowing.
            h.wfile.write(b": keepalive\n\n")
        h.wfile.flush()
        time.sleep(SSE_POLL_SECONDS)


def bind_server(handler_cls, port: int,
                host: str = "127.0.0.1") -> ThreadingHTTPServer:
    """Bind (not start) a threaded server; EADDRINUSE becomes the
    typed :class:`PortInUseError` the CLI entries catch."""
    try:
        server = ThreadingHTTPServer((host, port), handler_cls)
    except OSError as e:
        if e.errno == errno.EADDRINUSE:
            raise PortInUseError(port) from e
        raise
    server.daemon_threads = True
    return server


def make_server(state, port: int) -> ThreadingHTTPServer:
    """Build (not start) the API server bound to 127.0.0.1:``port``
    (0 = ephemeral).  ``state`` is the daemon's ControlState."""

    class Handler(ApiHandler):
        def _route_get(self):
            # partition, not urlparse: census/member are the bench's
            # hot path and carry no query string.
            upath, _, query = self.path.partition("?")
            # Sampled server-side latency (the replica pool's scheme,
            # via the shared reservoir) when the state carries one.
            lat = getattr(state, "lat", None)
            if lat is not None and lat.should_sample(state.queries):
                t0 = time.perf_counter()
                route_get(self, state, upath, query)
                lat.record((time.perf_counter() - t0) * 1e3)
            else:
                route_get(self, state, upath, query)

        def _route_post(self):
            route_post(self, state, self.path)

    return bind_server(Handler, port)
