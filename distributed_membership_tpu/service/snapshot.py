"""Host snapshots: the O(1)-queryable membership view.

At every segment boundary the chunked driver has ALREADY pulled the
carry to host (the checkpoint path needs it), so PUBLISHING a snapshot
costs the engine thread only the O(N) liveness booleans — the
O(N*VIEW_SIZE) view-derived statistics (who knows whom, freshest
heartbeat, staleness) are computed lazily on the FIRST query that
needs them, on an API thread, and cached on the snapshot.  That keeps
the tick loop's boundary work flat no matter how often clients poll
(the BENCH_SERVICE bound: <= 5% slowdown under 8 hammering clients),
and a boundary nobody queries costs nobody anything.

The derivation itself is one argsort + ``ufunc.reduceat`` pass over
the flattened present view entries — the grouped max/min without
``np.maximum.at``'s unbuffered per-element loop, which at 65k x 16
entries is ~10x slower than the sort.

Publication is double-buffered by immutability: a :class:`Snapshot`'s
arrays are never mutated after derivation and :class:`SnapshotStore`
swaps the reference — readers that grabbed the old snapshot keep a
consistent view while the engine publishes the next one; no locks on
the query path (the derive lock is per-snapshot and taken at most for
one computation).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

import numpy as np


class Snapshot:
    """One membership view over host arrays.  All [N] numpy.

    Eager fields (engine-thread cheap): ``live`` (started & in_group &
    ~failed), ``removed`` (down: crashed or left), ``started``,
    ``in_group``, ``self_hb``.  Derived on first access (see
    :meth:`_derive`): ``known_by``/``suspected_by`` (live observers
    holding / suspecting an entry), ``best_hb`` (freshest heartbeat any
    live observer has seen, -1 = known by nobody), ``staleness`` (min
    over live observers of tick - view_ts, -1 = unknown), ``suspected``
    (live members some observer's entry has aged past TFAIL — the
    protocol's suspicion precondition, surfaced before the removal
    lands).
    """

    def __init__(self, tick: int, n: int, tfail: int, *, started,
                 in_group, failed, self_hb, view, view_ts):
        self.tick = int(tick)
        self.n = int(n)
        self.tfail = int(tfail)
        self.started = np.asarray(started).astype(bool)
        self.in_group = np.asarray(in_group).astype(bool)
        failed = np.asarray(failed).astype(bool)
        self.live = self.started & self.in_group & ~failed
        self.removed = self.started & failed
        self.self_hb = np.asarray(self_hb).astype(np.int64)
        self._view = np.asarray(view)
        self._view_ts = np.asarray(view_ts)
        self.decoded_at = time.time()
        self._lock = threading.Lock()
        self._derived = False
        self._census: Optional[dict] = None
        self._census_body: Optional[bytes] = None

    def _derive(self) -> None:
        """The O(N*S) view statistics, once, on whichever thread asks
        first.  Unpacking mirrors ``tpu_hash.unpack``: a view cell
        holds ``member + n*heartbeat + 1`` (0 = empty), so ``member =
        (v-1) % n`` and ``hb = (v-1) // n`` — int64 math so 1M-node
        heartbeats never wrap the unpack arithmetic.

        Grouped max/min via two radix ``np.sort``s of packed uint64
        (member, value) keys: the group tail/head IS the per-member
        max/min.  No ``ufunc.at`` (unbuffered per-element loop, ~10x
        slower at 65k x 16) and no ``argsort`` + index gathers (~2.5x
        slower); empty cells go to a sentinel bucket ``n`` instead of
        a mask-compress pass.  ~70 ms at 65k x 16 on one slow core —
        this runs under the GIL on a query thread, so its cost is the
        floor of the serving overhead BENCH_SERVICE measures."""
        if self._derived:
            return
        with self._lock:
            if self._derived:
                return
            n = self.n
            v = self._view.astype(np.int64) - 1          # -1 = empty
            present = (v >= 0) & self.live[:, None]
            if n & (n - 1) == 0:
                hb, member = v >> n.bit_length() - 1, v & (n - 1)
            else:
                hb, member = np.divmod(v, n)
            member = np.where(present, member, n).ravel()
            # Empty cells carry hb = -1 (from v = -1); zero them so the
            # uint64 pack can't smear sign bits into the member field.
            hb = np.where(present, hb, 0).ravel()
            stale = (self.tick
                     - self._view_ts.astype(np.int64)).ravel()

            counts = np.bincount(member, minlength=n + 1)
            known_by = counts[:n].astype(np.int64)
            best_hb = np.full(n, -1, np.int64)
            staleness = np.full(n, -1, np.int64)

            key = np.sort((member.astype(np.uint64) << np.uint64(32))
                          | hb.astype(np.uint64))
            m = (key >> np.uint64(32)).astype(np.int64)
            ends = np.flatnonzero(np.r_[m[1:] != m[:-1], True])
            uniq = m[ends]
            keep = uniq < n
            best_hb[uniq[keep]] = (
                key[ends] & np.uint64(0xFFFFFFFF)).astype(
                    np.int64)[keep]

            # Staleness fits 41 bits (TOTAL_TIME is int32-bounded);
            # sentinel 1<<40 keeps empty cells out of the group min.
            sr = np.where(present.ravel(), stale, 1 << 40)
            key = np.sort((member.astype(np.uint64) << np.uint64(41))
                          | sr.astype(np.uint64))
            m = (key >> np.uint64(41)).astype(np.int64)
            starts = np.flatnonzero(np.r_[True, m[1:] != m[:-1]])
            uniq = m[starts]
            keep = uniq < n
            staleness[uniq[keep]] = (
                key[starts] & np.uint64((1 << 41) - 1)).astype(
                    np.int64)[keep]

            sus = np.where(present.ravel() & (stale >= self.tfail),
                           member, n)
            suspected_by = np.bincount(
                sus, minlength=n + 1)[:n].astype(np.int64)
            self.known_by = known_by
            self.best_hb = best_hb
            self.staleness = staleness
            self.suspected_by = suspected_by
            self.suspected = self.live & (suspected_by > 0)
            self._derived = True

    def census(self) -> dict:
        if self._census is None:
            self._derive()
            self._census = {
                "tick": self.tick,
                "n": self.n,
                "live": int(self.live.sum()),
                "suspected": int(self.suspected.sum()),
                "removed": int(self.removed.sum()),
                "unstarted": int((~self.started).sum()),
                "known_members": int((self.known_by > 0).sum()),
                "view_entries": int(self.known_by.sum()),
                "max_staleness": int(self.staleness.max(initial=-1)),
            }
        return self._census

    def census_json(self) -> bytes:
        """The census reply pre-encoded: the hammering-dashboards hot
        path pays the JSON encode once per snapshot, not per query."""
        if self._census_body is None:
            self._census_body = (json.dumps(self.census())
                                 + "\n").encode()
        return self._census_body

    def member(self, i: int) -> dict:
        self._derive()
        return {
            "id": int(i),
            "tick": self.tick,
            "live": bool(self.live[i]),
            "suspected": bool(self.suspected[i]),
            "removed": bool(self.removed[i]),
            "started": bool(self.started[i]),
            "in_group": bool(self.in_group[i]),
            "self_hb": int(self.self_hb[i]),
            "known_by": int(self.known_by[i]),
            "suspected_by": int(self.suspected_by[i]),
            "best_heartbeat": int(self.best_hb[i]),
            "staleness": int(self.staleness[i]),
        }


def decode_state(carry, tick: int, n: int, tfail: int) -> Snapshot:
    """Wrap a host carry as a :class:`Snapshot` (numpy only, lazy).

    Works on any carry exposing the hash twins' field names
    (``view``/``view_ts`` packed membership, ``started``/``in_group``/
    ``failed``/``self_hb``): both :class:`~backends.tpu_hash.HashState`
    and the sharded twin qualify (``np.asarray`` on a sharded leaf
    yields the assembled global array).
    """
    return Snapshot(tick, n, tfail,
                    started=carry.started, in_group=carry.in_group,
                    failed=carry.failed, self_hb=carry.self_hb,
                    view=carry.view, view_ts=carry.view_ts)


class SnapshotStore:
    """Reference-swap publication of immutable snapshots.

    ``publish`` rebinds one attribute (atomic under the GIL);
    ``get`` hands back whatever snapshot is current.  Readers never
    block the engine and never see a half-written view.
    """

    def __init__(self):
        self._snap: Optional[Snapshot] = None

    def publish(self, snap: Snapshot) -> None:
        self._snap = snap

    def get(self) -> Optional[Snapshot]:
        return self._snap
