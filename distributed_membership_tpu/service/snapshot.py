"""Host snapshots: the O(1)-queryable membership view.

At every segment boundary the chunked driver has ALREADY pulled the
carry to host (the checkpoint path needs it), so PUBLISHING a snapshot
costs the engine thread only the O(N) liveness booleans — the
O(N*VIEW_SIZE) view-derived statistics (who knows whom, freshest
heartbeat, staleness) never run on the engine thread: the daemon's
snapshot publisher derives them off-thread at publish time, and a
snapshot nobody publishes against still falls back to the lazy
first-query derive.

Two derivation paths, one result:

  * :meth:`Snapshot._derive` — the full double-``np.sort`` pass over
    all N*S packed view entries (the grouped max/min without
    ``np.maximum.at``'s unbuffered per-element loop, which at 65k x 16
    entries is ~10x slower than the sort).  ~70 ms at 65k x 16 on one
    slow core.  This is the FALLBACK and the byte-identity ORACLE.
  * :meth:`Snapshot.derive_incremental` — the delta path: diff the
    ``view``/``view_ts`` planes against the previous boundary's
    snapshot, re-derive only the members touched by changed rows
    (subset sort), and advance everyone else arithmetically
    (``staleness += dt``; ``suspected_by`` += the entries whose age
    crossed TFAIL inside the boundary window — a vectorized window
    count, no sort).  Between quiet boundaries the dirty-row count is
    O(heartbeat fanout), not O(N), so the delta derive is
    milliseconds where the full derive is tens of them — and it is
    byte-identical to the oracle (tests/test_query_tier.py pins every
    stat at every boundary of the grading scenarios).

Publication is double-buffered by immutability: a :class:`Snapshot`'s
arrays are never mutated after derivation and :class:`SnapshotStore`
swaps the reference — readers that grabbed the old snapshot keep a
consistent view while the engine publishes the next one; no locks on
the query path (the derive lock is per-snapshot and taken at most for
one computation).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Optional

import numpy as np


class Snapshot:
    """One membership view over host arrays.  All [N] numpy.

    Eager fields (engine-thread cheap): ``live`` (started & in_group &
    ~failed), ``removed`` (down: crashed or left), ``started``,
    ``in_group``, ``self_hb``.  Derived on first access (see
    :meth:`_derive`): ``known_by``/``suspected_by`` (live observers
    holding / suspecting an entry), ``best_hb`` (freshest heartbeat any
    live observer has seen, -1 = known by nobody), ``staleness`` (min
    over live observers of tick - view_ts, -1 = unknown), ``suspected``
    (live members some observer's entry has aged past TFAIL — the
    protocol's suspicion precondition, surfaced before the removal
    lands).
    """

    def __init__(self, tick: int, n: int, tfail: int, *, started,
                 in_group, failed, self_hb, view, view_ts):
        self.tick = int(tick)
        self.n = int(n)
        self.tfail = int(tfail)
        self.started = np.asarray(started).astype(bool)
        self.in_group = np.asarray(in_group).astype(bool)
        failed = np.asarray(failed).astype(bool)
        self.live = self.started & self.in_group & ~failed
        self.removed = self.started & failed
        self.self_hb = np.asarray(self_hb).astype(np.int64)
        self._view = np.asarray(view)
        self._view_ts = np.asarray(view_ts)
        self.decoded_at = time.time()
        self._lock = threading.Lock()
        self._derived = False
        self._census: Optional[dict] = None
        self._census_body: Optional[bytes] = None
        # How this snapshot's stats were computed: None until derived,
        # then {"mode": "full"|"delta", "ms": float, ...} — the PERF.md
        # derive-cost accounting and the identity tests read this.
        self.derive_info: Optional[dict] = None

    def _unpack_members(self, view):
        """Per-entry member ids from a packed [N,S] view plane.  Empty
        cells (v = 0) decode to SOME id in [0, n); callers must mask
        with their own ``present`` before trusting the values."""
        v = view.astype(np.int64) - 1
        n = self.n
        if n & (n - 1) == 0:
            return v >> n.bit_length() - 1, v & (n - 1)
        return np.divmod(v, n)

    def _derive(self) -> None:
        """The O(N*S) view statistics, once, on whichever thread asks
        first.  Unpacking mirrors ``tpu_hash.unpack``: a view cell
        holds ``member + n*heartbeat + 1`` (0 = empty), so ``member =
        (v-1) % n`` and ``hb = (v-1) // n`` — int64 math so 1M-node
        heartbeats never wrap the unpack arithmetic.

        Grouped max/min via two radix ``np.sort``s of packed uint64
        (member, value) keys: the group tail/head IS the per-member
        max/min.  No ``ufunc.at`` (unbuffered per-element loop, ~10x
        slower at 65k x 16) and no ``argsort`` + index gathers (~2.5x
        slower); empty cells go to a sentinel bucket ``n`` instead of
        a mask-compress pass.  ~70 ms at 65k x 16 on one slow core —
        this runs under the GIL on a query thread, so its cost is the
        floor of the serving overhead BENCH_SERVICE measures."""
        if self._derived:
            return
        with self._lock:
            if self._derived:
                return
            t_start = time.perf_counter()
            n = self.n
            v = self._view.astype(np.int64) - 1          # -1 = empty
            present = (v >= 0) & self.live[:, None]
            hb, member = self._unpack_members(self._view)
            member = np.where(present, member, n).ravel()
            # Empty cells carry hb = -1 (from v = -1); zero them so the
            # uint64 pack can't smear sign bits into the member field.
            hb = np.where(present, hb, 0).ravel()
            stale = (self.tick
                     - self._view_ts.astype(np.int64)).ravel()

            counts = np.bincount(member, minlength=n + 1)
            known_by = counts[:n].astype(np.int64)
            best_hb = np.full(n, -1, np.int64)
            staleness = np.full(n, -1, np.int64)

            key = np.sort((member.astype(np.uint64) << np.uint64(32))
                          | hb.astype(np.uint64))
            m = (key >> np.uint64(32)).astype(np.int64)
            ends = np.flatnonzero(np.r_[m[1:] != m[:-1], True])
            uniq = m[ends]
            keep = uniq < n
            best_hb[uniq[keep]] = (
                key[ends] & np.uint64(0xFFFFFFFF)).astype(
                    np.int64)[keep]

            # Staleness fits 41 bits (TOTAL_TIME is int32-bounded);
            # sentinel 1<<40 keeps empty cells out of the group min.
            sr = np.where(present.ravel(), stale, 1 << 40)
            key = np.sort((member.astype(np.uint64) << np.uint64(41))
                          | sr.astype(np.uint64))
            m = (key >> np.uint64(41)).astype(np.int64)
            starts = np.flatnonzero(np.r_[True, m[1:] != m[:-1]])
            uniq = m[starts]
            keep = uniq < n
            staleness[uniq[keep]] = (
                key[starts] & np.uint64((1 << 41) - 1)).astype(
                    np.int64)[keep]

            sus = np.where(present.ravel() & (stale >= self.tfail),
                           member, n)
            suspected_by = np.bincount(
                sus, minlength=n + 1)[:n].astype(np.int64)
            self.known_by = known_by
            self.best_hb = best_hb
            self.staleness = staleness
            self.suspected_by = suspected_by
            self.suspected = self.live & (suspected_by > 0)
            self.derive_info = {
                "mode": "full",
                "ms": round((time.perf_counter() - t_start) * 1e3, 3),
            }
            self._derived = True

    def dirty_rows(self, prev: "Snapshot") -> np.ndarray:
        """Boolean [N]: observer rows whose CONTRIBUTION changed since
        ``prev`` — liveness flipped, or content changed while live.  A
        row that is down in both snapshots contributes to neither, so
        content churn there is invisible to every derived stat (and to
        the shm delta writer, which publishes the same row set)."""
        row_changed = ((self._view != prev._view).any(axis=1)
                       | (self._view_ts != prev._view_ts).any(axis=1))
        return ((self.live != prev.live)
                | (self.live & prev.live & row_changed))

    def derive_incremental(self, prev: Optional["Snapshot"]) -> bool:
        """Derive the view statistics as a DELTA against a fully
        derived predecessor; byte-identical to :meth:`_derive`.
        Returns False (nothing computed — caller falls back to the
        full derive) when ``prev`` is unusable: missing, not yet
        derived, a different world shape, or from a later tick.

        Exactness argument, per member m:
          * m untouched by any dirty row: every entry mentioning m
            lives in a clean row (identical packed cell, observer live
            in both) — ``known_by``/``best_hb`` depend only on those
            cells, so they carry over; ``staleness`` is
            ``tick - max(view_ts)`` over the same cells, so it
            advances by exactly ``dt``; ``suspected_by`` gains exactly
            the entries whose ``view_ts`` fell inside the window
            ``(t0 - TFAIL, t1 - TFAIL]`` (integer threshold crossing).
          * m mentioned by a dirty row (old or new side): ``known_by``
            and ``suspected_by`` update by exact entry-count deltas,
            and ``best_hb``/``staleness`` are recomputed from scratch
            over ALL of m's present entries (subset sort — the same
            packed-key group tail/head as the full path).
        """
        if self._derived:
            return True
        if (prev is None or not prev._derived or prev.n != self.n
                or prev.tfail != self.tfail or self.tick < prev.tick
                or self._view.shape != prev._view.shape):
            return False
        with self._lock:
            if self._derived:
                return True
            t_start = time.perf_counter()
            n, tfail = self.n, self.tfail
            t0, t1 = prev.tick, self.tick
            dt = t1 - t0
            dirty = self.dirty_rows(prev)
            d = np.flatnonzero(dirty)

            v1 = self._view.astype(np.int64) - 1
            present1 = (v1 >= 0) & self.live[:, None]
            hb1, mem1 = self._unpack_members(self._view)
            ts1 = self._view_ts.astype(np.int64)

            # Old/new contributing entries of the dirty rows only.
            v0d = prev._view[d].astype(np.int64) - 1
            p0d = (v0d >= 0) & prev.live[d, None]
            _, m0d = self._unpack_members(prev._view[d])
            ts0d = prev._view_ts[d].astype(np.int64)
            p1d, m1d, ts1d = present1[d], mem1[d], ts1[d]

            # Affected members: anyone a dirty row mentioned, before
            # or after.  Their sorted stats are recomputed exactly.
            a_mask = np.zeros(n, bool)
            a_mask[m0d[p0d]] = True
            a_mask[m1d[p1d]] = True

            # known_by: exact entry-count delta (dirty rows only).
            known_by = prev.known_by.copy()
            known_by -= np.bincount(m0d[p0d], minlength=n)[:n]
            known_by += np.bincount(m1d[p1d], minlength=n)[:n]

            # suspected_by: dirty-row delta + the clean-row entries
            # whose age crossed TFAIL inside (t0, t1] — a vectorized
            # window count, no sort.
            suspected_by = prev.suspected_by.copy()
            suspected_by -= np.bincount(
                m0d[p0d & (t0 - ts0d >= tfail)], minlength=n)[:n]
            suspected_by += np.bincount(
                m1d[p1d & (t1 - ts1d >= tfail)], minlength=n)[:n]
            clean_live = self.live & ~dirty
            win = (present1 & clean_live[:, None]
                   & (ts1 > t0 - tfail) & (ts1 <= t1 - tfail))
            suspected_by += np.bincount(mem1[win], minlength=n)[:n]

            # best_hb carries over; staleness ages uniformly (-1 =
            # unknown stays -1).  Affected members are then re-derived
            # from scratch over all their present entries.
            best_hb = prev.best_hb.copy()
            staleness = np.where(prev.staleness >= 0,
                                 prev.staleness + dt, prev.staleness)
            aff = np.flatnonzero(a_mask)
            if len(aff):
                best_hb[aff] = -1
                staleness[aff] = -1
                asel = present1 & a_mask[mem1]
                am = mem1[asel]
                if len(am):
                    ah, ats = hb1[asel], ts1[asel]
                    key = np.sort(
                        (am.astype(np.uint64) << np.uint64(32))
                        | ah.astype(np.uint64))
                    m = (key >> np.uint64(32)).astype(np.int64)
                    ends = np.flatnonzero(np.r_[m[1:] != m[:-1], True])
                    best_hb[m[ends]] = (
                        key[ends] & np.uint64(0xFFFFFFFF)).astype(
                            np.int64)
                    key = np.sort(
                        (am.astype(np.uint64) << np.uint64(41))
                        | (t1 - ats).astype(np.uint64))
                    m = (key >> np.uint64(41)).astype(np.int64)
                    starts = np.flatnonzero(np.r_[True,
                                                  m[1:] != m[:-1]])
                    staleness[m[starts]] = (
                        key[starts] & np.uint64((1 << 41) - 1)).astype(
                            np.int64)
            self.known_by = known_by
            self.best_hb = best_hb
            self.staleness = staleness
            self.suspected_by = suspected_by
            self.suspected = self.live & (suspected_by > 0)
            self.derive_info = {
                "mode": "delta",
                "ms": round((time.perf_counter() - t_start) * 1e3, 3),
                "dirty_rows": int(len(d)),
                "affected_members": int(len(aff)),
                "dt": int(dt),
            }
            self._derived = True
        return True

    def precompute(self, prev: Optional["Snapshot"] = None) -> None:
        """Publish-time derivation (the daemon's snapshot publisher
        calls this OFF the engine thread): delta-derive against the
        previous published snapshot when possible, full derive
        otherwise, then pre-encode the census reply — so no query
        ever triggers a derive."""
        if not self.derive_incremental(prev):
            self._derive()
        self.census_json()

    def census(self) -> dict:
        if self._census is None:
            self._derive()
            self._census = {
                "tick": self.tick,
                "n": self.n,
                "live": int(self.live.sum()),
                "suspected": int(self.suspected.sum()),
                "removed": int(self.removed.sum()),
                "unstarted": int((~self.started).sum()),
                "known_members": int((self.known_by > 0).sum()),
                "view_entries": int(self.known_by.sum()),
                "max_staleness": int(self.staleness.max(initial=-1)),
            }
        return self._census

    def census_json(self) -> bytes:
        """The census reply pre-encoded: the hammering-dashboards hot
        path pays the JSON encode once per snapshot, not per query."""
        if self._census_body is None:
            self._census_body = (json.dumps(self.census())
                                 + "\n").encode()
        return self._census_body

    def member(self, i: int) -> dict:
        self._derive()
        return {
            "id": int(i),
            "tick": self.tick,
            "live": bool(self.live[i]),
            "suspected": bool(self.suspected[i]),
            "removed": bool(self.removed[i]),
            "started": bool(self.started[i]),
            "in_group": bool(self.in_group[i]),
            "self_hb": int(self.self_hb[i]),
            "known_by": int(self.known_by[i]),
            "suspected_by": int(self.suspected_by[i]),
            "best_heartbeat": int(self.best_hb[i]),
            "staleness": int(self.staleness[i]),
        }


def decode_state(carry, tick: int, n: int, tfail: int) -> Snapshot:
    """Wrap a host carry as a :class:`Snapshot` (numpy only, lazy).

    Works on any carry exposing the hash twins' field names
    (``view``/``view_ts`` packed membership, ``started``/``in_group``/
    ``failed``/``self_hb``): both :class:`~backends.tpu_hash.HashState`
    and the sharded twin qualify (``np.asarray`` on a sharded leaf
    yields the assembled global array).
    """
    return Snapshot(tick, n, tfail,
                    started=carry.started, in_group=carry.in_group,
                    failed=carry.failed, self_hb=carry.self_hb,
                    view=carry.view, view_ts=carry.view_ts)


class SnapshotStore:
    """Reference-swap publication of immutable snapshots.

    ``publish`` rebinds one attribute (atomic under the GIL);
    ``get`` hands back whatever snapshot is current.  Readers never
    block the engine and never see a half-written view.
    """

    def __init__(self):
        self._snap: Optional[Snapshot] = None

    def publish(self, snap: Snapshot) -> None:
        self._snap = snap

    def get(self) -> Optional[Snapshot]:
        return self._snap
