"""Membership control plane: a live query/inject service driving the
jitted tick engine.

Every other layer runs in batch — start, scan, exit.  This package is
the always-on posture: ``python -m distributed_membership_tpu run.conf
--serve [--port P]`` keeps the CHECKPOINT_EVERY-tick segment loop
(runtime/checkpoint.py) ticking on the device while a stdlib-only
threaded HTTP API answers liveness queries and accepts live fault
injection.  Between segments the daemon

  * publishes a double-buffered host :class:`~snapshot.Snapshot`
    (live/suspected/removed masks, heartbeat staleness, census, current
    tick) decoded from the already-pulled scan carry — queries are
    answered from the snapshot in O(1) per member and never touch
    device state;
  * drains a command queue of injected scenario events (validated by
    scenario/schema.py, journaled to ``service_events.jsonl`` so
    ``RESUME`` replays them, compiled with the base schedule into the
    NEXT segment's tensor plan);
  * hands control back to the device for the next segment.

Crash-safe by construction: kill the daemon, restart with ``--resume``,
and the trajectory (dbg.log, timeline.jsonl, grader verdicts, pending
injected events) is bit-exact vs. an uninterrupted run
(tests/test_service.py).  Endpoints and semantics: README "Service".
"""

from distributed_membership_tpu.service.snapshot import (  # noqa: F401
    Snapshot, SnapshotStore, decode_state)
from distributed_membership_tpu.service.daemon import (  # noqa: F401
    serve_conf, serve_run)
