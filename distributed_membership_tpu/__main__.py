from distributed_membership_tpu.runtime.application import main
import sys

sys.exit(main())
