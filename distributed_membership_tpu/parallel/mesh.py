"""Device mesh construction for the node-sharded backends.

The reference's "distribution" is N logical peers multiplexed in one process
(SURVEY.md §2); the rebuild's real distribution axis is the *node* axis: the
``[N, ...]`` protocol state is sharded row-wise over a mesh, gossip between
co-located nodes stays on-chip, and cross-shard gossip rides ICI via the
collectives in :mod:`distributed_membership_tpu.parallel.collectives`.

Two mesh shapes:

* 1-D (:func:`make_mesh`) — the default; shard ``d`` owns contiguous rows
  ``[d*L, (d+1)*L)`` and the ring exchange's block shifts are single
  ``ppermute`` rotations over the one axis.
* 2-D torus (:func:`make_mesh2d`) — for slices whose physical ICI topology
  is a torus (a v4-32 is 4x4x2; larger slices 3-D).  The node axis is
  sharded over BOTH axes, outer-major: shard ``(o, i)`` holds flat index
  ``o*DI + i``.  Collectives that read the whole axis (``all_gather``,
  ``psum``, ``psum_scatter``, ``axis_index``) take the axis-name TUPLE and
  behave exactly as on the flattened 1-D mesh, so the protocol code is
  shape-agnostic; the ring exchange's block shift decomposes into per-axis
  rotations (see tpu_hash_sharded ``block_send``) so every hop moves
  payloads between physical torus neighbors instead of asking the router
  to realize an arbitrary flat permutation.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODE_AXIS = "nodes"
# 2-D torus axis names (outer-major flattening: flat = o * DI + i).
NODE_OUTER = "nodes_o"
NODE_INNER = "nodes_i"


def _take_devices(n_devices: int | None):
    devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)} "
                f"(set --xla_force_host_platform_device_count for CPU testing)")
        devices = devices[:n_devices]
    return devices


def make_mesh(n_devices: int | None = None) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` devices (default: all)."""
    return Mesh(np.asarray(_take_devices(n_devices)), (NODE_AXIS,))


def make_mesh2d(outer: int, inner: int) -> Mesh:
    """A 2-D ``outer x inner`` torus mesh over the first outer*inner
    devices.  On real hardware pass the slice's physical topology so the
    per-axis ring rotations ride each ICI dimension's links; on the
    virtual CPU mesh any factorization exercises the same program."""
    devices = _take_devices(outer * inner)
    return Mesh(np.asarray(devices).reshape(outer, inner),
                (NODE_OUTER, NODE_INNER))


def make_torus_mesh(*dims: int) -> Mesh:
    """An N-D torus mesh (major axis first).  2-D keeps make_mesh2d's
    axis names; higher ranks name axes ``nodes_d0`` (outermost) …
    ``nodes_d{N-1}``.  The intended 3-D reading is multi-slice: the
    outermost axis spans slices over DCN, the inner two a slice's ICI
    torus — the ring exchange's block shifts decompose per axis
    (tpu_hash_sharded.make_block_send), so each gossip shift crosses
    DCN at most twice (one mostly-zero carry stream) regardless of
    slice count, and all other traffic stays on ICI."""
    if len(dims) == 1:
        return make_mesh(dims[0])
    if len(dims) == 2:
        return make_mesh2d(*dims)
    devices = _take_devices(int(np.prod(dims)))
    names = tuple(f"nodes_d{k}" for k in range(len(dims)))
    return Mesh(np.asarray(devices).reshape(*dims), names)


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Shard axis 0 (the node axis) over the mesh (both axes if 2-D).

    ``mesh.axis_names`` / ``mesh.size`` are the idiomatic accessors for
    the axis tuple and total device count — no wrappers needed."""
    return NamedSharding(mesh, P(tuple(mesh.axis_names)))


def check_divisible(n: int, mesh: Mesh) -> int:
    if n % mesh.size != 0:
        raise ValueError(
            f"node count {n} must be divisible by mesh size {mesh.size}")
    return n // mesh.size
