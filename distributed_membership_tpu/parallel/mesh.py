"""Device mesh construction for the node-sharded backends.

The reference's "distribution" is N logical peers multiplexed in one process
(SURVEY.md §2); the rebuild's real distribution axis is the *node* axis: the
``[N, ...]`` protocol state is sharded row-wise over a 1-D mesh, gossip
between co-located nodes stays on-chip, and cross-shard gossip rides ICI via
the collectives in :mod:`distributed_membership_tpu.parallel.collectives`.
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

NODE_AXIS = "nodes"


def make_mesh(n_devices: int | None = None) -> Mesh:
    """A 1-D mesh over the first ``n_devices`` devices (default: all)."""
    devices = jax.devices()
    if n_devices is not None:
        if len(devices) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devices)} "
                f"(set --xla_force_host_platform_device_count for CPU testing)")
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (NODE_AXIS,))


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Shard axis 0 (the node axis) over the mesh."""
    return NamedSharding(mesh, P(NODE_AXIS))


def check_divisible(n: int, mesh: Mesh) -> int:
    s = mesh.shape[NODE_AXIS]
    if n % s != 0:
        raise ValueError(f"node count {n} must be divisible by mesh size {s}")
    return n // s
