"""ICI collectives for cross-shard gossip.

The reference's communication backend is a global in-memory mailbox
(EmulNet, SURVEY.md §2 component #3).  Sharded over a mesh, its TPU-native
equivalent is: every shard computes a *partial* contribution tensor for all
receivers (max over its local senders), and the shards then reduce those
partials with ``max`` while scattering receiver rows to their owners.

XLA has no built-in reduce-scatter for ``max`` (``lax.psum_scatter`` is
sum-only), so we implement the classic ring algorithm with
``lax.ppermute``: chunk ``b`` starts at shard ``b+1`` and travels one hop
per step, max-combining each host's partial, arriving fully reduced at its
owner after ``S-1`` hops.  Bandwidth-optimal: each shard moves
``(S-1)/S`` of one copy of the data over ICI neighbor links, versus the
``pmax`` all-reduce which replicates the whole tensor to every shard.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def allreduce_max(x: jax.Array, axis_name: str) -> jax.Array:
    """All-reduce max over the mesh axis (every shard gets the full result)."""
    return lax.pmax(x, axis_name)


def ring_reduce_scatter_max(x: jax.Array, axis_name: str) -> jax.Array:
    """Reduce-scatter with max over a 1-D mesh axis using a ppermute ring.

    Args:
      x: per-shard partial of shape ``[S*B, ...]`` — the full (unsharded)
        first axis; shard ``s`` owns rows ``[s*B, (s+1)*B)`` of the result.
      axis_name: mesh axis to reduce over.

    Returns:
      ``[B, ...]``: the max over all shards' partials of this shard's rows.
    """
    # Static axis size: psum of a Python scalar constant-folds to an int
    # on every supported jax (lax.axis_size only exists on newer releases).
    s = lax.psum(1, axis_name)
    me = lax.axis_index(axis_name)
    if s == 1:
        return x
    b = x.shape[0] // s
    blocks = x.reshape(s, b, *x.shape[1:])
    perm = [(j, (j + 1) % s) for j in range(s)]

    # Chunk destined for shard `d` starts at shard `d+1`; from shard `me`'s
    # perspective, it holds chunk (me - 1) at step 0 and chunk (me - 1 - i)
    # after receiving at step i, max-combining its own partial each hop.
    #
    # `me` is traced, so indexing chunk (me - 1 - i) directly would be a
    # DYNAMIC gather per hop — S-1 of them, each materializing a [B, ...]
    # copy from the [S, B, ...] buffer between the ppermutes (and on TPU,
    # relayouting the buffer for every per-hop slice).  One pre-rotation
    # puts the hop schedule in STATIC order instead:
    # rolled[i] == blocks[(me - 1 - i) % s], so the loop body is a
    # constant-index slice XLA folds into the combine.  The combine order
    # per chunk is unchanged hop for hop, so results are bit-identical.
    rolled = jnp.roll(blocks[::-1], me, axis=0)
    acc = rolled[0]
    for i in range(1, s):
        acc = lax.ppermute(acc, axis_name, perm)
        acc = jnp.maximum(acc, rolled[i])
    return acc


def reduce_scatter_sum(x: jax.Array, axis_name: str) -> jax.Array:
    """Sum reduce-scatter of a ``[S*B, ...]`` partial (XLA-native)."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=0, tiled=True)


def all_gather_vec(x: jax.Array, axis_name: str) -> jax.Array:
    """Gather a sharded ``[B, ...]`` vector into the full ``[S*B, ...]``."""
    return lax.all_gather(x, axis_name, axis=0, tiled=True)
