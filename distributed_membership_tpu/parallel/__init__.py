"""Device-mesh parallelism helpers.

Also the single compatibility seam for ``shard_map``: newer jax exports it
as ``jax.shard_map`` (kwarg ``check_vma``), older releases (including this
image's 0.4.x) only under ``jax.experimental.shard_map`` (same knob named
``check_rep``) — importing from here keeps every backend and test working
on both.
"""

try:
    from jax import shard_map  # noqa: F401  (jax >= 0.6)
except ImportError:  # pragma: no cover - which branch runs depends on jax
    import functools

    from jax.experimental.shard_map import shard_map as _shard_map

    @functools.wraps(_shard_map)
    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(*args, **kwargs)
