"""Python port of the grading oracle (reference Grader_verbose.sh).

The reference grades a run by grepping dbg.log:

  * Join: 100 unique ``(logger, "Node <x> joined at")`` pairs
    (``cut -d" " -f2,4-7 | sort -u``), or the fallback — every one of the 10
    loggers has logged 9 *distinct other* nodes joined
    (Grader_verbose.sh:41-61);
  * Completeness (single failure): >= 9 unique ``removed`` lines naming the
    failed node (:62-69);
  * Accuracy (single failure): zero unique ``removed`` lines NOT naming the
    failed node (:70-77);
  * Multi failure: per failed node (first 5), >= 5 removal lines → 2 pts each;
    accuracy: exactly 20 unique removed lines not naming it → 2 pts each
    (:111-140);
  * Msg-drop scenario: join (15) + completeness (15); accuracy commented out
    (:153-181).

This module replicates those checks with the same string semantics
(space-split fields, substring matching — the shell uses plain ``grep $addr``)
so a log that passes here passes the shell script and vice versa.  Scores sum
to the reference's 90-point scale.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List


def _unique(lines) -> List[str]:
    return sorted(set(lines))


def _fields(line: str) -> List[str]:
    # `cut -d" "` semantics: split on single spaces, 1-indexed, keep empties.
    return line.split(" ")


def _cut(line: str, idxs) -> str:
    f = _fields(line)
    return " ".join(f[i - 1] for i in idxs if i - 1 < len(f))


@dataclasses.dataclass
class ScenarioResult:
    scenario: str
    join_ok: bool
    completeness_pts: int
    completeness_max: int
    accuracy_pts: int
    accuracy_max: int
    join_pts: int
    join_max: int
    details: Dict[str, object]

    @property
    def points(self) -> int:
        return self.join_pts + self.completeness_pts + self.accuracy_pts

    @property
    def max_points(self) -> int:
        return self.join_max + self.completeness_max + self.accuracy_max

    @property
    def passed(self) -> bool:
        return self.points == self.max_points


def _check_join(lines: List[str], n_nodes: int) -> bool:
    joined = [l for l in lines if "joined" in l]
    pairs = _unique(_cut(l, [2, 4, 5, 6, 7]) for l in joined)
    if len(pairs) == n_nodes * n_nodes:
        return True
    # Fallback path (Grader_verbose.sh:46-55): each logger saw N-1 others.
    loggers = _unique(_cut(l, [2]) for l in joined)
    cnt = 0
    for logger in loggers:
        tos = _unique(
            _cut(l, [4, 5, 6, 7])
            for l in joined
            if l.startswith(" " + logger) and logger not in _cut(l, [4, 5, 6, 7])
        )
        if len(tos) == n_nodes - 1:
            cnt += 1
    return cnt == n_nodes


def _failed_addrs(lines: List[str]) -> List[str]:
    # `grep "Node failed at time" | sort -u | awk '{print $1}'`: sorted unique
    # full lines, then the first whitespace field (the logger == failed node).
    failed_lines = _unique(l for l in lines if "Node failed at time" in l)
    return [l.split()[0] for l in failed_lines]


def grade_single(dbg_text: str, n_nodes: int = 10, join_pts: int = 10,
                 fail_pts: int = 10, scenario: str = "singlefailure",
                 check_accuracy: bool = True) -> ScenarioResult:
    lines = dbg_text.splitlines()
    join_ok = _check_join(lines, n_nodes)
    failed = _failed_addrs(lines)
    removed = _unique(l for l in lines if "removed" in l)

    failcount = 0
    accuracycount = -1
    if failed:
        addr = failed[0]
        failcount = sum(1 for l in removed if addr in l)
        accuracycount = sum(1 for l in removed if addr not in l)

    comp_ok = failcount >= n_nodes - 1
    acc_ok = accuracycount == 0 and failcount > 0
    return ScenarioResult(
        scenario=scenario,
        join_ok=join_ok,
        join_pts=join_pts if join_ok else 0, join_max=join_pts,
        completeness_pts=fail_pts if comp_ok else 0, completeness_max=fail_pts,
        accuracy_pts=(fail_pts if acc_ok else 0) if check_accuracy else 0,
        accuracy_max=fail_pts if check_accuracy else 0,
        details={"failed": failed, "failcount": failcount,
                 "accuracycount": accuracycount, "removed_lines": len(removed)},
    )


def grade_multi(dbg_text: str, n_nodes: int = 10) -> ScenarioResult:
    lines = dbg_text.splitlines()
    join_ok = _check_join(lines, n_nodes)
    failed = _failed_addrs(lines)
    removed = _unique(l for l in lines if "removed" in l)
    n_failed = max(len(failed), 1)
    n_survivors = n_nodes - n_failed

    # Completeness: 2 pts per failed node with >= n_survivors removal lines,
    # first 5 failed nodes only (Grader_verbose.sh:111-126).
    comp_pts = 0
    for addr in failed[:5]:
        if sum(1 for l in removed if addr in l) >= n_survivors:
            comp_pts += 2

    # Accuracy: 2 pts per failed node whose complement count is exactly
    # (total expected removals) - (its own removals) (=20 at N=10, :127-140).
    expected_complement = n_survivors * n_failed - n_survivors
    acc_pts = 0
    for addr in failed:
        if sum(1 for l in removed if addr not in l) == expected_complement:
            acc_pts += 2
        if acc_pts > 9:
            break
    acc_pts = min(acc_pts, 10)

    return ScenarioResult(
        scenario="multifailure",
        join_ok=join_ok,
        join_pts=10 if join_ok else 0, join_max=10,
        completeness_pts=comp_pts, completeness_max=10,
        accuracy_pts=acc_pts, accuracy_max=10,
        details={"failed": failed, "removed_lines": len(removed)},
    )


def grade_msgdrop(dbg_text: str, n_nodes: int = 10) -> ScenarioResult:
    # Join 15 + completeness 15, accuracy disabled (Grader_verbose.sh:153-189).
    r = grade_single(dbg_text, n_nodes, join_pts=15, fail_pts=15,
                     scenario="msgdropsinglefailure", check_accuracy=False)
    return r


SCENARIO_GRADERS = {
    "singlefailure": grade_single,
    "multifailure": grade_multi,
    "msgdropsinglefailure": grade_msgdrop,
}


def grade_scenario(scenario: str, dbg_text: str, n_nodes: int = 10) -> ScenarioResult:
    return SCENARIO_GRADERS[scenario](dbg_text, n_nodes)
