"""Grader-facing event log: the dbg.log / stats.log contract.

Rebuild of the reference ``Log`` class (Log.{h,cpp}).  The grading oracle
(Grader_verbose.sh) greps ``dbg.log`` for ``joined`` / ``removed`` /
``Node failed at time`` lines, so this file *is* the compatibility surface.
Byte format replicated from Log.cpp:

  * first line: the magic number — hex of the character sum of "CS425"
    (Log.cpp:79-88), i.e. ``131``;
  * each entry: ``"\\n <addr> [<time>] <message>"`` — note the leading space
    before the address (Log.cpp:97-99: ``fprintf(fp, "\\n %s", stdstring)``
    where stdstring carries a trailing space, then ``"[%d] "`` then the body);
  * messages prefixed ``#STATSLOG#`` are routed to stats.log instead
    (Log.cpp:90-95);
  * event line bodies: ``Node <addr> joined at time <t>`` (Log.cpp:118) and
    ``Node <addr> removed at time <t>`` (Log.cpp:129).

Defect D1 (static 30-char buffer overflow truncating the log, Log.cpp:117-118)
is structurally impossible here.  Unlike the reference, which flushes every
line (MAXWRITES=1, Log.h:18), we buffer in memory and flush on close — the
TPU backends emit events in bulk after a ``lax.scan``, so per-line flushing
would be pure overhead.
"""

from __future__ import annotations

import os
from typing import List, Optional

from distributed_membership_tpu.addressing import addr_str

MAGIC_SOURCE = "CS425"  # Log.h:19
DBG_LOG = "dbg.log"     # Log.h:21
STATS_LOG = "stats.log"  # Log.h:22
STATS_PREFIX = "#STATSLOG#"


def magic_line() -> str:
    """Hex char-sum of the magic string, '131' for CS425 (Log.cpp:79-88)."""
    return format(sum(ord(c) for c in MAGIC_SOURCE), "x")


def format_entry(addr: str, time: int, message: str) -> str:
    """One log entry exactly as Log.cpp:97-99 emits it."""
    return f"\n {addr} [{time}] {message}"


def joined_message(added_addr: str, time: int) -> str:
    return f"Node {added_addr} joined at time {time}"  # Log.cpp:118


def removed_message(removed_addr: str, time: int) -> str:
    return f"Node {removed_addr} removed at time {time}"  # Log.cpp:129


class EventLog:
    """In-memory accumulator for the dbg.log / stats.log channels."""

    def __init__(self, directory: str = "."):
        self.directory = directory
        self._dbg: List[str] = []
        self._stats: List[str] = []
        self._wrote_magic = False

    # -- primitive, mirrors Log::LOG (Log.cpp:44-109) --------------------
    def log(self, node_id: int, time: int, message: str, port: int = 0) -> None:
        if not self._wrote_magic:
            self._dbg.append(magic_line() + "\n")
            self._wrote_magic = True
        entry = format_entry(addr_str(node_id, port), time, message)
        if message.startswith(STATS_PREFIX):
            self._stats.append(entry)
        else:
            self._dbg.append(entry)

    # -- event helpers, mirror logNodeAdd / logNodeRemove -----------------
    def node_add(self, logger_id: int, added_id: int, time: int) -> None:
        self.log(logger_id, time, joined_message(addr_str(added_id), time))

    def node_remove(self, logger_id: int, removed_id: int, time: int) -> None:
        self.log(logger_id, time, removed_message(addr_str(removed_id), time))

    def node_failed_single(self, failed_id: int, time: int) -> None:
        # Application.cpp:184 — no spaces around '='.
        self.log(failed_id, time, f"Node failed at time={time}")

    def node_failed_multi(self, failed_id: int, time: int) -> None:
        # Application.cpp:192 — spaces around '='.
        self.log(failed_id, time, f"Node failed at time = {time}")

    # ---------------------------------------------------------------------
    def dbg_text(self) -> str:
        return "".join(self._dbg)

    def stats_text(self) -> str:
        return "".join(self._stats)

    def flush(self, directory: Optional[str] = None) -> str:
        """Write dbg.log and stats.log; returns the dbg.log path."""
        directory = directory or self.directory
        os.makedirs(directory, exist_ok=True)
        dbg_path = os.path.join(directory, DBG_LOG)
        with open(dbg_path, "w") as fh:
            fh.write(self.dbg_text())
        with open(os.path.join(directory, STATS_LOG), "w") as fh:
            fh.write(self.stats_text())
        return dbg_path
