"""Checkpoint/resume: the resilient-run harness for the jitted backends.

A 700-tick 1M-node run used to be one monolithic ``lax.scan`` that had to
complete inside a single flaky hardware window or produce nothing (round 5:
the TPU relay was dark all round and every ladder pass banked zero rungs).
Production ML stacks on shared mesh hardware treat preemption as normal and
checkpoint/restore as the baseline availability mechanism; this module is
that mechanism for the simulator:

  * :func:`chunked_run` drives a backend's tick loop in
    ``CHECKPOINT_EVERY``-tick scan segments.  Between segments the full
    carry (membership tensors, mailboxes, counters, event aggregates) is
    pulled to host and — when ``CHECKPOINT_DIR`` is set — snapshotted to a
    versioned on-disk checkpoint with atomic write-rename, plus a manifest
    recording ``(params_text, seed, backend, tick, state_hash)``.  The
    per-tick PRNG keys are re-derived from the run seed via
    ``runtime/failures.plan_tensors`` (fold_in of the tick index), so only
    the tick index needs persisting — never key material.
  * ``RESUME: 1`` validates the manifest against the requested config and
    continues the run **bit-exactly**: resumed dbg.log/stats.log and final
    grades are identical to an uninterrupted run (pinned by
    tests/test_checkpoint.py, which kills runs mid-flight at several ticks).
  * With ``EVENT_MODE: full``, each segment's stacked event tensors are
    flushed to a host-side compaction (:class:`CompactEvents`) immediately,
    so device memory for events is O(CHECKPOINT_EVERY * N * M) instead of
    the whole-run O(T * N * M) cliff (~350 GB at N=1M).

Fault injection for tests and drills: set the ``DM_CRASH_AT_TICK`` env var
to a tick index and the driver raises ``RuntimeError`` the moment it would
start the segment containing that tick — leaving exactly the on-disk state
a real mid-run kill leaves.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import time
from typing import Callable, List, NamedTuple, Optional

import numpy as np

from distributed_membership_tpu.config import Params

CKPT_VERSION = 1
MANIFEST_NAME = "MANIFEST.json"
KEEP_CHECKPOINTS = 3       # versioned history depth; older files pruned
CRASH_ENV = "DM_CRASH_AT_TICK"
# Boundary state reporting for fleet workers (fleet/scheduler.py sets
# this): the driver atomically rewrites the named JSON file with
# {tick, total, ts} at every segment boundary, so a controller can read
# a HEADLESS worker's progress without an HTTP surface on the worker.
STATE_FILE_ENV = "DM_RUN_STATE_FILE"

# Fields that do not change what the run computes per tick: the clock
# (reset by parse), and the checkpoint-control keys themselves — a resume
# may legitimately use a different CHECKPOINT_EVERY/DIR (segment boundaries
# never affect per-tick math; bit-exactness is pinned across chunkings).
_IDENTITY_EXCLUDE = frozenset(
    {"globaltime", "dropmsg", "CHECKPOINT_EVERY", "CHECKPOINT_DIR",
     "RESUME", "CHECKPOINT_COMPRESS",
     # Multi-tick residency is trajectory-inert by contract: the T-tick
     # megakernel blocks and the shrunk boundary carry are bit-exact vs
     # the per-tick scan (tests/test_megakernel.py pins all four ring
     # twins), so a resume may change T or the pack width — the on-disk
     # snapshot is always the full-width carry at a segment boundary.
     "MEGA_TICKS", "MEGA_PACK",
     # The batched exchange wire is trajectory-inert by contract too: the
     # sender-aligned all_to_all delivers exactly the payloads the legacy
     # per-shift rotations deliver (tests/test_exchange.py pins all four
     # ring twins), and its double-buffered carry lane is flushed into
     # the mailbox at every segment boundary, so the on-disk snapshot is
     # always the legacy-shaped carry — a resume may switch modes.
     "EXCHANGE_MODE",
     # Telemetry is trajectory-inert by contract (tests/test_timeline.py
     # pins bit-exactness on/off), so a resume may turn the flight
     # recorder on or move its output dir without invalidating the run.
     "TELEMETRY", "TELEMETRY_DIR",
     # The control plane (service/ package) is trajectory-inert too:
     # snapshots are decoded from the already-pulled host carry and
     # queries never touch device state, so a resume may serve on a
     # different port (or not serve at all) without invalidating the
     # run (tests/test_service.py pins serve-on/off bit-exactness).
     "SERVICE_PORT", "SERVICE_SNAPSHOT_EVERY",
     # The query tier rides the same contract: replicas read snapshots
     # out of shared memory the publisher thread wrote off the engine
     # thread; neither the pool size nor the ring depth can reach the
     # per-tick math (tests/test_query_tier.py pins replica replies
     # byte-identical to the engine's own).
     "SERVICE_WORKERS", "SERVICE_SHM_BUFFERS",
     # The fleet keys configure the CONTROLLER process, never the run's
     # per-tick math — a conf submitted to a fleet resumes bit-exactly
     # under a controller with different scheduling knobs (or none).
     "FLEET_PORT", "FLEET_MAX_CONCURRENCY", "FLEET_DIR", "FLEET_LINGER",
     "FLEET_MIGRATE_ON", "FLEET_MIGRATE_MAX",
     # The watchdog (observability/watchdog.py) only OBSERVES host-side
     # artifacts (runlog, beacons, the published snapshot metadata) —
     # a resume may toggle it freely.
     "WATCHDOG"})


def params_identity(params: Params) -> str:
    """Canonical text of every protocol-relevant config field — the
    manifest's ``params_text``.  Two configs with equal identity compute
    the same per-tick transition for the same seed."""
    d = {k: v for k, v in dataclasses.asdict(params).items()
         if k not in _IDENTITY_EXCLUDE}
    return json.dumps(d, sort_keys=True)


def state_hash(leaves) -> str:
    """sha256 over the carry's flattened leaves (dtype, shape, bytes) —
    detects on-disk corruption and wrong-file resumes before any compute."""
    h = hashlib.sha256()
    for leaf in leaves:
        a = np.ascontiguousarray(np.asarray(leaf))
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()


# --------------------------------------------------------------------------
# Host-side event compaction (the EVENT_MODE=full per-segment flush)

class CompactEvents(NamedTuple):
    """Sparse host form of the full-event stacked tensors.

    ``joins``/``removes`` rows are ``(tick, logger_index, member_index)``
    (0-based, as the stacked tensors index them); ``sent``/``recv`` keep
    the dense ``[T, N]`` msgcount shape (already O(T*N) in the reference's
    own profile matrices).  ``events_to_log`` in backends/tpu.py and
    backends/tpu_sparse.py consume this form directly.
    """
    joins: np.ndarray     # [K, 3] i64
    removes: np.ndarray   # [R, 3] i64
    sent: np.ndarray      # [T, N] i32
    recv: np.ndarray      # [T, N] i32
    total: int            # ticks covered


def _triples(t, i, j, t0: int) -> np.ndarray:
    out = np.stack([np.asarray(t, np.int64) + t0,
                    np.asarray(i, np.int64),
                    np.asarray(j, np.int64)], axis=1)
    return out.reshape(-1, 3)


def compact_dense(events, t0: int = 0) -> CompactEvents:
    """Compact the dense backend's TickEvents ([C, N, N] bool planes)."""
    jt, ji, jj = np.nonzero(np.asarray(events.joins))
    rt, ri, rj = np.nonzero(np.asarray(events.removes))
    sent = np.asarray(events.sent)
    return CompactEvents(_triples(jt, ji, jj, t0), _triples(rt, ri, rj, t0),
                         sent, np.asarray(events.recv), sent.shape[0])


def compact_sparse(events, t0: int = 0) -> CompactEvents:
    """Compact SparseTickEvents ([C, N, M] member-id planes, -1 = none)."""
    join_ids = np.asarray(events.join_ids)
    rm_ids = np.asarray(events.rm_ids)
    jt, ji, js = np.nonzero(join_ids >= 0)
    rt, ri, rs = np.nonzero(rm_ids >= 0)
    sent = np.asarray(events.sent)
    return CompactEvents(_triples(jt, ji, join_ids[jt, ji, js], t0),
                         _triples(rt, ri, rm_ids[rt, ri, rs], t0),
                         sent, np.asarray(events.recv), sent.shape[0])


def concat_compact(parts: List[CompactEvents]) -> CompactEvents:
    parts = [p for p in parts if p is not None]
    if len(parts) == 1:
        return parts[0]
    return CompactEvents(
        np.concatenate([p.joins for p in parts]),
        np.concatenate([p.removes for p in parts]),
        np.concatenate([p.sent for p in parts]),
        np.concatenate([p.recv for p in parts]),
        sum(p.total for p in parts))


def _empty_compact(n: int) -> CompactEvents:
    z3 = np.zeros((0, 3), np.int64)
    zn = np.zeros((0, n), np.int32)
    return CompactEvents(z3, z3.copy(), zn, zn.copy(), 0)


# --------------------------------------------------------------------------
# On-disk format

def _manifest_path(ckpt_dir: str) -> str:
    return os.path.join(ckpt_dir, MANIFEST_NAME)


def load_manifest(ckpt_dir: Optional[str]) -> Optional[dict]:
    """The manifest dict, or None when absent/unreadable (a torn write is
    a fresh start, never a crash — resume must not brick the retry loop)."""
    if not ckpt_dir:
        return None
    try:
        with open(_manifest_path(ckpt_dir)) as fh:
            return json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None


def manifest_tick(ckpt_dir: Optional[str]) -> Optional[int]:
    """Latest durably-checkpointed tick (ladder/bench resume provenance)."""
    m = load_manifest(ckpt_dir)
    return None if m is None else int(m.get("tick", 0)) or None


def _atomic_write(path: str, write_fn: Callable[[str], None]) -> None:
    tmp = path + ".tmp"
    write_fn(tmp)
    os.replace(tmp, path)


def _process_count() -> int:
    from distributed_membership_tpu.runtime.distributed import process_count
    return process_count()


def _manifest_base(params: Params, seed: int, total: int,
                   collect_events: bool) -> dict:
    base = {
        "version": CKPT_VERSION,
        "params_text": params_identity(params),
        "seed": int(seed),
        "backend": params.BACKEND,
        "total_time": int(total),
        "collect_events": bool(collect_events),
        # Process topology (runtime/distributed.py): a multi-process run
        # shards the SAME global mesh, so its per-tick math is identical
        # to the single-process twin — but each process snapshots its own
        # CHECKPOINT_DIR, and resuming one process's directory under a
        # different topology would silently re-shard a carry the other
        # processes still hold.  Refuse loudly instead.
        "process_count": _process_count(),
    }
    if params.SCENARIO:
        # Content digest, not just the path (already in params_text): a
        # silently edited schedule must fail the resume validation, not
        # resume into a different chaos plan.
        from distributed_membership_tpu.scenario.compile import (
            scenario_digest)
        try:
            base["scenario_digest"] = scenario_digest(params.SCENARIO)
        except OSError:
            base["scenario_digest"] = "unreadable"
    return base


def _save_checkpoint(ckpt_dir: str, base: dict, tick: int,
                     carry_leaves: list, payload: dict,
                     compress: bool = False) -> None:
    """One versioned snapshot: ``ckpt_<tick>.npz`` (atomic write-rename),
    then the manifest pointing at it (atomic too — a crash between the
    two leaves the previous manifest valid).  Runs on the chunked
    driver's background writer thread (one worker, so manifest
    read-modify-writes stay sequential); ``compress`` selects
    ``np.savez_compressed`` (CHECKPOINT_COMPRESS)."""
    os.makedirs(ckpt_dir, exist_ok=True)
    fname = f"ckpt_{tick:08d}.npz"
    arrays = {f"c{i}": np.asarray(leaf)
              for i, leaf in enumerate(carry_leaves)}
    arrays.update({f"e_{k}": np.asarray(v) for k, v in payload.items()})

    def _write_npz(tmp):
        with open(tmp, "wb") as fh:
            (np.savez_compressed if compress else np.savez)(fh, **arrays)

    _atomic_write(os.path.join(ckpt_dir, fname), _write_npz)
    shash = state_hash(carry_leaves)

    prev = load_manifest(ckpt_dir)
    history = []
    reshard_chain = None
    if prev is not None and all(
            prev.get(k) == base[k] for k in base):
        history = [h for h in prev.get("checkpoints", ())
                   if h["tick"] < tick]
        # Reshard provenance (elastic/reshard.py stamps it) must survive
        # every later boundary write — the manifest is rebuilt from
        # `base` each time, so carry the chain forward like the history.
        reshard_chain = prev.get("reshard")
    history.append({"tick": int(tick), "file": fname, "state_hash": shash})
    for stale in history[:-KEEP_CHECKPOINTS]:
        try:
            os.unlink(os.path.join(ckpt_dir, stale["file"]))
        except OSError:
            pass
    history = history[-KEEP_CHECKPOINTS:]
    manifest = dict(base)
    manifest.update({
        "tick": int(tick), "file": fname, "state_hash": shash,
        "checkpoints": history,
        "wrote_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    })
    if reshard_chain:
        manifest["reshard"] = reshard_chain
    def _write_manifest(tmp):
        with open(tmp, "w") as fh:
            json.dump(manifest, fh, indent=1)

    _atomic_write(_manifest_path(ckpt_dir), _write_manifest)


def _load_for_resume(ckpt_dir: str, base: dict, template_leaves: list):
    """→ (tick, carry_leaves, payload dict) from the latest valid
    checkpoint, or None when no checkpoint exists.  A manifest that exists
    but names a DIFFERENT run (config/seed/backend/length) raises — a
    silent fresh start would quietly compute something other than what
    the operator asked to resume."""
    manifest = load_manifest(ckpt_dir)
    if manifest is None:
        return None
    for k, want in base.items():
        if manifest.get(k) != want:
            raise ValueError(
                f"RESUME manifest mismatch in {ckpt_dir!r}: field {k!r} "
                f"was {manifest.get(k)!r}, this run wants {want!r} — "
                "point CHECKPOINT_DIR elsewhere or clear it")
    path = os.path.join(ckpt_dir, manifest["file"])
    try:
        npz = np.load(path)
    except OSError as e:
        raise ValueError(
            f"RESUME: checkpoint file {path!r} named by the manifest is "
            f"unreadable ({e})") from e
    with npz as data:
        leaves = []
        for i, tmpl in enumerate(template_leaves):
            key = f"c{i}"
            if key not in data:
                raise ValueError(
                    f"RESUME: checkpoint {path!r} is missing carry leaf "
                    f"{i} (truncated or from an incompatible code "
                    "version)")
            a = data[key]
            # Shape/dtype only — never fetch the template's VALUE (in a
            # multi-process run the global carry spans non-addressable
            # devices and materializing it here would be both a crash
            # and a pointless transfer).
            if a.shape != tuple(tmpl.shape) or a.dtype != tmpl.dtype:
                raise ValueError(
                    f"RESUME: carry leaf {i} shape/dtype mismatch "
                    f"({a.shape}/{a.dtype} on disk vs "
                    f"{tuple(tmpl.shape)}/{tmpl.dtype}) — checkpoint is "
                    "from a different config")
            leaves.append(a)
        payload = {k[len("e_"):]: data[k] for k in data.files
                   if k.startswith("e_")}
    got = state_hash(leaves)
    if got != manifest["state_hash"]:
        raise ValueError(
            f"RESUME: state hash mismatch for {path!r} (manifest "
            f"{manifest['state_hash'][:12]}…, file {got[:12]}…) — "
            "checkpoint is corrupt")
    return int(manifest["tick"]), leaves, payload


# --------------------------------------------------------------------------
# The chunked driver

def _crash_tick() -> Optional[int]:
    v = os.environ.get(CRASH_ENV)
    return int(v) if v else None


def _state_reporter(total: int) -> Optional[Callable[[int], None]]:
    """The fleet worker's progress beacon: a callable writing
    ``{tick, total, ts}`` to ``$DM_RUN_STATE_FILE`` (the shared
    observability/beacon.py format — atomic rename, so a reader never
    sees a torn file), or None when the env is unset.  Best-effort by
    design — a full disk must not kill the run over a progress report
    the checkpoints already imply."""
    path = os.environ.get(STATE_FILE_ENV)
    if not path:
        return None
    from distributed_membership_tpu.observability.beacon import (
        write_beacon)

    def report(tick: int) -> None:
        write_beacon(path, {"tick": int(tick), "total": int(total),
                            "ts": time.time()})
    return report


def read_run_state(path: str) -> Optional[dict]:
    """The beacon's current value, or None (absent/torn)."""
    from distributed_membership_tpu.observability.beacon import (
        read_beacon)
    return read_beacon(path)


class RunInterrupted(RuntimeError):
    """A graceful stop (SIGTERM/SIGINT, or a boundary hook's ``stop``)
    halted :func:`chunked_run` at a segment boundary.  By the time this
    raises, the boundary is fully durable: the background writer has
    been barriered (the manifest points at the stop tick), and the
    segment's telemetry/runlog records are flushed.  ``tick`` is the
    boundary the run stopped at — ``RESUME: 1`` continues from exactly
    there, bit-exactly."""

    def __init__(self, message: str, tick: int):
        super().__init__(message)
        self.tick = int(tick)


# One process-wide boundary hook (the service daemon runs one engine per
# process).  ``hook(carry, tick)`` is called with the HOST carry once
# before the first segment (with the start tick — the initial snapshot,
# including a resume's restored state) and again at every segment
# boundary after the checkpoint hand-off.  It may return None, or a dict
# steering the remaining segments:
#
#   ``segment_fn``    — replacement jitted segment runner (the daemon's
#                       event injection recompiles the step with the
#                       merged scenario program baked in)
#   ``extra_inputs``  — replacement scan-invariant input tuple (the
#                       merged ScenarioTensors ride here)
#   ``stop``          — truthy: stop before dispatching the next
#                       segment (raises :class:`RunInterrupted` after
#                       the writer barrier)
_BOUNDARY_HOOK: Optional[Callable] = None


class boundary_hook:
    """Context manager installing the process-wide boundary hook."""

    def __init__(self, fn: Callable):
        self.fn = fn

    def __enter__(self):
        global _BOUNDARY_HOOK
        self._prev = _BOUNDARY_HOOK
        _BOUNDARY_HOOK = self.fn
        return self

    def __exit__(self, *exc):
        global _BOUNDARY_HOOK
        _BOUNDARY_HOOK = self._prev
        return False


def chunked_run(params: Params, plan, seed: int, total: int, *,
                init_carry, segment_fn, collect_events: bool,
                compact_fn=None, event_type=None, finalize=None,
                telemetry_sink=None, extra_inputs=()):
    """Run the tick loop in ``CHECKPOINT_EVERY``-tick segments.

    ``init_carry()`` builds the fresh device carry; ``segment_fn(carry,
    ticks, keys, start_ticks, fail_mask, fail_time, drop_lo, drop_hi)``
    is the backend's jitted scan over one segment (at most two segment
    lengths compile: ``every`` and the final remainder).  Full-event runs
    pass ``compact_fn`` (per-segment host flush into
    :class:`CompactEvents`); aggregate runs pass ``event_type`` (the
    per-tick outputs are scalars, concatenated field-wise).
    ``finalize(carry, acc) -> (carry, acc)``, when given, runs once
    after the LAST segment (also on a resume that finds the run already
    complete) — the chunked home of run-total epilogues that ride the
    monolithic scan's tail on the unchunked path (tpu_hash's
    PROBE_IO approx_lag counter correction).

    ``extra_inputs`` is a tuple of additional scan-invariant inputs
    appended to every ``segment_fn`` call after the failure schedule —
    the scenario engine's tensor plan rides here
    (scenario/compile.ScenarioTensors).  Nothing scenario-shaped enters
    the carry or the snapshots: the plan is re-derived from the
    scenario file on resume, and the manifest pins the file's content
    digest so an edited schedule cannot silently resume.

    ``telemetry_sink(telem, t0)``, when given, marks the backend's
    per-tick outputs as the pair ``(events, TickTelemetry-of-[K]-series)``
    (TELEMETRY: scalars — observability/timeline.py): the telemetry half
    is split off after the per-segment host flush and handed to the sink
    with the segment's first tick, so timeline.jsonl grows at every
    boundary and a kill loses at most the in-flight segment's series
    (the resume re-runs and re-flushes it).

    When ``params.TELEMETRY_DIR`` is set, per-segment timing events
    (device-sync / flush / checkpoint-write-wait seconds) are appended to
    ``<TELEMETRY_DIR>/runlog.jsonl`` (observability/runlog.py) for ANY
    chunked backend, independent of the TELEMETRY knob.

    Checkpoint writes are double-buffered: the host ``np.savez`` of
    segment ``i`` runs on a background writer thread while segment
    ``i+1`` is dispatched to the device, with a completion barrier at
    the following boundary — so the measured snapshot overhead is the
    device→host pull plus whatever write time the next segment's
    compute fails to hide (BENCH_CHECKPOINT re-measures it).  Durability
    is unchanged one segment back: a hard kill can lose only the
    in-flight snapshot, whose predecessor manifest stays valid (the
    same guarantee a kill mid-``np.savez`` always had).

    Returns ``(final_carry, events)`` with ``events`` a
    :class:`CompactEvents` (full mode) or ``event_type`` of ``[T]``
    streams (aggregate mode) — bit-identical content to the monolithic
    scan's.
    """
    import jax

    from distributed_membership_tpu.runtime.failures import plan_tensors

    every = params.CHECKPOINT_EVERY
    if every <= 0:
        raise ValueError("chunked_run requires CHECKPOINT_EVERY > 0")
    if (compact_fn is None) == (event_type is None):
        raise ValueError("pass exactly one of compact_fn/event_type")
    ckpt_dir = params.CHECKPOINT_DIR or None
    compress = bool(params.CHECKPOINT_COMPRESS)
    from distributed_membership_tpu.observability.runlog import maybe_runlog
    runlog = maybe_runlog(params.TELEMETRY_DIR or None)

    (ticks, keys, start_ticks, fail_mask, fail_time,
     drop_lo, drop_hi) = plan_tensors(params, plan, seed, total)
    base = _manifest_base(params, seed, total, collect_events)

    template = init_carry()
    template_leaves, treedef = jax.tree_util.tree_flatten(template)

    start = 0
    carry = template
    n = params.EN_GPSZ
    if compact_fn is not None:
        acc = _empty_compact(n)
    else:
        acc = None          # becomes a tuple of [t] arrays lazily

    if params.RESUME and ckpt_dir:
        loaded = _load_for_resume(ckpt_dir, base, template_leaves)
        if loaded is not None:
            start, leaves, payload = loaded
            carry = jax.tree_util.tree_unflatten(treedef, leaves)
            if compact_fn is not None:
                acc = CompactEvents(
                    payload["joins"], payload["removes"],
                    payload["sent"], payload["recv"], start)
            elif start > 0:
                acc = tuple(payload[f"s{i}"] for i in range(4))

    # Background writer: one worker thread so snapshot writes serialize
    # (the manifest is read-modify-write) while overlapping the next
    # segment's device work; `pending` holds the single in-flight write.
    executor = None
    pending = None
    if ckpt_dir:
        from concurrent.futures import ThreadPoolExecutor
        executor = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="ckpt-writer")

    def _await_writer():
        nonlocal pending
        if pending is not None:
            fut, pending = pending, None
            fut.result()    # surface writer exceptions on the main thread

    crash_at = _crash_tick()
    report_state = _state_reporter(total)
    if report_state is not None:
        report_state(start)
    if runlog is not None:
        runlog.event("segments_start", backend=params.BACKEND,
                     total=int(total), every=int(every),
                     tick_start=int(start), resumed=bool(start > 0),
                     checkpoint_dir=ckpt_dir or "")

    def _apply_hook(tick):
        """Run the boundary hook on the host carry; rebind the segment
        runner/inputs it returns.  → True when it requests a stop."""
        nonlocal segment_fn, extra_inputs
        if _BOUNDARY_HOOK is None:
            return False
        upd = _BOUNDARY_HOOK(carry, int(tick))
        if not upd:
            return False
        if "segment_fn" in upd:
            segment_fn = upd["segment_fn"]
        if "extra_inputs" in upd:
            extra_inputs = tuple(upd["extra_inputs"])
        return bool(upd.get("stop"))

    # Graceful interrupt: SIGTERM/SIGINT no longer kill the process
    # wherever it happens to be (abandoning the in-flight double-
    # buffered snapshot write) — the handler only sets a flag, checked
    # at the next segment boundary, where the stop path barriers the
    # background writer and flushes runlog before raising
    # :class:`RunInterrupted`.  Signals can only be installed from the
    # main thread; elsewhere (the bench's timing threads, pytest
    # workers) the run keeps the process defaults.
    import signal as _signal
    import threading as _threading
    stop_signal: list = []
    orig_handlers = {}
    if _threading.current_thread() is _threading.main_thread():
        def _graceful(signum, frame):
            stop_signal.append(signum)
        for s in (_signal.SIGTERM, _signal.SIGINT):
            try:
                orig_handlers[s] = _signal.signal(s, _graceful)
            except (ValueError, OSError):   # pragma: no cover
                pass

    def _stop_at_boundary(tick, hook_stop):
        if not (stop_signal or hook_stop) or tick >= total:
            return
        _await_writer()     # boundary `tick` is durable before we raise
        if runlog is not None:
            runlog.event(
                "interrupted", tick=int(tick),
                signal=int(stop_signal[0]) if stop_signal else 0,
                durable_tick=int(manifest_tick(ckpt_dir) or 0))
        raise RunInterrupted(
            f"run stopped at segment boundary {tick} "
            f"({'signal ' + str(stop_signal[0]) if stop_signal else 'stop requested'}); "
            f"last durable checkpoint: {manifest_tick(ckpt_dir) or 'none'}",
            tick)

    try:
        # Initial hook call: the pre-run snapshot (a resume's restored
        # carry included), and the seam where a resumed daemon re-arms
        # a merged segment runner before any tick executes.
        _stop_at_boundary(start, _apply_hook(start))
        for a in range(start, total, every):
            if crash_at is not None and a >= crash_at:
                # Flush the in-flight snapshot first so the fault
                # injection leaves the deterministic on-disk state the
                # tests pin (a real kill could additionally lose that
                # one in-flight write — see the durability note above).
                _await_writer()
                raise RuntimeError(
                    f"injected crash at tick {a} ({CRASH_ENV}={crash_at}); "
                    f"last durable checkpoint: "
                    f"{manifest_tick(ckpt_dir) or 'none'}")
            b = min(a + every, total)
            t_seg = time.perf_counter()
            carry, ev = segment_fn(carry, ticks[a:b], keys[a:b],
                                   start_ticks, fail_mask, fail_time,
                                   drop_lo, drop_hi, *extra_inputs)
            # Per-segment flush: events leave the device NOW, so full-mode
            # device memory is O(every * N * M), and the carry lands on
            # host for the snapshot.  to_host (not np.asarray): in a
            # multi-process run the carry's node-sharded leaves are not
            # fully addressable — every process gathers the same GLOBAL
            # host value, so snapshots and log artifacts stay
            # byte-identical across processes and to the 1-process twin.
            from distributed_membership_tpu.runtime.distributed import (
                to_host)
            carry = to_host(carry)
            ev = to_host(ev)
            t_sync = time.perf_counter()
            if telemetry_sink is not None:
                ev, telem = ev
                telemetry_sink(telem, a)
            if compact_fn is not None:
                acc = concat_compact([acc, compact_fn(ev, a)])
                payload = {"joins": acc.joins, "removes": acc.removes,
                           "sent": acc.sent, "recv": acc.recv}
            else:
                seg = tuple(np.asarray(x) for x in ev)
                acc = (seg if acc is None else
                       tuple(np.concatenate([p, s])
                             for p, s in zip(acc, seg)))
                payload = {f"s{i}": acc[i] for i in range(4)}
            ckpt_wait_s = 0.0
            if ckpt_dir:
                # Barrier for the PREVIOUS write, then hand this one to
                # the writer; the next segment's dispatch overlaps it.
                # (Each iteration rebinds carry/acc to fresh host
                # arrays, so the submitted snapshot is never mutated.)
                t_wait = time.perf_counter()
                _await_writer()
                ckpt_wait_s = time.perf_counter() - t_wait
                pending = executor.submit(
                    _save_checkpoint, ckpt_dir, base, b,
                    jax.tree_util.tree_leaves(carry), payload, compress)
            if report_state is not None:
                report_state(b)
            if runlog is not None:
                # Per-boundary attribution: device_sync_s is dispatch +
                # device compute + the host pull; ckpt_wait_s is write
                # time the NEXT segment's compute failed to hide.
                runlog.event(
                    "segment", t0=int(a), t1=int(b),
                    device_sync_s=round(t_sync - t_seg, 4),
                    flush_s=round(
                        time.perf_counter() - t_sync - ckpt_wait_s, 4),
                    ckpt_wait_s=round(ckpt_wait_s, 4))
            # Boundary hook AFTER the checkpoint hand-off: the hook sees
            # exactly the state the manifest will point at, and a
            # runner/inputs swap it returns takes effect from the NEXT
            # segment (the injection contract — service/daemon.py).
            _stop_at_boundary(b, _apply_hook(b))
        _await_writer()
    finally:
        for s, h in orig_handlers.items():
            try:
                _signal.signal(s, h)
            except (ValueError, OSError):   # pragma: no cover
                pass
        if executor is not None:
            executor.shutdown(wait=True)
    if runlog is not None:
        runlog.event("segments_done", total=int(total),
                     tick_start=int(start))

    if finalize is not None and acc is not None and total > 0:
        carry, acc = finalize(carry, acc)
    if compact_fn is not None:
        events = acc
    elif acc is None:        # zero-length run (total == start == 0)
        events = event_type(*(np.zeros((0,), np.int32) for _ in range(4)))
    else:
        events = event_type(*acc)
    return carry, events
