"""Failure injection plans.

The reference injects failures inline in the driver (``Application::fail``,
Application.cpp:173-202): crash-stop of one random node (SINGLE_FAILURE) or of
``EN_GPSZ/2`` contiguous nodes at t=100, plus a message-drop window
``dropmsg=1`` for t in [50, 300) when DROP_MSG is set (consumed by the network
send path, EmulNet.cpp:90-94).  Failed nodes never recover — ``bFailed`` is
never reset and there is no LEAVE message (SURVEY.md §5).

Here the plan is computed up front from the seeded RNG so every backend —
including the jitted TPU step, which needs the schedule as tensors — injects
the *same* failures for the same seed.  An extension adds correlated rack
failures for scale scenarios (BASELINE.json config #4).
"""

from __future__ import annotations

import dataclasses
import random
from typing import List, Optional

from distributed_membership_tpu.config import Params


@dataclasses.dataclass
class FailurePlan:
    kind: str                    # 'single' | 'multi' | 'racks' | 'none'
    #                              | 'scenario' (general scenario path)
    fail_time: Optional[int]
    failed_indices: List[int]    # node indices (0-based) crashed at fail_time
    #                              (general scenarios: the PERMANENTLY
    #                              failed set, fail_time = earliest crash)
    drop_start: Optional[int]    # tick when dropmsg flips on (None if never)
    drop_stop: Optional[int]
    # Compiled general-path scenario (scenario/compile.ScenarioProgram),
    # None for legacy plans and legacy-shaped scenarios.  Threading it on
    # the plan lets the scenario subsystem ride every existing
    # (params, plan, seed) seam — finish_run, chunked_run, run_scan —
    # without new plumbing.
    scenario: Optional[object] = None


def draw_single(n: int, rng: random.Random) -> int:
    """Application.cpp:182: removed = rand() % EN_GPSZ."""
    return rng.randrange(n)


def draw_multi(n: int, rng: random.Random):
    """Application.cpp:189: removed = rand() % EN_GPSZ / 2 (C precedence:
    (rand() % N) / 2), then the N/2 contiguous nodes from there fail.
    Returns the [lo, hi) range."""
    start = rng.randrange(n) // 2
    return start, min(start + n // 2, n)


def draw_racks(params: Params, rng: random.Random) -> List[int]:
    """Correlated rack failures: RACK_FAILURES distinct racks of
    RACK_SIZE contiguous nodes (the scale-scenario extension)."""
    n = params.EN_GPSZ
    n_racks = max(n // params.RACK_SIZE, 1)
    racks = rng.sample(range(n_racks), min(params.RACK_FAILURES, n_racks))
    return sorted(
        i
        for r in racks
        for i in range(r * params.RACK_SIZE,
                       min((r + 1) * params.RACK_SIZE, n))
    )


def make_plan(params: Params, rng: random.Random) -> FailurePlan:
    n = params.EN_GPSZ
    drop_start = params.DROP_START if params.DROP_MSG else None
    drop_stop = params.DROP_STOP if params.DROP_MSG else None

    if params.RACK_SIZE > 0 and params.RACK_FAILURES > 0:
        return FailurePlan("racks", params.FAIL_TIME,
                           draw_racks(params, rng), drop_start, drop_stop)

    if params.SINGLE_FAILURE:
        return FailurePlan("single", params.FAIL_TIME,
                           [draw_single(n, rng)], drop_start, drop_stop)

    lo, hi = draw_multi(n, rng)
    return FailurePlan("multi", params.FAIL_TIME, list(range(lo, hi)),
                       drop_start, drop_stop)


def resolve_plan(params: Params, rng: random.Random) -> FailurePlan:
    """The failure schedule for a run: the legacy seeded draw, or — when
    ``SCENARIO:`` names a schedule file — the compiled scenario
    (scenario/compile.py).  Legacy-shaped scenarios lower to a plain
    FailurePlan (and may set the params drop-window keys), so every
    backend runs them through the unchanged legacy code; general
    scenarios attach ``plan.scenario`` for the tensor-plan path."""
    if params.SCENARIO:
        from distributed_membership_tpu.scenario.compile import (
            resolve_scenario_plan)
        return resolve_scenario_plan(params, rng)
    return make_plan(params, rng)


def make_run_key(params: Params, seed: int):
    """Root PRNG key under the configured implementation (PRNG_IMPL).

    The default threefry2x32 stays the legacy raw-uint32 PRNGKey — the
    implicit pin of every bit-exactness test; 'rbg'/'unsafe_rbg' return
    typed key arrays that flow through the same split/fold_in stream but
    draw via XLA's hardware RNG (cheap on the TPU VPU where threefry's
    dense u32 rounds are real per-tick compute)."""
    import jax

    if params.PRNG_IMPL == "threefry2x32":
        return jax.random.PRNGKey(seed)
    return jax.random.key(seed, impl=params.PRNG_IMPL)


def plan_tensors(params: Params, plan: FailurePlan, seed: int, total: int):
    """Convert a (params, plan, seed) triple into the tensor schedule every
    jitted backend consumes: ``(ticks, keys, start_ticks, fail_mask,
    fail_time, drop_lo, drop_hi)``.

    Shared by the tpu / tpu_sharded / tpu_sparse run paths so the
    drop-window sentinel (total + 1 = never) and the per-tick key derivation
    (fold_in of the run seed) cannot diverge between backends.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    n = params.EN_GPSZ
    start_ticks = jnp.asarray(
        [params.start_tick(i) for i in range(n)], jnp.int32)
    fail_mask = np.zeros((n,), bool)
    fail_time = -1
    if plan.fail_time is not None:
        fail_mask[plan.failed_indices] = True
        fail_time = plan.fail_time
    drop_lo = plan.drop_start if plan.drop_start is not None else total + 1
    drop_hi = plan.drop_stop if plan.drop_stop is not None else total + 1

    ticks = jnp.arange(total, dtype=jnp.int32)
    root = make_run_key(params, seed)
    keys = jax.vmap(lambda t: jax.random.fold_in(root, t))(ticks)
    return (ticks, keys, start_ticks, jnp.asarray(fail_mask),
            jnp.asarray(fail_time, jnp.int32), jnp.asarray(drop_lo, jnp.int32),
            jnp.asarray(drop_hi, jnp.int32))


def log_failures(plan: FailurePlan, log, t: int) -> None:
    """Emit the 'Node failed at time...' lines exactly as Application.cpp:184,192."""
    from distributed_membership_tpu.addressing import index_to_id
    if plan.fail_time != t:
        return
    if plan.kind == "single":
        log.node_failed_single(index_to_id(plan.failed_indices[0]), t)
    else:
        for i in plan.failed_indices:
            log.node_failed_multi(index_to_id(i), t)
