"""Multi-process mesh runtime: one global mesh spanning K processes.

The sharded backend's mesh (parallel/mesh.py) is built over
``jax.devices()`` — in a single process that is the local chip set, but
after :func:`jax.distributed.initialize` it is the GLOBAL device list
across every process of the run, and the very same ``shard_map`` programs
run unchanged with XLA moving the cross-process legs of each collective
over DCN (or, on the CPU CI twin, gloo).  This module is the glue that
makes that path reachable without perturbing single-process runs at all:

* :func:`maybe_initialize` — idempotent ``jax.distributed.initialize``
  driven entirely by ``DM_DIST_*`` environment variables, so the SAME CLI
  invocation works single-process (vars unset: no-op) and as one rank of
  a pod run (vars set by the operator or by
  ``scripts/multiproc_launch.py``).  Must run before the first jax
  backend init in the process.
* :func:`to_host` — the multi-process-safe replacement for
  ``jax.tree.map(np.asarray, ...)``: a jax.Array whose shards live on
  other processes is not fully addressable and ``np.asarray`` raises, so
  replicated leaves are read off the local shard and sharded leaves are
  process-allgathered (every process gets the full global value, which
  keeps every process's checkpoints and log artifacts byte-identical —
  the property tests/test_exchange.py pins against the single-process
  twin).
* :func:`device_put_global` — the reverse seam: re-shard a host-global
  carry onto the mesh for the next scan segment
  (``jax.make_array_from_callback``; each process materializes only the
  shards it owns).

Environment contract (all unset = single-process, no-op):

* ``DM_DIST_PROCS``     — total process count K (> 1 arms the init)
* ``DM_DIST_PROC_ID``   — this process's rank in [0, K)
* ``DM_DIST_COORD``     — coordinator address, e.g. ``localhost:9911``
* ``DM_DIST_CPU_COLL``  — CPU collectives implementation (default
  ``gloo``, the cross-process CPU backend jax ships)
"""

from __future__ import annotations

import os

PROCS_ENV = "DM_DIST_PROCS"
PROC_ID_ENV = "DM_DIST_PROC_ID"
COORD_ENV = "DM_DIST_COORD"
CPU_COLL_ENV = "DM_DIST_CPU_COLL"

_INITIALIZED = False


def maybe_initialize() -> tuple:
    """Initialize jax.distributed from ``DM_DIST_*`` if requested.

    Returns ``(process_index, process_count)``.  Idempotent; a no-op
    (returning ``(0, 1)``-shaped info from the env alone, without
    touching jax) when ``DM_DIST_PROCS`` is unset or <= 1.  Call before
    the first jax backend init (platform resolution included — the
    coordinator handshake must precede device enumeration)."""
    global _INITIALIZED
    procs = int(os.environ.get(PROCS_ENV, "1") or 1)
    if procs <= 1:
        return 0, 1
    pid = int(os.environ.get(PROC_ID_ENV, "0") or 0)
    if _INITIALIZED:
        return pid, procs
    coord = os.environ.get(COORD_ENV)
    if not coord:
        raise ValueError(
            f"{PROCS_ENV}={procs} requires {COORD_ENV} "
            "(coordinator host:port shared by every process)")
    import jax
    # The CPU CI twin: cross-process collectives on the CPU backend need
    # an explicit implementation; gloo is the one jax ships.  Harmless
    # on TPU (the knob only affects the cpu backend).
    jax.config.update("jax_cpu_collectives_implementation",
                      os.environ.get(CPU_COLL_ENV, "gloo"))
    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=procs, process_id=pid)
    _INITIALIZED = True
    return pid, procs


def process_count() -> int:
    """Global process count (1 until/without distributed init)."""
    import jax
    return int(jax.process_count())


def process_index() -> int:
    import jax
    return int(jax.process_index())


def _leaf_to_host(x):
    import jax
    import numpy as np
    if not isinstance(x, jax.Array) or x.is_fully_addressable:
        return np.asarray(x)
    if x.is_fully_replicated:
        # Every shard holds the full value; read the first local one.
        return np.asarray(x.addressable_data(0))
    # Node-sharded leaf with remote shards: gather the global value onto
    # every process (a collective — all processes must reach this
    # together, which they do: the chunked driver's per-segment flush is
    # the only caller and every process runs the same segment schedule).
    from jax.experimental import multihost_utils
    return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def to_host(tree):
    """``jax.tree.map(np.asarray, tree)``, multi-process-safe."""
    import jax
    return jax.tree.map(_leaf_to_host, tree)


def device_put_global(tree, mesh, spec_tree):
    """Re-shard a host-global pytree onto ``mesh`` per ``spec_tree``.

    Single-process this is a no-op (jit re-shards host arrays against
    the in_specs on its own); multi-process, host numpy cannot express a
    global array, so each leaf is rebuilt with
    ``jax.make_array_from_callback`` — the callback hands XLA exactly
    the shard slices this process's devices own."""
    import jax
    import numpy as np
    if process_count() <= 1:
        return tree
    from jax.sharding import NamedSharding

    def _put(a, spec):
        if isinstance(a, jax.Array):
            # First segment: the init runner's output is already the
            # global device carry.
            return a
        a = np.asarray(a)
        sh = NamedSharding(mesh, spec)
        return jax.make_array_from_callback(a.shape, sh,
                                            lambda idx, _a=a: _a[idx])
    return jax.tree.map(_put, tree, spec_tree)
