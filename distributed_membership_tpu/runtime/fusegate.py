"""Auto-enable gate for the Pallas/folded fast paths (VERDICT r3 item 2).

The FUSED_RECEIVE / FUSED_GOSSIP / FUSED_PROBE / FOLDED conf keys
default to ``-1`` (auto).  Auto resolves ON only when every link in the evidence chain
holds; otherwise it quietly stays off (auto never raises — explicit
``1`` keeps today's loud structural errors):

1. this process resolved its platform to a real TPU
   (``DM_RESOLVED_PLATFORM`` — set by runtime.platform.resolve_platform,
   which the CLI, bench, and profilers all call first);
2. the config structurally supports the path (same predicates
   tpu_hash.make_config enforces for explicit opt-in);
3. the REAL chip has a banked bit-exactness verdict for the exact
   kernel family: ``scripts/tpu_correctness.py`` runs the full scan
   under each variant on hardware and bit-compares final states; the
   ladder daemon banks its record into ``artifacts/TPU_PROFILE.json``.
   Interpret-mode equality on CPU does NOT clear a family — round 4
   opened with the gossip kernels failing to even lower on real Mosaic
   after a fully green CPU suite.

The family keys mirror tpu_correctness.py's ``mismatched_elements``:
``fused_receive``, ``fused_gossip``, ``fused_both``,
``fused_gossip_drops`` (the masks-as-inputs kernels on lossy/flaky
configs), ``fused_probe`` (the fused probe/agg traversal),
``folded_s{S}``, ``folded_fused_s{S}``,
``folded_fused_probe_s{S}``, ``mega_t{T}`` (the T-tick megakernel scan
with the shrunk boundary carry, one family PER BLOCK SIZE — a chip that
proved T=8 has proved nothing about T=32; tpu_hash.MEGA_AUTO_TICKS
lists the block sizes the correctness arms bank), and their
``sharded_`` twins.
A missing record, a non-tpu record, or a family
absent from the record (e.g. a fold factor the correctness N could not
fold) all read as NOT cleared — fail closed.
"""

from __future__ import annotations

import json
import os

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
PROFILE_ENV = "DM_TPU_PROFILE"          # test override
DEFAULT_PROFILE = os.path.join(_ROOT, "artifacts", "TPU_PROFILE.json")


def on_tpu() -> bool:
    """Has this process resolved to a real TPU?  Cheap: reads the cache
    env var only — never probes (make_config runs on every conf load)."""
    return os.environ.get("DM_RESOLVED_PLATFORM") == "tpu"


def banked_correctness() -> dict | None:
    """The banked real-TPU correctness verdict, or None.

    The ladder banks the correctness families in up to three per-arm
    records (single-chip / folded / sharded — scripts/tpu_ladder.py
    CORRECTNESS_ARMS); they are merged here family-keyed, later records
    overriding earlier ones, so a re-run that fixes one family updates
    just that family's verdict."""
    path = os.environ.get(PROFILE_ENV, DEFAULT_PROFILE)
    try:
        with open(path) as fh:
            rows = json.load(fh)
    except (OSError, json.JSONDecodeError):
        return None
    mism: dict = {}
    found = False
    for r in rows:
        if (r.get("check") != "fused_vs_jnp_same_platform"
                or r.get("platform") != "tpu"):
            continue
        fams = r.get("mismatched_elements")
        if not isinstance(fams, dict):
            continue          # detail-free records prove nothing
        found = True
        mism.update(fams)
    if not found:
        return None
    return {"check": "fused_vs_jnp_same_platform", "platform": "tpu",
            "ok": not any(any(v.values()) if isinstance(v, dict) else v
                          for v in mism.values()),
            "mismatched_elements": mism}


def families_clean(rec: dict | None, *families: str) -> bool:
    """True iff ``rec`` (a banked real-TPU correctness record) covers
    EVERY named family with zero mismatched elements.  A record without
    per-family detail clears nothing — a bare ``ok: true`` cannot prove
    a family it never names (fail closed)."""
    if rec is None:
        return False
    mism = rec.get("mismatched_elements")
    if not isinstance(mism, dict):
        return False
    for fam in families:
        if fam not in mism:          # family not checked: fail closed
            return False
        if any(mism[fam].values() if isinstance(mism[fam], dict)
               else [mism[fam]]):
            return False
    return True


def hw_cleared(*families: str) -> bool:
    """Convenience single-call form of :func:`families_clean` (re-reads
    the profile; batch callers should load once via banked_correctness)."""
    return families_clean(banked_correctness(), *families)
