"""Application driver: conf in, dbg.log / stats.log / msgcount.log out.

The rebuild's equivalent of the reference driver (Application.cpp:27-114):
parse the conf, dispatch to the backend selected by ``BACKEND:`` (the
extension point BASELINE.json prescribes), then write the three output
artifacts the reference produces — dbg.log + stats.log (Log.cpp) and
msgcount.log (EmulNet::ENcleanup, EmulNet.cpp:184-218).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from distributed_membership_tpu.backends import RunResult, get_backend
from distributed_membership_tpu.config import Params
from distributed_membership_tpu.eventlog import EventLog
from distributed_membership_tpu.grader import SCENARIO_GRADERS
from distributed_membership_tpu.observability.metrics import write_msgcount


def apply_overrides(params: Params, backend: str | None = None,
                    checkpoint_every: int | None = None,
                    checkpoint_dir: str | None = None,
                    resume: bool | None = None,
                    telemetry: str | None = None,
                    telemetry_dir: str | None = None,
                    scenario: str | None = None,
                    mesh_shape: str | None = None) -> Params:
    """Merge CLI overrides into an un-validated Params (shared by
    ``run_conf`` and the service daemon's ``serve_conf``)."""
    if backend is not None:
        params.BACKEND = backend
    # Crash-recovery knobs (runtime/checkpoint.py): CLI overrides win over
    # the conf's CHECKPOINT_* / RESUME keys so an operator can resume a
    # run whose conf predates the checkpoint keys.
    if checkpoint_every is not None:
        params.CHECKPOINT_EVERY = checkpoint_every
    if checkpoint_dir is not None:
        params.CHECKPOINT_DIR = checkpoint_dir
    if resume is not None:
        params.RESUME = int(resume)
    # Flight-recorder knobs (observability/timeline.py, runlog.py): CLI
    # overrides win, as the checkpoint keys above.
    if telemetry is not None:
        params.TELEMETRY = telemetry
    if telemetry_dir is not None:
        params.TELEMETRY_DIR = telemetry_dir
    # Scenario engine (scenario/ package): --scenario wins over the
    # conf's SCENARIO key, same precedence as every knob above.
    if scenario is not None:
        params.SCENARIO = scenario
    # Elastic mesh (elastic/reshard.py): --mesh-shape retargets a
    # sharded run's device mesh.  MESH_SHAPE is part of the checkpoint
    # identity, so resuming onto a new shape requires an explicit
    # reshard first — this override is how the resharded run (or the
    # multiproc launcher's children) states the new geometry.
    if mesh_shape is not None:
        params.MESH_SHAPE = mesh_shape
    return params


def run_conf(conf_path: str, backend: str | None = None,
             seed: int | None = None, out_dir: str = ".",
             checkpoint_every: int | None = None,
             checkpoint_dir: str | None = None,
             resume: bool | None = None,
             telemetry: str | None = None,
             telemetry_dir: str | None = None,
             scenario: str | None = None,
             mesh_shape: str | None = None) -> RunResult:
    # Validation runs AFTER the CLI overrides merge: cross-field rules
    # (e.g. RNG_MODE hoisted requiring CHECKPOINT_EVERY > 0) must see the
    # effective config, not the conf file alone.
    params = Params.from_file(conf_path, validate=False)
    apply_overrides(params, backend=backend,
                    checkpoint_every=checkpoint_every,
                    checkpoint_dir=checkpoint_dir, resume=resume,
                    telemetry=telemetry, telemetry_dir=telemetry_dir,
                    scenario=scenario, mesh_shape=mesh_shape)
    params.validate()
    log = EventLog(out_dir)
    result = None
    if params.RESUME and params.CHECKPOINT_DIR:
        # A served run may have journaled live injections beside its
        # checkpoints; a headless resume must replay them or the
        # resumed trajectory silently diverges from the acknowledged
        # one (service/daemon.py, returns None when nothing applies).
        from distributed_membership_tpu.service.daemon import (
            resume_journal_run)
        result = resume_journal_run(params, log, seed)
    if result is None:
        result = get_backend(params.BACKEND)(params, log, seed=seed)
    result.log.flush(out_dir)
    if not result.extra.get("aggregate"):
        # Aggregate (scale) runs carry per-node totals only; the [N, T]
        # msgcount matrix is exactly what cannot exist at 1M nodes.
        write_msgcount(result, out_dir)
    return result


SCENARIOS = ("singlefailure", "multifailure", "msgdropsinglefailure")
SCENARIO_TITLES = ("Single Failure Scenario", "Multi Failure Scenario",
                   "Message Drop Single Failure Scenario")


def default_testcases_dir() -> str:
    return os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))), "testcases")


def resolve_platform_if_needed(backend, testdir: str, pin=None):
    """Pin/probe the jax platform only when a jax backend will run —
    the pure-host backends must not pay the accelerator probe.
    Returns the resolved platform name or None when jax is unneeded."""
    if backend is not None:
        needs_jax = _backend_needs_jax(backend)
    else:
        needs_jax = any(
            _backend_needs_jax(_conf_backend(
                os.path.join(testdir, f"{s}.conf")))
            for s in SCENARIOS)
    if not needs_jax:
        return None
    from distributed_membership_tpu.runtime.platform import resolve_platform
    return resolve_platform(pin=pin)


def run_scenario_graded(scenario: str, testdir: str, backend, seed,
                        out_dir: str):
    """Run one grading scenario and grade its dbg.log; the shared core of
    grade_all and scripts/package_results.py."""
    result = run_conf(os.path.join(testdir, f"{scenario}.conf"),
                      backend=backend, seed=seed, out_dir=out_dir)
    grade = SCENARIO_GRADERS[scenario](result.log.dbg_text(),
                                       result.params.EN_GPSZ)
    return result, grade


def grade_all(args) -> int:
    """Run the three grading scenarios and print the /90 total — the
    rebuild's equivalent of Grader_verbose.sh's build-run-score loop
    (Grader_verbose.sh:27-196; 'make' is jit compilation here)."""
    import tempfile

    testdir = args.testcases
    if testdir is None:
        testdir = default_testcases_dir()
    resolve_platform_if_needed(args.backend, testdir, pin=args.platform)

    total = 0
    print("============================================")
    print("Grading Started")
    print("============================================")
    for scenario, title in zip(SCENARIOS, SCENARIO_TITLES):
        print(title)
        print("============================")
        with tempfile.TemporaryDirectory() as tmp:
            _, g = run_scenario_graded(scenario, testdir, args.backend,
                                       args.seed, tmp)
        print(f"Checking Join.................."
              f"{g.join_pts}/{g.join_max}")
        print(f"Checking Completeness.........."
              f"{g.completeness_pts}/{g.completeness_max}")
        if g.accuracy_max:
            print(f"Checking Accuracy.............."
                  f"{g.accuracy_pts}/{g.accuracy_max}")
        print("============================================")
        total += g.points
    print(f"Final grade {total}")
    return 0 if total == 90 else 1


def _backend_needs_jax(backend: str) -> bool:
    """True when the backend will touch jax (everything except the
    pure-host emul paths, whose runs must not pay a probe subprocess)."""
    return backend not in ("emul", "emul_native")


def _conf_backend(conf_path: str) -> str:
    try:
        return Params.from_file(conf_path).BACKEND
    except Exception:
        return "tpu"   # unknown conf: assume jax so the probe still runs


def params_backend_needs_jax(args) -> bool:
    backend = args.backend
    if backend is None:
        backend = _conf_backend(args.conf)
    return _backend_needs_jax(backend)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m distributed_membership_tpu",
        description="TPU-native gossip membership simulator "
                    "(drop-in for the reference ./Application <conf>)")
    ap.add_argument("conf", nargs="?", default=None,
                    help="testcase .conf file (legacy 4-key format + "
                         "extensions); omit with --grade-all")
    ap.add_argument("--backend", default=None,
                    help="override BACKEND from the conf (emul|emul_native|"
                         "tpu|tpu_sharded|tpu_sparse|tpu_hash|"
                         "tpu_hash_sharded)")
    ap.add_argument("--grade-all", action="store_true",
                    help="run all three grading scenarios and print the /90 "
                         "total (Grader_verbose.sh's build-run-score loop)")
    ap.add_argument("--testcases", default=None,
                    help="directory holding the three scenario .conf files "
                         "(default: ./testcases next to the repo root)")
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--out-dir", default=".")
    ap.add_argument("--checkpoint-every", type=int, default=None,
                    metavar="TICKS",
                    help="run the tick loop in TICKS-sized scan segments, "
                         "snapshotting the full carry between segments "
                         "(CHECKPOINT_EVERY conf key; "
                         "runtime/checkpoint.py)")
    ap.add_argument("--checkpoint-dir", default=None,
                    help="directory for checkpoint snapshots + manifest "
                         "(CHECKPOINT_DIR conf key)")
    ap.add_argument("--resume", action="store_true", default=None,
                    help="resume bit-exactly from --checkpoint-dir's "
                         "latest valid checkpoint (validated against this "
                         "config/seed; starts fresh when none exists)")
    ap.add_argument("--telemetry", default=None,
                    choices=["off", "scalars"],
                    help="TELEMETRY conf key: 'scalars' arms the flight "
                         "recorder's in-scan per-tick series on the ring "
                         "backends (observability/timeline.py)")
    ap.add_argument("--telemetry-dir", default=None,
                    help="TELEMETRY_DIR conf key: directory for "
                         "timeline.jsonl / runlog.jsonl / summary.json "
                         "(render with scripts/run_report.py)")
    ap.add_argument("--mesh-shape", default=None, metavar="SHAPE",
                    help="MESH_SHAPE conf key ('D', 'OxI' or 'SxOxI'; "
                         "tpu_hash_sharded only).  Resuming onto a "
                         "shape different from the checkpoint's "
                         "requires an explicit reshard first "
                         "(python -m distributed_membership_tpu."
                         "elastic.reshard)")
    ap.add_argument("--scenario", default=None, metavar="FILE",
                    help="SCENARIO conf key: a declarative chaos-schedule "
                         "JSON (crash/restart/leave/partition/link_flake/"
                         "drop_window events — scenario/ package; examples "
                         "in scenarios/ at the repo root)")
    ap.add_argument("--serve", action="store_true",
                    help="run as the membership control-plane daemon "
                         "(service/ package): serve liveness queries and "
                         "live fault injection over HTTP between scan "
                         "segments; requires --checkpoint-every (or the "
                         "conf's CHECKPOINT_EVERY) and a ring-family "
                         "backend")
    ap.add_argument("--port", type=int, default=None, metavar="P",
                    help="SERVICE_PORT conf key: port for --serve "
                         "(0 = ephemeral, written to "
                         "<out-dir>/service.json; default ephemeral); "
                         "with --fleet it is the FLEET_PORT instead")
    ap.add_argument("--fleet", action="store_true",
                    help="run the fleet controller (fleet/ package): a "
                         "control plane scheduling many runs submitted "
                         "over HTTP (POST /v1/runs) into subprocess "
                         "workers, proxying each run's --serve surface "
                         "under /v1/runs/<id>/.  conf is optional and "
                         "read for FLEET_* keys only")
    ap.add_argument("--platform", default=None, choices=["cpu", "tpu", "axon"],
                    help="pin the jax platform (e.g. cpu for hermetic runs on "
                         "a virtual device mesh)")
    ap.add_argument("--grade", metavar="SCENARIO", default=None,
                    choices=sorted(SCENARIO_GRADERS),
                    help="self-grade the run with the ported grading oracle")
    ap.add_argument("--json", action="store_true", help="print a JSON summary line")
    args = ap.parse_args(argv)

    if args.grade_all:
        return grade_all(args)
    if args.serve and args.fleet:
        ap.error("--serve and --fleet are mutually exclusive (submit "
                 "the run to the fleet instead)")
    if args.conf is None and not args.fleet:
        ap.error("conf is required unless --grade-all or --fleet is "
                 "given")
    if args.port is not None and not (args.serve or args.fleet):
        ap.error("--port requires --serve or --fleet")

    if args.fleet:
        # The controller itself never touches jax — workers are full
        # CLI subprocesses that resolve their own platform.
        from distributed_membership_tpu.fleet.daemon import fleet_conf
        return fleet_conf(args.conf, port=args.port,
                          out_dir=args.out_dir)

    if params_backend_needs_jax(args):
        # An unreachable TPU relay makes the first jax backend init hang
        # forever (not fail); resolve the platform up front with a
        # subprocess probe + cpu fallback (runtime/platform.py).
        from distributed_membership_tpu.runtime.platform import (
            resolve_platform)
        resolve_platform(pin=args.platform)
        # Multi-process mesh runtime: when DM_DIST_* is set (e.g. by
        # scripts/multiproc_launch.py) join the coordinator BEFORE the
        # first backend init so jax.devices() is the global pod device
        # list and every mesh below spans all processes.  No-op when
        # unset (runtime/distributed.py).
        from distributed_membership_tpu.runtime.distributed import (
            maybe_initialize)
        maybe_initialize()

    if args.serve:
        # Control-plane posture (service/ package): the daemon owns the
        # run end-to-end — artifacts, snapshots, the HTTP lifecycle —
        # and exits 0 on a graceful stop.
        from distributed_membership_tpu.service.daemon import serve_conf
        return serve_conf(
            args.conf, port=args.port, out_dir=args.out_dir,
            seed=args.seed, backend=args.backend,
            checkpoint_every=args.checkpoint_every,
            checkpoint_dir=args.checkpoint_dir, resume=args.resume,
            telemetry=args.telemetry, telemetry_dir=args.telemetry_dir,
            scenario=args.scenario)

    from distributed_membership_tpu.runtime.checkpoint import RunInterrupted
    try:
        result = run_conf(args.conf, backend=args.backend, seed=args.seed,
                          out_dir=args.out_dir,
                          checkpoint_every=args.checkpoint_every,
                          checkpoint_dir=args.checkpoint_dir,
                          resume=args.resume,
                          telemetry=args.telemetry,
                          telemetry_dir=args.telemetry_dir,
                          scenario=args.scenario,
                          mesh_shape=args.mesh_shape)
    except RunInterrupted as e:
        # Graceful SIGTERM/SIGINT: the chunked driver already barriered
        # the checkpoint writer and flushed timeline/runlog at the stop
        # boundary — report where to resume from and exit clean.
        print(f"interrupted: {e} — rerun with --resume to continue")
        return 0

    summary = {
        "backend": result.params.BACKEND,
        "n_nodes": result.params.EN_GPSZ,
        "ticks": result.params.TOTAL_TIME,
        "wall_seconds": round(result.wall_seconds, 4),
        "node_ticks_per_sec": round(
            result.params.EN_GPSZ * result.params.TOTAL_TIME
            / max(result.wall_seconds, 1e-9), 1),
        "msgs_sent": int(result.sent.sum()),
        "failed_indices": result.failed_indices,
    }
    if "detection_summary" in result.extra:
        summary["detection"] = result.extra["detection_summary"]
    if "scenario_report" in result.extra:
        summary["scenario"] = result.extra["scenario_report"]
    if result.extra.get("timeline_path"):
        summary["timeline_path"] = result.extra["timeline_path"]
    if args.grade:
        g = SCENARIO_GRADERS[args.grade](result.log.dbg_text(),
                                         result.params.EN_GPSZ)
        summary["grade"] = {"points": g.points, "max": g.max_points,
                            "join": g.join_ok,
                            "completeness": g.completeness_pts,
                            "accuracy": g.accuracy_pts}
    if args.json:
        print(json.dumps(summary))
    else:
        for k, v in summary.items():
            print(f"{k}: {v}")
    if args.grade and not g.passed:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
