"""Robust JAX platform resolution.

This image boots every interpreter with an ``axon`` PJRT plugin
(sitecustomize on PYTHONPATH) that forces ``jax_platforms=axon,cpu`` and
dials a TPU relay during backend initialization.  When the relay is down,
the first ``jax.devices()`` — or any implicit backend init, e.g. the first
``jnp`` op — HANGS indefinitely rather than failing (observed both rounds).

Nothing in-process can time that out safely, so the probe runs in a
throwaway subprocess with a wall-clock timeout; on failure the caller's
process pins ``jax_platforms=cpu`` *via jax.config* (the env var alone is
overridden by the plugin's registration) before its first backend init.

Call :func:`resolve_platform` before any jax computation.  The result is
cached in ``DM_RESOLVED_PLATFORM`` so child processes and repeated calls
skip the probe.
"""

from __future__ import annotations

import os
import subprocess
import sys

_PROBE = "import jax; print(jax.devices()[0].platform)"
_CACHE_VAR = "DM_RESOLVED_PLATFORM"


def probe_platform(timeout: float = 90.0, retries: int = 2) -> str | None:
    """What platform does a fresh interpreter's default jax init land on?

    Returns the platform name, or None if init fails or hangs past
    ``timeout`` (``retries`` attempts).
    """
    for _ in range(retries):
        try:
            r = subprocess.run(
                [sys.executable, "-c", _PROBE],
                timeout=timeout, capture_output=True, text=True)
        except subprocess.TimeoutExpired:
            continue
        if r.returncode == 0 and r.stdout.strip():
            return r.stdout.strip().splitlines()[-1]
    return None


def resolve_platform(timeout: float = 90.0, retries: int = 2,
                     pin: str | None = None) -> str:
    """Ensure this process's jax will initialize, and say on what.

    ``pin`` skips probing and pins that platform outright.  Otherwise:
    probe in a subprocess; if the default init is unusable, pin cpu here
    and return 'cpu'.  Must run before the first jax backend init in this
    process.
    """
    import jax

    if pin:
        jax.config.update("jax_platforms", pin)
        os.environ[_CACHE_VAR] = pin
        return pin

    cached = os.environ.get(_CACHE_VAR)
    if cached:
        if cached == "cpu":
            jax.config.update("jax_platforms", "cpu")
        return cached

    platform = probe_platform(timeout=timeout, retries=retries)
    if platform is None:
        print("warning: default jax backend init failed or hung; "
              "falling back to cpu", file=sys.stderr)
        jax.config.update("jax_platforms", "cpu")
        platform = "cpu"
    os.environ[_CACHE_VAR] = platform
    return platform
