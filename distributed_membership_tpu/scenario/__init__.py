"""Scenario engine: declarative chaos schedules compiled to in-scan
tensor plans.

The reference injects exactly one failure shape — a crash at ``FAIL_TIME``
plus a single global drop window (runtime/failures.py, Application.cpp:
173-202).  This package generalizes that into a declarative scenario
subsystem:

  * :mod:`schema` — a small JSON schema of timed events (``crash``,
    ``restart``, ``leave``, ``partition``, ``link_flake``,
    ``drop_window``) with range/list/draw node selectors;
  * :mod:`compile` — lowers a scenario into tick-indexed tensor plans
    (:class:`~compile.ScenarioTensors`) that ride the jitted ring steps
    of all four ring twins (tpu_hash natural/folded, tpu_hash_sharded
    natural/folded) as scan inputs — composing with CHECKPOINT_EVERY /
    RESUME bit-exactly — plus a host twin (:class:`~compile.ScenarioHost`)
    for the reference ``emul`` backend.  Scenarios expressible in legacy
    terms (crashes at one time + at most one global drop window) lower
    straight to a :class:`~runtime.failures.FailurePlan`, so they
    reproduce ``make_plan`` bit-exactly on EVERY backend;
  * :mod:`oracle` — the scenario oracle: false-positive removals during
    partitions, re-convergence tick after heal, rejoin completion per
    restart event — rendered through the run_report pipeline.

Select with the ``SCENARIO:`` conf key / ``--scenario`` CLI flag; example
schedules live in ``scenarios/`` at the repo root (README "Scenarios").
"""

from distributed_membership_tpu.scenario.schema import (  # noqa: F401
    Scenario, load_scenario, validate_scenario)
from distributed_membership_tpu.scenario.compile import (  # noqa: F401
    ScenarioHost, ScenarioProgram, ScenarioStatic, ScenarioTensors,
    compile_scenario, resolve_scenario_plan)
