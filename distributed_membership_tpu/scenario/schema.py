"""Scenario schema: declarative timed chaos events.

A scenario file is JSON::

    {
      "name": "partition_heal",
      "events": [
        {"kind": "partition", "start": 60, "stop": 120,
         "groups": [[0, 1024], [1024, 2048]]},
        {"kind": "crash",   "time": 30, "range": [4, 8]},
        {"kind": "restart", "time": 90, "range": [4, 8]},
        {"kind": "leave",   "time": 50, "nodes": [17]},
        {"kind": "link_flake", "start": 100, "stop": 200,
         "src": [0, 1024], "dst": [1024, 2048], "drop_prob": 0.2},
        {"kind": "drop_window", "start": 50, "stop": 300,
         "drop_prob": 0.1}
      ]
    }

Event kinds:

  * ``crash`` / ``leave`` — the selected nodes go down at the END of tick
    ``time`` (they act through it, exactly like the reference's
    ``Application::fail`` timing).  ``leave`` is mechanically identical in
    the simulator (the reference protocol has no LEAVE message); the
    oracle classifies its removals as expected departures, not failures.
  * ``restart`` — the selected nodes come back at the end of ``time``
    with a FRESH INCARNATION: state wiped to a self-only view, heartbeat
    bumped to ``2*(time+1)`` so it strictly dominates any stale gossip of
    the pre-crash incarnation (heartbeats advance +2 per live tick, so
    this is the value an uninterrupted peer would be near).  The rejoin
    is warm — neighbors re-admit the id through normal gossip; the
    introducer handshake is not re-run (it does not exist in the
    JOIN_MODE=warm scale regime the ring twins target).
  * ``partition`` — for ``start < t <= stop`` (the legacy drop-window
    convention), messages crossing group boundaries are dropped
    deterministically.  ``groups`` must be disjoint contiguous index
    ranges in ascending order tiling ``[0, N)``; the compiler lowers them
    to boundary cuts so the send-path predicate is the elementwise
    ``group[src] != group[dst]`` — no per-message gather.  At most one
    partition window may be active at any tick.
  * ``link_flake`` — for ``start < t <= stop``, messages from
    ``src`` range to ``dst`` range (directed) take an EXTRA drop
    probability ``drop_prob``; it combines with any active global window
    as independent loss (``p + q - p*q``) on the same per-message coin.
  * ``drop_window`` — a global Bernoulli drop window, the generalization
    of the legacy DROP_MSG/[DROP_START, DROP_STOP) injection; multiple
    windows may be given (the max of the active probabilities applies).
  * ``one_way_flake`` — asymmetric gray failure: messages from ``src``
    range to ``dst`` range are dropped with ``drop_prob`` (default 1.0 —
    a hard one-way blackhole) while the reverse direction flows
    untouched.  Sugar over ``link_flake`` (which is already directed):
    it lowers into the same flake tensor rows, so it costs no new RNG or
    tensor machinery — only the default probability and the intent
    differ.
  * ``delay_window`` — gray failure by delay/reorder: for
    ``start < t <= stop``, delivery TO nodes in the ``dst`` range (all
    nodes when omitted) is held — inbound mail accumulates in the
    existing max-merge mailboxes (newer heartbeats supersede older ones,
    which is exactly reorder-absorption) and drains the first tick after
    the window closes.  The delayed node keeps sending, probing, and
    aging its failure-detector timers, so peers see it as healthy while
    its own view goes stale — the classic asymmetric gray-failure
    pressure.  Probe acks that land inside the window are lost rather
    than delayed (the one-shot expected-ack slot has no queue; the
    reference's EmulNet drops late acks the same way).

Node selectors for crash/restart/leave (exactly one per event):

  * ``"range": [lo, hi]`` — indices ``lo <= i < hi``;
  * ``"nodes": [i, ...]`` — an explicit list (compiled to unit ranges);
  * ``"draw": "single" | "multi" | "racks"`` — defer to the seeded
    failure draw the legacy planner makes (runtime/failures.py
    draw_single/draw_multi/draw_racks), so a scenario file can replay the
    shipped testcases bit-exactly without hardcoding the seed-dependent
    victim.

Probabilities are quantized to integer percent at compile time
(``int(p * 100) / 100``), matching the reference's EmulNet.cpp:92
comparison so every backend drops identically.
"""

from __future__ import annotations

import dataclasses
import json
from typing import List

EVENT_KINDS = ("crash", "restart", "leave", "partition", "link_flake",
               "drop_window", "one_way_flake", "delay_window")
DRAW_KINDS = ("single", "multi", "racks")
_POINT_KINDS = ("crash", "restart", "leave")


@dataclasses.dataclass
class Scenario:
    """A parsed (but not yet compiled) scenario."""
    name: str
    events: List[dict]
    source: str = ""          # file path, for provenance/manifests

    @classmethod
    def from_dict(cls, d: dict, source: str = "") -> "Scenario":
        if not isinstance(d, dict) or "events" not in d:
            raise ValueError(
                f"scenario {source or '<dict>'}: expected an object with "
                "an 'events' list")
        events = d["events"]
        if not isinstance(events, list) or not events:
            raise ValueError(
                f"scenario {source or '<dict>'}: 'events' must be a "
                "non-empty list")
        return cls(name=str(d.get("name", "unnamed")),
                   events=[dict(e) for e in events], source=source)


def load_scenario(path: str) -> Scenario:
    with open(path) as fh:
        try:
            d = json.load(fh)
        except json.JSONDecodeError as e:
            raise ValueError(f"scenario {path!r}: invalid JSON ({e})") from e
    return Scenario.from_dict(d, source=path)


def _check_range(ev: dict, key: str, n: int, what: str) -> None:
    r = ev.get(key)
    if (not isinstance(r, (list, tuple)) or len(r) != 2
            or not all(isinstance(x, int) for x in r)
            or not 0 <= r[0] < r[1] <= n):
        raise ValueError(
            f"scenario event {ev}: {what} {key!r} must be [lo, hi] with "
            f"0 <= lo < hi <= N={n}")


def validate_scenario(scn: Scenario, n: int, total: int) -> None:
    """Structural validation against a concrete (N, TOTAL_TIME).

    Raises ``ValueError`` on the first violation — a scenario typo must
    fail at config time, never silently simulate something else.
    """
    part_spans = []
    for ev in scn.events:
        kind = ev.get("kind")
        if kind not in EVENT_KINDS:
            raise ValueError(
                f"scenario {scn.name!r}: unknown event kind {kind!r} "
                f"(known: {EVENT_KINDS})")
        if kind in _POINT_KINDS:
            t = ev.get("time")
            if not isinstance(t, int) or not 0 <= t < total:
                raise ValueError(
                    f"scenario event {ev}: 'time' must be an int in "
                    f"[0, TOTAL_TIME={total})")
            sels = [k for k in ("range", "nodes", "draw") if k in ev]
            if len(sels) != 1:
                raise ValueError(
                    f"scenario event {ev}: exactly one of range/nodes/"
                    "draw is required")
            if "range" in ev:
                _check_range(ev, "range", n, kind)
            elif "nodes" in ev:
                nodes = ev["nodes"]
                if (not isinstance(nodes, list) or not nodes
                        or not all(isinstance(x, int) and 0 <= x < n
                                   for x in nodes)):
                    raise ValueError(
                        f"scenario event {ev}: 'nodes' must be a "
                        f"non-empty list of indices in [0, N={n})")
            else:
                if ev["draw"] not in DRAW_KINDS:
                    raise ValueError(
                        f"scenario event {ev}: 'draw' must be one of "
                        f"{DRAW_KINDS}")
                if kind != "crash":
                    raise ValueError(
                        f"scenario event {ev}: 'draw' selectors are "
                        "crash-only (restart/leave need a determined set)")
        else:
            start, stop = ev.get("start"), ev.get("stop")
            if (not isinstance(start, int) or not isinstance(stop, int)
                    or not 0 <= start < stop):
                raise ValueError(
                    f"scenario event {ev}: needs int 'start' < 'stop'")
            if kind == "partition":
                groups = ev.get("groups")
                if (not isinstance(groups, list) or len(groups) < 2):
                    raise ValueError(
                        f"scenario event {ev}: 'groups' must list >= 2 "
                        "contiguous index ranges")
                prev = 0
                for g in groups:
                    if (not isinstance(g, (list, tuple)) or len(g) != 2
                            or g[0] != prev or g[1] <= g[0]):
                        raise ValueError(
                            f"scenario event {ev}: groups must be "
                            "ascending contiguous ranges tiling [0, N) "
                            f"(got {groups})")
                    prev = g[1]
                if prev != n:
                    raise ValueError(
                        f"scenario event {ev}: groups cover [0, {prev}) "
                        f"but N={n}")
                part_spans.append((start, stop))
            elif kind in ("link_flake", "one_way_flake"):
                _check_range(ev, "src", n, kind)
                _check_range(ev, "dst", n, kind)
            elif kind == "delay_window":
                if "dst" in ev:
                    _check_range(ev, "dst", n, kind)
            if kind in ("link_flake", "drop_window") or (
                    kind == "one_way_flake" and "drop_prob" in ev):
                p = ev.get("drop_prob")
                if not isinstance(p, (int, float)) or not 0 < p <= 1:
                    raise ValueError(
                        f"scenario event {ev}: 'drop_prob' must be in "
                        "(0, 1]")
    part_spans.sort()
    for (s1, e1), (s2, e2) in zip(part_spans, part_spans[1:]):
        if s2 < e1:
            raise ValueError(
                f"scenario {scn.name!r}: partition windows ({s1}, {e1}] "
                f"and ({s2}, {e2}] overlap — at most one partition may "
                "be active per tick (one group vector applies)")
