"""Scenario oracle: grade a run against its declared chaos schedule.

The legacy grader answers one question (was the single crash detected
completely and accurately).  Under a scenario the interesting questions
are different — did the detector FALSE-POSITIVE during a partition, did
the membership re-converge after the heal, did restarted nodes actually
rejoin — and this module computes them from whatever the run produced:

  * the per-tick telemetry series (``TELEMETRY: scalars`` —
    observability/timeline.py) when recorded: joins/removals/suspected/
    live per tick;
  * otherwise, in full-event runs, per-tick join/removal counts parsed
    from dbg.log (the same line grammar the grader greps);
  * the final carry (live/failed flags + a staleness census over the
    packed views — layout-agnostic: natural ``[N, S]`` and folded
    ``[N*S/128, 128]`` planes share the node-major flat order, so one
    ``reshape(-1)`` covers all four ring twins and the sharded carries).

Every metric is a deterministic function of bit-exact run artifacts, so
the report is identical across the natural/folded twins and across a
kill/resume (the acceptance pin in tests/test_scenario.py).

Key partition metrics (per partition window ``(start, stop]``):

  * ``removals_during`` — removals in ``(start, stop + TREMOVE]``: with
    no concurrent crash events these are all FALSE-POSITIVE removals of
    live (merely unreachable) nodes;
  * ``refill_joins`` — admissions from the partition's start to the end
    of the run: the re-admission traffic that heals those removals.
    (Freed slots start refilling DURING the partition — same-side
    gossip admits same-side ids into them — so the refill window opens
    at ``start``, not at the heal; ``joins_after_heal`` is also
    reported for the post-heal share.)
  * ``unhealed_removals`` — ``max(0, removals_during − refill_joins)``:
    the acceptance criterion's "permanent removals of live partitioned
    nodes" (0 = every partition-era eviction was re-filled — read it
    together with ``final.suspected_entries == 0``);
  * ``reconverged_tick`` — first post-heal tick with zero suspected
    entries (telemetry basis), else the last post-heal churn tick
    (event basis) — the measured re-convergence time.

Invariant verdicts (``report["invariants"]`` — hard pass/fail, the
chaos campaign's grading contract, chaos/campaign.py):

  * ``no_false_removals`` — the detection summary's accuracy count
    (removals − true detections) must be 0, UNLESS the schedule itself
    masks liveness: partitions, restart churn (a temporarily-crashed
    node's removals are counted "false" by the scalar accuracy metric),
    delay windows long enough to age an entry past TFAIL, or sustained
    heavy loss (drop_prob >= 0.5 over >= TFAIL ticks).  The excuses are
    a deterministic function of the SCHEDULE, never of the run, so a
    violation cannot excuse itself.
  * ``removals_healed`` — every partition-era eviction was re-filled
    (``unhealed_removals == 0`` per window) and the final views carry
    zero suspected entries of live nodes: excused false removals must
    HEAL.
  * ``restarts_rejoined`` — every restarted node is live at the end.
  * ``detection_slo`` — the PR-5 detection-latency SLO verdict
    (observability/latency_dist.slo_verdict) when the run recorded the
    hist tier's ``h_latency``; unassessed (and passing) otherwise.

``report["violations"]`` lists the failing invariant names and
``report["ok"]`` rolls them up — False means the run violated its
schedule's contract.
"""

from __future__ import annotations

import re
from typing import Optional

import numpy as np

from distributed_membership_tpu.scenario.compile import (
    DOWN_KINDS, ScenarioProgram)

_REMOVED_RE = re.compile(r"removed at time (\d+)\s*$")
_JOINED_RE = re.compile(r"joined at time (\d+)\s*$")


def _series_from_dbg(dbg_text: str, total: int):
    """Per-tick join/removal counts from dbg.log lines (the grader's
    line grammar; variant-prefix lines without the suffix are skipped,
    as observability.metrics does)."""
    joins = np.zeros((total,), np.int64)
    removals = np.zeros((total,), np.int64)
    for line in dbg_text.splitlines():
        m = _REMOVED_RE.search(line)
        if m:
            t = int(m.group(1))
            if 0 <= t < total:
                removals[t] += 1
            continue
        m = _JOINED_RE.search(line)
        if m:
            t = int(m.group(1))
            if 0 <= t < total:
                joins[t] += 1
    return joins, removals


def _final_state_census(final_state, params, total: int) -> dict:
    """Live/failed counts + a staleness census over the final views."""
    failed = np.asarray(final_state.failed)
    started = np.asarray(final_state.started)
    in_group = np.asarray(final_state.in_group)
    live = started & in_group & ~failed
    out = {"live": int(live.sum()), "failed": int(failed.sum())}
    n = params.EN_GPSZ
    s = params.VIEW_SIZE if params.VIEW_SIZE > 0 else n
    view = np.asarray(final_state.view).reshape(-1)
    view_ts = np.asarray(final_state.view_ts).reshape(-1)
    if view.size == n * s:
        # Node-major flat order holds for natural AND folded planes
        # (folded flat index = node*S + slot — module docstring).
        holder_live = np.repeat(live, s)
        present = (view > 0) & holder_live
        stale = present & ((total - 1) - view_ts >= params.TFAIL)
        out["suspected_entries"] = int(stale.sum())
        out["present_entries"] = int(present.sum())
    return out


def _masking_excuses(program: ScenarioProgram, params) -> list:
    """Schedule features that legitimately cause the scalar accuracy
    metric to count removals of live nodes (module docstring) — a
    deterministic function of the SCHEDULE, independent of the run."""
    excuses = []
    if program.partitions:
        excuses.append("partition")
    if any(e["kind"] == "restart" for e in program.point_events):
        excuses.append("restart_churn")
    if any(w["stop"] - w["start"] >= params.TFAIL
           for w in program.delays):
        excuses.append("long_delay")
    heavy = [w for w in program.flakes + program.drop_windows
             if (w["drop_prob"] >= 0.5
                 and w["stop"] - w["start"] >= params.TFAIL)]
    if heavy:
        excuses.append("heavy_loss")
    return excuses


def _invariant_verdicts(program: ScenarioProgram, params, report: dict,
                        summary: Optional[dict],
                        timeline: Optional[dict]) -> dict:
    """The hard verdicts (module docstring).  Each entry carries its
    evidence plus ``ok``; unassessable invariants (missing artifact
    stream) pass with ``assessed: False`` — absence of evidence is not
    a violation, and the campaign runner requires the streams it needs."""
    inv: dict = {}

    fr = None if summary is None else summary.get("false_removals")
    excuses = _masking_excuses(program, params)
    inv["no_false_removals"] = {
        "count": fr, "excused_by": excuses,
        "assessed": fr is not None,
        "ok": fr is None or fr == 0 or bool(excuses)}

    unhealed = sum(p.get("unhealed_removals", 0)
                   for p in report.get("partitions", ()))
    susp = report.get("final", {}).get("suspected_entries")
    inv["removals_healed"] = {
        "unhealed_removals": unhealed, "suspected_entries": susp,
        "assessed": bool(report.get("partitions")) or susp is not None,
        "ok": unhealed == 0 and not susp}

    restarts = report.get("restarts", ())
    not_back = [r for r in restarts if r.get("rejoined") is False]
    inv["restarts_rejoined"] = {
        "restart_events": len(restarts), "not_rejoined": len(not_back),
        "assessed": bool(restarts),
        "ok": not not_back}

    slo = None
    if timeline is not None and "h_latency" in timeline:
        from distributed_membership_tpu.observability.latency_dist import (
            slo_verdict)
        slo = slo_verdict(timeline)
    inv["detection_slo"] = {
        "assessed": bool(slo) and slo.get("passed") is not None,
        "max_cdf_deviation": (None if slo is None
                              else slo.get("max_cdf_deviation")),
        "ok": slo is None or slo.get("passed") is not False}
    return inv


def _window_sum(series, lo: int, hi: int, t0: int = 0) -> int:
    """Sum of series[t] for lo < t <= hi (series starts at tick t0)."""
    a = max(lo + 1 - t0, 0)
    b = max(min(hi + 1 - t0, len(series)), a)
    return int(np.asarray(series[a:b]).sum())


def scenario_report(program: ScenarioProgram, params, *,
                    final_state=None, summary: Optional[dict] = None,
                    timeline: Optional[dict] = None,
                    dbg_text: Optional[str] = None,
                    final_live: Optional[int] = None,
                    final_failed: Optional[int] = None,
                    final_failed_indices=None) -> dict:
    """The oracle report dict (see module docstring for the metrics)."""
    total = params.TOTAL_TIME
    t0 = 0
    joins = removals = suspected = None
    basis = "none"
    if timeline is not None and timeline.get("ticks", 0) > 0:
        joins = timeline["joins"]
        removals = timeline["removals"]
        suspected = timeline["suspected"]
        t0 = int(timeline.get("t0", 0))
        basis = "telemetry"
    elif dbg_text is not None:
        joins, removals = _series_from_dbg(dbg_text, total)
        basis = "dbg"

    report: dict = {
        "scenario": program.scenario.name,
        "basis": basis,
        "events": [],
        "partitions": [],
        "crashes": [],
        "restarts": [],
    }
    end = t0 + (len(joins) if joins is not None else total) - 1

    for ev in program.point_events:
        count = sum(hi - lo for lo, hi in ev["ranges"])
        entry = {"kind": ev["kind"], "time": ev["time"], "nodes": count}
        report["events"].append(dict(entry))
        if ev["kind"] in DOWN_KINDS:
            if removals is not None:
                entry["removals_within_2tremove"] = _window_sum(
                    removals, ev["time"], ev["time"] + 2 * params.TREMOVE,
                    t0)
            report["crashes"].append(entry)
        else:
            idxs = [i for lo, hi in ev["ranges"] for i in range(lo, hi)]
            if final_state is not None:
                failed = np.asarray(final_state.failed)
                entry["rejoined"] = bool((~failed[idxs]).all())
            elif final_failed_indices is not None:
                down = set(final_failed_indices)
                entry["rejoined"] = not down.intersection(idxs)
            if joins is not None:
                entry["joins_after"] = _window_sum(joins, ev["time"],
                                                   end, t0)
            report["restarts"].append(entry)

    for w in program.partitions:
        start, stop = w["start"], w["stop"]
        p: dict = {"start": start, "stop": stop,
                   "groups": len(w["cuts"]) + 1}
        report["events"].append({"kind": "partition", "start": start,
                                 "stop": stop})
        if removals is not None:
            p["removals_during"] = _window_sum(
                removals, start, stop + params.TREMOVE, t0)
            p["refill_joins"] = _window_sum(joins, start, end, t0)
            p["joins_after_heal"] = _window_sum(joins, stop, end, t0)
            p["unhealed_removals"] = max(
                0, p["removals_during"] - p["refill_joins"])
        if suspected is not None:
            post = np.asarray(suspected[max(stop + 1 - t0, 0):])
            zeros = np.nonzero(post == 0)[0]
            p["reconverged_tick"] = (int(stop + 1 + zeros[0])
                                     if zeros.size else None)
            p["reconverge_basis"] = "suspected"
        elif removals is not None:
            churn = np.asarray(joins[max(stop + 1 - t0, 0):]) \
                + np.asarray(removals[max(stop + 1 - t0, 0):])
            nz = np.nonzero(churn)[0]
            p["reconverged_tick"] = (int(stop + 1 + nz[-1])
                                     if nz.size else None)
            p["reconverge_basis"] = "churn"
        report["partitions"].append(p)

    for w in program.flakes:
        report["events"].append({"kind": "link_flake", **{
            k: w[k] for k in ("start", "stop", "drop_prob")}})
    for w in program.drop_windows:
        report["events"].append({"kind": "drop_window", **{
            k: w[k] for k in ("start", "stop", "drop_prob")}})
    for w in program.delays:
        report["events"].append({"kind": "delay_window",
                                 "start": w["start"], "stop": w["stop"],
                                 "dst": list(w["dst"])})

    if joins is not None:
        report["totals"] = {"joins_total": int(np.asarray(joins).sum()),
                            "removals_total":
                                int(np.asarray(removals).sum())}
    if final_state is not None:
        report["final"] = _final_state_census(final_state, params, total)
    elif final_live is not None:
        report["final"] = {"live": int(final_live),
                           "failed": int(final_failed or 0)}
    if summary is not None:
        report["detection_summary"] = {
            k: summary[k] for k in ("detections_total", "false_removals")
            if k in summary}
    report["invariants"] = _invariant_verdicts(program, params, report,
                                               summary, timeline)
    report["violations"] = sorted(
        name for name, v in report["invariants"].items() if not v["ok"])
    report["ok"] = not report["violations"]
    return report
