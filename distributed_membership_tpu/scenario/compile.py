"""Scenario compiler: events → tick-indexed tensor plans.

The compiler has two lowerings:

  * **Legacy** — a scenario whose events are crashes at ONE time plus at
    most one global drop window is exactly the failure shape the
    reference injects, so it lowers straight to a
    :class:`~distributed_membership_tpu.runtime.failures.FailurePlan`
    (draw selectors consume the same seeded RNG stream ``make_plan``
    does — the shipped ``scenarios/*.json`` testcase twins reproduce
    ``make_plan`` bit-exactly on EVERY backend, pinned in
    tests/test_scenario.py).
  * **General** — anything with restart/leave/partition/link_flake or
    multi-time crashes compiles to a :class:`ScenarioProgram` carrying
    :class:`ScenarioTensors`: small time/range tensors that ride the
    jitted ring steps as scan INPUTS (like the failure schedule), so the
    per-tick activation is pure elementwise math on ``t`` — no [N, T]
    materialization, no new gathers (tests/test_hlo_census.py bounds the
    addition), and checkpoint/resume composes for free (the tensors are
    re-derived from the scenario file; nothing scenario-shaped enters
    the carry).

Shape conventions (every array padded to length >= 1 with inert rows so
the jitted program's structure depends only on :class:`ScenarioStatic`,
which rides ``HashConfig`` as the runner-cache key):

  * windows are active for ``start < t <= stop`` — the legacy
    DROP_START/DROP_STOP convention (``(t > lo) & (t <= hi)``);
  * partition groups lower to boundary cuts (``part_cut``, padded with
    N), so the send-path predicate is ``group[src] != group[dst]`` with
    ``group(x) = sum(x >= cuts)`` — elementwise, gather-free;
  * probabilities are pre-quantized to integer percent (schema note).
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import List, NamedTuple, Optional, Tuple

import numpy as np

from distributed_membership_tpu.scenario.schema import (
    Scenario, load_scenario, validate_scenario)

DOWN_KINDS = ("crash", "leave")

# Backends implementing the general tensor-plan path.  Everything else
# accepts only legacy-shaped scenarios (which lower to a FailurePlan and
# run the unchanged code).  The jitted twins additionally require the
# ring exchange (tpu_hash.make_config gates it).
GENERAL_BACKENDS = ("emul", "tpu_hash", "tpu_hash_sharded")


class ScenarioStatic(NamedTuple):
    """Hashable structural descriptor — everything that changes the
    traced program (tensor shapes + which code blocks exist).  Rides
    ``HashConfig.scenario`` so runner caches key on it."""
    n: int
    n_events: int         # point-event rows (crash/leave/restart ranges)
    n_parts: int          # partition windows
    n_cuts: int           # group-boundary cut columns
    n_flakes: int         # link_flake / one_way_flake windows
    n_windows: int        # global drop windows
    n_delays: int         # delay_window (hold-inbound) windows
    has_drop: bool        # any coin-consuming loss (windows or flakes)
    has_updown: bool      # any crash/leave/restart event


class ScenarioTensors(NamedTuple):
    """The in-scan plan (all jnp arrays; shapes per ScenarioStatic)."""
    ev_time: object       # [E] i32 (pad -9: never fires)
    ev_down: object       # [E] bool — crash | leave rows
    ev_up: object         # [E] bool — restart rows
    ev_lo: object         # [E] i32
    ev_hi: object         # [E] i32
    part_start: object    # [P] i32 (pad -9)
    part_stop: object     # [P] i32 (pad -9)
    part_cut: object      # [P, C] i32 (pad N — group 0 everywhere)
    fl_start: object      # [F] i32 (pad -9)
    fl_stop: object       # [F] i32
    fl_slo: object        # [F] i32
    fl_shi: object        # [F] i32
    fl_dlo: object        # [F] i32
    fl_dhi: object        # [F] i32
    fl_prob: object       # [F] f32 (quantized)
    dw_lo: object         # [W] i32 (pad -9)
    dw_hi: object         # [W] i32
    dw_prob: object       # [W] f32 (quantized)
    dl_start: object      # [D] i32 (pad -9)
    dl_stop: object       # [D] i32
    dl_lo: object         # [D] i32 — dst range held during the window
    dl_hi: object         # [D] i32


def _quant(p: float) -> float:
    """Integer-percent quantization (EmulNet.cpp:92 semantics), applied
    once at compile so every backend drops identically."""
    return int(float(p) * 100) / 100.0


# ---------------------------------------------------------------------------
# In-step helpers (pure jnp; called inside the jitted ring steps)

def updown_masks(scn: ScenarioTensors, t, node_ids):
    """(down_now, up_now) bool masks shaped like ``node_ids`` — which
    nodes crash/leave resp. restart at the end of tick ``t``.  Pure
    elementwise broadcast over the [E] event rows."""
    hit = scn.ev_time == t                                  # [E]
    x = node_ids[..., None]
    in_rng = (x >= scn.ev_lo) & (x < scn.ev_hi)             # [..., E]
    down = (in_rng & (hit & scn.ev_down)).any(-1)
    up = (in_rng & (hit & scn.ev_up)).any(-1)
    return down, up


def cuts_at(scn: ScenarioTensors, t, n: int):
    """The active partition's [C] group-boundary cuts at tick ``t`` (all
    N — i.e. "one group" — when no partition is active; windows never
    overlap, schema.validate_scenario)."""
    import jax.numpy as jnp

    act = (t > scn.part_start) & (t <= scn.part_stop)       # [P]
    return jnp.where(act[:, None], scn.part_cut, n).min(0)  # [C]


def cross_group(cuts, src, dst):
    """``group[src] != group[dst]`` under the cut row — the partition
    send-path predicate (elementwise; broadcastable src/dst)."""
    import jax.numpy as jnp

    def grp(x):
        return (x[..., None] >= cuts).sum(-1)
    return grp(src) != grp(dst)


def delayed_mask(scn: ScenarioTensors, t, node_ids):
    """Bool mask shaped like ``node_ids``: which nodes have inbound
    delivery held at tick ``t`` (any active delay window covering the
    id).  Purely elementwise over the [D] rows — callers gate the call
    on ``static.n_delays`` so delay-free programs stay op-identical."""
    act = (t > scn.dl_start) & (t <= scn.dl_stop)            # [D]
    x = node_ids[..., None]
    return (act & (x >= scn.dl_lo) & (x < scn.dl_hi)).any(-1)


def base_drop_prob(scn: ScenarioTensors, t):
    """Scalar f32: the max active global drop-window probability at t."""
    import jax.numpy as jnp

    act = (t > scn.dw_lo) & (t <= scn.dw_hi)
    return jnp.where(act, scn.dw_prob, 0.0).max()


def site_drop_prob(static: ScenarioStatic, scn: ScenarioTensors, t,
                   src, dst):
    """Per-message effective drop probability for a send site: the
    active global window combined with any matching link-flake window as
    independent loss (``p + q - p*q``; exactly ``p`` where no flake
    matches, so flake-free runs stay bit-identical to the window-only
    form).  Returns a scalar when the scenario has no flakes, else a
    tensor broadcast over ``src``/``dst``."""
    import jax.numpy as jnp

    p = base_drop_prob(scn, t)
    if static.n_flakes == 0:
        return p
    act = (t > scn.fl_start) & (t <= scn.fl_stop)           # [F]
    s = src[..., None] if hasattr(src, "ndim") else jnp.asarray(src)[..., None]
    d = dst[..., None] if hasattr(dst, "ndim") else jnp.asarray(dst)[..., None]
    m = act & (s >= scn.fl_slo) & (s < scn.fl_shi) \
        & (d >= scn.fl_dlo) & (d < scn.fl_dhi)
    q = jnp.where(m, scn.fl_prob, 0.0).max(-1)
    return p + q - p * q


# ---------------------------------------------------------------------------
# Compiled program

@dataclasses.dataclass
class ScenarioProgram:
    """A compiled general-path scenario: the resolved event list plus
    the tensor-plan builder.  Attached to the run's ``FailurePlan``
    (``plan.scenario``) so it threads through the existing backend
    entrypoints unchanged."""
    scenario: Scenario
    n: int
    static: ScenarioStatic
    point_events: List[dict]      # {kind, time, ranges: [(lo, hi)...]}
    partitions: List[dict]        # {start, stop, cuts: [..]}
    flakes: List[dict]            # {start, stop, src, dst, drop_prob}
    drop_windows: List[dict]      # {start, stop, drop_prob}
    delays: List[dict] = dataclasses.field(default_factory=list)
    # ^ {start, stop, dst: (lo, hi)} — hold-inbound windows

    _tensors: Optional[ScenarioTensors] = dataclasses.field(
        default=None, repr=False, compare=False)

    def tensors(self) -> ScenarioTensors:
        """The jnp tensor plan (built once per program)."""
        if self._tensors is None:
            import jax.numpy as jnp
            np_t = self.numpy_tensors()
            self._tensors = ScenarioTensors(
                *(jnp.asarray(a) for a in np_t))
        return self._tensors

    def numpy_tensors(self) -> ScenarioTensors:
        st = self.static
        e = max(st.n_events, 1)
        ev_time = np.full((e,), -9, np.int32)
        ev_down = np.zeros((e,), bool)
        ev_up = np.zeros((e,), bool)
        ev_lo = np.zeros((e,), np.int32)
        ev_hi = np.zeros((e,), np.int32)
        i = 0
        for ev in self.point_events:
            for lo, hi in ev["ranges"]:
                ev_time[i] = ev["time"]
                ev_down[i] = ev["kind"] in DOWN_KINDS
                ev_up[i] = ev["kind"] == "restart"
                ev_lo[i], ev_hi[i] = lo, hi
                i += 1
        p = max(st.n_parts, 1)
        c = max(st.n_cuts, 1)
        part_start = np.full((p,), -9, np.int32)
        part_stop = np.full((p,), -9, np.int32)
        part_cut = np.full((p, c), self.n, np.int32)
        for j, w in enumerate(self.partitions):
            part_start[j], part_stop[j] = w["start"], w["stop"]
            part_cut[j, :len(w["cuts"])] = w["cuts"]
        f = max(st.n_flakes, 1)
        fl = {k: np.full((f,), -9, np.int32)
              for k in ("start", "stop")}
        fl.update({k: np.zeros((f,), np.int32)
                   for k in ("slo", "shi", "dlo", "dhi")})
        fl_prob = np.zeros((f,), np.float32)
        for j, w in enumerate(self.flakes):
            fl["start"][j], fl["stop"][j] = w["start"], w["stop"]
            fl["slo"][j], fl["shi"][j] = w["src"]
            fl["dlo"][j], fl["dhi"][j] = w["dst"]
            fl_prob[j] = w["drop_prob"]
        wn = max(st.n_windows, 1)
        dw_lo = np.full((wn,), -9, np.int32)
        dw_hi = np.full((wn,), -9, np.int32)
        dw_prob = np.zeros((wn,), np.float32)
        for j, w in enumerate(self.drop_windows):
            dw_lo[j], dw_hi[j] = w["start"], w["stop"]
            dw_prob[j] = w["drop_prob"]
        d = max(st.n_delays, 1)
        dl_start = np.full((d,), -9, np.int32)
        dl_stop = np.full((d,), -9, np.int32)
        dl_lo = np.zeros((d,), np.int32)
        dl_hi = np.zeros((d,), np.int32)
        for j, w in enumerate(self.delays):
            dl_start[j], dl_stop[j] = w["start"], w["stop"]
            dl_lo[j], dl_hi[j] = w["dst"]
        return ScenarioTensors(
            ev_time, ev_down, ev_up, ev_lo, ev_hi,
            part_start, part_stop, part_cut,
            fl["start"], fl["stop"], fl["slo"], fl["shi"], fl["dlo"],
            fl["dhi"], fl_prob, dw_lo, dw_hi, dw_prob,
            dl_start, dl_stop, dl_lo, dl_hi)

    def host(self) -> "ScenarioHost":
        return ScenarioHost(self)


class ScenarioHost:
    """Host-side twin of the tensor plan for the ``emul`` backend's
    queue-level network: the same window/partition/flake semantics
    evaluated per message in numpy/python."""

    def __init__(self, program: ScenarioProgram):
        self.program = program
        t = program.numpy_tensors()
        self._t = t
        self.n = program.n

    def down_at(self, t: int) -> List[int]:
        return self._fire(t, self._t.ev_down)

    def up_at(self, t: int) -> List[int]:
        return self._fire(t, self._t.ev_up)

    def _fire(self, t: int, kind_mask) -> List[int]:
        out: List[int] = []
        tt = self._t
        for j in range(len(tt.ev_time)):
            if tt.ev_time[j] == t and kind_mask[j]:
                out.extend(range(int(tt.ev_lo[j]), int(tt.ev_hi[j])))
        return sorted(set(out))

    def _cuts(self, t: int):
        tt = self._t
        act = (t > tt.part_start) & (t <= tt.part_stop)
        return np.where(act[:, None], tt.part_cut, self.n).min(0)

    def blocked(self, t: int, src: int, dst: int) -> bool:
        if self.program.static.n_parts == 0:
            return False
        cuts = self._cuts(t)
        return int((src >= cuts).sum()) != int((dst >= cuts).sum())

    def delayed(self, t: int, idx: int) -> bool:
        """Whether node ``idx`` has inbound delivery held at tick ``t``
        (host twin of :func:`delayed_mask`)."""
        if self.program.static.n_delays == 0:
            return False
        tt = self._t
        return bool(((t > tt.dl_start) & (t <= tt.dl_stop)
                     & (idx >= tt.dl_lo) & (idx < tt.dl_hi)).any())

    def drop_pct(self, t: int, src: int, dst: int) -> int:
        """Effective drop percentage for one message (reference-style
        integer percent; see site_drop_prob for the combine)."""
        tt = self._t
        act = (t > tt.dw_lo) & (t <= tt.dw_hi)
        p = float(np.where(act, tt.dw_prob, 0.0).max())
        q = 0.0
        if self.program.static.n_flakes:
            m = ((t > tt.fl_start) & (t <= tt.fl_stop)
                 & (src >= tt.fl_slo) & (src < tt.fl_shi)
                 & (dst >= tt.fl_dlo) & (dst < tt.fl_dhi))
            q = float(np.where(m, tt.fl_prob, 0.0).max())
        return int((p + q - p * q) * 100)


# ---------------------------------------------------------------------------
# Compilation

def _resolve_ranges(ev: dict, params, rng) -> Tuple[List[Tuple[int, int]],
                                                    str]:
    """→ (ranges, plan_kind_hint) for one point event; draw selectors
    consume ``rng`` exactly as the legacy planner does
    (runtime/failures.draw_*), so a draw-based scenario is bit-exact
    with make_plan for the same seed."""
    from distributed_membership_tpu.runtime.failures import (
        draw_multi, draw_racks, draw_single)

    if "range" in ev:
        lo, hi = ev["range"]
        return [(int(lo), int(hi))], "multi"
    if "nodes" in ev:
        return [(int(i), int(i) + 1) for i in sorted(set(ev["nodes"]))], \
            "multi"
    draw = ev["draw"]
    if draw == "single":
        idx = draw_single(params.EN_GPSZ, rng)
        return [(idx, idx + 1)], "single"
    if draw == "multi":
        lo, hi = draw_multi(params.EN_GPSZ, rng)
        return ([(lo, hi)] if hi > lo else []), "multi"
    indices = draw_racks(params, rng)
    return [(i, i + 1) for i in indices], "racks"


def _indices(ranges: List[Tuple[int, int]]) -> List[int]:
    return sorted({i for lo, hi in ranges for i in range(lo, hi)})


def scenario_digest(path: str) -> str:
    """sha256 of the scenario file bytes — the checkpoint manifest's
    provenance field (a changed schedule must not silently resume)."""
    with open(path, "rb") as fh:
        return hashlib.sha256(fh.read()).hexdigest()


def compile_scenario(scn: Scenario, params, rng, force_general: bool = False):
    """→ a FailurePlan, with ``plan.scenario`` set to the
    :class:`ScenarioProgram` on the general path and ``None`` on the
    legacy lowering (where ``params`` may be mutated to carry the
    scenario's drop window through the unchanged legacy code).

    ``force_general=True`` compiles even a legacy-shaped scenario on the
    general tensor-plan path (and never mutates ``params``) — the
    service daemon's live event injection merges the base schedule with
    injected events and needs one uniform program shape regardless of
    how the base run was lowered.  The two lowerings are bit-exact for
    legacy-shaped schedules (pinned by tests/test_scenario.py), so
    forcing the general path changes the compiled artifact, not the
    trajectory.
    """
    from distributed_membership_tpu.runtime.failures import FailurePlan

    n, total = params.EN_GPSZ, params.TOTAL_TIME
    validate_scenario(scn, n, total)

    point, parts, flakes, windows, delays = [], [], [], [], []
    kind_hint = "multi"
    for ev in scn.events:
        kind = ev["kind"]
        if kind in ("crash", "restart", "leave"):
            ranges, hint = _resolve_ranges(ev, params, rng)
            if kind == "crash":
                kind_hint = hint
            point.append({"kind": kind, "time": int(ev["time"]),
                          "ranges": ranges})
        elif kind == "partition":
            parts.append({"start": int(ev["start"]),
                          "stop": int(ev["stop"]),
                          "cuts": [int(g[0]) for g in ev["groups"][1:]]})
        elif kind in ("link_flake", "one_way_flake"):
            # one_way_flake is sugar over the (already directed) flake
            # rows: drop_prob defaults to a hard 1.0 blackhole.
            flakes.append({"start": int(ev["start"]),
                           "stop": int(ev["stop"]),
                           "src": (int(ev["src"][0]), int(ev["src"][1])),
                           "dst": (int(ev["dst"][0]), int(ev["dst"][1])),
                           "drop_prob": _quant(ev.get("drop_prob", 1.0))})
        elif kind == "delay_window":
            dst = ev.get("dst", (0, n))
            delays.append({"start": int(ev["start"]),
                           "stop": int(ev["stop"]),
                           "dst": (int(dst[0]), int(dst[1]))})
        else:
            windows.append({"start": int(ev["start"]),
                            "stop": int(ev["stop"]),
                            "drop_prob": _quant(ev["drop_prob"])})

    crashes = [e for e in point if e["kind"] in DOWN_KINDS]
    crash_times = sorted({e["time"] for e in crashes})
    restarts = [e for e in point if e["kind"] == "restart"]

    # A conf-level drop window coexists with a scenario window only when
    # they are the SAME window (then the legacy lowering still applies —
    # the shipped msgdrop twin names the window its conf already has);
    # different windows compose on the general path.
    conf_window_ok = (not windows or not params.DROP_MSG or (
        len(windows) == 1
        and windows[0]["start"] == params.DROP_START
        and windows[0]["stop"] == params.DROP_STOP
        and windows[0]["drop_prob"] == params.effective_drop_prob()))
    legacy_shape = (
        not parts and not flakes and not delays and not restarts
        and all(e["kind"] == "crash" for e in point)
        and len(crash_times) <= 1 and len(windows) <= 1
        and conf_window_ok)
    if legacy_shape and not force_general:
        if windows and not params.DROP_MSG:
            w = windows[0]
            params.DROP_MSG = 1
            params.MSG_DROP_PROB = w["drop_prob"]
            params.DROP_START = w["start"]
            params.DROP_STOP = w["stop"]
        drop_start = params.DROP_START if params.DROP_MSG else None
        drop_stop = params.DROP_STOP if params.DROP_MSG else None
        fail_time = crash_times[0] if crash_times else None
        failed = _indices([r for e in crashes for r in e["ranges"]])
        return FailurePlan(kind_hint if failed else "none",
                           fail_time if failed else None, failed,
                           drop_start, drop_stop)

    if params.BACKEND not in GENERAL_BACKENDS:
        raise ValueError(
            f"scenario {scn.name!r} needs the general tensor-plan path "
            f"(restart/partition/link_flake/multi-time events), which "
            f"BACKEND {params.BACKEND!r} does not implement "
            f"(supported: {GENERAL_BACKENDS}; legacy-shaped scenarios — "
            "crashes at one time + one drop window — run everywhere)")

    # Conf-level drop window composes as one more global window.
    if params.DROP_MSG:
        windows.append({"start": params.DROP_START,
                        "stop": params.DROP_STOP,
                        "drop_prob": params.effective_drop_prob()})

    # Permanent failures: last down transition not followed by a restart
    # covering the node.  These seed the detection-oracle id set
    # (fail_ids / detection_summary); restart-churned nodes are live at
    # the end and their removals are the oracle's churn events.
    last_down: dict = {}
    last_up: dict = {}
    for e in point:
        for i in _indices(e["ranges"]):
            if e["kind"] in DOWN_KINDS:
                last_down[i] = max(last_down.get(i, -1), e["time"])
            else:
                last_up[i] = max(last_up.get(i, -1), e["time"])
    perm_set = {i for i, td in last_down.items()
                if td > last_up.get(i, -1)}
    permanent = sorted(perm_set)
    fail_time = (min(e["time"] for e in crashes
                     if perm_set.intersection(_indices(e["ranges"])))
                 if permanent else None)

    n_events = sum(len(e["ranges"]) for e in point)
    static = ScenarioStatic(
        n=n, n_events=n_events, n_parts=len(parts),
        n_cuts=max((len(p["cuts"]) for p in parts), default=0),
        n_flakes=len(flakes), n_windows=len(windows),
        n_delays=len(delays),
        has_drop=bool(windows or flakes), has_updown=n_events > 0)
    program = ScenarioProgram(
        scenario=scn, n=n, static=static, point_events=point,
        partitions=parts, flakes=flakes, drop_windows=windows,
        delays=delays)
    return FailurePlan("scenario", fail_time, permanent, None, None,
                       scenario=program)


def resolve_scenario_plan(params, rng):
    """Load ``params.SCENARIO`` and compile it (the ``resolve_plan``
    hook in runtime/failures.py)."""
    scn = load_scenario(params.SCENARIO)
    return compile_scenario(scn, params, rng)
