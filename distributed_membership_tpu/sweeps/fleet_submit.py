"""Submit a sweep grid to a running fleet controller.

The phase sweep (sweeps/phase.py) vmaps a whole grid through one
compile — the right shape when every cell shares a step function.  This
is the other sweep shape: cells that are FULL runs (different confs,
scenarios, seeds), fanned out to ``--fleet``'s bounded scheduler over
plain HTTP and multiplexed behind one control plane instead of N loose
processes.  Stdlib only, like everything in the serving stack.

    python -m distributed_membership_tpu.sweeps.fleet_submit \
        --port 8800 base.conf --set MSG_DROP_PROB=0.0,0.1,0.2 \
        --seeds 1,2 --wait

builds the cross product (3 drop rates x 2 seeds = 6 runs), submits
each as ``<stem>-<KEY>-<value>-s<seed>``, and with ``--wait`` polls
``GET /v1/runs`` until every submitted run reaches a terminal state
(exit 0 only if all are ``done``).

``--scenario-dir DIR`` crosses the grid with every ``*.json`` chaos
schedule in DIR, shipped inline in the submission body (the chaos
campaign fan-out — chaos/campaign.py builds on these helpers).
Transient 502s from the fleet proxy retry with exponential backoff.
"""

from __future__ import annotations

import argparse
import http.client
import itertools
import json
import os
import re
import sys
import time
from typing import Dict, List, Optional, Sequence, Tuple

TERMINAL = ("done", "failed", "killed")


def override_conf(conf_text: str, key: str, value) -> str:
    """``conf_text`` with ``KEY: value`` replaced (or appended)."""
    pat = re.compile(rf"^\s*{re.escape(key)}\s*:.*$", re.MULTILINE)
    line = f"{key}: {value}"
    if pat.search(conf_text):
        return pat.sub(line, conf_text)
    if conf_text and not conf_text.endswith("\n"):
        conf_text += "\n"
    return conf_text + line + "\n"


def grid(conf_text: str, axes: Dict[str, Sequence],
         seeds: Sequence[int] = (None,),
         stem: str = "cell") -> List[dict]:
    """Cross product of conf overrides x seeds -> submission bodies.

    Each body is exactly what ``POST /v1/runs`` takes; run ids encode
    the cell coordinates (``stem-KEY-value-sN``) so a fleet listing
    reads as the sweep grid."""
    keys = sorted(axes)
    subs = []
    for combo in itertools.product(*(axes[k] for k in keys)):
        conf = conf_text
        rid = stem
        for k, v in zip(keys, combo):
            conf = override_conf(conf, k, v)
            rid += f"-{k}-{v}".replace(".", "p")
        for seed in seeds:
            body = {"conf": conf, "run_id": (rid if seed is None
                                             else f"{rid}-s{seed}")}
            if seed is not None:
                body["seed"] = int(seed)
            subs.append(body)
    return subs


def scenario_dir_subs(subs: List[dict], scenario_dir: str) -> List[dict]:
    """Cross ``subs`` with every ``*.json`` scenario in a directory.

    Each scenario payload rides the submission inline (the scheduler
    writes it to the run dir and hands the worker ``--scenario``), so a
    directory of fuzzer output — chaos/fuzz.py — fans out without any
    shared-filesystem assumption between submitter and workers."""
    paths = sorted(p for p in os.listdir(scenario_dir)
                   if p.endswith(".json"))
    if not paths:
        raise ValueError(f"no *.json scenarios in {scenario_dir!r}")
    out = []
    for body in subs:
        for p in paths:
            with open(os.path.join(scenario_dir, p)) as fh:
                payload = json.load(fh)
            stem = os.path.splitext(p)[0]
            out.append(dict(body, scenario=payload,
                            run_id=f"{body['run_id']}-{stem}"))
    return out


def _req(port: int, method: str, path: str,
         body: Optional[dict] = None,
         timeout: float = 30.0,
         retries: int = 0, backoff: float = 0.25) -> Tuple[int, dict]:
    """One HTTP round trip; a 502 from the fleet proxy (upstream worker
    briefly unreachable — restart, resume, overloaded accept queue) is
    TRANSIENT and retried with exponential backoff when ``retries`` > 0.
    Anything else — including connection errors, which mean the
    controller itself is gone — stays loud."""
    attempt = 0
    while True:
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=timeout)
        try:
            conn.request(
                method, path,
                body=None if body is None else json.dumps(body),
                headers={"Content-Type": "application/json"})
            resp = conn.getresponse()
            status, obj = resp.status, json.loads(resp.read() or b"{}")
        finally:
            conn.close()
        if status != 502 or attempt >= retries:
            return status, obj
        time.sleep(backoff * (2 ** attempt))
        attempt += 1


def submit_grid(port: int, subs: List[dict],
                priority: int = 0, retries: int = 5) -> List[dict]:
    """POST every cell; raises on the first refusal (a refused cell
    means the grid itself is malformed — better loud than partial).
    Transient 502s retry with backoff so a proxy hiccup mid-grid does
    not strand a half-submitted sweep."""
    acks = []
    for body in subs:
        body = dict(body, priority=priority)
        code, obj = _req(port, "POST", "/v1/runs", body=body,
                         retries=retries)
        if code != 202:
            raise RuntimeError(f"fleet refused {body.get('run_id')}: "
                               f"{obj.get('error', obj)}")
        acks.append(obj)
    return acks


def wait_grid(port: int, run_ids: Sequence[str],
              timeout: float = 3600.0,
              poll: float = 0.5) -> Dict[str, dict]:
    """Poll the listing until every run is terminal; -> {id: row}."""
    want = set(run_ids)
    deadline = time.monotonic() + timeout
    while True:
        code, obj = _req(port, "GET", "/v1/runs")
        rows = {r["run_id"]: r for r in obj.get("runs", [])
                if r["run_id"] in want}
        if (code == 200 and len(rows) == len(want)
                and all(r["state"] in TERMINAL
                        for r in rows.values())):
            return rows
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"grid not terminal after {timeout}s: "
                f"{ {k: v['state'] for k, v in rows.items()} }")
        time.sleep(poll)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="fan a conf grid out to a --fleet controller")
    ap.add_argument("conf", help="base .conf file for every cell")
    ap.add_argument("--port", type=int, required=True,
                    help="fleet controller port (see its fleet.json)")
    ap.add_argument("--set", action="append", default=[],
                    metavar="KEY=V1,V2,...",
                    help="sweep axis: comma-separated values for one "
                         "conf key (repeatable; axes cross-multiply)")
    ap.add_argument("--seeds", default=None,
                    help="comma-separated seeds (one run per seed per "
                         "cell)")
    ap.add_argument("--stem", default=None,
                    help="run-id prefix (default: conf file stem)")
    ap.add_argument("--scenario-dir", default=None,
                    help="submit every *.json scenario in this "
                         "directory inline (one run per grid cell per "
                         "scenario — chaos campaign fan-out)")
    ap.add_argument("--priority", type=int, default=0,
                    help="queue priority for the whole grid (lower "
                         "dispatches first)")
    ap.add_argument("--wait", action="store_true",
                    help="block until every run is terminal; exit 0 "
                         "only if all are done")
    args = ap.parse_args(argv)

    with open(args.conf) as fh:
        conf_text = fh.read()
    axes: Dict[str, list] = {}
    for spec in args.set:
        key, _, vals = spec.partition("=")
        if not vals:
            ap.error(f"--set {spec!r}: expected KEY=V1,V2,...")
        axes[key.strip()] = [v.strip() for v in vals.split(",") if
                             v.strip()]
    seeds: Sequence = (None,)
    if args.seeds:
        seeds = [int(s) for s in args.seeds.split(",")]
    stem = args.stem or os.path.splitext(
        os.path.basename(args.conf))[0]
    subs = grid(conf_text, axes, seeds=seeds, stem=stem)
    if args.scenario_dir:
        subs = scenario_dir_subs(subs, args.scenario_dir)
    acks = submit_grid(args.port, subs, priority=args.priority)
    for ack in acks:
        print(f"fleet_submit: {ack['run_id']} -> {ack['state']} "
              f"({ack['mode']})")
    if not args.wait:
        return 0
    rows = wait_grid(args.port, [a["run_id"] for a in acks])
    bad = 0
    for rid in sorted(rows):
        row = rows[rid]
        print(f"fleet_submit: {rid} {row['state']} "
              f"tick {row['tick']}/{row['total']}")
        bad += row["state"] != "done"
    return 1 if bad else 0


if __name__ == "__main__":
    sys.exit(main())
