"""Phase-diagram sweep: fanout x drop-rate grid (BASELINE.json config #5).

Maps the detection phase boundary of the gossip/SWIM protocol: for each
(fanout, drop_rate) cell the sweep runs the `tpu_hash` scale protocol from a
warm bootstrap, crashes one node, and records detection completeness,
latency percentiles, false removals, and message volume.

**One compile for the whole grid.**  The step is built with
``dynamic_knobs=True`` (backends/tpu_hash.py): fanout and drop probability
enter as *traced* scalars, so the full grid — every cell x every seed — runs
as a single ``jax.vmap`` over one jitted scan.  A naive sweep would pay one
XLA compile per cell (~56 compiles); this pays one.

Drops here apply to the WHOLE run (the phase variable is the channel's loss
rate), unlike the grading scenarios' [50, 300) window
(Application.cpp:177-179).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from distributed_membership_tpu.backends.tpu_hash import (
    HashConfig, I32, init_state_warm, make_config, make_step)
from distributed_membership_tpu.config import Params
from distributed_membership_tpu.observability.aggregates import (
    LAT_BINS, latency_stats)


@dataclasses.dataclass(frozen=True)
class SweepSpec:
    n: int = 4096
    view_size: int = 32
    gossip_len: int = 8
    probes: int = 8          # cycle = 4 ticks
    tfail: int = 8
    tremove: int = 24
    ticks: int = 120
    fail_time: int = 60
    exchange: str = "auto"   # both lowerings sweepable (VERDICT r2 weak-7)
    fanouts: Sequence[int] = tuple(range(1, 9))
    drop_rates: Sequence[float] = (0.0, 0.05, 0.1, 0.15, 0.2, 0.25, 0.3)
    seeds: Sequence[int] = (0, 1, 2)
    name: str = "phase_sweep"   # artifact file stem

    def to_params(self) -> Params:
        # fanout here is only the static bound; cells pass theirs
        # dynamically.  The fast-path knobs are PINNED off: the sweep
        # runs make_step(dynamic_knobs=True) with drops injected as
        # traced values, which the FUSED_GOSSIP kernel and the folded
        # layout cannot take — a drop-free base config would otherwise
        # let the -1 auto default resolve them on under a banked TPU
        # record and trip make_step's dynamic-knobs guard.
        return Params.from_text(
            f"MAX_NNB: {self.n}\nSINGLE_FAILURE: 1\nDROP_MSG: 0\n"
            f"MSG_DROP_PROB: 0\nVIEW_SIZE: {self.view_size}\n"
            f"GOSSIP_LEN: {self.gossip_len}\nPROBES: {self.probes}\n"
            f"FANOUT: {max(self.fanouts)}\nTFAIL: {self.tfail}\n"
            f"TREMOVE: {self.tremove}\nTOTAL_TIME: {self.ticks}\n"
            f"FAIL_TIME: {self.fail_time}\nJOIN_MODE: warm\n"
            f"EVENT_MODE: agg\nEXCHANGE: {self.exchange}\n"
            f"FUSED_RECEIVE: 0\nFUSED_GOSSIP: 0\nFOLDED: 0\n"
            f"BACKEND: tpu_hash\n")

    @staticmethod
    def north_star() -> "SweepSpec":
        """The S=16 scale regime (N=65536, cycle 8) at the 5-cycle default
        TREMOVE: maps the loss knee Params.min_tremove_cycles_under_loss
        guards against, at the scale the claims are quoted for."""
        return SweepSpec(
            n=65536, view_size=16, gossip_len=4, probes=2, tfail=16,
            tremove=40, ticks=160, fail_time=80,
            fanouts=(3,), drop_rates=(0.0, 0.05, 0.1, 0.15, 0.25),
            seeds=(0, 1), name="phase_sweep_s16")


def run_sweep(spec: SweepSpec = SweepSpec()) -> list[dict]:
    """Execute the grid; returns one record per (fanout, drop, seed)."""
    params = spec.to_params()
    cfg = make_config(params, collect_events=False)
    if cfg.probe_io_lag:
        # This driver runs make_step + its own scan, bypassing
        # _get_runner's on-device lag tail — totals would silently lose
        # the final tick's ack sends (the documented approx_lag
        # contract).  Reject rather than drift.
        raise ValueError("PROBE_IO approx_lag is not supported by the "
                         "sweep driver (no lag tail in its scan)")
    # The crashed node is a *traced* per-lane value here, so the sweep needs
    # the AggStats path (per-id accumulators indexable by a traced id) —
    # the static-failed-id FastAgg fast path cannot apply.
    cfg = dataclasses.replace(cfg, fast_agg=False, fail_ids=())
    step = make_step(cfg, dynamic_knobs=True)
    n, total = spec.n, spec.ticks

    ticks = jnp.arange(total, dtype=I32)
    start_ticks = jnp.full((n,), -1, I32)            # warm: active from t=0
    fail_time = jnp.asarray(spec.fail_time, I32)
    drop_lo = jnp.asarray(-1, I32)                   # drops active all run
    drop_hi = jnp.asarray(total + 1, I32)

    def one_run(seed, fanout, drop):
        # Key streams via make_run_key, the same root the backends use
        # (honors PRNG_IMPL; seed is traced here — both impls accept it).
        from distributed_membership_tpu.runtime.failures import make_run_key

        keys = jax.vmap(lambda t: jax.random.fold_in(
            make_run_key(params, seed), t))(ticks)
        # The crashed node varies with the seed, as Application::fail's
        # rand() % N does (Application.cpp:182).
        failed = jax.random.randint(make_run_key(params, seed ^ 0xFA11),
                                    (), 0, n, dtype=I32)
        fail_mask = jnp.zeros((n,), bool).at[failed].set(True)
        state0 = init_state_warm(cfg, make_run_key(params, seed ^ 0x5EED))

        def body(state, inp):
            t, k = inp
            return step(state, (t, k, start_ticks, fail_mask, fail_time,
                                drop_lo, drop_hi), fanout, drop)

        final_state, _ = jax.lax.scan(body, state0, (ticks, keys))
        agg = final_state.agg
        return {
            "false_removals": agg.rm_count.sum() - agg.det_count.sum(),
            "trackers": agg.trackers[failed],
            "detections": agg.det_count[failed],
            "tracker_nodes": agg.tracker_obs.sum(),
            "detecting_trackers": (agg.det_obs & agg.tracker_obs).sum(),
            "lat_hist": agg.lat_hist,
            "msgs_sent": agg.sent_total.sum(),
        }

    grid = [(seed, f, d) for f in spec.fanouts for d in spec.drop_rates
            for seed in spec.seeds]
    seeds_a = jnp.asarray([g[0] for g in grid], I32)
    fanout_a = jnp.asarray([g[1] for g in grid], I32)
    drop_a = jnp.asarray([g[2] for g in grid], jnp.float32)

    out = jax.jit(jax.vmap(one_run))(seeds_a, fanout_a, drop_a)
    out = jax.tree.map(np.asarray, out)

    records = []
    for i, (seed, fanout, drop) in enumerate(grid):
        hist = out["lat_hist"][i]
        lstats = latency_stats(hist)
        trackers = int(out["tracker_nodes"][i])
        detecting = int(out["detecting_trackers"][i])
        records.append({
            "fanout": int(fanout), "drop_rate": float(drop),
            "seed": int(seed),
            "false_removals": int(out["false_removals"][i]),
            "trackers": trackers,
            "observer_completeness": detecting / trackers if trackers else 1.0,
            "detections": int(out["detections"][i]),
            "latency_p50": lstats.get("latency_p50"),
            "latency_p99": lstats.get("latency_p99"),
            "latency_overflow": int(hist[LAT_BINS - 1]),
            "msgs_sent": int(out["msgs_sent"][i]),
        })
    return records


def summarize(records: list[dict]) -> list[dict]:
    """Collapse seeds: one row per (fanout, drop_rate) cell with means."""
    cells: dict = {}
    for r in records:
        cells.setdefault((r["fanout"], r["drop_rate"]), []).append(r)
    rows = []
    for (fanout, drop), rs in sorted(cells.items()):
        rows.append({
            "fanout": fanout, "drop_rate": drop, "runs": len(rs),
            "observer_completeness_mean": float(np.mean(
                [r["observer_completeness"] for r in rs])),
            "false_removals_mean": float(np.mean(
                [r["false_removals"] for r in rs])),
            "latency_p50_mean": (float(np.mean(
                [r["latency_p50"] for r in rs
                 if r["latency_p50"] is not None]))
                if any(r["latency_p50"] is not None for r in rs) else None),
            "msgs_sent_mean": float(np.mean([r["msgs_sent"] for r in rs])),
        })
    return rows


def write_artifacts(records, rows, out_dir: str,
                    name: str = "phase_sweep") -> None:
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, f"{name}_runs.json"), "w") as fh:
        json.dump(records, fh, indent=1)
    with open(os.path.join(out_dir, f"{name}_grid.csv"), "w") as fh:
        cols = list(rows[0].keys())
        fh.write(",".join(cols) + "\n")
        for r in rows:
            fh.write(",".join(str(r[c]) for c in cols) + "\n")
