// emul_engine: native host simulator core for the `emul_native` backend.
//
// A fresh C++ implementation of the membership protocol + in-memory network
// with the same tick semantics as the Python `emul` backend (the executable
// spec, backends/emul.py) and the reference it mirrors:
//   * two-pass synchronous tick: receives ascending, protocol descending
//     (Application::mp1Run, Application.cpp:121-164);
//   * bounded global message buffer, newest-first intra-tick delivery
//     (EmulNet::ENrecv's top-down swap-remove scan, EmulNet.cpp:144-177);
//   * JOINREQ/JOINREP handshake via the introducer, full-list gossip to
//     FANOUT random targets, TFAIL/TREMOVE sweep, stale-entry withholding
//     (MP1Node.cpp:73-495).
//
// Deliberately NOT a translation of the reference's design:
//   * members are (id, heartbeat, timestamp) in a sorted std::vector per
//     node — integer keys end-to-end (no strcmp on binary addresses:
//     reference defect D5, EmulNet.cpp:154, is structurally impossible);
//   * messages are 24-byte PODs in one reusable buffer — no per-message
//     malloc/free, so the reference's leak-per-message (D4,
//     EmulNet.cpp:156) has no analog;
//   * protocol events (join/remove) stream into a caller-provided buffer;
//     the log-format contract stays in one place (Python's EventLog);
//   * all randomness derives from one caller-provided seed via
//     std::mt19937_64 — runs are reproducible, unlike the reference's
//     random_device-seeded gossip (MP1Node.cpp:450).
//
// Build: g++ -O2 -shared -fPIC (driven by backends/emul_native.py).

#include <algorithm>
#include <cstdint>
#include <random>
#include <vector>

namespace {

struct Msg {
  int32_t src;
  int32_t dst;
  int32_t kind;  // 0 JOINREQ, 1 JOINREP, 2 LIST
  int32_t id;    // payload member id (JOINREQ/LIST)
  int64_t hb;    // payload heartbeat
};

constexpr int32_t KIND_JOINREQ = 0;
constexpr int32_t KIND_JOINREP = 1;
constexpr int32_t KIND_LIST = 2;

// Wire sizes, for buffer accounting parity with the reference
// (MP1Node.cpp:143,247,364; EmulNet.h:23-30).
constexpr int64_t LIST_MSG_SIZE = 19;
constexpr int64_t JOINREQ_MSG_SIZE = 19;
constexpr int64_t JOINREP_MSG_SIZE = 4;
constexpr int64_t EN_MSG_HDR = 16;

struct Entry {
  int32_t id;
  int64_t hb;
  int32_t ts;
};

struct Node {
  int32_t id = 0;  // 1-based (ENinit assigns 1..N, EmulNet.cpp:74)
  bool failed = false;
  bool in_group = false;
  bool started = false;
  int64_t hb = 0;
  std::vector<Entry> members;   // sorted by id
  std::vector<Msg> inbox;       // drained every tick
};

struct Event {
  int32_t kind;     // 0 joined, 1 removed
  int32_t logger;   // 1-based node id doing the logging
  int32_t subject;  // 1-based node id joined/removed
  int32_t tick;
};

struct Sim {
  // config
  int32_t n, total_time, tfail, tremove, fanout;
  int32_t fail_time, drop_start, drop_stop, drop_pct;
  int64_t en_buffsize, max_msg_size;
  int32_t join_mode;   // 0 staggered, 1 batch
  double step_rate;
  // state
  std::vector<Node> nodes;
  std::vector<Msg> net;         // the global bounded buffer
  bool dropmsg = false;
  std::mt19937_64 rng_net, rng_gossip;
  // outputs
  int32_t* sent;                // [n, total_time]
  int32_t* recv;
  Event* events;
  int64_t events_cap, n_events = 0, overflowed = 0;

  int start_tick(int i) const {
    return join_mode == 1 ? 0 : static_cast<int>(step_rate * i);
  }

  void emit(int32_t kind, int32_t logger, int32_t subject, int32_t tick) {
    if (n_events >= events_cap) { overflowed = 1; return; }
    events[n_events++] = Event{kind, logger, subject, tick};
  }

  // ENsend (EmulNet.cpp:87-118): drop on full buffer / oversize / Bernoulli
  // inside the drop window; count only accepted sends.
  void send(int32_t src, int32_t dst, int32_t kind, int32_t id, int64_t hb,
            int64_t size, int t) {
    if (static_cast<int64_t>(net.size()) >= en_buffsize) return;
    if (size + EN_MSG_HDR >= max_msg_size) return;
    if (dropmsg &&
        static_cast<int32_t>(rng_net() % 100) < drop_pct) return;
    net.push_back(Msg{src, dst, kind, id, hb});
    sent[(src - 1) * total_time + t] += 1;
  }

  // ENrecv semantics: scan top-down, swap-remove → newest-first delivery.
  void recv_all(Node& node, int t) {
    for (int64_t i = static_cast<int64_t>(net.size()) - 1; i >= 0; --i) {
      if (net[i].dst == node.id) {
        node.inbox.push_back(net[i]);
        net[i] = net.back();
        net.pop_back();
        recv[(node.id - 1) * total_time + t] += 1;
      }
    }
  }

  // updatelistCallBack (MP1Node.cpp:259-301): strict-increase merge,
  // sorted insert + join event for unknown ids.
  bool update_list(Node& node, int32_t eid, int64_t ehb, int t) {
    auto it = std::lower_bound(
        node.members.begin(), node.members.end(), eid,
        [](const Entry& e, int32_t key) { return e.id < key; });
    if (it != node.members.end() && it->id == eid) {
      if (it->hb < ehb) {
        it->hb = ehb;
        it->ts = t;
      }
      return false;
    }
    node.members.insert(it, Entry{eid, ehb, t});
    emit(0, node.id, eid, t);
    return true;
  }

  void node_start(Node& node, int t) {
    node.started = true;
    node.failed = false;
    node.in_group = false;
    node.hb = 0;
    node.members.clear();
    if (node.id == 1) {  // the introducer (getjoinaddr, Application.cpp:209)
      update_my_pos(node, t);
      node.in_group = true;
    } else {
      send(node.id, 1, KIND_JOINREQ, node.id, node.hb, JOINREQ_MSG_SIZE, t);
    }
  }

  // updateMyPos with the D3 fix: a plain insert-if-absent.
  size_t update_my_pos(Node& node, int t) {
    auto it = std::lower_bound(
        node.members.begin(), node.members.end(), node.id,
        [](const Entry& e, int32_t key) { return e.id < key; });
    if (it == node.members.end() || it->id != node.id)
      it = node.members.insert(it, Entry{node.id, node.hb, t});
    return static_cast<size_t>(it - node.members.begin());
  }

  void node_loop(Node& node, int t) {
    // drain inbox (checkMessages, MP1Node.cpp:208-223)
    std::vector<int32_t> new_nodes;
    for (const Msg& m : node.inbox) {
      switch (m.kind) {
        case KIND_JOINREQ:
          if (update_list(node, m.id, m.hb, t)) new_nodes.push_back(m.id);
          send(node.id, m.id, KIND_JOINREP, 0, 0, JOINREP_MSG_SIZE, t);
          break;
        case KIND_JOINREP:
          node.in_group = true;
          break;
        case KIND_LIST:
          update_list(node, m.id, m.hb, t);
          break;
      }
    }
    node.inbox.clear();
    if (!node.in_group) return;

    // nodeLoopOps (MP1Node.cpp:404-495)
    size_t mypos = update_my_pos(node, t);
    node.hb += 1;  // double increment: own entry holds the odd
    node.members[mypos].hb = node.hb;  // intermediate (MP1Node.cpp:412-414)
    node.hb += 1;
    node.members[mypos].ts = t;

    // TFAIL/TREMOVE sweep: one in-place filtering pass (order-preserving,
    // equivalent to the reference's swap-remove + re-sort).
    int32_t numfailed = 0;
    size_t w = 0;
    for (size_t r = 0; r < node.members.size(); ++r) {
      const Entry& e = node.members[r];
      int difft = t - e.ts;
      if (difft >= tfail) {
        ++numfailed;
        if (difft >= tremove) {
          emit(1, node.id, e.id, t);
          continue;
        }
      }
      node.members[w++] = e;
    }
    node.members.resize(w);

    // gossip targets: this tick's joiners guaranteed, then rejection-sample
    // distinct fresh non-self entries up to the potential bound
    // (MP1Node.cpp:449-489).
    std::vector<int32_t> gossip = new_nodes;
    int64_t numpotential =
        static_cast<int64_t>(node.members.size()) - 1 - numfailed;
    while (static_cast<int64_t>(gossip.size()) < fanout &&
           static_cast<int64_t>(gossip.size()) < numpotential) {
      const Entry& e =
          node.members[rng_gossip() % node.members.size()];
      if (e.id == node.id) continue;
      if (t - e.ts >= tfail) continue;
      if (std::find(gossip.begin(), gossip.end(), e.id) != gossip.end())
        continue;
      gossip.push_back(e.id);
    }

    // sendMemberList: one LIST per fresh entry per target (MP1Node.cpp:360-395).
    for (int32_t target : gossip) {
      for (const Entry& e : node.members) {
        if (t - e.ts >= tfail) continue;
        send(node.id, target, KIND_LIST, e.id, e.hb, LIST_MSG_SIZE, t);
      }
    }
  }
};

}  // namespace

extern "C" {

struct DmConfig {
  int32_t n, total_time, tfail, tremove, fanout;
  int32_t fail_time, drop_start, drop_stop, drop_pct;
  int64_t en_buffsize, max_msg_size;
  int32_t join_mode;
  double step_rate;
  uint64_t seed;
};

// Runs the full simulation.  fail_mask: [n] bytes (1 = crash at fail_time).
// sent/recv: [n * total_time] int32, zeroed by caller.  events:
// [events_cap] records of 4 x int32.  Returns 0 on success, 1 if the event
// buffer overflowed (results truncated).
int dm_run(const DmConfig* cfg, const uint8_t* fail_mask, int32_t* sent,
           int32_t* recv, int32_t* events, int64_t events_cap,
           int64_t* n_events_out) {
  Sim sim;
  sim.n = cfg->n;
  sim.total_time = cfg->total_time;
  sim.tfail = cfg->tfail;
  sim.tremove = cfg->tremove;
  sim.fanout = cfg->fanout;
  sim.fail_time = cfg->fail_time;
  sim.drop_start = cfg->drop_start;
  sim.drop_stop = cfg->drop_stop;
  sim.drop_pct = cfg->drop_pct;
  sim.en_buffsize = cfg->en_buffsize;
  sim.max_msg_size = cfg->max_msg_size;
  sim.join_mode = cfg->join_mode;
  sim.step_rate = cfg->step_rate;
  sim.sent = sent;
  sim.recv = recv;
  sim.events = reinterpret_cast<Event*>(events);
  sim.events_cap = events_cap;
  sim.rng_net.seed(cfg->seed * 0x9E3779B97F4A7C15ULL + 1);
  sim.rng_gossip.seed(cfg->seed * 0xC2B2AE3D27D4EB4FULL + 2);

  sim.nodes.resize(sim.n);
  for (int i = 0; i < sim.n; ++i) sim.nodes[i].id = i + 1;
  sim.net.reserve(static_cast<size_t>(sim.en_buffsize));

  for (int t = 0; t < sim.total_time; ++t) {
    // pass 1: receive, ascending (Application.cpp:125-135)
    for (int i = 0; i < sim.n; ++i) {
      Node& node = sim.nodes[i];
      if (t > sim.start_tick(i) && node.started && !node.failed)
        sim.recv_all(node, t);
    }
    // pass 2: start / act, descending (Application.cpp:138-163)
    for (int i = sim.n - 1; i >= 0; --i) {
      Node& node = sim.nodes[i];
      if (t == sim.start_tick(i)) {
        sim.node_start(node, t);
      } else if (t > sim.start_tick(i) && node.started && !node.failed) {
        sim.node_loop(node, t);
      }
    }
    // failure + drop-window injection, end of tick (Application::fail)
    if (sim.drop_start >= 0 && t == sim.drop_start) sim.dropmsg = true;
    if (t == sim.fail_time) {
      for (int i = 0; i < sim.n; ++i)
        if (fail_mask[i]) sim.nodes[i].failed = true;
    }
    if (sim.drop_stop >= 0 && t == sim.drop_stop) sim.dropmsg = false;
  }

  *n_events_out = sim.n_events;
  return sim.overflowed ? 1 : 0;
}

}  // extern "C"
