"""Vectorized gossip-target sampling.

The reference picks gossip targets by rejection sampling: draw uniform
indices into the member list, skip self / suspected-failed / duplicates,
until FANOUT distinct targets (MP1Node.cpp:449-489).  The resulting *set* is
a uniform random k-subset of the eligible entries.  On TPU we produce the
identically-distributed subset in one shot: attach an iid uniform score to
every eligible slot and keep the k smallest — no data-dependent loop, fully
vmappable, identical distribution.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_k_distinct(key: jax.Array, eligible: jax.Array, k: jax.Array) -> jax.Array:
    """Select a uniform random subset of ``k[i]`` True positions per row.

    Args:
      key: PRNG key.
      eligible: ``[N, M]`` bool — candidate positions per row.
      k: ``[N]`` int — subset size per row (values beyond the number of
        eligible positions select all of them).

    Returns:
      ``[N, M]`` bool mask with ``min(k[i], eligible[i].sum())`` True
      positions per row, uniformly distributed over eligible subsets.
    """
    n, m = eligible.shape
    scores = jax.random.uniform(key, (n, m))
    scores = jnp.where(eligible, scores, 2.0)  # ineligible sorts last
    sorted_scores = jnp.sort(scores, axis=1)
    kth = jnp.take_along_axis(
        sorted_scores, jnp.clip(k - 1, 0, m - 1)[:, None], axis=1)
    return eligible & (scores <= kth) & (k > 0)[:, None]
