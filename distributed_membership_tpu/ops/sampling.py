"""Vectorized gossip-target sampling.

The reference picks gossip targets by rejection sampling: draw uniform
indices into the member list, skip self / suspected-failed / duplicates,
until FANOUT distinct targets (MP1Node.cpp:449-489).  The resulting *set* is
a uniform random k-subset of the eligible entries.  On TPU we produce the
identically-distributed subset in one shot: attach an iid uniform score to
every eligible slot and keep the k smallest — no data-dependent loop, fully
vmappable, identical distribution.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def sample_k_distinct(key: jax.Array, eligible: jax.Array, k: jax.Array,
                      scores: jax.Array | None = None) -> jax.Array:
    """Select a uniform random subset of ``k[i]`` True positions per row.

    Args:
      key: PRNG key (ignored if ``scores`` is given).
      eligible: ``[N, M]`` bool — candidate positions per row.
      k: ``[N]`` int — subset size per row (values beyond the number of
        eligible positions select all of them).
      scores: optional pre-drawn iid uniform ``[N, M]`` scores — used by the
        sharded backend, which draws per-shard ``[L, N]`` scores by default
        (and, in its ``replicated_rng`` bit-parity debug mode, the full
        tensor replicated + row-sliced so selections match the dense
        backend's exactly).

    Returns:
      ``[N, M]`` bool mask with ``min(k[i], eligible[i].sum())`` True
      positions per row, uniformly distributed over eligible subsets.
    """
    n, m = eligible.shape
    if scores is None:
        scores = jax.random.uniform(key, (n, m))
    scores = jnp.where(eligible, scores, 2.0)  # ineligible sorts last
    # Rank-based selection (double argsort): exactly k positions even under
    # float ties, with the same lowest-index-first tie-break as lax.top_k —
    # keeping this spec path set-identical to sample_k_indices.
    order = jnp.argsort(scores, axis=1, stable=True)
    rank = jnp.argsort(order, axis=1, stable=True)
    return eligible & (rank < k[:, None])


def sample_k_indices(key: jax.Array, eligible: jax.Array, k: jax.Array,
                     k_max: int, scores: jax.Array | None = None):
    """Index-form of :func:`sample_k_distinct` via ``lax.top_k``.

    Selects the same uniform k-subset (identical scores → identical set) but
    returns it as ``([N, k_max] indices, [N, k_max] valid mask)`` — the form
    the O(N*K*M) scatter-based gossip delivery wants, avoiding any dense
    [senders, receivers] mask.  ``k_max`` is the static bound on ``k``
    (the FANOUT protocol constant).

    Cost per row is O(M * k_max) (top_k) instead of O(M log M) (full sort).
    """
    n, m = eligible.shape
    if scores is None:
        scores = jax.random.uniform(key, (n, m))
    neg = jnp.where(eligible, -scores, -2.0)  # ineligible last under top_k
    top_vals, top_idx = jax.lax.top_k(neg, min(k_max, m))
    arange_k = jnp.arange(top_idx.shape[1])
    valid = (arange_k[None, :] < k[:, None]) & (top_vals > -2.0)
    return top_idx, valid
