"""Multi-tick residency: the T-tick megakernel scan and the shrunk carry.

``MEGA_TICKS: T`` (config.py) fuses T protocol ticks per outer scan
iteration: the outer ``lax.scan`` steps over ``[nblk, T, ...]`` blocks of
the per-tick operands (tick indices, RNG keys or hoisted RNG plans) and
each block runs the SAME per-tick step function in an inner ``lax.scan``
whose carry never leaves the device between ticks — XLA keeps the inner
loop's live state resident (VMEM where it fits, without a round trip
through the scan-boundary copy machinery either way), so the per-tick
scan overhead and the carry materialization amortize over T.  The carry
crosses the outer scan boundary only once per T-block — exactly the
boundary ``CHECKPOINT_EVERY`` already defines (T tiles the segment;
backends/tpu_hash.make_config validates), so checkpoint/resume, the
service boundary hook, and ``EVENT_MODE: full`` flushes keep their
existing semantics unchanged.

``T <= 1`` (and segments shorter than one block) bypass the block
machinery entirely and run the plain per-tick ``lax.scan`` — the
``MEGA_TICKS: 1`` program is the PR-8 fused program BY CONSTRUCTION,
which tests/test_hlo_census.py pins op-count-identical.  A tail segment
whose length is not a multiple of T runs its ``L % T`` remainder as a
plain scan after the blocks (a smaller block, same step stream).

The **shrunk carry** (``MEGA_PACK``) cuts the bytes that cross each
T-block boundary: the timestamp planes (``view_ts`` [N, S] i32 and
``self_hb`` [N] i32 — values bounded by the run's tick count, plus the
-1/"never" sentinels) are packed two-per-u32 as 16-bit lanes with a +1
offset, and every bool plane (liveness/suspicion/handshake masks) is
bit-packed 32-per-u32.  Reconstruction is bit-exact whenever the 16-bit
bound holds (:func:`pack_fits`); the bound is STATIC — heartbeats
advance +2/tick from 1 and timestamps are tick values, so the proven
bound is the run's effective total tick count, checked host-side at
``make_config``/``run_scan`` time.  Overflow "widening" is therefore a
static variant selection: an auto (``-1``) pack silently downgrades to
the wide carry when the bound does not fit; a pinned ``MEGA_PACK: 1``
raises loudly (auto never raises — the FUSED_* contract).  The ``view``
plane (u32 ``hb * N + id + 1``) is NOT packable — its payload spans the
full 32 bits at any interesting N — and the mailboxes are transient
u32 payloads; both stay wide.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.tree_util import tree_flatten_with_path, tree_unflatten

U32 = jnp.uint32
I32 = jnp.int32

# Largest effective run length (in ticks) whose timestamp/heartbeat
# values provably fit the 16-bit packed lanes: heartbeats advance +2 per
# tick from 1 (reference double increment) and view_ts holds tick
# values, so every packable value is <= 2*total + 1; with the +1
# sentinel offset the packed lane needs 2*total + 2 < 2**16.  A small
# margin keeps the bound conservative against off-by-a-few evolutions.
PACK_SAFE_TICKS = (1 << 15) - 16

# i32 state fields whose values are tick/heartbeat-bounded and may carry
# as 16-bit lanes.  Keyed by FIELD NAME so the natural, folded (reshaped
# planes, same names) and sharded (same names minus wf_prev) twins all
# route through one codec with no per-layout special cases.
_TS16_FIELDS = frozenset({"view_ts", "self_hb"})


def pack_fits(total_ticks: int) -> bool:
    """Does the 16-bit packed carry provably cover a run of this many
    effective ticks?  (Static host-side check — see module docstring.)"""
    return 0 <= int(total_ticks) <= PACK_SAFE_TICKS


def fits16(x) -> bool:
    """Dynamic twin of :func:`pack_fits` for tests: do these values
    actually survive the u16+1 round trip?  (The production path never
    needs this — the static bound decides the variant.)"""
    import numpy as np

    a = np.asarray(x).astype(np.int64)
    return bool(((a + 1 >= 0) & (a + 1 < (1 << 16))).all())


def _leaf_name(path) -> str:
    """Last attribute name on a tree path ('' when unnamed)."""
    for entry in reversed(path):
        name = getattr(entry, "name", None)
        if name is not None:
            return str(name)
    return ""


def _pack_bits(a):
    """[...] bool -> ([ceil(size/32)] u32 words, static spec)."""
    flat = a.reshape(-1)
    m = flat.shape[0]
    pad = (-m) % 32
    if pad:
        flat = jnp.concatenate(
            [flat, jnp.zeros((pad,), dtype=jnp.bool_)])
    lanes = flat.reshape(-1, 32).astype(U32)
    shifts = jnp.arange(32, dtype=U32)[None, :]
    return jnp.sum(lanes << shifts, axis=1, dtype=U32)


def _unpack_bits(words, shape):
    size = 1
    for d in shape:
        size *= d
    shifts = jnp.arange(32, dtype=U32)[None, :]
    bits = (words[:, None] >> shifts) & U32(1)
    return bits.reshape(-1)[:size].astype(jnp.bool_).reshape(shape)


def _pack_u16(a):
    """[..., d] i32 in [-1, 2**16 - 2] -> [..., ceil(d/2)] u32 lanes."""
    u = (a + 1).astype(U32)
    d = u.shape[-1]
    if d % 2:
        u = jnp.concatenate(
            [u, jnp.zeros(u.shape[:-1] + (1,), dtype=U32)], axis=-1)
    pair = u.reshape(u.shape[:-1] + (-1, 2))
    return pair[..., 0] | (pair[..., 1] << U32(16))


def _unpack_u16(words, shape):
    lo = words & U32(0xFFFF)
    hi = words >> U32(16)
    u = jnp.stack([lo, hi], axis=-1).reshape(words.shape[:-1] + (-1,))
    return u[..., :shape[-1]].astype(I32) - 1


def make_codec(state, pack16: bool):
    """(pack, unpack) for a carry pytree: bool leaves bit-packed
    32-per-u32 (always exact), :data:`_TS16_FIELDS` i32 leaves packed as
    16-bit pairs when ``pack16`` (exact under the static tick bound —
    module docstring), everything else identity.  Classification uses
    only static leaf metadata (field name, dtype, shape), so the codec
    builds the same way from live tracers inside a jit/shard_map trace
    as from host arrays.
    """
    leaves, treedef = tree_flatten_with_path(state)
    plan = []
    for path, leaf in leaves:
        name = _leaf_name(path)
        shape = tuple(leaf.shape)
        if leaf.dtype == jnp.bool_:
            plan.append(("bits", shape))
        elif pack16 and name in _TS16_FIELDS and leaf.dtype == I32:
            plan.append(("u16", shape))
        else:
            plan.append(("raw", shape))

    def pack(st):
        out = []
        for (kind, _), (_, leaf) in zip(plan,
                                        tree_flatten_with_path(st)[0]):
            if kind == "bits":
                out.append(_pack_bits(leaf))
            elif kind == "u16":
                out.append(_pack_u16(leaf))
            else:
                out.append(leaf)
        return tuple(out)

    def unpack(packed):
        out = []
        for (kind, shape), leaf in zip(plan, packed):
            if kind == "bits":
                out.append(_unpack_bits(leaf, shape))
            elif kind == "u16":
                out.append(_unpack_u16(leaf, shape))
            else:
                out.append(leaf)
        return tree_unflatten(treedef, out)

    return pack, unpack


def carry_bytes(state, pack16: bool = True) -> dict:
    """Boundary-crossing byte accounting for PERF.md / the bench row:
    ``full`` is the wide carry, ``packed`` what the shrunk carry moves
    per T-block boundary under this codec.  Works on arrays or
    ShapeDtypeStructs (an ``eval_shape`` carry costs nothing)."""
    leaves, _ = tree_flatten_with_path(state)
    full = packed = 0
    for path, leaf in leaves:
        size = 1
        for d in leaf.shape:
            size *= d
        nbytes = size * jnp.dtype(leaf.dtype).itemsize
        full += nbytes
        if leaf.dtype == jnp.bool_:
            packed += 4 * (-(-size // 32))
        elif pack16 and _leaf_name(path) in _TS16_FIELDS \
                and leaf.dtype == I32:
            last = leaf.shape[-1] if leaf.shape else 1
            packed += nbytes // last * (-(-last // 2))
        else:
            packed += nbytes
    return {"full": int(full), "packed": int(packed)}


def mega_scan(body, state, xs, t_block: int, pack16: bool = False):
    """``lax.scan(body, state, xs)`` restructured into T-tick blocks.

    Drop-in replacement for the segment runners' per-tick scan: same
    (carry, ys) contract, bit-identical trajectory and outputs.  The
    leading axis L of ``xs`` splits into ``L // T`` blocks driven by an
    outer scan whose carry is the (optionally shrunk — ``pack16``)
    packed carry, plus an ``L % T`` plain-scan tail; ys leaves are
    emitted per inner tick and restitched to the flat ``[L, ...]`` shape
    the chunked driver and telemetry sinks already consume.

    ``t_block <= 1`` or ``L <= T`` returns the plain scan unchanged —
    the op-count-identity anchor the census pins.
    """
    length = jax.tree_util.tree_leaves(xs)[0].shape[0]
    t = int(t_block)
    if t <= 1 or length <= t:
        return jax.lax.scan(body, state, xs)
    nblk, tail = divmod(length, t)

    head = jax.tree.map(
        lambda a: a[:nblk * t].reshape((nblk, t) + a.shape[1:]), xs)
    pack, unpack = make_codec(state, pack16)

    def block(packed, xs_blk):
        st, ys = jax.lax.scan(body, unpack(packed), xs_blk)
        return pack(st), ys

    packed, ys_blocks = jax.lax.scan(block, pack(state), head)
    state = unpack(packed)
    ys = jax.tree.map(
        lambda a: a.reshape((nblk * t,) + a.shape[2:]), ys_blocks)
    if tail:
        xs_tail = jax.tree.map(lambda a: a[nblk * t:], xs)
        state, ys_tail = jax.lax.scan(body, state, xs_tail)
        ys = jax.tree.map(
            lambda a, b: jnp.concatenate([a, b], axis=0), ys, ys_tail)
    return state, ys
