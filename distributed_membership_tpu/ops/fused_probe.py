"""Fused probe-window traversal: window update + aggregate/telemetry
reductions in ONE pass over the ``[N, S]`` state.

After receive and gossip were fused (ops/fused_receive, ops/fused_gossip)
the remaining per-tick full-tensor passes in the ring step are the probe
stage and the reductions that read the same planes right after it
(backends/tpu_hash.py make_step):

* the probe-window read — a P-column cyclic band of the post-receive
  view, rolled to the tick's pointer, validated (occupied, not self,
  observer act) and recorded as ``probe_ids1`` for the two-tick ack
  pipeline (whose packed-u32 single-gather application already rides the
  fused RECEIVE kernel via the ack candidate plane);
* the FastAgg per-fail-id compare passes over the removal plane
  (observability/aggregates.update_fast_agg);
* the telemetry staleness/suspicion bucket counts over the post-receive
  ``view_ts`` (observability/timeline.build_tick_hist) when
  ``TELEMETRY: hist`` is on.

These all traverse the same [N, S] (or folded [N*S/128, 128]) planes, so
the kernels here run them as one grid walk: per row block the view is
read once, the rolled window ids come out as a plane, and the agg/hist
reductions ride as [rows, 1]/[rows, 8] column partials plus (folded) one
any-plane.  Integer sums and or-reductions are order-free, so every
partial reduces outside to values bit-equal to the unfused lowering.

What stays OUTSIDE the kernel — by design, for bit-exactness:

* drop coins / scenario cuts (``PROBE`` leg): suppression happens in the
  cheap [N, P] window space with the exact ops/rng_plan.py streams the
  jnp path draws — the kernel only pre-validates (occupied, not self,
  act), and every suppressed position is consulted nowhere else;
* the packed probe-table gather (ops._pack_probe_table consumers): an
  [N]-class gather Mosaic TC cannot express — it remains the step's ONE
  permitted big gather (tests/test_hlo_census.py pins that);
* the folded window compaction gather (``window_idx``): pre-existing,
  and it now gathers the kernel's VALIDATED id plane instead of the raw
  window — same gather count, one fewer plane pass.

Routing: all four ring twins (tpu_hash natural/FOLDED and their sharded
twins) call these kernels behind the ``FUSED_PROBE: -1|0|1`` conf knob;
auto resolution rides the fusegate correctness families ``fused_probe``
/ ``folded_fused_probe_s{S}`` (+ ``sharded_`` prefixes) like the other
kernels (runtime/fusegate.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from distributed_membership_tpu.ops.fused_receive import _pick_block

I32 = jnp.int32
U32 = jnp.uint32
LANES = 128

# h_staleness / h_suspicion geometry (observability/timeline.py).  The
# bucket width is a power of two so the in-kernel bucket index is a shift
# (Mosaic-safe); the import is asserted at module load so a width change
# cannot silently fork the counts.
from distributed_membership_tpu.observability.timeline import (  # noqa: E402
    HIST_BUCKETS, STALENESS_BUCKET_TICKS)

_NB = HIST_BUCKETS["h_staleness"]
assert STALENESS_BUCKET_TICKS & (STALENESS_BUCKET_TICKS - 1) == 0
_BUCKET_SHIFT = STALENESS_BUCKET_TICKS.bit_length() - 1


def probe_fused_supported(n: int, s: int, p_cnt: int) -> bool:
    """Natural-layout eligibility: whole-lane rows (same tiling rule as
    the other kernels) and a window narrower than the view."""
    return s % 128 == 0 and n >= 8 and 0 < p_cnt < s


def _bucket_rows(vals, mask):
    """[b, 8] per-row staleness bucket counts: lane-axis reductions only
    (sublane reductions are the less-robust Mosaic path).  ``vals`` are
    non-negative tick deltas; bucket = clip(vals >> shift, 0, 7), the
    same index :func:`timeline.hist_bucket_counts` computes with ``//``
    (clip spelled as compare+select — arith.maxsi/minsi are not relied
    on, mirroring the umax story in ops/fused_receive)."""
    b = jax.lax.shift_right_arithmetic(vals, _BUCKET_SHIFT)
    b = jnp.where(b > _NB - 1, _NB - 1, b)
    b = jnp.where(b < 0, 0, b)
    cols = [((b == k) & mask).astype(I32).sum(axis=1, dtype=I32,
                                              keepdims=True)
            for k in range(_NB)]
    return jnp.concatenate(cols, axis=1)


def _probe_body(n, tfail, fail_ids, want_hist, want_agg,
                t, rolled, node, actb, ts, rm):
    """Shared per-block computation (jnp ops only) for both layouts.

    ``rolled`` is the view block already rolled so that lane (segment
    position, folded) 0 holds the window pointer's slot; ``node`` the
    per-element observer id; ``actb`` the observer-act bool.  Returns
    (ids, stale_rows, susp_rows, det_cols, det_any, rm_cnt) with the
    optional pieces None when the corresponding want_* is off.
    """
    pres = rolled > 0
    w_id = ((rolled - U32(1)) % U32(n)).astype(I32)
    valid = pres & (w_id != node) & actb
    ids = jnp.where(valid, w_id.astype(U32) + U32(1), U32(0))

    stale_rows = susp_rows = None
    if want_hist:
        difft = t - ts
        # presence must match the UNROLLED view — but a roll is a
        # permutation of each row/segment and the bucket counts only see
        # the element multiset, so counting on the rolled plane with the
        # equally-rolled ts is bit-equal.  ``ts`` arrives pre-rolled.
        presv = rolled > 0
        stale_rows = _bucket_rows(difft, presv)
        susp_rows = _bucket_rows(difft - tfail,
                                 presv & (difft >= tfail))

    det_cols = det_any = rm_cnt = None
    if want_agg:
        rm_cnt = (rm >= 0).astype(I32).sum(axis=1, dtype=I32,
                                           keepdims=True)
        det_cols = [(rm == f).astype(I32).sum(axis=1, dtype=I32,
                                              keepdims=True)
                    for f in fail_ids]
        if fail_ids:
            da = rm == fail_ids[0]
            for f in fail_ids[1:]:
                da = da | (rm == f)
            det_any = da.astype(I32)
    return ids, stale_rows, susp_rows, det_cols, det_any, rm_cnt


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4, 5, 6, 7))
def probe_window_fused(n: int, s: int, p_cnt: int, tfail: int,
                       fail_ids: tuple, want_hist: bool, want_agg: bool,
                       interpret: bool, t, ptr, row0,
                       view: jax.Array, view_ts, act, rm_ids):
    """Natural-layout fused probe traversal.

    Args:
      t, ptr, row0: traced scalars — tick, window pointer
        (``(t*P) mod S``), and the first row's GLOBAL id (0 single-chip;
        the shard row offset on the sharded twin).
      view:    [rows, S] u32 post-receive view.
      view_ts: [rows, S] i32 post-receive timestamps (None unless
               ``want_hist``).
      act:     [rows] bool observer liveness.
      rm_ids:  [rows, S] i32 removal plane from the receive pass (None
               unless ``want_agg``).

    Returns a dict: ``ids`` [rows, ceil128(P)] u32 pre-suppression probe
    ids (slice ``[:, :P]``; 0 = invalid — drop coins / scenario cuts
    apply OUTSIDE in [N, P] space), plus ``stale_rows``/``susp_rows``
    ([rows, 8] i32 per-row bucket partials) when ``want_hist`` and
    ``det_cols`` (tuple of [rows, 1] per fail id), ``rm_cnt`` ([rows, 1])
    when ``want_agg``.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows = view.shape[0]
    b = _pick_block(rows)
    grid = (rows // b,)
    wp = ((p_cnt + LANES - 1) // LANES) * LANES
    n_fail = len(fail_ids) if want_agg else 0

    def kernel(sc_ref, view_ref, *rest):
        rest = list(rest)
        ts_ref = rest.pop(0) if want_hist else None
        act_ref = rest.pop(0)
        rm_ref = rest.pop(0) if want_agg else None
        outs = rest
        i = pl.program_id(0)
        t_k, ptr_k, row0_k = sc_ref[0], sc_ref[1], sc_ref[2]
        c = jax.lax.rem(s - ptr_k, s)
        rolled = pltpu.roll(view_ref[:], c, axis=1)
        node = (row0_k + i * b
                + jax.lax.broadcasted_iota(I32, (b, s), 0))
        actb = act_ref[:] != 0
        ts = (pltpu.roll(ts_ref[:], c, axis=1) if want_hist else None)
        rm = rm_ref[:] if want_agg else None
        ids, stale_r, susp_r, det_cols, _, rm_cnt = _probe_body(
            n, tfail, fail_ids, want_hist, want_agg,
            t_k, rolled, node, actb, ts, rm)
        k = 0
        outs[k][:] = ids[:, :wp]
        k += 1
        if want_hist:
            outs[k][:] = stale_r
            outs[k + 1][:] = susp_r
            k += 2
        if want_agg:
            outs[k][:] = rm_cnt
            k += 1
            for d in det_cols:
                outs[k][:] = d
                k += 1

    row_spec = pl.BlockSpec((b, s), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    col_spec = pl.BlockSpec((b, 1), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    hist_spec = pl.BlockSpec((b, _NB), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM), row_spec]
    operands = [jnp.stack([jnp.asarray(t, I32), jnp.asarray(ptr, I32),
                           jnp.asarray(row0, I32)]), view]
    if want_hist:
        in_specs.append(row_spec)
        operands.append(view_ts)
    in_specs.append(col_spec)
    operands.append(act.astype(I32)[:, None])
    if want_agg:
        in_specs.append(row_spec)
        operands.append(rm_ids)

    out_specs = [pl.BlockSpec((b, wp), lambda i: (i, 0),
                              memory_space=pltpu.VMEM)]
    out_shape = [jax.ShapeDtypeStruct((rows, wp), U32)]
    if want_hist:
        out_specs += [hist_spec, hist_spec]
        out_shape += [jax.ShapeDtypeStruct((rows, _NB), I32)] * 2
    if want_agg:
        out_specs += [col_spec] * (1 + n_fail)
        out_shape += [jax.ShapeDtypeStruct((rows, 1), I32)] * (1 + n_fail)

    from distributed_membership_tpu.observability.timeline import (
        PHASE_PROBE)
    with jax.named_scope(PHASE_PROBE):
        out = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shape,
            interpret=interpret,
        )(*operands)
    out = list(out)
    res = {"ids": out.pop(0)}
    if want_hist:
        res["stale_rows"] = out.pop(0)
        res["susp_rows"] = out.pop(0)
    if want_agg:
        res["rm_cnt"] = out.pop(0)
        res["det_cols"] = tuple(out[:n_fail])
    return res


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4, 5, 6, 7))
def probe_folded_window_fused(n: int, s: int, p_cnt: int, tfail: int,
                              fail_ids: tuple, want_hist: bool,
                              want_agg: bool, interpret: bool,
                              t, ptr, row0,
                              view: jax.Array, view_ts, actp, rm_ids):
    """Folded-layout fused probe traversal ([rows, 128] planes, F = 128/S
    nodes per row — backends/tpu_hash_folded.py layout contract).

    Same contract as :func:`probe_window_fused` with two layout
    differences: the window roll is SEGMENT-wise (roll_slots — spelled
    as the two-roll position select, as in ops/fused_folded), and the
    validated ``ids`` come back as a full S-folded [rows, 128] plane —
    the caller compacts the window positions with its pre-existing
    ``window_idx`` gather (same gather count as the unfused path).  When
    ``want_agg``, an extra ``det_any`` [rows, 128] i32 plane marks
    per-ELEMENT fail-id removals (per-node any needs the segment-aware
    rowany reduction the backend owns).  ``actp``/``rm_ids`` are folded
    planes; ``row0`` is the shard's first global NODE id.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows = view.shape[0]
    f = LANES // s
    b = _pick_block(rows)
    grid = (rows // b,)
    n_fail = len(fail_ids) if want_agg else 0

    def _seg_roll(x, c):
        lane = jax.lax.broadcasted_iota(I32, x.shape, 1)
        pos = jax.lax.rem(lane, s)
        return jnp.where(pos < c, pltpu.roll(x, c + LANES - s, axis=1),
                         pltpu.roll(x, c, axis=1))

    def kernel(sc_ref, view_ref, *rest):
        rest = list(rest)
        ts_ref = rest.pop(0) if want_hist else None
        actp_ref = rest.pop(0)
        rm_ref = rest.pop(0) if want_agg else None
        outs = rest
        i = pl.program_id(0)
        t_k, ptr_k, row0_k = sc_ref[0], sc_ref[1], sc_ref[2]
        c = jax.lax.rem(s - ptr_k, s)
        rolled = _seg_roll(view_ref[:], c)
        lane = jax.lax.broadcasted_iota(I32, (b, LANES), 1)
        prow = jax.lax.broadcasted_iota(I32, (b, LANES), 0)
        node = row0_k + (i * b + prow) * f + lane // s
        actb = actp_ref[:] != 0
        ts = _seg_roll(ts_ref[:], c) if want_hist else None
        rm = rm_ref[:] if want_agg else None
        ids, stale_r, susp_r, det_cols, det_any, rm_cnt = _probe_body(
            n, tfail, fail_ids, want_hist, want_agg,
            t_k, rolled, node, actb, ts, rm)
        k = 0
        outs[k][:] = ids
        k += 1
        if want_hist:
            outs[k][:] = stale_r
            outs[k + 1][:] = susp_r
            k += 2
        if want_agg:
            outs[k][:] = rm_cnt
            k += 1
            for d in det_cols:
                outs[k][:] = d
                k += 1
            if n_fail:
                outs[k][:] = det_any

    row_spec = pl.BlockSpec((b, LANES), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    col_spec = pl.BlockSpec((b, 1), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    hist_spec = pl.BlockSpec((b, _NB), lambda i: (i, 0),
                             memory_space=pltpu.VMEM)
    in_specs = [pl.BlockSpec(memory_space=pltpu.SMEM), row_spec]
    operands = [jnp.stack([jnp.asarray(t, I32), jnp.asarray(ptr, I32),
                           jnp.asarray(row0, I32)]), view]
    if want_hist:
        in_specs.append(row_spec)
        operands.append(view_ts)
    in_specs.append(row_spec)
    operands.append(actp.astype(I32))
    if want_agg:
        in_specs.append(row_spec)
        operands.append(rm_ids)

    out_specs = [row_spec]
    out_shape = [jax.ShapeDtypeStruct((rows, LANES), U32)]
    if want_hist:
        out_specs += [hist_spec, hist_spec]
        out_shape += [jax.ShapeDtypeStruct((rows, _NB), I32)] * 2
    if want_agg:
        out_specs += [col_spec] * (1 + n_fail)
        out_shape += [jax.ShapeDtypeStruct((rows, 1), I32)] * (1 + n_fail)
        if n_fail:
            out_specs.append(row_spec)
            out_shape.append(jax.ShapeDtypeStruct((rows, LANES), I32))

    from distributed_membership_tpu.observability.timeline import (
        PHASE_PROBE)
    with jax.named_scope(PHASE_PROBE):
        out = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=in_specs,
            out_specs=out_specs,
            out_shape=out_shape,
            interpret=interpret,
        )(*operands)
    out = list(out)
    res = {"ids": out.pop(0)}
    if want_hist:
        res["stale_rows"] = out.pop(0)
        res["susp_rows"] = out.pop(0)
    if want_agg:
        res["rm_cnt"] = out.pop(0)
        res["det_cols"] = tuple(out[:n_fail])
        out = out[n_fail:]
        if n_fail:
            res["det_any"] = out.pop(0)
    return res
