"""Fused circulant-gossip delivery: all ``fanout`` shifts in ONE Pallas
traversal of the mailbox.

The ring exchange (backends/tpu_hash.py make_step, 'ring' mode) delivers
gossip by, per shift ``r_j``: mask the sender payload, roll rows by
``r_j``, roll columns by ``r_j * STRIDE mod S``, and max into ``mail`` —
at fanout F that is ~3F full [N, S] HBM passes, the majority of the
per-tick budget after the receive pass was fused (PERF.md "The Pallas
story").  This kernel is output-stationary instead: the grid walks
``(mail block, shift)`` with the mail block resident in VMEM across all F
shifts, and the *input* block index is computed from the shift via scalar
prefetch — receiver rows ``[iB, iB+B)`` need sender rows
``[iB - r_j, iB - r_j + B) mod N``, which always lie inside two adjacent
payload blocks; an in-VMEM dynamic row slice assembles them and a dynamic
lane roll applies the column alignment.  HBM traffic drops to one
read+write of mail plus 2F block-reads of payload: ~(2F + 2) passes, and
no [N, S] intermediate is ever materialized.

Supported when (enforced by :func:`gossip_fused_supported`):

* ``S % 128 == 0`` — whole-lane rows (same tiling rule as fused_receive);
* ``(N * STRIDE) % S == 0`` — the wrapped/unwrapped receiver rows share
  one column shift, matching the jnp path's single-roll fast case
  (tpu_hash.py make_step: "they coincide iff N*STRIDE % S == 0").

Message drops, scenario link-flakes, and drop windows all compose: the
per-shift keep decisions are never drawn in-kernel (replicating the RNG
stream inside Mosaic would fork the semantics) — the step computes them
OUTSIDE from the ops/rng_plan.py batched coin streams, exactly as the
jnp shift loop does, and hands them to the kernel as a stacked
``masks [K, N, S]`` input.  The kernel fetches mask blocks with the same
scalar-prefetch index maps as the payload blocks and zeroes non-kept
sender entries in VMEM, so the payload itself stays a SINGLE unmasked
[N, S] tensor (no per-shift [K, N, S] payload copies) and the delivered
bits are bit-identical to the unfused path by construction.

Semantics are pinned bit-exactly against the jnp shift loop in interpret
mode (tests/test_fused_gossip.py) and end-to-end via the FUSED_GOSSIP
conf key; the real Mosaic lowering is gated by scripts/tpu_correctness.py
on hardware, like the receive kernel.

Reference lineage: the delivery being fused is the TPU-native lowering of
EmulNet message delivery + the LIST gossip burst
(/root/reference/EmulNet.cpp:87-118, MP1Node.cpp:360-402); the circulant
redesign itself is documented at tpu_hash.make_step.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from distributed_membership_tpu.ops.fused_receive import _pick_block, umax
from distributed_membership_tpu.ops.view_merge import STRIDE

I32 = jnp.int32
U32 = jnp.uint32


def gossip_fused_supported(n: int, s: int) -> bool:
    """Lane tiling + single-column-shift circulant case (module docstring)."""
    return s % 128 == 0 and n >= 8 and (n * STRIDE) % s == 0


def _lo_block_idx(i, b: int, rows: int, shift):
    """Block index holding the FIRST sender row for output block ``i``
    under a row shift (sender rows start at ``(i*b - shift) mod rows``;
    shift in [0, rows) so one +rows keeps the dividend non-negative).
    Shared by both kernels' scalar-prefetch index maps — the wrap math
    is the subtlest part and must not fork."""
    return jax.lax.rem(i * b - shift + rows, rows) // b


def _assemble_senders(plo, phi, off, b: int):
    """Concatenate the two fetched adjacent blocks and extract the B
    sender rows starting at in-block offset ``off``.  Mosaic TC has no
    ``dynamic_slice`` lowering (the real-chip correctness rung caught
    this — interpret mode accepts it), so the dynamic start is applied
    as a dynamic sublane rotate (``pltpu.roll`` on axis 0, which Mosaic
    lowers as tpu.dynamic_rotate) bringing row ``off`` to row 0,
    followed by a static slice."""
    from jax.experimental.pallas import tpu as pltpu

    rows2b = jnp.concatenate([plo, phi], axis=0)
    return pltpu.roll(rows2b, 2 * b - off, axis=0)[:b]


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4))
def gossip_fused_stacked(rows: int, s: int, k_max: int, single_col: bool,
                         interpret: bool, mail: jax.Array,
                         payloads: jax.Array, c_shifts: jax.Array,
                         s1s: jax.Array, s2s: jax.Array,
                         masks: jax.Array | None = None) -> jax.Array:
    """Sharded-ring variant: accumulate K PRE-ROUTED payloads into mail.

    The torus exchange (tpu_hash_sharded.make_ring_sharded_step) routes
    each shift's payload across shards with a ``ppermute`` (wire traffic
    the kernel cannot absorb), then pays ~3 local [L, S] passes per shift
    for the intra-shard row roll + column alignment + max.  This kernel
    replaces that local tail: the grid walks (mail block, shift) with the
    mail block VMEM-resident, sender rows arrive via scalar-prefetch
    block indexing from the stacked ``payloads [K, L, S]``, and the
    column alignment applies ``s1s[j]`` — or the
    ``s2s[j]``/receiver-row select pair when ``single_col`` is False
    (the (L*STRIDE) % S != 0 wrapped-row case).  ~(2K + 2) local passes
    instead of ~3K.

    Drop/flake handling: either pre-mask the stack (the sharded ring
    must — the keep coins are sender-row-indexed, so they have to be
    applied BEFORE the payload rides the ppermute wire) and leave
    ``masks`` None, or pass ``masks [K, L, S]`` i32 (nonzero = deliver)
    and the kernel zeroes non-kept entries in VMEM after assembling the
    sender rows.  With ``masks`` the payload stack may be SHARED across
    shifts: ``payloads [1, L, S]`` is broadcast to every j, which is how
    the single-chip lossy branch avoids materializing K payload copies.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b = _pick_block(rows)
    nb = rows // b
    shared_payload = payloads.shape[0] == 1

    def _lo_block(i, j, c, s1v, s2v):
        return _lo_block_idx(i, b, rows, c[j])

    def _payload_j(i, j, c, s1v, s2v):
        return 0 if shared_payload else j

    def kernel(c_ref, s1_ref, s2_ref, mail_ref, plo_ref, phi_ref,
               *rest):
        out_ref = rest[-1]
        i, j = pl.program_id(0), pl.program_id(1)
        c = c_ref[j]
        off = jax.lax.rem(jax.lax.rem(i * b - c + rows, rows), b)
        senders = _assemble_senders(plo_ref[0], phi_ref[0], off, b)
        if masks is not None:
            mlo_ref, mhi_ref = rest[0], rest[1]
            keep = _assemble_senders(mlo_ref[0], mhi_ref[0], off, b)
            senders = jnp.where(keep != 0, senders, U32(0))
        r1 = pltpu.roll(senders, s1_ref[j], axis=1)
        if single_col:
            delivered = r1
        else:
            r2 = pltpu.roll(senders, s2_ref[j], axis=1)
            recv_row = i * b + jax.lax.broadcasted_iota(I32, (b, s), 0)
            delivered = jnp.where(recv_row >= c, r1, r2)

        @pl.when(j == 0)
        def _init():
            out_ref[:] = mail_ref[:]

        out_ref[:] = umax(out_ref[:], delivered)

    in_specs = [
        pl.BlockSpec((b, s), lambda i, j, c, s1v, s2v: (i, 0)),
        pl.BlockSpec((1, b, s), lambda i, j, c, s1v, s2v:
                     (_payload_j(i, j, c, s1v, s2v),
                      _lo_block(i, j, c, s1v, s2v), 0)),
        pl.BlockSpec((1, b, s), lambda i, j, c, s1v, s2v:
                     (_payload_j(i, j, c, s1v, s2v), jax.lax.rem(
                         _lo_block(i, j, c, s1v, s2v) + 1, nb), 0)),
    ]
    operands = [mail, payloads, payloads]
    if masks is not None:
        in_specs += [
            pl.BlockSpec((1, b, s), lambda i, j, c, s1v, s2v:
                         (j, _lo_block(i, j, c, s1v, s2v), 0)),
            pl.BlockSpec((1, b, s), lambda i, j, c, s1v, s2v:
                         (j, jax.lax.rem(
                             _lo_block(i, j, c, s1v, s2v) + 1, nb), 0)),
        ]
        operands += [masks, masks]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(nb, k_max),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((b, s), lambda i, j, c, s1v, s2v: (i, 0)),
    )
    from distributed_membership_tpu.observability.timeline import (
        PHASE_GOSSIP)
    with jax.named_scope(PHASE_GOSSIP):
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((rows, s), U32),
            interpret=interpret,
        )(c_shifts.astype(I32), s1s.astype(I32), s2s.astype(I32),
          *operands)


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3))
def gossip_fused(n: int, s: int, k_max: int, interpret: bool,
                 mail: jax.Array, payload: jax.Array,
                 k_eff: jax.Array, shifts: jax.Array,
                 masks: jax.Array | None = None) -> jax.Array:
    """``max(mail, max_j roll2d(where(j < k_eff, payload, 0), shifts[j]))``.

    Args:
      mail:    [N, S] u32 receiver mailboxes (max-combined).
      payload: [N, S] u32 keep-masked sender rows (0 where not gossiped);
               the caller applies entry thinning / act masking.
      k_eff:   [N] i32 per-sender effective fanout (shift j delivers rows
               with ``j < k_eff``).
      shifts:  [k_max] i32 circulant row shifts, values in [1, N).
      masks:   optional [k_max, N, S] i32 per-shift keep masks (nonzero =
               deliver), sender-indexed.  When given they SUBSUME the
               ``k_eff`` fanout gate (the caller folds ``j < k_eff`` in
               along with drop coins / scenario flakes / drop windows),
               so the k_eff planes are not fetched — lossy and scenario
               configs ride this kernel with a single unmasked payload.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows = mail.shape[0]
    b = _pick_block(rows)
    nb = rows // b
    cstride = STRIDE % s

    def _lo_block(i, j, sh):
        return _lo_block_idx(i, b, rows, sh[j])

    def kernel(sh_ref, mail_ref, plo_ref, phi_ref, *rest):
        out_ref = rest[-1]
        i, j = pl.program_id(0), pl.program_id(1)
        r = sh_ref[j]
        off = jax.lax.rem(jax.lax.rem(i * b - r + rows, rows), b)
        senders = _assemble_senders(plo_ref[:], phi_ref[:], off, b)
        if masks is None:
            # k_eff rides as [rows, 1] planes (1-D refs can't take the
            # sublane rotate _assemble_senders needs on the real chip).
            klo_ref, khi_ref = rest[0], rest[1]
            ke = _assemble_senders(klo_ref[:], khi_ref[:], off, b)
            senders = jnp.where(j < ke, senders, U32(0))
        else:
            mlo_ref, mhi_ref = rest[0], rest[1]
            keep = _assemble_senders(mlo_ref[0], mhi_ref[0], off, b)
            senders = jnp.where(keep != 0, senders, U32(0))

        # Column alignment: one shift for all rows (the supported case
        # (N*STRIDE) % S == 0 — see module docstring).
        s1 = jax.lax.rem(jax.lax.rem(r, s) * cstride, s)
        delivered = pltpu.roll(senders, s1, axis=1)

        @pl.when(j == 0)
        def _init():
            out_ref[:] = mail_ref[:]

        out_ref[:] = umax(out_ref[:], delivered)

    row_block = lambda i, j, sh: (i, 0)           # noqa: E731
    in_specs = [
        pl.BlockSpec((b, s), row_block),                       # mail
        pl.BlockSpec((b, s), lambda i, j, sh:
                     (_lo_block(i, j, sh), 0)),                # payload lo
        pl.BlockSpec((b, s), lambda i, j, sh:
                     (jax.lax.rem(_lo_block(i, j, sh) + 1, nb), 0)),
    ]
    if masks is None:
        in_specs += [
            pl.BlockSpec((b, 1), lambda i, j, sh:
                         (_lo_block(i, j, sh), 0)),            # k_eff lo
            pl.BlockSpec((b, 1), lambda i, j, sh:
                         (jax.lax.rem(_lo_block(i, j, sh) + 1, nb), 0)),
        ]
        extra = (k_eff.astype(I32)[:, None], k_eff.astype(I32)[:, None])
    else:
        in_specs += [
            pl.BlockSpec((1, b, s), lambda i, j, sh:
                         (j, _lo_block(i, j, sh), 0)),         # mask lo
            pl.BlockSpec((1, b, s), lambda i, j, sh:
                         (j, jax.lax.rem(_lo_block(i, j, sh) + 1, nb), 0)),
        ]
        extra = (masks, masks)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(nb, k_max),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((b, s), row_block),
    )
    from distributed_membership_tpu.observability.timeline import (
        PHASE_GOSSIP)
    with jax.named_scope(PHASE_GOSSIP):
        return pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((rows, s), U32),
            interpret=interpret,
        )(shifts.astype(I32), mail, payload, payload, *extra)
