"""Fused Pallas kernels for the FOLDED ``[N/F, 128]`` layout — the
combination PERF.md's roofline says the 10k-ticks/s north star needs.

Round 3 shipped two levers separately: the folded layout (S < 128 state
stored at ``F = 128/S`` nodes per physical row — zero lane padding,
backends/tpu_hash_folded.py) and the fused kernels (receive + gossip
delivery as single Pallas traversals of the *natural* ``[N, S]`` layout,
ops/fused_receive.py / ops/fused_gossip.py).  They were mutually
exclusive by construction because the natural kernels assume
``S % 128 == 0``.  This module lifts that: a folded plane's minormost
axis is ALREADY exactly 128 lanes, so the same kernel patterns apply
directly — per-node structure just moves into lane arithmetic
(``node = row*F + lane//S``, ``slot = lane % S``), mirroring the jnp
folded step.

Two kernels:

* :func:`receive_folded_fused` — the folded receive pass (admit +
  ack-merge + self-write + TFAIL/TREMOVE sweep) in one traversal.  The
  kernel body is :func:`_folded_receive_body`, which is ALSO the jnp
  path's implementation (tpu_hash_folded._folded_receive calls it), so
  the two cannot drift.  Per-node inputs arrive pre-broadcast as
  ``[rows, 128]`` planes (``rep(act)``, ``rep(self_val)``, the rcol
  mask): in-kernel re-broadcast of a per-node vector would need
  lane-splitting reshapes Mosaic handles poorly, and the three extra
  plane reads still leave this one traversal versus the jnp path's ~12.
  Per-node reductions (numfailed/size) move OUT of the kernel: the
  folded layout's row sums are segment sums over S-lane groups, so the
  kernel returns the pre-remove ``stale`` mask as a plane and the caller
  reduces — one extra fused XLA pass, no in-kernel lane-segment
  reduction.

* :func:`gossip_folded_stacked` — all ``fanout`` circulant shifts
  delivered into the folded mailbox in one output-stationary traversal.
  Stacked-payload design (like ops/fused_gossip.gossip_fused_stacked):
  the caller masks each shift's payload in jnp and stacks them, so —
  unlike the natural single-chip kernel — per-shift DROP masks are
  representable bit-exactly and FOLDED+FUSED_GOSSIP supports lossy
  configs.  In folded space a node-axis roll by ``r`` decomposes into an
  aligned row roll ``rq = r//F`` plus a carry-select lane roll
  ``rr = (r%F)*S`` (wrapped lanes take the once-more-rolled row), so the
  kernel fetches ``B+1`` sender rows (the one extra row feeds the
  carry), applies the lane roll + carry select, then the segment-wise
  slot roll — tpu_hash_folded.roll_nodes/roll_slots exactly, block-local.

Reference lineage: the step semantics being fused replicate
/root/reference/MP1Node.cpp:404-495 (nodeLoopOps) and EmulNet delivery
(/root/reference/EmulNet.cpp:87-118) — see the tpu_hash module docstring
for the full mapping; the folded decompositions are proven against the
natural layout in tests/test_folded.py and the fused twins against the
jnp folded step in tests/test_fused_folded.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from distributed_membership_tpu.ops.fused_receive import _pick_block, umax

I32 = jnp.int32
U32 = jnp.uint32
EMPTY = -1
LANES = 128


def _folded_receive_body(n: int, tfail: int, tremove: int,
                         self_mask, node, t, view, view_ts, mail,
                         cand_sf, rcol, actp, selfvalp):
    """The folded receive pass, elementwise only — legal both as plain
    jnp (tpu_hash_folded._folded_receive) and as a Pallas kernel body.

    ``self_mask``/``node`` are the static element-coordinate planes
    (closure constants in the jnp path, iota-derived in the kernel);
    ``rcol``/``actp`` are the receive/act masks pre-broadcast to element
    planes and ``selfvalp`` the packed self entry likewise (only its
    self-slot elements matter).

    Returns (view, view_ts, mail_cleared, join_mask, rm_ids, stale) —
    ``stale`` is the pre-remove TFAIL mask; callers reduce it (and the
    post-remove occupancy) to per-node numfailed/size.
    """
    in_id = ((mail - U32(1)) % U32(n)).astype(I32)
    occupied = view > 0
    matches = in_id == ((view - U32(1)) % U32(n)).astype(I32)
    # Bitwise, not jnp.where: an i1-branch select lowers to an i8->i1
    # arith.trunci Mosaic's backend rejects (see ops/fused_receive._admit).
    ok = ((self_mask & (in_id == node))
          | (~self_mask & (~occupied | matches)))
    take = (mail > 0) & ok
    admitted = jnp.where(take, umax(view, mail), view)
    new_view = jnp.where(rcol, admitted, view)
    changed = new_view > view
    new_ts = jnp.where(changed, t, view_ts)
    join_mask = changed & ~occupied
    mail = jnp.where(rcol, U32(0), mail)

    c_id = ((cand_sf - U32(1)) % U32(n)).astype(I32)
    v_id = ((new_view - U32(1)) % U32(n)).astype(I32)
    match = (cand_sf > 0) & (new_view > 0) & (c_id == v_id) & rcol
    upd = match & (cand_sf > new_view)
    new_view = jnp.where(upd, cand_sf, new_view)
    new_ts = jnp.where(upd, t, new_ts)

    s_on = self_mask & actp
    new_view = jnp.where(s_on, selfvalp, new_view)
    new_ts = jnp.where(s_on, t, new_ts)

    present = new_view > 0
    difft = t - new_ts
    stale = present & (difft >= tfail) & actp
    removes = stale & (difft >= tremove)
    cur_id = jnp.where(present,
                       ((new_view - U32(1)) % U32(n)).astype(I32), EMPTY)
    rm_ids = jnp.where(removes, cur_id, EMPTY)
    new_view = jnp.where(removes, U32(0), new_view)
    return new_view, new_ts, mail, join_mask, rm_ids, stale


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4, 5))
def receive_folded_fused(n: int, s: int, tfail: int, tremove: int,
                         stride: int, interpret: bool,
                         t, row0, view, view_ts, mail, cand_sf,
                         rcol, actp, selfvalp):
    """One-traversal Pallas version of the folded receive pass.

    ``row0`` is the first plane row's global node-id offset (0
    single-chip; ``shard * n_local`` on the sharded ring — traced, so it
    rides SMEM next to ``t``).  Masks travel as int32 (bool VMEM tiling
    is dtype-hostile, as in ops/fused_receive).
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows = view.shape[0]
    f = LANES // s
    b = _pick_block(rows)
    grid = (rows // b,)

    def kernel(sc_ref, view_ref, ts_ref, mail_ref, cand_ref, rcol_ref,
               actp_ref, sval_ref,
               view_out, ts_out, mailc_out, join_out, rm_out, stale_out):
        i = pl.program_id(0)
        t_k, row0_k = sc_ref[0], sc_ref[1]
        lane = jax.lax.broadcasted_iota(I32, (b, LANES), 1)
        prow = jax.lax.broadcasted_iota(I32, (b, LANES), 0)
        pos = jax.lax.rem(lane, s)
        node = row0_k + (i * b + prow) * f + lane // s
        self_slot = jax.lax.rem(
            jax.lax.rem(node, s) * ((1 + stride) % s), s)
        self_mask = pos == self_slot
        (nv, nts, mc, join, rm, stale) = _folded_receive_body(
            n, tfail, tremove, self_mask, node, t_k,
            view_ref[:], ts_ref[:], mail_ref[:], cand_ref[:],
            rcol_ref[:] != 0, actp_ref[:] != 0, sval_ref[:])
        view_out[:] = nv
        ts_out[:] = nts
        mailc_out[:] = mc
        join_out[:] = join.astype(I32)
        rm_out[:] = rm
        stale_out[:] = stale.astype(I32)

    row_spec = pl.BlockSpec((b, LANES), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),   # (t, row0)
            row_spec, row_spec, row_spec, row_spec,  # view, ts, mail, cand
            row_spec, row_spec, row_spec,            # rcol, actp, selfvalp
        ],
        out_specs=[row_spec] * 6,
        # Donate the state planes in place (view->view, ts->ts,
        # mail->mail_cleared); input 0 is the SMEM scalar pair.
        input_output_aliases={1: 0, 2: 1, 3: 2},
        out_shape=[
            jax.ShapeDtypeStruct((rows, LANES), U32),   # view
            jax.ShapeDtypeStruct((rows, LANES), I32),   # view_ts
            jax.ShapeDtypeStruct((rows, LANES), U32),   # mail cleared
            jax.ShapeDtypeStruct((rows, LANES), I32),   # join mask
            jax.ShapeDtypeStruct((rows, LANES), I32),   # rm ids
            jax.ShapeDtypeStruct((rows, LANES), I32),   # stale mask
        ],
        interpret=interpret,
    )(jnp.stack([jnp.asarray(t, I32), jnp.asarray(row0, I32)]),
      view, view_ts, mail, cand_sf, rcol.astype(I32), actp.astype(I32),
      selfvalp)
    (view2, ts2, mailc, join_i, rm_ids, stale_i) = out
    return view2, ts2, mailc, join_i != 0, rm_ids, stale_i != 0


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4))
def gossip_folded_stacked(rows: int, s: int, k_max: int, single_col: bool,
                          interpret: bool, mail: jax.Array,
                          payloads: jax.Array, thr: jax.Array,
                          c1: jax.Array, c2: jax.Array,
                          masks: jax.Array | None = None) -> jax.Array:
    """Accumulate K pre-masked folded payloads into the folded mailbox.

    Per shift j the jnp folded path computes
    ``roll_slots(roll_nodes(payloads[j], r_j), c1_j)`` (with a
    ``node >= thr_j`` row select between the ``c1_j``/``c2_j`` slot
    alignments when ``single_col`` is False) and maxes into mail — ~5
    plane passes per shift.  Here the grid walks ``(mail block, shift)``
    with the mail block VMEM-resident across all K shifts; sender rows
    arrive by scalar-prefetch block indexing.

    Args:
      mail:     [rows, 128] u32 folded mailbox planes.
      payloads: [K, rows, 128] u32 — per-shift sender-masked folded
                views (entry thinning, fanout gating, and any DROP masks
                already applied; on the sharded ring also already
                ppermuted).
      thr:      [K] i32 node-axis shift per stacked payload (the global
                shift single-chip; the intra-shard residual on the
                sharded ring) — the folded row-roll decomposition
                ``rq = thr//F``, ``rr = (thr%F)*S``
                (tpu_hash_folded.roll_nodes) is derived here, once, and
                the same value is the node-index threshold of the
                two-alignment receiver select when not ``single_col``.
      c1, c2:   [K] i32 slot-roll amounts (tpu_hash_folded.roll_slots)
                for unwrapped/wrapped receiver rows; ``c2`` ignored when
                ``single_col``.
      masks:    optional [K, rows, 128] i32 per-shift keep masks
                (nonzero = deliver), sender-indexed in the folded
                layout.  When given, the kernel zeroes non-kept sender
                entries in VMEM and ``payloads`` may be a SHARED
                [1, rows, 128] stack (the unmasked folded view broadcast
                to every shift) — the single-chip lossy/scenario branch
                uses this to skip materializing K payload copies.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    f = LANES // s
    b = _pick_block(rows)
    nb = rows // b
    rq = thr.astype(I32) // f
    rr = jax.lax.rem(thr.astype(I32), f) * s

    def _lo_block(i, j, thr_v, rq_v, rr_v, c1_v, c2_v):
        # First sender row for output block i is (i*b - rq - 1) mod rows
        # (the -1 fetches the carry row roll_nodes' wrapped lanes need).
        return jax.lax.rem(i * b - rq_v[j] - 1 + rows, rows) // b

    def _seg_roll(x, c):
        # tpu_hash_folded.roll_slots: segment-wise lane roll, c in [0, s).
        lane = jax.lax.broadcasted_iota(I32, x.shape, 1)
        pos = jax.lax.rem(lane, s)
        # roll by c-s == roll by c-s+128 over the 128-lane axis.
        return jnp.where(pos < c, pltpu.roll(x, c + LANES - s, axis=1),
                         pltpu.roll(x, c, axis=1))

    shared_payload = payloads.shape[0] == 1

    def kernel(thr_ref, rq_ref, rr_ref, c1_ref, c2_ref,
               mail_ref, plo_ref, phi_ref, *rest):
        out_ref = rest[-1]
        i, j = pl.program_id(0), pl.program_id(1)
        rq_j, rr_j = rq_ref[j], rr_ref[j]
        start = jax.lax.rem(i * b - rq_j - 1 + rows, rows)
        off = jax.lax.rem(start, b)
        rows2b = jnp.concatenate([plo_ref[0], phi_ref[0]], axis=0)
        if masks is not None:
            mlo_ref, mhi_ref = rest[0], rest[1]
            keep2b = jnp.concatenate([mlo_ref[0], mhi_ref[0]], axis=0)
            rows2b = jnp.where(keep2b != 0, rows2b, U32(0))
        # The b+1 sender rows starting at ``off``: Mosaic TC has no
        # dynamic_slice lowering, so rotate row ``off`` to row 0 (dynamic
        # sublane roll) and take static slices — as in
        # fused_gossip._assemble_senders.
        rolled = pltpu.roll(rows2b, 2 * b - off, axis=0)
        # roll_nodes: a = rows rolled by rq, carry = rolled once more.
        a = rolled[1:b + 1]
        carry = rolled[:b]
        lane = jax.lax.broadcasted_iota(I32, (b, LANES), 1)
        x = jnp.where(lane < rr_j, pltpu.roll(carry, rr_j, axis=1),
                      pltpu.roll(a, rr_j, axis=1))
        r1 = _seg_roll(x, c1_ref[j])
        if single_col:
            delivered = r1
        else:
            r2 = _seg_roll(x, c2_ref[j])
            prow = jax.lax.broadcasted_iota(I32, (b, LANES), 0)
            node = (i * b + prow) * f + lane // s
            delivered = jnp.where(node >= thr_ref[j], r1, r2)

        @pl.when(j == 0)
        def _init():
            out_ref[:] = mail_ref[:]

        out_ref[:] = umax(out_ref[:], delivered)

    def _payload_j(i, j, *sc):
        return 0 if shared_payload else j

    in_specs = [
        pl.BlockSpec((b, LANES),
                     lambda i, j, *sc: (i, 0)),                 # mail
        pl.BlockSpec((1, b, LANES), lambda i, j, *sc:
                     (_payload_j(i, j, *sc),
                      _lo_block(i, j, *sc), 0)),                # payload lo
        pl.BlockSpec((1, b, LANES), lambda i, j, *sc:
                     (_payload_j(i, j, *sc), jax.lax.rem(
                         _lo_block(i, j, *sc) + 1, nb), 0)),    # payload hi
    ]
    operands = [mail, payloads, payloads]
    if masks is not None:
        in_specs += [
            pl.BlockSpec((1, b, LANES), lambda i, j, *sc:
                         (j, _lo_block(i, j, *sc), 0)),         # mask lo
            pl.BlockSpec((1, b, LANES), lambda i, j, *sc:
                         (j, jax.lax.rem(
                             _lo_block(i, j, *sc) + 1, nb), 0)),
        ]
        operands += [masks, masks]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(nb, k_max),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((b, LANES), lambda i, j, *sc: (i, 0)),
    )
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((rows, LANES), U32),
        interpret=interpret,
    )(thr.astype(I32), rq, rr, c1.astype(I32),
      c2.astype(I32), *operands)
