"""Gossip delivery as heartbeat-max propagation.

The reference's entire LIST burst — one 19-byte message per live member-list
entry, per target, per tick (MP1Node::sendMemberList, MP1Node.cpp:360-395) —
is semantically *heartbeat-max propagation over a random fanout graph*: the
receiver-side merge (updatelistCallBack, MP1Node.cpp:259-301) keeps the max
heartbeat per entry and is commutative in the incoming message set.  So
instead of a mailbox we compute, per tick,

    contrib[r, e] = max over senders s targeting r of hb[s, e]   (live e only)

and max-combine ``contrib`` into the receiver's pending-delivery buffer.
Message *counts* (the reference's sent_msgs/recv_msgs profiling matrices,
EmulNet.h:83-84) and per-message Bernoulli drops (ENsend, EmulNet.cpp:92)
are preserved exactly: each (sender, receiver, entry) triple is one message.

The dense [S, R, E] intermediate is materialized in sender chunks to bound
memory; the chunk loop is a ``lax.scan`` (static trip count, TPU-friendly).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _chunk_size(n: int, budget_elems: int = 1 << 22) -> int:
    """Largest divisor of n such that chunk*n*n stays within budget."""
    per_sender = max(n * n, 1)
    target = max(budget_elems // per_sender, 1)
    best = 1
    for c in range(1, n + 1):
        if n % c == 0 and c <= target:
            best = c
    return best


def fanout_deliver(key: jax.Array, target_mask: jax.Array, send_hb: jax.Array,
                   drop_active: jax.Array, drop_prob: float):
    """Deliver one tick of gossip.

    Args:
      key: PRNG key for per-message drop decisions.
      target_mask: ``[S, R]`` bool — sender s gossips to receiver r this tick.
      send_hb: ``[S, E]`` int32 — heartbeat per live entry, -1 where the entry
        is absent or withheld (the TFAIL staleness gate, MP1Node.cpp:376).
      drop_active: scalar bool — whether the message-drop window is open
        (Application.cpp:177-179,198-200).
      drop_prob: static float — effective drop probability.  The reference
        computes ``rand()%100 < int(p*100)`` (EmulNet.cpp:90-92), i.e. the
        effective probability is ``int(p*100)/100``; callers pass that.

    Returns:
      contrib:  ``[R, E]`` int32 — max heartbeat arriving per (receiver, entry),
                -1 where nothing arrived.
      sent:     ``[S]`` int32 — messages accepted from each sender (post-drop,
                matching the reference counting sends after the drop check).
      recv_add: ``[R]`` int32 — messages now in flight to each receiver.
    """
    s, r = target_mask.shape
    e = send_hb.shape[1]
    c = _chunk_size(s)
    n_chunks = s // c
    tm = target_mask.reshape(n_chunks, c, r)
    sh = send_hb.reshape(n_chunks, c, e)
    keys = jax.random.split(key, n_chunks)
    use_drops = drop_prob > 0.0

    def body(carry, inp):
        contrib, recv_add = carry
        tm_c, sh_c, key_c = inp
        mask = tm_c[:, :, None] & (sh_c >= 0)[:, None, :]          # [c, R, E]
        if use_drops:
            dropped = jax.random.bernoulli(key_c, drop_prob, (c, r, e))
            mask = mask & ~(dropped & drop_active)
        vals = jnp.where(mask, sh_c[:, None, :], -1)
        contrib = jnp.maximum(contrib, vals.max(axis=0))
        recv_add = recv_add + mask.sum(axis=(0, 2), dtype=jnp.int32)
        sent_c = mask.sum(axis=(1, 2), dtype=jnp.int32)
        return (contrib, recv_add), sent_c

    init = (jnp.full((r, e), -1, jnp.int32), jnp.zeros((r,), jnp.int32))
    (contrib, recv_add), sent_chunks = jax.lax.scan(body, init, (tm, sh, keys))
    return contrib, sent_chunks.reshape(s), recv_add


def fanout_deliver_indexed(key: jax.Array, targets: jax.Array,
                           valid: jax.Array, send_hb: jax.Array,
                           n_receivers: int, drop_active: jax.Array,
                           drop_prob: float):
    """Scatter-max gossip delivery with targets in index form.

    The production path: O(S * K * E) work and memory instead of
    :func:`fanout_deliver`'s dense O(S * R * E) mask (kept as the executable
    spec / for tests).  Delivers exactly the same messages for the same
    target sets.

    Args:
      targets: ``[S, K]`` int32 — receiver index per (sender, slot).
      valid:   ``[S, K]`` bool — slot actually targeted.
      send_hb: ``[S, E]`` int32 — live-entry heartbeats, -1 = withheld.
      n_receivers: R.
      drop_active / drop_prob: as in :func:`fanout_deliver`; the Bernoulli
        drop is per (sender, slot, entry) — one coin per wire message,
        matching ENsend (EmulNet.cpp:92).

    Returns ``(contrib [R, E], sent [S], recv_add [R])``.
    """
    s, k = targets.shape
    e = send_hb.shape[1]
    live = send_hb >= 0                                     # [S, E]
    msg = valid[:, :, None] & live[:, None, :]              # [S, K, E]
    if drop_prob > 0.0:
        dropped = jax.random.bernoulli(key, drop_prob, (s, k, e))
        msg = msg & ~(dropped & drop_active)
    vals = jnp.where(msg, send_hb[:, None, :], -1)          # [S, K, E]
    # Invalid slots scatter to a scrap row R (out-of-range handled by 'drop').
    tgt = jnp.where(valid, targets, n_receivers).reshape(s * k)
    contrib = jnp.full((n_receivers + 1, e), -1, jnp.int32)
    contrib = contrib.at[tgt].max(vals.reshape(s * k, e), mode="drop")
    sent = msg.sum(axis=(1, 2), dtype=jnp.int32)
    counts = msg.sum(axis=2, dtype=jnp.int32).reshape(s * k)
    recv_add = jnp.zeros((n_receivers + 1,), jnp.int32).at[tgt].add(
        counts, mode="drop")
    return contrib[:n_receivers], sent, recv_add[:n_receivers]


def broadcast_deliver(key: jax.Array, recipients: jax.Array,
                      send_hb: jax.Array, drop_active: jax.Array,
                      drop_prob: float):
    """One sender's full live list to a set of recipients (the introducer's
    guaranteed burst to this tick's new joiners, MP1Node.cpp:240-242,454 —
    whose size is unbounded by FANOUT, so it can't ride the K-slot path).

    Args:
      recipients: ``[R]`` bool.
      send_hb: ``[E]`` int32 — the sender's live entries, -1 withheld.

    Returns ``(contrib [R, E], sent scalar, recv_add [R])``.
    """
    r = recipients.shape[0]
    e = send_hb.shape[0]
    msg = recipients[:, None] & (send_hb >= 0)[None, :]     # [R, E]
    if drop_prob > 0.0:
        dropped = jax.random.bernoulli(key, drop_prob, (r, e))
        msg = msg & ~(dropped & drop_active)
    contrib = jnp.where(msg, send_hb[None, :], -1)
    sent = msg.sum(dtype=jnp.int32)
    recv_add = msg.sum(axis=1, dtype=jnp.int32)
    return contrib, sent, recv_add
