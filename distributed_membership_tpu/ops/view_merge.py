"""Bounded member-view merge: the sparse analog of the dense [N, N] max-merge.

At large N a dense id-indexed member table is impossible (SURVEY.md §7 hard
part #2), so each node keeps a *bounded view*: M slots of
``(member id, heartbeat, timestamp)``, the fixed-size partial list the spec
explicitly permits (mp1_specifications.pdf §4: "a partial list of fixed size
can be maintained").  The receiver-side combine stays the reference's merge
rule — per member id keep the max heartbeat, refresh the local timestamp only
on *strict* increase (MP1Node.cpp:278-288) — but is computed by sorting the
concatenation of (local slots, incoming entries, a synthetic self entry) by
``(id, -heartbeat, origin-rank)`` and keeping each id-group's head.  Two
batched ``lax.sort``s over rows of length M+Q+1: static shapes, no
data-dependent control flow, fully TPU-tileable.

Slot-retention policy when more unique ids survive than slots (a *new*
design decision — the reference never evicts):
  1. the node's own entry (a node never forgets itself);
  2. existing members (ids already in the local view), freshest heartbeat
     first — so an entry being tracked toward TREMOVE is never dropped in
     favor of a newcomer and failure detection over the monitored set stays
     complete;
  3. new members, highest heartbeat first.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

I32 = jnp.int32
EMPTY = -1          # slot_id value for a free slot
_ID_INF = 2**30     # sorts empty/invalid entries last
# Per-node slot-map stride of the hash backends: odd prime, so which id
# pairs collide decorrelates across nodes (h_i(id) = (id + i*STRIDE) % S
# — backends/tpu_hash.py, which re-exports this).  Defined in this leaf
# module so the Pallas kernels (ops/fused_gossip) share the SAME
# constant instead of a test-pinned duplicate (ADVICE r3).
STRIDE = 7919


class MergeResult(NamedTuple):
    slot_id: jax.Array   # [N, M] i32, EMPTY where free
    slot_hb: jax.Array   # [N, M] i32
    slot_ts: jax.Array   # [N, M] i32
    join_mask: jax.Array  # [N, M] bool — this slot was newly inserted (a
    #                       grader 'joined' event: id was not in the view)


def _has_id(sorted_ids: jax.Array, query: jax.Array) -> jax.Array:
    """Row-batched membership test: is query[n, q] in sorted_ids[n, :]?"""
    pos = jax.vmap(jnp.searchsorted)(sorted_ids, query)
    pos = jnp.clip(pos, 0, sorted_ids.shape[1] - 1)
    return jnp.take_along_axis(sorted_ids, pos, axis=1) == query


def merge_views(
    slot_id: jax.Array, slot_hb: jax.Array, slot_ts: jax.Array,
    in_id: jax.Array, in_hb: jax.Array, in_valid: jax.Array,
    self_id: jax.Array, self_hb: jax.Array, self_on: jax.Array,
    t: jax.Array, apply_row: jax.Array,
) -> MergeResult:
    """Merge incoming entries (and the self refresh) into bounded views.

    Args:
      slot_id/hb/ts: ``[N, M]`` current views (id EMPTY = free slot).
      in_id/in_hb:   ``[N, Q]`` incoming entries (drained mailbox).
      in_valid:      ``[N, Q]`` bool — entry present.
      self_id:       ``[N]`` each row's own member id.
      self_hb:       ``[N]`` the self-refresh heartbeat (the odd intermediate
                     value of the reference's double increment,
                     MP1Node.cpp:412-415).
      self_on:       ``[N]`` bool — row performs its self refresh this tick
                     (the reference's nodeLoopOps eligibility).
      t:             scalar i32 current tick (timestamp for refreshed entries).
      apply_row:     ``[N]`` bool — rows not applying keep their view
                     verbatim (non-receiving nodes, Application.cpp:130).

    Merge semantics per id (matches backends/tpu.py's dense step):
      * incoming hb > local hb  → hb := incoming, ts := t;
      * incoming hb <= local hb → entry unchanged (no ts refresh);
      * id not in view          → inserted with ts = t, join event;
      * self entry              → hb := self_hb, ts := t (always wins: the
        self-refresh hb strictly exceeds any gossiped echo of it).
    """
    n, m = slot_id.shape
    q = in_id.shape[1]
    L = m + q + 1

    local_valid = slot_id != EMPTY
    sorted_local = jnp.sort(jnp.where(local_valid, slot_id, _ID_INF), axis=1)

    # Origin ranks (tiebreak for equal heartbeat): self=0, local=1, incoming=2
    # — local before incoming implements the strict-increase rule.
    self_ent_id = self_id[:, None]
    self_ent_valid = self_on[:, None]
    ids = jnp.concatenate([slot_id, in_id, self_ent_id], axis=1)
    hbs = jnp.concatenate([slot_hb, in_hb, self_hb[:, None]], axis=1)
    tss = jnp.concatenate(
        [slot_ts, jnp.full((n, q), t, I32), jnp.full((n, 1), t, I32)], axis=1)
    valid = jnp.concatenate([local_valid, in_valid, self_ent_valid], axis=1)
    rank = jnp.concatenate(
        [jnp.ones((n, m), I32), jnp.full((n, q), 2, I32), jnp.zeros((n, 1), I32)],
        axis=1)

    # Is each non-local entry's id already a member? (decides update vs join)
    known = jnp.concatenate(
        [jnp.ones((n, m), bool),
         _has_id(sorted_local, jnp.concatenate([in_id, self_ent_id], axis=1))],
        axis=1)

    id_key = jnp.where(valid, ids, _ID_INF)
    neg_hb = jnp.where(valid, -hbs, _ID_INF)
    id_key, neg_hb, rank, tss, ids, hbs, known = jax.lax.sort(
        (id_key, neg_hb, rank, tss, ids, hbs, known.astype(I32)), num_keys=3)

    winner = (id_key != _ID_INF) & (
        jnp.concatenate([jnp.ones((n, 1), bool),
                         id_key[:, 1:] != id_key[:, :-1]], axis=1))

    # Retention priority (see module docstring): 0 self, 1 existing member,
    # 2 new member, 3 dropped.
    is_self = ids == self_id[:, None]
    keep = jnp.where(
        ~winner, 3,
        jnp.where(is_self, 0, jnp.where(known == 1, 1, 2))).astype(I32)
    join = winner & (known == 0)

    keep, neg_hb2, ids, hbs, tss, join = jax.lax.sort(
        (keep, jnp.where(winner, -hbs, _ID_INF), ids, hbs, tss,
         join.astype(I32)), num_keys=2)
    kept = keep[:, :m] < 3

    ar = apply_row[:, None]
    new_id = jnp.where(ar, jnp.where(kept, ids[:, :m], EMPTY), slot_id)
    new_hb = jnp.where(ar & kept, hbs[:, :m], jnp.where(ar, 0, slot_hb))
    new_ts = jnp.where(ar & kept, tss[:, :m], jnp.where(ar, 0, slot_ts))
    join_mask = ar & kept & (join[:, :m] == 1)
    return MergeResult(new_id, new_hb, new_ts, join_mask)


def mix32(x: jax.Array) -> jax.Array:
    """Nonlinear u32 mixer (lowbias32-style finalizer).

    Affine slot maps like ``(id + salt) % Q`` keep collision *pairs* fixed
    under any per-tick salt — ``i`` and ``j`` collide iff ``i ≡ j (mod Q)``,
    every tick, forever — so max-combine starves the same loser each round
    and its entry is never refreshed (measured: ~10k false removals per
    150-tick N=8192 run).  A nonlinear mix makes each tick's collision pairs
    independent, turning systematic starvation into i.i.d. percent-level
    loss that TREMOVE's consecutive-miss requirement filters out entirely.
    """
    x = x.astype(jnp.uint32)
    x = (x ^ (x >> 16)) * jnp.uint32(0x7FEB352D)
    x = (x ^ (x >> 15)) * jnp.uint32(0x846CA68B)
    return x ^ (x >> 16)


def hash_slot(msg_id: jax.Array, salt: jax.Array | int, qsz: int,
              n_pad: int) -> jax.Array:
    """Per-receiver mailbox slot for a message about ``msg_id``.

    Injective (lossless) whenever ``qsz >= n_pad``; otherwise a per-tick
    pseudorandom map via :func:`mix32` (see its docstring for why affine
    salting is not enough)."""
    if qsz >= n_pad:
        return jax.lax.rem(msg_id + salt, qsz)
    mixed = mix32(msg_id.astype(jnp.uint32)
                  + jnp.uint32(0x9E3779B9) * jnp.asarray(salt, jnp.uint32))
    return jax.lax.rem(mixed, jnp.uint32(qsz)).astype(msg_id.dtype)


def scatter_mailbox(mail: jax.Array, tgt: jax.Array, msg_id: jax.Array,
                    msg_hb: jax.Array, msg_valid: jax.Array,
                    n_pad: int, salt: jax.Array | int = 0) -> jax.Array:
    """Max-combine messages into per-receiver hash-slotted mailboxes.

    The mailbox is the sparse analog of EmulNet's bounded global buffer
    (EmulNet.h:35-72): ``mail`` is ``[N, Q]`` uint32 with 0 = empty and
    ``hb * n_pad + id + 1`` otherwise.  A message lands in slot
    ``id % Q`` of its receiver — the same id from any number of senders
    max-combines losslessly (gossip *is* a max), and when Q >= N the slot map
    is injective so nothing is ever lost.  Two *different* ids colliding in a
    slot keep the higher heartbeat and drop the other — the bounded-capacity
    drop, the reference's ENBUFFSIZE-full drop recast per receiver
    (EmulNet.cpp:90: messages beyond capacity are silently discarded).

    Args:
      mail: ``[N, Q]`` uint32 current mailboxes.
      tgt: ``[...]`` i32 receiver node index per message.
      msg_id / msg_hb: ``[...]`` i32 entry payload.
      msg_valid: ``[...]`` bool.
      n_pad: id range bound used for packing (the global N).
      salt: slot-map rotation (pass the tick): decorrelates *which* id pairs
        collide across ticks via :func:`hash_slot`'s nonlinear mix, so
        bounded-capacity loss is i.i.d. per tick instead of systematically
        starving the same id pair.  Injectivity for Q >= N is preserved.

    Requires ``max_hb * n_pad + n_pad < 2**32`` — validated by the caller
    (config.validate_sparse_packing).
    """
    n, qsz = mail.shape
    packed = (msg_hb.astype(jnp.uint32) * jnp.uint32(n_pad)
              + msg_id.astype(jnp.uint32) + jnp.uint32(1))
    addr = tgt * qsz + hash_slot(msg_id, salt, qsz, n_pad)
    addr = jnp.where(msg_valid, addr, n * qsz).reshape(-1)
    packed = jnp.where(msg_valid, packed, 0).reshape(-1)
    flat = mail.reshape(-1)
    flat = flat.at[addr].max(packed, mode="drop")
    return flat.reshape(n, qsz)


def unpack_mailbox(mail: jax.Array, n_pad: int):
    """Inverse of :func:`scatter_mailbox` packing → (id, hb, valid)."""
    valid = mail > 0
    v = mail - jnp.uint32(1)
    msg_id = (v % jnp.uint32(n_pad)).astype(I32)
    msg_hb = (v // jnp.uint32(n_pad)).astype(I32)
    return jnp.where(valid, msg_id, EMPTY), jnp.where(valid, msg_hb, -1), valid
