"""Fused receive pass: admit + ack-apply + self-refresh + TFAIL/TREMOVE
sweep in one traversal of the ``[N, S]`` state.

After the ring-exchange redesign the hash backend's per-tick cost is pure
HBM streaming (PERF.md roofline): XLA fuses elementwise chains well, but
the receive path still spans several producer/consumer groups (admit
combine, ack candidate compare, self-slot row update, sweep reductions)
that lower to ~12 passes over the resident state.  This module provides:

* :func:`receive_core` — the pure-jnp reference.  `tpu_hash.make_step`
  calls it directly (it IS the ring receive path), so the semantics are
  single-sourced;
* :func:`receive_fused` — the same computation as ONE Pallas kernel
  (grid over row blocks, whole-row lanes): each state element is read
  once and written once, ~6 passes instead of ~12.

The fused path is opt-in (``FUSED_RECEIVE: 1`` conf key): it requires
``S % 128 == 0`` (lane tiling) and ``N`` divisible by the row-block, and
is validated bit-exactly against :func:`receive_core` in interpret mode
(tests/test_fused_receive.py) — the TPU lowering reuses the identical
kernel body.

Reference semantics preserved exactly (see tpu_hash.make_step): sticky
admission (make_admit), strict-increase ack refresh with occupant match,
the double-heartbeat self refresh (MP1Node.cpp:412-415), and the
TFAIL/TREMOVE sweep (MP1Node.cpp:429-446).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

I32 = jnp.int32
U32 = jnp.uint32
EMPTY = -1


def umax(a, b):
    """Unsigned elementwise max as compare+select.

    ``jnp.maximum`` on u32 operands lowers to ``arith.maxui``, which
    Mosaic's TPU backend fails to legalize on vectors (real-chip compile
    failure, round-4 ladder: "failed to legalize operation 'arith.maxui'"
    — artifacts/rung_errors.log; interpret mode and the AOT ``.lower()``
    gate both accept it, so only hardware catches it).  The unsigned
    compare predicate (``arith.cmpi ugt``) DOES legalize — the kernels
    lean on it everywhere — so compare+select is the portable spelling.
    Bit-identical to ``jnp.maximum`` for integers (no NaN cases)."""
    return jnp.where(b > a, b, a)


def _admit(n: int, self_mask, row_ids, view, incoming):
    """Sticky admit-or-refresh (tpu_hash.make_admit, inlined so the same
    expression serves both the jnp path and the Pallas kernel body).
    ``row_ids`` may be the plain [rows] vector (make_admit callers) or
    the [rows, 1] column the all-2-D kernel body uses."""
    rowc = row_ids if row_ids.ndim == 2 else row_ids[:, None]
    in_id = ((incoming - U32(1)) % U32(n)).astype(I32)
    occupied = view > 0
    matches = in_id == ((view - U32(1)) % U32(n)).astype(I32)
    # Boolean algebra, NOT jnp.where: a select between two i1 vectors
    # reaches Mosaic's backend as an unsupported i8->i1 arith.trunci
    # (real-chip compile failure the AOT .lower() gate cannot see —
    # caught by the round-4 ladder, artifacts/rung_errors.log).
    ok = ((self_mask & (in_id == rowc))
          | (~self_mask & (~occupied | matches)))
    take = (incoming > 0) & ok
    return jnp.where(take, umax(view, incoming), view)


def _receive_body(n: int, s: int, tfail: int, tremove: int, stride: int,
                  t, view, view_ts, mail, cand, rcol, actc,
                  sonc, spackc, rowc, admitc=None):
    """The shared computation (jnp ops only — legal in both contexts).

    The per-node vectors arrive as COLUMN vectors ([rows, 1]): every use
    broadcasts against the [rows, S] planes anyway, and all-2-D shapes
    keep the Pallas twin free of 1-D refs/values, which Mosaic TC's
    lowering handles far less robustly than lane-tiled 2-D (the same
    reason fused_gossip's k_eff sidecar rides [rows, 1] planes).

    ``admitc`` (optional [rows, S] bool) is a precomputed receive-side
    drop/flake mask: entries with ``admitc`` False behave as if the mail
    was never delivered this tick — they neither admit nor refresh
    (``incoming > 0`` gates them out of :func:`_admit` after zeroing).
    The mailbox clear is computed from the ORIGINAL mail, so suppressed
    entries still clear where ``rcol`` says the row received.  ``None``
    (the default) leaves the program byte-identical to before the mask
    existed — census pins depend on that.

    Returns (view, view_ts, mail_cleared, join_mask, rm_ids,
    numfailed, size) — the last two as [rows, 1] columns.
    """
    col = jax.lax.broadcasted_iota(I32, view.shape, 1)
    # slot_of(i, i) = i*(1+STRIDE) mod S, computed modularly (the overflow
    # guard of tpu_hash.slot_of).
    self_slot = jax.lax.rem(
        jax.lax.rem(rowc, s) * ((1 + stride) % s), s)
    self_mask = col == self_slot

    prev_present = view > 0
    # --- admit gossip mail (sticky admission) ---
    mail_in = mail if admitc is None else jnp.where(admitc, mail, U32(0))
    admitted = _admit(n, self_mask, rowc, view, mail_in)
    new_view = jnp.where(rcol, admitted, view)
    changed = new_view > view
    new_ts = jnp.where(changed, t, view_ts)
    join_mask = changed & ~prev_present
    mail_cleared = jnp.where(rcol, U32(0), mail)

    # --- ack application: occupant-matched strict-increase refresh ---
    c_id = ((cand - U32(1)) % U32(n)).astype(I32)
    v_id = ((new_view - U32(1)) % U32(n)).astype(I32)
    match = (cand > 0) & (new_view > 0) & (c_id == v_id) & rcol
    upd = match & (cand > new_view)
    new_view = jnp.where(upd, cand, new_view)
    new_ts = jnp.where(upd, t, new_ts)

    # --- self refresh (double heartbeat increment, caller packs) ---
    s_on = self_mask & sonc
    new_view = jnp.where(s_on, spackc, new_view)
    new_ts = jnp.where(s_on, t, new_ts)

    # --- TFAIL / TREMOVE sweep ---
    present = new_view > 0
    difft = t - new_ts
    stale = present & (difft >= tfail) & actc
    numfailed = stale.sum(1, dtype=I32, keepdims=True)
    removes = stale & (difft >= tremove)
    cur_id = jnp.where(present,
                       ((new_view - U32(1)) % U32(n)).astype(I32), EMPTY)
    rm_ids = jnp.where(removes, cur_id, EMPTY)
    new_view = jnp.where(removes, U32(0), new_view)
    size = (new_view > 0).sum(1, dtype=I32, keepdims=True)

    return (new_view, new_ts, mail_cleared, join_mask, rm_ids,
            numfailed, size)


def receive_core(n: int, s: int, tfail: int, tremove: int, stride: int,
                 t, view, view_ts, mail, cand, recv_mask, act,
                 self_on, self_pack, row_ids, admit_mask=None):
    """Pure-jnp receive pass (reference AND default implementation).
    Takes the per-node vectors [N]-shaped; the column lifting/squeezing
    happens here so callers are unchanged.  ``admit_mask`` (optional
    [N, S] bool) suppresses admission of this tick's delivered entries
    (see :func:`_receive_body`)."""
    from distributed_membership_tpu.observability.timeline import (
        PHASE_RECEIVE)
    with jax.named_scope(PHASE_RECEIVE):
        (new_view, new_ts, mail_cleared, join_mask, rm_ids, nf, sz) = \
            _receive_body(n, s, tfail, tremove, stride, t, view, view_ts,
                          mail, cand, recv_mask[:, None], act[:, None],
                          self_on[:, None], self_pack[:, None],
                          row_ids[:, None], admit_mask)
    return (new_view, new_ts, mail_cleared, join_mask, rm_ids,
            nf[:, 0], sz[:, 0])


def _pick_block(n: int) -> int:
    for b in (512, 256, 128, 64, 32, 16, 8):
        if n % b == 0:
            return b
    return n


def fused_supported(n: int, s: int) -> bool:
    """Lane tiling wants whole 128-lane rows; row blocks must divide N."""
    return s % 128 == 0 and n >= 8


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4, 5))
def receive_fused(n: int, s: int, tfail: int, tremove: int, stride: int,
                  interpret: bool,
                  t, view, view_ts, mail, cand, recv_mask, act,
                  self_on, self_pack, row_ids, admit_mask=None):
    """One-traversal Pallas version of :func:`receive_core`.

    Masks travel as int32 (bool VMEM tiling is dtype-hostile); the kernel
    body is :func:`_receive_body` itself — jnp ops lower inside Pallas —
    so the two paths cannot drift.  ``admit_mask`` (optional [rows, S]
    bool) rides as one extra i32 plane input; ``None`` keeps the
    pallas_call signature (and the census op counts) unchanged.
    """
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    rows = view.shape[0]       # == n single-chip; the local row count L
    #                            when a shard calls with its slice
    b = _pick_block(rows)
    grid = (rows // b,)

    def kernel(t_ref, view_ref, ts_ref, mail_ref, cand_ref, recv_ref,
               act_ref, son_ref, spack_ref, rows_ref, *rest):
        (view_out, ts_out, mailc_out, join_out, rm_out,
         nf_out, size_out) = rest[-7:]
        admitc = None if admit_mask is None else rest[0][:] != 0
        (nv, nts, mc, join, rm, nf, sz) = _receive_body(
            n, s, tfail, tremove, stride, t_ref[0],
            view_ref[:], ts_ref[:], mail_ref[:], cand_ref[:],
            recv_ref[:] != 0, act_ref[:] != 0, son_ref[:] != 0,
            spack_ref[:], rows_ref[:], admitc)
        view_out[:] = nv
        ts_out[:] = nts
        mailc_out[:] = mc
        join_out[:] = join.astype(I32)
        rm_out[:] = rm
        nf_out[:] = nf
        size_out[:] = sz

    row_spec = pl.BlockSpec((b, s), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    # Per-node vectors ride as [rows, 1] planes: 1-D VMEM refs are the
    # Mosaic TC pattern the gossip kernel already had to avoid — every
    # use broadcasts against the [rows, S] planes anyway
    # (_receive_body's column-vector contract).
    col_spec = pl.BlockSpec((b, 1), lambda i: (i, 0),
                            memory_space=pltpu.VMEM)
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),   # t
        row_spec, row_spec, row_spec, row_spec,  # view/ts/mail/cand
        col_spec, col_spec, col_spec,            # recv, act, self_on
        col_spec, col_spec,                      # self_pack, row_ids
    ]
    operands = [jnp.asarray(t, I32).reshape(1), view, view_ts, mail, cand,
                recv_mask.astype(I32)[:, None], act.astype(I32)[:, None],
                self_on.astype(I32)[:, None], self_pack[:, None],
                row_ids[:, None]]
    if admit_mask is not None:
        in_specs.append(row_spec)                # admit mask (i32 plane)
        operands.append(admit_mask.astype(I32))
    from distributed_membership_tpu.observability.timeline import (
        PHASE_RECEIVE)
    with jax.named_scope(PHASE_RECEIVE):
        out = pl.pallas_call(
            kernel,
            grid=grid,
            in_specs=in_specs,
            out_specs=[row_spec, row_spec, row_spec, row_spec, row_spec,
                       col_spec, col_spec],
            # Donate the big state buffers in place (view->view, ts->ts,
            # mail->mail_cleared): no duplicate [N, S] allocations live
            # across the call — the point of an HBM-roofline kernel.
            # (Input index 0 is the SMEM t scalar, so state inputs start
            # at 1.)
            input_output_aliases={1: 0, 2: 1, 3: 2},
            out_shape=[
                jax.ShapeDtypeStruct((rows, s), U32),   # view
                jax.ShapeDtypeStruct((rows, s), I32),   # view_ts
                jax.ShapeDtypeStruct((rows, s), U32),   # mail cleared
                jax.ShapeDtypeStruct((rows, s), I32),   # join mask (i32)
                jax.ShapeDtypeStruct((rows, s), I32),   # rm ids
                jax.ShapeDtypeStruct((rows, 1), I32),   # numfailed
                jax.ShapeDtypeStruct((rows, 1), I32),   # size
            ],
            interpret=interpret,
        )(*operands)
    (view2, ts2, mailc, join_i, rm_ids, nf, size) = out
    return (view2, ts2, mailc, join_i != 0, rm_ids, nf[:, 0], size[:, 0])
