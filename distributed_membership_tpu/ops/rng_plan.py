"""Batched per-tick RNG plan for the ring-exchange steps.

**The problem.**  The ring step consumes half a dozen independent random
streams per tick — gossip-shift draws, per-shift drop masks, entry
thinning, control-plane drop coins, the seed-burst coin, probe- and
ack-leg coins.  Each was drawn at its use site with its own
``jax.random.uniform``/``bernoulli`` call, so XLA lowers one threefry
expansion per call: the round-4 HLO census at 1M_s16 attributed ~9G
element-ops/tick to threefry fusions, one of the two remaining suspects
for the unexplained ~100 ms/tick (PERF.md "Round-5 levers").

**The fix.**  Same keys, same bits, fewer invocations: every draw keeps
the key derivation the scattered code used (``split(key, 8)``,
``fold_in(k_drop, j)``, …), but draws of equal FLAT element count are
stacked and produced by ONE vmapped ``jax.random.uniform`` over the
stacked key tensor.  vmap of the threefry primitive batches into a
single larger invocation, and a vmapped draw is defined to equal the
per-key draw — so the streams are bit-for-bit the scattered ones (the
whole trajectory stays pinned against the natural path;
tests/test_rng_plan.py).  Grouping is by flat count because threefry's
counter pairing depends on the total draw size: ``uniform(k, (n, s))``
equals ``uniform(k, (n*s,)).reshape(n, s)`` (same flat stream — the
contract tpu_hash_folded already relies on) but NOT a prefix of a
longer draw, so only same-size draws may share an invocation.

**Modes** (``RNG_MODE`` conf key, resolved into ``HashConfig.rng_mode``):

* ``scattered`` — one threefry per draw site, the pre-plan lowering.
  Kept as the A/B arm for the ladder rungs (``1M_s16_onegather``
  isolates the gather consolidation on this arm) and the bit-exactness
  pins.
* ``batched`` (default) — the grouped vmapped draws above.
* ``hoisted`` — opt-in, chunked runs only (``CHECKPOINT_EVERY`` > 0):
  the whole segment's plans are pre-drawn as ``[K, ...]`` tensors by
  vmapping the builder over the segment's tick keys, so RNG leaves the
  per-tick critical path entirely (the scan consumes slices).  Memory
  cost is O(K * fanout * N * S) floats — pick CHECKPOINT_EVERY
  accordingly (README).

The drop coins are stored as uniforms, not booleans: ``bernoulli(key,
p, shape)`` is definitionally ``uniform(key, shape, f32) < p``
(jax._src.random._bernoulli), so comparing the planned uniform against
``p`` at the use site reproduces the coin bit-for-bit — and keeps the
plan valid for the dynamic-knob sweeps where ``p`` is traced
(sweeps/phase.py).
"""

from __future__ import annotations

import math
from typing import NamedTuple

import jax
import jax.numpy as jnp

I32 = jnp.int32


def batched_uniforms(requests, batched: bool = True):
    """Draw ``[(key, shape), ...]`` uniforms; one threefry per flat-count
    group when ``batched`` (one per request otherwise).  Returns the
    draws FLAT (callers reshape to their layout — natural or folded —
    which cannot change the bits, by the flat-count contract above)."""
    out = [None] * len(requests)
    if not batched:
        for i, (k, shape) in enumerate(requests):
            out[i] = jax.random.uniform(k, shape).reshape(-1)
        return out
    groups: dict = {}
    for i, (_, shape) in enumerate(requests):
        groups.setdefault(math.prod(shape), []).append(i)
    for cnt, idxs in groups.items():
        if len(idxs) == 1:
            i = idxs[0]
            out[i] = jax.random.uniform(requests[i][0], (cnt,))
            continue
        keys = jnp.stack([requests[i][0] for i in idxs])
        flat = jax.vmap(lambda k: jax.random.uniform(k, (cnt,)))(keys)
        for row, i in enumerate(idxs):
            out[i] = flat[row]
    return out


_EMPTY = None   # placeholder builder below keeps pytree structure static


def _empty():
    return jnp.zeros((0,), jnp.float32)


class RingRng(NamedTuple):
    """One tick's random material for the ring step (flat arrays; every
    consumer reshapes to its own layout).  Fields are zero-length
    placeholders when the config doesn't consume that stream, so the
    pytree structure is static across modes and the whole tuple can ride
    ``lax.scan`` xs in hoisted mode."""
    shift_draw: jax.Array   # [k_max] i32 — shift values, or table indices
    #                         when SHIFT_SET (the raw randint draw)
    thin_u: jax.Array       # [N*S] f32 entry-thinning uniforms (g < s)
    gossip_u: jax.Array     # [k_max, N*S] f32 per-shift drop coins
    ctrl_u: jax.Array       # [2*N] f32 control-plane drop coins
    burst_u: jax.Array      # [cap*S] f32 seed-burst drop coins
    probe_u: jax.Array      # [N*P] f32 probe-leg (issue-time) drop coins
    ack_u: jax.Array        # [N*P] f32 ack-leg drop coins


def hash_ring_rng(key, *, n: int, s: int, g: int, k_max: int, p_cnt: int,
                  seed_rows: int, shift_set: int, use_drop: bool,
                  need_ctrl: bool, need_burst: bool,
                  batched: bool = True) -> RingRng:
    """The single-chip ring step's per-tick plan (tpu_hash.make_step ring
    branch and its folded twin — identical keys and flat counts, so the
    two layouts stay bit-exact on the same seed).

    Key derivation is EXACTLY the scattered step's: ``split(key, 8)`` to
    ``(k_targets, k_entries, k_drop, k_ctrl, k_drop_p, k_shifts, k_ack1,
    k_ack2)``, per-shift drop keys ``fold_in(k_drop, j)``, the seed-burst
    coin on raw ``k_drop`` (ring mode's ``k_drop_s``)."""
    (_k_targets, k_entries, k_drop, k_ctrl, _k_drop_p, k_shifts,
     k_ack1, k_ack2) = jax.random.split(key, 8)

    if shift_set:
        shift_draw = jax.random.randint(k_shifts, (k_max,), 0, shift_set)
    else:
        shift_draw = jax.random.randint(k_shifts, (k_max,), 1, max(n, 2))

    req = []
    slots = {}

    def want(name, k, shape, when=True):
        if when:
            slots[name] = len(req)
            req.append((k, shape))

    want("thin", k_entries, (n, s), g < s)
    if use_drop:
        for j in range(k_max):
            want(f"gossip{j}", jax.random.fold_in(k_drop, j), (n, s))
        want("ctrl", k_ctrl, (2, n), need_ctrl)
        want("burst", k_drop, (seed_rows, s), need_burst)
        want("probe", k_ack1, (n, p_cnt), p_cnt > 0)
        want("ack", k_ack2, (n, p_cnt), p_cnt > 0)
    drawn = batched_uniforms(req, batched=batched)

    def got(name):
        return drawn[slots[name]] if name in slots else _empty()

    gossip = ([drawn[slots[f"gossip{j}"]] for j in range(k_max)]
              if use_drop and k_max > 0 and "gossip0" in slots else [])
    return RingRng(
        shift_draw=shift_draw,
        thin_u=got("thin"),
        gossip_u=(jnp.stack(gossip) if gossip
                  else jnp.zeros((0, 0), jnp.float32)),
        ctrl_u=got("ctrl"),
        burst_u=got("burst"),
        probe_u=got("probe"),
        ack_u=got("ack"),
    )


def sharded_ring_rng(key, me, *, n: int, n_local: int, s: int, g: int,
                     k_max: int, p_cnt: int, seed_rows: int,
                     use_drop: bool, cold_join: bool,
                     batched: bool = True) -> RingRng:
    """The sharded ring step's plan (tpu_hash_sharded
    make_ring_sharded_step and its folded twin), built INSIDE shard_map:
    per-shard streams from ``fold_in(key, me)`` / ``split(key_l, 4)``,
    the replicated streams from the shared tick key (shifts at fold_in
    0x517F, cold-join control at 0xC281, burst at 0xB125) — exactly the
    scattered derivations."""
    key_l = jax.random.fold_in(key, me)
    k_entries, k_probe_drop, k_ack2, k_dropg = jax.random.split(key_l, 4)
    k_shifts = jax.random.fold_in(key, 0x517F)
    shift_draw = jax.random.randint(k_shifts, (k_max,), 1, max(n, 2))

    req = []
    slots = {}

    def want(name, k, shape, when=True):
        if when:
            slots[name] = len(req)
            req.append((k, shape))

    want("thin", k_entries, (n_local, s), g < s)
    if use_drop:
        for j in range(k_max):
            want(f"gossip{j}", jax.random.fold_in(k_dropg, j),
                 (n_local, s))
        want("ctrl", jax.random.fold_in(key, 0xC281), (2, n), cold_join)
        want("burst", jax.random.fold_in(key, 0xB125), (seed_rows, s),
             cold_join)
        want("probe", k_probe_drop, (n_local, p_cnt), p_cnt > 0)
        want("ack", k_ack2, (n_local, p_cnt), p_cnt > 0)
    drawn = batched_uniforms(req, batched=batched)

    def got(name):
        return drawn[slots[name]] if name in slots else _empty()

    gossip = ([drawn[slots[f"gossip{j}"]] for j in range(k_max)]
              if use_drop and k_max > 0 and "gossip0" in slots else [])
    return RingRng(
        shift_draw=shift_draw,
        thin_u=got("thin"),
        gossip_u=(jnp.stack(gossip) if gossip
                  else jnp.zeros((0, 0), jnp.float32)),
        ctrl_u=got("ctrl"),
        burst_u=got("burst"),
        probe_u=got("probe"),
        ack_u=got("ack"),
    )
