"""Batched fanout exchange: every gossip shift in ONE collective round.

The legacy ring exchange (``tpu_hash_sharded`` / ``tpu_hash_folded``
gossip loops) pays one ``make_block_send`` launch PER SHIFT — a
``lax.switch`` whose executed branch is a masked ``ppermute`` rotation
pair per mesh axis, so a tick costs O(fanout x axes) sequential
collective launches, each a full DCN round-trip latency at pod scale.
This module collapses that to O(axes): the SENDER applies the receive
alignment (block-relative row roll + slot-stride column rolls — all
per-shard local ops that commute with transport, because transport is a
pure permutation of whole [L, S] blocks and the alignment constants
depend only on ``(b, c)`` and the DESTINATION index, which the sender
knows: ``r = (me + b) mod D``), buckets the aligned payloads by
destination shard, and ships all buckets in a single tuple-axis
``lax.all_to_all``.

Bucketing is scatter-free on purpose: destinations are traced scalars,
so a ``.at[r].max`` combine would emit a scatter per shift — the
hlo_census gather/scatter budget pins would move, and XLA's scatter is
the op class the [1M,16] roofline work evicted.  Instead each shift
folds in with a masked select over the static destination iota
(``where(iota == r, aligned, 0)`` + ``maximum``), exact because the
payload combine is a u32 max with identity 0 and the count combine an
i32 sum with identity 0 — the same associative/commutative merges the
legacy receiver applies one shift at a time.

The exchanged buffers form the double-buffered carry lane
(``zero_xbuf`` / head-merge / boundary flush in the step builders):
tick t's all_to_all result is CONSUMED at tick t+1's head, which is
exactly when the legacy merge becomes observable (mail is only read by
the receive pass at the head of the next tick), so deferral is
bit-exact while freeing XLA to overlap the collective with the probe /
agg tail of the producing tick.

Wire format: one operand per tick.  Counts ride as extra rows of the
payload plane — ``[L]`` i32 cast to u32 (counts are nonnegative, the
cast is exact), zero-padded up to full rows of the payload's lane width
and concatenated on the row axis — so the collective moves a single
``[D, rows, lanes]`` array instead of a tuple (one launch, not two).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

I32 = jnp.int32
U32 = jnp.uint32


class BatchedExchange:
    """Per-tick batched gossip exchange for one sharded-step build.

    Natural layout: payload planes are ``[L, S]`` (``folded=False``).
    Folded layout: planes are ``[lf, 128]`` with ``f = 128 // s`` nodes
    per row (``folded=True``).  Counts are ``[L]`` i32 in both.
    """

    def __init__(self, *, n_shards: int, axes, n_local: int, s: int,
                 cstride: int, single_col_roll: bool,
                 folded: bool = False, lanes: int = 128):
        self.d = n_shards
        # Tuple axis ⇒ flattened outer-major semantics: bucket k of the
        # all_to_all is flat shard k, matching ``me = lax.axis_index(AX)``
        # in the step builders and the flat-index block arithmetic.
        self.ax = axes if len(axes) > 1 else axes[0]
        self.n_local = n_local
        self.s = s
        self.cstride = cstride
        self.single_col_roll = single_col_roll
        self.folded = folded
        if folded:
            self.f = lanes // s
            self.lf = n_local // self.f
            self.pay_shape = (n_shards, self.lf, lanes)
        else:
            self.pay_shape = (n_shards, n_local, s)
        self.cnt_shape = (n_shards, n_local)
        self._l_idx = jnp.arange(n_local, dtype=I32)
        self._dst_iota = jnp.arange(n_shards, dtype=I32)

    # ---- carry lane -------------------------------------------------
    def zero(self):
        """Empty destination buckets / empty carried xbuf (identity of
        both combines, so a zero xbuf head-merges as a no-op)."""
        return (jnp.zeros(self.pay_shape, U32),
                jnp.zeros(self.cnt_shape, I32))

    # ---- sender side ------------------------------------------------
    def _rep(self, v):
        # [L] per-node vector -> folded plane (f nodes x s lanes per row).
        return jnp.repeat(v.reshape(self.lf, self.f), self.s, axis=1,
                          total_repeat_length=self.pay_shape[2])

    def _align(self, payload, b, c, r):
        """Apply the receive alignment on the SENDER for destination
        ``r`` — verbatim the legacy receiver math with ``me := r``."""
        dd, ll, s = self.d, self.n_local, self.s
        bp = jnp.where(r < b, b - dd, b)
        base1 = lax.rem(lax.rem(bp * ll + c, s) + s, s)
        s1 = lax.rem(base1 * self.cstride, s)
        base2 = lax.rem(lax.rem(bp * ll + c - ll, s) + s, s)
        s2 = lax.rem(base2 * self.cstride, s)
        if self.folded:
            from distributed_membership_tpu.backends.tpu_hash_folded import (
                roll_nodes, roll_slots)
            p = roll_nodes(payload, c, self.f, s)
            r1 = roll_slots(p, s1, s)
            if self.single_col_roll:
                return r1
            return jnp.where(self._rep(self._l_idx >= c),
                             r1, roll_slots(p, s2, s))
        p = jnp.roll(payload, c, axis=0)
        r1 = jnp.roll(p, s1, axis=1)
        if self.single_col_roll:
            return r1
        return jnp.where((self._l_idx >= c)[:, None],
                         r1, jnp.roll(p, s2, axis=1))

    def add_shift(self, pay, cnt, payload, cnt_j, b, c, me):
        """Fold one gossip shift ``u = b*L + c`` into the destination
        buckets (scatter-free masked combine; see module docstring)."""
        r = lax.rem(me + b, self.d)
        aligned = self._align(payload, b, c, r)
        cnt_r = jnp.roll(cnt_j, c, axis=0)
        hit = self._dst_iota == r
        pay = jnp.maximum(pay, jnp.where(hit[:, None, None],
                                         aligned[None], U32(0)))
        cnt = cnt + jnp.where(hit[:, None], cnt_r[None], I32(0))
        return pay, cnt

    # ---- the one collective ----------------------------------------
    def exchange(self, pay, cnt):
        """Ship all buckets: ONE ``all_to_all`` across the whole mesh.

        Returns ``(pay_recv, cnt_recv)`` where slice ``k`` is what flat
        shard ``k`` addressed to this shard (self-delivery included)."""
        from distributed_membership_tpu.observability.timeline import (
            PHASE_COLLECTIVE)
        dd, ll = self.d, self.n_local
        lanes = self.pay_shape[2]
        rows = self.pay_shape[1]
        cnt_u = cnt.astype(U32)
        pad = (-ll) % lanes
        if pad:
            cnt_u = jnp.concatenate(
                [cnt_u, jnp.zeros((dd, pad), U32)], axis=1)
        buf = jnp.concatenate([pay, cnt_u.reshape(dd, -1, lanes)], axis=1)
        if dd > 1:
            with jax.named_scope(PHASE_COLLECTIVE):
                buf = lax.all_to_all(buf, self.ax, 0, 0)
        pay_r = buf[:, :rows]
        cnt_r = buf[:, rows:].reshape(dd, -1)[:, :ll].astype(I32)
        return pay_r, cnt_r

    # ---- receiver side (next tick's head / boundary flush) ----------
    def merge_mail(self, mail, pay_recv):
        return jnp.maximum(mail, pay_recv.max(0))

    def merge_pending(self, cnt_recv):
        return cnt_recv.sum(0)

    def wipe(self, pay, cnt, up_now):
        """Zero a restarting node's undelivered rows in the freshly
        exchanged buffers.  The legacy step merges gossip into mail
        BEFORE the scenario up/down wipe; with delivery deferred one
        tick the wipe must chase it into the xbuf — ``where(mask, 0, .)``
        distributes over both max and sum, so wiping the two halves
        separately equals the legacy wipe of the merged value."""
        plane = self._rep(up_now) if self.folded else up_now[:, None]
        pay = jnp.where(plane[None], U32(0), pay)
        cnt = jnp.where(up_now[None, :], I32(0), cnt)
        return pay, cnt
