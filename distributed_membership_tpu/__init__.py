"""distributed_membership_tpu — a TPU-native gossip-membership framework.

A ground-up rebuild of the capabilities of patour/distributed-membership
(the Coursera MP1 gossip-heartbeat membership protocol + EmulNet discrete-tick
network simulator, reference mounted at /root/reference) designed for TPU
hardware from the start:

- the per-node protocol step (reference ``MP1Node::nodeLoop``, MP1Node.cpp:182)
  becomes a single jitted tensor transition over an ``(N_nodes x member_view)``
  state, run under ``lax.scan`` for a whole simulation with no per-tick host sync;
- the message queue (reference ``EmulNet``, EmulNet.cpp) disappears: the LIST
  gossip burst is semantically heartbeat-max propagation over a random K-fanout
  graph, implemented as masked scatter-max on one chip and ring reduce-max /
  ``all_to_all`` over ICI when the node axis is sharded across a mesh;
- the tick driver (reference ``Application::run``, Application.cpp:90) survives
  as a thin host loop that selects a backend via the ``BACKEND:`` config key
  while keeping the reference's ``.conf`` format and ``dbg.log`` event-log
  contract, so the original grader checks pass unchanged at N=10.

Layout:
    config         Params / .conf parsing (reference Params.{h,cpp})
    addressing     Address model (reference Member.h:29-55)
    eventlog       dbg.log / stats.log writer (reference Log.{h,cpp})
    grader         Python port of the grading oracle (Grader_verbose.sh)
    backends/      'emul' (faithful queue semantics) and 'tpu' (vectorized)
    ops/           merge / sampling kernels
    parallel/      mesh + collectives (ppermute ring reduce-max, sharded step)
    runtime/       tick engine, failure injection, CLI
    observability/ msgcount counters + dump (reference EmulNet.cpp:184-218)
    native/        C++ host simulator core (accelerated emul backend)
"""

__version__ = "0.1.0"

from distributed_membership_tpu.config import Params  # noqa: F401
