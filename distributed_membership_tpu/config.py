"""Params: configuration + simulation clock.

Rebuild of the reference's ``Params`` (Params.h:23-33, Params.cpp:19-50).
The reference fscanf's exactly four keys from a ``.conf`` file
(``MAX_NNB, SINGLE_FAILURE, DROP_MSG, MSG_DROP_PROB``, Params.cpp:22-25) and
derives ``EN_GPSZ = MAX_NNB``, ``STEP_RATE = .25``, ``MAX_MSG_SIZE = 4000``
(Params.cpp:29-31).  This parser accepts those files byte-for-byte and extends
the format with optional ``KEY: value`` lines (notably ``BACKEND:``) while
remaining ignorable by the reference's fscanf (extensions go after the four
legacy keys).

Like the reference, Params doubles as the global simulation clock:
``getcurrtime()`` returns ``globaltime`` which the driver increments
(Application.cpp:99, Params.cpp:48-50).
"""

from __future__ import annotations

import dataclasses
import re

# Reference compile-time constants, kept as defaults but made configurable
# (MP1Node.h:21-22, Application.h:27, MP1Node.cpp:456, EmulNet.h:10-12).
DEFAULT_TFAIL = 5
DEFAULT_TREMOVE = 20
DEFAULT_TOTAL_TIME = 700
DEFAULT_FANOUT = 5
DEFAULT_EN_BUFFSIZE = 30000
DEFAULT_PORTNUM = 8001  # Params.cpp:12 (unused for addressing: ENinit forces port 0)

_KNOWN_BACKENDS = ("emul", "emul_native", "tpu", "tpu_sharded", "tpu_sparse",
                   "tpu_hash", "tpu_hash_sharded")


@dataclasses.dataclass
class Params:
    """All simulation knobs plus the global clock.

    Field groups:
      * legacy .conf keys — identical meaning to Params.h:23-28;
      * derived values — same derivations as Params.cpp:29-34;
      * extensions — new keys for the TPU rebuild (backend select, seed,
        scale, protocol constants that were #defines in the reference).
    """

    # --- legacy keys (Params.cpp:22-25) ---
    MAX_NNB: int = 10
    SINGLE_FAILURE: int = 1
    DROP_MSG: int = 0
    MSG_DROP_PROB: float = 0.0

    # --- derived (Params.cpp:29-34) ---
    EN_GPSZ: int = 10          # == MAX_NNB
    STEP_RATE: float = 0.25
    MAX_MSG_SIZE: int = 4000
    globaltime: int = 0
    dropmsg: int = 0

    # --- constants promoted from #defines ---
    PORTNUM: int = DEFAULT_PORTNUM
    TFAIL: int = DEFAULT_TFAIL
    TREMOVE: int = DEFAULT_TREMOVE
    TOTAL_TIME: int = DEFAULT_TOTAL_TIME
    FANOUT: int = DEFAULT_FANOUT
    EN_BUFFSIZE: int = DEFAULT_EN_BUFFSIZE

    # --- rebuild extensions ---
    BACKEND: str = "emul"
    SEED: int = 0
    # JOIN_MODE 'staggered' reproduces the reference's t == int(STEP_RATE*i)
    # introduction schedule (Application.cpp:143); 'batch' starts every node at
    # t=0 (introducer at t=0, joiners send JOINREQ at t=0) for scale runs.
    JOIN_MODE: str = "staggered"
    # Failure-injection schedule (reference hardcodes these: Application.cpp:177-200).
    FAIL_TIME: int = 100
    DROP_START: int = 50
    DROP_STOP: int = 300
    # Bounded member view (0 = full list). The spec explicitly permits a
    # partial fixed-size list; this is the 1M-node scaling mechanism.
    VIEW_SIZE: int = 0
    # Entries piggybacked per gossip message in the sparse backend.
    GOSSIP_LEN: int = 0  # 0 = whole view
    # Per-receiver mailbox slots in the sparse backend (0 = auto: lossless
    # == N while affordable, else sized to the expected per-tick in-traffic).
    MAILBOX_SIZE: int = 0
    # SWIM direct probes per tick in the sparse backend (0 = pure gossip).
    # Required for bounded views at scale: refresh by gossip alone decays as
    # FANOUT*GOSSIP_LEN/N (backends/tpu_sparse.py docstring).
    PROBES: int = 0
    # Correlated failure injection for scale scenarios: fail RACK_FAILURES
    # whole racks of RACK_SIZE contiguous nodes at FAIL_TIME.
    RACK_SIZE: int = 0
    RACK_FAILURES: int = 0
    # Event extraction mode on the bounded-view backends: 'full' stacks
    # per-tick event tensors and reconstructs dbg.log exactly (grader
    # parity; O(T*N*M) memory — ~350 GB at N=1M), 'agg' folds events into
    # O(N) on-device aggregates and reports a detection summary instead
    # (observability/aggregates.py), 'auto' picks by cluster size.
    EVENT_MODE: str = "auto"
    # Message-exchange lowering on the tpu_hash backend: 'scatter' is the
    # reference-shaped delivery (sampled targets + scatter-max), 'ring' the
    # TPU fast path (circulant-roll gossip + gather-pipeline probes — see
    # backends/tpu_hash.py make_step), 'auto' picks ring for warm-join
    # bounded-view scale runs and scatter otherwise.
    EXCHANGE: str = "auto"
    # Cross-shard wire lowering of the ring gossip shifts on
    # tpu_hash_sharded (ops/exchange.py): 'legacy' moves each of the
    # `fanout` shift payloads with its own masked ppermute rotation per
    # mesh axis (O(fanout*axes) sequential collective launches per
    # tick), 'batched' aligns every shift on the SENDER, max/sum-combines
    # same-destination payloads into per-shard buckets, and ships them
    # all in ONE all_to_all per tick (<= axes collective launches),
    # double-buffering the result through the scan carry so the
    # collective overlaps the probe/agg tail of the tick that issued it.
    # Trajectory-inert: bit-exact vs legacy (tests/test_exchange.py), so
    # checkpoints ignore it and a resume may switch modes.  '-1' = auto:
    # batched IFF on a real TPU with a banked bit-exactness verdict for
    # the exchange family (runtime/fusegate.py — fail closed, exactly
    # the FUSED_* posture); elsewhere legacy.
    EXCHANGE_MODE: str = "-1"
    # Run the ring receive pass as one Pallas kernel (ops/fused_receive)
    # instead of the fused-by-XLA jnp expression.  Requires EXCHANGE ring
    # and VIEW_SIZE % 128 == 0; interpret-mode fallback off-TPU.
    # 1 = on (structural violations raise), 0 = off, -1 = auto: on IFF
    # the process resolved to a real TPU, the config structurally
    # supports the kernel, AND the chip has a banked bit-exactness
    # verdict for the family (runtime/fusegate.py — fail closed).
    FUSED_RECEIVE: int = -1
    # Deliver all circulant gossip shifts in one Pallas traversal
    # (ops/fused_gossip) instead of fanout separate roll+max passes.
    # Requires EXCHANGE ring, VIEW_SIZE % 128 == 0, and N a multiple of
    # the view size ((N*STRIDE) % S == 0).  DROP_MSG, drop windows, and
    # scenario link-flakes all compose: the per-shift keep masks are
    # precomputed from the exact unfused RNG streams and ride the kernel
    # as inputs (bit-exact trajectories either way).
    # 1/0/-1 as FUSED_RECEIVE (auto gated on banked chip evidence).
    FUSED_GOSSIP: int = -1
    # Run the probe-window read plus the FastAgg removal reductions and
    # the TELEMETRY hist staleness/suspicion bucket counts as ONE Pallas
    # traversal of the post-receive planes (ops/fused_probe) instead of
    # separate full-tensor passes.  Requires EXCHANGE ring and
    # 0 < PROBES < VIEW_SIZE; drop coins and scenario cuts stay outside
    # in the cheap [N, PROBES] window space with the exact unfused
    # streams, so trajectories are bit-exact.
    # 1/0/-1 as FUSED_RECEIVE (auto gated on banked chip evidence).
    FUSED_PROBE: int = -1
    # Folded [N/F, 128] physical layout for VIEW_SIZE < 128 (F = 128/S):
    # removes the 128-lane padding that costs the S=16 regime ~8x HBM on
    # TPU (backends/tpu_hash_folded.py).  Requires EXCHANGE ring,
    # JOIN_MODE warm, aggregate events, 128 % VIEW_SIZE == 0.  Bit-exact
    # with the natural layout (same seed -> same trajectory).
    # 1/0/-1 as FUSED_RECEIVE (auto gated on banked chip evidence).
    FOLDED: int = -1
    # Multi-tick residency (ops/megakernel.py): fuse T protocol ticks
    # per outer scan iteration — the carry stays device-resident across
    # an inner T-tick loop and materializes at block boundaries only,
    # which CHECKPOINT_EVERY already defines (T must tile K; the run's
    # tail segment shorter than T runs a smaller block).  Requires the
    # ring exchange on tpu_hash/tpu_hash_sharded and CHECKPOINT_EVERY
    # > 0.  Bit-exact with the per-tick scan (same step function, same
    # operand stream — tests/test_megakernel.py); T=1 is op-count
    # identical to the plain program (tests/test_hlo_census.py).
    # T >= 2 = on, 0 = off, -1 = auto: on IFF the process resolved to a
    # real TPU, the config structurally supports it, AND the chip has a
    # banked bit-exactness verdict for the mega_t{T} family
    # (runtime/fusegate.py — fail closed, like FUSED_PROBE).
    MEGA_TICKS: int = -1
    # Shrunk T-block carry (ops/megakernel.py codec): timestamp planes
    # (view_ts/self_hb) cross block boundaries as 16-bit lanes packed
    # two-per-u32 and bool planes bit-packed 32-per-u32, cutting the
    # HBM bytes per boundary.  Bit-exact iff the run's effective tick
    # count fits the 16-bit bound (megakernel.PACK_SAFE_TICKS); the
    # check is static and host-side — 1 = on (an unprovable bound
    # raises), 0 = wide carry, -1 = auto (packs when the bound fits,
    # silently widens otherwise; auto never raises).  Needs MEGA_TICKS.
    MEGA_PACK: int = -1
    # Device-mesh shape for the sharded backends: '' = auto (largest
    # 1-D mesh dividing the node count), 'D' = 1-D over D devices,
    # 'OxI' = 2-D torus, 'SxOxI' = 3-D multi-slice torus (outermost
    # axis over DCN).  Ring exchange only — the block shifts decompose
    # into per-axis ring rotations (parallel/mesh.py,
    # tpu_hash_sharded.make_block_send).
    MESH_SHAPE: str = ""
    # Per-node attribution of probe-recv / ack-send counters on the
    # jitted ring paths: 'exact' builds the [N]-index histograms (and,
    # sharded, the [N] psum_scatter) that charge each message to its
    # true row at ANY size; 'approx' charges probe traffic to the
    # prober's row (totals stay exact — tests/test_probe_io.py);
    # 'auto' picks exact up to tpu_hash.PROBE_IO_EXACT_MAX nodes.
    # 'none' is PROFILING-ONLY: the probe-RECV and ack-SEND counters are
    # zeroed (probe sends and ack receives are still counted — msgcount
    # is asymmetric in this mode, not probe-free), which removes the
    # counter-side per-target random gather from the tick — the bisect
    # prices that gather on hardware with it (tpu_bisect.py 'nocount').
    # 'approx_lag' keeps the counters but rides them on the ack-value
    # gather (ONE [N, 2]-wide per-target gather per tick instead of two):
    # probe-recv/ack-send attribution is delayed one tick, run TOTALS
    # stay equal to exact (tests/test_probe_io.py), per-tick ack-send
    # columns shift by one.  Single-chip ring, natural layout only.
    PROBE_IO: str = "auto"
    # Enforce EmulNet's bounded send buffer (EN_BUFFSIZE, reference
    # ENBUFFSIZE=30000 with drop-on-full, EmulNet.cpp:92-94) on the
    # tpu_hash ring exchange as a per-tick global send budget: sends are
    # accepted in traversal order — join control (JOINREP then JOINREQ),
    # gossip shifts, the introducer seed burst, then probes; node-minor
    # within each — until the budget is spent, the rest drop.  A
    # budget-dropped JOINREQ/JOINREP strands the joiner FOREVER (the
    # reference's handshake never retries, MP1Node.cpp:126-159), so
    # cold-join storms over the cap permanently lose late joiners.  The
    # emul backends always enforce the cap exactly; the jitted paths
    # default to unbounded — see README "Network-semantics fidelity
    # notes" for the deviation list.
    ENFORCE_BUFFSIZE: int = 0
    # PRNG implementation for the jitted backends' key streams:
    # 'threefry2x32' (JAX default — deterministic across platforms and
    # the implicit pin of every bit-exactness test) or 'rbg'
    # (XLA's hardware RNG path — far cheaper on the TPU VPU, where the
    # per-tick [N, S] threefry draws are dense u32 compute; trajectories
    # change but stay protocol-valid, so scale/bench regimes can trade
    # cross-run bit-stability for throughput).  The host/emul backends
    # use Python RNG and ignore this key.
    PRNG_IMPL: str = "threefry2x32"
    # How the ring-exchange steps draw their per-tick random streams
    # (ops/rng_plan.py; bit-for-bit identical streams in every mode —
    # the keys and bits are the same, only the threefry invocation
    # structure changes):
    #   'scattered' — one threefry expansion per draw site (the
    #     pre-round-6 lowering; kept as the ladder's A/B arm),
    #   'batched' (default) — same-size draws stacked into ONE vmapped
    #     threefry over the stacked keys (~(1+fanout) [N, S] coins per
    #     tick collapse into one invocation — the round-4 census's ~9G
    #     threefry element-ops/tick suspect, engineered down),
    #   'hoisted' — batched AND pre-drawn per CHECKPOINT_EVERY segment
    #     as [K, ...] tensors, so RNG leaves the per-tick critical path
    #     entirely.  Opt-in: requires CHECKPOINT_EVERY > 0 and the
    #     single-chip tpu_hash backend, and costs
    #     O(CHECKPOINT_EVERY * fanout * N * S) floats of device memory.
    # Non-ring paths (scatter exchange, emul/dense/sparse backends)
    # keep their site-local draws regardless.
    RNG_MODE: str = "batched"
    # Probe/ack pipeline gather lowering on the ring paths:
    #   'packed' (default) — ack heartbeat + will-flush + act + counter
    #     bits ride ONE packed-u32 per-target gather per tick (indices
    #     for the t-2 ack application and the t-1 counter attribution
    #     concatenated into a single [N, 2P] gather; on the sharded
    #     ring the three [N] all_gathers collapse into one) — the
    #     mitigation for the census's four-[N, P]-random-gathers
    #     suspect, bit-exact with 'split' in every PROBE_IO mode,
    #   'split' — the pre-round-6 two-gather lowering, kept for the
    #     ladder's A/B arm (1M_s16_onegather) and the bit-exactness
    #     pins (tests/test_rng_plan.py, tests/test_probe_io.py).
    PROBE_GATHER: str = "packed"
    # Natural-layout roll mitigation (round-5 experiment): draw each
    # tick's gossip shifts from a STATIC K-entry table instead of
    # uniform [1, N), and deliver via lax.switch over K static-roll
    # branches.  At 1M_s16 XLA lays the [N, S] planes node-minor, which
    # turns the dynamic row-roll into a misaligned dynamic LANE rotate —
    # the suspected owner of the unattributed ~100 ms/tick (PERF.md);
    # static shifts compile to aligned copies.  Protocol-visible change:
    # the gossip graph becomes a union of K fixed circulants (table
    # includes shift 1, so it stays connected; spread is golden-ratio).
    # 0 = off (default).  Single-chip tpu_hash ring only; composes
    # with FOLDED (the switch branches make roll_nodes/roll_slots
    # fully static), not with FUSED_GOSSIP.
    SHIFT_SET: int = 0
    # Resilient-run harness (runtime/checkpoint.py): run the tick loop in
    # CHECKPOINT_EVERY-tick lax.scan segments instead of one monolithic
    # whole-run scan.  Between segments the full carry is pulled to host
    # and — when CHECKPOINT_DIR is set — snapshotted to a versioned
    # on-disk checkpoint (atomic write-rename + manifest), so a run
    # killed by a flaky relay resumes from the last segment instead of
    # producing nothing.  Chunking is bit-exact with the monolithic scan
    # (same step function, same per-tick fold_in key stream — pinned in
    # tests/test_checkpoint.py) and bounds the EVENT_MODE=full stacked
    # event tensors at O(CHECKPOINT_EVERY * N * M) device memory instead
    # of O(T * N * M).  0 = off (monolithic scan, the default).
    # Supported by the jitted backends (tpu, tpu_sparse, tpu_hash incl.
    # FOLDED, tpu_hash_sharded); the host emul paths reject it loudly.
    CHECKPOINT_EVERY: int = 0
    # Directory for checkpoint snapshots + MANIFEST.json ('' = chunk the
    # scan but persist nothing — the memory win without the disk I/O).
    CHECKPOINT_DIR: str = ""
    # 1 = write snapshots with np.savez_compressed (zlib): smaller files
    # and less disk bandwidth for more CPU per boundary — the snapshot
    # write runs on the background writer thread (runtime/checkpoint.py
    # double-buffers it against the next segment's device work), so the
    # CPU usually hides.  Resume reads either format transparently.
    CHECKPOINT_COMPRESS: int = 0
    # Flight recorder, part 1 (observability/timeline.py): 'scalars'
    # makes the jitted ring steps (tpu_hash natural + FOLDED,
    # tpu_hash_sharded) emit a small tuple of per-tick scalar reductions
    # — live/suspected counts, admissions, removals, true detections,
    # msgs sent/recv/dropped, probe acks, gossip payload rows — stacked
    # as [K]-shaped series per CHECKPOINT_EVERY segment and flushed
    # host-side into TELEMETRY_DIR/timeline.jsonl at every segment
    # boundary.  Trajectory-inert by construction (no RNG consumed, no
    # state touched — bit-exactness pinned in tests/test_timeline.py)
    # and structurally free when 'off' (the default program is op-count
    # identical — tests/test_hlo_census.py).  'hist' adds the
    # distribution tier on top: per-tick fixed-bucket histograms
    # (heartbeat staleness, suspicion age, detection latency, view
    # occupancy, drop counts — bucket edges in
    # observability/timeline.py) computed in-graph as bucketed one-hot
    # reductions — still no RNG/gathers/scatters (census-pinned), still
    # trajectory-inert, feeding the detection-latency SLO report
    # (scripts/run_report.py --slo).  Ring exchange only; the
    # scatter/emul paths reject the knob loudly.
    TELEMETRY: str = "off"
    # Directory for the flight-recorder artifacts: timeline.jsonl
    # (TELEMETRY: scalars) and runlog.jsonl (per-segment wall/sync/
    # checkpoint-overlap events from the chunked driver — written for
    # ANY chunked backend when this key is set, independent of
    # TELEMETRY).  '' = keep telemetry in memory only (the series still
    # lands in RunResult.extra['timeline']).
    TELEMETRY_DIR: str = ""
    # Declarative chaos schedule (scenario/ package): path to a scenario
    # JSON describing timed events — crash / restart / leave / partition
    # / link_flake / drop_window — compiled to in-scan tensor plans
    # (scenario/compile.py).  Legacy-shaped scenarios (crashes at one
    # time + at most one global drop window) lower to the unchanged
    # FailurePlan path and run on EVERY backend; general scenarios
    # (restarts, partitions, flaky links) run on emul and the ring
    # twins (tpu_hash incl. FOLDED, tpu_hash_sharded) and are rejected
    # loudly elsewhere at plan-resolution time.  '' = off.
    SCENARIO: str = ""
    # 1 = resume from CHECKPOINT_DIR's latest valid checkpoint when one
    # exists (manifest validated against this config/seed — a mismatch
    # raises instead of silently computing a different run); when none
    # exists the run starts fresh, so retry loops can always pass
    # RESUME: 1.  Requires CHECKPOINT_EVERY > 0 and CHECKPOINT_DIR.
    RESUME: int = 0
    # Membership control plane (service/ package): -1 = off (the
    # default batch posture), 0 = serve on an OS-assigned ephemeral
    # port (written to <out_dir>/service.json), 1..65535 = serve on
    # that port.  When armed the run is driven by the service daemon:
    # between CHECKPOINT_EVERY-tick segments it publishes a host
    # snapshot (liveness masks, heartbeat staleness, census) and
    # drains injected scenario events into the next segment's plan
    # tensors — so the key requires the chunked driver
    # (CHECKPOINT_EVERY > 0) and the ring-family backends whose carry
    # the snapshot decoder understands (tpu_hash, tpu_hash_sharded).
    # Trajectory-inert: dbg.log/timeline.jsonl/grades are bit-exact
    # vs. the same run with the service off (tests/test_service.py).
    SERVICE_PORT: int = -1
    # Decode + publish the host snapshot every k-th segment boundary
    # (1 = every boundary).  The decode is O(N*VIEW_SIZE) numpy on the
    # already-pulled carry; raise this on very large runs if the
    # boundary-time decode shows up in runlog.jsonl flush_s.
    SERVICE_SNAPSHOT_EVERY: int = 1
    # Read-replica pool (service/replica.py): 0 = queries answered by
    # the engine daemon's own API thread (the classic posture); W >= 1
    # spawns W read-only worker PROCESSES that map the snapshot shm
    # ring (service/shm_ring.py) and serve the whole GET surface on
    # their own ports (service.json lists them) — reads scale across
    # cores while writes (/v1/events, admin) stay on the engine
    # daemon.  Trajectory-inert, identity-excluded like SERVICE_PORT.
    SERVICE_WORKERS: int = 0
    # Slots in the shared-memory snapshot ring (>= 2).  A reader holds
    # a slot for at most one request while the writer cycles the ring,
    # so B slots give a reader B-1 publication intervals of slack
    # before a seqlock retry; raise it if replicas report torn reads
    # under very fast boundaries.
    SERVICE_SHM_BUFFERS: int = 4
    # Fleet controller (fleet/ package, ``--fleet``): one control-plane
    # process owning a journaled run registry and a bounded-worker
    # scheduler, multiplexing many runs (each a subprocess driving the
    # chunked engine) behind /v1/runs/<id>/.  The FLEET_* keys configure
    # the CONTROLLER (read from the optional conf given to --fleet);
    # they are trajectory-inert for any run that carries them.
    # -1 = off, 0 = ephemeral port (written to <dir>/fleet.json),
    # 1..65535 = that port.
    FLEET_PORT: int = -1
    # Max subprocess workers running concurrently; queued runs wait
    # FIFO within priority class (lower number = served first).
    FLEET_MAX_CONCURRENCY: int = 2
    # Root directory for the fleet: fleet_runs.jsonl (the submission
    # journal) + one subdirectory per run (conf, checkpoints,
    # telemetry, artifacts).  '' = the --fleet --out-dir.
    FLEET_DIR: str = ""
    # 1 = keep a completed run's worker daemon serving its final
    # snapshot until killed (tests/bench query completed runs
    # deterministically); 0 = shut workers down on completion so the
    # process table holds only ticking runs.
    FLEET_LINGER: int = 0
    # Automatic failover/migration policy (elastic/migrate.py): comma
    # list of triggers the scheduler acts on — 'death' (worker process
    # died with a durable checkpoint), 'alerts' (watchdog alert rules
    # firing in the run's runlog), 'stale-beacon' (progress beacon
    # stopped advancing) — '' = off (manual POST /v1/runs/<id>/migrate
    # still works).  Controller key, trajectory-inert.
    FLEET_MIGRATE_ON: str = ""
    # Per-run cap on AUTOMATIC migrations (manual drains don't count):
    # a run that keeps dying lands in a terminal failed state instead of
    # thrashing the fleet forever.  0 = manual migration only.
    FLEET_MIGRATE_MAX: int = 2
    # Mid-run SLO watchdog (observability/watchdog.py), served runs
    # only: a daemon thread evaluates degradation rules (tick-rate
    # collapse, publisher backlog growth, replica staleness, live
    # detection-latency SLO) at segment boundaries, off the engine
    # thread, emitting structured alert records into runlog.jsonl.
    # Trajectory-inert and identity-excluded like the SERVICE_* keys
    # (host-side observation only); 0 turns it off for overhead
    # benches.
    WATCHDOG: int = 1

    def getcurrtime(self) -> int:
        """Time since start of run, in ticks (Params.cpp:48-50)."""
        return self.globaltime

    # ------------------------------------------------------------------
    def setparams(self, config_file: str,
                  validate: bool = True) -> "Params":
        """Parse a .conf file (legacy 4-key format + extensions).

        Mirrors Params::setparams (Params.cpp:19-40): reads the four legacy
        keys, then derives EN_GPSZ / STEP_RATE / MAX_MSG_SIZE and zeroes the
        clock. Any further ``KEY: value`` lines set extension fields.
        """
        with open(config_file, "r") as fh:
            text = fh.read()
        self.parse(text, validate=validate)
        return self

    def parse(self, text: str, validate: bool = True) -> "Params":
        for line in text.splitlines():
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            m = re.match(r"([A-Za-z_][A-Za-z0-9_]*)\s*:\s*(.*)", line)
            if not m:
                continue
            key, raw = m.group(1), m.group(2).strip()
            self._set(key, raw)

        # Derivations, as Params.cpp:29-34.
        self.EN_GPSZ = self.MAX_NNB
        self.globaltime = 0
        self.dropmsg = 0
        if validate:
            self.validate()
        return self

    def _set(self, key: str, raw: str) -> None:
        if not hasattr(self, key):
            # Unknown keys are ignored (forward compatibility), matching the
            # reference's fscanf which simply never reads them.
            return
        cur = getattr(self, key)
        if isinstance(cur, bool):
            setattr(self, key, raw.lower() in ("1", "true", "yes"))
        elif isinstance(cur, int):
            setattr(self, key, int(raw))
        elif isinstance(cur, float):
            setattr(self, key, float(raw))
        else:
            setattr(self, key, raw)

    def validate(self) -> None:
        if self.BACKEND not in _KNOWN_BACKENDS:
            raise ValueError(
                f"BACKEND must be one of {_KNOWN_BACKENDS}, got {self.BACKEND!r}"
            )
        if self.EN_GPSZ < 1:
            raise ValueError("MAX_NNB must be >= 1")
        if self.EVENT_MODE not in ("auto", "full", "agg"):
            raise ValueError(
                f"EVENT_MODE must be auto|full|agg, got {self.EVENT_MODE!r}")
        if self.JOIN_MODE not in ("staggered", "batch", "warm"):
            raise ValueError(
                f"JOIN_MODE must be staggered|batch|warm, got {self.JOIN_MODE!r}")
        if self.EXCHANGE not in ("auto", "scatter", "ring"):
            raise ValueError(
                f"EXCHANGE must be auto|scatter|ring, got {self.EXCHANGE!r}")
        if self.EXCHANGE_MODE not in ("-1", "legacy", "batched"):
            raise ValueError(
                f"EXCHANGE_MODE must be -1|legacy|batched, got "
                f"{self.EXCHANGE_MODE!r}")
        if self.EXCHANGE_MODE == "batched" and self.EXCHANGE == "scatter":
            raise ValueError(
                "EXCHANGE_MODE batched applies to the ring exchange's "
                "gossip shifts (EXCHANGE ring/auto); the scatter lowering "
                "has no per-shift collective round to batch")
        if self.PRNG_IMPL not in ("threefry2x32", "rbg", "unsafe_rbg"):
            raise ValueError(
                f"PRNG_IMPL must be threefry2x32|rbg|unsafe_rbg, got "
                f"{self.PRNG_IMPL!r}")
        if self.PROBE_IO not in ("auto", "exact", "approx", "approx_lag",
                                 "none"):
            raise ValueError(
                f"PROBE_IO must be auto|exact|approx|approx_lag|none, "
                f"got {self.PROBE_IO!r}")
        if self.SHIFT_SET and not 2 <= self.SHIFT_SET <= 64:
            raise ValueError(
                f"SHIFT_SET must be 0 (off) or 2..64 static shift "
                f"candidates (got {self.SHIFT_SET}); each candidate adds "
                f"a lax.switch branch to the compiled step")
        if self.CHECKPOINT_EVERY < 0:
            raise ValueError(
                f"CHECKPOINT_EVERY must be >= 0 (0 = off), got "
                f"{self.CHECKPOINT_EVERY}")
        if self.CHECKPOINT_EVERY and self.BACKEND in (
                "emul", "emul_native", "tpu_sharded"):
            # Loud-rejection policy: the host emul loops and the legacy
            # dense-sharded path have no chunked driver — silently running
            # monolithic would drop the crash tolerance the key asks for.
            raise ValueError(
                f"CHECKPOINT_EVERY is not supported by BACKEND "
                f"{self.BACKEND!r} (chunked drivers: tpu, tpu_sparse, "
                "tpu_hash, tpu_hash_sharded)")
        # (CHECKPOINT_EVERY x PROBE_IO approx_lag composes since round 6:
        # the lag state rides the checkpointed carry and the counter
        # epilogue is applied by the chunked driver's finalize hook —
        # kill/resume bit-exactness pinned in tests/test_checkpoint.py.)
        if self.RNG_MODE not in ("scattered", "batched", "hoisted"):
            raise ValueError(
                f"RNG_MODE must be scattered|batched|hoisted, got "
                f"{self.RNG_MODE!r}")
        if self.RNG_MODE == "hoisted":
            # Loud-rejection policy: hoisting pre-draws one SEGMENT of
            # RNG, so it only exists inside the chunked driver, and only
            # the single-chip tpu_hash runners implement the plan-fed
            # scan (the sharded step derives per-shard streams inside
            # shard_map — hoist there would silently change nothing).
            if self.CHECKPOINT_EVERY <= 0:
                raise ValueError(
                    "RNG_MODE hoisted requires CHECKPOINT_EVERY > 0 "
                    "(the pre-drawn [K, ...] RNG tensors are segment-"
                    "scoped; a whole-run hoist would be O(T*fanout*N*S) "
                    "memory)")
            if self.BACKEND != "tpu_hash":
                raise ValueError(
                    "RNG_MODE hoisted is single-chip tpu_hash only "
                    f"(got BACKEND {self.BACKEND!r})")
            if self.resolved_exchange() != "ring":
                raise ValueError(
                    "RNG_MODE hoisted requires the ring exchange (the "
                    "scatter lowering keeps its site-local draws)")
        if self.TELEMETRY not in ("off", "scalars", "hist"):
            raise ValueError(
                f"TELEMETRY must be off|scalars|hist, got "
                f"{self.TELEMETRY!r}")
        if self.TELEMETRY in ("scalars", "hist"):
            # Loud-rejection policy (as PROBE_IO approx_lag / SHIFT_SET):
            # only the ring steps emit the per-tick series — silently
            # accepting the knob elsewhere would hand back an empty
            # timeline while claiming flight-recorder coverage.
            if self.BACKEND not in ("tpu_hash", "tpu_hash_sharded"):
                raise ValueError(
                    f"TELEMETRY {self.TELEMETRY} is implemented by the "
                    "ring backends only (tpu_hash, tpu_hash_sharded; "
                    f"got BACKEND {self.BACKEND!r})")
            if self.resolved_exchange() != "ring":
                raise ValueError(
                    f"TELEMETRY {self.TELEMETRY} requires the ring "
                    "exchange (the scatter lowering keeps the default "
                    "program)")
        if self.PROBE_GATHER not in ("packed", "split"):
            raise ValueError(
                f"PROBE_GATHER must be packed|split, got "
                f"{self.PROBE_GATHER!r}")
        if self.CHECKPOINT_COMPRESS not in (0, 1):
            raise ValueError(
                f"CHECKPOINT_COMPRESS must be 0 or 1, got "
                f"{self.CHECKPOINT_COMPRESS!r}")
        if self.RESUME not in (0, 1):
            raise ValueError(f"RESUME must be 0 or 1, got {self.RESUME!r}")
        if self.RESUME and not (self.CHECKPOINT_EVERY
                                and self.CHECKPOINT_DIR):
            raise ValueError(
                "RESUME: 1 requires CHECKPOINT_EVERY > 0 and a "
                "CHECKPOINT_DIR to resume from")
        if not -1 <= self.SERVICE_PORT <= 65535:
            raise ValueError(
                f"SERVICE_PORT must be -1 (off), 0 (ephemeral) or a "
                f"port in 1..65535, got {self.SERVICE_PORT}")
        if self.SERVICE_PORT >= 0:
            # The daemon's tick engine IS the chunked driver: snapshots
            # are decoded and events injected at segment boundaries, so
            # a monolithic scan has no seam to serve from.
            if self.CHECKPOINT_EVERY <= 0:
                raise ValueError(
                    "SERVICE_PORT requires CHECKPOINT_EVERY > 0 (the "
                    "control plane serves between scan segments — "
                    "runtime/checkpoint.py)")
            # Loud-rejection policy (as TELEMETRY / PROBE_IO): the
            # snapshot decoder reads the hash twins' packed-view carry;
            # silently serving another backend would answer queries
            # from a carry it cannot decode.
            if self.BACKEND not in ("tpu_hash", "tpu_hash_sharded"):
                raise ValueError(
                    "SERVICE_PORT is implemented by the ring-family "
                    "backends only (tpu_hash, tpu_hash_sharded; got "
                    f"BACKEND {self.BACKEND!r})")
            if self.FOLDED == 1:
                raise ValueError(
                    "SERVICE_PORT and FOLDED are incompatible (the "
                    "folded plane carry is not decodable by the "
                    "service snapshot reader; leave FOLDED on auto, "
                    "which keeps it off under the service)")
        if self.SERVICE_SNAPSHOT_EVERY < 1:
            raise ValueError(
                f"SERVICE_SNAPSHOT_EVERY must be >= 1 segment "
                f"boundaries, got {self.SERVICE_SNAPSHOT_EVERY}")
        if self.SERVICE_WORKERS < 0:
            raise ValueError(
                f"SERVICE_WORKERS must be >= 0 replica processes, got "
                f"{self.SERVICE_WORKERS}")
        if self.SERVICE_WORKERS > 0 and self.SERVICE_PORT < 0:
            raise ValueError(
                "SERVICE_WORKERS requires the control plane "
                "(SERVICE_PORT >= 0): the serve daemon publishes the "
                "shm ring the replicas read")
        if self.SERVICE_SHM_BUFFERS < 2:
            raise ValueError(
                f"SERVICE_SHM_BUFFERS must be >= 2 ring slots (the "
                f"seqlock needs a stable slot while the writer fills "
                f"another), got {self.SERVICE_SHM_BUFFERS}")
        if not -1 <= self.FLEET_PORT <= 65535:
            raise ValueError(
                f"FLEET_PORT must be -1 (off), 0 (ephemeral) or a "
                f"port in 1..65535, got {self.FLEET_PORT}")
        if self.FLEET_MAX_CONCURRENCY < 1:
            raise ValueError(
                f"FLEET_MAX_CONCURRENCY must be >= 1 worker, got "
                f"{self.FLEET_MAX_CONCURRENCY}")
        if self.FLEET_LINGER not in (0, 1):
            raise ValueError(
                f"FLEET_LINGER must be 0 or 1, got {self.FLEET_LINGER!r}")
        if self.FLEET_MIGRATE_ON:
            bad = [t for t in
                   (p.strip() for p in self.FLEET_MIGRATE_ON.split(","))
                   if t not in ("death", "alerts", "stale-beacon")]
            if bad:
                raise ValueError(
                    f"FLEET_MIGRATE_ON must be a comma list drawn from "
                    f"'death', 'alerts', 'stale-beacon', got {bad!r} in "
                    f"{self.FLEET_MIGRATE_ON!r}")
        if self.FLEET_MIGRATE_MAX < 0:
            raise ValueError(
                f"FLEET_MIGRATE_MAX must be >= 0 automatic migrations "
                f"per run (0 = manual only), got {self.FLEET_MIGRATE_MAX!r}")
        if self.WATCHDOG not in (0, 1):
            raise ValueError(
                f"WATCHDOG must be 0 or 1, got {self.WATCHDOG!r}")
        for knob in ("FUSED_RECEIVE", "FUSED_GOSSIP", "FUSED_PROBE",
                     "FOLDED"):
            if getattr(self, knob) not in (-1, 0, 1):
                raise ValueError(
                    f"{knob} must be 1 (on), 0 (off) or -1 (auto), got "
                    f"{getattr(self, knob)!r}")
        if self.MEGA_TICKS < -1:
            raise ValueError(
                f"MEGA_TICKS must be -1 (auto), 0 (off) or a positive "
                f"ticks-per-block T, got {self.MEGA_TICKS!r}")
        if self.MEGA_TICKS > 0:
            # Loud-rejection policy (as TELEMETRY / RNG_MODE hoisted):
            # only the ring-family scan runners implement the T-block
            # restructuring — silently accepting the knob elsewhere
            # would time/checkpoint a program that never blocked.
            if self.BACKEND not in ("tpu_hash", "tpu_hash_sharded"):
                raise ValueError(
                    "MEGA_TICKS is implemented by the ring backends "
                    "only (tpu_hash, tpu_hash_sharded; got BACKEND "
                    f"{self.BACKEND!r})")
            if self.CHECKPOINT_EVERY <= 0:
                raise ValueError(
                    "MEGA_TICKS requires CHECKPOINT_EVERY > 0 (T-tick "
                    "blocks tile the chunked segments; the monolithic "
                    "scan has no block boundary to align to — "
                    "runtime/checkpoint.py)")
            if self.CHECKPOINT_EVERY % self.MEGA_TICKS != 0:
                raise ValueError(
                    f"MEGA_TICKS ({self.MEGA_TICKS}) must tile "
                    f"CHECKPOINT_EVERY ({self.CHECKPOINT_EVERY}): "
                    "K % T == 0, so block boundaries and segment "
                    "boundaries coincide (only the run's final tail "
                    "segment may be shorter than T)")
        if self.MEGA_PACK not in (-1, 0, 1):
            raise ValueError(
                f"MEGA_PACK must be 1 (on), 0 (off) or -1 (auto), got "
                f"{self.MEGA_PACK!r}")
        if self.MEGA_PACK == 1 and self.MEGA_TICKS == 0:
            raise ValueError(
                "MEGA_PACK: 1 requires MEGA_TICKS (the shrunk carry "
                "exists only at T-block boundaries)")
        if self.MESH_SHAPE:
            parts = self.MESH_SHAPE.lower().split("x")
            if not (1 <= len(parts) <= 3
                    and all(p.isdigit() and int(p) > 0 for p in parts)):
                raise ValueError(
                    f"MESH_SHAPE must be 'D', 'OxI' or 'SxOxI' (positive "
                    f"ints; 3-D = multi-slice torus, outermost axis over "
                    f"DCN), got {self.MESH_SHAPE!r}")
            if self.BACKEND != "tpu_hash_sharded":
                # Only the flagship sharded backend reads the key; the
                # others build their own auto mesh and would silently run
                # on a different shape than requested.
                raise ValueError(
                    "MESH_SHAPE is only supported by BACKEND "
                    f"tpu_hash_sharded (got {self.BACKEND!r})")
        if self.JOIN_MODE == "warm" and self.BACKEND not in (
                "tpu_sparse", "tpu_hash", "tpu_hash_sharded"):
            # Warm bootstrap needs backend support (pre-seeded views); on the
            # introducer-join backends a -1 start tick would silently
            # simulate nothing.
            raise ValueError(
                f"JOIN_MODE warm is not supported by BACKEND {self.BACKEND!r}")
        # Heartbeats advance by +2 per tick (reference double increment,
        # MP1Node.cpp:412-414). int32 state is safe iff 2*TOTAL_TIME fits;
        # the TPU backends use int32 — make the bound explicit rather than
        # silently overflowing (SURVEY.md hard-part #5).
        if 2 * self.TOTAL_TIME >= 2**31:
            raise ValueError("TOTAL_TIME too large for int32 heartbeats")
        # SWIM protocol period: with bounded views, an entry is refreshed
        # once per probe cycle of ceil(VIEW_SIZE/PROBES) ticks, so
        # TFAIL/TREMOVE are meaningful only in units of that cycle.  A
        # TREMOVE spanning < 4 cycles leaves so few refresh chances that
        # ordinary percent-level message loss produces false removals in
        # bulk (measured: ~9k per 65k-node run at 2 cycles).  Reject the
        # misconfiguration instead of silently failing accuracy.
        if (self.PROBES > 0 and self.VIEW_SIZE > 0
                and self.BACKEND in ("tpu_sparse", "tpu_hash",
                                     "tpu_hash_sharded")):
            cycle = -(-self.VIEW_SIZE // self.PROBES)
            if self.TREMOVE < 4 * cycle:
                raise ValueError(
                    f"TREMOVE={self.TREMOVE} spans under 4 probe cycles "
                    f"(cycle = ceil(VIEW_SIZE/PROBES) = {cycle} ticks): "
                    "too few refresh chances per removal window; raise "
                    "TREMOVE or PROBES")
            k_min = self.min_tremove_cycles_under_loss()
            if k_min and self.TREMOVE < k_min * cycle:
                # Warning, not an error: the phase sweep intentionally maps
                # the false-removal knee below this floor.  Production
                # configs should heed it (measured: the floor is tight —
                # see artifacts/LOSS_STRESS.json).
                import warnings
                warnings.warn(
                    f"TREMOVE={self.TREMOVE} spans under "
                    f"{k_min} probe cycles (cycle={cycle}) at drop "
                    f"probability {self.effective_drop_prob()}: expected "
                    "false removals > 0 over this run "
                    "(Params.min_tremove_cycles_under_loss)",
                    stacklevel=2)

    def min_tremove_cycles_under_loss(self) -> int:
        """Smallest TREMOVE-in-probe-cycles making expected false removals
        < 1 over the whole run under the configured drop probability.

        A probe/ack round trip fails with q = 1-(1-p)^2 per cycle (both
        legs draw a coin — EmulNet.cpp:87-118 semantics); a false removal
        needs k = TREMOVE/cycle *consecutive* failed cycles for one entry,
        so by union bound the expected count is at most
        ``N * VIEW_SIZE * (TOTAL_TIME/cycle) * q**k``.  The model counts
        only probe/ack refreshes: gossip-driven refreshes (an entry also
        refreshes when any neighbor gossips a higher heartbeat for it)
        are deliberately ignored, so q overstates the per-cycle failure
        probability and the floor is an UPPER bound on the needed
        TREMOVE — a conservative warning that can fire for configs that
        are actually safe, never the reverse.  The floor sizes k
        so that bound is <= 0.01, not merely < 1: the knee is sharp — at
        N=65536, S=16, p=0.1 a k targeting expectation < 1 still produced
        one false removal (artifacts/LOSS_STRESS.json maps the knee), so
        the ln(100) ~ 4.6 tightening (~3 extra cycles at p=0.1) buys the
        measured-zero regime.
        Returns 0 when loss or probing is off."""
        import math

        p = self.effective_drop_prob()
        if p <= 0 or self.PROBES <= 0 or self.VIEW_SIZE <= 0:
            return 0
        cycle = -(-self.VIEW_SIZE // self.PROBES)
        # Loss applies only inside the drop window: the k consecutive
        # failed cycles a false removal needs must FIT in the window
        # (outside it, the round trip succeeds and refreshes the entry),
        # so the floor is capped at window//cycle + 1 — windowed-drop
        # configs like the grading scenario's [50, 300) aren't warned
        # about removals that cannot happen.
        window = min(self.DROP_STOP, self.TOTAL_TIME) - max(
            self.DROP_START, 0)
        if window <= 0:
            return 0
        q = 1.0 - (1.0 - p) ** 2
        cap = window // cycle + 1
        if q >= 1.0:
            # Total loss: no TREMOVE inside the window avoids false
            # removals; return the cap so the validate warning fires
            # whenever TREMOVE could fail inside the window.
            return max(4, cap)
        trials = (self.EN_GPSZ * self.VIEW_SIZE * max(window // cycle, 1))
        k = max(4, math.ceil(math.log(trials / 0.01) / -math.log(q)))
        return min(k, cap)

    def drop_pct(self) -> int:
        """Integer drop percentage, quantized once.

        The reference compares an integer percentage (``rand() % 100 <
        (int)(MSG_DROP_PROB * 100)``, EmulNet.cpp:92), so all backends must
        quantize identically — and exactly once: re-deriving the int from the
        float ratio loses a point for some values (int(0.57*100/100*100)=56).
        """
        return int(self.MSG_DROP_PROB * 100) if self.DROP_MSG else 0

    def effective_drop_prob(self) -> float:
        """The quantized drop probability as a float (see :meth:`drop_pct`)."""
        return self.drop_pct() / 100.0

    def validate_sparse_packing(self, total_time: int | None = None) -> None:
        """The sparse backend's mailbox packs (heartbeat, id) into uint32 as
        ``hb * N + id + 1`` (ops/view_merge.scatter_mailbox); heartbeats reach
        2*total_time + 2.  Reject configs where that overflows.

        ``total_time`` is the *effective* run length — callers that extend the
        run past TOTAL_TIME (bench/sweep drivers pass ``total_time=`` to
        run_scan) must validate against the extended value, or the overflow
        guard is silently bypassed."""
        total = self.TOTAL_TIME if total_time is None else total_time
        max_packed = (2 * total + 2) * self.EN_GPSZ + self.EN_GPSZ
        if max_packed >= 2**32:
            raise ValueError(
                f"MAX_NNB={self.EN_GPSZ} x total_time={total} "
                "overflows the sparse backend's uint32 (heartbeat, id) "
                "packing; reduce the run length or node count")

    def resolved_event_mode(self) -> str:
        """'full' or 'agg' (see EVENT_MODE).  The auto threshold is sized so
        the stacked [T, N, M] event tensors stay well under a GB."""
        if self.EVENT_MODE != "auto":
            return self.EVENT_MODE
        return "full" if self.EN_GPSZ <= 4096 else "agg"

    def resolved_exchange(self) -> str:
        """'scatter' or 'ring' (see EXCHANGE).  Auto picks the ring fast
        path exactly in the regime it was designed for — warm-join
        bounded-view scale runs — and the reference-shaped scatter
        elsewhere (cold joins, full views, the grader-parity sizes)."""
        if self.EXCHANGE != "auto":
            return self.EXCHANGE
        scale_run = (self.JOIN_MODE == "warm" and self.VIEW_SIZE > 0
                     and self.VIEW_SIZE < self.EN_GPSZ
                     and self.PROBES < max(self.VIEW_SIZE, 1))
        return "ring" if scale_run else "scatter"

    # ------------------------------------------------------------------
    def start_tick(self, i: int) -> int:
        """Tick at which node index i is introduced.

        Reference: node i starts when ``getcurrtime() == (int)(STEP_RATE*i)``
        (Application.cpp:143); with STEP_RATE=.25 that is i//4.
        """
        if self.JOIN_MODE == "warm":
            return -1  # active (and past the recv/act gates) from t=0
        if self.JOIN_MODE == "batch":
            return 0
        return int(self.STEP_RATE * i)

    @classmethod
    def from_file(cls, config_file: str,
                  validate: bool = True) -> "Params":
        return cls().setparams(config_file, validate=validate)

    @classmethod
    def from_text(cls, text: str) -> "Params":
        return cls().parse(text)
