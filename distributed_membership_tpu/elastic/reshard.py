"""Reshard-on-resume: rewrite a durable checkpoint for a new topology.

Every per-process checkpoint in this runtime stores the FULL GLOBAL
carry — the chunked driver gathers node-sharded leaves to host at each
segment boundary (runtime/distributed.py ``to_host``), so each
process's npz holds identical global arrays whose shapes depend on
(N, S, FOLDED) but never on MESH_SHAPE or the process count.  Resuming
onto a different topology is therefore a host-side metadata operation
plus an honest redistribution proof, not a device shuffle:

1. load the checkpointed carry from the source per-process dirs and
   cross-check they agree (tick, params identity, state hash);
2. validate the target geometry LOUDLY (mesh-shape grammar, N
   divisibility, proc divisibility, ``PACK_SAFE_TICKS`` / fold bounds) —
   the same refuse-don't-guess posture as config validation;
3. redistribute host-side: round-trip the carry through the
   ops/megakernel.py boundary codec (bit-packed bools, u16 stamp lanes
   when the static tick bound allows) and through the old→new per-shard
   row split, verifying bit-exactness — this is the transport a real
   cross-host migration pays, timed and byte-accounted for the bench;
4. stamp the manifest with a reshard-provenance record
   (``from_shape``/``to_shape``/``from_procs``/``to_procs``/carry
   digest) APPENDED to any existing chain, so provenance survives
   chained migrations (runtime/checkpoint.py carries the chain across
   later boundary writes);
5. fan the rewritten checkpoint out to the target per-process dirs.

``MESH_SHAPE`` stays in the resume identity on purpose: a topology
change must be EXPLICIT (this module, or ``multiproc_launch.py
--resume --mesh-shape/--procs``), never a silent re-shard of a carry
some other process still holds.

CLI: ``python -m distributed_membership_tpu.elastic.reshard
--src RUN/p0/ckpt --src RUN/p1/ckpt --dst RUN/p0/ckpt
--mesh-shape 4x2``.
"""

from __future__ import annotations

import argparse
import json
import os
import time
from typing import List, Optional

import numpy as np

__all__ = ["ReshardError", "mesh_size", "validate_geometry", "reshard"]


class ReshardError(ValueError):
    """A target geometry this checkpoint cannot legally resume onto."""


def mesh_size(shape: str, default: int = 1) -> int:
    """Device count of a MESH_SHAPE string ('' = ``default``)."""
    if not shape:
        return int(default)
    out = 1
    for p in shape.lower().split("x"):
        out *= int(p)
    return out


def _check_shape_grammar(shape: str, label: str) -> None:
    if not shape:
        return
    parts = shape.lower().split("x")
    if not (1 <= len(parts) <= 3
            and all(p.isdigit() and int(p) > 0 for p in parts)):
        raise ReshardError(
            f"{label} must be 'D', 'OxI' or 'SxOxI' (positive ints), "
            f"got {shape!r}")


def validate_geometry(n: int, total_time: int, from_shape: str,
                      to_shape: str, from_procs: int, to_procs: int,
                      *, pack16: bool = False,
                      folded: bool = False) -> None:
    """Refuse-loudly gate for a reshard target.  Every refusal names the
    violated bound — an operator mid-migration gets told exactly which
    knob to change, not a stack trace from the mesh builder."""
    from distributed_membership_tpu.ops.megakernel import (
        PACK_SAFE_TICKS, pack_fits)

    _check_shape_grammar(from_shape, "source MESH_SHAPE")
    _check_shape_grammar(to_shape, "target MESH_SHAPE")
    if to_procs < 1:
        raise ReshardError(
            f"target process count must be >= 1, got {to_procs}")
    size = mesh_size(to_shape, default=to_procs)
    if n % size != 0:
        raise ReshardError(
            f"target MESH_SHAPE {to_shape!r} ({size} devices) does not "
            f"divide N={n}: the sharded backend splits member rows "
            f"evenly across the mesh (N % mesh_size == 0)")
    if size % to_procs != 0:
        raise ReshardError(
            f"target MESH_SHAPE {to_shape!r} ({size} devices) does not "
            f"divide across {to_procs} processes (mesh_size % procs "
            f"== 0: every process owns the same number of devices)")
    if folded and (n // size) % 2 != 0:
        raise ReshardError(
            f"FOLDED carry needs an even per-device row count, got "
            f"N={n} over {size} devices ({n // size} rows each) for "
            f"target MESH_SHAPE {to_shape!r}")
    if pack16 and not pack_fits(total_time):
        raise ReshardError(
            f"MEGA_PACK's 16-bit stamp lanes cover at most "
            f"PACK_SAFE_TICKS={PACK_SAFE_TICKS} ticks; this run has "
            f"TOTAL_TIME={total_time} — resume unpacked (MEGA_PACK: 0) "
            f"on the new geometry instead")


def _load_ckpt_arrays(ckpt_dir: str, manifest: dict):
    """→ (carry_leaves, payload_arrays) verified against the manifest's
    state hash (same corruption gate as a real resume)."""
    from distributed_membership_tpu.runtime.checkpoint import state_hash

    path = os.path.join(ckpt_dir, manifest["file"])
    try:
        npz = np.load(path)
    except OSError as e:
        raise ReshardError(
            f"checkpoint file {path!r} named by the manifest is "
            f"unreadable ({e})") from e
    with npz as data:
        ckeys = sorted((k for k in data.files if k.startswith("c")
                        and k[1:].isdigit()), key=lambda k: int(k[1:]))
        leaves = [data[k] for k in ckeys]
        payload = {k: data[k] for k in data.files if k.startswith("e_")}
    got = state_hash(leaves)
    if got != manifest["state_hash"]:
        raise ReshardError(
            f"state hash mismatch for {path!r} (manifest "
            f"{manifest['state_hash'][:12]}…, file {got[:12]}…) — "
            "checkpoint is corrupt; refusing to reshard it")
    return leaves, payload


def _codec_roundtrip(leaves: list, pack16: bool, total_time: int) -> dict:
    """Pack/unpack the carry through the ops/megakernel.py boundary
    codec and verify bit-exactness — the transport a migration's carry
    actually rides.  Raw npz leaves are unnamed, so the name-keyed u16
    stamp lanes are applied here by the DYNAMIC bound (``fits16``) under
    the static tick bound, with the round-trip as proof."""
    from distributed_membership_tpu.ops import megakernel as mk

    t0 = time.perf_counter()
    plan = []
    for leaf in leaves:
        if leaf.dtype == np.bool_:
            plan.append("bits")
        elif (pack16 and mk.pack_fits(total_time) and leaf.ndim >= 1
              and leaf.dtype == np.int32 and mk.fits16(leaf)):
            plan.append("u16")
        else:
            plan.append("raw")
    packed_bytes = 0
    for kind, leaf in zip(plan, leaves):
        if kind == "bits":
            words = np.asarray(mk._pack_bits(leaf))
            packed_bytes += words.nbytes
            back = np.asarray(mk._unpack_bits(words, leaf.shape))
        elif kind == "u16":
            words = np.asarray(mk._pack_u16(leaf))
            packed_bytes += words.nbytes
            back = np.asarray(mk._unpack_u16(words, leaf.shape))
        else:
            packed_bytes += leaf.nbytes
            back = leaf
        if back.dtype != leaf.dtype or not np.array_equal(back, leaf):
            raise ReshardError(
                "boundary codec round-trip diverged on a carry leaf "
                f"(kind={kind}, shape={leaf.shape}, dtype={leaf.dtype}) "
                "— refusing to ship a lossy carry")
    full_bytes = sum(leaf.nbytes for leaf in leaves)
    return {"carry_bytes_full": int(full_bytes),
            "carry_bytes_packed": int(packed_bytes),
            "codec_seconds": time.perf_counter() - t0}


def _redistribute(leaves: list, n: int, from_size: int,
                  to_size: int) -> float:
    """Gather-to-host → re-split proof: slice every node-sharded leaf
    into the old per-device row shards, reassemble, re-split per the new
    mesh, reassemble again, and verify bit-exactness.  Returns the wall
    seconds the host-side shuffle cost (the bench's redistribution
    number)."""
    t0 = time.perf_counter()
    if n <= 0 or n % from_size or n % to_size:
        return 0.0          # unsharded source/target: nothing to move
    for leaf in leaves:
        if leaf.ndim < 1 or leaf.shape[0] != n:
            continue        # replicated / non-row leaf: no row shards
        gathered = np.concatenate(np.split(leaf, from_size, axis=0))
        shards = np.split(np.ascontiguousarray(gathered), to_size,
                          axis=0)
        back = np.concatenate(shards, axis=0)
        if not np.array_equal(back, leaf):
            raise ReshardError(
                f"host redistribution diverged on a [{n}, ...] leaf "
                f"({from_size} -> {to_size} row shards)")
    return time.perf_counter() - t0


def reshard(src_dirs: List[str], dst_dirs: List[str], *,
            to_mesh_shape: Optional[str] = None,
            pack16: bool = False) -> dict:
    """Rewrite the checkpoint in ``src_dirs`` (one per source process)
    for the topology implied by ``to_mesh_shape`` + ``len(dst_dirs)``
    target processes.  Returns a stats dict (tick, shapes, carry bytes,
    codec/redistribution seconds, carry digest).  Raises
    :class:`ReshardError` on any geometry the checkpoint cannot legally
    resume onto, and never touches ``dst_dirs`` before every validation
    has passed."""
    from distributed_membership_tpu.runtime.checkpoint import (
        CKPT_VERSION, MANIFEST_NAME, load_manifest)

    if not src_dirs or not dst_dirs:
        raise ReshardError("need at least one --src and one --dst "
                           "checkpoint directory")
    t_start = time.perf_counter()
    manifests = []
    for d in src_dirs:
        m = load_manifest(d)
        if m is None:
            raise ReshardError(
                f"no readable {MANIFEST_NAME} in {d!r} — nothing durable "
                "to reshard")
        manifests.append(m)
    head = manifests[0]
    if int(head.get("version", 0)) != CKPT_VERSION:
        raise ReshardError(
            f"checkpoint version {head.get('version')!r} in "
            f"{src_dirs[0]!r} (this code writes {CKPT_VERSION})")
    for d, m in zip(src_dirs[1:], manifests[1:]):
        for k in ("tick", "state_hash", "params_text", "seed",
                  "backend", "total_time", "process_count"):
            if m.get(k) != head.get(k):
                raise ReshardError(
                    f"source checkpoints disagree: field {k!r} is "
                    f"{m.get(k)!r} in {d!r} vs {head.get(k)!r} in "
                    f"{src_dirs[0]!r} — not one run's boundary")
    from_procs = int(head.get("process_count", 1))
    if len(src_dirs) != from_procs:
        raise ReshardError(
            f"checkpoint was written by {from_procs} process(es) but "
            f"{len(src_dirs)} --src dir(s) given — every source "
            "process's directory must be presented (gather-to-host "
            "covers the whole mesh, not a slice of it)")

    params = json.loads(head["params_text"])
    n = int(params.get("EN_GPSZ", 0))
    from_shape = params.get("MESH_SHAPE", "") or ""
    folded = int(params.get("FOLDED", 0)) == 1
    total_time = int(head["total_time"])
    to_procs = len(dst_dirs)
    to_shape = from_shape if to_mesh_shape is None else to_mesh_shape
    validate_geometry(n, total_time, from_shape, to_shape, from_procs,
                      to_procs, pack16=pack16, folded=folded)

    leaves, payload = _load_ckpt_arrays(src_dirs[0], head)
    stats = _codec_roundtrip(leaves, pack16, total_time)
    stats["redistribute_seconds"] = _redistribute(
        leaves, n, mesh_size(from_shape, default=from_procs),
        mesh_size(to_shape, default=to_procs))

    tick = int(head["tick"])
    digest = head["state_hash"]
    record = {"from_shape": from_shape, "to_shape": to_shape,
              "from_procs": from_procs, "to_procs": to_procs,
              "carry_digest": digest, "tick": tick,
              "ts": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())}
    chain = list(head.get("reshard", ())) + [record]

    new_params = dict(params)
    new_params["MESH_SHAPE"] = to_shape
    fname = f"ckpt_{tick:08d}.npz"
    arrays = {f"c{i}": leaf for i, leaf in enumerate(leaves)}
    arrays.update(payload)
    manifest = dict(head)
    manifest.update({
        "params_text": json.dumps(new_params, sort_keys=True),
        "process_count": to_procs,
        "file": fname,
        "checkpoints": [{"tick": tick, "file": fname,
                         "state_hash": digest}],
        "reshard": chain,
        "wrote_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    })
    for d in dst_dirs:
        os.makedirs(d, exist_ok=True)
        npz_path = os.path.join(d, fname)
        tmp = npz_path + ".tmp"
        with open(tmp, "wb") as fh:
            np.savez(fh, **arrays)
        os.replace(tmp, npz_path)
        tmp = os.path.join(d, MANIFEST_NAME) + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(manifest, fh, indent=1)
        os.replace(tmp, os.path.join(d, MANIFEST_NAME))
        # Stale snapshots from the old topology would out-version the
        # resharded one on a later history walk — drop them.
        for f in os.listdir(d):
            if (f.startswith("ckpt_") and f.endswith(".npz")
                    and f != fname):
                try:
                    os.unlink(os.path.join(d, f))
                except OSError:
                    pass

    stats.update({"tick": tick, "from_shape": from_shape,
                  "to_shape": to_shape, "from_procs": from_procs,
                  "to_procs": to_procs, "carry_digest": digest,
                  "wall_seconds": time.perf_counter() - t_start})
    return stats


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="Rewrite a durable checkpoint for a new MESH_SHAPE "
                    "and/or process count (reshard-on-resume)")
    ap.add_argument("--src", action="append", required=True,
                    metavar="DIR", help="source per-process checkpoint "
                    "dir (repeat once per source process)")
    ap.add_argument("--dst", action="append", required=True,
                    metavar="DIR", help="target per-process checkpoint "
                    "dir (repeat once per target process; may overlap "
                    "--src for in-place reshards)")
    ap.add_argument("--mesh-shape", default=None,
                    help="target MESH_SHAPE (default: keep the source's)")
    ap.add_argument("--pack16", action="store_true",
                    help="round-trip the carry through the 16-bit stamp "
                    "lanes too (requires the static tick bound)")
    args = ap.parse_args(argv)
    try:
        stats = reshard(args.src, args.dst,
                        to_mesh_shape=args.mesh_shape,
                        pack16=args.pack16)
    except ReshardError as e:
        print(f"reshard: {e}")
        return 2
    print(json.dumps(stats, sort_keys=True))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
