"""Fleet migration policy: which PR-18 health signals move a run.

A migration is two journaled, fsync-before-ACK registry transitions —
``migrating`` (with the trigger rule and the tick the fleet last saw
the run alive at) then ``requeued`` (with the durable tick the resume
will start from) — after which the ordinary dispatch path relaunches
the run wherever the placement model says it fits.  Downtime in ticks
is ``from_tick - resume_tick``: the work between the last durable
boundary and the last observed beacon, recomputed bit-exactly on
resume.

Triggers (``FLEET_MIGRATE_ON``, comma list; '' = manual only):

* ``death``        the worker process died and left a durable
                   checkpoint (or a restartable chunked run).
* ``alerts``       watchdog alert rules (observability/watchdog.py)
                   fired in the run's runlog since this worker started
                   — the run is alive but degrading, so drain it
                   gracefully (SIGTERM -> boundary checkpoint).
* ``stale-beacon`` the progress beacon stopped advancing: the worker
                   is wedged, SIGKILL it and adopt the last durable
                   boundary.

``FLEET_MIGRATE_MAX`` caps AUTOMATIC migrations per run (a run that
keeps dying lands terminal instead of thrashing); manual operator
drains (``POST /v1/runs/<id>/migrate``) are always allowed.
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from typing import Optional

TRIGGERS = ("death", "alerts", "stale-beacon")

# Watchdog rules that mean "this run should move", as opposed to rules
# that indicate a query-side wobble the run itself will survive.
DEFAULT_ALERT_RULES = ("tick_rate_collapse", "detection_slo")

__all__ = ["TRIGGERS", "MigratePolicy", "migrate_record", "alert_count"]


@dataclasses.dataclass(frozen=True)
class MigratePolicy:
    triggers: frozenset = frozenset()
    max_migrations: int = 2
    stale_beacon_s: float = 15.0
    alert_rules: tuple = DEFAULT_ALERT_RULES

    @classmethod
    def from_conf(cls, migrate_on: str,
                  max_migrations: int = 2,
                  stale_beacon_s: float = 15.0) -> "MigratePolicy":
        """Parse FLEET_MIGRATE_ON/_MAX; loud on unknown trigger names
        (config.validate repeats this check for conf-borne values)."""
        names = frozenset(p.strip() for p in migrate_on.split(",")
                          if p.strip())
        bad = sorted(names - frozenset(TRIGGERS))
        if bad:
            raise ValueError(
                f"FLEET_MIGRATE_ON: unknown trigger(s) {bad!r} — "
                f"choose from {', '.join(TRIGGERS)}")
        if max_migrations < 0:
            raise ValueError(
                f"FLEET_MIGRATE_MAX must be >= 0, got {max_migrations!r}")
        return cls(triggers=names, max_migrations=int(max_migrations),
                   stale_beacon_s=float(stale_beacon_s))

    @property
    def on_death(self) -> bool:
        return "death" in self.triggers

    def sick_trigger(self, *, run_dir: str, beacon: Optional[dict],
                     total: int,
                     started_wall: float) -> Optional[str]:
        """The live-worker trigger evaluation (scheduler poll loop):
        returns a trigger name or None.  Alert rows older than
        ``started_wall`` belong to a previous incarnation of this run
        dir and never re-trigger a fresh worker."""
        if "alerts" in self.triggers and alert_count(
                run_dir, self.alert_rules, since=started_wall) > 0:
            return "alerts"
        if ("stale-beacon" in self.triggers and beacon is not None
                and int(beacon.get("tick", 0)) < int(total)
                and time.time() - float(beacon.get("ts", 0.0))
                > self.stale_beacon_s):
            return "stale-beacon"
        return None


def alert_count(run_dir: str, rules=DEFAULT_ALERT_RULES,
                since: float = 0.0) -> int:
    """Watchdog alert records in ``<run_dir>/runlog.jsonl`` matching
    ``rules`` and newer than ``since`` (torn-line tolerant, same
    posture as every JSONL reader in the repo)."""
    path = os.path.join(run_dir, "runlog.jsonl")
    if not os.path.exists(path):
        return 0
    count = 0
    try:
        with open(path) as fh:
            for line in fh:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if (row.get("kind") == "alert"
                        and row.get("rule") in rules
                        and float(row.get("ts", 0.0) or 0.0) >= since):
                    count += 1
    except OSError:
        return 0
    return count


def migrate_record(registry, rec, trigger: str, *,
                   from_tick: Optional[int] = None) -> dict:
    """Journal one migration: ``migrating`` -> ``requeued`` (both
    fsynced before the registry returns — the same ACK discipline as
    every other transition).  ``from_tick`` is where the fleet last saw
    the run alive (beacon); ``rec.tick`` already holds the durable
    manifest tick the resume starts from.  Returns the detail row the
    reporter renders (trigger, from/resume ticks, downtime)."""
    seen = int(rec.tick if from_tick is None else from_tick)
    resume_tick = int(rec.tick)
    registry.set_state(rec, "migrating", trigger=trigger,
                       from_tick=seen, tick=resume_tick)
    registry.set_state(rec, "requeued", trigger=trigger,
                       from_tick=seen, resume_tick=resume_tick,
                       tick=resume_tick)
    rec.migrate_requested = False
    return {"trigger": trigger, "from_tick": seen,
            "resume_tick": resume_tick,
            "downtime_ticks": max(seen - resume_tick, 0)}
