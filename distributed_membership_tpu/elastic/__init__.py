"""Elastic mesh: topology is a runtime variable, not a config constant.

Three legs (see ROADMAP "Elastic mesh"):

* ``elastic/reshard.py`` — reshard-on-resume: rewrite a durable
  checkpoint so ``--resume`` can continue on a different ``MESH_SHAPE``
  and process count, with the carry redistributed host-side and the
  manifest stamped with chained reshard provenance.
* ``elastic/migrate.py`` — the fleet migration policy: which PR-18
  health signals (worker death, watchdog alerts, stale beacons) move a
  run, and the journaled ``migrating`` → ``requeued`` transition.
* ``fleet/placement.py`` — the capacity model the scheduler consults so
  migration targets are chosen, not guessed.
"""
